"""Key-value store backends (role of tmlibs/db in the reference).

The reference uses goleveldb for blockstore/state/txindex/addrbook
(`tmlibs/db`); here the persistent backend is SQLite (stdlib, ACID,
single-file) and MemDB backs tests/replay.
"""

from tendermint_tpu.db.kv import DB, MemDB, SQLiteDB, db_provider

__all__ = ["DB", "MemDB", "SQLiteDB", "db_provider"]
