"""FullCommitStore: DB-backed persistence for CERTIFIED FullCommits.

The durable half of the light-client serving layer's proof cache
(`tendermint_tpu/lightclient/cache.py`): one encoded FullCommit per
height under `fc:%012d` keys, with the `get_by_height` floor-lookup
contract every certifier provider shares (largest stored height <= h —
the bisection walk's restart primitive, `certifiers/provider.py`).

Trust discipline is the CALLER's: only commits that passed
certification may be stored (the cache layer enforces it, same
never-cache-a-negative rule as the VerifiedSigCache) — the store
itself is a dumb ordered map, so a replica restart reloads exactly the
trust it had proven, nothing more.

`prune(keep_recent)` bounds the footprint on long-lived replicas: the
newest N commits stay, plus every retained height stays reachable via
the floor lookup through the gaps below.
"""

from __future__ import annotations

import threading

from tendermint_tpu.certifiers.certifier import FullCommit
from tendermint_tpu.certifiers.provider import Provider
from tendermint_tpu.db.kv import DB

_PREFIX = b"fc:"


def _key(height: int) -> bytes:
    return _PREFIX + b"%012d" % height


class FullCommitStore(Provider):
    """Ordered-KV-backed Provider of certified FullCommits."""

    def __init__(self, db: DB) -> None:
        self._db = db
        self._lock = threading.RLock()
        # height index kept hot: the floor lookup must not scan the DB
        # per query on the serving path
        self._heights: list[int] = [
            int(k[len(_PREFIX):]) for k, _v in db.iterate(_PREFIX)
        ]
        self._heights.sort()

    def store_commit(self, fc: FullCommit) -> None:
        import bisect

        h = fc.height()
        with self._lock:
            known = self._heights and self._in_index(h)
            self._db.set(_key(h), fc.encode())
            if not known:
                bisect.insort(self._heights, h)

    def _in_index(self, height: int) -> bool:
        import bisect

        i = bisect.bisect_left(self._heights, height)
        return i < len(self._heights) and self._heights[i] == height

    def get_by_height(self, height: int) -> FullCommit | None:
        import bisect

        with self._lock:
            i = bisect.bisect_right(self._heights, height)
            if i == 0:
                return None
            raw = self._db.get(_key(self._heights[i - 1]))
        return FullCommit.decode(raw) if raw is not None else None

    def get_exact(self, height: int) -> FullCommit | None:
        """Exact-height lookup (the serving path: a proof request for
        height H must never be answered with H-1's commit)."""
        raw = self._db.get(_key(height))
        return FullCommit.decode(raw) if raw is not None else None

    def latest_commit(self) -> FullCommit | None:
        with self._lock:
            if not self._heights:
                return None
            raw = self._db.get(_key(self._heights[-1]))
        return FullCommit.decode(raw) if raw is not None else None

    def latest_height(self) -> int:
        with self._lock:
            return self._heights[-1] if self._heights else 0

    def heights(self) -> list[int]:
        with self._lock:
            return list(self._heights)

    def __len__(self) -> int:
        with self._lock:
            return len(self._heights)

    def prune(self, keep_recent: int) -> int:
        """Drop all but the newest `keep_recent` commits; returns the
        number pruned. 0 keeps everything."""
        if keep_recent <= 0:
            return 0
        with self._lock:
            drop = self._heights[:-keep_recent]
            if not drop:
                return 0
            for h in drop:
                self._db.delete(_key(h))
            self._heights = self._heights[-keep_recent:]
        return len(drop)
