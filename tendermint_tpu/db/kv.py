"""Minimal ordered key-value store interface + backends.

Mirrors the `dbm.DB` seam in the reference (`tmlibs/db`): Get/Set/Delete
with synchronous variants and ordered iteration; consumers are the block
store, state DB, tx index, and address book.
"""

from __future__ import annotations

import os
import sqlite3
import threading
from typing import Iterator


class DB:
    """Interface: bytes -> bytes with ordered iteration."""

    def get(self, key: bytes) -> bytes | None:
        raise NotImplementedError

    def set(self, key: bytes, value: bytes) -> None:
        raise NotImplementedError

    def set_sync(self, key: bytes, value: bytes) -> None:
        self.set(key, value)

    def delete(self, key: bytes) -> None:
        raise NotImplementedError

    def has(self, key: bytes) -> bool:
        return self.get(key) is not None

    def iterate(self, prefix: bytes = b"") -> Iterator[tuple[bytes, bytes]]:
        raise NotImplementedError

    def close(self) -> None:
        pass


class MemDB(DB):
    """In-memory store (reference memdb) — tests and replay fakes."""

    def __init__(self) -> None:
        self._data: dict[bytes, bytes] = {}
        self._lock = threading.Lock()

    def get(self, key: bytes) -> bytes | None:
        with self._lock:
            return self._data.get(bytes(key))

    def set(self, key: bytes, value: bytes) -> None:
        with self._lock:
            self._data[bytes(key)] = bytes(value)

    def delete(self, key: bytes) -> None:
        with self._lock:
            self._data.pop(bytes(key), None)

    def iterate(self, prefix: bytes = b"") -> Iterator[tuple[bytes, bytes]]:
        with self._lock:
            items = sorted(
                (k, v) for k, v in self._data.items() if k.startswith(prefix)
            )
        yield from items


class SQLiteDB(DB):
    """SQLite-backed store — the persistent backend (goleveldb's role).

    WAL journal mode gives crash safety with one fsync per commit;
    `set_sync` additionally checkpoints for consensus-critical writes
    (the reference distinguishes SetSync at the same call sites).
    """

    def __init__(self, path: str) -> None:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._lock = threading.Lock()
        with self._lock:
            self._conn.execute("PRAGMA journal_mode=WAL")
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS kv (k BLOB PRIMARY KEY, v BLOB NOT NULL)"
            )
            self._conn.commit()

    def get(self, key: bytes) -> bytes | None:
        with self._lock:
            row = self._conn.execute(
                "SELECT v FROM kv WHERE k = ?", (bytes(key),)
            ).fetchone()
        return row[0] if row else None

    def set(self, key: bytes, value: bytes) -> None:
        with self._lock:
            self._conn.execute(
                "INSERT OR REPLACE INTO kv (k, v) VALUES (?, ?)",
                (bytes(key), bytes(value)),
            )
            self._conn.commit()

    def set_sync(self, key: bytes, value: bytes) -> None:
        self.set(key, value)
        with self._lock:
            self._conn.execute("PRAGMA wal_checkpoint(FULL)")

    def delete(self, key: bytes) -> None:
        with self._lock:
            self._conn.execute("DELETE FROM kv WHERE k = ?", (bytes(key),))
            self._conn.commit()

    def iterate(self, prefix: bytes = b"") -> Iterator[tuple[bytes, bytes]]:
        with self._lock:
            if prefix:
                hi = bytes(prefix[:-1] + bytes([prefix[-1] + 1])) if prefix[-1] < 255 else None
                if hi is not None:
                    rows = self._conn.execute(
                        "SELECT k, v FROM kv WHERE k >= ? AND k < ? ORDER BY k",
                        (bytes(prefix), hi),
                    ).fetchall()
                else:
                    rows = self._conn.execute(
                        "SELECT k, v FROM kv WHERE k >= ? ORDER BY k", (bytes(prefix),)
                    ).fetchall()
                    rows = [(k, v) for k, v in rows if bytes(k).startswith(prefix)]
            else:
                rows = self._conn.execute("SELECT k, v FROM kv ORDER BY k").fetchall()
        for k, v in rows:
            yield bytes(k), bytes(v)

    def close(self) -> None:
        with self._lock:
            self._conn.close()


def db_provider(name: str, backend: str, db_dir: str) -> DB:
    """Factory matching the reference's node DBProvider seam
    (`node/node.go:59-72`)."""
    if backend == "memdb":
        return MemDB()
    if backend == "sqlite":
        return SQLiteDB(os.path.join(db_dir, f"{name}.db"))
    raise ValueError(f"unknown db backend {backend!r}")
