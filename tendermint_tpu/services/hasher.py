"""TreeHasher: batched Merkle tree construction service.

Fills the `tmlibs/merkle.SimpleHash*` slot (reference call sites:
`types/block.go:177`, `types/tx.go:33-46`, `types/part_set.go:95-122`).
The device backend hashes all leaves as one batched SHA-256 kernel call
and reduces the tree in log2(N) fused levels entirely on device; the
host backend is the bit-identical sequential reference.

The reference's tree uses RIPEMD-160 (`docs/specification/merkle.rst`);
this framework's target variant is SHA-256 (BASELINE.md north star).
Device trees support sha256; ripemd160 trees fall back to host.
"""

from __future__ import annotations

import numpy as np

from tendermint_tpu.merkle import simple as host_merkle


class TreeHasher:
    """Merkle root/proof builder with host and device backends."""

    def __init__(self, backend: str = "device", algo: str = "sha256") -> None:
        if backend not in ("device", "host"):
            raise ValueError(f"unknown backend {backend!r}")
        self.algo = algo
        # device tree reduction is sha256-only; ripemd160 stays on host
        self.backend = backend if algo == "sha256" else "host"

    def root_from_items(self, items: list[bytes]) -> bytes:
        """SimpleMerkle root over raw byte leaves (leaf-prefixed hashes)."""
        if self.backend == "device" and len(items) > 1:
            from tendermint_tpu.ops.merkle_kernel import merkle_root_device

            return merkle_root_device(items)
        return host_merkle.simple_hash_from_byte_slices(items, self.algo)

    def root_from_hashes(self, hashes: list[bytes]) -> bytes:
        """Root over already-hashed leaves (PartSet/Commit aggregation)."""
        if self.backend == "device" and len(hashes) > 1:
            from tendermint_tpu.ops.merkle_kernel import merkle_root_from_leaf_words
            from tendermint_tpu.ops.padding import digests_to_bytes_be

            words = np.stack(
                [np.frombuffer(h, dtype=">u4").astype(np.uint32) for h in hashes]
            )
            root = merkle_root_from_leaf_words(words)
            return digests_to_bytes_be(np.asarray(root)[None, :])[0]
        return host_merkle.simple_hash_from_hashes(hashes, self.algo)

    def proofs(self, items: list[bytes]):
        """Merkle proofs stay on host: O(N log N) pointer work, tiny data."""
        return host_merkle.simple_proofs_from_byte_slices(items, self.algo)


_DEFAULT: TreeHasher | None = None


def default_hasher() -> TreeHasher:
    global _DEFAULT
    if _DEFAULT is None:
        _DEFAULT = TreeHasher()
    return _DEFAULT
