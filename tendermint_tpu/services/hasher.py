"""TreeHasher: batched Merkle tree construction service.

Fills the `tmlibs/merkle.SimpleHash*` slot (reference call sites:
`types/block.go:177`, `types/tx.go:33-46`, `types/part_set.go:95-122`).
The device backend hashes all leaves as one batched SHA-256 kernel call
and reduces the tree in log2(N) fused levels entirely on device; the
host backend is the bit-identical sequential reference.

The reference's tree uses RIPEMD-160 (`docs/specification/merkle.rst`);
this framework's target variant is SHA-256 (BASELINE.md north star).
Device trees support BOTH variants (raw-leaf builds and
already-hashed-leaf aggregation).
"""

from __future__ import annotations

import os
import time

import numpy as np

from tendermint_tpu.merkle import simple as host_merkle
from tendermint_tpu.telemetry import launchlog as _launchlog
from tendermint_tpu.telemetry import metrics as _metrics


def _observe_hash(
    backend: str, leaves: int, seconds: float, kind: str = "hash"
) -> None:
    _metrics.HASH_BATCH_LEAVES.labels(backend=backend).observe(leaves)
    _metrics.HASH_SECONDS.labels(backend=backend).observe(seconds)
    # device-observatory seam: closes/annotates the ambient launch
    # record (host micro-roots outside a dispatch handle record nothing)
    _launchlog.observe(kind, backend, leaves, seconds)

# Below this leaf count the ~60 ms per-launch dispatch floor
# (docs/PLATFORM_NOTES.md) makes host hashlib strictly faster; the device
# tree only wins on big blocks (BASELINE config 4 is 65k leaves).
DEVICE_MIN_LEAVES = int(os.environ.get("TENDERMINT_TPU_MIN_DEVICE_LEAVES", "8192"))


class TreeHasher:
    """Merkle root/proof builder with host and device backends."""

    def __init__(
        self,
        backend: str = "device",
        algo: str = "sha256",
        min_device_leaves: int | None = None,
        mesh=None,
    ) -> None:
        if backend not in ("device", "host"):
            raise ValueError(f"unknown backend {backend!r}")
        if algo not in ("sha256", "ripemd160"):
            raise ValueError(f"unknown algo {algo!r}")
        self.algo = algo
        # device trees support both variants: sha256 (the framework's
        # target) and ripemd160 (the reference's bit-compat tree,
        # `docs/specification/merkle.rst`)
        self.backend = backend
        self.min_device_leaves = (
            DEVICE_MIN_LEAVES if min_device_leaves is None else min_device_leaves
        )
        # Optional `parallel.mesh.MeshManager`: the LEAF hashing lane
        # (the O(N) term — statesync chunk gates, big-block leaf
        # passes) shards over the mesh; tree reduction stays
        # single-device (inner levels halve too fast to amortize
        # collectives). None = single-device legacy.
        self.mesh = mesh

    def _use_device(self, n: int) -> bool:
        return self.backend == "device" and n >= max(2, self.min_device_leaves)

    def root_from_items(self, items: list[bytes]) -> bytes:
        """SimpleMerkle root over raw byte leaves (leaf-prefixed hashes)."""
        t0 = time.perf_counter()
        if self._use_device(len(items)):
            from tendermint_tpu.ops.merkle_kernel import merkle_root_device

            out = merkle_root_device(items, self.algo)
            _observe_hash("device", len(items), time.perf_counter() - t0)
            return out
        out = host_merkle.simple_hash_from_byte_slices(items, self.algo)
        _observe_hash("host", len(items), time.perf_counter() - t0)
        return out

    def root_from_hashes(self, hashes: list[bytes]) -> bytes:
        """Root over already-hashed leaves (PartSet/Commit aggregation)."""
        t0 = time.perf_counter()
        if self._use_device(len(hashes)):
            from tendermint_tpu.ops.merkle_kernel import merkle_root_from_leaf_words
            from tendermint_tpu.ops.padding import (
                digests_to_bytes_be,
                digests_to_bytes_le,
            )

            # sha256 digests are big-endian words; ripemd160 little-endian
            dt, to_bytes = (
                (">u4", digests_to_bytes_be)
                if self.algo == "sha256"
                else ("<u4", digests_to_bytes_le)
            )
            words = (
                np.frombuffer(b"".join(hashes), dtype=dt)
                .astype(np.uint32)
                .reshape(len(hashes), -1)
            )
            root = merkle_root_from_leaf_words(words, algo=self.algo)
            out = to_bytes(np.asarray(root)[None, :])[0]
            _observe_hash("device", len(hashes), time.perf_counter() - t0)
            return out
        out = host_merkle.simple_hash_from_hashes(hashes, self.algo)
        _observe_hash("host", len(hashes), time.perf_counter() - t0)
        return out

    def leaf_hashes(self, items: list[bytes]) -> list[bytes]:
        """Per-item domain-separated leaf hashes (state-sync chunk
        verification) — one batched device launch above the threshold
        (sharded over every active mesh chip when a mesh is attached),
        host hashlib below it."""
        t0 = time.perf_counter()
        if self._use_device(len(items)):
            if self.mesh is not None and self.mesh.n_total > 1:
                from tendermint_tpu.ops.merkle_kernel import leaf_hashes_sharded

                out = leaf_hashes_sharded(items, self.algo, self.mesh)
                _observe_hash(
                    "mesh", len(items), time.perf_counter() - t0,
                    kind="leaf_hashes",
                )
                return out
            from tendermint_tpu.ops.merkle_kernel import leaf_hashes_device

            out = leaf_hashes_device(items, self.algo)
            _observe_hash(
                "device", len(items), time.perf_counter() - t0,
                kind="leaf_hashes",
            )
            return out
        out = [host_merkle.leaf_hash(x, self.algo) for x in items]
        _observe_hash(
            "host", len(items), time.perf_counter() - t0, kind="leaf_hashes"
        )
        return out

    def leaf_hashes_async(self, items: list[bytes], queue=None):
        """`leaf_hashes` through a `DispatchQueue` handle — the chunk-
        verify gate submits the whole-set hash and overlaps payload
        decode while it runs (statesync/snapshot.py). The resilient
        wrapper overrides this with the breaker-guarded version."""
        from tendermint_tpu.services.dispatch import default_dispatch_queue

        q = queue if queue is not None else default_dispatch_queue()
        return q.submit(lambda: self.leaf_hashes(items), kind="hash")

    def proofs(self, items: list[bytes]):
        """Merkle proofs stay on host: O(N log N) pointer work, tiny data."""
        return host_merkle.simple_proofs_from_byte_slices(items, self.algo)


_DEFAULT: TreeHasher | None = None


def default_hasher() -> TreeHasher:
    global _DEFAULT
    if _DEFAULT is None:
        from tendermint_tpu.services.resilient import ResilientTreeHasher

        _DEFAULT = ResilientTreeHasher(TreeHasher())
    return _DEFAULT


def auto_hasher() -> TreeHasher:
    """Device-backed hasher iff a TPU backend is actually up.

    The node composition root calls this once at start so block production
    (`types/tx.go:33-46` analog) rides the device tree on TPU while CPU-only
    runs (tests, dev) never pay an XLA compile for host-sized work.

    Device trees come wrapped in `ResilientTreeHasher` — a device fault
    degrades block hashing to host hashlib behind a circuit breaker
    instead of failing block production (`services/resilient.py`). Host
    runs get the wrapper too when fault injection is armed, so chaos
    tests drive the same dispatch path on CPU CI.
    """
    import jax

    from tendermint_tpu.services.verifier import _mesh_opt_in_cpu
    from tendermint_tpu.utils.fail import device_faults_armed

    if jax.default_backend() == "tpu":
        from tendermint_tpu.parallel.mesh import (
            default_mesh_manager,
            mesh_device_count,
        )
        from tendermint_tpu.services.resilient import ResilientTreeHasher

        mesh = default_mesh_manager() if mesh_device_count() > 1 else None
        return ResilientTreeHasher(TreeHasher(backend="device", mesh=mesh))
    if _mesh_opt_in_cpu():
        # the CPU virtual-device recipe (docs/PLATFORM_NOTES.md): the
        # leaf lane shards over the forced mesh, breaker-wrapped like
        # the TPU composition so chaos tests drive the same path
        from tendermint_tpu.parallel.mesh import default_mesh_manager
        from tendermint_tpu.services.resilient import ResilientTreeHasher

        return ResilientTreeHasher(
            TreeHasher(backend="device", mesh=default_mesh_manager()),
            TreeHasher(backend="host"),
        )
    if device_faults_armed():
        from tendermint_tpu.services.resilient import ResilientTreeHasher

        return ResilientTreeHasher(
            TreeHasher(backend="device"), TreeHasher(backend="host")
        )
    return TreeHasher(backend="host")
