"""Batching service layer: the seam between consensus logic and TPU kernels.

`BatchVerifier` / `TreeHasher` sit exactly where the reference calls
`crypto.PubKey.VerifyBytes` and `tmlibs/merkle.SimpleHash*` (SURVEY.md §2b),
replacing one-at-a-time calls with an accumulate→flush batching contract.
"""

from tendermint_tpu.services.hasher import TreeHasher
from tendermint_tpu.services.resilient import (
    ResilientTreeHasher,
    ResilientVerifier,
)
from tendermint_tpu.services.verifier import (
    BatchVerifier,
    DeviceBatchVerifier,
    HostBatchVerifier,
    ShardedBatchVerifier,
    ShardedTableBatchVerifier,
    TableBatchVerifier,
    default_verifier,
)

__all__ = [
    "BatchVerifier",
    "DeviceBatchVerifier",
    "HostBatchVerifier",
    "ResilientTreeHasher",
    "ResilientVerifier",
    "ShardedBatchVerifier",
    "ShardedTableBatchVerifier",
    "TableBatchVerifier",
    "TreeHasher",
    "default_verifier",
]
