"""Cross-subsystem verify coalescer + verified-signature dedup cache.

The verify spine's remaining redundancy is CROSS-consumer: the same
ed25519 triple is proven on gossip arrival, again inside the commit that
seals the block, again on a fast-sync redo and on the light-client
certifier walk (PAPERS.md: EdDSA amortization in committee consensus;
the certifier re-walks overlapping valsets) — and each of the four
independent consumers (consensus vote drain, fast-sync, statesync trust
anchoring, RPC/light-client certifiers — and, since the ingress
pipeline, mempool CheckTx windows as the fifth, `consumer="mempool"`,
and, since the light-client serving layer, bisection-walk rounds as
the sixth, `consumer="lightclient"` — one batched launch per bisection
round, `lightclient/bisect.py`) pays the fixed ~86 ms device launch
(docs/PLATFORM_NOTES.md) on its own small, partially-duplicate batch.
Two layers remove both costs:

* `VerifiedSigCache` — a sharded, thread-safe LRU of PROVEN triples,
  keyed by SHA-256 over the length-prefixed `pubkey‖msg‖sig` (prefixes
  make distinct triples unable to alias across field boundaries).
  POSITIVES ONLY: a failed verdict is never cached, so a forged
  signature can not pin a verdict — every re-offer re-verifies. A hit
  answers without touching the device; steady-state consensus batches
  carry only novel signatures.

* `VerifyCoalescer` — a time/size-windowed merge stage between
  `verify_batch_async` call sites and the `DispatchQueue`: concurrent
  requests from different consumers coalesce into single bucket-shaped
  device launches, each consumer getting a sub-handle that splits the
  joined verdict back out. Requests flush round-robin across consumers
  so one hot consumer can not starve the rest, and per-consumer
  submission order is preserved (PR 4's drain-order discipline). The
  flush window adapts from the launch:apply ratio the dispatch
  telemetry already measures.

`CoalescingVerifier` is the `BatchVerifier`-shaped facade over both,
wrapped around the resilient device stack by `default_verifier()` —
device faults keep degrading through `ResilientVerifier.call_async`
inside the merged handles, invisible to the sub-handle consumers.

Env knobs (all optional):
  TENDERMINT_TPU_VERIFY_CACHE_SIZE   proven triples kept (65536; 0 off)
  TENDERMINT_TPU_COALESCE_WINDOW_MS  fixed flush window (adaptive)
  TENDERMINT_TPU_COALESCE_MAX_BATCH  triples per merged launch (4096)
  TENDERMINT_TPU_COALESCE=0          default_verifier() skips the wrap
"""

from __future__ import annotations

import hashlib
import os
import queue as queue_mod
import threading
import time
from collections import OrderedDict, deque
from typing import Callable, Sequence

import numpy as np

from tendermint_tpu.services.verifier import BatchVerifier, Triple
from tendermint_tpu.telemetry import TRACER
from tendermint_tpu.telemetry import launchlog as _launchlog
from tendermint_tpu.telemetry import metrics as _metrics
from tendermint_tpu.telemetry import tracectx as _trace
from tendermint_tpu.telemetry.flightrec import FLIGHT
from tendermint_tpu.utils.lockrank import ranked_lock

CACHE_SIZE = int(os.environ.get("TENDERMINT_TPU_VERIFY_CACHE_SIZE", "65536"))
MAX_COALESCED_BATCH = int(
    os.environ.get("TENDERMINT_TPU_COALESCE_MAX_BATCH", "4096")
)

# Window bounds: never stall a request longer than one small fraction of
# the launch cost it amortizes, never spin under 0.2 ms (scheduler
# granularity noise dominates below that).
_WINDOW_MIN_S = 2e-4
_WINDOW_MAX_S = 0.01
_WINDOW_REFRESH_FLUSHES = 32

_STOP = object()


def _mesh_width(verifier) -> int:
    """Chips behind a verifier stack (1 for single-device backends):
    walks the `mesh` passthrough the resilient/sharded layers export."""
    mesh = getattr(verifier, "mesh", None)
    if mesh is None:
        mesh = getattr(getattr(verifier, "primary", None), "mesh", None)
    n = getattr(mesh, "n_total", 1)
    return max(1, int(n) if n else 1)


def consumer_kwargs(verifier, consumer: str) -> dict:
    """`{"consumer": ...}` when `verifier` advertises the tag surface
    (every in-tree BatchVerifier), `{}` for minimal test fakes — call
    sites stay compatible with both."""
    if consumer and getattr(verifier, "accepts_consumer", False):
        return {"consumer": consumer}
    return {}


class VerifiedSigCache:
    """Sharded LRU of PROVEN (pubkey, msg, sig) triples.

    Only positive verdicts enter (`add` after a True verdict); a lookup
    hit therefore means "this exact triple verified before". Sharding
    keeps the four consumer threads off one lock; each shard holds
    `capacity / shards` keys with LRU eviction.
    """

    SHARDS = 8

    def __init__(self, capacity: int | None = None) -> None:
        self.capacity = CACHE_SIZE if capacity is None else capacity
        per_shard = max(1, self.capacity // self.SHARDS)
        self._per_shard = per_shard
        self._shards = [
            (ranked_lock("batcher.shard", seq=i), OrderedDict())
            for i in range(self.SHARDS)
        ]
        self.enabled = self.capacity > 0

    @staticmethod
    def key(pubkey: bytes, msg: bytes, sig: bytes) -> bytes:
        """SHA-256 over the LENGTH-PREFIXED concatenation. The prefixes
        are load-bearing: raw `pubkey‖msg‖sig` would let two distinct
        triples alias by shifting bytes across a field boundary
        (`pk+b"ab", m` vs `pk+b"a", b"b"+m`)."""
        h = hashlib.sha256()
        h.update(len(pubkey).to_bytes(4, "big"))
        h.update(pubkey)
        h.update(len(msg).to_bytes(4, "big"))
        h.update(msg)
        h.update(len(sig).to_bytes(4, "big"))
        h.update(sig)
        return h.digest()

    def _shard(self, key: bytes):
        return self._shards[key[0] % self.SHARDS]

    def hit(self, key: bytes) -> bool:
        """Membership + LRU touch + hit/miss telemetry."""
        if not self.enabled:
            return False
        lock, od = self._shard(key)
        with lock:
            if key in od:
                od.move_to_end(key)
                _metrics.VERIFY_CACHE_HITS.inc()
                return True
        _metrics.VERIFY_CACHE_MISSES.inc()
        return False

    def add(self, key: bytes) -> None:
        """Record one PROVEN triple (callers must only pass keys whose
        verify came back True — negatives are never cached, so a forged
        sig can't pin a verdict)."""
        if not self.enabled:
            return
        lock, od = self._shard(key)
        with lock:
            od[key] = True
            od.move_to_end(key)
            while len(od) > self._per_shard:
                od.popitem(last=False)
                _metrics.VERIFY_CACHE_EVICTIONS.inc()

    def __contains__(self, key: bytes) -> bool:
        lock, od = self._shard(key)
        with lock:
            return key in od

    def __len__(self) -> int:
        return sum(len(od) for _lock, od in self._shards)

    def stats(self) -> dict:
        return {
            "capacity": self.capacity,
            "entries": len(self),
            "hits": _metrics.VERIFY_CACHE_HITS.value,
            "misses": _metrics.VERIFY_CACHE_MISSES.value,
            "evictions": _metrics.VERIFY_CACHE_EVICTIONS.value,
        }


class _Request:
    """One consumer's verify submission inside the coalescer."""

    __slots__ = (
        "consumer",
        "out",
        "novel",
        "novel_pos",
        "novel_keys",
        "event",
        "error",
        "submitted_at",
        "flushed",
        "ctx",
    )

    def __init__(self, consumer, out, novel, novel_pos, novel_keys, ctx=None):
        self.consumer = consumer
        self.out = out
        self.novel = novel
        self.novel_pos = novel_pos
        self.novel_keys = novel_keys
        self.event = threading.Event()
        self.error: BaseException | None = None
        self.submitted_at = time.perf_counter()
        self.flushed = False
        # the submitter thread's ambient trace context, captured at
        # submit — the flusher runs on its own thread
        self.ctx = ctx


class SubHandle:
    """Per-consumer future over a coalesced launch — API-compatible with
    `VerifyHandle` (done/result/then), resolving to this request's own
    verdict mask. Joining an unflushed request forces a barrier flush so
    a lone consumer never waits out the window."""

    __slots__ = ("_coalescer", "_req")

    kind = "verify"

    def __init__(self, coalescer: "VerifyCoalescer", req: _Request):
        self._coalescer = coalescer
        self._req = req

    def done(self) -> bool:
        return self._req.event.is_set()

    def result(self, timeout: float | None = None):
        req = self._req
        if not req.event.is_set() and not req.flushed:
            self._coalescer.request_barrier()
        if not req.event.wait(timeout):
            raise TimeoutError(f"coalesced verify not resolved in {timeout}s")
        if req.error is not None:
            raise req.error
        return req.out

    def then(self, fn: Callable):
        from tendermint_tpu.services.dispatch import ChainedHandle

        return ChainedHandle(self, fn)


def _adaptive_window_s() -> float:
    """Flush window derived from what the telemetry already measured:
    a small fraction of the mean device launch cost, scaled up when the
    dispatch overlap histogram says launches dominate applies (more
    coalescing amortizes more of the bottleneck). Env override wins."""
    env = os.environ.get("TENDERMINT_TPU_COALESCE_WINDOW_MS")
    if env:
        return max(0.0, float(env) / 1e3)
    from tendermint_tpu.services.dispatch import measured_launch_apply_ratio
    from tendermint_tpu.telemetry import REGISTRY

    launch_mean = None
    fam = REGISTRY.get("tendermint_verify_seconds")
    if fam is not None:
        # "mesh" first: when the sharded backend is live its launch
        # cost (which includes the cross-chip dispatch) is the one the
        # window amortizes
        for backend in ("mesh", "tables", "device", "host"):
            snap = fam.labels(backend=backend).value
            if snap["count"]:
                launch_mean = snap["sum"] / snap["count"]
                break
    if launch_mean is None:
        return 0.002
    ratio = measured_launch_apply_ratio() or 1.0
    return min(max(0.1 * launch_mean * min(ratio, 4.0), _WINDOW_MIN_S), _WINDOW_MAX_S)


class VerifyCoalescer:
    """Time/size-windowed merge of concurrent verify requests.

    Consumers `submit()` triples (already dedup-filtered by the caller)
    and get a `SubHandle`; a flusher thread merges pending requests —
    round-robin across consumers, whole requests only, per-consumer FIFO
    — into single `verify_batch_async` launches on the coalescer's own
    `DispatchQueue`; a joiner thread joins merged handles in submission
    order and scatters the verdict slices back out, feeding proven
    positives to the dedup cache.

    Flush triggers (`tendermint_batcher_flush_total{reason}`):
      window  — the oldest pending request aged past the flush window;
      size    — pending triples reached the max merged batch;
      barrier — a consumer joined an unflushed request (latency beats
                coalescing for whoever is already blocked).
    """

    def __init__(
        self,
        verifier: BatchVerifier,
        cache: VerifiedSigCache | None = None,
        max_batch: int | None = None,
        window_s: float | None = None,
        depth: int = 2,
    ) -> None:
        self._verifier = verifier
        self._cache = cache
        self._max_batch = (
            MAX_COALESCED_BATCH if max_batch is None else max(1, max_batch)
        )
        self._fixed_window = window_s
        self._window_s = window_s if window_s is not None else 0.002
        self._depth = depth
        # Non-reentrant usage throughout; ranked above the handle locks
        # (sub-handle joins may poke the window under a handle join).
        self._cond = threading.Condition(ranked_lock("batcher.window"))
        self._queues: "dict[str, deque[_Request]]" = {}
        self._pending_triples = 0
        self._barrier = False
        self._rr_idx = 0
        self._flushes = 0
        self._running = False
        self._queue = None  # created with the threads
        self._flusher: threading.Thread | None = None
        self._joiner: threading.Thread | None = None
        self._join_q: "queue_mod.SimpleQueue" = queue_mod.SimpleQueue()

    # -- lifecycle ---------------------------------------------------------

    def _ensure_threads(self) -> None:
        if self._running:
            return
        with self._cond:
            if self._running:
                return
            from tendermint_tpu.services.dispatch import DispatchQueue

            if self._queue is None:
                self._queue = DispatchQueue(depth=self._depth, name="coalescer")
            self._running = True
            self._flusher = threading.Thread(
                target=self._flush_loop, name="verify-coalescer", daemon=True
            )
            self._joiner = threading.Thread(
                target=self._join_loop, name="verify-coalescer-join", daemon=True
            )
            self._flusher.start()
            self._joiner.start()

    def close(self) -> None:
        """Flush the backlog and stop both threads (tests; production
        coalescers live for the process)."""
        with self._cond:
            self._running = False
            self._cond.notify_all()
        if self._flusher is not None:
            self._flusher.join(timeout=5)
        self._join_q.put(_STOP)
        if self._joiner is not None:
            self._joiner.join(timeout=5)
        if self._queue is not None:
            self._queue.close()

    # -- submit side -------------------------------------------------------

    def submit(self, triples: Sequence[Triple], consumer: str = "default") -> SubHandle:
        """Cache-filter `triples` and queue the novel remainder for the
        next coalesced launch; the returned handle resolves to the full
        per-item verdict mask (cache hits pre-filled True)."""
        cache = self._cache
        n = len(triples)
        out = np.zeros(n, dtype=bool)
        novel: list[Triple] = []
        novel_pos: list[int] = []
        novel_keys: list[bytes] = []
        for i, (pk, msg, sig) in enumerate(triples):
            key = VerifiedSigCache.key(pk, msg, sig) if cache is not None else None
            if key is not None and cache.hit(key):
                out[i] = True
                continue
            novel.append((pk, msg, sig))
            novel_pos.append(i)
            novel_keys.append(key)
        req = _Request(
            consumer, out, novel, novel_pos, novel_keys, ctx=_trace.current()
        )
        if not novel:
            req.flushed = True
            req.event.set()
            return SubHandle(self, req)
        self._ensure_threads()
        with self._cond:
            self._queues.setdefault(consumer, deque()).append(req)
            self._pending_triples += len(novel)
            self._cond.notify_all()
        return SubHandle(self, req)

    def request_barrier(self) -> None:
        """A consumer is blocked on an unflushed request: flush now."""
        with self._cond:
            self._barrier = True
            self._cond.notify_all()

    def stats(self) -> dict:
        """Live window state for the `dump_telemetry?profile=1` queue
        view: pending requests/triples per consumer, the current flush
        window, flushes so far."""
        with self._cond:
            return {
                "window_ms": round(self._window_s * 1e3, 3),
                "max_batch": self._max_batch,
                "flushes": self._flushes,
                "pending_triples": self._pending_triples,
                "pending_requests": {
                    c: len(q) for c, q in self._queues.items() if q
                },
            }

    # -- flusher -----------------------------------------------------------

    def _oldest_age_locked(self, now: float) -> float | None:
        oldest = None
        for q in self._queues.values():
            if q and (oldest is None or q[0].submitted_at < oldest):
                oldest = q[0].submitted_at
        return None if oldest is None else now - oldest

    def _flush_reason_locked(self, now: float) -> str | None:
        if self._pending_triples == 0:
            # a barrier with nothing pending is satisfied trivially
            self._barrier = False
            return None
        if self._barrier:
            return "barrier"
        if self._pending_triples >= self._max_batch:
            return "size"
        age = self._oldest_age_locked(now)
        if age is not None and age >= self._window_s:
            return "window"
        return None

    def _take_locked(self) -> list[_Request]:
        """Round-robin pop: one whole request per non-empty consumer per
        cycle, cycles until the size cap or empty. Per-consumer FIFO is
        preserved — that is the drain-order discipline sub-handles keep."""
        consumers = [c for c, q in self._queues.items() if q]
        if not consumers:
            return []
        start = self._rr_idx % len(consumers)
        self._rr_idx += 1
        order = consumers[start:] + consumers[:start]
        batch: list[_Request] = []
        total = 0
        progressed = True
        while progressed and total < self._max_batch:
            progressed = False
            for c in order:
                q = self._queues[c]
                if not q or total >= self._max_batch:
                    continue
                req = q.popleft()
                req.flushed = True
                batch.append(req)
                total += len(req.novel)
                progressed = True
        self._pending_triples -= total
        if self._pending_triples <= 0:
            self._pending_triples = 0
            self._barrier = False
        return batch

    def _flush_loop(self) -> None:
        while True:
            with self._cond:
                now = time.perf_counter()
                reason = self._flush_reason_locked(now)
                while reason is None and self._running:
                    age = self._oldest_age_locked(now)
                    timeout = (
                        None if age is None else max(0.0, self._window_s - age)
                    )
                    self._cond.wait(timeout)
                    now = time.perf_counter()
                    reason = self._flush_reason_locked(now)
                if reason is None and not self._running:
                    return  # close(): nothing pending, exit
                batch = self._take_locked()
            if batch:
                self._launch(batch, reason)

    def _launch(self, batch: list[_Request], reason: str) -> None:
        now = time.perf_counter()
        _metrics.BATCHER_FLUSH.labels(reason=reason).inc()
        _metrics.BATCHER_COALESCE.observe(len(batch))
        for req in batch:
            _metrics.BATCHER_WAIT.labels(consumer=req.consumer).observe(
                now - req.submitted_at
            )
        merged: list[Triple] = []
        for req in batch:
            merged.extend(req.novel)
        # trace attribution: one exemplar context per merged launch (the
        # oldest traced request's) — the flush span links the aggregate
        # back to a concrete traced message, and launching with it
        # ambient carries it into the dispatch-queue handle
        exemplar = next((r.ctx for r in batch if r.ctx is not None), None)
        FLIGHT.record(
            "coalescer_flush",
            reason=reason,
            requests=len(batch),
            triples=len(merged),
        )
        if exemplar is not None:
            wall_now = time.time()
            oldest = min(req.submitted_at for req in batch)
            TRACER.add(
                "batcher.flush",
                wall_now - (now - oldest),
                wall_now,
                trace=exemplar.trace,
                reason=reason,
                requests=len(batch),
                triples=len(merged),
            )
        # launch-ledger tags: the flush-level facts only the coalescer
        # knows — consumer mix, rows the dedup cache withheld from this
        # launch, request count — captured by the dispatch handle at
        # submit (telemetry/launchlog.py)
        consumer_rows: dict[str, int] = {}
        cached_rows = 0
        for req in batch:
            consumer_rows[req.consumer] = consumer_rows.get(
                req.consumer, 0
            ) + len(req.novel)
            cached_rows += len(req.out) - len(req.novel)
        try:
            with _launchlog.tag(
                consumers=consumer_rows,
                rows_cached=cached_rows,
                requests=len(batch),
            ), _trace.use(exemplar):
                if hasattr(self._verifier, "verify_batch_async"):
                    handle = self._verifier.verify_batch_async(
                        merged, queue=self._queue
                    )
                else:
                    handle = self._queue.submit(
                        lambda m=merged: self._verifier.verify_batch(m),
                        kind="verify",
                    )
        except BaseException as e:  # dispatch-layer failure: fail the batch
            for req in batch:
                req.error = e
                req.event.set()
            return
        self._flushes += 1
        if self._fixed_window is None and self._flushes % _WINDOW_REFRESH_FLUSHES == 1:
            try:
                self._window_s = _adaptive_window_s()
            except Exception:
                pass
        self._join_q.put((handle, batch))

    # -- joiner ------------------------------------------------------------

    def _join_loop(self) -> None:
        while True:
            item = self._join_q.get()
            if item is _STOP:
                return
            handle, batch = item
            try:
                mask = handle.result()
            except BaseException as e:
                for req in batch:
                    req.error = e
                    req.event.set()
                continue
            at = 0
            cache = self._cache
            for req in batch:
                k = len(req.novel)
                verdicts = mask[at : at + k]
                at += k
                for pos, key, ok in zip(req.novel_pos, req.novel_keys, verdicts):
                    ok = bool(ok)
                    req.out[pos] = ok
                    if ok and cache is not None and key is not None:
                        cache.add(key)  # positives only
                req.novel = req.novel_keys = None  # drop payloads promptly
                req.event.set()


class CoalescingVerifier(BatchVerifier):
    """The verify-spine facade: dedup cache + coalescer over any inner
    `BatchVerifier` (normally the resilient device stack).

    * `verify_batch` (sync) — cache-filter, verify the novel remainder
      on the inner backend directly (no window wait), feed positives
      back to the cache.
    * `verify_batch_async` — cache-filter + coalesce: concurrent
      consumers share launches (the `queue` argument is ignored — the
      coalescer owns its dispatch queue so merged launches from all
      consumers stay FIFO; per-consumer order is preserved regardless).
    * `verify_commits*` — lane-level cache filtering in front of the
      inner commit-grid path: cached lanes are withheld from the device
      and re-merged as True at the join; when the inner backend has no
      grid surface the novel lanes route through the coalescer as flat
      triples.
    """

    accepts_consumer = True

    def __init__(
        self,
        inner: BatchVerifier,
        cache_size: int | None = None,
        window_s: float | None = None,
        max_batch: int | None = None,
    ) -> None:
        super().__init__()
        self.inner = inner
        cache = VerifiedSigCache(cache_size)
        self.cache = cache if cache.enabled else None
        if max_batch is None:
            # A merged launch should be able to FILL the whole mesh:
            # the per-launch cap is per-chip, so N chips coalesce N
            # windows' worth before the size trigger fires (the env
            # knob stays a per-chip figure either way).
            max_batch = MAX_COALESCED_BATCH * _mesh_width(inner)
        self.coalescer = VerifyCoalescer(
            inner, self.cache, max_batch=max_batch, window_s=window_s
        )

    # -- passthrough -------------------------------------------------------

    @property
    def breaker(self):
        return self.inner.breaker

    @property
    def degraded(self) -> bool:
        return bool(getattr(self.inner, "degraded", False))

    def snapshot(self) -> dict:
        out = {}
        if hasattr(self.inner, "snapshot"):
            out.update(self.inner.snapshot())
        if self.cache is not None:
            out["verify_cache"] = self.cache.stats()
        return out

    def stats(self) -> dict:
        """Coalescer window state (queue-wait unification view)."""
        return self.coalescer.stats()

    def prebuild(self, pubkeys) -> None:
        if hasattr(self.inner, "prebuild"):
            self.inner.prebuild(pubkeys)

    def warm_kernels(self) -> None:
        if hasattr(self.inner, "warm_kernels"):
            self.inner.warm_kernels()

    def close(self) -> None:
        self.coalescer.close()

    # -- flat triples ------------------------------------------------------

    def verify_batch(self, triples: Sequence[Triple]) -> np.ndarray:
        cache = self.cache
        if cache is None:
            return self.inner.verify_batch(triples)
        out = np.zeros(len(triples), dtype=bool)
        novel, novel_pos, novel_keys = [], [], []
        for i, (pk, msg, sig) in enumerate(triples):
            key = VerifiedSigCache.key(pk, msg, sig)
            if cache.hit(key):
                out[i] = True
            else:
                novel.append((pk, msg, sig))
                novel_pos.append(i)
                novel_keys.append(key)
        if novel:
            verdicts = self.inner.verify_batch(novel)
            for pos, key, ok in zip(novel_pos, novel_keys, verdicts):
                ok = bool(ok)
                out[pos] = ok
                if ok:
                    cache.add(key)
        return out

    def verify_batch_async(
        self, triples: Sequence[Triple], queue=None, consumer: str = "default"
    ):
        return self.coalescer.submit(triples, consumer=consumer)

    # -- commit grids ------------------------------------------------------

    def _filter_lanes(self, pubkeys, commits):
        """Split commit lanes into cached (verdict already proven) and
        novel. Returns (filtered_commits, cached_mask, novel_lanes) with
        novel_lanes = [(ci, lane, key), ...] for post-verdict caching."""
        n = len(pubkeys)
        k = len(commits)
        cached = np.zeros((k, n), dtype=bool)
        novel_lanes: list[tuple[int, int, bytes]] = []
        filtered = []
        any_novel = False
        cache = self.cache
        for ci, (msgs, sigs) in enumerate(commits):
            f_msgs: list = [None] * n
            f_sigs: list = [None] * n
            for i in range(n):
                msg, sig = msgs[i], sigs[i]
                if msg is None or sig is None:
                    continue
                key = None
                if cache is not None:
                    key = VerifiedSigCache.key(pubkeys[i], msg, sig)
                    if cache.hit(key):
                        cached[ci, i] = True
                        continue
                f_msgs[i], f_sigs[i] = msg, sig
                novel_lanes.append((ci, i, key))
                any_novel = True
            filtered.append((f_msgs, f_sigs))
        return filtered, cached, novel_lanes, any_novel

    def _merge_grid(self, grid, cached, novel_lanes) -> np.ndarray:
        out = np.asarray(grid, dtype=bool) | cached
        cache = self.cache
        if cache is not None:
            for ci, i, key in novel_lanes:
                if out[ci, i] and key is not None:
                    cache.add(key)
        return out

    def _flat_lane_grid(self, pubkeys, filtered, cached, novel_lanes, consumer):
        """Inner backend has no commit-grid surface: route the novel
        lanes through the coalescer as flat triples and scatter the
        verdict mask back to grid shape."""
        triples = [
            (pubkeys[i], filtered[ci][0][i], filtered[ci][1][i])
            for ci, i, _key in novel_lanes
        ]
        handle = self.coalescer.submit(triples, consumer=consumer)

        def _assemble(mask):
            out = cached.copy()
            cache = self.cache
            for (ci, i, key), ok in zip(novel_lanes, mask):
                if bool(ok):
                    out[ci, i] = True
                    if cache is not None and key is not None:
                        cache.add(key)
            return out

        return handle.then(_assemble)

    def verify_commits(self, pubkeys, commits, force_fused=None) -> np.ndarray:
        if self.cache is None and hasattr(self.inner, "verify_commits"):
            return self.inner.verify_commits(
                pubkeys, commits, force_fused=force_fused
            )
        filtered, cached, novel_lanes, any_novel = self._filter_lanes(
            pubkeys, commits
        )
        if not any_novel:
            return cached
        if hasattr(self.inner, "verify_commits"):
            # the withheld lanes never reach the device — the launch
            # record carries how many the cache saved it
            with _launchlog.tag(rows_cached=int(cached.sum())):
                grid = self.inner.verify_commits(
                    pubkeys, filtered, force_fused=force_fused
                )
            return self._merge_grid(grid, cached, novel_lanes)
        return self._flat_lane_grid(
            pubkeys, filtered, cached, novel_lanes, "default"
        ).result()

    def verify_commits_async(
        self, pubkeys, commits, queue=None, force_fused=None, consumer="default"
    ):
        from tendermint_tpu.services.dispatch import CompletedHandle

        if self.cache is None and hasattr(self.inner, "verify_commits_async"):
            return self.inner.verify_commits_async(
                pubkeys, commits, queue=queue, force_fused=force_fused
            )
        filtered, cached, novel_lanes, any_novel = self._filter_lanes(
            pubkeys, commits
        )
        if not any_novel:
            return CompletedHandle(cached)
        if hasattr(self.inner, "verify_commits_async"):
            with _launchlog.tag(rows_cached=int(cached.sum())):
                handle = self.inner.verify_commits_async(
                    pubkeys, filtered, queue=queue, force_fused=force_fused
                )
            return handle.then(
                lambda grid: self._merge_grid(grid, cached, novel_lanes)
            )
        return self._flat_lane_grid(
            pubkeys, filtered, cached, novel_lanes, consumer
        )
