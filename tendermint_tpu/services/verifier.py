"""BatchVerifier: accumulate→flush ed25519 verification service.

The reference verifies one signature at a time inside its hot loops
(`types/vote_set.go:177`, `types/validator_set.go:253`). Here every
consumer — VoteSet, ValidatorSet.verify_commit, fast-sync, light client —
talks to a `BatchVerifier`:

* `verify_batch(triples)` — synchronous batch verdicts (the call the
  types layer already targets);
* `add(pk, msg, sig)` / `flush()` — optimistic accumulation across
  call sites, flushed as one device batch (SURVEY.md §7 hard part 3:
  per-item verdict masks preserve per-vote error attribution).

Backends: `HostBatchVerifier` (sequential host library — the CPU
baseline and the no-TPU test fake) and `DeviceBatchVerifier` (the
batched curve kernel in `ops.ed25519_kernel`, padded to power-of-two
buckets so recompiles stay bounded).
"""

from __future__ import annotations

import os
import time
from typing import Sequence

import numpy as np

from tendermint_tpu.telemetry import launchlog as _launchlog
from tendermint_tpu.telemetry import metrics as _metrics

Triple = tuple[bytes, bytes, bytes]  # (pubkey32, message, signature64)


def _observe_verify(
    backend: str, n: int, seconds: float, kind: str = "verify"
) -> None:
    """One verify call's worth of hot-path telemetry. Each executing
    backend reports itself, so a resilient host fallback shows up under
    backend="host" while the failed device attempt stays attributed to
    the dispatch-failure counters. Also closes/annotates the ambient
    LaunchLedger record (telemetry/launchlog.py) — the device
    observatory's one-record-per-launch seam."""
    _metrics.VERIFY_BATCH_SIZE.labels(backend=backend).observe(n)
    _metrics.VERIFY_SECONDS.labels(backend=backend).observe(seconds)
    _launchlog.observe(kind, backend, n, seconds)


class BatchVerifier:
    """Interface + shared accumulate/flush bookkeeping."""

    # Every in-tree verifier tolerates the `consumer=` tag on its async
    # surface (the coalescer uses it for fairness + wait telemetry;
    # plain backends ignore it). Call sites gate on this attribute so
    # minimal test fakes without the kwarg keep working
    # (`services/batcher.py::consumer_kwargs`).
    accepts_consumer = True

    def verify_batch(self, triples: Sequence[Triple]) -> np.ndarray:
        raise NotImplementedError

    def __init__(self) -> None:
        self._pending: list[Triple] = []

    def add(self, pubkey: bytes, msg: bytes, sig: bytes) -> int:
        """Queue a triple; returns its index into the next flush()'s mask."""
        self._pending.append((pubkey, msg, sig))
        return len(self._pending) - 1

    def pending(self) -> int:
        return len(self._pending)

    def flush(self) -> np.ndarray:
        """Verify everything queued since the last flush; per-item verdicts."""
        triples, self._pending = self._pending, []
        if not triples:
            return np.zeros(0, dtype=bool)
        return self.verify_batch(triples)

    def verify_one(self, pubkey: bytes, msg: bytes, sig: bytes) -> bool:
        return bool(self.verify_batch([(pubkey, msg, sig)])[0])

    # -- async seam (services/dispatch.py) ---------------------------------
    #
    # Backends split a verify into `launch` (host prep + device kernel
    # dispatch — cheap, the device computes in the background) and
    # `finalize` (materialize + mask — where np.asarray blocks). The
    # base implementation has no device half, so launch does the whole
    # verify — still useful: run on a DispatchQueue worker it overlaps
    # with the submitter's host work.

    def launch_verify_batch(self, triples: Sequence[Triple]):
        return self.verify_batch(triples)

    def finalize_verify_batch(self, launched) -> np.ndarray:
        return launched

    def verify_batch_async(
        self, triples: Sequence[Triple], queue=None, consumer: str = "default"
    ):
        """Submit a batch verify through a `DispatchQueue`; returns a
        `VerifyHandle` whose `.result()` yields the same per-item
        verdict mask `verify_batch` would. Device arrays stay
        un-materialized until the join. `consumer` only matters to the
        coalescing wrapper; plain backends ignore it."""
        from tendermint_tpu.services.dispatch import default_dispatch_queue

        q = queue if queue is not None else default_dispatch_queue()
        return q.submit(
            lambda: self.launch_verify_batch(triples),
            self.finalize_verify_batch,
            kind="verify",
        )


class HostBatchVerifier(BatchVerifier):
    """Sequential host-library backend (CPU baseline / TPU-free tests)."""

    def verify_batch(self, triples: Sequence[Triple]) -> np.ndarray:
        from tendermint_tpu.crypto.keys import PUBKEY_LEN, PubKey

        t0 = time.perf_counter()
        out = np.zeros(len(triples), dtype=bool)
        for i, (pk, msg, sig) in enumerate(triples):
            if len(pk) != PUBKEY_LEN:
                continue
            out[i] = PubKey(pk).verify(msg, sig)
        _observe_verify("host", len(triples), time.perf_counter() - t0)
        return out


# Below this many lanes the host library wins: every device launch has a
# fixed cost (measured ~86 ms through the axon tunnel, docs/
# PLATFORM_NOTES.md) while a host verify is ~60 us — single votes and
# small commits must never wait on a kernel launch (the consensus hot
# path verifies one gossiped vote at a time).
DEVICE_MIN_BATCH = int(os.environ.get("TENDERMINT_TPU_MIN_DEVICE_BATCH", "512"))


class DeviceBatchVerifier(BatchVerifier):
    """TPU-batched backend over `ops.ed25519_kernel.batch_verify`.

    Batches are padded to power-of-two buckets (min 8) inside
    batch_verify; compiled executables persist in the jit cache per
    bucket size. Batches smaller than DEVICE_MIN_BATCH short-circuit to
    the host library (launch overhead dominates there).
    """

    def __init__(self, min_device_batch: int | None = None) -> None:
        super().__init__()
        self._host = HostBatchVerifier()
        self._min_batch = (
            DEVICE_MIN_BATCH if min_device_batch is None else min_device_batch
        )

    def verify_batch(self, triples: Sequence[Triple]) -> np.ndarray:
        return self.finalize_verify_batch(self.launch_verify_batch(triples))

    def launch_verify_batch(self, triples: Sequence[Triple]):
        """Host prep + device kernel dispatch; the verdict array stays
        on device until `finalize_verify_batch` (sub-min batches answer
        on host immediately — a single vote must never wait in a
        pipeline behind a kernel launch)."""
        if not triples:
            return ("host", np.zeros(0, dtype=bool))
        if len(triples) < self._min_batch:
            return ("host", self._host.verify_batch(triples))
        from tendermint_tpu.ops.ed25519_kernel import (
            bucket_size,
            launch_batch_verify,
        )

        pubs, msgs, sigs = zip(*triples)
        t0 = time.perf_counter()
        launched = launch_batch_verify(list(pubs), list(msgs), list(sigs))
        # occupancy/transfer attribution: launch_batch_verify pads to
        # the power-of-two bucket and ships 4 (size, 32) u8 arrays
        size = bucket_size(len(triples))
        _launchlog.annotate(_additive=True, rows_padded=size - len(triples))
        _launchlog.add_transfer(4 * size * 32)
        return ("device", launched, t0)

    def finalize_verify_batch(self, launched) -> np.ndarray:
        if launched[0] == "host":
            return launched[1]
        from tendermint_tpu.ops.ed25519_kernel import materialize_batch_verify

        _tag, payload, t0 = launched
        out = materialize_batch_verify(payload)
        # latency covers launch -> materialized (in-flight time included:
        # that is what the pipeline hides, and what the sync path paid)
        _observe_verify("device", len(out), time.perf_counter() - t0)
        return out


class TableBuildError(RuntimeError):
    """Comb-table construction is unavailable (device build faulted and
    the set is too large to host-build); callers answer with host
    crypto."""


class TableBatchVerifier(DeviceBatchVerifier):
    """Valset-table-cached backend: the steady-state consensus fast path.

    Commit-shaped verification (lanes aligned to a known validator set)
    routes through per-validator comb tables (`ops.ed25519_tables`) built
    ON DEVICE once per validator set and cached by the hash of its pubkey
    sequence — SURVEY.md §7 hard part 4's pre-staged valset arrays. A
    cached verify costs ~0.7k field muls/signature vs ~4.8k for the
    generic ladder `DeviceBatchVerifier` falls back to for ad-hoc
    triples (proposal sigs, mixed-key batches).
    """

    # diffs up to this many NEW keys build host-side (0.14 s/key,
    # compile-free); larger diffs build the missing keys as one device
    # kernel call (~0.7 s warm per 2048 keys — the persistent XLA cache
    # keeps even a fresh process warm, utils/jax_cache.py)
    MAX_INCREMENTAL_KEYS = 128

    def __init__(self, cache_size: int = 4, min_device_batch: int | None = None) -> None:
        super().__init__(min_device_batch)
        import threading
        from collections import OrderedDict

        from tendermint_tpu.utils.circuit import CircuitBreaker

        # key -> (pubkeys tuple, tables, ok)
        self._tables: "OrderedDict[bytes, tuple]" = OrderedDict()
        self._cache_size = cache_size
        self._cache_lock = threading.RLock()
        # Table CONSTRUCTION gets its own breaker (ROADMAP open item):
        # the build kernel is a separate executable from the verify
        # kernel, so it can be sick on its own — N build faults stop us
        # dialing the device builder, and small sets degrade to the
        # compile-free host build while verify stays on device.
        self._build_breaker = CircuitBreaker(
            failure_threshold=int(
                os.environ.get("TENDERMINT_TPU_BREAKER_THRESHOLD", 3)
            ),
            reset_timeout_s=float(
                os.environ.get("TENDERMINT_TPU_BREAKER_RESET_S", 5.0)
            ),
            name="tables",
        )

    @staticmethod
    def _cache_key(pubkeys: tuple[bytes, ...]) -> bytes:
        import hashlib

        return hashlib.sha256(b"".join(pubkeys)).digest()

    def _incremental_build(self, pubkeys: tuple[bytes, ...]):
        """Assemble tables for `pubkeys` from the cached set with the
        largest overlap: device-gather the shared columns, host-build
        only the new keys. Returns None when no cached set shares enough
        (EndBlock diffs touch few keys — reference
        `state/execution.go:120-159` — so turnover is usually tiny)."""
        import jax.numpy as jnp

        from tendermint_tpu.ops.ed25519_tables import host_build_key_tables

        with self._cache_lock:
            best = None
            for _k, (old_pubs, old_t, old_ok) in self._tables.items():
                pos = {pk: i for i, pk in enumerate(old_pubs)}
                hits = sum(1 for pk in pubkeys if pk in pos)
                if best is None or hits > best[0]:
                    best = (hits, pos, old_t, old_ok)
        if best is None or best[0] == 0:
            # no overlap: concatenating against an unrelated cached set
            # would copy its whole table on device for nothing
            return None
        hits, pos, old_t, old_ok = best
        missing = [pk for pk in pubkeys if pk not in pos]
        if missing:
            if len(missing) <= self.MAX_INCREMENTAL_KEYS:
                new_t, new_ok = host_build_key_tables(missing)
                new_t = jnp.asarray(new_t)
            else:
                # big turnover (e.g. a 500-key valset rotation): the
                # device build kernel beats 0.14 s/key host work (and
                # pads to the ONE chunk-shaped executable on TPU itself)
                from tendermint_tpu.ops.ed25519_tables import build_key_tables

                miss_arr = np.frombuffer(b"".join(missing), dtype=np.uint8)
                new_t, new_ok = build_key_tables(miss_arr.reshape(-1, 32))
            combined = jnp.concatenate([old_t, new_t], axis=3)
            ok_comb = np.concatenate([old_ok, new_ok])
        else:  # same keys, different order/subset: pure gather
            combined, ok_comb = old_t, old_ok
        new_pos = {pk: i for i, pk in enumerate(missing)}
        n_old = len(pos)
        perm = np.array(
            [pos.get(pk, n_old + new_pos.get(pk, 0)) for pk in pubkeys],
            dtype=np.int32,
        )
        tables = jnp.take(combined, jnp.asarray(perm), axis=3)
        return tables, ok_comb[perm]

    def _tables_for(self, pubkeys: tuple[bytes, ...]):
        key = self._cache_key(pubkeys)
        with self._cache_lock:
            hit = self._tables.get(key)
            if hit is not None:
                self._tables.move_to_end(key)
                _metrics.TABLE_CACHE.labels(event="hit").inc()
                return hit[1], hit[2]
        _metrics.TABLE_CACHE.labels(event="miss").inc()
        tables, ok = self._build_tables(pubkeys)
        with self._cache_lock:
            self._tables[key] = (tuple(pubkeys), tables, ok)
            while len(self._tables) > self._cache_size:
                self._tables.popitem(last=False)
        return tables, ok

    def _build_tables(self, pubkeys: tuple[bytes, ...]):
        """Construct tables for an uncached set, behind the table-build
        breaker: device build (incremental when a cached set overlaps),
        degrading to the compile-free host build for sets small enough
        to afford it (~0.14 s/key), else raising `TableBuildError` so
        `verify_commits` answers with host crypto."""
        from tendermint_tpu.utils.fail import device_fail_point

        if self._build_breaker.allow():
            try:
                device_fail_point("tables")
                built = self._incremental_build(pubkeys)
                if built is not None:
                    _metrics.TABLE_CACHE.labels(event="incremental").inc()
                else:
                    from tendermint_tpu.ops.ed25519_tables import build_key_tables

                    pub = np.frombuffer(b"".join(pubkeys), dtype=np.uint8).reshape(
                        len(pubkeys), 32
                    )
                    built = build_key_tables(pub)
                self._build_breaker.record_success()
                return built
            except Exception as e:
                self._build_breaker.record_failure()
                import logging

                from tendermint_tpu.utils.log import kv, logger

                kv(
                    logger("resilient"),
                    logging.WARNING,
                    "table build failed",
                    n_keys=len(pubkeys),
                    error=f"{type(e).__name__}: {e}"[:120],
                    breaker=self._build_breaker.state,
                )
        if len(pubkeys) <= self.MAX_INCREMENTAL_KEYS:
            import jax.numpy as jnp

            from tendermint_tpu.ops.ed25519_tables import host_build_key_tables

            _metrics.TABLE_CACHE.labels(event="host_build").inc()
            t, ok = host_build_key_tables(list(pubkeys))
            return jnp.asarray(t), ok
        raise TableBuildError(
            f"table build unavailable for {len(pubkeys)} keys "
            f"(breaker {self._build_breaker.state})"
        )

    def warm_kernels(self) -> None:
        """Background-load the chunked build executable (one dummy
        2048-key device build) so the FIRST real valset build doesn't
        pay the ~25 s per-process executable upload (the compile itself
        is served by the persistent cache — utils/jax_cache.py). Called
        by the node at startup on TPU backends."""
        import threading

        import jax

        if jax.default_backend() != "tpu":
            return  # XLA:CPU would burn minutes compiling a kernel this
            # host will never use at scale (docs/PLATFORM_NOTES.md)

        def _warm():
            try:
                import numpy as _np

                from tendermint_tpu.ops.ed25519_tables import build_key_tables

                dummy = _np.zeros((2048, 32), dtype=_np.uint8)
                dummy[:, 0] = 1  # identity encodings: decompress cleanly
                build_key_tables(dummy)
            except Exception:
                pass  # warming is best-effort

        threading.Thread(target=_warm, daemon=True, name="warm-build-kernel").start()

    def prebuild(self, pubkeys) -> None:
        """Warm the table cache for a validator set in the background —
        called when a valset rotation is decided (EndBlock diffs) so the
        first verify against the NEXT set doesn't stall on a build."""
        import threading

        pubs = tuple(bytes(pk) for pk in pubkeys)
        if self._cache_key(pubs) in self._tables:
            return

        threading.Thread(
            target=lambda: self._tables_for(pubs), daemon=True
        ).start()

    def verify_commits(
        self,
        pubkeys: Sequence[bytes],
        commits: Sequence[tuple[Sequence[bytes | None], Sequence[bytes | None]]],
        force_fused: bool | None = None,
    ) -> np.ndarray:
        """K commits over one N-validator set -> (K, N) bool verdicts.

        Each commit is (msgs, sigs): length-N sequences aligned to
        validator index, None marking absent votes (absent lanes report
        False — callers already track presence). Replaces the
        reference's per-commit sequential loop
        (`types/validator_set.go:236-261`) with one K*N-lane device
        batch against cached tables; fast-sync stacks many commits of
        the same valset into a single call (BASELINE config 3).

        `force_fused` overrides the fused-shaping decision (tests gate
        the chunk/pad logic on the CPU mesh with it); None = auto.
        """
        return self.finalize_verify_commits(
            self.launch_verify_commits(pubkeys, commits, force_fused=force_fused)
        )

    def launch_verify_commits(
        self, pubkeys, commits, force_fused: bool | None = None
    ):
        """Async half of `verify_commits`: lane prep + one kernel launch
        per chunk, device outputs left un-materialized (the fast-sync
        pipeline preps/applies other windows while these fly). Paths
        with no device half (small commits, table-build degradation)
        compute on the spot and tag themselves "host"."""
        from tendermint_tpu.ops.ed25519_tables import (
            prepare_commit_lanes,
            verify_tables_kernel,
        )

        n = len(pubkeys)
        k = len(commits)
        if n == 0 or k == 0:
            return ("host", np.zeros((k, n), dtype=bool))
        if k * n < self._min_batch:
            # small commits: host loop beats a device launch
            return ("host", self._host_commit_loop(pubkeys, commits))
        # malformed pubkeys degrade to a False verdict (matching every
        # other backend) instead of corrupting the packed table build
        length_ok = np.array([len(pk) == 32 for pk in pubkeys], dtype=bool)
        if not length_ok.all():
            placeholder = b"\x01" + b"\x00" * 31  # identity point encoding
            pubkeys = [
                pk if ok else placeholder for pk, ok in zip(pubkeys, length_ok)
            ]
        try:
            tables, key_ok = self._tables_for(tuple(pubkeys))
        except TableBuildError:
            # table construction is down and the set is too big to
            # host-build: answer this call with host crypto (slow but
            # correct) instead of raising out of the consensus path
            return ("host", self._host_commit_loop(pubkeys, commits))
        key_ok = key_ok & length_ok
        # The fused pallas path wants K in multiples of 8 (lane planes
        # are (8, 16K)) up to MAX_FUSED_STACK; pad with absent-vote
        # commits (verify False, masked by precheck) and chunk larger
        # windows so every launch takes the fast path. Only shape for it
        # when it actually wins: off-TPU the kernel never selects fused
        # (padding would be pure wasted lanes), and below K=8 the fused
        # launch's fixed cost (~107 ms: table read + dispatch,
        # docs/PLATFORM_NOTES.md) exceeds the materialized K-small path
        # (~63 ms) — single-commit latency is the consensus loop's.
        import jax

        from tendermint_tpu.ops.ed25519_tables import MAX_FUSED_STACK

        fusable = (
            (n % 128 == 0 and k >= 8 and jax.default_backend() == "tpu")
            if force_fused is None
            else force_fused
        )
        launches = []  # (device_out, precheck, real, part_len) per chunk
        chunk = MAX_FUSED_STACK if fusable else len(commits)
        t0 = time.perf_counter()
        for lo in range(0, k, chunk):
            part = list(commits[lo : lo + chunk])
            real = len(part)
            if fusable and real % 8 != 0:
                absent = ([None] * n, [None] * n)
                part.extend([absent] * (8 - real % 8))
            s, h, r, precheck = prepare_commit_lanes(pubkeys, part)
            dev = verify_tables_kernel(tables, s, h, r)
            launches.append((dev, precheck, real, len(part)))
            _launchlog.annotate(
                _additive=True, rows_padded=(len(part) - real) * n
            )
            _launchlog.add_transfer(s.nbytes + h.nbytes + r.nbytes)
        return ("device", launches, key_ok, n, k, t0)

    def finalize_verify_commits(self, launched) -> np.ndarray:
        if launched[0] == "host":
            return launched[1]
        _tag, launches, key_ok, n, k, t0 = launched
        out_rows = []
        for dev, precheck, real, part_len in launches:
            out = np.asarray(dev)
            out = (out & precheck & np.tile(key_ok, part_len)).reshape(-1, n)
            out_rows.append(out[:real])
        _observe_verify("tables", k * n, time.perf_counter() - t0, kind="tables")
        return np.concatenate(out_rows, axis=0)

    def verify_commits_async(
        self,
        pubkeys,
        commits,
        queue=None,
        force_fused: bool | None = None,
        consumer: str = "default",
    ):
        """`verify_commits` through the dispatch queue: a VerifyHandle
        resolving to the (K, N) verdict grid, kernels in flight until
        the consumer joins."""
        from tendermint_tpu.services.dispatch import default_dispatch_queue

        q = queue if queue is not None else default_dispatch_queue()
        return q.submit(
            lambda: self.launch_verify_commits(
                pubkeys, commits, force_fused=force_fused
            ),
            self.finalize_verify_commits,
            kind="verify",
        )

    def _host_commit_loop(self, pubkeys, commits) -> np.ndarray:
        """Sequential host verification of commit-shaped lanes — the
        small-commit path and the table-build degradation target."""
        n = len(pubkeys)
        out = np.zeros((len(commits), n), dtype=bool)
        for ci, (msgs, sigs) in enumerate(commits):
            lanes = [
                i
                for i in range(n)
                if msgs[i] is not None and sigs[i] is not None
            ]
            lane_triples = [(pubkeys[i], msgs[i], sigs[i]) for i in lanes]
            if lane_triples:
                verdicts = self._host.verify_batch(lane_triples)
                for i, v in zip(lanes, verdicts):
                    out[ci, i] = v
        return out


class _MeshFlatMixin:
    """Shared mesh plumbing for the sharded verifier backends: the flat
    (pub, r, s, h) batch lane over `parallel.mesh.MeshManager`, with the
    survivor re-mesh loop around every launch.

    Geometry: rows are padded to `per_shard_bucket * n_active` where the
    per-shard bucket is the power-of-two `bucket_size` of the per-chip
    share (min 8) — compiled executables are keyed by PER-CHIP shard
    shape, so the same buckets serve any mesh size and a survivor
    re-mesh only recompiles the step, not a new zoo of shapes.
    """

    mesh = None  # set by subclass __init__

    def _mesh_flat_launch(self, pub, r, s, h, powers):
        """One sharded launch over the active mesh; re-meshes onto
        survivors on an attributable shard fault, raises
        `MeshExhaustedError` (into the caller's breaker) when no
        devices remain. Returns (verdict, tally) un-materialized."""
        from tendermint_tpu.ops.ed25519_kernel import bucket_size
        from tendermint_tpu.ops.padding import pad_rows_to
        from tendermint_tpu.parallel.mesh import MeshExhaustedError
        from tendermint_tpu.utils.fail import ShardDeviceFault

        m = self.mesh
        m.maybe_reprobe()
        n = pub.shape[0]
        while True:
            ndev = m.n_active
            if ndev == 0:
                raise MeshExhaustedError(
                    f"all {m.n_total} mesh devices faulted"
                )
            try:
                m.check_shard_faults()
                size = bucket_size(-(-n // ndev)) * ndev
                arrs = pad_rows_to([pub, r, s, h, powers], size)
                step = m.verify_step()
                ok, total = step(*arrs)
                # annotated only on the successful attempt: a shard
                # fault retried onto survivors must not double-count
                _launchlog.annotate(_additive=True, rows_padded=size - n)
                _launchlog.annotate(mesh_width=ndev)
                _launchlog.add_transfer(sum(a.nbytes for a in arrs))
                return ok, total
            except ShardDeviceFault as e:
                if not m.record_shard_fault(e.shard):
                    raise MeshExhaustedError(
                        f"all {m.n_total} mesh devices faulted"
                    ) from e

    def launch_verify_batch(self, triples):
        """Flat-batch async half: host prep + ONE launch sharded over
        every active chip (the coalescer's merged batches land here —
        one logical device that is actually N chips). Sub-threshold
        batches and single-device meshes take the legacy paths."""
        if self.mesh.n_total <= 1:
            return super().launch_verify_batch(triples)
        if not triples:
            return ("host", np.zeros(0, dtype=bool))
        if len(triples) < self._min_batch:
            return ("host", self._host.verify_batch(triples))
        from tendermint_tpu.ops.ed25519_kernel import prepare_batch

        pubs, msgs, sigs = zip(*triples)
        t0 = time.perf_counter()
        pub, r, s, h, precheck = prepare_batch(pubs, msgs, sigs)
        powers = np.zeros(len(triples), dtype=np.int32)
        ok, _total = self._mesh_flat_launch(pub, r, s, h, powers)
        return ("mesh", ok, precheck, len(triples), t0)

    def finalize_verify_batch(self, launched) -> np.ndarray:
        if launched[0] != "mesh":
            return super().finalize_verify_batch(launched)
        _tag, ok, precheck, n, t0 = launched
        out = np.asarray(ok)[:n] & precheck
        _observe_verify("mesh", n, time.perf_counter() - t0)
        return out

    def verify_batch_with_powers(self, triples, powers):
        """Commit-tally lane: per-item verdicts PLUS the psum-reduced
        verified-power total computed on device across all shards (the
        `sharded_verify_and_tally` collective — no host gather for the
        2/3-quorum sum). Pad rows and precheck-failed rows carry zero
        bytes, verify False on every backend, and so never contribute
        power."""
        from tendermint_tpu.ops.ed25519_kernel import prepare_batch

        if not triples:
            return np.zeros(0, dtype=bool), 0
        pubs, msgs, sigs = zip(*triples)
        t0 = time.perf_counter()
        pub, r, s, h, precheck = prepare_batch(pubs, msgs, sigs)
        pw = np.asarray(powers, dtype=np.int32) * precheck
        ok, total = self._mesh_flat_launch(pub, r, s, h, pw)
        mask = np.asarray(ok)[: len(triples)] & precheck
        _observe_verify("mesh", len(triples), time.perf_counter() - t0)
        return mask, int(total)

    def snapshot(self) -> dict:
        return {"mesh": self.mesh.snapshot()}


class ShardedBatchVerifier(_MeshFlatMixin, DeviceBatchVerifier):
    """Generic-ladder batch verifier sharded over a device mesh.

    The CPU-mesh / ad-hoc-triple production backend: every launch splits
    the batch axis over the active chips of a `MeshManager` and psums
    the power tally. Commit grids flatten their present lanes into the
    same flat mesh lane and scatter verdicts back — so fast-sync windows
    and consensus commits ride N chips even without valset tables.
    """

    def __init__(self, mesh=None, min_device_batch: int | None = None) -> None:
        super().__init__(min_device_batch)
        from tendermint_tpu.parallel.mesh import MeshManager

        self.mesh = mesh if mesh is not None else MeshManager()

    def verify_commits(self, pubkeys, commits, force_fused=None) -> np.ndarray:
        return self.finalize_verify_commits(
            self.launch_verify_commits(pubkeys, commits, force_fused=force_fused)
        )

    def launch_verify_commits(self, pubkeys, commits, force_fused=None):
        """Commit grids as flat mesh lanes: present (msg, sig) lanes
        flatten into one batch-sharded launch (`force_fused` is a
        single-device-tables concept; ignored here)."""
        n, k = len(pubkeys), len(commits)
        lanes: list[tuple[int, int]] = []
        triples: list[Triple] = []
        for ci, (msgs, sigs) in enumerate(commits):
            for i in range(n):
                if msgs[i] is not None and sigs[i] is not None:
                    lanes.append((ci, i))
                    triples.append((pubkeys[i], msgs[i], sigs[i]))
        if self.mesh.n_total <= 1 or len(triples) < self._min_batch:
            grid = np.zeros((k, n), dtype=bool)
            if triples:
                verdicts = self._host.verify_batch(triples)
                for (ci, i), ok in zip(lanes, verdicts):
                    grid[ci, i] = bool(ok)
            return ("host_grid", grid)
        launched = self.launch_verify_batch(triples)
        return ("mesh_grid", launched, lanes, k, n)

    def finalize_verify_commits(self, launched) -> np.ndarray:
        if launched[0] == "host_grid":
            return launched[1]
        _tag, flat, lanes, k, n = launched
        mask = self.finalize_verify_batch(flat)
        grid = np.zeros((k, n), dtype=bool)
        for (ci, i), ok in zip(lanes, mask):
            grid[ci, i] = bool(ok)
        return grid

    def verify_commits_async(
        self, pubkeys, commits, queue=None, force_fused=None, consumer="default"
    ):
        from tendermint_tpu.services.dispatch import default_dispatch_queue

        q = queue if queue is not None else default_dispatch_queue()
        return q.submit(
            lambda: self.launch_verify_commits(
                pubkeys, commits, force_fused=force_fused
            ),
            self.finalize_verify_commits,
            kind="verify",
        )


class ShardedTableBatchVerifier(_MeshFlatMixin, TableBatchVerifier):
    """The mesh-aware TABLE fast path: the TPU steady-state backend.

    Commit-grid verification shards along the VALIDATOR axis
    (`parallel.mesh.sharded_tables_verify_and_tally`): each chip holds
    1/ndev of the cached comb-table columns plus the lanes of its own
    validators for every stacked commit, and the power tally psums over
    ICI. Stack/chunk geometry derives from the PER-CHIP shard size —
    the fused-pallas selection inside `verify_tables_kernel` sees
    per-shard shapes under shard_map, and the host-side K padding uses
    per-chip lane counts, not the global batch (the single-device
    assumption this class exists to remove).

    Falls back per-call to the single-device table path when the valset
    does not split evenly over the active chips (N % ndev != 0), and to
    flat mesh lanes when the mesh executor has no table program (the
    host-emulated CPU seam).
    """

    def __init__(
        self,
        mesh=None,
        cache_size: int = 4,
        min_device_batch: int | None = None,
    ) -> None:
        super().__init__(cache_size=cache_size, min_device_batch=min_device_batch)
        from tendermint_tpu.parallel.mesh import MeshManager

        self.mesh = mesh if mesh is not None else MeshManager()
        # (valset key, active device tuple) -> mesh-sharded table array;
        # re-sharding 1.25 GB of tables per launch would eat the win
        self._sharded_tables: dict = {}

    def _tables_for_mesh(self, pubkeys: tuple[bytes, ...], mesh_obj):
        """Valset tables placed WITH the validator-axis sharding for the
        active mesh (cached per device set; the underlying build rides
        the same table-build breaker as the single-device path)."""
        import jax as _jax
        from jax.sharding import NamedSharding, PartitionSpec as _P

        from tendermint_tpu.parallel.mesh import BATCH_AXIS

        tables, key_ok = self._tables_for(pubkeys)
        key = (self._cache_key(pubkeys), tuple(mesh_obj.devices.flat))
        with self._cache_lock:
            hit = self._sharded_tables.get(key)
        if hit is not None:
            _metrics.TABLE_DEVICE_CACHE.labels(result="hit").inc()
            return hit, key_ok
        _metrics.TABLE_DEVICE_CACHE.labels(result="miss").inc()
        sharding = NamedSharding(mesh_obj, _P(None, None, None, BATCH_AXIS))
        t_put = time.perf_counter()
        placed = _jax.device_put(tables, sharding)
        # the re-ship cost every placement-cache miss pays (GB-scale at
        # large valsets): both the bytes and the device_put stall land
        # on this launch's ledger record
        _launchlog.add_transfer(int(getattr(tables, "nbytes", 0)))
        _launchlog.annotate(
            _additive=True, device_put_s=time.perf_counter() - t_put
        )
        with self._cache_lock:
            self._sharded_tables[key] = placed
            while len(self._sharded_tables) > self._cache_size * 2:
                self._sharded_tables.pop(next(iter(self._sharded_tables)))
        return placed, key_ok

    def launch_verify_commits(self, pubkeys, commits, force_fused=None):
        n, k = len(pubkeys), len(commits)
        m = self.mesh
        if m.n_total <= 1:
            return super().launch_verify_commits(
                pubkeys, commits, force_fused=force_fused
            )
        if n == 0 or k == 0:
            return ("host", np.zeros((k, n), dtype=bool))
        if k * n < self._min_batch:
            return ("host", self._host_commit_loop(pubkeys, commits))
        if m.executor != "device":
            # host-emulated mesh: flat lanes keep the fault/re-mesh
            # choreography testable without the tables program
            return ShardedBatchVerifier.launch_verify_commits(
                self, pubkeys, commits
            )
        m.maybe_reprobe()
        from tendermint_tpu.parallel.mesh import MeshExhaustedError
        from tendermint_tpu.utils.fail import ShardDeviceFault

        while True:
            if m.n_active == 0:
                raise MeshExhaustedError(
                    f"all {m.n_total} mesh devices faulted"
                )
            if n % m.n_active != 0:
                # uneven split: per-call fallback to the single-device
                # table path (still breaker-guarded upstream)
                return super().launch_verify_commits(
                    pubkeys, commits, force_fused=force_fused
                )
            try:
                m.check_shard_faults()
                return self._launch_mesh_tables(
                    pubkeys, commits, force_fused=force_fused
                )
            except ShardDeviceFault as e:
                if not m.record_shard_fault(e.shard):
                    raise MeshExhaustedError(
                        f"all {m.n_total} mesh devices faulted"
                    ) from e

    def _launch_mesh_tables(self, pubkeys, commits, force_fused=None):
        from tendermint_tpu.ops.ed25519_tables import prepare_commit_lanes
        from tendermint_tpu.parallel.mesh import shard_lanes_validator_major

        n, k = len(pubkeys), len(commits)
        m = self.mesh
        ndev = m.n_active
        length_ok = np.array([len(pk) == 32 for pk in pubkeys], dtype=bool)
        if not length_ok.all():
            placeholder = b"\x01" + b"\x00" * 31
            pubkeys = [
                pk if ok else placeholder for pk, ok in zip(pubkeys, length_ok)
            ]
        try:
            tables, key_ok = self._tables_for_mesh(tuple(pubkeys), m.mesh())
        except TableBuildError:
            return ("host", self._host_commit_loop(pubkeys, commits))
        key_ok = key_ok & length_ok
        # Stack/chunk geometry from the PER-CHIP lane count: the fused
        # plane shape inside verify_tables_kernel sees n/ndev columns
        # per shard under shard_map, so fusability and the K padding
        # rule use shard_n, not the global validator count (absent-vote
        # pad commits verify False via precheck, sliced off at finalize)
        import jax as _jax

        from tendermint_tpu.ops.ed25519_tables import MAX_FUSED_STACK

        shard_n = n // ndev
        fusable = (
            (shard_n % 128 == 0 and k >= 8 and _jax.default_backend() == "tpu")
            if force_fused is None
            else force_fused
        )
        step = m.tables_step()
        chunk = MAX_FUSED_STACK if fusable else k
        launches = []  # (device_ok, real, part_len) per chunk
        t0 = time.perf_counter()
        for lo in range(0, k, chunk):
            part = list(commits[lo : lo + chunk])
            real = len(part)
            if fusable and real % 8 != 0:
                absent = ([None] * n, [None] * n)
                part.extend([absent] * (8 - real % 8))
            s, h, r, precheck = prepare_commit_lanes(pubkeys, part)
            lane_ok = precheck & np.tile(key_ok, len(part))
            powers = np.ones(len(part) * n, dtype=np.int32)
            s, h, r, lane_ok_s, powers = shard_lanes_validator_major(
                [s, h, r, lane_ok, powers], n, ndev
            )
            ok, _total = step(tables, s, h, r, lane_ok_s, powers)
            launches.append((ok, real, len(part)))
            _launchlog.annotate(
                _additive=True, rows_padded=(len(part) - real) * n
            )
            _launchlog.add_transfer(
                s.nbytes + h.nbytes + r.nbytes + lane_ok_s.nbytes + powers.nbytes
            )
        _launchlog.annotate(mesh_width=ndev)
        return ("mesh_tables", launches, ndev, k, n, t0)

    def finalize_verify_commits(self, launched) -> np.ndarray:
        if launched[0] in ("host_grid", "mesh_grid"):
            return ShardedBatchVerifier.finalize_verify_commits(self, launched)
        if launched[0] != "mesh_tables":
            return super().finalize_verify_commits(launched)
        from tendermint_tpu.parallel.mesh import unshard_lanes_validator_major

        _tag, launches, ndev, k, n, t0 = launched
        rows = []
        for ok, real, part_len in launches:
            lanes = unshard_lanes_validator_major(np.asarray(ok), n, ndev)
            rows.append(lanes.reshape(part_len, n)[:real])
        _observe_verify("mesh", k * n, time.perf_counter() - t0, kind="tables")
        return np.concatenate(rows, axis=0)


_DEFAULT: BatchVerifier | None = None


def _mesh_opt_in_cpu() -> bool:
    """CPU backends join the sharded mesh only when
    TENDERMINT_TPU_MESH_DEVICES explicitly asks for >= 2 devices (the
    virtual-device test recipe, docs/PLATFORM_NOTES.md); on TPU the
    mesh is the default whenever more than one chip is visible."""
    knob = os.environ.get("TENDERMINT_TPU_MESH_DEVICES")
    if not knob or int(knob) < 2:
        return False
    from tendermint_tpu.parallel.mesh import mesh_device_count

    return mesh_device_count() > 1


def default_verifier() -> BatchVerifier:
    """Process-wide verifier: device-backed iff an accelerator is up,
    MESH-backed iff more than one chip is visible.

    On a multi-chip backend the device layer is the sharded mesh stack
    (`ShardedTableBatchVerifier` over `parallel.mesh.MeshManager`): the
    coalescer feeds one logical device that is actually N chips, and a
    per-shard device fault re-meshes onto the survivors before the
    breaker ever considers host fallback (docs/PERFORMANCE.md "Mesh
    scale-out"; TENDERMINT_TPU_MESH_DEVICES=1 forces the single-device
    legacy path).

    On CPU-only hosts the emulated curve kernel is far slower than the
    host crypto library, so fall back to HostBatchVerifier there —
    unless TENDERMINT_TPU_MESH_DEVICES >= 2 opts into the sharded stack
    over virtual devices (the CI mesh recipe, docs/PLATFORM_NOTES.md).
    Consensus paths that don't thread an explicit verifier use this
    (mirrors the reference's package-global crypto functions).

    Device backends are wrapped in `ResilientVerifier` so a device
    fault degrades verification to host instead of killing consensus
    (`services/resilient.py`); host-only runs get the wrapper too when
    fault injection / TENDERMINT_TPU_RESILIENT is armed, so chaos tests
    exercise the same dispatch path CI-side.

    Whatever the backend stack, the outermost layer is the
    `CoalescingVerifier` (`services/batcher.py`): a verified-signature
    dedup cache plus the cross-consumer launch coalescer. Disable with
    TENDERMINT_TPU_COALESCE=0 (the wrap is verdict-transparent — only
    positives are cached, failures always re-verify).
    """
    global _DEFAULT
    if _DEFAULT is None:
        import jax

        from tendermint_tpu.utils.fail import device_faults_armed

        if jax.default_backend() == "cpu":
            if _mesh_opt_in_cpu():
                # CPU hosts (incl. the 8-virtual-device CI mesh) ride
                # the sharded backend only on explicit opt-in — the
                # emulated kernels are slower than host crypto, so this
                # exists for mesh-path testing, not speed
                from tendermint_tpu.parallel.mesh import default_mesh_manager
                from tendermint_tpu.services.resilient import ResilientVerifier

                inner: BatchVerifier = ResilientVerifier(
                    ShardedBatchVerifier(mesh=default_mesh_manager())
                )
            elif device_faults_armed():
                from tendermint_tpu.services.resilient import ResilientVerifier

                inner = ResilientVerifier(DeviceBatchVerifier())
            else:
                inner = HostBatchVerifier()
        else:
            from tendermint_tpu.parallel.mesh import mesh_device_count
            from tendermint_tpu.services.resilient import ResilientVerifier

            if mesh_device_count() > 1:
                from tendermint_tpu.parallel.mesh import default_mesh_manager

                inner = ResilientVerifier(
                    ShardedTableBatchVerifier(mesh=default_mesh_manager())
                )
            else:
                inner = ResilientVerifier(TableBatchVerifier())
        if os.environ.get("TENDERMINT_TPU_COALESCE", "1") != "0":
            from tendermint_tpu.services.batcher import CoalescingVerifier

            _DEFAULT = CoalescingVerifier(inner)
        else:
            _DEFAULT = inner
    return _DEFAULT


def set_default_verifier(v: BatchVerifier) -> None:
    global _DEFAULT
    _DEFAULT = v
