"""BatchVerifier: accumulate→flush ed25519 verification service.

The reference verifies one signature at a time inside its hot loops
(`types/vote_set.go:177`, `types/validator_set.go:253`). Here every
consumer — VoteSet, ValidatorSet.verify_commit, fast-sync, light client —
talks to a `BatchVerifier`:

* `verify_batch(triples)` — synchronous batch verdicts (the call the
  types layer already targets);
* `add(pk, msg, sig)` / `flush()` — optimistic accumulation across
  call sites, flushed as one device batch (SURVEY.md §7 hard part 3:
  per-item verdict masks preserve per-vote error attribution).

Backends: `HostBatchVerifier` (sequential host library — the CPU
baseline and the no-TPU test fake) and `DeviceBatchVerifier` (the
batched curve kernel in `ops.ed25519_kernel`, padded to power-of-two
buckets so recompiles stay bounded).
"""

from __future__ import annotations

from typing import Iterable, Sequence

import numpy as np

Triple = tuple[bytes, bytes, bytes]  # (pubkey32, message, signature64)


class BatchVerifier:
    """Interface + shared accumulate/flush bookkeeping."""

    def verify_batch(self, triples: Sequence[Triple]) -> np.ndarray:
        raise NotImplementedError

    def __init__(self) -> None:
        self._pending: list[Triple] = []

    def add(self, pubkey: bytes, msg: bytes, sig: bytes) -> int:
        """Queue a triple; returns its index into the next flush()'s mask."""
        self._pending.append((pubkey, msg, sig))
        return len(self._pending) - 1

    def pending(self) -> int:
        return len(self._pending)

    def flush(self) -> np.ndarray:
        """Verify everything queued since the last flush; per-item verdicts."""
        triples, self._pending = self._pending, []
        if not triples:
            return np.zeros(0, dtype=bool)
        return self.verify_batch(triples)

    def verify_one(self, pubkey: bytes, msg: bytes, sig: bytes) -> bool:
        return bool(self.verify_batch([(pubkey, msg, sig)])[0])


class HostBatchVerifier(BatchVerifier):
    """Sequential host-library backend (CPU baseline / TPU-free tests)."""

    def verify_batch(self, triples: Sequence[Triple]) -> np.ndarray:
        from tendermint_tpu.crypto.keys import PUBKEY_LEN, PubKey

        out = np.zeros(len(triples), dtype=bool)
        for i, (pk, msg, sig) in enumerate(triples):
            if len(pk) != PUBKEY_LEN:
                continue
            out[i] = PubKey(pk).verify(msg, sig)
        return out


class DeviceBatchVerifier(BatchVerifier):
    """TPU-batched backend over `ops.ed25519_kernel.batch_verify`.

    Batches are padded to power-of-two buckets (min 8) inside
    batch_verify; compiled executables persist in the jit cache per
    bucket size.
    """

    def verify_batch(self, triples: Sequence[Triple]) -> np.ndarray:
        from tendermint_tpu.ops.ed25519_kernel import batch_verify

        if not triples:
            return np.zeros(0, dtype=bool)
        pubs, msgs, sigs = zip(*triples)
        return batch_verify(list(pubs), list(msgs), list(sigs))


_DEFAULT: BatchVerifier | None = None


def default_verifier() -> BatchVerifier:
    """Process-wide verifier: device-backed iff an accelerator is up.

    On CPU-only hosts the emulated curve kernel is far slower than the
    host crypto library, so fall back to HostBatchVerifier there.
    Consensus paths that don't thread an explicit verifier use this
    (mirrors the reference's package-global crypto functions).
    """
    global _DEFAULT
    if _DEFAULT is None:
        import jax

        if jax.default_backend() == "cpu":
            _DEFAULT = HostBatchVerifier()
        else:
            _DEFAULT = DeviceBatchVerifier()
    return _DEFAULT


def set_default_verifier(v: BatchVerifier) -> None:
    global _DEFAULT
    _DEFAULT = v
