"""Fault-tolerant device dispatch: health-checked verify/hash backends.

A device fault mid-consensus (XLA compile error, runtime error, hung
dispatch) must degrade a node, not kill it — the reference is
crash-only but recoverable (failpoints + WAL replay); the batched TPU
backends here get the complementary property: *stay up, verify on
host*. Committee-based-consensus measurements (PAPERS.md) make batched
verification the throughput lever, but safety must survive losing it.

`ResilientVerifier` / `ResilientTreeHasher` wrap a primary (device)
backend and a host fallback behind a shared `CircuitBreaker`
(`utils/circuit.py`):

* every primary call gets bounded retries with jittered backoff
  (`utils/backoff.py`) and an optional dispatch timeout;
* N consecutive failures trip the breaker OPEN — calls route straight
  to the host fallback (no device latency tax while it is sick);
* after a reset window one probe call tests the device; success closes
  the breaker, the node transparently re-upgrades.

Deterministic fault injection rides `utils/fail.py`
(`TENDERMINT_TPU_DEVICE_FAIL=verify:3` style), so chaos tests can trip
and heal the breaker mid-height. Degradation state is logged through
`utils/log.py` on every transition and exported via `snapshot()`.

Env knobs (all optional):
  TENDERMINT_TPU_BREAKER_THRESHOLD   consecutive failures to trip (3)
  TENDERMINT_TPU_BREAKER_RESET_S     OPEN -> probe window seconds (5)
  TENDERMINT_TPU_DEVICE_RETRIES      in-call retries before failing (1)
  TENDERMINT_TPU_DEVICE_TIMEOUT_S    per-dispatch timeout (0 = none)
  TENDERMINT_TPU_RESILIENT=1         wrap even on host-only backends
  TENDERMINT_TPU_DEVICE_FAIL         fault injection spec (utils/fail.py)
"""

from __future__ import annotations

import logging
import os
import time
from typing import Sequence

import numpy as np

from tendermint_tpu.services.hasher import TreeHasher
from tendermint_tpu.services.verifier import (
    BatchVerifier,
    HostBatchVerifier,
    Triple,
)
from tendermint_tpu.utils.backoff import backoff_delay
from tendermint_tpu.utils.circuit import CircuitBreaker
from tendermint_tpu.utils.fail import device_fail_point
from tendermint_tpu.utils.log import kv, logger

_log = logger("resilient")


def _env_int(name: str, default: int) -> int:
    return int(os.environ.get(name, default))


def _env_float(name: str, default: float) -> float:
    return float(os.environ.get(name, default))


class _ResilientDispatch:
    """Shared breaker-guarded call plumbing for both services."""

    def __init__(
        self,
        kind: str,
        breaker: CircuitBreaker | None = None,
        max_retries: int | None = None,
        retry_base_s: float = 0.05,
        dispatch_timeout_s: float | None = None,
    ) -> None:
        self._kind = kind
        self._breaker = breaker or CircuitBreaker(
            failure_threshold=_env_int("TENDERMINT_TPU_BREAKER_THRESHOLD", 3),
            reset_timeout_s=_env_float("TENDERMINT_TPU_BREAKER_RESET_S", 5.0),
        )
        # telemetry + transition logs attach to externally supplied
        # breakers too (chaos tests hand in tuned ones and still expect
        # the exported trip/recovery counters to move)
        self._breaker.bind_telemetry(kind)
        self._breaker.add_state_listener(self._log_transition)
        self._max_retries = (
            _env_int("TENDERMINT_TPU_DEVICE_RETRIES", 1)
            if max_retries is None
            else max_retries
        )
        self._retry_base_s = retry_base_s
        self._timeout_s = (
            _env_float("TENDERMINT_TPU_DEVICE_TIMEOUT_S", 0.0)
            if dispatch_timeout_s is None
            else dispatch_timeout_s
        )
        self._executor = None
        self.fallback_calls = 0
        self.primary_calls = 0

    @property
    def breaker(self) -> CircuitBreaker:
        return self._breaker

    def _log_transition(self, old: str, new: str) -> None:
        level = logging.WARNING if new != "closed" else logging.INFO
        kv(
            _log,
            level,
            f"{self._kind} backend breaker {old} -> {new}",
            kind=self._kind,
            **{
                k: v
                for k, v in self._breaker.snapshot().items()
                if k != "state"
            },
        )

    def _run_with_timeout(self, fn, args, kwargs):
        """Optional hung-dispatch guard. The worker thread cannot be
        killed — a genuinely wedged XLA call leaks its thread — but the
        caller unblocks, the failure is counted, and the host fallback
        answers; that is the trade this layer exists to make."""
        if self._timeout_s <= 0:
            return fn(*args, **kwargs)
        from concurrent.futures import ThreadPoolExecutor, TimeoutError as FutTimeout

        if self._executor is None:
            self._executor = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix=f"{self._kind}-dispatch"
            )
        future = self._executor.submit(fn, *args, **kwargs)
        try:
            return future.result(timeout=self._timeout_s)
        except FutTimeout:
            future.cancel()
            raise TimeoutError(
                f"{self._kind} device dispatch exceeded {self._timeout_s}s"
            ) from None

    def call(self, primary_fn, fallback_fn, *args, **kwargs):
        """Route one operation: primary behind the breaker (with retries
        + fault injection + timeout), host fallback otherwise."""
        from tendermint_tpu.telemetry import metrics

        if self._breaker.allow():
            for attempt in range(1 + max(0, self._max_retries)):
                try:
                    device_fail_point(self._kind)
                    out = self._run_with_timeout(primary_fn, args, kwargs)
                    self._breaker.record_success()
                    self.primary_calls += 1
                    metrics.DISPATCH_PRIMARY.labels(kind=self._kind).inc()
                    return out
                except Exception as e:
                    self._breaker.record_failure()
                    metrics.DISPATCH_FAILURES.labels(kind=self._kind).inc()
                    kv(
                        _log,
                        logging.WARNING,
                        f"{self._kind} device dispatch failed",
                        kind=self._kind,
                        attempt=attempt,
                        error=f"{type(e).__name__}: {e}"[:120],
                        breaker=self._breaker.state,
                    )
                    if (
                        attempt < self._max_retries
                        and self._breaker.allow()
                    ):
                        time.sleep(
                            backoff_delay(attempt, self._retry_base_s, cap=1.0)
                        )
                        continue
                    break
        self.fallback_calls += 1
        metrics.DISPATCH_FALLBACK.labels(kind=self._kind).inc()
        return fallback_fn(*args, **kwargs)

    def call_async(self, queue, launch_primary, finalize_primary, fallback_fn):
        """Async analog of `call` for the dispatch pipeline: the launch
        runs on the queue worker, the finalize at the consumer's join.

        Breaker fallback propagates THROUGH the handle — a fault at
        either stage (launch raise, or a device error surfacing at
        materialization) records the failure and resolves the handle via
        the host fallback instead of raising into the pipeline consumer.
        One primary attempt, no in-call retries: a retry would stall
        every launch queued behind this one; the breaker is the
        cross-call policy."""
        from tendermint_tpu.telemetry import metrics

        def _fallback_now():
            self.fallback_calls += 1
            metrics.DISPATCH_FALLBACK.labels(kind=self._kind).inc()
            return fallback_fn()

        def _record_fault(stage: str, e: BaseException) -> None:
            self._breaker.record_failure()
            metrics.DISPATCH_FAILURES.labels(kind=self._kind).inc()
            kv(
                _log,
                logging.WARNING,
                f"{self._kind} async device dispatch failed",
                kind=self._kind,
                stage=stage,
                error=f"{type(e).__name__}: {e}"[:120],
                breaker=self._breaker.state,
            )

        def _launch():
            if self._breaker.allow():
                try:
                    device_fail_point(self._kind)
                    return ("primary", self._run_with_timeout(launch_primary, (), {}))
                except Exception as e:
                    _record_fault("launch", e)
            return ("fallback", _fallback_now())

        def _finalize(tagged):
            tag, payload = tagged
            if tag == "fallback":
                return payload
            try:
                out = finalize_primary(payload)
            except Exception as e:
                # in-flight launch faulted: host re-verify, not an
                # exception in the consumer
                _record_fault("finalize", e)
                return _fallback_now()
            self._breaker.record_success()
            self.primary_calls += 1
            metrics.DISPATCH_PRIMARY.labels(kind=self._kind).inc()
            return out

        return queue.submit(_launch, _finalize, kind=self._kind)

    def snapshot(self) -> dict:
        out = self._breaker.snapshot()
        out.update(
            kind=self._kind,
            primary_calls=self.primary_calls,
            fallback_calls=self.fallback_calls,
        )
        return out


class ResilientVerifier(BatchVerifier):
    """BatchVerifier that survives its device backend.

    Implements the full verifier surface (verify_batch, verify_commits,
    prebuild, warm_kernels) so VoteSet, ValidatorSet.verify_commit,
    fast-sync, and the certifier can use it as a drop-in wherever
    `default_verifier()` hands it out.
    """

    def __init__(
        self,
        primary: BatchVerifier,
        fallback: BatchVerifier | None = None,
        breaker: CircuitBreaker | None = None,
        max_retries: int | None = None,
        dispatch_timeout_s: float | None = None,
    ) -> None:
        super().__init__()
        self.primary = primary
        self.fallback = fallback if fallback is not None else HostBatchVerifier()
        self._dispatch = _ResilientDispatch(
            "verify",
            breaker=breaker,
            max_retries=max_retries,
            dispatch_timeout_s=dispatch_timeout_s,
        )

    @property
    def breaker(self) -> CircuitBreaker:
        return self._dispatch.breaker

    @property
    def degraded(self) -> bool:
        return self._dispatch.breaker.state != "closed"

    @property
    def mesh(self):
        """The primary's `MeshManager` when the device backend is
        sharded (None otherwise) — the coalescer reads the mesh size to
        scale its merge windows, dashboards read the snapshot."""
        return getattr(self.primary, "mesh", None)

    def snapshot(self) -> dict:
        out = self._dispatch.snapshot()
        mesh = self.mesh
        if mesh is not None:
            out["mesh"] = mesh.snapshot()
        return out

    def verify_batch(self, triples: Sequence[Triple]) -> np.ndarray:
        return self._dispatch.call(
            self.primary.verify_batch, self.fallback.verify_batch, triples
        )

    def verify_batch_async(
        self, triples: Sequence[Triple], queue=None, consumer: str = "default"
    ):
        """Breaker-guarded async verify: the handle always resolves to
        a verdict mask — a faulted in-flight launch re-verifies on host
        at the join instead of raising into the pipeline."""
        from tendermint_tpu.services.dispatch import default_dispatch_queue

        q = queue if queue is not None else default_dispatch_queue()
        return self._dispatch.call_async(
            q,
            lambda: self.primary.launch_verify_batch(triples),
            self.primary.finalize_verify_batch,
            lambda: self.fallback.verify_batch(triples),
        )

    def verify_commits_async(
        self, pubkeys, commits, queue=None, force_fused=None, consumer="default"
    ):
        """Async commit-grid verify with the same guarantee: device
        faults at launch OR materialization degrade to the host commit
        loop inside the handle."""
        from tendermint_tpu.services.dispatch import default_dispatch_queue

        q = queue if queue is not None else default_dispatch_queue()
        if hasattr(self.primary, "launch_verify_commits"):
            return self._dispatch.call_async(
                q,
                lambda: self.primary.launch_verify_commits(
                    pubkeys, commits, force_fused=force_fused
                ),
                self.primary.finalize_verify_commits,
                lambda: self._host_verify_commits(pubkeys, commits),
            )
        # primary without the commit-grid surface: host loop, but still
        # on the queue worker so the submitter's host work overlaps
        return q.submit(
            lambda: self._host_verify_commits(pubkeys, commits), kind="verify"
        )

    def verify_commits(self, pubkeys, commits, force_fused=None):
        """K commits over one valset -> (K, N) verdicts; host loop when
        the primary lacks the fused path or the breaker is open."""
        if hasattr(self.primary, "verify_commits"):
            return self._dispatch.call(
                lambda: self.primary.verify_commits(
                    pubkeys, commits, force_fused=force_fused
                ),
                lambda: self._host_verify_commits(pubkeys, commits),
            )
        return self._host_verify_commits(pubkeys, commits)

    def _host_verify_commits(self, pubkeys, commits) -> np.ndarray:
        n, k = len(pubkeys), len(commits)
        out = np.zeros((k, n), dtype=bool)
        for ci, (msgs, sigs) in enumerate(commits):
            lanes = [
                i for i in range(n) if msgs[i] is not None and sigs[i] is not None
            ]
            if not lanes:
                continue
            verdicts = self.fallback.verify_batch(
                [(pubkeys[i], msgs[i], sigs[i]) for i in lanes]
            )
            for i, v in zip(lanes, verdicts):
                out[ci, i] = v
        return out

    # table warming is an optimization, never worth a crash — and never
    # worth dispatching to a device the breaker says is sick
    def prebuild(self, pubkeys) -> None:
        if hasattr(self.primary, "prebuild") and self.breaker.state == "closed":
            try:
                self.primary.prebuild(pubkeys)
            except Exception:
                pass

    def warm_kernels(self) -> None:
        if hasattr(self.primary, "warm_kernels") and self.breaker.state == "closed":
            try:
                self.primary.warm_kernels()
            except Exception:
                pass


class ResilientTreeHasher(TreeHasher):
    """TreeHasher that degrades device Merkle builds to host hashlib.

    Subclasses TreeHasher so every call site (`Block.make_block`,
    part-set builds, fast-sync stores) keeps its type expectations; only
    the two root builders dispatch through the breaker — proofs are
    host-side already.
    """

    def __init__(
        self,
        primary: TreeHasher | None = None,
        fallback: TreeHasher | None = None,
        breaker: CircuitBreaker | None = None,
        max_retries: int | None = None,
        dispatch_timeout_s: float | None = None,
    ) -> None:
        primary = primary if primary is not None else TreeHasher(backend="device")
        super().__init__(
            backend=primary.backend,
            algo=primary.algo,
            min_device_leaves=primary.min_device_leaves,
            mesh=primary.mesh,
        )
        self.primary = primary
        self.fallback = (
            fallback
            if fallback is not None
            else TreeHasher(backend="host", algo=primary.algo)
        )
        self._dispatch = _ResilientDispatch(
            "hash",
            breaker=breaker,
            max_retries=max_retries,
            dispatch_timeout_s=dispatch_timeout_s,
        )

    @property
    def breaker(self) -> CircuitBreaker:
        return self._dispatch.breaker

    @property
    def degraded(self) -> bool:
        return self._dispatch.breaker.state != "closed"

    def snapshot(self) -> dict:
        return self._dispatch.snapshot()

    def root_from_items(self, items: list[bytes]) -> bytes:
        return self._dispatch.call(
            self.primary.root_from_items, self.fallback.root_from_items, items
        )

    def root_from_hashes(self, hashes: list[bytes]) -> bytes:
        return self._dispatch.call(
            self.primary.root_from_hashes, self.fallback.root_from_hashes, hashes
        )

    def leaf_hashes(self, items: list[bytes]) -> list[bytes]:
        return self._dispatch.call(
            self.primary.leaf_hashes, self.fallback.leaf_hashes, items
        )

    def leaf_hashes_async(self, items: list[bytes], queue=None):
        """Breaker-guarded async leaf hashing (the statesync chunk-
        verify gate): the handle resolves to the per-item hashes, with
        device faults degrading to host hashlib inside the handle."""
        from tendermint_tpu.services.dispatch import default_dispatch_queue

        q = queue if queue is not None else default_dispatch_queue()
        return self._dispatch.call_async(
            q,
            lambda: self.primary.leaf_hashes(items),
            lambda hashes: hashes,
            lambda: self.fallback.leaf_hashes(items),
        )

    def proofs(self, items: list[bytes]):
        return self.fallback.proofs(items)
