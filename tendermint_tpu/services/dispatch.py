"""Bounded-depth asynchronous device dispatch: the pipeline substrate.

Every device call site used to block synchronously on the full
round-trip (~86 ms fixed launch cost through the axon tunnel,
docs/PLATFORM_NOTES.md) even though JAX dispatch is already
asynchronous — the stall was self-inflicted by eager `np.asarray`
materialization at the call site. This module gives the verify/hash
services an async seam instead:

* `VerifyHandle` — the future returned by `verify_batch_async` /
  `verify_commits_async`: the device launch happens on the queue's
  worker thread, device arrays stay UN-materialized until `.result()`
  (which is where `np.asarray` finally blocks, on the consumer's
  thread). `then(fn)` chains a consumer-side post-processing step
  (verdict tallies, scatter maps) without another thread hop.

* `DispatchQueue` — keeps at most `depth` launches in flight and
  preserves submission order: the single worker launches FIFO, and a
  slot frees only when the consumer joins (or abandons via close) the
  handle. `submit()` blocks when the pipeline is full — backpressure
  reaches the producer, never an unbounded launch backlog on device.

Ordering guarantee (what the fast-sync/vote pipelines build on): for
handles H1, H2 submitted in that order to one queue, H1's launch starts
before H2's, and a consumer joining in submission order observes
verdicts in submission order. The queue never reorders.

Telemetry: `tendermint_dispatch_inflight{queue=}` (submitted, not yet
joined), `tendermint_dispatch_queue_wait_seconds` (submit -> launch
start), and `tendermint_dispatch_overlap_ratio` — the fraction of a
handle's submit->join wall time the consumer spent doing OTHER work
rather than blocked inside `result()`. Overlap > 0 is the direct proof
the pipeline engaged (tools/bench_hotpath.py `fastsync_pipeline`).
"""

from __future__ import annotations

import os
import queue as queue_mod
import threading
import time
from typing import Callable

from tendermint_tpu.telemetry import TRACER
from tendermint_tpu.telemetry import launchlog as _launchlog
from tendermint_tpu.telemetry import metrics as _metrics
from tendermint_tpu.telemetry import tracectx as _trace
from tendermint_tpu.telemetry.flightrec import FLIGHT
from tendermint_tpu.utils.lockrank import ranked_lock

# In-flight launches per queue (submitted, not yet joined). 2 is the
# classic double-buffer: one launch on device, one window of host prep.
DISPATCH_DEPTH = int(os.environ.get("TENDERMINT_TPU_DISPATCH_DEPTH", "2"))

# A submit() that cannot get a slot within this window means the
# consumer abandoned its handles — fail loudly instead of wedging the
# sync/consensus thread forever behind a leaked slot.
_STALL_TIMEOUT_S = float(os.environ.get("TENDERMINT_TPU_DISPATCH_STALL_S", "60"))

_STOP = object()


class VerifyHandle:
    """Future for one async dispatch through a `DispatchQueue`.

    Lifecycle: submit (consumer thread) -> launch (worker thread; host
    prep + device kernel dispatch, result left un-materialized) ->
    result() (consumer thread; materializes, runs the finalize step,
    releases the queue slot). `result()` is idempotent — the finalize
    runs once, later calls return the cached verdict (or re-raise the
    cached error).
    """

    __slots__ = (
        "_queue",
        "_launch_fn",
        "_finalize_fn",
        "kind",
        "_event",
        "_launched",
        "_launch_exc",
        "_value",
        "_exc",
        "_finalized",
        "_lock",
        "_submitted_at",
        "_launched_at",
        "_ctx",
        "_submitted_wall",
        "_launch_rec",
        "_launch_tags",
    )

    def __init__(self, queue: "DispatchQueue", launch_fn, finalize_fn, kind: str):
        self._queue = queue
        self._launch_fn = launch_fn
        self._finalize_fn = finalize_fn
        self.kind = kind
        self._event = threading.Event()
        self._launched = None
        self._launch_exc: BaseException | None = None
        self._value = None
        self._exc: BaseException | None = None
        self._finalized = False
        self._lock = ranked_lock("dispatch.handle")
        self._submitted_at = time.perf_counter()
        self._launched_at: float | None = None
        # trace context ambient on the SUBMITTING thread — the worker
        # records a `dispatch.launch` span against it (sampled only)
        self._ctx = _trace.current()
        self._submitted_wall = time.time() if self._ctx is not None else 0.0
        # launch-ledger tags ambient at submit (the coalescer's consumer
        # mix / cached-rows annotations cross threads here, like _ctx)
        self._launch_rec = None
        self._launch_tags = _launchlog.current_tags() if queue.launch_ledger else None

    # -- worker side -------------------------------------------------------

    def _run_launch(self) -> None:
        self._launched_at = time.perf_counter()
        _metrics.DISPATCH_QUEUE_WAIT.labels(queue=self._queue.name).observe(
            self._launched_at - self._submitted_at
        )
        # one LaunchLedger record per dispatch unit: opened here so the
        # backend's prep/launch code annotates it, closed at the
        # consumer's finalize (telemetry/launchlog.py). Queues carrying
        # host work (the consensus apply pipeline) opt out — the device
        # observatory must only see device launches.
        rec = None
        if self._queue.launch_ledger:
            rec = _launchlog.begin(
                kind=self.kind, queue=self._queue.name, tags=self._launch_tags
            )
        if rec is not None:
            rec["queue_wait_s"] = self._launched_at - self._submitted_at
            if self._ctx is not None:
                rec["trace"] = self._ctx.trace
        try:
            with _trace.use(self._ctx):
                self._launched = self._launch_fn()
        except BaseException as e:  # delivered at result(), never lost
            self._launch_exc = e
        finally:
            if rec is not None:
                now = time.perf_counter()
                rec["host_prep_s"] = now - self._launched_at
                rec["_t_launch_end"] = now
                self._launch_rec = _launchlog.detach(rec)
            self._launch_fn = None  # drop closed-over prep data promptly
            FLIGHT.record(
                "dispatch_launch",
                queue=self._queue.name,
                work=self.kind,
                error=type(self._launch_exc).__name__
                if self._launch_exc is not None
                else "",
            )
            if self._ctx is not None:
                TRACER.add(
                    "dispatch.launch",
                    self._submitted_wall,
                    time.time(),
                    trace=self._ctx.trace,
                    queue=self._queue.name,
                    kind=self.kind,
                )
            self._event.set()

    # -- consumer side -----------------------------------------------------

    def done(self) -> bool:
        """Launch completed (the verdict may still need materializing)."""
        return self._event.is_set()

    def result(self, timeout: float | None = None):
        """Join: wait for the launch, materialize + finalize the verdict.

        Raises whatever the launch or finalize raised (e.g. the
        ValidationError a commit tally produces). Blocking time spent
        here — waiting for the launch plus materializing device arrays —
        is the NON-overlapped share of this handle's life; everything
        the consumer did between submit and this call was overlap.
        """
        t_join = time.perf_counter()
        if not self._event.wait(timeout):
            raise TimeoutError(f"{self.kind} dispatch not launched in {timeout}s")
        with self._lock:
            if not self._finalized:
                self._finalized = True
                rec, self._launch_rec = self._launch_rec, None
                t_fin0 = time.perf_counter()
                if rec is not None:
                    # in-flight: kernel enqueued on the worker -> the
                    # consumer reaches finalize (the window the
                    # pipeline's overlap hides)
                    rec["in_flight_s"] = max(
                        0.0, t_fin0 - rec.get("_t_launch_end", t_fin0)
                    )
                    _launchlog.reattach(rec)
                try:
                    if self._launch_exc is not None:
                        raise self._launch_exc
                    if self._finalize_fn is not None:
                        self._value = self._finalize_fn(self._launched)
                    else:
                        self._value = self._launched
                except BaseException as e:
                    self._exc = e
                finally:
                    self._launched = None
                    self._finalize_fn = None
                    now = time.perf_counter()
                    if rec is not None:
                        rec["finalize_s"] = now - t_fin0
                        rec["total_s"] = now - self._submitted_at
                        _launchlog.commit(rec, error=self._exc)
                    blocked = now - t_join
                    total = now - self._submitted_at
                    if total > 0:
                        _metrics.DISPATCH_OVERLAP.labels(
                            queue=self._queue.name
                        ).observe(max(0.0, min(1.0, 1.0 - blocked / total)))
                    self._queue._release()
            if self._exc is not None:
                raise self._exc
            return self._value

    def then(self, fn: Callable) -> "ChainedHandle":
        """Chain a consumer-side mapping over this handle's result —
        runs at the chained handle's result(), on the joining thread."""
        return ChainedHandle(self, fn)


class ChainedHandle:
    """`handle.then(fn)`: a handle whose result is fn(parent.result()).
    The mapping runs once; its outcome (value or exception) is cached so
    repeated joins behave like VerifyHandle's."""

    __slots__ = ("_parent", "_fn", "_value", "_exc", "_done", "_lock", "kind")

    def __init__(self, parent, fn):
        self._parent = parent
        self._fn = fn
        self._value = None
        self._exc: BaseException | None = None
        self._done = False
        self._lock = ranked_lock("dispatch.handle")
        self.kind = getattr(parent, "kind", "verify")

    def done(self) -> bool:
        return self._parent.done()

    def result(self, timeout: float | None = None):
        # Join the parent BEFORE taking our lock (tmlint L002): parent
        # joins are idempotent and cache their outcome, so concurrent
        # joiners may all block here, but none blocks while holding
        # this handle's lock.
        try:
            parent_value = self._parent.result(timeout)
            parent_exc: BaseException | None = None
        except BaseException as e:
            parent_exc = e
        with self._lock:
            if not self._done:
                self._done = True
                try:
                    if parent_exc is not None:
                        raise parent_exc
                    self._value = self._fn(parent_value)
                except BaseException as e:
                    self._exc = e
                finally:
                    self._fn = None
            if self._exc is not None:
                raise self._exc
            return self._value

    def then(self, fn: Callable) -> "ChainedHandle":
        return ChainedHandle(self, fn)


class CompletedHandle:
    """An already-resolved handle — the no-async-backend degenerate case
    (the work ran synchronously at submit time). Keeps pipeline
    consumers free of `isinstance` forks."""

    __slots__ = ("_value", "_exc", "kind")

    def __init__(self, value=None, exc: BaseException | None = None, kind="verify"):
        self._value = value
        self._exc = exc
        self.kind = kind

    def done(self) -> bool:
        return True

    def result(self, timeout: float | None = None):
        if self._exc is not None:
            raise self._exc
        return self._value

    def then(self, fn: Callable) -> "ChainedHandle":
        return ChainedHandle(self, fn)


class DispatchQueue:
    """FIFO launch queue with a bounded in-flight window.

    One worker thread executes launch functions in submission order;
    `depth` bounds submitted-but-unjoined handles. Consumers MUST join
    handles in submission order (the pipelines do) — a submit past the
    depth blocks until the oldest handle is joined, which is the
    backpressure that keeps device memory and launch backlog bounded.
    """

    def __init__(
        self,
        depth: int | None = None,
        name: str = "default",
        launch_ledger: bool = True,
    ) -> None:
        self.name = name
        # False = this queue carries host-side work (e.g. the pipelined
        # consensus apply), which must not mint device LaunchLedger rows
        self.launch_ledger = launch_ledger
        self.depth = max(1, DISPATCH_DEPTH if depth is None else depth)
        self._sem = threading.Semaphore(self.depth)
        self._work: "queue_mod.SimpleQueue" = queue_mod.SimpleQueue()
        self._thread: threading.Thread | None = None
        self._thread_lock = ranked_lock("dispatch.worker")
        self._state_lock = ranked_lock("dispatch.state")
        self._inflight = 0
        self._closed = False

    # -- plumbing ----------------------------------------------------------

    def _ensure_worker(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        with self._thread_lock:
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._worker, name=f"dispatch-{self.name}", daemon=True
                )
                self._thread.start()

    def _worker(self) -> None:
        while True:
            item = self._work.get()
            if item is _STOP:
                return
            item._run_launch()

    def _release(self) -> None:
        with self._state_lock:
            self._inflight -= 1
            _metrics.DISPATCH_INFLIGHT.labels(queue=self.name).set(self._inflight)
        self._sem.release()

    # -- API ---------------------------------------------------------------

    def inflight(self) -> int:
        with self._state_lock:
            return self._inflight

    def submit(self, launch_fn, finalize_fn=None, kind: str = "verify") -> VerifyHandle:
        """Enqueue one launch; blocks while `depth` handles are already
        in flight (joined-in-order consumers never block here — they
        join the oldest handle before submitting past the depth)."""
        if self._closed:
            raise RuntimeError(f"dispatch queue {self.name!r} is closed")
        self._ensure_worker()
        if not self._sem.acquire(timeout=_STALL_TIMEOUT_S):
            raise RuntimeError(
                f"dispatch queue {self.name!r} stalled: {self.depth} handles "
                f"in flight and none joined within {_STALL_TIMEOUT_S}s"
            )
        with self._state_lock:
            self._inflight += 1
            _metrics.DISPATCH_INFLIGHT.labels(queue=self.name).set(self._inflight)
        handle = VerifyHandle(self, launch_fn, finalize_fn, kind)
        self._work.put(handle)
        return handle

    def close(self) -> None:
        """Stop accepting work and let the worker exit after the current
        backlog. In-flight handles remain joinable."""
        self._closed = True
        if self._thread is not None:
            self._work.put(_STOP)


def measured_launch_apply_ratio(queue: str | None = None) -> float | None:
    """launch:apply ratio inferred from the overlap histogram this
    module already exports: a handle's overlap `o` is the share of its
    life the consumer spent on OTHER work (host prep + ABCI applies), so
    blocked:overlapped = (1-o):o estimates device-launch time vs host
    apply time. None until any handle has been joined.

    Consumers of the estimate: the fast-sync pipeline sizes its depth
    (≈ 1 + ratio windows keeps the device busy while one applies) and
    the verify coalescer scales its flush window (launch-dominated
    pipelines amortize more per merged launch). `queue` narrows to one
    pipeline's series; None aggregates all of them.
    """
    from tendermint_tpu.telemetry import REGISTRY

    fam = REGISTRY.get("tendermint_dispatch_overlap_ratio")
    if fam is None:
        return None
    total = 0.0
    count = 0
    for values, snap in fam.samples():
        if queue is not None and values != (queue,):
            continue
        total += snap["sum"]
        count += snap["count"]
    if count == 0:
        return None
    o = min(max(total / count, 0.01), 0.99)
    return (1.0 - o) / o


_DEFAULT_QUEUE: DispatchQueue | None = None
_DEFAULT_LOCK = ranked_lock("dispatch.global")


def default_dispatch_queue() -> DispatchQueue:
    """Process-wide queue for call sites that don't own a pipeline
    (ad-hoc async verifies). Pipelined consumers (fast-sync, the
    consensus vote drain) create their OWN queues so one consumer's
    unjoined handles can never backpressure another."""
    global _DEFAULT_QUEUE
    if _DEFAULT_QUEUE is None:
        with _DEFAULT_LOCK:
            if _DEFAULT_QUEUE is None:
                _DEFAULT_QUEUE = DispatchQueue(name="default")
    return _DEFAULT_QUEUE
