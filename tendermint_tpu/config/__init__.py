"""Typed config tree + TOML persistence (reference `config/config.go`,
`config/toml.go`).

Defaults match the reference's shape: Base (home/moniker/fast-sync),
RPC, P2P, Mempool, Consensus sub-configs, with `Default*` and `Test*`
presets. `load_config` merges `$home/config.toml` over defaults;
`write_config` emits a commented TOML on `init`.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field, fields

from tendermint_tpu.consensus.config import ConsensusConfig


@dataclass
class BaseConfig:
    """Reference `config/config.go:57-132`."""

    moniker: str = "anonymous"
    fast_sync: bool = True
    db_dir: str = "data"
    log_level: str = "state:info,*:error"
    genesis_file: str = "genesis.json"
    priv_validator_file: str = "priv_validator.json"


@dataclass
class RPCConfig:
    """Reference `config/config.go:163-193`."""

    laddr: str = "tcp://127.0.0.1:46657"
    grpc_laddr: str = ""
    unsafe: bool = False


@dataclass
class P2PConfig:
    """Reference `config/config.go:199-256`."""

    laddr: str = "tcp://0.0.0.0:46656"
    # dialable address advertised to peers via PEX; REQUIRED for
    # multi-machine deployments when binding 0.0.0.0 (loopback is only
    # inferred for single-host setups)
    external_address: str = ""
    seeds: str = ""  # comma-separated host:port
    persistent_peers: str = ""
    secret_connections: bool = True  # X25519+AEAD STS on every peer link
    pex: bool = True  # peer-exchange discovery (addrbook + PEX reactor)
    # ask the ABCI app to vet peers via Query("/p2p/filter/...") before
    # admission (reference node/node.go:259-281, config FilterPeers)
    filter_peers: bool = False
    max_num_peers: int = 50
    pex_ensure_interval_s: float = 30.0  # reference ensurePeersPeriod
    send_rate: int = 512000  # bytes/s (flow limits live in MConnection)
    recv_rate: int = 512000
    # keepalive: ping idle peers, drop them when silent past the grace
    # window (reference pingTimeout 40s, `p2p/connection.go:312-345`)
    ping_interval_s: float = 40.0
    pong_timeout_s: float = 20.0
    # persistent-peer redial policy (reference `p2p/switch.go:15-18`:
    # reconnectAttempts 30 with backoff)
    reconnect_max_attempts: int = 30
    reconnect_base_backoff_s: float = 1.0


@dataclass
class StateSyncConfig:
    """Snapshot bootstrap + serving (`tendermint_tpu/statesync/`).

    `enable` turns on the bootstrap mode: a fresh node discovers peer
    snapshots, restores the newest one that passes certifier anchoring,
    and fast-syncs only the tail. `trust_height`/`trust_hash` pin the
    operator's known-good header (light-client subjective init) —
    REQUIRED in production; empty falls back to trusting the genesis
    validator set. `snapshot_interval` > 0 makes this node SERVE
    snapshots, taken every that many committed heights."""

    enable: bool = False
    trust_height: int = 0
    trust_hash: str = ""  # hex header hash at trust_height
    trust_period_s: float = 0.0  # 0 = no anchoring-header freshness check
    snapshot_interval: int = 0  # 0 = don't take/serve snapshots
    snapshot_keep_recent: int = 2
    # Blocks a snapshot-serving node keeps below its head: after each
    # snapshot the store prunes to head-retain_blocks+1 (0 = keep all).
    # Peers needing older history state-sync + fast-sync the tail.
    retain_blocks: int = 0
    chunk_size: int = 65536
    discovery_time_s: float = 3.0
    chunk_request_timeout_s: float = 10.0
    chunk_inflight_per_peer: int = 4
    giveup_time_s: float = 45.0  # then fall back to plain fast-sync


@dataclass
class ReplicaConfig:
    """Stateless read-replica mode (`tendermint_tpu/lightclient/`).

    `enable` turns the node into a replica: it bootstraps like any
    fresh node (statesync when `[statesync] enable` is set, else
    fast-sync from genesis), NEVER joins consensus (no ConsensusState,
    no validator key use), follows the chain via a follow-mode
    fast-sync tail plus the 0x68 FullCommit subscription, and serves
    light-client queries (FullCommits, commits, merkle-proof-carrying
    reads). Replicas certify every pushed FullCommit through a
    bisecting light-client pin before serving it — a forged push is
    scored + turned into evidence, never trusted.

    `fullcommit_cache_size` bounds the certified-commit cache (0 =
    unbounded); `serve_lightclient` keeps the serving half on for
    non-replica nodes too (every full node serves 0x68 by default)."""

    enable: bool = False
    fullcommit_cache_size: int = 2048
    serve_lightclient: bool = True


@dataclass
class MempoolConfig:
    """Reference `config/config.go:267-288` + the ingress pipeline
    (`mempool/ingress.py`): `lanes` shards the pool into tx-hash
    partitions (0 = env `TENDERMINT_TPU_MEMPOOL_LANES` or the built-in
    default), `ingress_batch` merges concurrent CheckTx arrivals into
    verify windows through the coalescer (`TENDERMINT_TPU_INGRESS_BATCH=0`
    overrides to the legacy synchronous path), `signed_txs` enables
    signed-envelope recognition — when on, the `0xED 0x01` tx prefix is
    reserved (see mempool/ingress.py); turn off for chains whose apps
    may emit payloads colliding with it (`TENDERMINT_TPU_SIGNED_TXS=0`
    overrides). All nodes of a chain must agree on `signed_txs`."""

    recheck: bool = True
    broadcast: bool = True
    wal_dir: str = "data/mempool.wal"
    cache_size: int = 100_000
    lanes: int = 0  # 0 = env/default (mempool.DEFAULT_LANES)
    ingress_batch: bool = True
    signed_txs: bool = True


@dataclass
class Config:
    home: str = "."
    base: BaseConfig = field(default_factory=BaseConfig)
    rpc: RPCConfig = field(default_factory=RPCConfig)
    p2p: P2PConfig = field(default_factory=P2PConfig)
    mempool: MempoolConfig = field(default_factory=MempoolConfig)
    consensus: ConsensusConfig = field(default_factory=ConsensusConfig)
    statesync: StateSyncConfig = field(default_factory=StateSyncConfig)
    replica: ReplicaConfig = field(default_factory=ReplicaConfig)

    # -- derived paths -----------------------------------------------------

    def genesis_path(self) -> str:
        return os.path.join(self.home, self.base.genesis_file)

    def priv_validator_path(self) -> str:
        return os.path.join(self.home, self.base.priv_validator_file)

    def node_key_path(self) -> str:
        return os.path.join(self.home, "node_key.json")

    def db_path(self, name: str) -> str:
        return os.path.join(self.home, self.base.db_dir, f"{name}.db")

    def wal_path(self) -> str:
        return os.path.join(self.home, self.base.db_dir, "cs.wal")

    def evidence_wal_path(self) -> str:
        return os.path.join(self.home, self.base.db_dir, "evidence.wal")

    def mempool_wal_path(self) -> str:
        return os.path.join(self.home, self.mempool.wal_dir)

    @classmethod
    def default(cls, home: str = ".") -> "Config":
        return cls(home=home)

    @classmethod
    def test_config(cls, home: str = ".") -> "Config":
        """Shrunk timeouts, ephemeral ports (reference `TestConfig`)."""
        cfg = cls(home=home, consensus=ConsensusConfig.test_config())
        cfg.rpc.laddr = "tcp://127.0.0.1:0"
        cfg.p2p.laddr = "tcp://127.0.0.1:0"
        cfg.p2p.pex_ensure_interval_s = 0.5
        cfg.statesync.discovery_time_s = 0.5
        cfg.statesync.chunk_request_timeout_s = 3.0
        cfg.statesync.giveup_time_s = 20.0
        try:
            import cryptography  # noqa: F401
        except ImportError:
            # X25519/AEAD transport crypto deliberately has NO pure-Python
            # fallback (unlike signing, `crypto/ed25519_ref.py`); in test
            # environments without the library, run links unencrypted so
            # the p2p/node matrices still exercise everything else.
            # Production configs keep secret_connections=True and fail
            # loudly if the library is missing.
            cfg.p2p.secret_connections = False
        return cfg


_SECTIONS = ("base", "rpc", "p2p", "mempool", "consensus", "statesync", "replica")


def write_config(cfg: Config) -> str:
    """Emit `$home/config.toml` (reference `config/toml.go`)."""
    lines = ["# tendermint_tpu configuration\n"]
    for section in _SECTIONS:
        sub = getattr(cfg, section)
        lines.append(f"[{section}]")
        for f in fields(sub):
            v = getattr(sub, f.name)
            if isinstance(v, bool):
                tv = "true" if v else "false"
            elif isinstance(v, (int, float)):
                tv = str(v)
            else:
                tv = '"%s"' % str(v).replace("\\", "\\\\").replace('"', '\\"')
            lines.append(f"{f.name} = {tv}")
        lines.append("")
    path = os.path.join(cfg.home, "config.toml")
    os.makedirs(cfg.home, exist_ok=True)
    with open(path, "w") as fh:
        fh.write("\n".join(lines))
    return path


def _parse_toml_subset(text: str) -> dict:
    """Parse exactly the dialect `write_config` emits ([section] headers,
    `key = true|false|int|float|"escaped string"` lines, # comments) —
    the stdlib `tomllib` only exists on Python 3.11+, and a node must
    still boot its own config files on 3.10 hosts."""
    doc: dict = {}
    section: dict | None = None
    for raw in text.splitlines():
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        if line.startswith("[") and line.endswith("]"):
            section = doc.setdefault(line[1:-1].strip(), {})
            continue
        if section is None or "=" not in line:
            continue
        key, _, val = (p.strip() for p in line.partition("="))
        if val.startswith('"') and val.endswith('"') and len(val) >= 2:
            body = val[1:-1]
            out, i = [], 0
            while i < len(body):
                if body[i] == "\\" and i + 1 < len(body):
                    out.append(body[i + 1])
                    i += 2
                else:
                    out.append(body[i])
                    i += 1
            section[key] = "".join(out)
        elif val in ("true", "false"):
            section[key] = val == "true"
        else:
            try:
                section[key] = int(val)
            except ValueError:
                try:
                    section[key] = float(val)
                except ValueError:
                    continue  # not our dialect: ignore the line
    return doc


def load_config(home: str) -> Config:
    """Defaults overlaid with `$home/config.toml` when present."""
    cfg = Config.default(home)
    path = os.path.join(home, "config.toml")
    if not os.path.exists(path):
        return cfg
    try:
        import tomllib  # Python 3.11+

        with open(path, "rb") as fh:
            doc = tomllib.load(fh)
    except ImportError:
        with open(path, "r") as fh:
            doc = _parse_toml_subset(fh.read())
    for section in _SECTIONS:
        sub = getattr(cfg, section)
        for f in fields(sub):
            if section in doc and f.name in doc[section]:
                setattr(sub, f.name, doc[section][f.name])
    return cfg
