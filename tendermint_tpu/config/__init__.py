"""Typed config tree + TOML persistence (reference `config/config.go`,
`config/toml.go`).

Defaults match the reference's shape: Base (home/moniker/fast-sync),
RPC, P2P, Mempool, Consensus sub-configs, with `Default*` and `Test*`
presets. `load_config` merges `$home/config.toml` over defaults;
`write_config` emits a commented TOML on `init`.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field, fields

from tendermint_tpu.consensus.config import ConsensusConfig


@dataclass
class BaseConfig:
    """Reference `config/config.go:57-132`."""

    moniker: str = "anonymous"
    fast_sync: bool = True
    db_dir: str = "data"
    log_level: str = "state:info,*:error"
    genesis_file: str = "genesis.json"
    priv_validator_file: str = "priv_validator.json"


@dataclass
class RPCConfig:
    """Reference `config/config.go:163-193`."""

    laddr: str = "tcp://127.0.0.1:46657"
    grpc_laddr: str = ""
    unsafe: bool = False


@dataclass
class P2PConfig:
    """Reference `config/config.go:199-256`."""

    laddr: str = "tcp://0.0.0.0:46656"
    # dialable address advertised to peers via PEX; REQUIRED for
    # multi-machine deployments when binding 0.0.0.0 (loopback is only
    # inferred for single-host setups)
    external_address: str = ""
    seeds: str = ""  # comma-separated host:port
    persistent_peers: str = ""
    secret_connections: bool = True  # X25519+AEAD STS on every peer link
    pex: bool = True  # peer-exchange discovery (addrbook + PEX reactor)
    # ask the ABCI app to vet peers via Query("/p2p/filter/...") before
    # admission (reference node/node.go:259-281, config FilterPeers)
    filter_peers: bool = False
    max_num_peers: int = 50
    pex_ensure_interval_s: float = 30.0  # reference ensurePeersPeriod
    send_rate: int = 512000  # bytes/s (flow limits live in MConnection)
    recv_rate: int = 512000
    # keepalive: ping idle peers, drop them when silent past the grace
    # window (reference pingTimeout 40s, `p2p/connection.go:312-345`)
    ping_interval_s: float = 40.0
    pong_timeout_s: float = 20.0
    # persistent-peer redial policy (reference `p2p/switch.go:15-18`:
    # reconnectAttempts 30 with backoff)
    reconnect_max_attempts: int = 30
    reconnect_base_backoff_s: float = 1.0


@dataclass
class MempoolConfig:
    """Reference `config/config.go:267-288`."""

    recheck: bool = True
    broadcast: bool = True
    wal_dir: str = "data/mempool.wal"
    cache_size: int = 100_000


@dataclass
class Config:
    home: str = "."
    base: BaseConfig = field(default_factory=BaseConfig)
    rpc: RPCConfig = field(default_factory=RPCConfig)
    p2p: P2PConfig = field(default_factory=P2PConfig)
    mempool: MempoolConfig = field(default_factory=MempoolConfig)
    consensus: ConsensusConfig = field(default_factory=ConsensusConfig)

    # -- derived paths -----------------------------------------------------

    def genesis_path(self) -> str:
        return os.path.join(self.home, self.base.genesis_file)

    def priv_validator_path(self) -> str:
        return os.path.join(self.home, self.base.priv_validator_file)

    def node_key_path(self) -> str:
        return os.path.join(self.home, "node_key.json")

    def db_path(self, name: str) -> str:
        return os.path.join(self.home, self.base.db_dir, f"{name}.db")

    def wal_path(self) -> str:
        return os.path.join(self.home, self.base.db_dir, "cs.wal")

    def mempool_wal_path(self) -> str:
        return os.path.join(self.home, self.mempool.wal_dir)

    @classmethod
    def default(cls, home: str = ".") -> "Config":
        return cls(home=home)

    @classmethod
    def test_config(cls, home: str = ".") -> "Config":
        """Shrunk timeouts, ephemeral ports (reference `TestConfig`)."""
        cfg = cls(home=home, consensus=ConsensusConfig.test_config())
        cfg.rpc.laddr = "tcp://127.0.0.1:0"
        cfg.p2p.laddr = "tcp://127.0.0.1:0"
        cfg.p2p.pex_ensure_interval_s = 0.5
        return cfg


_SECTIONS = ("base", "rpc", "p2p", "mempool", "consensus")


def write_config(cfg: Config) -> str:
    """Emit `$home/config.toml` (reference `config/toml.go`)."""
    lines = ["# tendermint_tpu configuration\n"]
    for section in _SECTIONS:
        sub = getattr(cfg, section)
        lines.append(f"[{section}]")
        for f in fields(sub):
            v = getattr(sub, f.name)
            if isinstance(v, bool):
                tv = "true" if v else "false"
            elif isinstance(v, (int, float)):
                tv = str(v)
            else:
                tv = '"%s"' % str(v).replace("\\", "\\\\").replace('"', '\\"')
            lines.append(f"{f.name} = {tv}")
        lines.append("")
    path = os.path.join(cfg.home, "config.toml")
    os.makedirs(cfg.home, exist_ok=True)
    with open(path, "w") as fh:
        fh.write("\n".join(lines))
    return path


def load_config(home: str) -> Config:
    """Defaults overlaid with `$home/config.toml` when present."""
    import tomllib

    cfg = Config.default(home)
    path = os.path.join(home, "config.toml")
    if not os.path.exists(path):
        return cfg
    with open(path, "rb") as fh:
        doc = tomllib.load(fh)
    for section in _SECTIONS:
        sub = getattr(cfg, section)
        for f in fields(sub):
            if section in doc and f.name in doc[section]:
                setattr(sub, f.name, doc[section][f.name])
    return cfg
