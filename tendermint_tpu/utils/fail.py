"""Crash-point injection (role of ebuchman/fail-test in the reference).

`fail_point()` calls are numbered in program order per process; when the
`FAIL_TEST_INDEX` env var equals the current index the process exits
immediately with status 1 — exactly the reference's semantics
(`consensus/state.go:1172-1233`, `state/execution.go:224-243`,
`test/persist/test_failure_indices.sh:39-41`). Used by the
kill-at-every-persistence-step recovery test matrix.
"""

from __future__ import annotations

import os
import sys

_counter = 0


class SimulatedCrash(BaseException):
    """In-process stand-in for the os._exit crash: derives from
    BaseException so the consensus receive loop's fault isolation
    (`except Exception`) cannot swallow it — the loop's thread dies
    mid-step exactly where the process would have. Raised instead of
    exiting when FAIL_TEST_SOFT is set (multi-node in-process chaos
    harnesses kill ONE node, not the whole test process)."""


def fail_point() -> None:
    global _counter
    target = os.environ.get("FAIL_TEST_INDEX")
    if target is None:
        return
    if _counter == int(target):
        if os.environ.get("FAIL_TEST_SOFT"):
            _counter += 1  # don't re-trip on the next call after restart
            raise SimulatedCrash(f"FAIL_TEST_INDEX={target}")
        sys.stderr.write(f"FAIL_TEST_INDEX={target}: exiting at fail point\n")
        sys.stderr.flush()
        os._exit(1)
    _counter += 1


def reset_for_testing() -> None:
    global _counter
    _counter = 0


# -- device fault injection ---------------------------------------------------
#
# The accelerator-dispatch analog of fail_point(): force device backend
# calls (batch verify, device merkle) to raise deterministically so the
# resilient-dispatch layer (`services/resilient.py`) can be driven
# through its degrade→probe→recover cycle in tests. Selected by the
# TENDERMINT_TPU_DEVICE_FAIL env var — "verify", "hash", "tables"
# (valset comb-table construction), "all", with an
# optional per-kind budget: "verify:3" fails the first 3 verify
# dispatches then clears; comma-separate for multiple kinds — or at
# runtime via set_device_fault()/clear_device_faults().


class InjectedDeviceFault(RuntimeError):
    """A test-injected device failure (stands in for compile errors,
    runtime errors, and dispatch timeouts)."""


_device_faults: dict[str, int] | None = None  # kind -> remaining (-1 = forever)


def _load_device_faults() -> dict[str, int]:
    global _device_faults
    if _device_faults is None:
        faults: dict[str, int] = {}
        spec = os.environ.get("TENDERMINT_TPU_DEVICE_FAIL", "")
        for part in filter(None, (p.strip() for p in spec.split(","))):
            kind, _, count = part.partition(":")
            faults[kind] = int(count) if count else -1
        _device_faults = faults
    return _device_faults


def set_device_fault(kind: str, count: int = -1) -> None:
    """Arm fault injection for `kind` ("verify"/"hash"/"tables"/"all");
    `count` dispatches fail (-1 = until cleared)."""
    _load_device_faults()[kind] = count


def clear_device_faults() -> None:
    global _device_faults
    _device_faults = {}


def device_faults_armed() -> bool:
    """Any fault injection configured (env or runtime)? The service
    layer uses this to wrap resilient dispatch even on host-only runs."""
    return bool(_load_device_faults()) or bool(
        os.environ.get("TENDERMINT_TPU_RESILIENT")
    )


def device_fail_point(kind: str) -> None:
    """Raise InjectedDeviceFault when a fault is armed for `kind` (or
    "all"), consuming one unit of a bounded budget."""
    faults = _load_device_faults()
    for k in (kind, "all"):
        remaining = faults.get(k)
        if remaining is None or remaining == 0:
            continue
        if remaining > 0:
            faults[k] = remaining - 1
        raise InjectedDeviceFault(f"injected {kind} device fault")


class ShardDeviceFault(InjectedDeviceFault):
    """A device fault attributable to ONE shard of a mesh (the
    per-chip analog of InjectedDeviceFault): the mesh layer catches it
    and re-meshes onto the survivors instead of tripping the whole
    verify breaker."""

    def __init__(self, shard: int):
        super().__init__(f"injected device fault on mesh shard {shard}")
        self.shard = shard


def shard_fail_point(indices) -> None:
    """Per-shard analog of `device_fail_point`: spec entries of the form
    "shard<i>" (optionally "shard<i>:<count>") fail launches that include
    device index `i` in their active mesh. Raises `ShardDeviceFault(i)`
    for the lowest armed index in `indices`, consuming budget."""
    faults = _load_device_faults()
    if not faults:
        return
    for i in indices:
        remaining = faults.get(f"shard{i}")
        if remaining is None or remaining == 0:
            continue
        if remaining > 0:
            faults[f"shard{i}"] = remaining - 1
        raise ShardDeviceFault(i)


def shard_fault_armed(index: int) -> bool:
    """True while a fault is still armed for mesh shard `index` (the
    re-probe path peeks without consuming budget)."""
    return bool(_load_device_faults().get(f"shard{index}"))
