"""Crash-point injection (role of ebuchman/fail-test in the reference).

`fail_point()` calls are numbered in program order per process; when the
`FAIL_TEST_INDEX` env var equals the current index the process exits
immediately with status 1 — exactly the reference's semantics
(`consensus/state.go:1172-1233`, `state/execution.go:224-243`,
`test/persist/test_failure_indices.sh:39-41`). Used by the
kill-at-every-persistence-step recovery test matrix.
"""

from __future__ import annotations

import os
import sys

_counter = 0


def fail_point() -> None:
    global _counter
    target = os.environ.get("FAIL_TEST_INDEX")
    if target is None:
        return
    if _counter == int(target):
        sys.stderr.write(f"FAIL_TEST_INDEX={target}: exiting at fail point\n")
        sys.stderr.flush()
        os._exit(1)
    _counter += 1


def reset_for_testing() -> None:
    global _counter
    _counter = 0
