"""Persistent XLA compilation cache.

The cold-start killer on this platform is XLA compile time, not device
work: the 10k-key table build is ~3.3 s warm but ~80 s cold because the
build kernel compiles per process (docs/PLATFORM_NOTES.md). The
persistent cache serializes compiled executables to disk so every
process after the first deserializes in milliseconds — the TPU analog
of the reference shipping precompiled binaries.

Called from every entry point that compiles kernels (node CLI, bench,
graft entries, tools). Opt out with TENDERMINT_TPU_XLA_CACHE=off.
"""

from __future__ import annotations

import os

_DEFAULT_DIR = os.path.expanduser("~/.cache/tendermint_tpu/xla")
_enabled = False


def enable_persistent_cache(cache_dir: str | None = None) -> str | None:
    """Point JAX at the on-disk executable cache. Idempotent; returns the
    cache dir or None when disabled via env.

    TENDERMINT_TPU_XLA_CACHE: off/0/false/no/disable disables; on/1/
    true/yes (or unset) uses the default dir; anything else must look
    like a path (contain a separator) and names the dir."""
    global _enabled
    env = os.environ.get("TENDERMINT_TPU_XLA_CACHE", "")
    if env.lower() in ("off", "0", "disable", "false", "no"):
        return None
    env_dir = env if env.lower() not in ("", "on", "1", "true", "yes") else ""
    if env_dir and os.sep not in env_dir:
        raise ValueError(
            f"TENDERMINT_TPU_XLA_CACHE={env!r}: expected on/off or a directory path"
        )
    path = cache_dir or env_dir or _DEFAULT_DIR
    if _enabled:
        return path
    import jax

    # TPU executables only: XLA:CPU AOT results bake in host machine
    # features and warn "could lead to SIGILL" when loaded on a host
    # whose feature detection differs (observed with the axon stack) —
    # and CPU compiles are cheap enough not to need the cache
    if jax.default_backend() != "tpu":
        return None
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    # every kernel here is worth caching: even "fast" compiles are tens
    # of launch floors on this device
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    _enabled = True
    from tendermint_tpu.telemetry import metrics

    metrics.XLA_CACHE_ENABLED.set(1)
    return path
