"""Host runtime utilities (the tmlibs role: SURVEY.md §2b layer 0)."""
