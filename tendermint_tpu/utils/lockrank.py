"""Runtime lock-rank sanitizer: the dynamic half of tmlint.

The Go reference keeps its concurrency spine honest with `go test
-race`; Python has no race detector, so this module enforces the next
best thing — a **declared lock acquisition order** — at test time.
`RANKS` below is the normative table (docs/STATIC_ANALYSIS.md): locks
must be acquired in ascending rank order, and same-rank locks (the
mempool lanes, the sig-cache shards) only in ascending `seq` order.
The PR 8 mempool discipline `lane -> _wal_lock -> _counter_lock` is
rows 40 -> 48 -> 52 of this table.

Two detectors run on every instrumented acquire:

* **Rank inversion** — acquiring a lock whose rank is <= the highest
  rank this thread already holds (same-rank + ascending seq excepted).
  Caught BEFORE the blocking acquire, so a deliberate ABBA deadlock is
  reported instead of hung.
* **Order-graph cycles** — every first-time edge ``held -> acquired``
  enters a process-global directed graph with the acquiring thread's
  stack; an edge that closes a cycle is a potential deadlock even when
  every participating lock is unranked (rank=None). The report carries
  the acquisition stacks of BOTH sides of the cycle — the
  flight-recorder-style dump the acceptance criteria require.

Zero-overhead discipline: `ranked_lock()` / `ranked_rlock()` return
plain `threading.Lock()` / `RLock()` objects unless
``TENDERMINT_TPU_LOCKRANK=1`` (tests/conftest.py sets it for the whole
tier-1 run), so production hot paths never pay for the bookkeeping.

Violations are RECORDED by default (``violations()`` / ``drain()``;
the conftest autouse fixture turns them into test failures carrying
``render_report()``). ``TENDERMINT_TPU_LOCKRANK_RAISE=1`` or
``set_raise(True)`` raises `LockRankViolation` at the offending acquire
instead — which is what lets an ABBA regression test run to completion
without deadlocking.

**Contention timing** (the PR 12 contention observatory): when the
profiler arms it (``set_timing(True)``, via
``telemetry/profiler.py``), every `RankedLock` additionally records
acquire-wait and hold durations into the
``tendermint_lock_wait_seconds{lock}`` /
``tendermint_lock_hold_seconds{lock}`` histograms plus a per-site
accumulator (``contention_snapshot()`` — the top-contended view
`dump_telemetry?profile=1` serves). The timing rides the same
hold-stack bookkeeping the sanitizer keeps, and costs one module-global
bool read when disarmed. Locks are only *instrumentable* when they were
constructed as `RankedLock`s: either the sanitizer is on, or
``TENDERMINT_TPU_PROFILE_HZ`` is set (any value, including ``0``) at
process start so a later boost can arm timing.
"""

from __future__ import annotations

import os
import sys
import threading
import time
import traceback

# -- the declared rank table (normative; see docs/STATIC_ANALYSIS.md) --------
#
# Ascending rank == legal acquisition order. Gaps are deliberate so new
# locks slot in without renumbering. tmlint rule L001 statically checks
# nested `with` blocks against this same table.
RANKS: dict[str, int] = {
    # consensus holds its big lock across mempool/evidence/dispatch work,
    # so it is the lowest-ranked lock in the process.
    "consensus.state": 10,  # ConsensusState._mtx
    "evidence.pool": 20,  # EvidencePool._lock
    # mempool (PR 8 order made normative): _avail -> lanes -> wal -> counter
    "mempool.avail": 30,  # Mempool._avail's Condition lock
    "mempool.ingress": 35,  # IngressBatcher._cond's lock
    "mempool.lane": 40,  # _Lane.lock (seq = lane index)
    "mempool.txcache": 44,  # TxCache._lock (under lanes via recheck/flush)
    "mempool.wal": 48,  # Mempool._wal_lock
    "mempool.counter": 52,  # Mempool._counter_lock
    "mempool.notif": 56,  # Mempool._notif_lock (under all lanes in update())
    "mempool.trace": 60,  # Mempool._trace_lock
    # contention-observatory sampler state (telemetry/profiler.py):
    # leaf-ish — held over sample aggregation only, never across a
    # dispatch/verify call, but snapshot() is served under RPC handlers
    # that may hold nothing, so it slots below the verify spine.
    "telemetry.profiler": 62,
    # light-client certified-commit cache: leaf shard/index locks (seq =
    # shard index; index lock = seq SHARDS), held over map surgery only
    "lightclient.cache": 63,
    # verify spine
    "dispatch.handle": 64,  # VerifyHandle/ChainedHandle._lock
    "batcher.shard": 68,  # VerifiedSigCache shard locks (seq = shard index)
    "batcher.window": 72,  # VerifyCoalescer._cond's lock
    "dispatch.worker": 76,  # DispatchQueue._thread_lock
    "dispatch.state": 80,  # DispatchQueue._state_lock
    "dispatch.global": 84,  # default_dispatch_queue singleton lock
    # light-client reactor bookkeeping (subscribers / request waits):
    # leaf — released before any send, certify, or evidence admission
    "lightclient.reactor": 86,
    # p2p locks are leaves: held only over dict/counter surgery, never
    # across reactor callbacks or sends.
    "p2p.switch": 88,  # Switch._mtx
    "p2p.scorer": 90,  # PeerScorer._lock
    "p2p.conn.write": 92,  # tcp/secret per-connection write locks
    "p2p.flowrate": 94,  # flowrate.Monitor._lock
}

_ENV = "TENDERMINT_TPU_LOCKRANK"
_ENV_RAISE = "TENDERMINT_TPU_LOCKRANK_RAISE"
_ENV_PROFILE = "TENDERMINT_TPU_PROFILE_HZ"  # owned by telemetry/profiler.py

_STACK_FRAMES = 14  # per-side stack depth kept in edge/violation reports


class LockRankViolation(RuntimeError):
    """Raised (in raise mode) before the offending acquire blocks."""


def enabled() -> bool:
    return os.environ.get(_ENV, "0") not in ("", "0")


def timing_capable() -> bool:
    """True when `ranked_lock()` should hand out instrumentable
    `RankedLock`s even without the sanitizer: the profiler env knob is
    present (any value — ``0`` means "off now, armable later")."""
    return os.environ.get(_ENV_PROFILE) is not None


_raise_mode: bool | None = None


def _should_raise() -> bool:
    if _raise_mode is not None:
        return _raise_mode
    return os.environ.get(_ENV_RAISE, "0") not in ("", "0")


def set_raise(on: bool | None) -> None:
    """Force raise mode on/off; None restores the env-var default."""
    global _raise_mode
    _raise_mode = on


# -- process-global state -----------------------------------------------------


class _TLS(threading.local):
    def __init__(self) -> None:
        self.stack: list[_Held] = []


_tls = _TLS()

_graph_lock = threading.Lock()
# edge (from_name, to_name) -> first-observation record
_edges: dict[tuple[str, str], dict] = {}
# adjacency for cycle detection (names)
_adj: dict[str, set[str]] = {}

_viol_lock = threading.Lock()
_violations: list[dict] = []


class _Held:
    __slots__ = ("lock", "count", "t_acquired", "wait_s", "site")

    def __init__(self, lock: "RankedLock") -> None:
        self.lock = lock
        self.count = 1
        # contention-timing stamps, set only while timing is armed at
        # first entry (None otherwise — the pair goes unrecorded)
        self.t_acquired: float | None = None
        self.wait_s = 0.0
        self.site: str | None = None


def _capture_stack() -> list[str]:
    # skip this helper + the sanitizer frames above it
    return traceback.format_list(
        traceback.extract_stack(limit=_STACK_FRAMES + 3)[:-3]
    )


def _record_violation(kind: str, message: str, stacks: list[dict]) -> None:
    v = {
        "kind": kind,
        "message": message,
        "thread": threading.current_thread().name,
        "stacks": stacks,
    }
    with _viol_lock:
        _violations.append(v)
    if _should_raise():
        raise LockRankViolation(message + "\n" + render_violation(v))


def violations() -> list[dict]:
    with _viol_lock:
        return list(_violations)


def drain() -> list[dict]:
    """Return and clear recorded violations (the conftest fixture)."""
    with _viol_lock:
        out = list(_violations)
        _violations.clear()
    return out


def reset() -> None:
    """Clear violations AND the learned order graph (test isolation)."""
    with _viol_lock:
        _violations.clear()
    with _graph_lock:
        _edges.clear()
        _adj.clear()


def render_violation(v: dict) -> str:
    lines = [f"[{v['kind']}] {v['message']} (thread {v['thread']})"]
    for s in v["stacks"]:
        lines.append(f"  -- {s['label']} (thread {s['thread']}):")
        for frame in s["stack"]:
            for ln in frame.rstrip().splitlines():
                lines.append("    " + ln)
    return "\n".join(lines)


def render_report() -> str:
    """Flight-recorder-style dump of every recorded violation."""
    vs = violations()
    if not vs:
        return "lockrank: no violations recorded"
    out = [f"lockrank: {len(vs)} violation(s)"]
    for i, v in enumerate(vs):
        out.append(f"--- violation {i + 1} ---")
        out.append(render_violation(v))
    return "\n".join(out)


# -- contention timing (armed by telemetry/profiler.py) -----------------------
#
# One module-global bool gates everything: disarmed, a timed acquire is
# a single global read on top of the sanitizer bookkeeping. Armed, a
# first-entry acquire stamps perf_counter around the blocking acquire
# (wait) and the final release stamps the hold — ONE per-instance stat
# update per acquire/release pair (the stat lock is per RankedLock, so
# it only serializes threads already serialized on that lock).
# Histogram observes and site capture only fire above noise floors —
# an uncontended micro-acquire costs three perf_counter reads and a
# handful of float ops.

_TIMING = False
# registry guard (stat creation + snapshot only — never the hot path);
# deliberately a PLAIN lock, like _graph_lock/_viol_lock above
_stats_lock = threading.Lock()
_STATS: list["_LockStat"] = []
_MAX_SITES = 16  # per-lock site table bound
_HIST_FLOOR_S = 1e-5  # histogram observes below this are noise, dropped
_SITE_FLOOR_S = 5e-5  # waits below this skip the sys._getframe site walk


def set_timing(on: bool) -> None:
    """Arm/disarm contention timing on every live RankedLock.
    Idempotent; the profiler owns the lifecycle."""
    global _TIMING
    _TIMING = bool(on)


def timing_enabled() -> bool:
    return _TIMING


def _acquire_site() -> str:
    """`file.py:lineno` of the nearest caller frame outside this module
    — the per-site attribution key. Cheap: no traceback formatting."""
    f = sys._getframe(2)
    here = __file__
    while f is not None and f.f_code.co_filename == here:
        f = f.f_back
    if f is None:
        return "?"
    return f"{f.f_code.co_filename.rsplit('/', 1)[-1]}:{f.f_lineno}"


class _LockStat:
    """Per-RankedLock contention accumulator (merged by name at
    snapshot). Guarded by its own plain lock; the labeled histogram
    children are resolved once and cached."""

    __slots__ = (
        "name",
        "mtx",
        "wait_count",
        "wait_s",
        "wait_max",
        "hold_count",
        "hold_s",
        "hold_max",
        "sites",
        "wait_child",
        "hold_child",
    )

    def __init__(self, name: str) -> None:
        self.name = name
        self.mtx = threading.Lock()
        self.wait_count = 0
        self.wait_s = 0.0
        self.wait_max = 0.0
        self.hold_count = 0
        self.hold_s = 0.0
        self.hold_max = 0.0
        self.sites: dict[str, list] = {}
        try:
            from tendermint_tpu.telemetry import metrics as _m

            self.wait_child = _m.LOCK_WAIT_SECONDS.labels(lock=name)
            self.hold_child = _m.LOCK_HOLD_SECONDS.labels(lock=name)
        except Exception:  # telemetry must never break a lock acquire
            self.wait_child = None
            self.hold_child = None

    def record(self, wait_s: float, hold_s: float, site: str | None) -> None:
        with self.mtx:
            self.wait_count += 1
            self.wait_s += wait_s
            if wait_s > self.wait_max:
                self.wait_max = wait_s
            self.hold_count += 1
            self.hold_s += hold_s
            if hold_s > self.hold_max:
                self.hold_max = hold_s
            if site is not None:
                s = self.sites.get(site)
                if s is None:
                    if len(self.sites) < _MAX_SITES:
                        self.sites[site] = [1, wait_s]
                else:
                    s[0] += 1
                    s[1] += wait_s
        if wait_s >= _HIST_FLOOR_S and self.wait_child is not None:
            self.wait_child.observe(wait_s)
        if hold_s >= _HIST_FLOOR_S and self.hold_child is not None:
            self.hold_child.observe(hold_s)


def _make_stat(name: str) -> _LockStat:
    st = _LockStat(name)
    with _stats_lock:
        _STATS.append(st)
    return st


def contention_snapshot(top: int = 10) -> dict:
    """Top-contended locks (by total acquire-wait) merged across
    instances sharing a name (lanes, shards), with per-site attribution
    — the `dump_telemetry?profile=1` lock view and the
    `tools/contention_report.py` input."""
    with _stats_lock:
        stats = list(_STATS)
    merged: dict[str, dict] = {}
    for st in stats:
        with st.mtx:
            if st.wait_count == 0 and st.hold_count == 0:
                continue
            row = merged.setdefault(
                st.name,
                {
                    "lock": st.name,
                    "wait_count": 0,
                    "wait_s": 0.0,
                    "wait_max_s": 0.0,
                    "hold_count": 0,
                    "hold_s": 0.0,
                    "hold_max_s": 0.0,
                    "_sites": {},
                },
            )
            row["wait_count"] += st.wait_count
            row["wait_s"] += st.wait_s
            row["wait_max_s"] = max(row["wait_max_s"], st.wait_max)
            row["hold_count"] += st.hold_count
            row["hold_s"] += st.hold_s
            row["hold_max_s"] = max(row["hold_max_s"], st.hold_max)
            for site, (cnt, w) in st.sites.items():
                s = row["_sites"].setdefault(site, [0, 0.0])
                s[0] += cnt
                s[1] += w
    rows = []
    for row in merged.values():
        sites = sorted(
            row.pop("_sites").items(), key=lambda kv: kv[1][1], reverse=True
        )[:3]
        row["wait_s"] = round(row["wait_s"], 6)
        row["wait_max_s"] = round(row["wait_max_s"], 6)
        row["hold_s"] = round(row["hold_s"], 6)
        row["hold_max_s"] = round(row["hold_max_s"], 6)
        row["top_sites"] = [
            {"site": site, "count": cnt, "wait_s": round(w, 6)}
            for site, (cnt, w) in sites
        ]
        rows.append(row)
    rows.sort(key=lambda r: r["wait_s"], reverse=True)
    return {"armed": _TIMING, "locks": rows[: max(0, top)]}


def reset_contention() -> None:
    with _stats_lock:
        stats = list(_STATS)
    for st in stats:
        with st.mtx:
            st.wait_count = 0
            st.wait_s = 0.0
            st.wait_max = 0.0
            st.hold_count = 0
            st.hold_s = 0.0
            st.hold_max = 0.0
            st.sites.clear()


def _find_path(src: str, dst: str) -> list[str] | None:
    """DFS path src -> dst in the order graph (caller holds _graph_lock)."""
    seen = {src}
    stack = [(src, [src])]
    while stack:
        node, path = stack.pop()
        for nxt in _adj.get(node, ()):
            if nxt == dst:
                return path + [nxt]
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + [nxt]))
    return None


def _note_edge(held: "RankedLock", acquiring: "RankedLock") -> None:
    """Record held -> acquiring in the order graph; a new edge that
    closes a cycle is a potential deadlock even if every lock is
    unranked (the classic ABBA shows up here as a 2-cycle)."""
    edge = (held.name, acquiring.name)
    if edge[0] == edge[1]:
        return  # same-name pairs (lanes, shards) are seq-checked instead
    if edge in _edges:
        return  # lock-free fast path: dict reads are GIL-atomic, and a
        # stale miss just falls through to the locked re-check below
    with _graph_lock:
        if edge in _edges:
            return
        back_path = _find_path(acquiring.name, held.name)
        _edges[edge] = {
            "thread": threading.current_thread().name,
            "stack": _capture_stack(),
        }
        _adj.setdefault(edge[0], set()).add(edge[1])
        if back_path is None:
            return
        # cycle: acquiring -> ... -> held exists, and we just added
        # held -> acquiring. Collect the stacks of every edge on the
        # reverse path plus this thread's — both sides of the deadlock.
        stacks = [
            {
                "label": f"order {edge[0]} -> {edge[1]} (this acquire)",
                "thread": threading.current_thread().name,
                "stack": _capture_stack(),
            }
        ]
        for a, b in zip(back_path, back_path[1:]):
            rec = _edges.get((a, b))
            if rec is not None:
                stacks.append(
                    {
                        "label": f"order {a} -> {b} (first observed)",
                        "thread": rec["thread"],
                        "stack": rec["stack"],
                    }
                )
        cycle = " -> ".join(back_path + [back_path[0]])
    _record_violation(
        "cycle",
        f"lock-order cycle (potential deadlock): {cycle}",
        stacks,
    )


class RankedLock:
    """threading.Lock with rank/order instrumentation.

    `rank=None` means unranked: the lock still participates in the
    order graph (cycle detection) but skips the rank comparison.
    """

    _factory = staticmethod(threading.Lock)

    def __init__(
        self,
        name: str,
        rank: int | None = None,
        seq: int = 0,
        sanitize: bool = True,
    ) -> None:
        self.name = name
        self.rank = RANKS.get(name) if rank is None else rank
        self.seq = seq
        # False when constructed purely for contention timing (profiler
        # env present, sanitizer off): hold-stack bookkeeping runs (the
        # Condition protocol and timing need it) but rank/order checks
        # don't — a timing-only process must never record violations.
        self.sanitize = sanitize
        self._stat: _LockStat | None = None  # lazy, first timed release
        self._inner = self._factory()

    # -- bookkeeping -------------------------------------------------------

    def _held_entry(self) -> _Held | None:
        for h in _tls.stack:
            if h.lock is self:
                return h
        return None

    def _check(self) -> None:
        stack = _tls.stack
        if not stack:
            return
        inverted = False
        if self.rank is not None:
            worst: RankedLock | None = None
            for h in stack:
                r = h.lock.rank
                if r is None:
                    continue
                bad = r > self.rank or (
                    r == self.rank
                    and h.lock is not self
                    and h.lock.seq >= self.seq
                )
                if bad and (worst is None or r >= (worst.rank or 0)):
                    worst = h.lock
            if worst is not None:
                inverted = True
                _record_violation(
                    "rank_inversion",
                    f"acquiring {self.name!r} (rank {self.rank}, seq "
                    f"{self.seq}) while holding {worst.name!r} (rank "
                    f"{worst.rank}, seq {worst.seq}) — declared order is "
                    f"ascending rank (see utils/lockrank.py RANKS)",
                    [
                        {
                            "label": f"acquire of {self.name} "
                            f"holding [{', '.join(h.lock.name for h in stack)}]",
                            "thread": threading.current_thread().name,
                            "stack": _capture_stack(),
                        }
                    ]
                    + self._reverse_edge_stacks(worst),
                )
        if not inverted:
            # an inversion is already the report; feeding it into the
            # order graph would double-report it as a cycle too
            self._note_edges()

    def _reverse_edge_stacks(self, worst: "RankedLock") -> list[dict]:
        """If the legal order (self -> worst) was ever observed, include
        that thread's stack: the report then shows BOTH threads'
        acquisition stacks for the inversion pair."""
        with _graph_lock:
            rec = _edges.get((self.name, worst.name))
        if rec is None:
            return []
        return [
            {
                "label": f"order {self.name} -> {worst.name} (first observed)",
                "thread": rec["thread"],
                "stack": rec["stack"],
            }
        ]

    def _note_edges(self) -> None:
        # one edge per DISTINCT held lock name: the transitive pairs
        # (lane -> counter with wal in between) matter both for cycle
        # detection and for inversion reports quoting the legal-order
        # thread's stack
        seen: set[str] = set()
        for h in _tls.stack:
            if h.lock.name in seen:
                continue
            seen.add(h.lock.name)
            _note_edge(h.lock, self)

    # -- lock protocol -----------------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        held = self._held_entry()
        if held is None and self.sanitize:
            self._check()
        timed = _TIMING and held is None
        if timed:
            t0 = time.perf_counter()
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            if held is not None:
                held.count += 1
            else:
                h = _Held(self)
                if timed:
                    h.t_acquired = time.perf_counter()
                    h.wait_s = h.t_acquired - t0
                    if h.wait_s >= _SITE_FLOOR_S:
                        h.site = _acquire_site()
                _tls.stack.append(h)
        return ok

    def release(self) -> None:
        stack = _tls.stack
        for i in range(len(stack) - 1, -1, -1):
            if stack[i].lock is self:
                stack[i].count -= 1
                if stack[i].count == 0:
                    h = stack[i]
                    del stack[i]
                    if h.t_acquired is not None:
                        stat = self._stat
                        if stat is None:
                            stat = self._stat = _make_stat(self.name)
                        stat.record(
                            h.wait_s,
                            time.perf_counter() - h.t_acquired,
                            h.site,
                        )
                break
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    # Condition-variable integration: threading.Condition picks these up
    # when present, so Condition(ranked_lock(...)) keeps correct owner
    # semantics (and wait() pops/pushes the hold like any release/acquire).
    def _is_owned(self) -> bool:
        return self._held_entry() is not None

    def _release_save(self):
        held = self._held_entry()
        n = held.count if held is not None else 1
        for _ in range(n):
            self.release()
        return n

    def _acquire_restore(self, n) -> None:
        for _ in range(n):
            self.acquire()

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<RankedLock {self.name} rank={self.rank} seq={self.seq}>"


class RankedRLock(RankedLock):
    _factory = staticmethod(threading.RLock)


def ranked_lock(name: str, rank: int | None = None, seq: int = 0):
    """A Lock carrying `name`'s declared rank — or a plain
    `threading.Lock` when neither the sanitizer nor the profiler knob
    is on (zero overhead). With only `TENDERMINT_TPU_PROFILE_HZ` set,
    the returned lock is timing-instrumentable but never rank-checked."""
    if enabled():
        return RankedLock(name, rank, seq)
    if timing_capable():
        return RankedLock(name, rank, seq, sanitize=False)
    return threading.Lock()


def ranked_rlock(name: str, rank: int | None = None, seq: int = 0):
    if enabled():
        return RankedRLock(name, rank, seq)
    if timing_capable():
        return RankedRLock(name, rank, seq, sanitize=False)
    return threading.RLock()
