"""Flow-rate monitoring + throttling (reference `tmlibs/flowrate`,
used per peer connection at `p2p/connection.go:72-73` with the
500 kB/s default caps at `config/config.go:244-247`)."""

from __future__ import annotations

import threading
import time

from tendermint_tpu.utils.lockrank import ranked_lock


class Monitor:
    """Byte-rate tracker with an exponential moving average, plus an
    optional limit: `throttle()` sleeps just enough to keep the average
    under the cap (the reference's blocking `Limit` mode)."""

    def __init__(
        self,
        limit_bytes_per_s: int = 0,
        window_s: float = 1.0,
        time_fn=time.monotonic,
    ) -> None:
        self.limit = limit_bytes_per_s
        self._window = window_s
        self._time = time_fn
        self._lock = ranked_lock("p2p.flowrate")
        self._total = 0
        self._rate = 0.0
        self._bucket = 0
        self._bucket_start = self._time()

    def update(self, n: int) -> None:
        with self._lock:
            self._total += n
            self._bucket += n
            self._roll()

    def _roll(self) -> None:
        now = self._time()
        elapsed = now - self._bucket_start
        if elapsed >= self._window:
            inst = self._bucket / elapsed
            # EMA: half the weight to the newest full window
            self._rate = inst if self._rate == 0 else (self._rate + inst) / 2
            self._bucket = 0
            self._bucket_start = now

    @property
    def total(self) -> int:
        with self._lock:
            return self._total

    @property
    def rate(self) -> float:
        """Bytes/s over the recent window."""
        with self._lock:
            self._roll()
            now = self._time()
            elapsed = now - self._bucket_start
            if elapsed > 0.05:
                inst = self._bucket / elapsed
                return (self._rate + inst) / 2 if self._rate else inst
            return self._rate

    def throttle(self) -> None:
        """Sleep long enough that the current window stays under the
        limit; no-op when unlimited."""
        if self.limit <= 0:
            return
        with self._lock:
            now = self._time()
            elapsed = now - self._bucket_start
            ahead = self._bucket / self.limit - elapsed
        if ahead > 0:
            time.sleep(min(ahead, self._window))
