"""Structured leveled logging with per-module levels.

Fills the tmlibs/log slot (reference go-kit logger + the
`log_level` config parsed in `cmd/.../root.go`): key-value lines,
per-subsystem levels from a spec like "state:info,consensus:debug,
*:error" (reference `config/config.go:150-157`).
"""

from __future__ import annotations

import logging
import time

_ROOT = "tendermint_tpu"

_LEVELS = {
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warn": logging.WARNING,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "none": logging.CRITICAL + 10,
}


class KVFormatter(logging.Formatter):
    """`ts=... level=... module=... msg="..." k=v` lines (go-kit style)."""

    def format(self, record: logging.LogRecord) -> str:
        ts = time.strftime("%H:%M:%S", time.localtime(record.created))
        module = record.name.removeprefix(_ROOT + ".")
        msg = record.getMessage().replace('"', "'")
        out = (
            f"ts={ts}.{int(record.msecs):03d} level={record.levelname.lower()} "
            f'module={module} msg="{msg}"'
        )
        extras = getattr(record, "kv", None)
        if extras:
            out += "".join(f" {k}={v}" for k, v in extras.items())
        return out


def logger(module: str) -> logging.Logger:
    """Subsystem logger, e.g. logger('consensus')."""
    return logging.getLogger(f"{_ROOT}.{module}")


def kv(log: logging.Logger, level: int, msg: str, **fields) -> None:
    """Structured emit: kv(log, logging.INFO, 'block committed', height=5)."""
    if log.isEnabledFor(level):
        log.log(level, msg, extra={"kv": fields})


def setup_logging(spec: str = "*:error", stream=None) -> None:
    """Apply a per-module level spec (reference log-level flag format:
    "module:level,...,*:default")."""
    root = logging.getLogger(_ROOT)
    # replace handlers (idempotent re-setup, e.g. tests)
    for h in list(root.handlers):
        root.removeHandler(h)
    handler = logging.StreamHandler(stream)
    handler.setFormatter(KVFormatter())
    root.addHandler(handler)
    root.propagate = False

    default = logging.ERROR
    per_module: dict[str, int] = {}
    for part in filter(None, (p.strip() for p in spec.split(","))):
        if ":" not in part:
            continue
        mod, _, level_name = part.partition(":")
        level = _LEVELS.get(level_name.strip().lower())
        if level is None:
            continue
        if mod.strip() == "*":
            default = level
        else:
            per_module[mod.strip()] = level
    root.setLevel(default)
    # reset levels from any previous spec, then apply the new one
    for name in list(logging.Logger.manager.loggerDict):
        if name.startswith(_ROOT + "."):
            logging.getLogger(name).setLevel(logging.NOTSET)
    for mod, level in per_module.items():
        logging.getLogger(f"{_ROOT}.{mod}").setLevel(level)
