"""Jittered exponential backoff (shared by persistent-peer redial and the
WS client's reconnect; reference retry policies `p2p/switch.go:15-18`,
`rpc/lib/client/ws_client.go:46-59`)."""

from __future__ import annotations

import random


def backoff_delay(attempt: int, base: float, cap: float = 30.0) -> float:
    """Delay for the given 0-indexed attempt: min(base * 2^attempt, cap)
    with up to +30% jitter so synchronized retriers fan out."""
    return min(base * (2**attempt), cap) * (1.0 + 0.3 * random.random())
