"""Thread-safe bit array (role of tmlibs `cmn.BitArray`; used for vote
bookkeeping `types/vote_set.go` and part-set completion tracking)."""

from __future__ import annotations

import random
import threading


class BitArray:
    def __init__(self, n: int, bits: int = 0):
        if n < 0:
            raise ValueError("negative size")
        self._n = n
        self._bits = bits & ((1 << n) - 1) if n else 0
        self._lock = threading.RLock()

    @property
    def size(self) -> int:
        return self._n

    def get(self, i: int) -> bool:
        if not (0 <= i < self._n):
            return False
        with self._lock:
            return bool((self._bits >> i) & 1)

    def set(self, i: int, v: bool) -> bool:
        if not (0 <= i < self._n):
            return False
        with self._lock:
            if v:
                self._bits |= 1 << i
            else:
                self._bits &= ~(1 << i)
        return True

    def copy(self) -> "BitArray":
        with self._lock:
            return BitArray(self._n, self._bits)

    def or_(self, other: "BitArray") -> "BitArray":
        return BitArray(max(self._n, other._n), self._bits | other._bits)

    def and_(self, other: "BitArray") -> "BitArray":
        return BitArray(min(self._n, other._n), self._bits & other._bits)

    def not_(self) -> "BitArray":
        return BitArray(self._n, ~self._bits)

    def sub(self, other: "BitArray") -> "BitArray":
        """Bits set in self but not in other."""
        return BitArray(self._n, self._bits & ~other._bits)

    def count(self) -> int:
        """Number of set bits."""
        with self._lock:
            return self._bits.bit_count()

    def is_empty(self) -> bool:
        with self._lock:
            return self._bits == 0

    def is_full(self) -> bool:
        with self._lock:
            return self._n > 0 and self._bits == (1 << self._n) - 1

    def pick_random(self, rng: random.Random | None = None) -> tuple[int, bool]:
        """A uniformly random set bit (used by gossip to pick a part to send,
        reference `consensus/reactor.go:418-497`)."""
        with self._lock:
            set_bits = [i for i in range(self._n) if (self._bits >> i) & 1]
        if not set_bits:
            return 0, False
        r = rng or random
        return r.choice(set_bits), True

    def num_set(self) -> int:
        with self._lock:
            return bin(self._bits).count("1")

    def to_int(self) -> int:
        with self._lock:
            return self._bits

    def update(self, other: "BitArray") -> None:
        with self._lock:
            self._bits = other._bits & ((1 << self._n) - 1) if self._n else 0

    def __eq__(self, other: object) -> bool:
        if not isinstance(other, BitArray):
            return NotImplemented
        return self._n == other._n and self._bits == other._bits

    def __repr__(self) -> str:
        return "BA{" + "".join("x" if self.get(i) else "_" for i in range(self._n)) + "}"
