"""Circuit breaker for device-backend dispatch.

The classic three-state machine: CLOSED (healthy, calls flow to the
primary), OPEN (N consecutive failures tripped it — calls route to the
fallback until a reset timeout elapses), HALF_OPEN (timeout elapsed —
exactly one probe call is let through; success re-closes, failure
re-opens). `services/resilient.py` wraps every device backend call in
one of these so a sick accelerator degrades a node to host crypto
instead of killing it mid-consensus.

Thread-safe: consensus, fast-sync, and RPC threads all dispatch through
the same breaker instance.
"""

from __future__ import annotations

import threading
import time

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

# numeric encoding for the telemetry gauge (ordered by "badness")
STATE_CODES = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


class CircuitBreaker:
    def __init__(
        self,
        failure_threshold: int = 3,
        reset_timeout_s: float = 5.0,
        clock=time.monotonic,
        on_state_change=None,
        name: str | None = None,
    ) -> None:
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        self.failure_threshold = failure_threshold
        self.reset_timeout_s = reset_timeout_s
        self._clock = clock
        self._listeners = [on_state_change] if on_state_change is not None else []
        self._mtx = threading.RLock()
        self._state = CLOSED
        self._consecutive_failures = 0
        self._opened_at = 0.0
        self._probe_in_flight = False
        # lifetime counters, exported with degradation state
        self.total_failures = 0
        self.total_successes = 0
        self.times_opened = 0
        self.name: str | None = None
        if name is not None:
            self.bind_telemetry(name)

    def add_state_listener(self, fn) -> None:
        """fn(old, new) on every transition; listeners run under the
        breaker lock, so keep them cheap (log lines, counter bumps)."""
        self._listeners.append(fn)

    def bind_telemetry(self, name: str) -> None:
        """Export this breaker under `kind=name`: a state gauge plus
        transition counters (to=open counts trips, to=closed counts
        recoveries) — the exporter hook `snapshot()` always promised.
        Idempotent; the first name wins."""
        if self.name is not None:
            return
        self.name = name
        from tendermint_tpu.telemetry import metrics

        metrics.BREAKER_STATE.labels(kind=name).set(STATE_CODES[self._state])

        def _export(old: str, new: str) -> None:
            metrics.BREAKER_STATE.labels(kind=name).set(STATE_CODES[new])
            metrics.BREAKER_TRANSITIONS.labels(kind=name, to=new).inc()
            # breaker transitions are forensic moments: black-box the
            # event and sample-everything for a window so the messages
            # around the degradation are all attributable
            from tendermint_tpu.telemetry import tracectx
            from tendermint_tpu.telemetry.flightrec import FLIGHT

            FLIGHT.record("breaker", breaker=name, frm=old, to=new)
            tracectx.boost()

        self._listeners.append(_export)

    @property
    def state(self) -> str:
        with self._mtx:
            self._maybe_half_open()
            return self._state

    def _set_state(self, new: str) -> None:
        old, self._state = self._state, new
        if old != new:
            for fn in self._listeners:
                fn(old, new)

    def _maybe_half_open(self) -> None:
        if (
            self._state == OPEN
            and self._clock() - self._opened_at >= self.reset_timeout_s
        ):
            self._probe_in_flight = False
            self._set_state(HALF_OPEN)

    def allow(self) -> bool:
        """May this call go to the primary? OPEN answers False until the
        reset timeout, then HALF_OPEN admits exactly one probe at a time
        (concurrent callers keep getting False until the probe reports)."""
        with self._mtx:
            self._maybe_half_open()
            if self._state == CLOSED:
                return True
            if self._state == HALF_OPEN and not self._probe_in_flight:
                self._probe_in_flight = True
                return True
            return False

    def record_success(self) -> None:
        with self._mtx:
            self.total_successes += 1
            self._consecutive_failures = 0
            self._probe_in_flight = False
            if self._state != CLOSED:
                self._set_state(CLOSED)

    def record_failure(self) -> None:
        with self._mtx:
            self.total_failures += 1
            self._consecutive_failures += 1
            if self._state == HALF_OPEN:
                # failed probe: back to OPEN for a full reset window
                self._probe_in_flight = False
                self._opened_at = self._clock()
                self.times_opened += 1
                self._set_state(OPEN)
            elif (
                self._state == CLOSED
                and self._consecutive_failures >= self.failure_threshold
            ):
                self._opened_at = self._clock()
                self.times_opened += 1
                self._set_state(OPEN)

    def snapshot(self) -> dict:
        """Degradation state for logs/metrics exporters (the same
        numbers `bind_telemetry` streams into the registry)."""
        with self._mtx:
            self._maybe_half_open()
            return {
                "state": self._state,
                "consecutive_failures": self._consecutive_failures,
                "total_failures": self.total_failures,
                "total_successes": self.total_successes,
                "times_opened": self.times_opened,
            }
