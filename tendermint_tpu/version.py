"""Version info (reference: version/version.go:3-10)."""

MAJOR = 0
MINOR = 1
PATCH = 0

__version__ = f"{MAJOR}.{MINOR}.{PATCH}"

# Wire-protocol compatibility versions, checked during the p2p handshake
# (reference: p2p/switch.go version/chainID compat check).
BLOCK_PROTOCOL = 1
P2P_PROTOCOL = 1
