"""Persisted chain state (reference `state/state.go:38-68`).

State is the consensus-critical snapshot between blocks: validators for
the next height, last validators (who must have signed LastCommit), app
hash, and consensus params. Persisted as canonical JSON in the state DB
under fixed keys; historical validator sets are stored per height with
change-height compression (`state/state.go:174-224`) so fast-sync and
light clients can verify old commits.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

from tendermint_tpu.abci.types import Result, Validator as ABCIValidator
from tendermint_tpu.crypto.keys import PubKey
from tendermint_tpu.db.kv import DB
from tendermint_tpu.types.block_id import BlockID
from tendermint_tpu.types.errors import ValidationError
from tendermint_tpu.types.genesis import GenesisDoc
from tendermint_tpu.types.params import ConsensusParams
from tendermint_tpu.types.part_set import PartSetHeader
from tendermint_tpu.types.validator_set import Validator, ValidatorSet

_STATE_KEY = b"stateKey"


def _valset_to_dict(vs: ValidatorSet) -> dict:
    return {
        "validators": [
            {
                "address": v.address.hex(),
                "pub_key": v.pub_key.data.hex(),
                "voting_power": v.voting_power,
                "accum": v.accum,
            }
            for v in vs.validators
        ]
    }


def _valset_from_dict(d: dict) -> ValidatorSet:
    return ValidatorSet(
        [
            Validator(
                address=bytes.fromhex(v["address"]),
                pub_key=PubKey(bytes.fromhex(v["pub_key"])),
                voting_power=v["voting_power"],
                accum=v["accum"],
            )
            for v in d["validators"]
        ]
    )


@dataclass
class ABCIResponses:
    """Results of executing a block against the app, saved *before* the
    app commits so a crash between app-commit and state-save can be
    replayed against a mock app (reference `state/state.go:286-293`,
    `consensus/replay.go:362-398`)."""

    height: int
    deliver_tx: list[Result] = field(default_factory=list)
    end_block_changes: list[ABCIValidator] = field(default_factory=list)

    def to_json(self) -> bytes:
        return json.dumps(
            {
                "height": self.height,
                "deliver_tx": [
                    {"code": r.code, "data": r.data.hex(), "log": r.log}
                    for r in self.deliver_tx
                ],
                "end_block_changes": [
                    {"pub_key": v.pub_key.hex(), "power": v.power}
                    for v in self.end_block_changes
                ],
            },
            sort_keys=True,
        ).encode()

    @classmethod
    def from_json(cls, raw: bytes) -> "ABCIResponses":
        d = json.loads(raw.decode())
        return cls(
            height=d["height"],
            deliver_tx=[
                Result(r["code"], bytes.fromhex(r["data"]), r["log"])
                for r in d["deliver_tx"]
            ],
            end_block_changes=[
                ABCIValidator(bytes.fromhex(v["pub_key"]), v["power"])
                for v in d["end_block_changes"]
            ],
        )


@dataclass
class State:
    chain_id: str
    consensus_params: ConsensusParams
    last_block_height: int
    last_block_id: BlockID
    last_block_time: int  # ns since epoch (matches Header.time)
    validators: ValidatorSet
    last_validators: ValidatorSet
    last_height_validators_changed: int
    app_hash: bytes
    db: DB | None = None

    # -- persistence ---------------------------------------------------------

    def to_json(self) -> bytes:
        return json.dumps(
            {
                "chain_id": self.chain_id,
                "consensus_params": self.consensus_params.to_dict(),
                "last_block_height": self.last_block_height,
                "last_block_id": {
                    "hash": self.last_block_id.hash.hex(),
                    "parts": {
                        "total": self.last_block_id.parts_header.total,
                        "hash": self.last_block_id.parts_header.hash.hex(),
                    },
                },
                "last_block_time": self.last_block_time,
                "validators": _valset_to_dict(self.validators),
                "last_validators": _valset_to_dict(self.last_validators),
                "last_height_validators_changed": self.last_height_validators_changed,
                "app_hash": self.app_hash.hex(),
            },
            sort_keys=True,
        ).encode()

    @classmethod
    def from_json(cls, raw: bytes, db: DB | None = None) -> "State":
        d = json.loads(raw.decode())
        bid = d["last_block_id"]
        return cls(
            chain_id=d["chain_id"],
            consensus_params=ConsensusParams.from_dict(d["consensus_params"]),
            last_block_height=d["last_block_height"],
            last_block_id=BlockID(
                bytes.fromhex(bid["hash"]),
                PartSetHeader(bid["parts"]["total"], bytes.fromhex(bid["parts"]["hash"])),
            ),
            last_block_time=d["last_block_time"],
            validators=_valset_from_dict(d["validators"]),
            last_validators=_valset_from_dict(d["last_validators"]),
            last_height_validators_changed=d["last_height_validators_changed"],
            app_hash=bytes.fromhex(d["app_hash"]),
            db=db,
        )

    def save(self) -> None:
        if self.db is None:
            raise ValidationError("state has no db to save to")
        self.save_validators_info()
        self.db.set_sync(_STATE_KEY, self.to_json())

    def copy(self) -> "State":
        return State(
            chain_id=self.chain_id,
            consensus_params=self.consensus_params,
            last_block_height=self.last_block_height,
            last_block_id=self.last_block_id,
            last_block_time=self.last_block_time,
            validators=self.validators.copy(),
            last_validators=self.last_validators.copy(),
            last_height_validators_changed=self.last_height_validators_changed,
            app_hash=self.app_hash,
            db=self.db,
        )

    def equals(self, other: "State") -> bool:
        return self.to_json() == other.to_json()

    # -- historical validator sets ------------------------------------------

    @staticmethod
    def _validators_key(height: int) -> bytes:
        return b"validatorsKey:%d" % height

    def save_validators_info(self) -> None:
        """Store validators-for-height(H+1) with change-height compression:
        full set only when it changed, else a pointer to the last change
        (reference `state/state.go:174-224`)."""
        if self.db is None:
            return
        next_height = self.last_block_height + 1
        changed = self.last_height_validators_changed
        if next_height == changed:
            doc = {"last_changed": changed, "validators": _valset_to_dict(self.validators)}
        else:
            doc = {"last_changed": changed}
        self.db.set(self._validators_key(next_height), json.dumps(doc, sort_keys=True).encode())

    def save_validators_full(self) -> None:
        """Write the FULL current validator set at its change height.

        Snapshot restore seeds a fresh state DB with this so the
        change-height pointers `save_validators_info` writes afterwards
        resolve (`load_validators` would otherwise chase a pointer into
        pre-snapshot history this node never stored)."""
        if self.db is None:
            return
        doc = {
            "last_changed": self.last_height_validators_changed,
            "validators": _valset_to_dict(self.validators),
        }
        self.db.set(
            self._validators_key(self.last_height_validators_changed),
            json.dumps(doc, sort_keys=True).encode(),
        )

    def load_validators(self, height: int) -> ValidatorSet:
        """Validator set that was responsible for signing at `height`."""
        if self.db is None:
            raise ValidationError("state has no db")
        raw = self.db.get(self._validators_key(height))
        if raw is None:
            raise ValidationError(f"no validators saved for height {height}")
        doc = json.loads(raw.decode())
        if "validators" not in doc:
            raw = self.db.get(self._validators_key(doc["last_changed"]))
            if raw is None:
                raise ValidationError(
                    f"dangling validators pointer {height}->{doc['last_changed']}"
                )
            doc = json.loads(raw.decode())
        return _valset_from_dict(doc["validators"])

    # -- ABCI responses (crash recovery) -------------------------------------

    @staticmethod
    def _abci_responses_key(height: int) -> bytes:
        return b"abciResponsesKey:%d" % height

    def save_abci_responses(self, responses: ABCIResponses) -> None:
        if self.db is None:
            return
        self.db.set_sync(self._abci_responses_key(responses.height), responses.to_json())

    def load_abci_responses(self, height: int) -> ABCIResponses | None:
        if self.db is None:
            return None
        raw = self.db.get(self._abci_responses_key(height))
        return ABCIResponses.from_json(raw) if raw is not None else None

    # -- transition ----------------------------------------------------------

    def set_block_and_validators(
        self,
        header,
        block_parts_header: PartSetHeader,
        abci_responses: ABCIResponses,
    ) -> None:
        """Advance past a block at height H: rotate validator sets and
        apply EndBlock diffs to the set for height H+1 (reference
        `state/state.go:238-265`)."""
        prev_vals = self.validators.copy()
        next_vals = self.validators.copy()
        if abci_responses.end_block_changes:
            next_vals.apply_changes(
                [
                    Validator(
                        address=PubKey(v.pub_key).address,
                        pub_key=PubKey(v.pub_key),
                        voting_power=v.power,
                    )
                    for v in abci_responses.end_block_changes
                ]
            )
            self.last_height_validators_changed = header.height + 1
        next_vals.increment_accum(1)
        self.last_block_height = header.height
        self.last_block_id = BlockID(header.hash(), block_parts_header)
        self.last_block_time = header.time
        self.validators = next_vals
        self.last_validators = prev_vals

    def get_validators(self) -> tuple[ValidatorSet, ValidatorSet]:
        return self.last_validators, self.validators

    def speculate_next(self, header, block_parts_header: PartSetHeader) -> "State":
        """A PROVISIONAL copy advanced past the block at `header` as if
        EndBlock changed nothing — everything `set_block_and_validators`
        derives without the ABCI responses (heights, block id, valset
        rotation + accum). `app_hash` stays the PRE-apply value and the
        copy is never persisted: the pipelined finalize enters H+1's
        NewHeight on this while the real apply is in flight, and the
        join barrier swaps in the applied state (rebuilding the
        valset-derived round state in the rare EndBlock-changes case)
        before anything reads applied fields."""
        nxt = self.copy()
        prev_vals = nxt.validators.copy()
        nxt.validators.increment_accum(1)
        nxt.last_validators = prev_vals
        nxt.last_block_height = header.height
        nxt.last_block_id = BlockID(header.hash(), block_parts_header)
        nxt.last_block_time = header.time
        return nxt


def load_state(db: DB) -> State | None:
    raw = db.get(_STATE_KEY)
    return State.from_json(raw, db=db) if raw is not None else None


def make_genesis_state(db: DB | None, genesis: GenesisDoc) -> State:
    """State at height 0 from a genesis document
    (reference `state/state.go:351-387`)."""
    genesis.validate_and_complete()
    valset = genesis.validator_set()
    last_vals = ValidatorSet([])
    return State(
        chain_id=genesis.chain_id,
        consensus_params=genesis.consensus_params,
        last_block_height=0,
        last_block_id=BlockID.zero(),
        last_block_time=genesis.genesis_time,
        validators=valset,
        last_validators=last_vals,
        last_height_validators_changed=1,
        app_hash=genesis.app_hash,
        db=db,
    )
