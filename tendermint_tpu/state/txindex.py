"""Transaction indexing (reference `state/txindex/`).

`KVTxIndexer` stores each tx's execution result keyed by tx hash in a
KV DB, batched per block (reference `kv/kv.go:17-60`, batch built in
`state/execution.go:279-293`); `NullTxIndexer` is the disabled default.
"""

from __future__ import annotations

import json
from dataclasses import dataclass

from tendermint_tpu.abci.types import Result
from tendermint_tpu.db.kv import DB
from tendermint_tpu.types.tx import tx_hash


@dataclass
class TxResult:
    """Where and how a tx executed (reference `types.TxResult`)."""

    height: int
    index: int
    tx: bytes
    result: Result

    def to_json(self) -> bytes:
        return json.dumps(
            {
                "height": self.height,
                "index": self.index,
                "tx": self.tx.hex(),
                "code": self.result.code,
                "data": self.result.data.hex(),
                "log": self.result.log,
            },
            sort_keys=True,
        ).encode()

    @classmethod
    def from_json(cls, raw: bytes) -> "TxResult":
        d = json.loads(raw.decode())
        return cls(
            height=d["height"],
            index=d["index"],
            tx=bytes.fromhex(d["tx"]),
            result=Result(d["code"], bytes.fromhex(d["data"]), d["log"]),
        )


class TxIndexer:
    def add_batch(self, block, abci_responses) -> None:
        raise NotImplementedError

    def get(self, tx_hash: bytes) -> TxResult | None:
        raise NotImplementedError


class NullTxIndexer(TxIndexer):
    """Indexing disabled (reference `null.TxIndex`)."""

    def add_batch(self, block, abci_responses) -> None:
        pass

    def get(self, tx_hash: bytes) -> TxResult | None:
        return None


class KVTxIndexer(TxIndexer):
    def __init__(self, db: DB) -> None:
        self._db = db

    def add_batch(self, block, abci_responses) -> None:
        for i, tx in enumerate(block.data.txs):
            tr = TxResult(
                height=block.header.height,
                index=i,
                tx=bytes(tx),
                result=abci_responses.deliver_tx[i],
            )
            self._db.set(b"tx:" + tx_hash(bytes(tx)), tr.to_json())

    def get(self, tx_hash: bytes) -> TxResult | None:
        raw = self._db.get(b"tx:" + tx_hash)
        return TxResult.from_json(raw) if raw is not None else None
