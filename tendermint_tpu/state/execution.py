"""Block validation + execution pipeline (reference `state/execution.go`).

`apply_block` is the commit-side hot path (§3.2 tail): validate the block
(including the batched `LastValidators.verify_commit` — the TPU hot
loop), stream txs through the app, save ABCIResponses *before* the app
commit (crash recovery), rotate validator sets, commit the app under the
mempool lock, and persist. Fail points bracket every persistence step
exactly like the reference (`state/execution.go:224-243`).
"""

from __future__ import annotations

from typing import Callable

from tendermint_tpu.abci.client import AppConnConsensus
from tendermint_tpu.abci.types import Result
from tendermint_tpu.state.state import ABCIResponses, State
from tendermint_tpu.types.block import Block
from tendermint_tpu.types.errors import ValidationError
from tendermint_tpu.types.part_set import PartSetHeader
from tendermint_tpu.types.services import MempoolI, NopMempool
from tendermint_tpu.utils.fail import fail_point


class BlockExecutionError(Exception):
    pass


def validate_block(
    state: State,
    block: Block,
    verifier=None,
    commit_preverified: bool = False,
    hasher=None,
) -> None:
    """Reference `validateBlock` (`state/execution.go:181-206`): header
    fields against state, then LastCommit against LastValidators — the
    latter as one signature batch. `commit_preverified=True` skips the
    LastCommit signature pass ONLY (structure still checked): fast-sync
    batch-verifies whole windows of commits in one device call before
    applying, so re-verifying per block would double the work.
    `hasher` routes the data_hash recomputation through a TreeHasher
    (device Merkle for big blocks)."""
    block.validate_basic(hasher)
    if block.header.chain_id != state.chain_id:
        raise ValidationError(
            f"wrong chain_id: got {block.header.chain_id}, want {state.chain_id}"
        )
    _validate_block_evidence(state, block, verifier)
    if block.header.height != state.last_block_height + 1:
        raise ValidationError(
            f"wrong height: got {block.header.height}, want {state.last_block_height + 1}"
        )
    if block.header.last_block_id != state.last_block_id:
        raise ValidationError(
            f"wrong last_block_id: got {block.header.last_block_id}, want {state.last_block_id}"
        )
    if block.header.app_hash != state.app_hash:
        raise ValidationError(
            f"wrong app_hash: got {block.header.app_hash.hex()}, want {state.app_hash.hex()}"
        )
    if block.header.validators_hash != state.validators.hash():
        raise ValidationError("wrong validators_hash")
    if block.header.height == 1:
        if len(block.last_commit.precommits) != 0:
            raise ValidationError("block at height 1 can't have LastCommit signatures")
    else:
        if len(block.last_commit.precommits) != state.last_validators.size():
            raise ValidationError(
                f"wrong LastCommit size: got {len(block.last_commit.precommits)}, "
                f"want {state.last_validators.size()}"
            )
        if not commit_preverified:
            state.last_validators.verify_commit(
                state.chain_id,
                state.last_block_id,
                block.header.height - 1,
                block.last_commit,
                verifier=verifier,
            )


def _validate_block_evidence(state: State, block: Block, verifier) -> None:
    """Evidence policy + proof checks (reference `VerifyEvidence
    state/validation.go`): count under ConsensusParams.max_evidence,
    every proof inside the max-age window, every signature genuine —
    the whole list as ONE batched verify (2 lanes per proof)."""
    from tendermint_tpu.types.evidence import verify_evidence_batch

    evidence = list(block.evidence)
    if not evidence:
        return
    params = state.consensus_params.evidence
    if len(evidence) > params.max_evidence:
        raise ValidationError(
            f"block carries {len(evidence)} evidence, max {params.max_evidence}"
        )
    for ev in evidence:
        if block.header.height - ev.height > params.max_age:
            raise ValidationError(
                f"expired evidence: height {ev.height} at block "
                f"{block.header.height} (max_age {params.max_age})"
            )
        if ev.height > block.header.height:
            raise ValidationError("evidence from the future")
    verify_evidence_batch(
        state.chain_id,
        evidence,
        [state.validators, state.last_validators],
        verifier=verifier,
    )


def exec_block_on_proxy_app(
    app_conn: AppConnConsensus,
    block: Block,
    on_tx_result: Callable[[int, bytes, Result], None] | None = None,
) -> ABCIResponses:
    """BeginBlock, DeliverTx per tx, EndBlock (reference
    `execBlockOnProxyApp state/execution.go:43-118`). Tx results stream
    to `on_tx_result` (the event bus slot); committed evidence rides
    BeginBlock so the app can hold equivocators accountable (reference
    ByzantineValidators in RequestBeginBlock)."""
    app_conn.begin_block_sync(
        block.hash(), block.header, evidence=list(block.evidence)
    )
    responses = ABCIResponses(height=block.header.height)
    for i, tx in enumerate(block.data.txs):
        res = app_conn.deliver_tx_async(bytes(tx))
        responses.deliver_tx.append(res)
        if on_tx_result is not None:
            on_tx_result(i, bytes(tx), res)
    responses.end_block_changes = app_conn.end_block_sync(block.header.height)
    return responses


def apply_block(
    state: State,
    block: Block,
    part_set_header: PartSetHeader,
    app_conn: AppConnConsensus,
    mempool: MempoolI | None = None,
    verifier=None,
    tx_indexer=None,
    on_tx_result: Callable[[int, bytes, Result], None] | None = None,
    commit_preverified: bool = False,
    hasher=None,
) -> State:
    """Validate, execute, persist; returns the advanced state
    (reference `ApplyBlock state/execution.go:216-249`). Mutates and
    returns `state`; callers pass a copy when they need the original."""
    validate_block(
        state,
        block,
        verifier=verifier,
        commit_preverified=commit_preverified,
        hasher=hasher,
    )

    fail_point()  # before any execution effects
    abci_responses = exec_block_on_proxy_app(app_conn, block, on_tx_result)

    fail_point()  # after app execution, before saving responses
    state.save_abci_responses(abci_responses)

    fail_point()  # responses saved, before state advance + app commit
    if tx_indexer is not None:
        tx_indexer.add_batch(block, abci_responses)
    state.set_block_and_validators(block.header, part_set_header, abci_responses)
    if abci_responses.end_block_changes and hasattr(verifier, "prebuild"):
        # valset rotation decided: warm the NEXT set's verify tables in
        # the background so the first commit signed by the new set
        # doesn't stall on a table build (SURVEY §7 hard part 4)
        verifier.prebuild([v.pub_key.data for v in state.validators])

    # app Commit under the mempool lock, then recheck leftover txs
    # (reference CommitStateUpdateMempool `state/execution.go:254-277`)
    mempool = mempool if mempool is not None else NopMempool()
    mempool.lock()
    try:
        res = app_conn.commit_sync()
        if not res.is_ok:
            raise BlockExecutionError(f"app commit failed: {res.log}")
        state.app_hash = res.data
        mempool.update(block.header.height, block.data.txs)
    finally:
        mempool.unlock()

    fail_point()  # app committed, before state save
    state.save()
    return state


def exec_commit_block(
    app_conn: AppConnConsensus, block: Block, verifier=None
) -> bytes:
    """Execute + commit a block against the app WITHOUT touching state —
    used by the handshake replay (reference `ExecCommitBlock
    state/execution.go:297-314`). Returns the new app hash."""
    exec_block_on_proxy_app(app_conn, block)
    res = app_conn.commit_sync()
    if not res.is_ok:
        raise BlockExecutionError(f"app commit failed: {res.log}")
    return res.data
