"""Chain state + block execution (reference `state/`)."""

from tendermint_tpu.state.state import ABCIResponses, State, load_state, make_genesis_state
from tendermint_tpu.state.execution import (
    BlockExecutionError,
    apply_block,
    exec_commit_block,
    validate_block,
)

__all__ = [
    "ABCIResponses",
    "BlockExecutionError",
    "State",
    "apply_block",
    "exec_commit_block",
    "load_state",
    "make_genesis_state",
    "validate_block",
]
