"""Node composition root (reference `node/node.go:75-353`)."""

from tendermint_tpu.node.node import Node

__all__ = ["Node"]
