"""Node: assemble every subsystem from config + genesis and run it
(reference `node/node.go:114-353`).

Composition order mirrors the reference: DBs -> priv validator ->
genesis -> state -> ABCI conns + handshake -> mempool -> reactors
(blockchain, consensus, mempool) -> switch -> p2p listener -> RPC.
"""

from __future__ import annotations

import os
import threading

from tendermint_tpu.abci.client import local_client_creator
from tendermint_tpu.blockchain.reactor import BlockchainReactor
from tendermint_tpu.blockchain.store import BlockStore
from tendermint_tpu.config import Config
from tendermint_tpu.consensus.reactor import ConsensusReactor
from tendermint_tpu.consensus.replay import Handshaker
from tendermint_tpu.consensus.state import ConsensusState
from tendermint_tpu.consensus.ticker import TimeoutTicker
from tendermint_tpu.db.kv import DB, SQLiteDB
from tendermint_tpu.mempool.mempool import Mempool
from tendermint_tpu.mempool.reactor import MempoolReactor
from tendermint_tpu.p2p.node_key import NodeKey
from tendermint_tpu.p2p.peer import NodeInfo
from tendermint_tpu.p2p.switch import Switch
from tendermint_tpu.p2p.tcp import TcpListener, dial
from tendermint_tpu.rpc.core import make_routes
from tendermint_tpu.rpc.server import RPCServer
from tendermint_tpu.state.state import State, load_state, make_genesis_state
from tendermint_tpu.state.txindex import KVTxIndexer
from tendermint_tpu.types import events as ev
from tendermint_tpu.types.genesis import GenesisDoc
from tendermint_tpu.types.priv_validator import PrivValidatorFS


class Node:
    """One full node. `start()` brings up p2p + RPC + (fast-sync then)
    consensus; `stop()` tears everything down."""

    def __init__(
        self,
        config: Config,
        genesis: GenesisDoc | None = None,
        priv_validator=None,
        app=None,
        client_creator=None,
        db_provider=None,
        verifier=None,
        node_key=None,
        hasher=None,
    ) -> None:
        self.config = config
        cfg = config
        if verifier is None:
            # resolve the process default ONCE (device tables on TPU,
            # host library elsewhere) so warming and every component
            # share the same instance — the CLI path passes None
            from tendermint_tpu.services.verifier import default_verifier

            verifier = default_verifier()
        from tendermint_tpu.utils.log import setup_logging

        setup_logging(cfg.base.log_level)

        def _db(name: str) -> DB:
            if db_provider is not None:
                return db_provider(name)
            return SQLiteDB(cfg.db_path(name))

        # genesis + priv validator (reference LoadOrGen + genesisDocProvider)
        self.genesis = (
            genesis
            if genesis is not None
            else GenesisDoc.from_file(cfg.genesis_path())
        )
        self.priv_validator = (
            priv_validator
            if priv_validator is not None
            else PrivValidatorFS.load_or_gen(cfg.priv_validator_path())
        )
        # Dedicated transport identity, NOT the validator signing key:
        # keeps consensus signatures and p2p handshake signatures under
        # different keys and lets remote-signer topologies (validator key
        # never on this host) still run encrypted p2p.
        self.node_key = (
            node_key
            if node_key is not None
            else NodeKey.load_or_gen(cfg.node_key_path())
        )
        self.node_id = self.node_key.node_id

        # span-timeline persistence: replay the pre-restart window into
        # the tracer (post-mortems survive the process), then sink every
        # new span to the bounded JSONL ring under the data dir
        from tendermint_tpu.telemetry import TRACER
        from tendermint_tpu.telemetry.spanlog import persist_spans

        self._span_log = persist_spans(
            TRACER, os.path.join(cfg.home, cfg.base.db_dir, "spans.jsonl")
        )
        # flight recorder: black-box dumps land next to the span log;
        # SIGUSR2 snapshots a live node (no-op off the main thread)
        from tendermint_tpu.telemetry.flightrec import FLIGHT, install_signal_dump

        FLIGHT.set_node_id(self.node_id)
        FLIGHT.set_dump_dir(os.path.join(cfg.home, cfg.base.db_dir))
        install_signal_dump()
        # finality observatory: one persisted record per committed
        # height (phases, critical path, laggard) under the data dir —
        # `/health`'s SLO window and tools/finality_report.py read it
        from tendermint_tpu.telemetry.heightlog import HeightLedger

        self.height_ledger = HeightLedger(
            path=os.path.join(cfg.home, cfg.base.db_dir, "heights.jsonl"),
            node_id=self.node_id,
        )
        # device observatory: the process-wide launch ledger persists
        # under this node's data dir (one record per device launch —
        # `dump_telemetry?launches=N`, tools/device_report.py)
        from tendermint_tpu.telemetry.launchlog import LAUNCHLOG

        LAUNCHLOG.attach(
            os.path.join(cfg.home, cfg.base.db_dir, "launches.jsonl"),
            node_id=self.node_id,
        )

        # state + stores
        self.state_db = _db("state")
        st = load_state(self.state_db)
        if st is None:
            st = make_genesis_state(self.state_db, self.genesis)
            st.save()
        self.state: State = st
        self.block_store = BlockStore(_db("blockstore"))

        # app conns + crash-recovery handshake (reference NewAppConns +
        # Handshaker). `client_creator` selects the process boundary:
        # in-proc (default) or `abci.socket.socket_client_creator` for
        # an app/sidecar in its own process (reference proxy/client.go)
        if client_creator is None:
            if app is None:
                from tendermint_tpu.abci.apps import KVStoreApp

                app = KVStoreApp()
            client_creator = local_client_creator(app)
        self.app = app
        self.app_conns = client_creator()
        Handshaker(self.state, self.block_store, verifier=verifier).handshake(
            self.app_conns
        )

        # mempool + tx index. The shared verifier makes CheckTx windows
        # the verify spine's fifth consumer (consumer="mempool"):
        # admission signature batches coalesce with consensus/fastsync/
        # statesync/rpc launches and share the VerifiedSigCache.
        self.mempool = Mempool(
            self.app_conns.mempool,
            height=self.state.last_block_height,
            cache_size=cfg.mempool.cache_size,
            wal_dir=cfg.mempool_wal_path() if cfg.mempool.wal_dir else None,
            recheck=cfg.mempool.recheck,
            node_id=self.node_id,
            lanes=cfg.mempool.lanes or None,
            verifier=verifier,
            ingress_batch=cfg.mempool.ingress_batch,
            signed_txs=cfg.mempool.signed_txs,
        )
        # re-validate txs that were in flight before a crash; the WAL is
        # compacted to the survivors so it cannot grow across restarts
        self.mempool.replay_wal()
        self.tx_indexer = KVTxIndexer(_db("txindex"))
        self.event_switch = ev.EventSwitch()

        # replica mode (tendermint_tpu/lightclient/): never join
        # consensus, follow the chain via fast-sync tail + FullCommit
        # subscription, serve light-client reads. The validator key is
        # deliberately unused — a replica must not be able to sign.
        self.is_replica = bool(cfg.replica.enable)
        if self.is_replica:
            self.priv_validator = None

        # fast-sync only when peers could be ahead AND we are not the
        # solo validator (reference node.go:174-205)
        solo = (
            self.priv_validator is not None
            and len(self.state.validators) == 1
            and self.state.validators.validators[0].address
            == self.priv_validator.address
        )
        fast_sync = cfg.base.fast_sync and (self.is_replica or not solo)
        # state-sync bootstrap: only a FRESH node (nothing committed
        # locally) may skip history, and it needs fast-sync for the tail
        state_sync = (
            cfg.statesync.enable and fast_sync and self.state.last_block_height == 0
        )

        # Device tree hasher for proposal data_hash/part sets on TPU
        # (reference SimpleHash hot spots `types/tx.go:33-46`,
        # `types/part_set.go:95-122`); host merkle elsewhere.
        if hasher is None:
            from tendermint_tpu.services.hasher import auto_hasher

            hasher = auto_hasher()
        self.hasher = hasher
        # background-load the table-build executable so the first real
        # valset build doesn't stall on the per-process program upload
        if hasattr(verifier, "warm_kernels"):
            verifier.warm_kernels()

        # evidence pool: WAL-backed so pending proofs survive a crash;
        # consensus wires the validator-set/height resolvers in its ctor
        from tendermint_tpu.evidence import EvidencePool, EvidenceReactor

        self.evidence_pool = EvidencePool(
            wal_path=cfg.evidence_wal_path(),
            params=self.state.consensus_params.evidence,
            verifier=verifier,
            chain_id=self.genesis.chain_id,
        )
        if self.is_replica:
            # replicas run NO consensus machinery at all: no round
            # state, no WAL, no vote signing — the follow-mode
            # fast-sync below is the only way their state advances
            self.consensus = None
            self.consensus_reactor = None
            # the evidence pool still needs its height clock + valset
            # resolver (forged-FullCommit evidence is admitted here);
            # best-effort like consensus's resolver — an unknown height
            # falls back to the live set, it never raises into the
            # gossip path (that would debit an honest relaying peer)
            self.evidence_pool.best_height_fn = lambda: self.block_store.height

            def _replica_evidence_valset(h: int):
                from tendermint_tpu.types.errors import ValidationError

                try:
                    return self.current_state.load_validators(h)
                except ValidationError:
                    return self.current_state.validators

            self.evidence_pool.val_set_fn = _replica_evidence_valset
        else:
            self.consensus = ConsensusState(
                config=cfg.consensus,
                state=self.state,
                app_conn=self.app_conns.consensus,
                block_store=self.block_store,
                mempool=self.mempool,
                priv_validator=self.priv_validator,
                event_switch=self.event_switch,
                wal_path=cfg.wal_path(),
                ticker=TimeoutTicker(),
                verifier=verifier,
                tx_indexer=self.tx_indexer,
                hasher=hasher,
                evidence_pool=self.evidence_pool,
                heightlog=self.height_ledger,
            )
            self.consensus_reactor = ConsensusReactor(
                self.consensus, fast_sync=fast_sync
            )
        self.evidence_reactor = EvidenceReactor(self.evidence_pool)
        self.blockchain_reactor = BlockchainReactor(
            state=self.state,
            store=self.block_store,
            app_conn=self.app_conns.consensus,
            fast_sync=fast_sync,
            on_caught_up=self._on_caught_up,
            verifier=verifier,
            tx_indexer=self.tx_indexer,
            hasher=hasher,
            deferred=state_sync,
            follow=self.is_replica,
        )
        self.mempool_reactor = MempoolReactor(
            self.mempool, broadcast=cfg.mempool.broadcast
        )

        # state sync: every node serves its snapshot store on the 0x60
        # channel; `state_sync` additionally runs the bootstrap routine
        # (discover -> anchor -> chunks -> restore -> fast-sync tail)
        from tendermint_tpu.statesync.reactor import StateSyncReactor
        from tendermint_tpu.statesync.snapshot import SnapshotStore
        from tendermint_tpu.statesync.trust import TrustAnchor, TrustOptions

        self.snapshot_store = SnapshotStore(
            _db("snapshots"),
            hasher=hasher,
            chunk_size=cfg.statesync.chunk_size,
            keep_recent=cfg.statesync.snapshot_keep_recent,
        )
        trust_anchor = TrustAnchor(
            chain_id=self.genesis.chain_id,
            base_validators=self.genesis.validator_set(),
            options=TrustOptions.from_config(cfg.statesync),
            verifier=verifier,
        )
        self.statesync_reactor = StateSyncReactor(
            snapshot_store=self.snapshot_store,
            block_store=self.block_store,
            state=self.state,
            sync=state_sync,
            trust_anchor=trust_anchor,
            state_db=self.state_db,
            app_restore_fn=getattr(self.app, "restore_state", None),
            app_snapshot_fn=getattr(self.app, "snapshot_state", None),
            on_synced=self._on_state_synced,
            hasher=hasher,
            snapshot_interval=cfg.statesync.snapshot_interval,
            retain_blocks=cfg.statesync.retain_blocks,
            discovery_time_s=cfg.statesync.discovery_time_s,
            chunk_request_timeout_s=cfg.statesync.chunk_request_timeout_s,
            chunk_inflight_per_peer=cfg.statesync.chunk_inflight_per_peer,
            giveup_time_s=cfg.statesync.giveup_time_s,
        )
        if cfg.statesync.snapshot_interval > 0:
            # runs on the consensus thread right after each commit, so
            # consensus state and app state snapshot at the same height
            self.event_switch.add_listener(
                "statesync", ev.EVENT_NEW_BLOCK, lambda _data: self._maybe_snapshot()
            )

        # light-client serving layer (ROADMAP item 1): every node serves
        # certified FullCommits on the 0x68 channel; replicas
        # additionally subscribe to the push stream and certify it
        # through a bisecting light-client pin before caching/serving —
        # the cache is positives-only, so a forged FullCommit can never
        # pin trust.
        from tendermint_tpu.db.fullcommit import FullCommitStore
        from tendermint_tpu.lightclient import (
            BisectingCertifier,
            CertifiedCommitCache,
            LightClientReactor,
            PeerProvider,
        )

        self.fullcommit_store = FullCommitStore(_db("fullcommits"))
        self.fullcommit_cache = CertifiedCommitCache(
            cfg.replica.fullcommit_cache_size, store=self.fullcommit_store
        )
        self.lightclient_reactor = LightClientReactor(
            chain_id=self.genesis.chain_id,
            block_store=self.block_store,
            state=self.state,
            cache=self.fullcommit_cache,
            subscribe=self.is_replica,
            evidence_pool=self.evidence_pool,
            verifier=verifier,
        )
        self.lightclient_certifier = None
        if self.is_replica:
            # subjective init: the statesync trust pin when configured,
            # else the genesis valset (fine for young chains — same
            # fallback the statesync TrustAnchor documents)
            self.lightclient_certifier = BisectingCertifier(
                self.genesis.chain_id,
                validators=self.genesis.validator_set(),
                height=0,
                trusted=self.fullcommit_cache,
                source=PeerProvider(self.lightclient_reactor),
                verifier=verifier,
                trust_period_ns=int(cfg.statesync.trust_period_s * 1e9),
            )
            self.lightclient_reactor.certifier = self.lightclient_certifier
        if not self.is_replica:
            # push freshly committed FullCommits to subscribed replicas
            # (runs on the consensus thread right after the commit is
            # stored; no-op without subscribers)
            self.event_switch.add_listener(
                "lightclient",
                ev.EVENT_NEW_BLOCK,
                lambda data: self._announce_fullcommit(data),
            )

        self.switch = Switch(
            NodeInfo(
                node_id=self.node_id,
                moniker=cfg.base.moniker,
                chain_id=self.genesis.chain_id,
            )
        )
        self.switch.send_rate = cfg.p2p.send_rate
        self.switch.recv_rate = cfg.p2p.recv_rate
        self.switch.ping_interval = cfg.p2p.ping_interval_s
        self.switch.pong_timeout = cfg.p2p.pong_timeout_s
        if cfg.p2p.filter_peers:
            # ABCI-driven peer admission (reference node/node.go:259-281):
            # the app vets each peer via Query before registration. The
            # reference filters on addr and pubkey; node ids here are the
            # transport identity (address of the node key), so the id
            # filter is the pubkey filter's analog.
            def _abci_peer_filter(remote_info, remote_addr):
                # addr filter uses the SOCKET's remote address (the peer
                # cannot choose it); self-reported listen_addr would let
                # a banned host dodge the blocklist
                paths = [f"/p2p/filter/id/{remote_info.node_id}"]
                if remote_addr:
                    paths.insert(0, f"/p2p/filter/addr/{remote_addr}")
                for path in paths:
                    res = self.app_conns.query.query_sync(path, b"")
                    if not res.is_ok:
                        return f"app rejected peer ({path}): code {res.code}"
                return None

            self.switch.peer_filter = _abci_peer_filter
        self.switch.add_reactor("blockchain", self.blockchain_reactor)
        if self.consensus_reactor is not None:
            self.switch.add_reactor("consensus", self.consensus_reactor)
        self.switch.add_reactor("mempool", self.mempool_reactor)
        self.switch.add_reactor("evidence", self.evidence_reactor)
        self.switch.add_reactor("statesync", self.statesync_reactor)
        if cfg.replica.serve_lightclient or self.is_replica:
            self.switch.add_reactor("lightclient", self.lightclient_reactor)
        self.pex_reactor = None
        if cfg.p2p.pex:
            from tendermint_tpu.p2p.addrbook import AddrBook
            from tendermint_tpu.p2p.pex import PEXReactor

            self.addr_book = AddrBook(os.path.join(cfg.home, "addrbook.json"))
            self.pex_reactor = PEXReactor(
                self.addr_book,
                max_peers=cfg.p2p.max_num_peers,
                node_key=self._node_key,
                ensure_interval_s=cfg.p2p.pex_ensure_interval_s,
            )
            self.switch.add_reactor("pex", self.pex_reactor)

        self.listener: TcpListener | None = None
        self.rpc: RPCServer | None = None
        self.grpc = None

        # persistent-peer reconnection (reference `reconnectToPeer
        # p2p/switch.go:290-320`: bounded retries with backoff). Seeds-only
        # topologies otherwise never heal a dropped link.
        self._persistent_addrs: set[str] = {
            a.strip()
            for a in cfg.p2p.persistent_peers.split(",")
            if a.strip()
        }
        self._persistent_lock = threading.Lock()
        self._persistent_dialing: set[str] = set()
        self._peer_addr: dict[str, str] = {}  # node_id -> dialed persistent addr
        self._p2p_running = False
        if self._persistent_addrs:
            self.switch.on_peer_removed = self._on_peer_removed

    # -- lifecycle ---------------------------------------------------------

    def _on_caught_up(self, state) -> None:
        """Fast-sync finished: start consensus (reference
        `SwitchToConsensus`). Replicas never get here — their follow-
        mode fast-sync has no caught-up exit."""
        if self.consensus_reactor is not None:
            self.consensus_reactor.switch_to_consensus(state)

    def _announce_fullcommit(self, data) -> None:
        """EVENT_NEW_BLOCK listener: push the just-committed height's
        FullCommit to 0x68 subscribers (cheap without subscribers)."""
        try:
            height = (
                data.block.header.height
                if data is not None and getattr(data, "block", None) is not None
                else self.block_store.height
            )
            self.lightclient_reactor.announce_height(height)
        except Exception:
            import logging

            logging.getLogger(__name__).exception("fullcommit announce failed")

    def _on_state_synced(self, state) -> None:
        """State sync ended: with a restored state, adopt it and
        fast-sync only the tail; with None (gave up), fall back to plain
        fast-sync from the current (genesis) state."""
        if state is not None:
            self.state = state
            self.statesync_reactor.state = state
        self.blockchain_reactor.begin_fast_sync(state)

    def _maybe_snapshot(self) -> None:
        try:
            self.statesync_reactor.maybe_take_snapshot(
                self.current_state, app=self.app
            )
        except Exception:
            import logging

            logging.getLogger(__name__).exception("snapshot failed")

    @property
    def _node_key(self):
        """Long-lived node identity key for SecretConnection handshakes —
        the dedicated node_key.json key (node_id is derived from it, so
        the encrypted transport's identity check pins peers to their
        ids), never the validator signing key."""
        if not self.config.p2p.secret_connections:
            return None
        return self.node_key.priv_key

    def start(self) -> None:
        if self.config.p2p.laddr:
            # bind BEFORE reactors start so the advertised listen_addr
            # (NodeInfo/PEX) carries the real port — but don't ACCEPT
            # until the reactors are running (an early inbound peer
            # would hit pre-start reactors)
            self.listener = TcpListener(
                self.switch,
                self.config.p2p.laddr,
                priv_key=self._node_key,
                start=False,
            )
            if self.config.p2p.external_address:
                self.switch.listen_addr = self.config.p2p.external_address
            else:
                host = self.config.p2p.laddr.split("://", 1)[-1].rpartition(":")[0]
                # single-host fallback only — multi-machine deployments
                # must set p2p.external_address or peers learn loopback
                adv_host = "127.0.0.1" if host in ("0.0.0.0", "") else host
                self.switch.listen_addr = f"{adv_host}:{self.listener.port}"
        # live-view gauges (peer count, p2p rates, mempool depth) read
        # through this node at scrape time (`GET /metrics`)
        from tendermint_tpu.telemetry.metrics import bind_node_gauges

        bind_node_gauges(self)
        # contention observatory: continuous profiling when the env
        # knob asks for it (TENDERMINT_TPU_PROFILE_HZ > 0); the
        # process-global sampler outlives any one node, so stop() never
        # tears it down
        from tendermint_tpu.telemetry.profiler import maybe_start_env

        maybe_start_env()
        self.switch.start()  # reactors start; consensus starts unless fast-syncing
        if self.listener is not None:
            self.listener.start_accepting()
        if self.config.rpc.laddr:
            self.rpc = RPCServer(
                make_routes(self),
                self.config.rpc.laddr,
                event_switch=self.event_switch,
            )
            self.rpc.start()
        if self.config.rpc.grpc_laddr:
            from tendermint_tpu.rpc.grpc_api import GRPCBroadcastServer

            self.grpc = GRPCBroadcastServer(self, self.config.rpc.grpc_laddr)
            self.grpc.start()
        for seed in filter(None, self.config.p2p.seeds.split(",")):
            self.dial_seed(seed.strip())
        self._p2p_running = True
        for addr in self._persistent_addrs:
            self._spawn_persistent_dial(addr)

    def dial_seed(self, addr: str) -> None:
        """Dial one seed address; failures are logged, not raised (the
        reference dials seeds with per-seed error handling). Also the
        dial_seeds RPC's worker."""
        try:
            dial(self.switch, addr, priv_key=self._node_key)
        except Exception:
            import logging

            logging.getLogger(__name__).warning("dial %s failed", addr)

    # -- persistent peers ---------------------------------------------------

    @staticmethod
    def _split_persistent_addr(addr: str) -> tuple[str | None, str]:
        """`id@host:port` -> (expected node_id, dialable addr); plain
        `host:port` -> (None, addr). The id form is the reference's
        persistent_peers syntax and pins reconnects to a transport
        identity instead of a (possibly NAT-shared) host."""
        head, sep, rest = addr.partition("@")
        if sep and head and "://" not in head and ":" not in head:
            return head, rest
        return None, addr

    def _on_peer_removed(self, peer, reason) -> None:
        """Heal dropped persistent links (reference `p2p/switch.go:290-320`)."""
        addr = self._peer_addr.pop(peer.id, None)
        if addr is None:
            for cand in self._persistent_addrs:
                expected_id, dial_addr = self._split_persistent_addr(cand)
                if expected_id == peer.id or (
                    expected_id is None
                    and peer.node_info.listen_addr == dial_addr
                ):
                    # inbound persistent peer (they dialed us): ours to heal
                    addr = cand
                    break
        if addr is None or not self._p2p_running:
            return
        self._spawn_persistent_dial(addr)

    def _adopt_inbound_persistent(self, addr: str) -> None:
        """Map an already-connected peer to its persistent address so a
        later drop gets redialed (they-dialed-first / race cases).
        Match precedence: pinned node_id (`id@host:port` form), then
        advertised listen_addr, then socket host — but the bare host
        match only when it is unambiguous (exactly one unmapped
        candidate), since several NAT'd peers can share one IP and a
        wrong mapping makes a later drop redial the wrong address."""
        expected_id, dial_addr = self._split_persistent_addr(addr)
        candidates = [p for p in self.switch.peers() if p.id not in self._peer_addr]
        if expected_id is not None:
            for p in candidates:
                if p.id == expected_id:
                    self._peer_addr[p.id] = addr
                    return
            return  # pinned id not connected: nothing safe to adopt
        for p in candidates:
            if p.node_info.listen_addr == dial_addr:
                self._peer_addr[p.id] = addr
                return
        host = dial_addr.split("://")[-1].rsplit(":", 1)[0]
        by_host = [
            p
            for p in candidates
            if p.remote_addr and p.remote_addr.rsplit(":", 1)[0] == host
        ]
        if len(by_host) == 1:
            self._peer_addr[by_host[0].id] = addr

    def _spawn_persistent_dial(self, addr: str) -> None:
        with self._persistent_lock:
            if addr in self._persistent_dialing:
                return  # a redial loop for this address is already running
            self._persistent_dialing.add(addr)
        threading.Thread(
            target=self._persistent_dial_loop,
            args=(addr,),
            name=f"persistent-dial-{addr}",
            daemon=True,
        ).start()

    def _persistent_dial_loop(self, addr: str) -> None:
        import logging
        import time

        from tendermint_tpu.utils.backoff import backoff_delay

        cfg = self.config.p2p
        log = logging.getLogger(__name__)
        expected_id, dial_addr = self._split_persistent_addr(addr)
        try:
            for attempt in range(max(1, cfg.reconnect_max_attempts)):
                if not self._p2p_running:
                    return
                try:
                    peer = dial(self.switch, dial_addr, priv_key=self._node_key)
                    if expected_id is not None and peer.id != expected_id:
                        self.switch.stop_peer(peer, "persistent peer id mismatch")
                        raise ConnectionError(
                            f"dialed {dial_addr}: got id {peer.id[:12]}, "
                            f"want {expected_id[:12]}"
                        )
                    self._peer_addr[peer.id] = addr
                    # the peer may have died between registration and the
                    # mapping write above — then _on_peer_removed already
                    # ran, found no mapping, and nobody would redial
                    if self.switch._peers.get(peer.id) is not peer:
                        self._peer_addr.pop(peer.id, None)
                        raise ConnectionError("peer dropped during dial")
                    return
                except Exception as e:
                    if "duplicate peer" in str(e):
                        # already connected (e.g. they dialed us first):
                        # adopt the live peer so a later drop still heals
                        self._adopt_inbound_persistent(addr)
                        return
                    # capped exponential backoff with jitter (reference
                    # reconnect backoff p2p/switch.go:290-320)
                    time.sleep(
                        backoff_delay(attempt, cfg.reconnect_base_backoff_s)
                    )
            log.warning(
                "giving up on persistent peer %s after %d attempts",
                addr,
                cfg.reconnect_max_attempts,
            )
        finally:
            with self._persistent_lock:
                self._persistent_dialing.discard(addr)

    def stop(self) -> None:
        self._p2p_running = False  # stop persistent-peer redial loops
        if self.grpc is not None:
            self.grpc.stop()
        if self.rpc is not None:
            self.rpc.stop()
        if self.listener is not None:
            self.listener.stop()
        self.switch.stop()
        self.mempool.close()
        self.evidence_pool.close()
        self.app_conns.close()
        if getattr(self, "_span_log", None) is not None:
            from tendermint_tpu.telemetry import TRACER

            TRACER.clear_sink(self._span_log.append)
            self._span_log.close()
        if getattr(self, "height_ledger", None) is not None:
            self.height_ledger.close()

    def health(self) -> dict:
        """The `/health` snapshot (telemetry/health.py): readiness +
        degradation checks + the rolling finality SLO, all node-local."""
        from tendermint_tpu.telemetry.health import build_health

        return build_health(self)

    # -- convenience -------------------------------------------------------

    @property
    def current_state(self) -> State:
        """The live chain state. Consensus REBINDS its state on every
        commit (finalize copies then adopts), so `self.state` only
        tracks fast-sync's in-place mutations — RPC must read through
        here or it serves startup-time state forever."""
        if self.consensus is not None and self.consensus.state is not None:
            return self.consensus.state
        return self.state

    @property
    def rpc_port(self) -> int:
        return self.rpc.port if self.rpc else 0

    @property
    def p2p_port(self) -> int:
        return self.listener.port if self.listener else 0

    def wait_height(self, height: int, timeout: float = 60.0) -> None:
        import time

        deadline = time.time() + timeout
        while time.time() < deadline:
            if self.block_store.height >= height:
                return
            time.sleep(0.05)
        raise TimeoutError(f"node did not reach height {height}")
