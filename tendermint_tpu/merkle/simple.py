"""SimpleMerkle tree with inclusion proofs (host reference implementation).

DELIBERATE DEVIATIONS from the reference's SimpleTree
(`docs/specification/merkle.rst:52-90`), both chosen TPU-first:

* **Split rule.** The reference splits leaves in half ("both sides of
  the tree the same size, but the left side may be one greater" — its
  6-leaf diagram splits 3/3). We split at the **largest power of two
  strictly less than n** (the RFC 6962 / Certificate Transparency
  rule). The two rules produce different shapes from 5 leaves up
  (reference 5 -> 3/2; ours 5 -> 4/2). Ours is exactly equivalent to
  bottom-up adjacent pairing with promotion of an unpaired trailing
  node, which is what the device kernel vectorizes as log2(N) batched
  levels; the reference's ceil-split tree has no such level-parallel
  form.
* **Domain separation.** The reference hashes raw concatenation of
  wire-encoded children; we prefix leaf = H(0x00||data) and inner =
  H(0x01||L||R) (RFC 6962 style), closing leaf/inner second-preimage
  attacks.

Roots are therefore NOT bit-compatible with reference roots (the
domain separation alone guarantees that); within this framework, host
(`merkle.simple`) and device (`ops/merkle_kernel.py`) trees implement
the identical rule and are bit-equal — asserted by tests and by
`bench.py`'s device-vs-host root check.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from tendermint_tpu.crypto.hashing import DEFAULT_ALGO, tmhash

LEAF_PREFIX = b"\x00"
INNER_PREFIX = b"\x01"


def leaf_hash(data: bytes, algo: str = DEFAULT_ALGO) -> bytes:
    return tmhash(LEAF_PREFIX + data, algo)


def inner_hash(left: bytes, right: bytes, algo: str = DEFAULT_ALGO) -> bytes:
    return tmhash(INNER_PREFIX + left + right, algo)


def _split_point(n: int) -> int:
    """Largest power of two strictly less than n (RFC 6962 split rule —
    a deliberate deviation from the reference's ceil(n/2) split; see the
    module docstring)."""
    if n < 2:
        raise ValueError("split requires n >= 2")
    k = 1
    while k * 2 < n:
        k *= 2
    return k


def simple_hash_from_hashes(hashes: list[bytes], algo: str = DEFAULT_ALGO) -> bytes:
    """Root from precomputed *leaf* hashes (already leaf-prefixed)."""
    n = len(hashes)
    if n == 0:
        return b""
    if n == 1:
        return hashes[0]
    k = _split_point(n)
    left = simple_hash_from_hashes(hashes[:k], algo)
    right = simple_hash_from_hashes(hashes[k:], algo)
    return inner_hash(left, right, algo)


def simple_hash_from_byte_slices(items: list[bytes], algo: str = DEFAULT_ALGO) -> bytes:
    """Root over raw byte slices (each hashed as a domain-separated leaf)."""
    return simple_hash_from_hashes([leaf_hash(x, algo) for x in items], algo)


@dataclass
class SimpleProof:
    """Inclusion proof: aunt hashes bottom-up (reference: merkle SimpleProof)."""

    index: int
    total: int
    leaf: bytes  # leaf hash (prefixed)
    aunts: list[bytes] = field(default_factory=list)

    def root(self, algo: str = DEFAULT_ALGO) -> bytes:
        return _root_from_aunts(self.index, self.total, self.leaf, self.aunts, algo)

    def encode(self) -> bytes:
        from tendermint_tpu.codec import Writer

        w = Writer().uvarint(self.index).uvarint(self.total).bytes(self.leaf)
        w.uvarint(len(self.aunts))
        for a in self.aunts:
            w.bytes(a)
        return w.build()

    @classmethod
    def decode(cls, data: bytes) -> "SimpleProof":
        from tendermint_tpu.codec import Reader

        r = Reader(data)
        index, total, leaf = r.uvarint(), r.uvarint(), r.bytes()
        aunts = [r.bytes() for _ in range(r.uvarint())]
        return cls(index=index, total=total, leaf=leaf, aunts=aunts)


def _root_from_aunts(
    index: int, total: int, leaf: bytes, aunts: list[bytes], algo: str
) -> bytes:
    if total == 0 or not (0 <= index < total):
        raise ValueError("invalid proof shape")
    if total == 1:
        if aunts:
            raise ValueError("unexpected aunts for single leaf")
        return leaf
    k = _split_point(total)
    if not aunts:
        raise ValueError("missing aunts")
    if index < k:
        left = _root_from_aunts(index, k, leaf, aunts[:-1], algo)
        return inner_hash(left, aunts[-1], algo)
    right = _root_from_aunts(index - k, total - k, leaf, aunts[:-1], algo)
    return inner_hash(aunts[-1], right, algo)


def _proofs(hashes: list[bytes], algo: str) -> tuple[bytes, list[list[bytes]]]:
    n = len(hashes)
    if n == 1:
        return hashes[0], [[]]
    k = _split_point(n)
    lroot, lproofs = _proofs(hashes[:k], algo)
    rroot, rproofs = _proofs(hashes[k:], algo)
    root = inner_hash(lroot, rroot, algo)
    return root, [p + [rroot] for p in lproofs] + [p + [lroot] for p in rproofs]


def simple_proofs_from_byte_slices(
    items: list[bytes], algo: str = DEFAULT_ALGO
) -> tuple[bytes, list[SimpleProof]]:
    """Root + per-item inclusion proofs (reference: SimpleProofsFromHashers)."""
    if not items:
        return b"", []
    leaves = [leaf_hash(x, algo) for x in items]
    root, aunt_lists = _proofs(leaves, algo)
    total = len(items)
    proofs = [
        SimpleProof(index=i, total=total, leaf=leaves[i], aunts=aunts)
        for i, aunts in enumerate(aunt_lists)
    ]
    return root, proofs


def simple_hash_from_map(kvs: dict[str, bytes], algo: str = DEFAULT_ALGO) -> bytes:
    """Root over a string->bytes map, keys sorted (reference: SimpleHashFromMap,
    used for the block header hash at `types/block.go:173-188`)."""
    from tendermint_tpu.codec import encode_bytes, encode_string

    items = [
        encode_string(k) + encode_bytes(v) for k, v in sorted(kvs.items())
    ]
    return simple_hash_from_byte_slices(items, algo)


def verify_proof(
    root: bytes, item: bytes, proof: SimpleProof, algo: str = DEFAULT_ALGO
) -> bool:
    """Check an item's inclusion proof against a known root
    (reference: `types/part_set.go:188-214` AddPart proof check)."""
    if proof.leaf != leaf_hash(item, algo):
        return False
    try:
        return proof.root(algo) == root
    except ValueError:
        return False
