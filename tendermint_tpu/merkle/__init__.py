"""Merkle trees (host reference implementation + the TreeHasher seam).

Role of `tmlibs/merkle` in the reference (`SimpleHashFromHashes`,
`SimpleHashFromBinaries`, proofs — spec at `docs/specification/merkle.rst:52-90`;
call sites `types/block.go:177`, `types/validator_set.go:153`, `types/tx.go:33-46`,
`types/part_set.go:111,204`). The batched device tree hasher lives in
`tendermint_tpu.ops.merkle_kernel` behind the `TreeHasher` interface.
"""

from tendermint_tpu.merkle.simple import (
    SimpleProof,
    simple_hash_from_byte_slices,
    simple_hash_from_hashes,
    simple_hash_from_map,
    simple_proofs_from_byte_slices,
    verify_proof,
)

__all__ = [
    "simple_hash_from_hashes",
    "simple_hash_from_byte_slices",
    "simple_hash_from_map",
    "simple_proofs_from_byte_slices",
    "SimpleProof",
    "verify_proof",
]
