"""Service interfaces decoupling consensus from mempool/blockstore
implementations (reference `types/services.go:21-33,67-71`)."""

from __future__ import annotations

from typing import Callable, Protocol

from tendermint_tpu.types.tx import Tx, Txs


class MempoolI(Protocol):
    """What consensus needs from a mempool (reference `types.Mempool`)."""

    def lock(self) -> None: ...
    def unlock(self) -> None: ...
    def size(self) -> int: ...
    def check_tx(self, tx: Tx, cb: Callable | None = None) -> None: ...
    def reap(self, max_txs: int) -> Txs: ...
    def update(self, height: int, txs: Txs) -> None: ...
    def flush(self) -> None: ...
    def tx_available(self) -> bool: ...
    def enable_txs_available(self) -> None: ...


class NopMempool:
    """No-op mempool (reference `types.MockMempool`) for replay/tests."""

    def lock(self) -> None:
        pass

    def unlock(self) -> None:
        pass

    def size(self) -> int:
        return 0

    def check_tx(self, tx: Tx, cb: Callable | None = None) -> None:
        pass

    def check_tx_async(self, tx: Tx, cb: Callable | None = None) -> None:
        pass

    def reap(self, max_txs: int) -> Txs:
        return Txs()

    def update(self, height: int, txs: Txs) -> None:
        pass

    def flush(self) -> None:
        pass

    def tx_available(self) -> bool:
        return False

    def enable_txs_available(self) -> None:
        pass


class BlockStoreI(Protocol):
    """What consensus/state need from block storage (reference `types.BlockStoreRPC`)."""

    @property
    def height(self) -> int: ...
    def load_block(self, height: int): ...
    def load_block_meta(self, height: int): ...
    def load_block_part(self, height: int, index: int): ...
    def load_block_commit(self, height: int): ...
    def load_seen_commit(self, height: int): ...
    def save_block(self, block, part_set, seen_commit) -> None: ...
