"""Vote: a signed prevote/precommit (reference `types/vote.go`).

Sign-bytes are canonical JSON wrapped with the chain ID
(reference `types/canonical_json.go:50-53`, `types/vote.go:60-65`); the
validator's identity is NOT in the sign-bytes — identity binds via the
signature key, which is what makes commit signatures batchable as
(pubkey, message, signature) triples with a shared message per block.
"""

from __future__ import annotations

from dataclasses import dataclass, replace

from tendermint_tpu.codec import Reader, Writer, canonical_dumps
from tendermint_tpu.types.block_id import BlockID
from tendermint_tpu.types.errors import ValidationError

VOTE_TYPE_PREVOTE = 1
VOTE_TYPE_PRECOMMIT = 2


def is_vote_type_valid(t: int) -> bool:
    return t in (VOTE_TYPE_PREVOTE, VOTE_TYPE_PRECOMMIT)


@dataclass(frozen=True)
class Vote:
    validator_address: bytes
    validator_index: int
    height: int
    round: int
    timestamp: int  # ns since epoch
    type: int
    block_id: BlockID
    signature: bytes = b""

    def sign_bytes(self, chain_id: str) -> bytes:
        return canonical_dumps(
            {
                "chain_id": chain_id,
                "vote": {
                    "block_id": self.block_id.to_dict(),
                    "height": self.height,
                    "round": self.round,
                    "timestamp": self.timestamp,
                    "type": self.type,
                },
            }
        )

    def with_signature(self, sig: bytes) -> "Vote":
        return replace(self, signature=sig)

    def validate_basic(self) -> None:
        if not is_vote_type_valid(self.type):
            raise ValidationError(f"invalid vote type {self.type}")
        if self.height < 1:
            raise ValidationError("vote height must be >= 1")
        if self.round < 0:
            raise ValidationError("negative vote round")
        if self.validator_index < 0:
            raise ValidationError("negative validator index")

    def encode(self) -> bytes:
        return (
            Writer()
            .bytes(self.validator_address)
            .uvarint(self.validator_index)
            .uvarint(self.height)
            .uvarint(self.round)
            .svarint(self.timestamp)
            .uvarint(self.type)
            .raw(self.block_id.encode())
            .bytes(self.signature)
            .build()
        )

    @classmethod
    def decode_from(cls, r: Reader) -> "Vote":
        return cls(
            validator_address=r.bytes(),
            validator_index=r.uvarint(),
            height=r.uvarint(),
            round=r.uvarint(),
            timestamp=r.svarint(),
            type=r.uvarint(),
            block_id=BlockID.decode_from(r),
            signature=r.bytes(),
        )

    @classmethod
    def decode(cls, data: bytes) -> "Vote":
        r = Reader(data)
        v = cls.decode_from(r)
        r.expect_done()
        return v

    def __str__(self) -> str:
        tname = {VOTE_TYPE_PREVOTE: "Prevote", VOTE_TYPE_PRECOMMIT: "Precommit"}.get(
            self.type, "?"
        )
        return (
            f"Vote{{{self.validator_index}:{self.validator_address.hex()[:8]} "
            f"{self.height}/{self.round}/{tname} {self.block_id}}}"
        )
