"""BlockID: block hash + part-set header (reference `types/block.go` BlockID)."""

from __future__ import annotations

from dataclasses import dataclass

from tendermint_tpu.codec import Reader, Writer
from tendermint_tpu.types.part_set import PartSetHeader


@dataclass(frozen=True)
class BlockID:
    hash: bytes
    parts_header: PartSetHeader

    @classmethod
    def zero(cls) -> "BlockID":
        return cls(hash=b"", parts_header=PartSetHeader.zero())

    def is_zero(self) -> bool:
        return len(self.hash) == 0 and self.parts_header.is_zero()

    def key(self) -> bytes:
        """Stable dict key for vote tallies (reference `BlockID.Key`)."""
        return self.hash + b"|" + self.parts_header.hash + self.parts_header.total.to_bytes(8, "little")

    def encode(self) -> bytes:
        return Writer().bytes(self.hash).raw(self.parts_header.encode()).build()

    @classmethod
    def decode_from(cls, r: Reader) -> "BlockID":
        h = r.bytes()
        psh = PartSetHeader.decode_from(r)
        return cls(hash=h, parts_header=psh)

    def to_dict(self) -> dict:
        """Canonical-JSON form for sign-bytes (reference types/canonical_json.go)."""
        return {
            "hash": self.hash,
            "parts": {"total": self.parts_header.total, "hash": self.parts_header.hash},
        }

    def __str__(self) -> str:
        return f"{self.hash.hex()[:12]}:{self.parts_header.total}"
