"""Typed consensus events + the event switch.

Reference `types/events.go:14-34` + tmlibs/events: the event bus doubles as
the observability plane (SURVEY.md §5.5) — every consensus step fires a typed
event consumed internally by the reactor and externally via RPC subscribe.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Any, Callable

# -- event name constants (reference types/events.go) -------------------------

EVENT_NEW_BLOCK = "NewBlock"
EVENT_NEW_BLOCK_HEADER = "NewBlockHeader"
EVENT_NEW_ROUND = "NewRound"
EVENT_NEW_ROUND_STEP = "NewRoundStep"
EVENT_TIMEOUT_PROPOSE = "TimeoutPropose"
EVENT_TIMEOUT_WAIT = "TimeoutWait"
EVENT_COMPLETE_PROPOSAL = "CompleteProposal"
EVENT_POLKA = "Polka"
EVENT_UNLOCK = "Unlock"
EVENT_LOCK = "Lock"
EVENT_RELOCK = "Relock"
EVENT_VOTE = "Vote"
EVENT_TX = "Tx"
EVENT_PROPOSAL_HEARTBEAT = "ProposalHeartbeat"


def event_tx(tx_hash: bytes) -> str:
    """Per-tx event key (reference `EventStringTx`)."""
    return f"Tx:{tx_hash.hex()}"


@dataclass
class EventDataNewBlock:
    block: Any


@dataclass
class EventDataNewBlockHeader:
    header: Any


@dataclass
class EventDataTx:
    height: int
    tx: bytes
    data: bytes
    log: str
    code: int


@dataclass
class EventDataRoundState:
    height: int
    round: int
    step: str
    round_state: Any = None


@dataclass
class EventDataVote:
    vote: Any


@dataclass
class EventDataProposalHeartbeat:
    heartbeat: Any


class EventSwitch:
    """Thread-safe pub/sub registry (tmlibs `events.EventSwitch` role).

    Listeners are keyed by (listener_id, event) so one subscriber can be
    removed wholesale (`remove_listener`), matching the reference semantics
    used by RPC websocket subscriptions.
    """

    def __init__(self) -> None:
        self._lock = threading.RLock()
        # event -> list of (listener_id, callback)
        self._listeners: dict[str, list[tuple[str, Callable[[Any], None]]]] = {}

    def add_listener(self, listener_id: str, event: str, cb: Callable[[Any], None]) -> None:
        with self._lock:
            self._listeners.setdefault(event, []).append((listener_id, cb))

    def remove_listener(self, listener_id: str, event: str | None = None) -> None:
        with self._lock:
            events = [event] if event is not None else list(self._listeners)
            for ev in events:
                if ev in self._listeners:
                    self._listeners[ev] = [
                        (lid, cb) for lid, cb in self._listeners[ev] if lid != listener_id
                    ]
                    if not self._listeners[ev]:
                        del self._listeners[ev]

    def fire(self, event: str, data: Any = None) -> None:
        with self._lock:
            cbs = [cb for _, cb in self._listeners.get(event, [])]
        for cb in cbs:
            # Listener callbacks are external code: a raising subscriber
            # must never propagate into the firing component (the
            # consensus loop fires NewBlock between commit and
            # _schedule_round0 — an escaping exception there would stall
            # the node at the new height).
            try:
                cb(data)
            except Exception:
                import logging

                logging.getLogger(__name__).exception(
                    "event listener raised for %s", event
                )


class EventCache:
    """Batch events and flush at once (reference `types.EventCache`, used for
    per-tx events inside block execution)."""

    def __init__(self, switch: EventSwitch):
        self._switch = switch
        self._pending: list[tuple[str, Any]] = []

    def fire(self, event: str, data: Any = None) -> None:
        self._pending.append((event, data))

    def flush(self) -> None:
        pending, self._pending = self._pending, []
        for event, data in pending:
            self._switch.fire(event, data)
