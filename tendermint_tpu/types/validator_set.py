"""Validators and the weighted-round-robin proposer rotation.

Reference: `types/validator_set.go` — sorted-by-address set, `IncrementAccum`
proposer selection (`:52-69`), Merkle hash of the set (`:145`), and the HOT
LOOP `VerifyCommit` (`:225-269`) which the reference runs as N sequential
ed25519 verifications. Here `verify_commit` routes through a `BatchVerifier`
(one device batch per commit) with the host loop as fallback.
"""

from __future__ import annotations

from dataclasses import dataclass, replace

from tendermint_tpu.codec import Writer
from tendermint_tpu.crypto import PubKey
from tendermint_tpu.merkle import simple_hash_from_byte_slices
from tendermint_tpu.types.block_id import BlockID
from tendermint_tpu.types.errors import ErrTooMuchChange, ValidationError
from tendermint_tpu.types.vote import VOTE_TYPE_PRECOMMIT


@dataclass(frozen=True)
class Validator:
    address: bytes
    pub_key: PubKey
    voting_power: int
    accum: int = 0

    def encode(self) -> bytes:
        """Deterministic encoding hashed into the validator-set root."""
        return (
            Writer().bytes(self.address).bytes(self.pub_key.data).uvarint(self.voting_power).build()
        )

    def compare_proposer_priority(self, other: "Validator") -> "Validator":
        """Higher accum wins; ties break to the lower address
        (reference `Validator.CompareAccum`)."""
        if self.accum > other.accum:
            return self
        if self.accum < other.accum:
            return other
        return self if self.address < other.address else other


class ValidatorSet:
    def __init__(self, validators: list[Validator]):
        seen: set[bytes] = set()
        for v in validators:
            if v.address in seen:
                raise ValidationError(f"duplicate validator address {v.address.hex()}")
            if v.voting_power < 0:
                raise ValidationError("negative voting power")
            seen.add(v.address)
        self.validators: list[Validator] = sorted(validators, key=lambda v: v.address)
        self._total = sum(v.voting_power for v in self.validators)
        self._proposer: Validator | None = None
        self._addr_index: dict[bytes, int] | None = None
        self._hash: bytes | None = None

    # -- basic accessors ---------------------------------------------------

    def size(self) -> int:
        return len(self.validators)

    def __len__(self) -> int:
        return len(self.validators)

    @property
    def total_voting_power(self) -> int:
        return self._total

    def get_by_address(self, address: bytes) -> tuple[int, Validator | None]:
        # amortized O(1): the address->index map is built once per
        # membership change (the reference's sort.Search is O(log n) per
        # call; per-precommit lookups in verify_commit_any make anything
        # worse than this quadratic at 10k validators)
        if self._addr_index is None:
            self._addr_index = {
                v.address: i for i, v in enumerate(self.validators)
            }
        i = self._addr_index.get(address, -1)
        if i < 0:
            return -1, None
        return i, self.validators[i]

    def get_by_index(self, index: int) -> Validator | None:
        if 0 <= index < len(self.validators):
            return self.validators[index]
        return None

    def has_address(self, address: bytes) -> bool:
        return self.get_by_address(address)[0] >= 0

    def copy(self) -> "ValidatorSet":
        vs = ValidatorSet(list(self.validators))
        vs._proposer = self._proposer
        return vs

    # -- proposer rotation -------------------------------------------------

    def increment_accum(self, times: int = 1) -> None:
        """Weighted round-robin (reference `IncrementAccum
        types/validator_set.go:52-69`): each step adds voting power to every
        accumulator, picks the max as proposer, subtracts total power from it."""
        for _ in range(times):
            self.validators = [
                replace(v, accum=v.accum + v.voting_power) for v in self.validators
            ]
            proposer = self.validators[0]
            for v in self.validators[1:]:
                proposer = proposer.compare_proposer_priority(v)
            idx, _ = self.get_by_address(proposer.address)
            self.validators[idx] = replace(proposer, accum=proposer.accum - self._total)
            self._proposer = self.validators[idx]

    @property
    def proposer(self) -> Validator:
        if not self.validators:
            raise ValidationError("empty validator set has no proposer")
        if self._proposer is None:
            p = self.validators[0]
            for v in self.validators[1:]:
                p = p.compare_proposer_priority(v)
            self._proposer = p
        return self._proposer

    # -- hashing -----------------------------------------------------------

    def hash(self) -> bytes:
        """Merkle root of the validator encodings (reference `Hash :145`).
        Cached: the encoding covers address/pubkey/power only, so accum
        rotation (increment_accum) does not change it; membership/power
        changes invalidate in apply_changes."""
        if self._hash is None:
            self._hash = simple_hash_from_byte_slices(
                [v.encode() for v in self.validators]
            )
        return self._hash

    # -- membership changes (EndBlock diffs) --------------------------------

    def apply_changes(self, changes: list[Validator]) -> None:
        """Apply app-driven diffs: power 0 removes, new address adds, else
        updates (reference `updateValidators state/execution.go:120-159`)."""
        for c in changes:
            idx, existing = self.get_by_address(c.address)
            if c.voting_power == 0:
                if existing is None:
                    raise ValidationError("removing unknown validator")
                self.validators.pop(idx)
                # positions shifted: drop the cached address index so the
                # next lookup in this same batch rebuilds it
                self._addr_index = None
            elif existing is None:
                self.validators.append(replace(c, accum=0))
                # keep sorted so index order stays canonical for any
                # further change in this same batch
                self.validators.sort(key=lambda v: v.address)
                self._addr_index = None
            else:
                self.validators[idx] = replace(existing, voting_power=c.voting_power)
        self._total = sum(v.voting_power for v in self.validators)
        self._proposer = None
        self._addr_index = None
        self._hash = None

    # -- commit verification (the hot loop) ---------------------------------

    def _collect_commit_sigs(
        self, chain_id: str, block_id: BlockID, height: int, commit
    ) -> tuple[list[tuple[bytes, bytes, bytes]], list[int]]:
        """Shared validation walk: returns (pubkey,msg,sig) triples and the
        vote indices they came from."""
        if len(self.validators) != len(commit.precommits):
            raise ValidationError(
                f"commit size {len(commit.precommits)} != valset size {len(self.validators)}"
            )
        if height != commit.height():
            raise ValidationError(f"commit height {commit.height()} != {height}")
        round_ = commit.round()
        triples: list[tuple[bytes, bytes, bytes]] = []
        indices: list[int] = []
        for idx, precommit in enumerate(commit.precommits):
            if precommit is None:
                continue
            if precommit.height != height:
                raise ValidationError(f"precommit height {precommit.height} != {height}")
            if precommit.round != round_:
                raise ValidationError(f"precommit round {precommit.round} != {round_}")
            if precommit.type != VOTE_TYPE_PRECOMMIT:
                raise ValidationError("commit vote is not a precommit")
            val = self.validators[idx]
            triples.append(
                (val.pub_key.data, precommit.sign_bytes(chain_id), precommit.signature)
            )
            indices.append(idx)
        return triples, indices

    def verify_commit(
        self,
        chain_id: str,
        block_id: BlockID,
        height: int,
        commit,
        verifier=None,
        consumer: str = "default",
    ) -> None:
        """Raise unless >2/3 of this set's power signed block_id at height.

        Reference `VerifyCommit types/validator_set.go:225-269` — but instead
        of one ed25519 verify per iteration, all signatures flush as a single
        device batch when a `BatchVerifier` is supplied. The K=1 case of
        `verify_commit_batched`.
        """
        self.verify_commit_batched(
            chain_id, [(block_id, height, commit)], verifier, consumer=consumer
        )

    def verify_commit_batched(
        self,
        chain_id: str,
        entries: list[tuple[BlockID, int, "object"]],
        verifier=None,
        consumer: str = "default",
    ) -> None:
        """Verify K commits signed by THIS validator set as one device
        batch — the fast-sync window shape (BASELINE config 3; reference
        verifies one commit per loop iteration at
        `blockchain/reactor.go:259`). `entries` is a list of
        (block_id, height, commit). Raises naming the failing validator
        (and entry, when K > 1). Verifiers exposing `verify_commits`
        (the valset-table cache) get commits in validator-lane order so
        repeated commits of one valset hit cached per-validator comb
        tables; other verifiers get flat triple batches.

        Verifiers advertising the consumer-tag surface (the coalescing
        stack) are routed through the ASYNC handles and joined here —
        that is how blocking callers (the certifier walk, statesync
        trust anchoring) coalesce with concurrent consumers for free.
        """
        if verifier is None:
            from tendermint_tpu.services.verifier import default_verifier

            verifier = default_verifier()
        if getattr(verifier, "accepts_consumer", False):
            self.verify_commit_batched_async(
                chain_id, entries, verifier, consumer=consumer
            ).result()
            return
        collected = [
            self._collect_commit_sigs(chain_id, bid, h, c)
            for bid, h, c in entries
        ]
        n = len(self.validators)
        if hasattr(verifier, "verify_commits") and any(
            triples for triples, _ in collected
        ):
            grid = verifier.verify_commits(
                [v.pub_key.data for v in self.validators],
                self._commit_lanes(collected, n),
            )
            ok_by_entry = self._grid_to_entry_oks(grid, collected)
        else:
            ok_by_entry = [
                _verify_triples(triples, verifier) for triples, _ in collected
            ]
        self._tally_commit_verdicts(entries, collected, ok_by_entry)

    def verify_commit_batched_async(
        self,
        chain_id: str,
        entries: list[tuple[BlockID, int, "object"]],
        verifier=None,
        queue=None,
        consumer: str = "default",
    ):
        """Pipelined `verify_commit_batched`: lane prep + device submit
        happen NOW (the caller's host-prep stage), the quorum tally —
        and any ValidationError — at the returned handle's `.result()`.

        Malformed commits (size/height/round mismatches) still raise
        synchronously here, before anything is launched: the fast-sync
        pipeline treats that exactly like a failed verdict. Verifiers
        without an async surface verify inline and hand back an
        already-resolved handle, so callers stay uniform.
        """
        if verifier is None:
            from tendermint_tpu.services.verifier import default_verifier

            verifier = default_verifier()
        collected = [
            self._collect_commit_sigs(chain_id, bid, h, c)
            for bid, h, c in entries
        ]
        n = len(self.validators)

        from tendermint_tpu.services.batcher import consumer_kwargs

        kw = consumer_kwargs(verifier, consumer)
        if hasattr(verifier, "verify_commits_async") and any(
            triples for triples, _ in collected
        ):
            handle = verifier.verify_commits_async(
                [v.pub_key.data for v in self.validators],
                self._commit_lanes(collected, n),
                queue=queue,
                **kw,
            )

            def _tally_grid(grid):
                self._tally_commit_verdicts(
                    entries, collected, self._grid_to_entry_oks(grid, collected)
                )
                return True

            return handle.then(_tally_grid)
        if hasattr(verifier, "verify_batch_async"):
            flat = [t for triples, _ in collected for t in triples]
            handle = verifier.verify_batch_async(flat, queue=queue, **kw)

            def _tally_flat(mask):
                ok_by_entry, at = [], 0
                for triples, _ in collected:
                    ok_by_entry.append(
                        [bool(v) for v in mask[at : at + len(triples)]]
                    )
                    at += len(triples)
                self._tally_commit_verdicts(entries, collected, ok_by_entry)
                return True

            return handle.then(_tally_flat)
        from tendermint_tpu.services.dispatch import CompletedHandle

        try:
            self.verify_commit_batched(chain_id, entries, verifier)
        except ValidationError as e:
            return CompletedHandle(exc=e)
        return CompletedHandle(True)

    @staticmethod
    def _commit_lanes(collected, n: int) -> list[tuple[list, list]]:
        """Triples+indices -> validator-index-aligned (msgs, sigs) lanes
        for the commit-grid verifiers (cached comb tables)."""
        lanes: list[tuple[list, list]] = []
        for triples, indices in collected:
            msgs: list[bytes | None] = [None] * n
            sigs: list[bytes | None] = [None] * n
            for (pk, msg, sig), idx in zip(triples, indices):
                msgs[idx], sigs[idx] = msg, sig
            lanes.append((msgs, sigs))
        return lanes

    @staticmethod
    def _grid_to_entry_oks(grid, collected) -> list[list[bool]]:
        return [
            [bool(grid[ei][i]) for i in indices]
            for ei, (_, indices) in enumerate(collected)
        ]

    def _tally_commit_verdicts(self, entries, collected, ok_by_entry) -> None:
        """Shared quorum walk: raises naming the failing validator (and
        entry, when K > 1), else requires >2/3 power per entry."""
        for ei, ((block_id, height, commit), (_, indices), oks) in enumerate(
            zip(entries, collected, ok_by_entry)
        ):
            tallied = 0
            for ok, idx in zip(oks, indices):
                if not ok:
                    where = (
                        f" (batch entry {ei}, height {height})"
                        if len(entries) > 1
                        else ""
                    )
                    raise ValidationError(
                        f"invalid commit signature from validator {idx}{where}"
                    )
                if commit.precommits[idx].block_id == block_id:
                    tallied += self.validators[idx].voting_power
            if not tallied * 3 > self._total * 2:
                raise ValidationError(
                    f"insufficient voting power: {tallied} of {self._total}"
                )

    def verify_commit_any(
        self,
        new_set: "ValidatorSet",
        chain_id: str,
        block_id: BlockID,
        height: int,
        commit,
        verifier=None,
        consumer: str = "default",
    ) -> None:
        """Light-client rule (reference `VerifyCommitAny
        types/validator_set.go:284-349`): enough of the OLD set (this one,
        >2/3) must have signed the commit produced under `new_set`, matching
        validators by address across the two sets."""
        if len(new_set.validators) != len(commit.precommits):
            raise ValidationError("commit size != new valset size")
        if height != commit.height():
            raise ValidationError("commit height mismatch")
        round_ = commit.round()
        triples: list[tuple[bytes, bytes, bytes]] = []
        old_powers: list[int] = []
        new_powers: list[int] = []
        seen: set[bytes] = set()
        for idx, precommit in enumerate(commit.precommits):
            if precommit is None:
                continue
            # Every non-nil precommit must be well-formed, even ones for other
            # blocks (matches verify_commit; reference validates all votes).
            if precommit.height != height or precommit.round != round_:
                raise ValidationError("commit vote height/round mismatch")
            if precommit.type != VOTE_TYPE_PRECOMMIT:
                raise ValidationError("commit vote is not a precommit")
            if precommit.block_id != block_id:
                continue
            new_val = new_set.validators[idx]
            _, old_val = self.get_by_address(new_val.address)
            if old_val is None or old_val.address in seen:
                continue
            seen.add(old_val.address)
            triples.append(
                (old_val.pub_key.data, precommit.sign_bytes(chain_id), precommit.signature)
            )
            old_powers.append(old_val.voting_power)
            new_powers.append(new_val.voting_power)
        ok_mask = _verify_triples(triples, verifier, consumer=consumer)
        old_tallied = 0
        new_tallied = 0
        for ok, op, np_ in zip(ok_mask, old_powers, new_powers):
            if not ok:
                raise ValidationError("invalid commit signature (old set)")
            old_tallied += op
            new_tallied += np_
        # BOTH quorums must hold: >2/3 of the old (trusted) set AND >2/3 of the
        # new set — otherwise a grown set could be "committed" by a minority of
        # its power (reference validator_set.go:340-346). The old-quorum
        # failure is typed so the light client can trigger bisection.
        if not old_tallied * 3 > self._total * 2:
            raise ErrTooMuchChange(
                f"insufficient old voting power: {old_tallied} of {self._total}"
            )
        if not new_tallied * 3 > new_set.total_voting_power * 2:
            raise ValidationError(
                f"insufficient new voting power: {new_tallied} of {new_set.total_voting_power}"
            )

    def __iter__(self):
        return iter(self.validators)

    def __repr__(self) -> str:
        return f"ValidatorSet(n={len(self.validators)}, power={self._total})"


def _verify_triples(
    triples: list[tuple[bytes, bytes, bytes]], verifier, consumer: str = "default"
) -> list[bool]:
    """Verify (pubkey,msg,sig) triples as one batch through the given
    BatchVerifier, defaulting to the process-wide verifier (device-backed
    when an accelerator is present). Tagged verifiers (the coalescing
    stack) route through an async handle joined here, so blocking
    callers — `verify_commit_any` in the certifier walk — still merge
    into coalesced launches."""
    if not triples:
        return []
    if verifier is None:
        from tendermint_tpu.services.verifier import default_verifier

        verifier = default_verifier()
    if getattr(verifier, "accepts_consumer", False) and hasattr(
        verifier, "verify_batch_async"
    ):
        return list(
            verifier.verify_batch_async(triples, consumer=consumer).result()
        )
    return list(verifier.verify_batch(triples))
