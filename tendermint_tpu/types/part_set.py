"""PartSet: block sharding with Merkle integrity proofs.

Role of `types/part_set.go` in the reference: a serialized block (the "long
sequence") is split into fixed-size parts, each carrying a Merkle inclusion
proof against the PartSetHeader root, gossiped peer-to-peer and reassembled
(`types/part_set.go:95-133,188-214`). This is the reference's blockwise
sequence-sharding structure (SURVEY.md §5.7); the TPU tree hasher builds all
part proofs in one batched tree reduction.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass

from tendermint_tpu.codec import Reader, Writer
from tendermint_tpu.merkle import (
    SimpleProof,
    simple_proofs_from_byte_slices,
    verify_proof,
)
from tendermint_tpu.types.errors import ValidationError
from tendermint_tpu.utils.bit_array import BitArray

DEFAULT_PART_SIZE = 4096  # reference: ConsensusParams.BlockPartSizeBytes (types/params.go:20-25)


@dataclass(frozen=True)
class PartSetHeader:
    total: int
    hash: bytes

    def is_zero(self) -> bool:
        return self.total == 0 and len(self.hash) == 0

    def encode(self) -> bytes:
        return Writer().uvarint(self.total).bytes(self.hash).build()

    @classmethod
    def decode_from(cls, r: Reader) -> "PartSetHeader":
        return cls(total=r.uvarint(), hash=r.bytes())

    def to_dict(self) -> dict:
        return {"total": self.total, "hash": self.hash}

    @classmethod
    def zero(cls) -> "PartSetHeader":
        return cls(total=0, hash=b"")


@dataclass
class Part:
    index: int
    bytes_: bytes
    proof: SimpleProof

    def encode(self) -> bytes:
        return (
            Writer().uvarint(self.index).bytes(self.bytes_).bytes(self.proof.encode()).build()
        )

    @classmethod
    def decode(cls, data: bytes) -> "Part":
        r = Reader(data)
        index = r.uvarint()
        bytes_ = r.bytes()
        proof = SimpleProof.decode(r.bytes())
        r.expect_done()
        return cls(index=index, bytes_=bytes_, proof=proof)


class PartSet:
    """Complete (maker side) or incrementally-filled (gossip side) part set."""

    def __init__(self, header: PartSetHeader):
        self.header = header
        self._parts: list[Part | None] = [None] * header.total
        self.parts_bit_array = BitArray(header.total)
        self._count = 0
        # Gossip side: concurrent peer readers deliver parts — guard the
        # check-then-set (reference part_set.go holds a mutex in AddPart).
        self._lock = threading.RLock()

    # -- construction ------------------------------------------------------

    @classmethod
    def from_data(
        cls, data: bytes, part_size: int = DEFAULT_PART_SIZE, hasher=None
    ) -> "PartSet":
        """Split serialized data into Merkle-proved parts
        (reference `NewPartSetFromData types/part_set.go:95-122`)."""
        if part_size <= 0:
            raise ValueError("part_size must be positive")
        chunks = [data[i : i + part_size] for i in range(0, len(data), part_size)] or [b""]
        if hasher is not None:
            root, proofs = hasher.proofs(chunks)
        else:
            root, proofs = simple_proofs_from_byte_slices(chunks)
        ps = cls(PartSetHeader(total=len(chunks), hash=root))
        for i, (chunk, proof) in enumerate(zip(chunks, proofs)):
            ps._parts[i] = Part(index=i, bytes_=chunk, proof=proof)
            ps.parts_bit_array.set(i, True)
        ps._count = len(chunks)
        return ps

    @classmethod
    def from_header(cls, header: PartSetHeader) -> "PartSet":
        return cls(header)

    # -- gossip side -------------------------------------------------------

    def add_part(self, part: Part) -> bool:
        """Verify the part's Merkle proof and slot it in
        (reference `AddPart types/part_set.go:188-214`)."""
        if not (0 <= part.index < self.header.total):
            raise ValidationError(f"part index {part.index} out of range")
        if part.proof.index != part.index or part.proof.total != self.header.total:
            raise ValidationError("part proof shape mismatch")
        if not verify_proof(self.header.hash, part.bytes_, part.proof):
            raise ValidationError("invalid part Merkle proof")
        with self._lock:
            if self._parts[part.index] is not None:
                return False  # already have it
            self._parts[part.index] = part
            self.parts_bit_array.set(part.index, True)
            self._count += 1
        return True

    # -- accessors ---------------------------------------------------------

    def get_part(self, index: int) -> Part | None:
        with self._lock:
            return self._parts[index]

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def total(self) -> int:
        return self.header.total

    def is_complete(self) -> bool:
        with self._lock:
            return self._count == self.header.total

    def assemble(self) -> bytes:
        """Reassemble the original serialized data (reader side)."""
        with self._lock:
            if not self.is_complete():
                raise ValidationError("part set incomplete")
            return b"".join(p.bytes_ for p in self._parts)  # type: ignore[union-attr]

    def has_header(self, header: PartSetHeader) -> bool:
        return self.header == header
