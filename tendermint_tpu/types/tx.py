"""Transactions and Merkle proofs over them (reference `types/tx.go`).

`Txs.hash` is a batched tree build — on device this goes through the
`TreeHasher` (65k-tx blocks are BASELINE config 4).
"""

from __future__ import annotations

from dataclasses import dataclass

from tendermint_tpu.crypto.hashing import sha256
from tendermint_tpu.merkle import (
    SimpleProof,
    simple_hash_from_byte_slices,
    simple_proofs_from_byte_slices,
    verify_proof,
)

Tx = bytes


def tx_hash(tx: Tx) -> bytes:
    """Content hash of an individual tx (indexing key, reference `Tx.Hash`)."""
    return sha256(tx)


class Txs(list):
    """list[bytes] with tree hashing (reference `types/tx.go:33-46,71-88`)."""

    def hash(self, hasher=None) -> bytes:
        """Merkle root over txs; `hasher` is an optional TreeHasher backend."""
        if hasher is not None:
            return hasher.root_from_items(list(self))
        return simple_hash_from_byte_slices(list(self))

    def proof(self, i: int) -> "TxProof":
        root, proofs = simple_proofs_from_byte_slices(list(self))
        return TxProof(root_hash=root, data=self[i], proof=proofs[i])

    def index(self, tx: Tx) -> int:
        for i, t in enumerate(self):
            if t == tx:
                return i
        return -1


@dataclass
class TxProof:
    """Inclusion proof of one tx in a block's data hash
    (reference `TxProof.Validate types/tx.go:101-112`)."""

    root_hash: bytes
    data: Tx
    proof: SimpleProof

    def validate(self, data_hash: bytes) -> bool:
        if data_hash != self.root_hash:
            return False
        return verify_proof(self.root_hash, self.data, self.proof)
