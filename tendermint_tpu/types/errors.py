"""Typed errors for the domain layer (reference: types/vote_set.go errors,
types/priv_validator.go double-sign refusal)."""

from __future__ import annotations


class TMError(Exception):
    """Base class for framework domain errors."""


class ValidationError(TMError):
    """A structure failed ValidateBasic-style checks."""


class VoteError(TMError):
    pass


class ErrVoteUnexpectedStep(VoteError):
    pass


class ErrVoteInvalidValidatorIndex(VoteError):
    pass


class ErrVoteInvalidValidatorAddress(VoteError):
    pass


class ErrVoteInvalidSignature(VoteError):
    pass


class ErrVoteNonDeterministicSignature(VoteError):
    pass


class ErrVoteConflictingVotes(VoteError):
    """Duplicate-vote evidence: one validator, two different votes for the
    same (height, round, type) — reference `types/vote_set.go:182-195`."""

    def __init__(self, vote_a, vote_b):
        super().__init__(
            f"conflicting votes from validator {vote_a.validator_address.hex()}"
        )
        self.vote_a = vote_a
        self.vote_b = vote_b


class ErrEvidenceUnprovable(ValidationError):
    """Evidence naming a validator outside every retained validator set:
    cannot be verified HERE (valset rotation / max-age horizon), which
    is not the same as forged — relaying peers are not penalized for it
    (`evidence/reactor.py`)."""


class ErrValidatorsChanged(ValidationError):
    """A commit's validators hash differs from the certifier's trusted
    set (reference `certifiers/errors.go` IsValidatorsChangedErr)."""


class ErrTooMuchChange(ValidationError):
    """The trusted validator set overlaps the commit's signers by less
    than the 2/3 continuity rule — a light client cannot jump this far
    in one step and must bisect (reference `certifiers/errors.go`
    IsTooMuchChangeErr, raised from `VerifyCommitAny
    types/validator_set.go:284-349`)."""


class ErrTrustExpired(ValidationError):
    """The light client's trusted header outlived the trust period, so
    the skip rule lost its slashing backstop — the pin must be
    re-initialized. A CLIENT-side condition: never evidence that the
    serving peer forged anything."""


class ErrNoSourceCommit(ValidationError):
    """The source provider had no commit to offer (peer fetch timed
    out, provider lags the requested height, or no provider is wired).
    An environmental fetch failure, not a forgery — callers must not
    score the serving peer for it."""


class ErrDoubleSign(TMError):
    """PrivValidator refused to sign: height/round/step regression or
    conflicting sign-bytes (reference `types/priv_validator.go:225-275`)."""


class FatalConsensusError(TMError):
    """An internal invariant/persistence failure (failed block apply, WAL
    write, app commit). Unlike bad peer input, this must HALT consensus —
    the reference panics (PanicConsensus/PanicSanity) so crash recovery
    takes over rather than voting from a half-advanced state."""
