"""Evidence of validator misbehavior (reference `types/evidence.go`).

`DuplicateVoteEvidence` — two signed, conflicting votes from one
validator at the same (height, round, type) — is the proof object the
whole Byzantine accountability pipeline moves: detected at the
`ErrVoteConflictingVotes` sites in `types/vote_set.py`, pooled and
gossiped (`evidence/`), committed into blocks (`Block.evidence` +
`Header.evidence_hash`), and reported to the application at BeginBlock
so the app can slash (PAPERS.md: "A Tendermint Light Client" — fork
*attribution*; "Practical Light Clients for Committee-Based
Blockchains" — committee members must be accountable for equivocation).

Verification rides the existing `BatchVerifier` seam as a 2-lane batch
(both votes share the offender's pubkey): on TPU, evidence checks
coalesce with the consensus verify traffic instead of stealing host
cycles; the breaker ladder degrades them like any other verify.
"""

from __future__ import annotations

from dataclasses import dataclass

from tendermint_tpu.codec import Reader, Writer
from tendermint_tpu.crypto.hashing import tmhash
from tendermint_tpu.merkle import simple_hash_from_byte_slices
from tendermint_tpu.types.errors import ErrEvidenceUnprovable, ValidationError
from tendermint_tpu.types.vote import Vote

# wire tag for the one concrete evidence kind; new kinds extend the
# registry below (unknown tags are a decode error, never a crash)
_TAG_DUPLICATE_VOTE = 0x01


@dataclass(frozen=True)
class DuplicateVoteEvidence:
    """One validator, two different signed votes for the same
    (height, round, type) — reference `types/evidence.go` DupeoutTx /
    DuplicateVoteEvidence. Votes are stored in canonical order (sorted
    by block-id key, then signature) so the SAME equivocation hashes
    identically no matter which vote was seen first — the dedup key of
    the evidence pool and the gossip layer."""

    vote_a: Vote
    vote_b: Vote

    @classmethod
    def make(cls, vote_a: Vote, vote_b: Vote) -> "DuplicateVoteEvidence":
        """Canonicalize the pair (detection order varies per node)."""
        ka = (vote_a.block_id.key(), vote_a.signature)
        kb = (vote_b.block_id.key(), vote_b.signature)
        if kb < ka:
            vote_a, vote_b = vote_b, vote_a
        return cls(vote_a=vote_a, vote_b=vote_b)

    # -- identity ------------------------------------------------------------

    @property
    def height(self) -> int:
        return self.vote_a.height

    @property
    def address(self) -> bytes:
        """The offending validator's address."""
        return self.vote_a.validator_address

    def hash(self) -> bytes:
        return tmhash(self.encode())

    # -- checks --------------------------------------------------------------

    def validate_basic(self) -> None:
        """Structural proof checks — everything except the signatures
        (reference `DuplicateVoteEvidence.Verify` minus the crypto)."""
        a, b = self.vote_a, self.vote_b
        a.validate_basic()
        b.validate_basic()
        if a.validator_address != b.validator_address:
            raise ValidationError("duplicate-vote evidence: different validators")
        if a.validator_index != b.validator_index:
            raise ValidationError("duplicate-vote evidence: different indices")
        if (a.height, a.round, a.type) != (b.height, b.round, b.type):
            raise ValidationError(
                "duplicate-vote evidence: votes are for different steps"
            )
        if a.block_id == b.block_id:
            raise ValidationError(
                "duplicate-vote evidence: votes agree (no conflict)"
            )
        if not a.signature or not b.signature:
            raise ValidationError("duplicate-vote evidence: unsigned vote")

    def verify(self, chain_id: str, val_set, verifier=None) -> None:
        """Full proof check: structure, the offender is (or was) in
        `val_set`, and both signatures are genuine — verified as one
        2-lane batch through the `BatchVerifier` seam so evidence
        checks ride the device verify spine."""
        self.validate_basic()
        idx, val = val_set.get_by_address(self.address)
        if idx < 0 or val is None:
            raise ErrEvidenceUnprovable(
                f"evidence validator {self.address.hex()[:12]} not in validator set"
            )
        if verifier is None:
            from tendermint_tpu.services.verifier import default_verifier

            verifier = default_verifier()
        pk = val.pub_key.data
        verdicts = verifier.verify_batch(
            [
                (pk, self.vote_a.sign_bytes(chain_id), self.vote_a.signature),
                (pk, self.vote_b.sign_bytes(chain_id), self.vote_b.signature),
            ]
        )
        if not (bool(verdicts[0]) and bool(verdicts[1])):
            raise ValidationError(
                f"duplicate-vote evidence: forged signature(s) "
                f"(a={bool(verdicts[0])}, b={bool(verdicts[1])})"
            )

    # -- wire ----------------------------------------------------------------

    def encode(self) -> bytes:
        return (
            Writer()
            .uvarint(_TAG_DUPLICATE_VOTE)
            .bytes(self.vote_a.encode())
            .bytes(self.vote_b.encode())
            .build()
        )

    @classmethod
    def decode_from(cls, r: Reader) -> "DuplicateVoteEvidence":
        return cls(
            vote_a=Vote.decode(r.bytes()),
            vote_b=Vote.decode(r.bytes()),
        )

    def __str__(self) -> str:
        return (
            f"DuplicateVoteEvidence{{val={self.address.hex()[:12]} "
            f"{self.height}/{self.vote_a.round}/{self.vote_a.type}}}"
        )


def decode_evidence(data: bytes):
    """One evidence object from its tagged wire form."""
    r = Reader(data)
    ev = decode_evidence_from(r)
    r.expect_done()
    return ev


def decode_evidence_from(r: Reader):
    tag = r.uvarint()
    if tag == _TAG_DUPLICATE_VOTE:
        return DuplicateVoteEvidence.decode_from(r)
    raise ValidationError(f"unknown evidence tag {tag:#x}")


def verify_evidence_batch(
    chain_id: str, evidence: list, val_sets: list, verifier=None
) -> None:
    """Verify a whole block's evidence list in ONE device batch (2 lanes
    per proof) — the commit-side analog of the fast-sync commit window:
    N proofs cost one launch, not N. `val_sets` are the candidate
    validator sets, tried in order per offender (typically [validators,
    last_validators]). Raises ValidationError naming the first bad
    proof."""
    if not evidence:
        return
    triples = []
    for ev in evidence:
        ev.validate_basic()
        val = None
        for vs in val_sets:
            if vs is None or vs.size() == 0:
                continue
            idx, cand = vs.get_by_address(ev.address)
            if idx >= 0 and cand is not None:
                val = cand
                break
        if val is None:
            raise ErrEvidenceUnprovable(
                f"evidence validator {ev.address.hex()[:12]} not in any "
                f"retained validator set"
            )
        pk = val.pub_key.data
        triples.append((pk, ev.vote_a.sign_bytes(chain_id), ev.vote_a.signature))
        triples.append((pk, ev.vote_b.sign_bytes(chain_id), ev.vote_b.signature))
    if verifier is None:
        from tendermint_tpu.services.verifier import default_verifier

        verifier = default_verifier()
    verdicts = verifier.verify_batch(triples)
    for i, ev in enumerate(evidence):
        if not (bool(verdicts[2 * i]) and bool(verdicts[2 * i + 1])):
            raise ValidationError(f"evidence {i} carries forged signature(s): {ev}")


def evidence_hash(evidence: list, hasher=None) -> bytes:
    """Merkle root over an evidence list (the `Header.evidence_hash`
    commitment); b"" for no evidence — headers of evidence-free blocks
    stay byte-identical to the pre-evidence format. `hasher` routes the
    root build through a TreeHasher backend (same seam as `Txs.hash`;
    host and device trees are bit-equal by construction)."""
    if not evidence:
        return b""
    items = [ev.encode() for ev in evidence]
    if hasher is not None:
        return hasher.root_from_items(items)
    return simple_hash_from_byte_slices(items)
