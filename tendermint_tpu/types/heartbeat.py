"""Proposer heartbeat (reference `types/heartbeat.go`): signed liveness ping
broadcast while the proposer waits for txs in no-empty-blocks mode."""

from __future__ import annotations

from dataclasses import dataclass, replace

from tendermint_tpu.codec import Reader, Writer, canonical_dumps


@dataclass(frozen=True)
class Heartbeat:
    validator_address: bytes
    validator_index: int
    height: int
    round: int
    sequence: int
    signature: bytes = b""

    def sign_bytes(self, chain_id: str) -> bytes:
        return canonical_dumps(
            {
                "chain_id": chain_id,
                "heartbeat": {
                    "validator_address": self.validator_address,
                    "validator_index": self.validator_index,
                    "height": self.height,
                    "round": self.round,
                    "sequence": self.sequence,
                },
            }
        )

    def with_signature(self, sig: bytes) -> "Heartbeat":
        return replace(self, signature=sig)

    def encode(self) -> bytes:
        return (
            Writer()
            .bytes(self.validator_address)
            .uvarint(self.validator_index)
            .uvarint(self.height)
            .uvarint(self.round)
            .uvarint(self.sequence)
            .bytes(self.signature)
            .build()
        )

    @classmethod
    def decode(cls, data: bytes) -> "Heartbeat":
        r = Reader(data)
        hb = cls(
            validator_address=r.bytes(),
            validator_index=r.uvarint(),
            height=r.uvarint(),
            round=r.uvarint(),
            sequence=r.uvarint(),
            signature=r.bytes(),
        )
        r.expect_done()
        return hb
