"""VoteSet: the signature-accumulating 2/3-quorum tracker.

Reference `types/vote_set.go` — the consensus HOT LOOP: `addVote:137-196`
verifies one ed25519 signature per vote then tallies. Here verification goes
through a pluggable verifier so live consensus can use the host path (1 sig,
latency-bound) while replay/fast-sync paths feed whole commits through the
TPU batch verifier. Conflict detection, peer-claimed-majority bookkeeping and
quorum semantics follow the reference.
"""

from __future__ import annotations

import threading

from tendermint_tpu.crypto import PubKey
from tendermint_tpu.types.block_id import BlockID
from tendermint_tpu.types.block import Commit
from tendermint_tpu.types.errors import (
    ErrVoteConflictingVotes,
    ErrVoteInvalidSignature,
    ErrVoteInvalidValidatorAddress,
    ErrVoteInvalidValidatorIndex,
    ErrVoteNonDeterministicSignature,
    ErrVoteUnexpectedStep,
    ValidationError,
)
from tendermint_tpu.types.validator_set import ValidatorSet
from tendermint_tpu.types.vote import VOTE_TYPE_PRECOMMIT, Vote, is_vote_type_valid
from tendermint_tpu.utils.bit_array import BitArray


class _BlockVotes:
    """Per-block-ID tally (reference `blockVotes`)."""

    __slots__ = ("peer_maj23", "bit_array", "votes", "sum")

    def __init__(self, peer_maj23: bool, num_validators: int):
        self.peer_maj23 = peer_maj23
        self.bit_array = BitArray(num_validators)
        self.votes: list[Vote | None] = [None] * num_validators
        self.sum = 0

    def add_verified_vote(self, vote: Vote, power: int) -> None:
        if self.votes[vote.validator_index] is None:
            self.bit_array.set(vote.validator_index, True)
            self.votes[vote.validator_index] = vote
            self.sum += power

    def get_by_index(self, i: int) -> Vote | None:
        return self.votes[i]


class VoteSet:
    def __init__(self, chain_id: str, height: int, round_: int, type_: int, val_set: ValidatorSet):
        if height < 1:
            raise ValidationError("VoteSet height must be >= 1")
        if not is_vote_type_valid(type_):
            raise ValidationError(f"invalid vote type {type_}")
        self.chain_id = chain_id
        self.height = height
        self.round = round_
        self.type = type_
        self.val_set = val_set
        self._lock = threading.RLock()
        n = val_set.size()
        self.votes_bit_array = BitArray(n)
        self.votes: list[Vote | None] = [None] * n
        self.sum = 0  # total power of all added votes (any block)
        self.maj23: BlockID | None = None
        self.votes_by_block: dict[bytes, _BlockVotes] = {}
        self.peer_maj23s: dict[str, BlockID] = {}
        # insertion-ordered keys of claim-created (still-empty) tallies,
        # for bounded eviction — see MAX_PEER_CLAIMS
        self._claim_keys: list[bytes] = []

    # -- add ----------------------------------------------------------------

    def add_vote(self, vote: Vote | None, verifier=None, preverified: bool = False) -> bool:
        """Add one vote; returns True if it changed the set. Raises VoteError
        subclasses on invalid/conflicting votes (reference `AddVote:126-196`).

        `preverified=True` skips the signature check: the caller already
        verified this exact (pubkey, sign_bytes, sig) in a device batch
        (the consensus loop's vote-storm drain) — every structural check
        still runs."""
        if vote is None:
            raise ValidationError("nil vote")
        with self._lock:
            return self._add_vote(vote, verifier, preverified)

    def _add_vote(self, vote: Vote, verifier, preverified: bool = False) -> bool:
        idx = vote.validator_index
        if idx < 0:
            raise ErrVoteInvalidValidatorIndex(f"negative index {idx}")
        if (vote.height, vote.round, vote.type) != (self.height, self.round, self.type):
            raise ErrVoteUnexpectedStep(
                f"vote {vote.height}/{vote.round}/{vote.type} != "
                f"set {self.height}/{self.round}/{self.type}"
            )
        val = self.val_set.get_by_index(idx)
        if val is None:
            raise ErrVoteInvalidValidatorIndex(f"index {idx} >= {self.val_set.size()}")
        if val.address != vote.validator_address:
            raise ErrVoteInvalidValidatorAddress(
                f"vote address {vote.validator_address.hex()} != validator {val.address.hex()}"
            )

        # Duplicate / conflict detection before paying for verification.
        existing = self._get_vote(idx, vote.block_id)
        if existing is not None and existing.signature == vote.signature:
            return False  # exact duplicate

        # Signature check — host single verify or device batch-of-one
        # (skipped when the receive loop batch-verified this vote already)
        if not preverified:
            self._verify_signature(vote, val.pub_key, verifier)

        return self._add_verified_vote(vote, val.voting_power)

    def _verify_signature(self, vote: Vote, pub_key: PubKey, verifier) -> None:
        msg = vote.sign_bytes(self.chain_id)
        if verifier is None:
            from tendermint_tpu.services.verifier import default_verifier

            verifier = default_verifier()
        ok = bool(verifier.verify_batch([(pub_key.data, msg, vote.signature)])[0])
        if not ok:
            raise ErrVoteInvalidSignature(f"invalid signature on {vote}")

    def _add_verified_vote(self, vote: Vote, power: int) -> bool:
        idx = vote.validator_index
        conflicting: Vote | None = None

        existing = self.votes[idx]
        if existing is not None:
            if existing.block_id == vote.block_id:
                # ed25519 is deterministic per key: two different signatures
                # over identical sign-bytes means a malleated/invalid replay.
                raise ErrVoteNonDeterministicSignature(
                    "same vote content with different signature"
                )
            conflicting = existing
        else:
            self.votes[idx] = vote
            self.votes_bit_array.set(idx, True)
            self.sum += power

        key = vote.block_id.key()
        bv = self.votes_by_block.get(key)
        if bv is not None:
            if conflicting is not None and not bv.peer_maj23:
                # A conflict only tracks against blocks a peer claimed maj23
                # for (reference :236-240).
                raise ErrVoteConflictingVotes(conflicting, vote)
        else:
            if conflicting is not None:
                raise ErrVoteConflictingVotes(conflicting, vote)
            bv = _BlockVotes(peer_maj23=False, num_validators=self.val_set.size())
            self.votes_by_block[key] = bv

        old_sum = bv.sum
        quorum = self.val_set.total_voting_power * 2 // 3 + 1
        bv.add_verified_vote(vote, power)

        # Did this vote tip a block over 2/3?
        if old_sum < quorum <= bv.sum and self.maj23 is None:
            self.maj23 = vote.block_id
            # Promote this block's votes into the canonical vote list
            # (conflicts resolved in favor of the maj23 block — ref :262-269).
            for i, v in enumerate(bv.votes):
                if v is not None:
                    self.votes[i] = v
        if conflicting is not None:
            raise ErrVoteConflictingVotes(conflicting, vote)
        return True

    def _get_vote(self, idx: int, block_id: BlockID) -> Vote | None:
        v = self.votes[idx]
        if v is not None and v.block_id == block_id:
            return v
        bv = self.votes_by_block.get(block_id.key())
        if bv is not None:
            return bv.get_by_index(idx)
        return None

    # -- peer claims --------------------------------------------------------

    # Bound on claim-created tallies a flooding peer set can force into
    # votes_by_block: each fresh fake block-id claim allocates a
    # validator-sized _BlockVotes, so without a cap N peers x unlimited
    # claims is unbounded per-round memory. Oldest still-EMPTY claim
    # tallies are evicted past the cap; tallies holding real votes are
    # never dropped (losing votes would be a safety regression).
    MAX_PEER_CLAIMS = 8

    def set_peer_maj23(self, peer_id: str, block_id: BlockID) -> None:
        """A peer claims 2/3 majority for block_id; start tracking its votes
        even across conflicts (reference `SetPeerMaj23`). One claim per
        peer per vote set; claim-created tallies are bounded (a flooding
        peer cannot grow per-round state without limit)."""
        with self._lock:
            if peer_id in self.peer_maj23s:
                return
            self.peer_maj23s[peer_id] = block_id
            key = block_id.key()
            bv = self.votes_by_block.get(key)
            if bv is not None:
                bv.peer_maj23 = True
                return
            self.votes_by_block[key] = _BlockVotes(
                peer_maj23=True, num_validators=self.val_set.size()
            )
            self._claim_keys.append(key)
            while len(self._claim_keys) > self.MAX_PEER_CLAIMS:
                old = self._claim_keys.pop(0)
                stale = self.votes_by_block.get(old)
                if stale is not None and stale.sum == 0 and old != key:
                    del self.votes_by_block[old]

    # -- queries ------------------------------------------------------------

    def get_by_index(self, idx: int) -> Vote | None:
        with self._lock:
            return self.votes[idx] if 0 <= idx < len(self.votes) else None

    def get_by_address(self, address: bytes) -> Vote | None:
        idx, _ = self.val_set.get_by_address(address)
        return self.get_by_index(idx) if idx >= 0 else None

    def has_two_thirds_majority(self) -> bool:
        with self._lock:
            return self.maj23 is not None

    def two_thirds_majority(self) -> BlockID | None:
        with self._lock:
            return self.maj23

    def has_two_thirds_any(self) -> bool:
        """>2/3 of power has voted for *something* (incl. conflicting blocks)."""
        with self._lock:
            return self.sum * 3 > self.val_set.total_voting_power * 2

    def has_all(self) -> bool:
        with self._lock:
            return self.sum == self.val_set.total_voting_power

    def bit_array(self) -> BitArray:
        with self._lock:
            return self.votes_bit_array.copy()

    def bit_array_by_block_id(self, block_id: BlockID) -> BitArray | None:
        with self._lock:
            bv = self.votes_by_block.get(block_id.key())
            return bv.bit_array.copy() if bv is not None else None

    # -- commit construction -------------------------------------------------

    def make_commit(self) -> Commit:
        """Seal the +2/3 precommits into a Commit (reference `MakeCommit`)."""
        if self.type != VOTE_TYPE_PRECOMMIT:
            raise ValidationError("cannot MakeCommit from a prevote set")
        with self._lock:
            if self.maj23 is None:
                raise ValidationError("cannot MakeCommit without +2/3 majority")
            precommits = [
                v if (v is not None and v.block_id == self.maj23) else None
                for v in self.votes
            ]
            return Commit(block_id=self.maj23, precommits=precommits)

    def __repr__(self) -> str:
        return (
            f"VoteSet{{{self.height}/{self.round}/{self.type} "
            f"{self.votes_bit_array} sum={self.sum}}}"
        )
