"""PrivValidator: the consensus signer with double-sign prevention.

Reference `types/priv_validator.go` — persists LastHeight/Round/Step (+ last
sign-bytes and signature) and refuses any regression; returns the cached
signature when asked to re-sign identical bytes (`signBytesHRS:225-275`).
The `Signer` seam (`:74-76`) keeps HSM/remote-signer integration open.
"""

from __future__ import annotations

import json
import os
import threading
from dataclasses import dataclass

from tendermint_tpu.crypto import PrivKey, PubKey, gen_priv_key
from tendermint_tpu.types.errors import ErrDoubleSign
from tendermint_tpu.types.heartbeat import Heartbeat
from tendermint_tpu.types.proposal import Proposal
from tendermint_tpu.types.vote import VOTE_TYPE_PRECOMMIT, VOTE_TYPE_PREVOTE, Vote

STEP_NONE = 0
STEP_PROPOSE = 1
STEP_PREVOTE = 2
STEP_PRECOMMIT = 3


def vote_to_step(vote: Vote) -> int:
    if vote.type == VOTE_TYPE_PREVOTE:
        return STEP_PREVOTE
    if vote.type == VOTE_TYPE_PRECOMMIT:
        return STEP_PRECOMMIT
    raise ValueError(f"unknown vote type {vote.type}")


class Signer:
    """Pluggable signing backend (reference `Signer` interface :74-76)."""

    def sign(self, msg: bytes) -> bytes:
        raise NotImplementedError

    def pub_key(self) -> PubKey:
        raise NotImplementedError


class DefaultSigner(Signer):
    def __init__(self, priv_key: PrivKey):
        self._priv_key = priv_key

    def sign(self, msg: bytes) -> bytes:
        return self._priv_key.sign(msg)

    def pub_key(self) -> PubKey:
        return self._priv_key.pub_key


@dataclass
class _LastSignState:
    height: int = 0
    round: int = 0
    step: int = STEP_NONE
    signature: bytes = b""
    sign_bytes: bytes = b""


class PrivValidator:
    """In-memory priv validator; see `PrivValidatorFS` for the file-backed one."""

    def __init__(self, priv_key: PrivKey, signer: Signer | None = None):
        self._signer = signer or DefaultSigner(priv_key)
        self.pub_key = self._signer.pub_key()
        self.address = self.pub_key.address
        self._last = _LastSignState()
        self._lock = threading.RLock()

    # -- persistence hook (overridden by PrivValidatorFS) --------------------

    def _save(self) -> None:
        pass

    # -- HRS guard -----------------------------------------------------------

    def _check_hrs(self, height: int, round_: int, step: int, sign_bytes: bytes) -> bytes | None:
        """Returns a cached signature to reuse, or None to proceed with a
        fresh signature. Raises ErrDoubleSign on any regression/conflict
        (reference `signBytesHRS:225-275`)."""
        last = self._last
        if (height, round_, step) < (last.height, last.round, last.step):
            raise ErrDoubleSign(
                f"sign regression: have {last.height}/{last.round}/{last.step}, "
                f"asked {height}/{round_}/{step}"
            )
        if (height, round_, step) == (last.height, last.round, last.step):
            if sign_bytes == last.sign_bytes:
                return last.signature  # idempotent re-sign
            raise ErrDoubleSign(
                f"conflicting sign-bytes at {height}/{round_}/{step}"
            )
        return None

    def _sign_and_record(self, height: int, round_: int, step: int, sign_bytes: bytes) -> bytes:
        with self._lock:
            cached = self._check_hrs(height, round_, step, sign_bytes)
            if cached is not None:
                return cached
            sig = self._signer.sign(sign_bytes)
            self._last = _LastSignState(
                height=height, round=round_, step=step, signature=sig, sign_bytes=sign_bytes
            )
            self._save()
            return sig

    # -- public signing API ---------------------------------------------------

    def _timestamp_tolerant_cached(
        self, kind: str, height: int, round_: int, step: int, sign_bytes: bytes
    ) -> tuple[int, bytes] | None:
        """If we already signed the SAME (h, r, s) payload differing only
        in its timestamp — the crash-replay case: a restarted node
        rebuilds the vote/proposal with a fresh clock — return the
        cached (timestamp, signature) so the caller re-emits the
        original artifact instead of double-signing or wedging
        (reference checkVotesOnlyDifferByTimestamp; without this a
        crashed solo validator can never re-vote at its in-progress
        height and halts forever)."""
        last = self._last
        if (height, round_, step) != (last.height, last.round, last.step):
            return None
        if not last.sign_bytes or sign_bytes == last.sign_bytes:
            return None
        try:
            now_doc = json.loads(sign_bytes)
            last_doc = json.loads(last.sign_bytes)
        except ValueError:
            return None
        last_ts = last_doc.get(kind, {}).get("timestamp")
        if last_ts is None:
            return None
        now_doc.get(kind, {}).pop("timestamp", None)
        last_doc.get(kind, {}).pop("timestamp", None)
        if now_doc != last_doc:
            return None
        return last_ts, last.signature

    def sign_vote(self, chain_id: str, vote: Vote) -> Vote:
        from dataclasses import replace as _replace

        with self._lock:
            step = vote_to_step(vote)
            cached = self._timestamp_tolerant_cached(
                "vote", vote.height, vote.round, step, vote.sign_bytes(chain_id)
            )
            if cached is not None:
                ts, sig = cached
                return _replace(vote, timestamp=ts, signature=sig)
            sig = self._sign_and_record(
                vote.height, vote.round, step, vote.sign_bytes(chain_id)
            )
        return vote.with_signature(sig)

    def sign_proposal(self, chain_id: str, proposal: Proposal) -> Proposal:
        from dataclasses import replace as _replace

        with self._lock:
            cached = self._timestamp_tolerant_cached(
                "proposal",
                proposal.height,
                proposal.round,
                STEP_PROPOSE,
                proposal.sign_bytes(chain_id),
            )
            if cached is not None:
                ts, sig = cached
                return _replace(proposal, timestamp=ts, signature=sig)
            sig = self._sign_and_record(
                proposal.height, proposal.round, STEP_PROPOSE,
                proposal.sign_bytes(chain_id),
            )
        return proposal.with_signature(sig)

    def sign_heartbeat(self, chain_id: str, hb: Heartbeat) -> Heartbeat:
        # No HRS check for heartbeats (reference `SignHeartbeat`).
        return hb.with_signature(self._signer.sign(hb.sign_bytes(chain_id)))

    def __repr__(self) -> str:
        return f"PrivValidator({self.address.hex()[:12]})"


class PrivValidatorFS(PrivValidator):
    """File-backed priv validator with atomic persistence
    (reference `types/priv_validator.go:163-183`)."""

    def __init__(self, file_path: str, priv_key: PrivKey, last: _LastSignState | None = None):
        super().__init__(priv_key)
        self._priv_key = priv_key
        self.file_path = file_path
        if last is not None:
            self._last = last

    def _save(self) -> None:
        doc = {
            "address": self.address.hex(),
            "pub_key": self.pub_key.data.hex(),
            "priv_key_seed": self._priv_key.seed.hex(),
            "last_height": self._last.height,
            "last_round": self._last.round,
            "last_step": self._last.step,
            "last_signature": self._last.signature.hex(),
            "last_signbytes": self._last.sign_bytes.hex(),
        }
        tmp = self.file_path + ".tmp"
        # signing key material: owner-only from creation (reference
        # WriteFileAtomic 0600), never umask-dependent
        fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
        with os.fdopen(fd, "w") as f:
            json.dump(doc, f, indent=2, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.file_path)  # atomic on POSIX

    def save(self) -> None:
        with self._lock:
            self._save()

    def reset(self) -> None:
        """Danger: forget sign state (test/ops only, reference `Reset`)."""
        with self._lock:
            self._last = _LastSignState()
            self._save()

    @classmethod
    def load(cls, file_path: str) -> "PrivValidatorFS":
        with open(file_path) as f:
            doc = json.load(f)
        last = _LastSignState(
            height=doc["last_height"],
            round=doc["last_round"],
            step=doc["last_step"],
            signature=bytes.fromhex(doc["last_signature"]),
            sign_bytes=bytes.fromhex(doc["last_signbytes"]),
        )
        return cls(file_path, PrivKey(bytes.fromhex(doc["priv_key_seed"])), last)

    @classmethod
    def load_or_gen(cls, file_path: str, seed: bytes | None = None) -> "PrivValidatorFS":
        """Reference `LoadOrGenPrivValidatorFS types/priv_validator.go:131-140`."""
        if os.path.exists(file_path):
            return cls.load(file_path)
        pv = cls(file_path, gen_priv_key(seed))
        pv.save()
        return pv
