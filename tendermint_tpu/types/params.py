"""On-chain consensus parameters, carried in the GenesisDoc
(reference `types/params.go:13-35`, ADR-005)."""

from __future__ import annotations

from dataclasses import dataclass, field

from tendermint_tpu.types.errors import ValidationError

MAX_BLOCK_SIZE_BYTES = 22020096  # 21MB hard cap (reference params.go Validate)


@dataclass
class BlockSizeParams:
    max_bytes: int = 22020096
    max_txs: int = 10000  # reference config.go:379 MaxBlockSizeTxs
    max_gas: int = -1


@dataclass
class TxSizeParams:
    max_bytes: int = 10240
    max_gas: int = -1


@dataclass
class BlockGossipParams:
    block_part_size_bytes: int = 4096  # reference types/params.go:20-25


@dataclass
class EvidenceParams:
    """On-chain evidence policy (reference `types/params.go` EvidenceParams).
    `max_age` is in heights: evidence older than `committing_height -
    max_age` is expired — unverifiable against any retained validator
    set, so pools prune it and proposals must not carry it. `max_evidence`
    caps the evidence list of one block (DoS bound on block size and on
    the per-block 2-lane verify batches)."""

    max_age: int = 100000
    max_evidence: int = 64


@dataclass
class ConsensusParams:
    block_size: BlockSizeParams = field(default_factory=BlockSizeParams)
    tx_size: TxSizeParams = field(default_factory=TxSizeParams)
    block_gossip: BlockGossipParams = field(default_factory=BlockGossipParams)
    evidence: EvidenceParams = field(default_factory=EvidenceParams)

    def validate(self) -> None:
        if self.block_size.max_bytes <= 0 or self.block_size.max_bytes > MAX_BLOCK_SIZE_BYTES:
            raise ValidationError(f"invalid block max_bytes {self.block_size.max_bytes}")
        if self.block_gossip.block_part_size_bytes <= 0:
            raise ValidationError("block_part_size_bytes must be positive")
        if self.evidence.max_age <= 0:
            raise ValidationError("evidence max_age must be positive")
        if self.evidence.max_evidence < 0:
            raise ValidationError("evidence max_evidence must be >= 0")

    def to_dict(self) -> dict:
        return {
            "block_size": {
                "max_bytes": self.block_size.max_bytes,
                "max_txs": self.block_size.max_txs,
                "max_gas": self.block_size.max_gas,
            },
            "tx_size": {"max_bytes": self.tx_size.max_bytes, "max_gas": self.tx_size.max_gas},
            "block_gossip": {
                "block_part_size_bytes": self.block_gossip.block_part_size_bytes
            },
            "evidence": {
                "max_age": self.evidence.max_age,
                "max_evidence": self.evidence.max_evidence,
            },
        }

    @classmethod
    def from_dict(cls, d: dict) -> "ConsensusParams":
        p = cls()
        if "block_size" in d:
            b = d["block_size"]
            p.block_size = BlockSizeParams(
                max_bytes=b.get("max_bytes", p.block_size.max_bytes),
                max_txs=b.get("max_txs", p.block_size.max_txs),
                max_gas=b.get("max_gas", p.block_size.max_gas),
            )
        if "tx_size" in d:
            t = d["tx_size"]
            p.tx_size = TxSizeParams(
                max_bytes=t.get("max_bytes", p.tx_size.max_bytes),
                max_gas=t.get("max_gas", p.tx_size.max_gas),
            )
        if "block_gossip" in d:
            g = d["block_gossip"]
            p.block_gossip = BlockGossipParams(
                block_part_size_bytes=g.get(
                    "block_part_size_bytes", p.block_gossip.block_part_size_bytes
                )
            )
        if "evidence" in d:
            e = d["evidence"]
            p.evidence = EvidenceParams(
                max_age=e.get("max_age", p.evidence.max_age),
                max_evidence=e.get("max_evidence", p.evidence.max_evidence),
            )
        return p
