"""Domain types (reference layer 1, `types/` — SURVEY.md §1)."""

from tendermint_tpu.types.block import Block, Commit, Data, Header
from tendermint_tpu.types.block_id import BlockID
from tendermint_tpu.types.errors import (
    ErrDoubleSign,
    ErrVoteConflictingVotes,
    ErrVoteInvalidSignature,
    ErrVoteInvalidValidatorAddress,
    ErrVoteInvalidValidatorIndex,
    ErrVoteNonDeterministicSignature,
    ErrVoteUnexpectedStep,
    TMError,
    ValidationError,
    VoteError,
)
from tendermint_tpu.types.events import EventCache, EventSwitch
from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator
from tendermint_tpu.types.heartbeat import Heartbeat
from tendermint_tpu.types.params import ConsensusParams
from tendermint_tpu.types.part_set import DEFAULT_PART_SIZE, Part, PartSet, PartSetHeader
from tendermint_tpu.types.priv_validator import (
    STEP_NONE,
    STEP_PRECOMMIT,
    STEP_PREVOTE,
    STEP_PROPOSE,
    PrivValidator,
    PrivValidatorFS,
    Signer,
)
from tendermint_tpu.types.proposal import Proposal
from tendermint_tpu.types.tx import Tx, TxProof, Txs, tx_hash
from tendermint_tpu.types.validator_set import Validator, ValidatorSet
from tendermint_tpu.types.vote import (
    VOTE_TYPE_PRECOMMIT,
    VOTE_TYPE_PREVOTE,
    Vote,
    is_vote_type_valid,
)
from tendermint_tpu.types.vote_set import VoteSet

__all__ = [
    "Block",
    "BlockID",
    "Commit",
    "ConsensusParams",
    "Data",
    "DEFAULT_PART_SIZE",
    "ErrDoubleSign",
    "ErrVoteConflictingVotes",
    "ErrVoteInvalidSignature",
    "ErrVoteInvalidValidatorAddress",
    "ErrVoteInvalidValidatorIndex",
    "ErrVoteNonDeterministicSignature",
    "ErrVoteUnexpectedStep",
    "EventCache",
    "EventSwitch",
    "GenesisDoc",
    "GenesisValidator",
    "Header",
    "Heartbeat",
    "Part",
    "PartSet",
    "PartSetHeader",
    "PrivValidator",
    "PrivValidatorFS",
    "Proposal",
    "Signer",
    "STEP_NONE",
    "STEP_PRECOMMIT",
    "STEP_PREVOTE",
    "STEP_PROPOSE",
    "TMError",
    "Tx",
    "TxProof",
    "Txs",
    "tx_hash",
    "ValidationError",
    "Validator",
    "ValidatorSet",
    "Vote",
    "VoteError",
    "VoteSet",
    "VOTE_TYPE_PRECOMMIT",
    "VOTE_TYPE_PREVOTE",
    "is_vote_type_valid",
]
