"""Block, Header, Data, Commit (reference `types/block.go`).

Header hash = SimpleMerkle over the 9-field map (`types/block.go:173-188`);
Commit = precommits in validator-set order (`:222-233`); both tree builds are
batchable through the TreeHasher.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from tendermint_tpu.codec import Reader, Writer, encode_string, encode_uvarint
from tendermint_tpu.merkle import simple_hash_from_byte_slices, simple_hash_from_map
from tendermint_tpu.types.block_id import BlockID
from tendermint_tpu.types.errors import ValidationError
from tendermint_tpu.types.part_set import DEFAULT_PART_SIZE, PartSet
from tendermint_tpu.types.tx import Txs
from tendermint_tpu.types.vote import VOTE_TYPE_PRECOMMIT, Vote
from tendermint_tpu.utils.bit_array import BitArray


@dataclass
class Header:
    chain_id: str
    height: int
    time: int  # ns since epoch
    num_txs: int
    last_block_id: BlockID
    last_commit_hash: bytes = b""
    data_hash: bytes = b""
    validators_hash: bytes = b""
    app_hash: bytes = b""
    evidence_hash: bytes = b""

    def hash(self) -> bytes:
        """SimpleMerkle of the field map (reference `Header.Hash :173-188`).
        Returns b"" if validators_hash is unset (header not yet filled).
        The evidence commitment only enters the map when evidence is
        present, so evidence-free headers hash exactly as before the
        field existed (wire + hash backward compatibility in one rule).
        """
        if not self.validators_hash:
            return b""
        kvs = {
            "chain_id": encode_string(self.chain_id),
            "height": encode_uvarint(self.height),
            "time": encode_uvarint(self.time),
            "num_txs": encode_uvarint(self.num_txs),
            "last_block_id": self.last_block_id.encode(),
            "last_commit": self.last_commit_hash,
            "data": self.data_hash,
            "validators": self.validators_hash,
            "app": self.app_hash,
        }
        if self.evidence_hash:
            kvs["evidence"] = self.evidence_hash
        return simple_hash_from_map(kvs)

    def encode(self) -> bytes:
        w = (
            Writer()
            .string(self.chain_id)
            .uvarint(self.height)
            .svarint(self.time)
            .uvarint(self.num_txs)
            .raw(self.last_block_id.encode())
            .bytes(self.last_commit_hash)
            .bytes(self.data_hash)
            .bytes(self.validators_hash)
            .bytes(self.app_hash)
        )
        # trailing optional field: absent when empty, so evidence-free
        # headers are byte-identical to the pre-evidence encoding
        if self.evidence_hash:
            w.bytes(self.evidence_hash)
        return w.build()

    @classmethod
    def decode_from(cls, r: Reader) -> "Header":
        h = cls(
            chain_id=r.string(),
            height=r.uvarint(),
            time=r.svarint(),
            num_txs=r.uvarint(),
            last_block_id=BlockID.decode_from(r),
            last_commit_hash=r.bytes(),
            data_hash=r.bytes(),
            validators_hash=r.bytes(),
            app_hash=r.bytes(),
        )
        if not r.done():
            h.evidence_hash = r.bytes()
        return h


@dataclass
class Commit:
    """>2/3 precommits for a block, in validator-set order; absent votes are
    None (reference `types/block.go:222-233`)."""

    block_id: BlockID
    precommits: list[Vote | None] = field(default_factory=list)

    def height(self) -> int:
        v = self.first_precommit()
        return v.height if v else 0

    def round(self) -> int:
        v = self.first_precommit()
        return v.round if v else 0

    def first_precommit(self) -> Vote | None:
        for v in self.precommits:
            if v is not None:
                return v
        return None

    def size(self) -> int:
        return len(self.precommits)

    def bit_array(self) -> BitArray:
        ba = BitArray(len(self.precommits))
        for i, v in enumerate(self.precommits):
            ba.set(i, v is not None)
        return ba

    def is_commit(self) -> bool:
        return len(self.precommits) > 0

    def hash(self) -> bytes:
        return simple_hash_from_byte_slices(
            [v.encode() if v is not None else b"" for v in self.precommits]
        )

    def validate_basic(self) -> None:
        if self.block_id.is_zero():
            raise ValidationError("commit has zero BlockID")
        if not self.precommits:
            raise ValidationError("commit has no precommits")
        h, r = self.height(), self.round()
        for i, v in enumerate(self.precommits):
            if v is None:
                continue
            if v.type != VOTE_TYPE_PRECOMMIT:
                raise ValidationError(f"commit vote {i} is not a precommit")
            if v.height != h or v.round != r:
                raise ValidationError(f"commit vote {i} has wrong height/round")

    def encode(self) -> bytes:
        w = Writer().raw(self.block_id.encode()).uvarint(len(self.precommits))
        for v in self.precommits:
            w.bytes(v.encode() if v is not None else b"")
        return w.build()

    @classmethod
    def decode_from(cls, r: Reader) -> "Commit":
        block_id = BlockID.decode_from(r)
        n = r.uvarint()
        precommits: list[Vote | None] = []
        for _ in range(n):
            b = r.bytes()
            precommits.append(Vote.decode(b) if b else None)
        return cls(block_id=block_id, precommits=precommits)

    @classmethod
    def empty(cls) -> "Commit":
        return cls(block_id=BlockID.zero(), precommits=[])


@dataclass
class EvidenceData:
    """Misbehavior proofs committed in a block (reference
    `types/block.go` EvidenceData). Hash = Merkle root over the encoded
    evidence (`Header.evidence_hash`); empty lists hash to b"" so
    evidence-free blocks are unchanged."""

    evidence: list = field(default_factory=list)

    def hash(self, hasher=None) -> bytes:
        from tendermint_tpu.types.evidence import evidence_hash

        return evidence_hash(self.evidence, hasher)

    def __len__(self) -> int:
        return len(self.evidence)

    def __iter__(self):
        return iter(self.evidence)

    def encode(self) -> bytes:
        w = Writer().uvarint(len(self.evidence))
        for ev in self.evidence:
            w.bytes(ev.encode())
        return w.build()

    @classmethod
    def decode_from(cls, r: Reader) -> "EvidenceData":
        from tendermint_tpu.types.evidence import decode_evidence

        n = r.uvarint()
        return cls(evidence=[decode_evidence(r.bytes()) for _ in range(n)])


@dataclass
class Data:
    txs: Txs = field(default_factory=Txs)

    def hash(self, hasher=None) -> bytes:
        return self.txs.hash(hasher)

    def encode(self) -> bytes:
        w = Writer().uvarint(len(self.txs))
        for tx in self.txs:
            w.bytes(tx)
        return w.build()

    @classmethod
    def decode_from(cls, r: Reader) -> "Data":
        n = r.uvarint()
        return cls(txs=Txs(r.bytes() for _ in range(n)))


@dataclass
class Block:
    header: Header
    data: Data
    last_commit: Commit
    evidence: EvidenceData = field(default_factory=EvidenceData)

    @classmethod
    def make_block(
        cls,
        height: int,
        chain_id: str,
        txs: Txs,
        last_commit: Commit,
        last_block_id: BlockID,
        time: int,
        validators_hash: bytes,
        app_hash: bytes,
        hasher=None,
        evidence: list | None = None,
    ) -> "Block":
        """Build + fill a proposal block (reference `types/block.go:26-45`)."""
        block = cls(
            header=Header(
                chain_id=chain_id,
                height=height,
                time=time,
                num_txs=len(txs),
                last_block_id=last_block_id,
                app_hash=app_hash,
                validators_hash=validators_hash,
            ),
            data=Data(txs=txs),
            last_commit=last_commit,
            evidence=EvidenceData(evidence=list(evidence) if evidence else []),
        )
        block.fill_header(hasher)
        return block

    def fill_header(self, hasher=None) -> None:
        if not self.header.last_commit_hash:
            self.header.last_commit_hash = self.last_commit.hash()
        if not self.header.data_hash:
            self.header.data_hash = self.data.hash(hasher)
        if not self.header.evidence_hash:
            self.header.evidence_hash = self.evidence.hash(hasher)

    def hash(self) -> bytes:
        return self.header.hash()

    def make_part_set(self, part_size: int = DEFAULT_PART_SIZE, hasher=None) -> PartSet:
        return PartSet.from_data(self.encode(), part_size, hasher)

    def hash_to(self, other_hash: bytes) -> bool:
        h = self.hash()
        return bool(h) and h == other_hash

    def validate_basic(self, hasher=None) -> None:
        """Cheap structural checks (reference `ValidateBasic :48-85`)."""
        if self.header.height < 1:
            raise ValidationError("block height must be >= 1")
        if self.header.num_txs != len(self.data.txs):
            raise ValidationError("header num_txs != len(txs)")
        if self.header.height > 1 and not self.last_commit.precommits:
            raise ValidationError("block at height > 1 missing last_commit")
        if self.header.last_commit_hash != self.last_commit.hash():
            raise ValidationError("last_commit_hash mismatch")
        if self.header.data_hash != self.data.hash(hasher):
            raise ValidationError("data_hash mismatch")
        if self.header.evidence_hash != self.evidence.hash(hasher):
            raise ValidationError("evidence_hash mismatch")
        for ev in self.evidence:
            ev.validate_basic()

    def encode(self) -> bytes:
        w = (
            Writer()
            .bytes(self.header.encode())
            .bytes(self.data.encode())
            .bytes(self.last_commit.encode())
        )
        # trailing optional section (mirrors Header.evidence_hash):
        # evidence-free blocks keep the legacy 3-field wire form, so
        # stored history and older peers decode unchanged
        if len(self.evidence):
            w.bytes(self.evidence.encode())
        return w.build()

    @classmethod
    def decode(cls, data: bytes) -> "Block":
        r = Reader(data)
        header = Header.decode_from(Reader(r.bytes()))
        d = Data.decode_from(Reader(r.bytes()))
        lc = Commit.decode_from(Reader(r.bytes()))
        evidence = (
            EvidenceData.decode_from(Reader(r.bytes()))
            if not r.done()
            else EvidenceData()
        )
        r.expect_done()
        return cls(header=header, data=d, last_commit=lc, evidence=evidence)

    def block_id(self, part_size: int = DEFAULT_PART_SIZE) -> BlockID:
        return BlockID(hash=self.hash(), parts_header=self.make_part_set(part_size).header)

    def __str__(self) -> str:
        return f"Block{{h={self.header.height} txs={self.header.num_txs} {self.hash().hex()[:12]}}}"
