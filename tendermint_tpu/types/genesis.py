"""GenesisDoc (reference `types/genesis.go`): the chain's initial conditions."""

from __future__ import annotations

import json
import time as _time
from dataclasses import dataclass, field

from tendermint_tpu.crypto import PubKey
from tendermint_tpu.types.errors import ValidationError
from tendermint_tpu.types.params import ConsensusParams
from tendermint_tpu.types.validator_set import Validator, ValidatorSet


@dataclass
class GenesisValidator:
    pub_key: PubKey
    power: int
    name: str = ""

    def to_validator(self) -> Validator:
        return Validator(
            address=self.pub_key.address, pub_key=self.pub_key, voting_power=self.power
        )


@dataclass
class GenesisDoc:
    chain_id: str
    genesis_time: int = 0  # ns since epoch
    consensus_params: ConsensusParams = field(default_factory=ConsensusParams)
    validators: list[GenesisValidator] = field(default_factory=list)
    app_hash: bytes = b""
    app_options: dict = field(default_factory=dict)

    def validate_and_complete(self) -> None:
        """Reference `GenesisDoc` validation (`types/genesis.go:56`)."""
        if not self.chain_id:
            raise ValidationError("genesis doc must include non-empty chain_id")
        self.consensus_params.validate()
        if not self.validators:
            raise ValidationError("genesis doc must include at least one validator")
        for v in self.validators:
            if v.power < 0:
                raise ValidationError("genesis validator with negative power")
        if self.genesis_time == 0:
            self.genesis_time = _time.time_ns()

    def validator_set(self) -> ValidatorSet:
        return ValidatorSet([v.to_validator() for v in self.validators])

    def validator_hash(self) -> bytes:
        return self.validator_set().hash()

    # -- JSON persistence -----------------------------------------------------

    def to_json(self) -> str:
        return json.dumps(
            {
                "chain_id": self.chain_id,
                "genesis_time": self.genesis_time,
                "consensus_params": self.consensus_params.to_dict(),
                "validators": [
                    {"pub_key": v.pub_key.data.hex(), "power": v.power, "name": v.name}
                    for v in self.validators
                ],
                "app_hash": self.app_hash.hex(),
                "app_options": self.app_options,
            },
            indent=2,
            sort_keys=True,
        )

    @classmethod
    def from_json(cls, s: str) -> "GenesisDoc":
        d = json.loads(s)
        doc = cls(
            chain_id=d["chain_id"],
            genesis_time=d.get("genesis_time", 0),
            consensus_params=ConsensusParams.from_dict(d.get("consensus_params", {})),
            validators=[
                GenesisValidator(
                    pub_key=PubKey(bytes.fromhex(v["pub_key"])),
                    power=v["power"],
                    name=v.get("name", ""),
                )
                for v in d.get("validators", [])
            ],
            app_hash=bytes.fromhex(d.get("app_hash", "")),
            app_options=d.get("app_options", {}),
        )
        doc.validate_and_complete()
        return doc

    def save_as(self, path: str) -> None:
        with open(path, "w") as f:
            f.write(self.to_json())

    @classmethod
    def from_file(cls, path: str) -> "GenesisDoc":
        with open(path) as f:
            return cls.from_json(f.read())
