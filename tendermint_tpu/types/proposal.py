"""Proposal: the proposer's signed offer of a block for a round
(reference `types/proposal.go`). POLRound/POLBlockID carry proof-of-lock
info for the lock/unlock safety rules (`consensus/state.go:963-1053`).
"""

from __future__ import annotations

from dataclasses import dataclass, replace

from tendermint_tpu.codec import Reader, Writer, canonical_dumps
from tendermint_tpu.types.block_id import BlockID
from tendermint_tpu.types.part_set import PartSetHeader


@dataclass(frozen=True)
class Proposal:
    height: int
    round: int
    block_parts_header: PartSetHeader
    pol_round: int  # -1 if no proof-of-lock
    pol_block_id: BlockID
    timestamp: int  # ns
    signature: bytes = b""

    def sign_bytes(self, chain_id: str) -> bytes:
        return canonical_dumps(
            {
                "chain_id": chain_id,
                "proposal": {
                    "height": self.height,
                    "round": self.round,
                    "block_parts_header": {
                        "total": self.block_parts_header.total,
                        "hash": self.block_parts_header.hash,
                    },
                    "pol_round": self.pol_round,
                    "pol_block_id": self.pol_block_id.to_dict(),
                    "timestamp": self.timestamp,
                },
            }
        )

    def with_signature(self, sig: bytes) -> "Proposal":
        return replace(self, signature=sig)

    def encode(self) -> bytes:
        return (
            Writer()
            .uvarint(self.height)
            .uvarint(self.round)
            .raw(self.block_parts_header.encode())
            .svarint(self.pol_round)
            .raw(self.pol_block_id.encode())
            .svarint(self.timestamp)
            .bytes(self.signature)
            .build()
        )

    @classmethod
    def decode_from(cls, r: Reader) -> "Proposal":
        return cls(
            height=r.uvarint(),
            round=r.uvarint(),
            block_parts_header=PartSetHeader.decode_from(r),
            pol_round=r.svarint(),
            pol_block_id=BlockID.decode_from(r),
            timestamp=r.svarint(),
            signature=r.bytes(),
        )

    @classmethod
    def decode(cls, data: bytes) -> "Proposal":
        r = Reader(data)
        p = cls.decode_from(r)
        r.expect_done()
        return p

    def __str__(self) -> str:
        return f"Proposal{{{self.height}/{self.round} parts={self.block_parts_header.total} pol={self.pol_round}}}"
