"""Trust anchoring: never restore a snapshot whose app_hash is unproven.

A snapshot of height H claims an app_hash. That hash is carried by the
header at H+1 (app_hash in header H+1 is the app state after block H),
so the syncing node fetches the FullCommit (header + commit + valset)
for H+1 from the serving peers and certifies it through
`certifiers/certifier.py` before touching a single chunk:

* the configured trust root — (height, header hash) from config, the
  operator's out-of-band social-consensus input, exactly the light
  client's subjective initialization — pins which chain we are on;
* from the trust root's validator set, `DynamicCertifier` walks to the
  snapshot height: unchanged valsets certify directly (batched device
  signature verification), changed ones must be vouched for by >2/3 of
  the trusted set (`verify_commit_any`);
* the certified header's app_hash must equal the manifest's, and its
  validators_hash must match the validator set the restored state will
  run consensus with.

A commit that fails any step rejects the snapshot — the node keeps
discovering rather than trusting an unverified app_hash.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

from tendermint_tpu.certifiers.certifier import DynamicCertifier, FullCommit
from tendermint_tpu.types.errors import ValidationError
from tendermint_tpu.types.validator_set import ValidatorSet


@dataclass
class TrustOptions:
    """Operator-configured trust root (reference light-client TrustOptions).

    `height`/`hash_` pin one known-good header; 0/empty falls back to
    trusting the genesis validator set (fine for young chains, weak once
    the valset has rotated). `trust_period_ns` bounds how stale the
    anchoring header may be (0 disables the freshness check — in-process
    tests use deterministic genesis times far in the past)."""

    height: int = 0
    hash_: bytes = b""
    trust_period_ns: int = 0

    @classmethod
    def from_config(cls, cfg) -> "TrustOptions":
        return cls(
            height=cfg.trust_height,
            hash_=bytes.fromhex(cfg.trust_hash) if cfg.trust_hash else b"",
            trust_period_ns=int(cfg.trust_period_s * 1e9),
        )


class TrustAnchor:
    """Verifies snapshot manifests against the light-client trust chain."""

    def __init__(
        self,
        chain_id: str,
        base_validators: ValidatorSet,
        options: TrustOptions | None = None,
        verifier=None,
        now_ns=None,
    ) -> None:
        self.chain_id = chain_id
        self.base_validators = base_validators
        self.options = options or TrustOptions()
        self.verifier = verifier
        self._now_ns = now_ns or (lambda: time.time_ns())

    def anchor_height(self, snapshot_height: int) -> int:
        """The header height that proves a snapshot's app_hash."""
        return snapshot_height + 1

    def verify_pin(self, fc: FullCommit) -> None:
        """Check a fetched FullCommit against the configured trust root.
        The pin is trusted by CONFIG (subjective initialization): its
        header hash must equal the operator's, and the carried valset
        must be the one the header names — no signature check can
        strengthen a root the operator already chose."""
        opts = self.options
        if fc.height() != opts.height:
            raise ValidationError(
                f"trust pin wants height {opts.height}, got {fc.height()}"
            )
        fc.validate_basic(self.chain_id)
        if fc.header.hash() != opts.hash_:
            raise ValidationError(
                f"header hash at trust height {opts.height} is "
                f"{fc.header.hash().hex()[:16]}, pinned {opts.hash_.hex()[:16]}"
            )

    def verify_snapshot(
        self,
        manifest,
        anchor_fc: FullCommit,
        pin_fc: FullCommit | None = None,
    ) -> None:
        """Certify `anchor_fc` (the FullCommit at manifest.height + 1)
        and bind the manifest to it. Raises ValidationError (or a
        certifier error subclass) on any failure."""
        if manifest.chain_id != self.chain_id:
            raise ValidationError(
                f"snapshot for chain {manifest.chain_id!r}, want {self.chain_id!r}"
            )
        if anchor_fc.height() != self.anchor_height(manifest.height):
            raise ValidationError(
                f"anchor commit at {anchor_fc.height()}, "
                f"want {self.anchor_height(manifest.height)}"
            )
        opts = self.options
        if opts.height > 0:
            if manifest.height < opts.height:
                raise ValidationError(
                    f"snapshot height {manifest.height} below trust root {opts.height}"
                )
            if pin_fc is None:
                raise ValidationError("trust root configured but no pin commit fetched")
            self.verify_pin(pin_fc)
            cert = DynamicCertifier(
                self.chain_id,
                pin_fc.validators,
                opts.height,
                self.verifier,
                consumer="statesync",
            )
        else:
            cert = DynamicCertifier(
                self.chain_id,
                self.base_validators,
                0,
                self.verifier,
                consumer="statesync",
            )
        if opts.trust_period_ns > 0:
            age = self._now_ns() - anchor_fc.header.time
            if age > opts.trust_period_ns:
                raise ValidationError(
                    f"anchoring header is {age / 1e9:.0f}s old, "
                    f"trust period {opts.trust_period_ns / 1e9:.0f}s"
                )
        # certify: same valset verifies directly (one batched device
        # call); a changed set must carry >2/3 of the trusted set's
        # signatures (DynamicCertifier.update / verify_commit_any)
        if anchor_fc.header.validators_hash == cert.validators.hash():
            cert.certify(anchor_fc)
        else:
            cert.update(anchor_fc)
        # the certified header vouches for the snapshot's app state ...
        if anchor_fc.header.app_hash != manifest.app_hash:
            raise ValidationError(
                f"certified app_hash {anchor_fc.header.app_hash.hex()[:16]} != "
                f"manifest app_hash {manifest.app_hash.hex()[:16]}"
            )

    def verify_restored_state(self, state, anchor_fc: FullCommit) -> None:
        """Post-restore check: the decoded state must be the one the
        certified header names — a chunk payload that verifies against
        the root but decodes to a different chain/valset/app_hash is a
        manifest-level forgery."""
        if state.chain_id != self.chain_id:
            raise ValidationError("restored state has wrong chain id")
        if state.app_hash != anchor_fc.header.app_hash:
            raise ValidationError("restored state app_hash not certified")
        # state.validators is the set for height H+1 — exactly the set
        # the anchoring header (at H+1) must name
        if state.validators.hash() != anchor_fc.header.validators_hash:
            raise ValidationError("restored validator set not certified")
