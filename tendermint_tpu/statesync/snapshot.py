"""SnapshotStore: periodic app+state snapshots as Merkle-verified chunks.

A snapshot of height H packs three things into one payload:

* the consensus `State` at H (canonical JSON — what a restored node
  boots from),
* the app's opaque state bytes (via the `snapshot_state()` hook on
  `abci.Application`),
* a short block-store tail ending at H (blocks + their seen commits),
  so the restored node can reconstruct LastCommit for consensus and
  serve recent blocks without re-downloading them.

The payload splits into fixed-size chunks; the chunk tree hashes
through the TreeHasher seam (`services/hasher.py`) so snapshot creation
AND restore-side verification ride the batched device Merkle path on
TPU, with the circuit breaker degrading to host hashlib
(`services/resilient.py`). Manifests + chunks persist in a `db/kv.py`
store under prefixed keys.
"""

from __future__ import annotations

import json
import time

from tendermint_tpu.codec.binary import Reader, Writer
from tendermint_tpu.db.kv import DB
from tendermint_tpu.merkle.simple import leaf_hash
from tendermint_tpu.telemetry import metrics as _metrics
from tendermint_tpu.types.block import Block, Commit
from tendermint_tpu.types.errors import ValidationError

SNAPSHOT_FORMAT = 1
DEFAULT_CHUNK_SIZE = 64 * 1024
DEFAULT_TAIL_LEN = 2  # blocks carried alongside the state
MAX_CHUNKS = 1 << 20  # manifest sanity cap (64 GiB at the default size)

_MANIFEST_PREFIX = b"ssm:"
_CHUNK_PREFIX = b"ssc:"


class SnapshotManifest:
    """Everything a syncing peer needs to fetch + verify one snapshot.

    `root` commits to the ordered chunk list (SimpleMerkle over raw
    chunk bytes); `chunk_hashes` are the per-chunk leaf hashes so single
    chunks can be blamed on arrival. `app_hash` is the state's app hash
    at `height` — trusted only once the header at `height + 1` carrying
    it passes certifier anchoring (`statesync/trust.py`).
    """

    def __init__(
        self,
        height: int,
        chunks: int,
        chunk_size: int,
        root: bytes,
        chunk_hashes: list[bytes],
        app_hash: bytes,
        chain_id: str,
        payload_len: int,
        format: int = SNAPSHOT_FORMAT,
    ) -> None:
        self.height = height
        self.format = format
        self.chunks = chunks
        self.chunk_size = chunk_size
        self.root = root
        self.chunk_hashes = chunk_hashes
        self.app_hash = app_hash
        self.chain_id = chain_id
        self.payload_len = payload_len

    def key(self) -> tuple[int, int]:
        return (self.height, self.format)

    def validate_basic(self) -> None:
        if self.height < 1:
            raise ValidationError(f"snapshot height {self.height} < 1")
        if not (0 < self.chunks <= MAX_CHUNKS):
            raise ValidationError(f"snapshot chunk count {self.chunks} out of range")
        if len(self.chunk_hashes) != self.chunks:
            raise ValidationError(
                f"manifest lists {len(self.chunk_hashes)} chunk hashes "
                f"for {self.chunks} chunks"
            )
        if self.chunk_size < 1 or self.payload_len < 1:
            raise ValidationError("bad snapshot chunk size / payload length")
        if self.payload_len > self.chunks * self.chunk_size:
            raise ValidationError("payload length exceeds chunk capacity")
        if not self.root:
            raise ValidationError("manifest has no root hash")

    def verify_root(self, hasher) -> None:
        """Bind the per-chunk hash list to the root via one batched tree
        reduction (device path on TPU). A manifest whose list does not
        fold to its root could blame honest chunks later."""
        if _root_from_leaf_hashes(self.chunk_hashes, hasher) != self.root:
            raise ValidationError("manifest chunk hashes do not match root")

    def to_json(self) -> bytes:
        return json.dumps(
            {
                "height": self.height,
                "format": self.format,
                "chunks": self.chunks,
                "chunk_size": self.chunk_size,
                "root": self.root.hex(),
                "chunk_hashes": [h.hex() for h in self.chunk_hashes],
                "app_hash": self.app_hash.hex(),
                "chain_id": self.chain_id,
                "payload_len": self.payload_len,
            },
            sort_keys=True,
        ).encode()

    @classmethod
    def from_json(cls, raw: bytes) -> "SnapshotManifest":
        d = json.loads(raw.decode())
        return cls(
            height=d["height"],
            format=d["format"],
            chunks=d["chunks"],
            chunk_size=d["chunk_size"],
            root=bytes.fromhex(d["root"]),
            chunk_hashes=[bytes.fromhex(h) for h in d["chunk_hashes"]],
            app_hash=bytes.fromhex(d["app_hash"]),
            chain_id=d["chain_id"],
            payload_len=d["payload_len"],
        )


def _root_from_leaf_hashes(hashes: list[bytes], hasher) -> bytes:
    if hasher is not None:
        return hasher.root_from_hashes(hashes)
    from tendermint_tpu.merkle.simple import simple_hash_from_hashes

    return simple_hash_from_hashes(hashes)


def _chunk_leaf_hashes(chunks: list[bytes], hasher) -> list[bytes]:
    """Leaf hashes for raw chunks, batched through the hasher seam when
    it exposes one (device SHA-256 over all chunks in one launch)."""
    if hasher is not None and hasattr(hasher, "leaf_hashes"):
        return hasher.leaf_hashes(chunks)
    return [leaf_hash(c) for c in chunks]


# -- payload ------------------------------------------------------------------


def build_payload(state, app_state: bytes, tail: list[tuple[Block, Commit]]) -> bytes:
    """Serialize (state, app bytes, block tail) into the chunkable blob."""
    w = Writer().bytes(state.to_json()).bytes(app_state)
    w.uvarint(len(tail))
    for block, seen_commit in tail:
        w.bytes(block.encode()).bytes(seen_commit.encode())
    return w.build()


def decode_payload(payload: bytes) -> tuple[bytes, bytes, list[tuple[Block, Commit]]]:
    """-> (state_json, app_state, [(block, seen_commit), ...])."""
    r = Reader(payload)
    state_json = r.bytes()
    app_state = r.bytes()
    tail = []
    for _ in range(r.uvarint()):
        block = Block.decode(r.bytes())
        commit = Commit.decode_from(Reader(r.bytes()))
        tail.append((block, commit))
    return state_json, app_state, tail


def split_chunks(payload: bytes, chunk_size: int) -> list[bytes]:
    return [payload[i : i + chunk_size] for i in range(0, len(payload), chunk_size)]


def verify_chunks_async(
    manifest: SnapshotManifest, chunks: list[bytes], hasher=None, queue=None
):
    """The chunk-verify gate as a dispatch HANDLE: the whole-set leaf
    hashing launches through the hasher's async seam (device batch on
    TPU, breaker-guarded), the per-chunk comparison + root fold run at
    the join. The restore path overlaps payload decoding with the
    in-flight hash launch and joins this gate before applying anything.
    The handle's `.result()` raises the same ValidationErrors the
    blocking `verify_chunks` did."""
    from tendermint_tpu.services.dispatch import CompletedHandle

    if len(chunks) != manifest.chunks:
        return CompletedHandle(
            exc=ValidationError(
                f"have {len(chunks)} chunks, manifest wants {manifest.chunks}"
            )
        )
    t0 = time.perf_counter()

    def _gate(hashes) -> bool:
        try:
            for i, (got, want) in enumerate(zip(hashes, manifest.chunk_hashes)):
                if got != want:
                    raise ValidationError(f"chunk {i} hash mismatch")
            if _root_from_leaf_hashes(hashes, hasher) != manifest.root:
                raise ValidationError("chunk tree does not fold to manifest root")
            return True
        finally:
            _metrics.STATESYNC_CHUNK_VERIFY_SECONDS.observe(
                time.perf_counter() - t0
            )

    if hasher is not None and hasattr(hasher, "leaf_hashes_async"):
        return hasher.leaf_hashes_async(chunks, queue=queue).then(_gate)
    hashes = _chunk_leaf_hashes(chunks, hasher)
    try:
        return CompletedHandle(_gate(hashes))
    except ValidationError as e:
        return CompletedHandle(exc=e)


def verify_chunks(
    manifest: SnapshotManifest, chunks: list[bytes], hasher=None
) -> None:
    """Blocking chunk-set verification (submit + join of the async
    gate): leaf-hash every chunk and fold the tree in one device batch
    (host hashlib behind the breaker otherwise). Raises ValidationError
    naming the first bad chunk index."""
    verify_chunks_async(manifest, chunks, hasher).result()


# -- store --------------------------------------------------------------------


class SnapshotStore:
    """Persists manifests + chunks; takes new snapshots from live state.

    Keys: `ssm:<height>:<format>` -> manifest JSON,
    `ssc:<height>:<format>:<index>` -> raw chunk bytes. Heights are
    zero-padded so `iterate` returns snapshots in height order.
    """

    def __init__(
        self,
        db: DB,
        hasher=None,
        chunk_size: int = DEFAULT_CHUNK_SIZE,
        keep_recent: int = 2,
    ) -> None:
        self._db = db
        self.hasher = hasher
        self.chunk_size = chunk_size
        self.keep_recent = keep_recent

    @staticmethod
    def _manifest_key(height: int, format: int) -> bytes:
        return _MANIFEST_PREFIX + b"%020d:%d" % (height, format)

    @staticmethod
    def _chunk_key(height: int, format: int, index: int) -> bytes:
        return _CHUNK_PREFIX + b"%020d:%d:%d" % (height, format, index)

    # -- creation ------------------------------------------------------------

    def take(
        self,
        state,
        app_state: bytes,
        block_store=None,
        tail_len: int = DEFAULT_TAIL_LEN,
    ) -> SnapshotManifest:
        """Snapshot the given state (at `state.last_block_height`).

        `app_state` comes from the app's `snapshot_state()` hook; the
        block tail is read from `block_store` (bounded by its base, so
        pruned stores snapshot what they still have).
        """
        height = state.last_block_height
        if height < 1:
            raise ValidationError("cannot snapshot before the first block")
        t0 = time.perf_counter()
        tail: list[tuple[Block, Commit]] = []
        if block_store is not None and tail_len > 0:
            lo = max(height - tail_len + 1, getattr(block_store, "base", 1), 1)
            for h in range(lo, height + 1):
                block = block_store.load_block(h)
                seen = block_store.load_seen_commit(h)
                if block is None or seen is None:
                    raise ValidationError(f"block store missing tail height {h}")
                tail.append((block, seen))
        payload = build_payload(state, app_state, tail)
        chunks = split_chunks(payload, self.chunk_size)
        chunk_hashes = _chunk_leaf_hashes(chunks, self.hasher)
        root = _root_from_leaf_hashes(chunk_hashes, self.hasher)
        manifest = SnapshotManifest(
            height=height,
            chunks=len(chunks),
            chunk_size=self.chunk_size,
            root=root,
            chunk_hashes=chunk_hashes,
            app_hash=state.app_hash,
            chain_id=state.chain_id,
            payload_len=len(payload),
        )
        for i, chunk in enumerate(chunks):
            self._db.set(self._chunk_key(height, manifest.format, i), chunk)
        # manifest last: a crash mid-write leaves orphan chunks, never a
        # manifest advertising chunks that are not there
        self._db.set_sync(
            self._manifest_key(height, manifest.format), manifest.to_json()
        )
        self.prune_snapshots()
        _metrics.STATESYNC_SNAPSHOT_SECONDS.observe(time.perf_counter() - t0)
        _metrics.STATESYNC_SNAPSHOTS_TAKEN.inc()
        return manifest

    # -- access --------------------------------------------------------------

    def list_manifests(self) -> list[SnapshotManifest]:
        """All stored manifests, ascending by height."""
        out = []
        for _k, raw in self._db.iterate(_MANIFEST_PREFIX):
            out.append(SnapshotManifest.from_json(raw))
        return out

    def get_manifest(self, height: int, format: int = SNAPSHOT_FORMAT):
        raw = self._db.get(self._manifest_key(height, format))
        return SnapshotManifest.from_json(raw) if raw is not None else None

    def load_chunk(
        self, height: int, format: int, index: int
    ) -> bytes | None:
        return self._db.get(self._chunk_key(height, format, index))

    def corrupt_chunk(
        self, height: int, format: int = SNAPSHOT_FORMAT, index: int = 0
    ) -> bool:
        """Chaos hook (tests, tools/statesync_demo.py): flip every byte
        of a STORED chunk so a peer serves garbage without knowing."""
        key = self._chunk_key(height, format, index)
        chunk = self._db.get(key)
        if chunk is None:
            return False
        self._db.set(key, bytes(b ^ 0xFF for b in chunk))
        return True

    def delete_snapshot(self, height: int, format: int = SNAPSHOT_FORMAT) -> None:
        m = self.get_manifest(height, format)
        self._db.delete(self._manifest_key(height, format))
        if m is not None:
            for i in range(m.chunks):
                self._db.delete(self._chunk_key(height, format, i))

    def prune_snapshots(self) -> None:
        """Keep only the newest `keep_recent` snapshots."""
        manifests = self.list_manifests()
        for m in manifests[: -self.keep_recent or None]:
            self.delete_snapshot(m.height, m.format)
