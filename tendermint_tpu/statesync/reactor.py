"""StateSyncReactor: snapshot discovery + chunk sync over its own p2p
channel (0x60).

Protocol (all frames `uvarint tag || fields`, like the blockchain
channel):

* `snapshots_request` -> `snapshots_response` (the serving node's
  manifests, newest last);
* `chunk_request(height, format, index)` -> `chunk_response(...)` or
  `no_chunk(...)`;
* `commit_request(height)` -> `commit_response(height, FullCommit?)` —
  the light-client material (header + commit + valset) the trust anchor
  (`statesync/trust.py`) certifies before any chunk is applied.

The client side runs a sync routine: discover -> anchor trust -> fetch
chunks (per-peer in-flight limits, timeout/requeue via ChunkPool — the
`blockchain/pool.py` requester pattern) -> batch-verify the chunk tree
through the device hasher -> restore state/app/block-tail -> hand off
to fast-sync via `on_synced`.

The server side answers from the SnapshotStore and, when wired to the
consensus event bus, takes a new snapshot every `snapshot_interval`
committed heights.
"""

from __future__ import annotations

import logging
import threading
import time

from tendermint_tpu.certifiers.certifier import FullCommit
from tendermint_tpu.codec.binary import Reader, Writer
from tendermint_tpu.merkle.simple import leaf_hash
from tendermint_tpu.p2p.connection import ChannelDescriptor
from tendermint_tpu.p2p.peer import Peer
from tendermint_tpu.p2p.switch import Reactor
from tendermint_tpu.statesync.snapshot import (
    SnapshotManifest,
    SnapshotStore,
    decode_payload,
    verify_chunks_async,
)
from tendermint_tpu.statesync.trust import TrustAnchor
from tendermint_tpu.telemetry import metrics as _metrics
from tendermint_tpu.types.errors import ValidationError
from tendermint_tpu.utils.log import kv, logger

STATESYNC_CHANNEL = 0x60

_MSG_SNAPSHOTS_REQUEST = 0x01
_MSG_SNAPSHOTS_RESPONSE = 0x02
_MSG_CHUNK_REQUEST = 0x03
_MSG_CHUNK_RESPONSE = 0x04
_MSG_NO_CHUNK = 0x05
_MSG_COMMIT_REQUEST = 0x06
_MSG_COMMIT_RESPONSE = 0x07

_SYNC_TICK_S = 0.05
_MAX_OFFERED_SNAPSHOTS = 8  # per peer; drop floods

_log = logger("statesync")


def decode_message(payload: bytes):
    r = Reader(payload)
    tag = r.uvarint()
    if tag == _MSG_SNAPSHOTS_REQUEST:
        return ("snapshots_request", None)
    if tag == _MSG_SNAPSHOTS_RESPONSE:
        manifests = [
            SnapshotManifest.from_json(r.bytes()) for _ in range(r.uvarint())
        ]
        return ("snapshots_response", manifests)
    if tag == _MSG_CHUNK_REQUEST:
        return ("chunk_request", (r.uvarint(), r.uvarint(), r.uvarint()))
    if tag == _MSG_CHUNK_RESPONSE:
        return ("chunk_response", (r.uvarint(), r.uvarint(), r.uvarint(), r.bytes()))
    if tag == _MSG_NO_CHUNK:
        return ("no_chunk", (r.uvarint(), r.uvarint(), r.uvarint()))
    if tag == _MSG_COMMIT_REQUEST:
        return ("commit_request", r.uvarint())
    if tag == _MSG_COMMIT_RESPONSE:
        height = r.uvarint()
        raw = r.bytes()
        return ("commit_response", (height, FullCommit.decode(raw) if raw else None))
    raise ValueError(f"unknown statesync message tag {tag:#x}")


def _enc_snapshots_response(manifests: list[SnapshotManifest]) -> bytes:
    w = Writer().uvarint(_MSG_SNAPSHOTS_RESPONSE).uvarint(len(manifests))
    for m in manifests:
        w.bytes(m.to_json())
    return w.build()


def _enc_chunk_request(height: int, format: int, index: int) -> bytes:
    return (
        Writer()
        .uvarint(_MSG_CHUNK_REQUEST)
        .uvarint(height)
        .uvarint(format)
        .uvarint(index)
        .build()
    )


def _enc_chunk_response(height: int, format: int, index: int, data: bytes) -> bytes:
    return (
        Writer()
        .uvarint(_MSG_CHUNK_RESPONSE)
        .uvarint(height)
        .uvarint(format)
        .uvarint(index)
        .bytes(data)
        .build()
    )


def _enc_no_chunk(height: int, format: int, index: int) -> bytes:
    return (
        Writer()
        .uvarint(_MSG_NO_CHUNK)
        .uvarint(height)
        .uvarint(format)
        .uvarint(index)
        .build()
    )


def _enc_commit_request(height: int) -> bytes:
    return Writer().uvarint(_MSG_COMMIT_REQUEST).uvarint(height).build()


def _enc_commit_response(height: int, fc: FullCommit | None) -> bytes:
    return (
        Writer()
        .uvarint(_MSG_COMMIT_RESPONSE)
        .uvarint(height)
        .bytes(fc.encode() if fc is not None else b"")
        .build()
    )


class ChunkPool:
    """Chunk-request bookkeeping: per-peer in-flight limits with
    timeout/requeue of stalled requests (the `blockchain/pool.py`
    requester pattern applied to snapshot chunk indices)."""

    def __init__(
        self,
        n_chunks: int,
        inflight_per_peer: int = 4,
        request_timeout_s: float = 10.0,
        time_fn=time.monotonic,
    ) -> None:
        self.n_chunks = n_chunks
        self.inflight_per_peer = inflight_per_peer
        self.request_timeout_s = request_timeout_s
        self._time_fn = time_fn
        self._lock = threading.Lock()
        self._peers: set[str] = set()
        self._requests: dict[int, tuple[str, float]] = {}  # idx -> (peer, sent_at)
        self._chunks: dict[int, bytes] = {}

    def add_peer(self, peer_id: str) -> None:
        with self._lock:
            self._peers.add(peer_id)

    def remove_peer(self, peer_id: str) -> None:
        """Forget the peer; its in-flight chunk requests requeue."""
        with self._lock:
            self._peers.discard(peer_id)
            for i in [i for i, (p, _) in self._requests.items() if p == peer_id]:
                del self._requests[i]

    def num_peers(self) -> int:
        with self._lock:
            return len(self._peers)

    def schedule(self, now: float | None = None) -> tuple[list[tuple[str, int]], list[str]]:
        """One tick -> (requests to send, peers to evict). A request
        older than the timeout evicts its peer (a peer advertising a
        snapshot it never serves must not stall the restore) and its
        chunk reassigns to the survivors in the same tick."""
        now = now if now is not None else self._time_fn()
        out: list[tuple[str, int]] = []
        evict: list[str] = []
        with self._lock:
            for i, (p, sent_at) in list(self._requests.items()):
                if now - sent_at > self.request_timeout_s:
                    if p in self._peers and p not in evict:
                        evict.append(p)
            for p in evict:
                self._peers.discard(p)
                for i in [i for i, (q, _) in self._requests.items() if q == p]:
                    del self._requests[i]
            if not self._peers:
                return out, evict
            loads = {p: 0 for p in self._peers}
            for p, _ in self._requests.values():
                if p in loads:
                    loads[p] += 1
            for i in range(self.n_chunks):
                if i in self._chunks or i in self._requests:
                    continue
                peer = min(
                    (p for p in loads if loads[p] < self.inflight_per_peer),
                    key=lambda p: loads[p],
                    default=None,
                )
                if peer is None:
                    break
                loads[peer] += 1
                self._requests[i] = (peer, now)
                out.append((peer, i))
        return out, evict

    def add_chunk(self, peer_id: str, index: int, data: bytes) -> bool:
        """Accept a response only for an index requested from that peer."""
        with self._lock:
            req = self._requests.get(index)
            if req is None or req[0] != peer_id:
                return False
            del self._requests[index]
            self._chunks[index] = data
        return True

    def requeue(self, index: int) -> None:
        """A delivered chunk failed its hash check: fetch it again."""
        with self._lock:
            self._chunks.pop(index, None)
            self._requests.pop(index, None)

    def is_complete(self) -> bool:
        with self._lock:
            return len(self._chunks) == self.n_chunks

    def chunks(self) -> list[bytes]:
        with self._lock:
            return [self._chunks[i] for i in range(self.n_chunks)]


class _Candidate:
    __slots__ = ("manifest", "peers", "root_checked")

    def __init__(self, manifest: SnapshotManifest) -> None:
        self.manifest = manifest
        self.peers: set[str] = set()
        self.root_checked = False


class StateSyncReactor(Reactor):
    """Serves snapshots to peers; optionally bootstraps from them.

    `on_synced(state_or_none)` fires once when the sync routine ends:
    with the restored State on success, with None when state sync gave
    up (no snapshots / all rejected) and the node should fall back to
    plain fast-sync from its current state.
    """

    def __init__(
        self,
        snapshot_store: SnapshotStore,
        block_store,
        state,
        sync: bool = False,
        trust_anchor: TrustAnchor | None = None,
        state_db=None,
        app_restore_fn=None,
        app_snapshot_fn=None,
        on_synced=None,
        hasher=None,
        snapshot_interval: int = 0,
        retain_blocks: int = 0,
        discovery_time_s: float = 3.0,
        chunk_request_timeout_s: float = 10.0,
        chunk_inflight_per_peer: int = 4,
        commit_timeout_s: float = 5.0,
        giveup_time_s: float = 45.0,
    ) -> None:
        super().__init__()
        self.snapshot_store = snapshot_store
        self.block_store = block_store
        self.state = state  # serving-side: load_validators / chain identity
        self.sync = sync
        self.trust_anchor = trust_anchor
        self.state_db = state_db
        self.app_restore_fn = app_restore_fn
        self.app_snapshot_fn = app_snapshot_fn
        self.on_synced = on_synced
        self.hasher = hasher
        self.snapshot_interval = snapshot_interval
        self.retain_blocks = retain_blocks
        self.discovery_time_s = discovery_time_s
        self.chunk_request_timeout_s = chunk_request_timeout_s
        self.chunk_inflight_per_peer = chunk_inflight_per_peer
        self.commit_timeout_s = commit_timeout_s
        self.giveup_time_s = giveup_time_s

        self._running = False
        self._thread: threading.Thread | None = None
        self._lock = threading.Lock()
        self._candidates: dict[tuple, _Candidate] = {}
        self._rejected: set[tuple] = set()
        self._pool: ChunkPool | None = None
        self._active_key: tuple | None = None
        # commit_request correlation: height -> (event, [FullCommit|None])
        self._commit_waits: dict[int, tuple[threading.Event, list]] = {}
        # Resume the snapshot cadence from the persisted store: a
        # restarted serving node advertises its existing snapshots
        # immediately and only re-takes once the interval elapses past
        # the newest persisted height (instead of re-taking at the next
        # interval as if it had never snapshotted).
        manifests = snapshot_store.list_manifests()
        self._last_snapshot_height = manifests[-1].height if manifests else 0
        self.restored_state = None  # set on successful restore; fast-sync
        # then advances it IN PLACE — read restored_manifest for the
        # height the snapshot itself landed at
        self.restored_manifest: SnapshotManifest | None = None

    # -- reactor interface -------------------------------------------------

    def get_channels(self) -> list[ChannelDescriptor]:
        # chunk frames are big (chunk_size + framing); keep the queue
        # shallow so backpressure reaches the scheduler, not the switch
        return [
            ChannelDescriptor(
                STATESYNC_CHANNEL, priority=3, send_queue_capacity=32
            )
        ]

    def on_start(self) -> None:
        self._running = True
        if self.sync:
            self._thread = threading.Thread(
                target=self._sync_routine, name="statesync", daemon=True
            )
            self._thread.start()

    def on_stop(self) -> None:
        self._running = False

    def add_peer(self, peer: Peer) -> None:
        if self.sync:
            peer.try_send(
                STATESYNC_CHANNEL, Writer().uvarint(_MSG_SNAPSHOTS_REQUEST).build()
            )

    def remove_peer(self, peer: Peer, reason) -> None:
        with self._lock:
            for cand in self._candidates.values():
                cand.peers.discard(peer.id)
        if self._pool is not None:
            self._pool.remove_peer(peer.id)

    def receive(self, chan_id: int, peer: Peer, payload: bytes) -> None:
        kind, arg = decode_message(payload)
        if kind == "snapshots_request":
            manifests = self.snapshot_store.list_manifests()
            peer.try_send(STATESYNC_CHANNEL, _enc_snapshots_response(manifests))
        elif kind == "snapshots_response":
            self._on_snapshots(peer, arg)
        elif kind == "chunk_request":
            height, fmt, index = arg
            chunk = self.snapshot_store.load_chunk(height, fmt, index)
            if chunk is not None:
                _metrics.STATESYNC_CHUNKS_SERVED.inc()
                peer.try_send(
                    STATESYNC_CHANNEL, _enc_chunk_response(height, fmt, index, chunk)
                )
            else:
                peer.try_send(STATESYNC_CHANNEL, _enc_no_chunk(height, fmt, index))
        elif kind == "chunk_response":
            self._on_chunk(peer, *arg)
        elif kind == "no_chunk":
            height, fmt, index = arg
            if self._pool is not None and self._active_key is not None:
                if self._active_key[:2] == (height, fmt):
                    # the peer lied about having this snapshot
                    self._pool.remove_peer(peer.id)
        elif kind == "commit_request":
            peer.try_send(
                STATESYNC_CHANNEL,
                _enc_commit_response(arg, self._serve_commit(arg)),
            )
        elif kind == "commit_response":
            height, fc = arg
            with self._lock:
                wait = self._commit_waits.get(height)
            if wait is not None:
                wait[1].append(fc)
                wait[0].set()

    # -- serving side ------------------------------------------------------

    def _serve_commit(self, height: int) -> FullCommit | None:
        """FullCommit for `height` from local stores: header from the
        block meta, canonical commit (falling back to the seen commit),
        validators from the historical valset index."""
        meta = self.block_store.load_block_meta(height)
        if meta is None:
            return None
        commit = self.block_store.load_block_commit(height)
        if commit is None:
            commit = self.block_store.load_seen_commit(height)
        if commit is None:
            return None
        try:
            validators = self.state.load_validators(height)
        except ValidationError:
            return None
        return FullCommit(header=meta.header, commit=commit, validators=validators)

    def maybe_take_snapshot(self, state, app=None) -> SnapshotManifest | None:
        """Take a snapshot when the interval elapsed. Wired to the
        consensus EVENT_NEW_BLOCK listener (runs on the consensus thread
        right after commit, so state + app are at the same height)."""
        if self.snapshot_interval <= 0:
            return None
        height = state.last_block_height
        if height < self.snapshot_interval:
            return None
        if height - self._last_snapshot_height < self.snapshot_interval:
            return None
        snapshot_fn = self.app_snapshot_fn
        if snapshot_fn is None and app is not None:
            snapshot_fn = getattr(app, "snapshot_state", None)
        if snapshot_fn is None:
            return None
        app_state = snapshot_fn()
        if app_state is None:
            return None  # app opted out of snapshots
        self._last_snapshot_height = height
        manifest = self.snapshot_store.take(
            state, app_state, block_store=self.block_store
        )
        kv(
            _log,
            logging.INFO,
            "snapshot taken",
            height=height,
            chunks=manifest.chunks,
            root=manifest.root.hex()[:12],
        )
        self._maybe_prune_blocks(height)
        return manifest

    def _maybe_prune_blocks(self, height: int) -> None:
        """Retention-driven `BlockStore.prune` ([statesync]
        retain_blocks): runs right AFTER a snapshot lands so the chunked
        payload — not the block store — carries the history peers need;
        the store keeps a bounded `retain_blocks` tail for fast-sync
        serving and the next snapshot's block tail."""
        if self.retain_blocks <= 0 or not hasattr(self.block_store, "prune"):
            return
        retain_height = height - self.retain_blocks + 1
        if retain_height <= 1:
            return
        try:
            pruned = self.block_store.prune(retain_height)
        except Exception:
            logging.getLogger(__name__).exception("block-store prune failed")
            return
        if pruned:
            kv(
                _log,
                logging.INFO,
                "pruned block store",
                retain_height=retain_height,
                pruned=pruned,
                base=getattr(self.block_store, "base", None),
            )

    # -- syncing side: message handling ------------------------------------

    def _on_snapshots(self, peer: Peer, manifests: list[SnapshotManifest]) -> None:
        for m in manifests[:_MAX_OFFERED_SNAPSHOTS]:
            try:
                m.validate_basic()
            except ValidationError as e:
                if self.switch is not None:
                    self.switch.stop_peer_for_error(peer, f"bad snapshot offer: {e}")
                return
            key = (m.height, m.format, m.root)
            with self._lock:
                if key in self._rejected:
                    continue
                cand = self._candidates.get(key)
                if cand is None:
                    cand = self._candidates[key] = _Candidate(m)
                cand.peers.add(peer.id)
            if self._pool is not None and self._active_key == key:
                self._pool.add_peer(peer.id)

    def _on_chunk(
        self, peer: Peer, height: int, fmt: int, index: int, data: bytes
    ) -> None:
        pool, key = self._pool, self._active_key
        if pool is None or key is None or key[:2] != (height, fmt):
            return
        manifest = self._candidates[key].manifest
        if index >= manifest.chunks:
            return
        # cheap host check on arrival — single-chunk blame; the batched
        # device pass over the WHOLE set gates the actual restore
        if leaf_hash(data) != manifest.chunk_hashes[index]:
            _metrics.STATESYNC_CHUNKS.labels(result="corrupt").inc()
            pool.remove_peer(peer.id)
            pool.requeue(index)
            if self.switch is not None:
                self.switch.stop_peer_for_error(
                    peer, f"corrupt statesync chunk {index}@{height}"
                )
            return
        if pool.add_chunk(peer.id, index, data):
            _metrics.STATESYNC_CHUNKS.labels(result="ok").inc()

    # -- syncing side: the routine -----------------------------------------

    def _peers_by_id(self) -> dict[str, Peer]:
        if self.switch is None:
            return {}
        return {p.id: p for p in self.switch.peers()}

    def _request_commit(self, height: int) -> FullCommit | None:
        """Fetch the FullCommit at `height` from any candidate-serving
        peer (each peer gets one `commit_timeout_s` shot)."""
        ev = threading.Event()
        box: list = []
        with self._lock:
            self._commit_waits[height] = (ev, box)
            peer_ids = set()
            for cand in self._candidates.values():
                peer_ids |= cand.peers
        try:
            peers = self._peers_by_id()
            for pid in peer_ids:
                peer = peers.get(pid)
                if peer is None:
                    continue
                ev.clear()
                peer.try_send(STATESYNC_CHANNEL, _enc_commit_request(height))
                if ev.wait(self.commit_timeout_s) and box and box[-1] is not None:
                    return box[-1]
            return None
        finally:
            with self._lock:
                self._commit_waits.pop(height, None)

    def _pick_candidate(self) -> tuple | None:
        """Best un-rejected candidate: highest height with a live peer."""
        with self._lock:
            best = None
            for key, cand in self._candidates.items():
                if key in self._rejected or not cand.peers:
                    continue
                if self.trust_anchor is not None:
                    if cand.manifest.chain_id != self.trust_anchor.chain_id:
                        continue
                    if (
                        self.trust_anchor.options.height > 0
                        and cand.manifest.height < self.trust_anchor.options.height
                    ):
                        continue
                if best is None or key[0] > best[0]:
                    best = key
            return best

    def _reject(self, key: tuple, reason: str) -> None:
        with self._lock:
            self._rejected.add(key)
        _metrics.STATESYNC_SNAPSHOTS_REJECTED.inc()
        kv(
            _log,
            logging.WARNING,
            "snapshot rejected",
            height=key[0],
            root=key[2].hex()[:12],
            reason=reason[:120],
        )

    def _sync_routine(self) -> None:
        t_start = time.monotonic()
        deadline = t_start + self.giveup_time_s
        last_discover = 0.0
        try:
            while self._running:
                now = time.monotonic()
                if now > deadline:
                    kv(
                        _log,
                        logging.WARNING,
                        "state sync gave up",
                        waited_s=round(now - t_start, 1),
                    )
                    self._finish(None)
                    return
                if now - last_discover > self.discovery_time_s:
                    last_discover = now
                    if self.switch is not None:
                        self.switch.broadcast(
                            STATESYNC_CHANNEL,
                            Writer().uvarint(_MSG_SNAPSHOTS_REQUEST).build(),
                        )
                if now - t_start < self.discovery_time_s:
                    time.sleep(_SYNC_TICK_S)
                    continue  # let first offers arrive before committing
                key = self._pick_candidate()
                if key is None:
                    time.sleep(_SYNC_TICK_S)
                    continue
                state = self._attempt(key)
                if state is not None:
                    self._finish(state)
                    return
                time.sleep(_SYNC_TICK_S)
        except Exception:
            logging.getLogger(__name__).exception("state sync failed")
            self._finish(None)

    def _attempt(self, key: tuple) -> object | None:
        """Try one candidate end-to-end; None means rejected/failed (the
        routine keeps discovering)."""
        cand = self._candidates[key]
        manifest = cand.manifest
        t0 = time.perf_counter()
        # 1. trust anchoring BEFORE fetching a single chunk
        try:
            if self.trust_anchor is not None:
                pin_fc = None
                if self.trust_anchor.options.height > 0:
                    pin_fc = self._request_commit(self.trust_anchor.options.height)
                    if pin_fc is None:
                        self._reject(key, "no peer served the trust-root commit")
                        return None
                anchor_fc = self._request_commit(
                    self.trust_anchor.anchor_height(manifest.height)
                )
                if anchor_fc is None:
                    self._reject(key, "no peer served the anchoring commit")
                    return None
                self.trust_anchor.verify_snapshot(manifest, anchor_fc, pin_fc)
            else:
                anchor_fc = None
            # bind the per-chunk hash list to the root (one device batch)
            manifest.verify_root(self.hasher)
        except ValidationError as e:
            self._reject(key, f"trust anchoring failed: {e}")
            return None
        kv(
            _log,
            logging.INFO,
            "snapshot anchored",
            height=manifest.height,
            chunks=manifest.chunks,
        )
        # 2. chunk fetch with per-peer in-flight limits + requeue
        pool = ChunkPool(
            manifest.chunks,
            inflight_per_peer=self.chunk_inflight_per_peer,
            request_timeout_s=self.chunk_request_timeout_s,
        )
        for pid in set(cand.peers):
            pool.add_peer(pid)
        self._pool, self._active_key = pool, key
        try:
            fetch_deadline = time.monotonic() + self.giveup_time_s
            while self._running and not pool.is_complete():
                if time.monotonic() > fetch_deadline:
                    self._reject(key, "chunk fetch timed out")
                    return None
                if pool.num_peers() == 0:
                    self._reject(key, "no peers left serving the snapshot")
                    return None
                requests, evictions = pool.schedule()
                for pid in evictions:
                    _metrics.STATESYNC_CHUNKS.labels(result="timeout").inc()
                peers = self._peers_by_id()
                for pid, index in requests:
                    peer = peers.get(pid)
                    if peer is None:
                        pool.remove_peer(pid)
                        continue
                    peer.try_send(
                        STATESYNC_CHANNEL,
                        _enc_chunk_request(manifest.height, manifest.format, index),
                    )
                time.sleep(_SYNC_TICK_S)
            if not pool.is_complete():
                return None
            # 3. whole-set verification in one device batch — launched
            # as a dispatch handle so the payload decode (pure parsing,
            # applies nothing) overlaps the in-flight hash kernel; the
            # gate is joined BEFORE any restore side effect
            chunks = pool.chunks()
            try:
                gate = verify_chunks_async(manifest, chunks, self.hasher)
                payload = b"".join(chunks)[: manifest.payload_len]
                try:
                    decoded = decode_payload(payload)
                except Exception as decode_err:
                    try:
                        gate.result()  # release the handle's queue slot
                    except ValidationError:
                        pass
                    raise ValidationError(
                        f"snapshot payload undecodable: {decode_err}"
                    ) from decode_err
                gate.result()
                state = self._restore(manifest, decoded, anchor_fc)
            except ValidationError as e:
                self._reject(key, f"restore failed: {e}")
                _metrics.STATESYNC_RESTORES.labels(result="failed").inc()
                return None
            _metrics.STATESYNC_RESTORES.labels(result="ok").inc()
            _metrics.STATESYNC_RESTORE_SECONDS.observe(time.perf_counter() - t0)
            self.restored_manifest = manifest
            return state
        finally:
            self._pool, self._active_key = None, None

    def _restore(self, manifest: SnapshotManifest, decoded, anchor_fc):
        """Apply a fully-decoded, fully-VERIFIED chunk payload: state
        DB, app state, block-store tail. Only reached after the batched
        Merkle gate joined clean (decoding itself happens earlier,
        overlapped with the in-flight hash launch)."""
        from tendermint_tpu.state.state import State

        state_json, app_state, tail = decoded
        state = State.from_json(state_json, db=self.state_db)
        if state.last_block_height != manifest.height:
            raise ValidationError(
                f"snapshot state is at {state.last_block_height}, "
                f"manifest says {manifest.height}"
            )
        if self.trust_anchor is not None and anchor_fc is not None:
            self.trust_anchor.verify_restored_state(state, anchor_fc)
        if self.app_restore_fn is None:
            raise ValidationError("app does not support state restore")
        self.app_restore_fn(app_state)
        # seed the historical-valset index before the first save writes
        # a change-height pointer into history this node never stored
        state.save_validators_full()
        state.save()
        if tail and hasattr(self.block_store, "bootstrap"):
            self.block_store.bootstrap(tail)
        kv(
            _log,
            logging.INFO,
            "state restored",
            height=manifest.height,
            app_hash=state.app_hash.hex()[:12],
            tail_blocks=len(tail),
        )
        return state

    def _finish(self, state) -> None:
        self.sync = False
        self.restored_state = state
        if self.on_synced is not None:
            self.on_synced(state)
