"""State sync: snapshot store + chunk-sync reactor + trust anchoring.

A fresh node normally joins by fast-syncing every block
(`blockchain/reactor.py`) — O(chain length) replay. This subsystem lands
a node at a recent snapshot height instead: peers serve fixed-size
snapshot chunks whose Merkle tree is built AND verified through the
batched device hasher (`services/hasher.py`), and the snapshot's
app_hash is trusted only after its sealing commit passes the light-
client certifier (`certifiers/certifier.py`) from a configured trust
root. Fast-sync then takes over for the tail.
"""

from tendermint_tpu.statesync.snapshot import (
    SnapshotManifest,
    SnapshotStore,
    build_payload,
    decode_payload,
)
from tendermint_tpu.statesync.reactor import STATESYNC_CHANNEL, StateSyncReactor
from tendermint_tpu.statesync.trust import TrustAnchor, TrustOptions

__all__ = [
    "STATESYNC_CHANNEL",
    "SnapshotManifest",
    "SnapshotStore",
    "StateSyncReactor",
    "TrustAnchor",
    "TrustOptions",
    "build_payload",
    "decode_payload",
]
