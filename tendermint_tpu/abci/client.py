"""ABCI client + the three-connection proxy (reference `proxy/`).

`AppConns` owns consensus/mempool/query connections to one application
(`proxy/multi_app_conn.go:12-18`). The local client serializes access
with one mutex per client — matching the reference's in-proc
`localClient` — while separate connections keep mempool CheckTx from
blocking consensus DeliverTx and vice versa.

Async semantics: the reference pipelines `DeliverTxAsync` over a socket
and collects callbacks (`state/execution.go:50-101`). In-process, calls
are synchronous but the `*_async` names keep the pipelining seam: a
remote transport can reintroduce true overlap without changing callers.
"""

from __future__ import annotations

import threading
from typing import Callable

from tendermint_tpu.abci.application import Application
from tendermint_tpu.abci.types import Result, ResultInfo, ResultQuery, Validator


class _LocalClient:
    """Mutex-wrapped in-process app access (reference localClient)."""

    def __init__(self, app: Application, lock: threading.Lock) -> None:
        self._app = app
        self._lock = lock
        self.error: Exception | None = None

    # every call holds the shared app mutex


class AppConnQuery(_LocalClient):
    def echo_sync(self, msg: str) -> str:
        with self._lock:
            return self._app.echo(msg)

    def info_sync(self) -> ResultInfo:
        with self._lock:
            return self._app.info()

    def query_sync(self, path: str, data: bytes, height: int = 0, prove: bool = False) -> ResultQuery:
        with self._lock:
            return self._app.query(path, data, height, prove)


class AppConnMempool(_LocalClient):
    def check_tx_async(self, tx: bytes, cb: Callable[[Result], None] | None = None) -> Result:
        with self._lock:
            res = self._app.check_tx(tx)
        if cb is not None:
            cb(res)
        return res

    def flush_sync(self) -> None:
        pass

    def flush_async(self) -> None:
        pass


def _accepts_evidence(begin_block) -> bool:
    """True when an app's begin_block takes the evidence argument —
    legacy 2-arg overrides predate the evidence pipeline and must keep
    working without a TypeError probe on the hot path."""
    import inspect

    try:
        params = inspect.signature(begin_block).parameters
    except (TypeError, ValueError):
        return True  # exotic callables: assume the current interface
    if any(
        p.kind is inspect.Parameter.VAR_POSITIONAL
        or p.kind is inspect.Parameter.VAR_KEYWORD
        for p in params.values()
    ):
        return True
    return "evidence" in params


class AppConnConsensus(_LocalClient):
    def init_chain_sync(self, validators: list[Validator]) -> None:
        with self._lock:
            self._app.init_chain(validators)

    def begin_block_sync(self, block_hash: bytes, header, evidence=()) -> None:
        accepts = getattr(self, "_bb_accepts_evidence", None)
        if accepts is None:
            accepts = self._bb_accepts_evidence = _accepts_evidence(
                self._app.begin_block
            )
        with self._lock:
            if accepts:
                self._app.begin_block(block_hash, header, evidence=evidence)
            else:
                self._app.begin_block(block_hash, header)

    def deliver_tx_async(self, tx: bytes, cb: Callable[[Result], None] | None = None) -> Result:
        with self._lock:
            res = self._app.deliver_tx(tx)
        if cb is not None:
            cb(res)
        return res

    def end_block_sync(self, height: int) -> list[Validator]:
        with self._lock:
            return self._app.end_block(height)

    def commit_sync(self) -> Result:
        with self._lock:
            return self._app.commit()


class AppConns:
    """The three typed connections to one application."""

    def __init__(self, consensus: AppConnConsensus, mempool: AppConnMempool, query: AppConnQuery):
        self.consensus = consensus
        self.mempool = mempool
        self.query = query

    def close(self) -> None:
        """Release transport resources (no-op for in-proc conns; the
        socket creator's conns close their TCP links)."""
        for conn in (self.consensus, self.mempool, self.query):
            closer = getattr(conn, "close", None)
            if closer is not None:
                closer()


ClientCreator = Callable[[], AppConns]


def local_client_creator(app: Application) -> ClientCreator:
    """In-proc creator: three connections sharing one app mutex
    (reference `proxy/client.go:24-44` NewLocalClientCreator)."""

    def create() -> AppConns:
        lock = threading.Lock()
        return AppConns(
            AppConnConsensus(app, lock),
            AppConnMempool(app, lock),
            AppConnQuery(app, lock),
        )

    return create
