"""ABCI: the application/consensus process seam.

The reference talks to its application over the ABCI socket/gRPC
protocol through three logical connections (consensus/mempool/query,
`proxy/app_conn.go:11-41`). Here the same seam exists with an in-process
client (reference's local client) — a future gRPC transport slots in
behind `ClientCreator` without touching consumers.
"""

from tendermint_tpu.abci.types import (
    CodeType,
    Result,
    ResultInfo,
    ResultQuery,
    Validator as ABCIValidator,
    OK,
)
from tendermint_tpu.abci.application import Application
from tendermint_tpu.abci.client import (
    AppConnConsensus,
    AppConnMempool,
    AppConnQuery,
    AppConns,
    local_client_creator,
)

__all__ = [
    "Application",
    "AppConnConsensus",
    "AppConnMempool",
    "AppConnQuery",
    "AppConns",
    "ABCIValidator",
    "CodeType",
    "OK",
    "Result",
    "ResultInfo",
    "ResultQuery",
    "local_client_creator",
]
