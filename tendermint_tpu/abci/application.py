"""ABCI application base class (role of abci types.Application)."""

from __future__ import annotations

from tendermint_tpu.abci.types import Result, ResultInfo, ResultQuery, Validator


class Application:
    """Override what you need; defaults are no-op OK responses."""

    # -- query connection ----------------------------------------------------

    def echo(self, msg: str) -> str:
        return msg

    def info(self) -> ResultInfo:
        return ResultInfo()

    def set_option(self, key: str, value: str) -> str:
        return ""

    def query(self, path: str, data: bytes, height: int = 0, prove: bool = False) -> ResultQuery:
        return ResultQuery()

    # -- mempool connection --------------------------------------------------

    def check_tx(self, tx: bytes) -> Result:
        return Result()

    # -- consensus connection ------------------------------------------------

    def init_chain(self, validators: list[Validator]) -> None:
        pass

    def begin_block(self, block_hash: bytes, header, evidence=()) -> None:
        """`evidence` is the block's committed misbehavior proofs
        (`types/evidence.py` DuplicateVoteEvidence — the reference's
        ByzantineValidators); apps that slash override and inspect it.
        Legacy 2-arg overrides keep working: the client only passes the
        evidence kwarg to apps whose signature accepts it."""
        pass

    def deliver_tx(self, tx: bytes) -> Result:
        return Result()

    def end_block(self, height: int) -> list[Validator]:
        return []

    def commit(self) -> Result:
        """Returns the app hash for the next block header."""
        return Result()

    # -- state sync (optional) ----------------------------------------------

    def snapshot_state(self) -> bytes | None:
        """Serialize the committed app state for a snapshot
        (`statesync/snapshot.py`). None = this app opts out of serving
        snapshots; the state-sync reactor then never offers any."""
        return None

    def restore_state(self, data: bytes) -> None:
        """Adopt app state from a verified snapshot. Only called after
        the chunk tree AND the trust anchor checks passed."""
        raise NotImplementedError(f"{type(self).__name__} cannot restore state")
