"""Remote ABCI: run the application in its OWN process over sockets.

Fills the reference's `proxy/client.go:14-80` remote slot (socket
transport; the reference also offers gRPC). This is the framework's
process boundary — the node and the app (or a TPU sidecar service)
communicate over three independent connections (consensus, mempool,
query) exactly like the in-proc `local_client_creator`, so either
creator plugs into `proxy`-level call sites unchanged.

Wire format: 4-byte big-endian length prefix, then
`uvarint msg_type || payload` using the deterministic codec. Each
connection is serial request/response (the reference pipelines with
Flush barriers; our async seams are thread-side, so serial per-conn
keeps the same observable ordering guarantees).
"""

from __future__ import annotations

import socket
import threading

from tendermint_tpu.abci.application import Application
from tendermint_tpu.abci.types import Result, ResultInfo, ResultQuery, Validator
from tendermint_tpu.codec.binary import Reader, Writer
from tendermint_tpu.p2p.tcp import TcpEndpoint
from tendermint_tpu.p2p.transport import EndpointClosed

_MSG_ECHO = 0x01
_MSG_INFO = 0x02
_MSG_FLUSH = 0x03
_MSG_CHECK_TX = 0x04
_MSG_DELIVER_TX = 0x05
_MSG_BEGIN_BLOCK = 0x06
_MSG_END_BLOCK = 0x07
_MSG_COMMIT = 0x08
_MSG_QUERY = 0x09
_MSG_INIT_CHAIN = 0x0A

def _enc_validators(w: Writer, vals: list[Validator]) -> Writer:
    w.uvarint(len(vals))
    for v in vals:
        w.bytes(v.pub_key).uvarint(v.power)
    return w


def _dec_validators(r: Reader) -> list[Validator]:
    return [
        Validator(pub_key=r.bytes(), power=r.uvarint())
        for _ in range(r.uvarint())
    ]


# -- server (app side) --------------------------------------------------------


class ABCISocketServer:
    """Serve one Application to any number of node connections
    (the node opens three). App callbacks run under one lock — the same
    serialization the in-proc `local_client_creator` provides."""

    def __init__(self, app: Application, laddr: str) -> None:
        from tendermint_tpu.p2p.tcp import parse_laddr

        self.app = app
        self._lock = threading.Lock()
        host, port = parse_laddr(laddr)
        self._srv = socket.create_server((host, port))
        self.addr = self._srv.getsockname()
        self._conns: list[TcpEndpoint] = []
        self._running = True
        threading.Thread(target=self._accept_loop, name="abci-accept", daemon=True).start()

    @property
    def port(self) -> int:
        return self.addr[1]

    def stop(self) -> None:
        self._running = False
        try:
            self._srv.close()
        except OSError:
            pass
        for ep in list(self._conns):
            ep.close()  # unblocks serve threads parked in recv

    def _accept_loop(self) -> None:
        while self._running:
            try:
                sock, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(
                target=self._serve_conn,
                args=(sock,),
                name="abci-conn",
                daemon=True,
            ).start()

    def _serve_conn(self, sock: socket.socket) -> None:
        # same length-prefixed framing as the p2p transport (one frame
        # codec to maintain — TcpEndpoint)
        ep = TcpEndpoint(sock)
        self._conns.append(ep)
        try:
            while self._running:
                ep.send(self._handle(ep.recv()))
        except (EndpointClosed, TimeoutError, OSError):
            pass
        finally:
            ep.close()
            try:
                self._conns.remove(ep)
            except ValueError:
                pass

    def _handle(self, req: bytes) -> bytes:
        return handle_abci_request(self.app, self._lock, req)


def handle_abci_request(app: Application, lock: threading.Lock, req: bytes) -> bytes:
    """Dispatch one framed ABCI request to the app — shared by every
    remote transport (socket here, gRPC in `abci/grpc_transport.py`);
    the reference likewise serves one request codec over both
    (`proxy/client.go:14-80`)."""
    r = Reader(req)
    tag = r.uvarint()
    w = Writer()
    with lock:
        if tag == _MSG_ECHO:
            w.string(app.echo(r.string()))
        elif tag == _MSG_INFO:
            info = app.info()
            w.string(info.data).string(info.version)
            w.uvarint(info.last_block_height).bytes(info.last_block_app_hash)
        elif tag == _MSG_FLUSH:
            pass
        elif tag == _MSG_CHECK_TX:
            w.raw(app.check_tx(r.bytes()).encode())
        elif tag == _MSG_DELIVER_TX:
            w.raw(app.deliver_tx(r.bytes()).encode())
        elif tag == _MSG_BEGIN_BLOCK:
            from tendermint_tpu.abci.client import _accepts_evidence
            from tendermint_tpu.types.block import Header
            from tendermint_tpu.types.evidence import decode_evidence

            block_hash = r.bytes()
            header = Header.decode_from(Reader(r.bytes()))
            # trailing optional evidence section (absent from legacy
            # clients' requests — and hidden from legacy 2-arg apps)
            evidence = []
            if not r.done():
                evidence = [decode_evidence(r.bytes()) for _ in range(r.uvarint())]
            if _accepts_evidence(app.begin_block):
                app.begin_block(block_hash, header, evidence=evidence)
            else:
                app.begin_block(block_hash, header)
        elif tag == _MSG_END_BLOCK:
            _enc_validators(w, app.end_block(r.uvarint()))
        elif tag == _MSG_COMMIT:
            w.raw(app.commit().encode())
        elif tag == _MSG_QUERY:
            res = app.query(
                r.string(), r.bytes(), r.uvarint(), r.bool()
            )
            w.uvarint(res.code).svarint(res.index).bytes(res.key)
            w.bytes(res.value).bytes(res.proof).uvarint(res.height)
            w.string(res.log)
        elif tag == _MSG_INIT_CHAIN:
            app.init_chain(_dec_validators(r))
        else:
            raise ConnectionError(f"unknown abci message {tag:#x}")
    return w.build()


# -- client (node side) -------------------------------------------------------


class _SocketConn:
    def __init__(self, addr: str, timeout: float = 30.0) -> None:
        from tendermint_tpu.p2p.tcp import parse_laddr

        host, port = parse_laddr(addr)
        sock = socket.create_connection((host, port), timeout=timeout)
        sock.settimeout(None)
        self._ep = TcpEndpoint(sock)
        self._lock = threading.Lock()

    def call(self, payload: bytes) -> Reader:
        with self._lock:
            self._ep.send(payload)
            return Reader(self._ep.recv())

    def close(self) -> None:
        self._ep.close()


class _RemoteQuery:
    def __init__(self, conn: _SocketConn) -> None:
        self._conn = conn

    def close(self) -> None:
        self._conn.close()

    def echo_sync(self, msg: str) -> str:
        return self._conn.call(Writer().uvarint(_MSG_ECHO).string(msg).build()).string()

    def info_sync(self) -> ResultInfo:
        r = self._conn.call(Writer().uvarint(_MSG_INFO).build())
        return ResultInfo(
            data=r.string(),
            version=r.string(),
            last_block_height=r.uvarint(),
            last_block_app_hash=r.bytes(),
        )

    def query_sync(self, path: str, data: bytes, height: int = 0, prove: bool = False) -> ResultQuery:
        r = self._conn.call(
            Writer()
            .uvarint(_MSG_QUERY)
            .string(path)
            .bytes(data)
            .uvarint(height)
            .bool(prove)
            .build()
        )
        return ResultQuery(
            code=r.uvarint(),
            index=r.svarint(),
            key=r.bytes(),
            value=r.bytes(),
            proof=r.bytes(),
            height=r.uvarint(),
            log=r.string(),
        )


class _RemoteMempool:
    def __init__(self, conn: _SocketConn) -> None:
        self._conn = conn

    def close(self) -> None:
        self._conn.close()

    def check_tx_async(self, tx: bytes, cb=None) -> Result:
        r = self._conn.call(Writer().uvarint(_MSG_CHECK_TX).bytes(tx).build())
        res = _read_result(r)
        if cb is not None:
            cb(res)
        return res

    def flush_sync(self) -> None:
        self._conn.call(Writer().uvarint(_MSG_FLUSH).build())

    def flush_async(self) -> None:
        self.flush_sync()


def _read_result(r: Reader) -> Result:
    return Result(code=r.uvarint(), data=r.bytes(), log=r.string())


class _RemoteConsensus:
    def __init__(self, conn: _SocketConn) -> None:
        self._conn = conn

    def close(self) -> None:
        self._conn.close()

    def init_chain_sync(self, validators) -> None:
        self._conn.call(
            _enc_validators(Writer().uvarint(_MSG_INIT_CHAIN), list(validators)).build()
        )

    def begin_block_sync(self, block_hash: bytes, header, evidence=()) -> None:
        w = (
            Writer()
            .uvarint(_MSG_BEGIN_BLOCK)
            .bytes(block_hash)
            .bytes(header.encode())
        )
        if evidence:
            w.uvarint(len(evidence))
            for ev in evidence:
                w.bytes(ev.encode())
        self._conn.call(w.build())

    def deliver_tx_async(self, tx: bytes, cb=None) -> Result:
        res = _read_result(
            self._conn.call(Writer().uvarint(_MSG_DELIVER_TX).bytes(tx).build())
        )
        if cb is not None:
            cb(res)
        return res

    def end_block_sync(self, height: int):
        r = self._conn.call(Writer().uvarint(_MSG_END_BLOCK).uvarint(height).build())
        return _dec_validators(r)

    def commit_sync(self) -> Result:
        return _read_result(self._conn.call(Writer().uvarint(_MSG_COMMIT).build()))


def socket_client_creator(addr: str):
    """ClientCreator over the socket transport (reference
    `proxy/client.go` NewRemoteClientCreator): three independent
    connections to one app server, same AppConns shape as
    `local_client_creator`."""
    from tendermint_tpu.abci.client import AppConns

    def create() -> AppConns:
        return AppConns(
            consensus=_RemoteConsensus(_SocketConn(addr)),
            mempool=_RemoteMempool(_SocketConn(addr)),
            query=_RemoteQuery(_SocketConn(addr)),
        )

    return create
