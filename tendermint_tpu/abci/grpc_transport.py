"""Remote ABCI over gRPC — the reference's second remote transport
(`proxy/client.go:62-80` offers socket AND grpc client creators;
`abci` repo `server/grpc_server.go`).

Same framed request codec as the socket transport (`abci/socket.py`
`handle_abci_request`), carried as unary-unary gRPC calls via grpcio's
generic-handler API (no protoc build step — the pattern established by
`rpc/grpc_api.py`). The node opens three independent channels
(consensus, mempool, query), mirroring the socket creator.
"""

from __future__ import annotations

import threading
from concurrent import futures

from tendermint_tpu.abci.application import Application
from tendermint_tpu.abci.socket import (
    _RemoteConsensus,
    _RemoteMempool,
    _RemoteQuery,
    handle_abci_request,
)
from tendermint_tpu.codec.binary import Reader

_SERVICE = "tendermint_tpu.ABCIApplication"
_METHOD = f"/{_SERVICE}/Call"


def _identity(b: bytes) -> bytes:
    return b


class ABCIGrpcServer:
    """Serve one Application over gRPC (app-side process)."""

    def __init__(self, app: Application, laddr: str) -> None:
        import grpc

        from tendermint_tpu.p2p.tcp import parse_laddr

        self.app = app
        self._lock = threading.Lock()
        host, port = parse_laddr(laddr)
        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=8))
        handler = grpc.method_handlers_generic_handler(
            _SERVICE,
            {
                "Call": grpc.unary_unary_rpc_method_handler(
                    self._call,
                    request_deserializer=_identity,
                    response_serializer=_identity,
                )
            },
        )
        self._server.add_generic_rpc_handlers((handler,))
        self.port = self._server.add_insecure_port(f"{host}:{port}")
        if self.port == 0:
            raise OSError(f"ABCI gRPC bind failed for {laddr}")
        self._server.start()

    def _call(self, request: bytes, context) -> bytes:
        return handle_abci_request(self.app, self._lock, request)

    def stop(self) -> None:
        self._server.stop(grace=0.5)


class _GrpcConn:
    """One logical ABCI connection = one gRPC channel; satisfies the
    same .call/.close contract as `_SocketConn`, so the socket
    transport's _Remote* wrappers run unchanged over it."""

    def __init__(self, addr: str, timeout: float = 30.0) -> None:
        import grpc

        self._timeout = timeout
        self._channel = grpc.insecure_channel(addr)
        self._fn = self._channel.unary_unary(
            _METHOD,
            request_serializer=_identity,
            response_deserializer=_identity,
        )

    def call(self, payload: bytes) -> Reader:
        return Reader(self._fn(payload, timeout=self._timeout))

    def close(self) -> None:
        self._channel.close()


def grpc_client_creator(addr: str):
    """ClientCreator over gRPC (reference `proxy/client.go` grpc arm of
    NewRemoteClientCreator): three independent channels to one app
    server, same AppConns shape as the socket and in-proc creators."""
    from tendermint_tpu.abci.client import AppConns

    def create() -> AppConns:
        return AppConns(
            consensus=_RemoteConsensus(_GrpcConn(addr)),
            mempool=_RemoteMempool(_GrpcConn(addr)),
            query=_RemoteQuery(_GrpcConn(addr)),
        )

    return create
