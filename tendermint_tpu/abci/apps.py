"""Built-in example/test applications (reference `proxy/client.go:62-80`:
dummy = kvstore, persistent_dummy, counter, nilapp)."""

from __future__ import annotations

import json

from tendermint_tpu.abci.application import Application
from tendermint_tpu.abci.types import CodeType, Result, ResultInfo, ResultQuery, Validator
from tendermint_tpu.crypto.hashing import tmhash
from tendermint_tpu.db.kv import DB, MemDB


class KVStoreApp(Application):
    """The reference "dummy" app: `key=value` txs into a Merkle-ized KV.

    App hash = hash over sorted (key, value) pairs — deterministic and
    cheap; the reference uses an iavl tree, which is an app-side detail.
    """

    def __init__(self) -> None:
        self._data: dict[bytes, bytes] = {}
        self._height = 0

    def _app_hash(self) -> bytes:
        if not self._data:
            return b""
        acc = b""
        for k in sorted(self._data):
            acc = tmhash(acc + k + b"\x00" + self._data[k] + b"\x01")
        return acc

    def info(self) -> ResultInfo:
        return ResultInfo(
            data=f"{{\"size\":{len(self._data)}}}",
            last_block_height=self._height,
            last_block_app_hash=self._app_hash() if self._height else b"",
        )

    def _parse(self, tx: bytes) -> tuple[bytes, bytes]:
        if b"=" in tx:
            k, v = tx.split(b"=", 1)
        else:
            k = v = tx
        return k, v

    def check_tx(self, tx: bytes) -> Result:
        return Result()

    def deliver_tx(self, tx: bytes) -> Result:
        k, v = self._parse(tx)
        self._data[k] = v
        return Result()

    def end_block(self, height: int) -> list[Validator]:
        self._height = height
        return []

    def commit(self) -> Result:
        return Result(data=self._app_hash())

    def query(self, path: str, data: bytes, height: int = 0, prove: bool = False) -> ResultQuery:
        v = self._data.get(data)
        if v is None:
            return ResultQuery(log="does not exist", key=data)
        return ResultQuery(key=data, value=v, log="exists")

    # -- state sync ----------------------------------------------------------

    def snapshot_state(self) -> bytes:
        return json.dumps(
            {
                "height": self._height,
                "data": {k.hex(): v.hex() for k, v in sorted(self._data.items())},
            },
            sort_keys=True,
        ).encode()

    def restore_state(self, data: bytes) -> None:
        doc = json.loads(data.decode())
        self._height = doc["height"]
        self._data = {
            bytes.fromhex(k): bytes.fromhex(v) for k, v in doc["data"].items()
        }


class PersistentKVStoreApp(KVStoreApp):
    """KVStore persisted to a DB with validator-set changes via special
    txs `val:<pubkey_hex>/<power>` (reference persistent_dummy)."""

    VAL_PREFIX = b"val:"

    def __init__(self, db: DB | None = None) -> None:
        super().__init__()
        self._db = db if db is not None else MemDB()
        self._val_changes: list[Validator] = []
        self._load()

    def _load(self) -> None:
        raw = self._db.get(b"__state__")
        if raw is None:
            return
        doc = json.loads(raw.decode())
        self._height = doc["height"]
        self._data = {
            bytes.fromhex(k): bytes.fromhex(v) for k, v in doc["data"].items()
        }

    def deliver_tx(self, tx: bytes) -> Result:
        if tx.startswith(self.VAL_PREFIX):
            try:
                spec = tx[len(self.VAL_PREFIX) :].decode()
                pub_hex, power_s = spec.split("/")
                val = Validator(pub_key=bytes.fromhex(pub_hex), power=int(power_s))
            except ValueError as e:
                return Result(CodeType.ENCODING_ERROR, log=f"bad val tx: {e}")
            self._val_changes.append(val)
            return Result()
        return super().deliver_tx(tx)

    def end_block(self, height: int) -> list[Validator]:
        self._height = height
        changes, self._val_changes = self._val_changes, []
        return changes

    def commit(self) -> Result:
        doc = {
            "height": self._height,
            "data": {k.hex(): v.hex() for k, v in self._data.items()},
        }
        self._db.set_sync(b"__state__", json.dumps(doc, sort_keys=True).encode())
        return Result(data=self._app_hash())

    def restore_state(self, data: bytes) -> None:
        super().restore_state(data)
        self._db.set_sync(b"__state__", data)  # snapshot doc == persist doc


class CounterApp(Application):
    """The reference counter app: txs must be the next serial number."""

    def __init__(self, serial: bool = True) -> None:
        self.serial = serial
        self.hash_count = 0
        self.tx_count = 0

    def info(self) -> ResultInfo:
        return ResultInfo(data=f"{{\"hashes\":{self.hash_count},\"txs\":{self.tx_count}}}")

    def _tx_value(self, tx: bytes) -> int:
        return int.from_bytes(tx, "big") if tx else 0

    def check_tx(self, tx: bytes) -> Result:
        if self.serial:
            if len(tx) > 8:
                return Result(CodeType.ENCODING_ERROR, log=f"tx too big: {len(tx)}")
            if self._tx_value(tx) < self.tx_count:
                return Result(
                    CodeType.BAD_NONCE,
                    log=f"invalid nonce: got {self._tx_value(tx)}, expected >= {self.tx_count}",
                )
        return Result()

    def deliver_tx(self, tx: bytes) -> Result:
        if self.serial:
            if len(tx) > 8:
                return Result(CodeType.ENCODING_ERROR, log=f"tx too big: {len(tx)}")
            if self._tx_value(tx) != self.tx_count:
                return Result(
                    CodeType.BAD_NONCE,
                    log=f"invalid nonce: got {self._tx_value(tx)}, expected {self.tx_count}",
                )
        self.tx_count += 1
        return Result()

    def commit(self) -> Result:
        self.hash_count += 1
        if self.tx_count == 0:
            return Result()
        return Result(data=self.tx_count.to_bytes(8, "big"))

    def query(self, path: str, data: bytes, height: int = 0, prove: bool = False) -> ResultQuery:
        if path == "hash":
            return ResultQuery(value=str(self.hash_count).encode())
        if path == "tx":
            return ResultQuery(value=str(self.tx_count).encode())
        return ResultQuery(code=CodeType.UNAUTHORIZED, log=f"invalid query path {path}")


class NilApp(Application):
    """Accepts everything, stores nothing (reference nilapp)."""
