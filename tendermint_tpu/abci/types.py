"""ABCI message/result types (role of the abci repo's types package)."""

from __future__ import annotations

from dataclasses import dataclass


class CodeType:
    """Response codes (subset the node actually branches on)."""

    OK = 0
    INTERNAL_ERROR = 1
    ENCODING_ERROR = 2
    BAD_NONCE = 3
    UNAUTHORIZED = 4
    # Node-level (non-app) rejection: duplicate tx already in the mempool
    # cache (reference mempool.go:172-178 returns ErrTxInCache).
    TX_IN_CACHE = 5


@dataclass
class Result:
    """CheckTx/DeliverTx result: code + data + log."""

    code: int = CodeType.OK
    data: bytes = b""
    log: str = ""

    @property
    def is_ok(self) -> bool:
        return self.code == CodeType.OK

    def encode(self) -> bytes:
        from tendermint_tpu.codec.binary import encode_bytes, encode_string, encode_uvarint

        return encode_uvarint(self.code) + encode_bytes(self.data) + encode_string(self.log)

    @classmethod
    def decode_from(cls, data: bytes, offset: int = 0) -> tuple["Result", int]:
        from tendermint_tpu.codec.binary import decode_bytes, decode_string, decode_uvarint

        code, offset = decode_uvarint(data, offset)
        d, offset = decode_bytes(data, offset)
        log, offset = decode_string(data, offset)
        return cls(code, d, log), offset


def OK(data: bytes = b"", log: str = "") -> Result:
    return Result(CodeType.OK, data, log)


@dataclass
class ResultInfo:
    """Info response: the handshake reads last_block height/app-hash
    (reference `consensus/replay.go:199-204`)."""

    data: str = ""
    version: str = ""
    last_block_height: int = 0
    last_block_app_hash: bytes = b""


@dataclass
class ResultQuery:
    code: int = CodeType.OK
    index: int = -1
    key: bytes = b""
    value: bytes = b""
    proof: bytes = b""
    height: int = 0
    log: str = ""

    @property
    def is_ok(self) -> bool:
        return self.code == CodeType.OK


@dataclass
class Validator:
    """Validator-set diff entry flowing app->consensus via EndBlock
    (reference `state/execution.go:110-159`). power 0 removes."""

    pub_key: bytes = b""
    power: int = 0
