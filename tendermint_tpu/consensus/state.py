"""The BFT consensus state machine (reference `consensus/state.go`).

Architecture: an event-sourced core — ONE thread (`_receive_loop`) owns
the RoundState and serializes every input (peer message, internal
message, timeout tock) exactly like the reference's `receiveRoutine`
(`consensus/state.go:497-547`). Every input is WAL'd before processing.
Public methods only enqueue; reads take a snapshot under the state lock.

Transitions follow `consensus/state.go`: NewHeight → NewRound → Propose
→ Prevote → PrevoteWait → Precommit → PrecommitWait → Commit, with the
POL lock/unlock safety rules (`:963-1053`) and commit finalization
(`:1078-1243`). Signature verification inside VoteSet/verify_commit
routes through the BatchVerifier seam (TPU batch when available).

Test seams (reference `consensus/state.go:107-110` + common_test.go):
`decide_proposal_fn` / `do_prevote_fn` / `set_proposal_fn` are
overridable, and any ticker implementing schedule/set_on_timeout/stop
can be injected (MockTicker drives deterministic tests).
"""

from __future__ import annotations

import os
import queue
import threading
import time as time_mod
from dataclasses import dataclass

from tendermint_tpu.consensus.config import ConsensusConfig
from tendermint_tpu.consensus.round_state import HeightVoteSet, RoundState, RoundStepType
from tendermint_tpu.consensus.ticker import AdaptiveTimeouts, TimeoutInfo, TimeoutTicker
from tendermint_tpu.consensus.wal import (
    WAL,
    EndHeightMessage,
    MsgRecord,
    RoundStateRecord,
    TimeoutRecord,
)
from tendermint_tpu.state import apply_block
from tendermint_tpu.state.state import State
from tendermint_tpu.types import events as ev
from tendermint_tpu.types.block import Block, Commit
from tendermint_tpu.types.block_id import BlockID
from tendermint_tpu.types.heartbeat import Heartbeat
from tendermint_tpu.types.errors import (
    ErrDoubleSign,
    ErrVoteConflictingVotes,
    ErrVoteInvalidSignature,
    ErrVoteInvalidValidatorAddress,
    ErrVoteInvalidValidatorIndex,
    ErrVoteNonDeterministicSignature,
    ErrVoteUnexpectedStep,
    FatalConsensusError,
    ValidationError,
)
from tendermint_tpu.types.part_set import Part, PartSet, PartSetHeader
from tendermint_tpu.types.proposal import Proposal
from tendermint_tpu.types.services import NopMempool
from tendermint_tpu.types.tx import Txs
from tendermint_tpu.types.vote import VOTE_TYPE_PRECOMMIT, VOTE_TYPE_PREVOTE, Vote
from tendermint_tpu.types.vote_set import VoteSet
from tendermint_tpu.telemetry import TRACER
from tendermint_tpu.telemetry import heightlog as _heightlog
from tendermint_tpu.telemetry import metrics as _metrics
from tendermint_tpu.telemetry import tracectx as _trace
from tendermint_tpu.telemetry.flightrec import FLIGHT
from tendermint_tpu.utils.fail import fail_point
from tendermint_tpu.utils.lockrank import ranked_rlock
from tendermint_tpu.utils import log as _log_mod
import logging as _logging

_SENTINEL = object()
# receive-loop-internal marker: "no new input — join the oldest
# in-flight vote-batch preverify instead"
_JOIN = object()
# receive-loop-internal marker: "queue idle — join the pending
# pipelined apply now" (an idle loop delays nothing by joining, and
# the height's ledger record / commit events land promptly instead of
# waiting for the next height's first barrier)
_JOIN_APPLY = object()


@dataclass
class _TxsAvailable:
    height: int


class ConsensusState:
    def __init__(
        self,
        config: ConsensusConfig,
        state: State,
        app_conn,
        block_store,
        mempool=None,
        priv_validator=None,
        event_switch=None,
        wal_path: str | None = None,
        ticker=None,
        verifier=None,
        tx_indexer=None,
        hasher=None,
        evidence_pool=None,
        heightlog=None,
    ) -> None:
        self.config = config
        self.app_conn = app_conn
        self.block_store = block_store
        self.mempool = mempool if mempool is not None else NopMempool()
        self.priv_validator = priv_validator
        self.event_switch = event_switch if event_switch is not None else ev.EventSwitch()
        self.verifier = verifier
        self.tx_indexer = tx_indexer
        # Byzantine accountability: ErrVoteConflictingVotes sites feed
        # DuplicateVoteEvidence here; proposals reap it; commits retire
        # it. None = detection still logs/records, proof is dropped
        # (the pre-evidence behavior).
        self.evidence_pool = evidence_pool
        if evidence_pool is not None:
            evidence_pool.chain_id = state.chain_id
            if evidence_pool.verifier is None:
                evidence_pool.verifier = verifier
            evidence_pool.val_set_fn = self._evidence_val_set
            evidence_pool.best_height_fn = lambda: self.height
        # reactor-wired hook: fn(peer_id, kind, detail) — classified
        # adversarial vote input debits the sending peer's p2p
        # misbehavior score (equivocation is VALIDATOR fault and goes to
        # the evidence pool instead; the relaying peer did nothing wrong)
        self.on_peer_misbehavior = None
        # TreeHasher for proposal-block data_hash/part-set builds; None = host
        # merkle (reference SimpleHash call sites `types/block.go:177`).
        self.hasher = hasher
        self.wal = WAL(wal_path, light=config.wal_light) if wal_path else None

        self._queue: "queue.Queue" = queue.Queue()
        self._vote_dispatch = None  # lazy DispatchQueue for vote preverify
        # The lowest-ranked lock in the process (lockrank
        # "consensus.state"): held across mempool update/lock, evidence
        # admission, and verify-spine joins, so everything it reaches
        # must rank above it.
        self._mtx = ranked_rlock("consensus.state")
        self._thread: threading.Thread | None = None
        self._running = False
        # Set when an internal invariant/persistence failure halts the
        # loop (the reference panics instead; see FatalConsensusError).
        self.fatal_error: BaseException | None = None

        self.ticker = ticker if ticker is not None else TimeoutTicker()
        self.ticker.set_on_timeout(self._enqueue_timeout)

        # test hooks (reference overridable fields `consensus/state.go:107-110`)
        self.decide_proposal_fn = self._default_decide_proposal
        self.do_prevote_fn = self._default_do_prevote
        self.set_proposal_fn = self._default_set_proposal

        # RoundState
        self.state: State = None  # type: ignore  # set by _update_to_state
        self.height = 0
        self.round = 0
        self.step = RoundStepType.NEW_HEIGHT
        self.start_time = 0.0
        self.commit_time = 0.0
        self.validators = None
        self.proposal: Proposal | None = None
        self.proposal_block: Block | None = None
        self.proposal_block_parts: PartSet | None = None
        self.locked_round = -1
        self.locked_block: Block | None = None
        self.locked_block_parts: PartSet | None = None
        self.votes: HeightVoteSet | None = None
        self.commit_round = -1
        self.last_commit: VoteSet | None = None

        # telemetry: the open round-phase span and the height stopwatch
        # (observed into tendermint_consensus_phase_seconds /
        # _height_seconds and the span tracer on every transition)
        self._phase_name: str | None = None
        self._phase_started = time_mod.monotonic()
        self._height_started = time_mod.monotonic()
        # finality observatory: one ledger record per committed height
        # (phase durations, wait-vs-work split, critical-path label,
        # laggard validator) — an injected ledger persists under the
        # node's data dir; the default is an in-memory ring.
        self.height_ledger = (
            heightlog if heightlog is not None else _heightlog.HeightLedger()
        )
        self.vote_arrivals = _heightlog.VoteArrivalRollup()
        # gossip observatory: the switch-owned GossipRollup, wired by
        # the consensus reactor's on_start (None standalone) — vote/part
        # duplicate adds and first-seen propagation stamps land here
        self.gossip = None
        self._last_commit_wall: float | None = None
        self._phase_acc: dict[str, list] = {}  # phase -> [dur_s, work_s]
        self._height_work0 = _heightlog.work_totals()
        self._phase_work0 = self._height_work0
        self._val_arrivals: dict[int, tuple[str, float]] = {}
        self._apply_s = 0.0
        # measured-latency timeout policy (falls back to the fixed
        # config ladder while cold or opted out)
        self.timeouts = AdaptiveTimeouts(
            config, rollup=self.vote_arrivals, ledger=self.height_ledger
        )
        # Cross-height pipeline: while height H's apply flies on the
        # apply dispatch queue, H+1 runs on a speculated state. All
        # fields are owned by the receive-loop thread (finalize, joins,
        # and the batch drain all run there); `stop()` drains after the
        # loop exits.
        self.pipeline_enabled = bool(
            getattr(config, "pipeline_commit", False)
        ) and os.environ.get("TENDERMINT_TPU_PIPELINE", "1") != "0"
        self._pending_apply: dict | None = None
        self._apply_dispatch = None  # lazy depth-1 DispatchQueue("apply")
        # bumped when the join barrier rebuilds the valset (EndBlock
        # changed it): preverify verdicts minted under an older gen are
        # discarded (their sigs bound validator indexes to stale keys)
        self._valset_gen = 0
        # node-local pipeline counters for GET /health (reported, never
        # folded into routing status — same discipline as the SLO)
        self.pipeline_stats = {
            "joins": 0,
            "stalls": 0,
            "valset_rebuilds": 0,
            "overlap_s_total": 0.0,
            "last_overlap_s": 0.0,
        }

        self._update_to_state(state)
        if hasattr(self.mempool, "set_on_txs_available"):
            self.mempool.set_on_txs_available(self._on_txs_available)
        # A brand-new WAL gets an ENDHEIGHT marker for the last committed
        # height so crash recovery of the FIRST in-progress height finds
        # its replay anchor (the reference seeds "#ENDHEIGHT: 0" likewise).
        if self.wal is not None and os.path.getsize(self.wal.path) == 0:
            self.wal.save(EndHeightMessage(state.last_block_height))

    # ------------------------------------------------------------------ API

    def start(self) -> None:
        self._catchup_replay()
        self._running = True
        # named for the contention profiler's subsystem classification
        # (telemetry/profiler.py) — this is THE consensus hot thread
        self._thread = threading.Thread(
            target=self._receive_loop, name="consensus-recv", daemon=True
        )
        self._thread.start()
        self._schedule_round0()

    def update_to_state(self, state: State) -> None:
        """Adopt an externally-advanced state BEFORE start() — the
        fast-sync handoff (reference `SwitchToConsensus
        consensus/reactor.go:79-96` calls updateToState with the synced
        state). Must not be called while the receive loop runs."""
        if self._running:
            raise ValidationError("update_to_state on a running consensus")
        with self._mtx:
            self._update_to_state(state)

    def stop(self) -> None:
        self._running = False
        self.ticker.stop()
        self._queue.put(_SENTINEL)
        if self._thread is not None:
            self._thread.join(timeout=5)
        if self._pending_apply is not None:
            # drain the pipeline: the in-flight apply persisted (or
            # failed) on its worker — join so shutdown state on disk is
            # the applied one, never a half-landed height
            with self._mtx:
                try:
                    self._join_apply("shutdown")
                except Exception as e:
                    self.fatal_error = e
                    import traceback

                    traceback.print_exc()
        if self._vote_dispatch is not None:
            self._vote_dispatch.close()
        if self._apply_dispatch is not None:
            self._apply_dispatch.close()
        if self.wal is not None:
            self.wal.close()

    def add_vote(self, vote: Vote, peer_id: str = "") -> None:
        if self.fatal_error is not None:
            return  # halted: nothing drains the queue anymore
        self._queue.put(self._record(vote, peer_id))

    def set_proposal(self, proposal: Proposal, peer_id: str = "") -> None:
        if self.fatal_error is not None:
            return
        self._queue.put(self._record(proposal, peer_id))

    def add_proposal_block_part(
        self, height: int, round_: int, part: Part, peer_id: str = ""
    ) -> None:
        if self.fatal_error is not None:
            return
        self._queue.put(self._record((height, round_, part), peer_id))

    @staticmethod
    def _record(msg, peer_id: str) -> MsgRecord:
        """Stamp the enqueued input with the caller thread's ambient
        trace context + arrival time (the p2p recv loop installs the
        wire context before reactor dispatch) so the receive loop can
        re-establish the context while processing — trace propagation
        survives the thread hop through the queue."""
        return MsgRecord(msg, peer_id, ctx=_trace.current(), arrived=time_mod.time())

    def get_round_state(self) -> RoundState:
        with self._mtx:
            return RoundState(
                height=self.height,
                round=self.round,
                step=self.step,
                start_time=self.start_time,
                commit_time=self.commit_time,
                validators=self.validators,
                proposal=self.proposal,
                proposal_block=self.proposal_block,
                proposal_block_parts=self.proposal_block_parts,
                locked_round=self.locked_round,
                locked_block=self.locked_block,
                locked_block_parts=self.locked_block_parts,
                votes=self.votes,
                commit_round=self.commit_round,
                last_commit=self.last_commit,
                last_validators=self.state.last_validators,
            )

    def is_proposer(self) -> bool:
        if self.priv_validator is None:
            return False
        return self.validators.proposer.address == self.priv_validator.address

    # ----------------------------------------------------------- the loop

    # A backlog of this many same-(height, round, type) votes switches the
    # loop to one batched device verify instead of per-vote singles
    # (SURVEY §7 hard part 3: a 10k-validator vote storm must not verify
    # 10k sigs one at a time on host while the TPU idles). Env-tunable
    # so small validator sets can opt into batched preverifies too.
    # While a pipelined apply is in flight the gate drops to ANY run —
    # votes preverify through the coalescer instead of their tally
    # queuing behind the apply join (measured: the extra thread hops of
    # universal async singles COST latency on an idle loop, so the
    # always-async variant was reverted).
    VOTE_DRAIN_MIN = int(os.environ.get("TENDERMINT_TPU_VOTE_DRAIN_MIN", "8"))
    VOTE_DRAIN_MAX = 4096
    # Vote-batch preverifies kept in flight: while batch K's signatures
    # fly on device, the loop keeps pulling the queue and drains batch
    # K+1 — verdicts join in drain order before ANY state mutation, so
    # consensus input order is exactly the synchronous loop's.
    VOTE_PIPELINE_DEPTH = int(
        os.environ.get("TENDERMINT_TPU_VOTE_PIPELINE_DEPTH", "2")
    )

    def _receive_loop(self) -> None:
        from collections import deque

        stashed = None
        pending: "deque" = deque()  # (records, handle) batches, drain order
        while self._running:
            if stashed is not None:
                item, stashed = stashed, None
            elif pending:
                # a preverify is in flight: don't block on the queue —
                # either drain more input behind it or join its verdict
                try:
                    item = self._queue.get_nowait()
                except queue.Empty:
                    item = _JOIN
            elif self._pending_apply is not None:
                # no input queued and nothing else in flight: join the
                # pipelined apply rather than sleeping on the queue —
                # the overlap already ran its course
                try:
                    item = self._queue.get_nowait()
                except queue.Empty:
                    item = _JOIN_APPLY
            else:
                item = self._queue.get()
            if item is _SENTINEL:
                # shutting down: join in-flight preverifies so their
                # dispatch slots release; the votes are WAL'd and replay
                # on restart, no state is mutated past this point
                for entry in pending:
                    try:
                        entry[1].result()
                    # tmlint: disable=T001 -- shutdown slot-release join: verdicts are discarded by design, votes replay from the WAL
                    except Exception:
                        pass
                return
            # Opportunistic vote-storm drain: batch the CONSECUTIVE run of
            # queued votes for the same (height, round, type). Consensus
            # message order is otherwise preserved — the drain stops at
            # the first non-matching item and stashes it for next turn.
            batch = None
            if (
                item is not _JOIN
                and isinstance(item, MsgRecord)
                and isinstance(item.msg, Vote)
                and (not self._queue.empty() or self._pending_apply is not None)
            ):
                key = (item.msg.height, item.msg.round, item.msg.type)
                batch = [item]
                while len(batch) < self.VOTE_DRAIN_MAX:
                    try:
                        nxt = self._queue.get_nowait()
                    except queue.Empty:
                        break
                    if nxt is _SENTINEL:
                        stashed = nxt
                        break
                    if (
                        isinstance(nxt, MsgRecord)
                        and isinstance(nxt.msg, Vote)
                        and (nxt.msg.height, nxt.msg.round, nxt.msg.type) == key
                    ):
                        batch.append(nxt)
                    else:
                        stashed = nxt
                        break
            if batch is not None:
                _metrics.VOTE_DRAIN_BATCH.observe(len(batch))
            try:
                if item is _JOIN:
                    self._join_vote_batch(*pending.popleft())
                elif item is _JOIN_APPLY:
                    with self._mtx:
                        self._join_apply("idle")
                elif batch is not None and (
                    len(batch) >= self.VOTE_DRAIN_MIN
                    # while an apply is in flight, runs of 2+ preverify
                    # asynchronously instead of tallying (and joining)
                    # behind it; singles stay on the cheap sync path —
                    # their tally join costs at most the apply remainder
                    or (self._pending_apply is not None and len(batch) >= 2)
                ):
                    # submit this run's preverify and keep pulling; the
                    # depth bound joins the oldest batch first so state
                    # mutation stays in drain order
                    while len(pending) >= self.VOTE_PIPELINE_DEPTH:
                        self._join_vote_batch(*pending.popleft())
                    pending.append(self._submit_vote_batch(batch))
                else:
                    # ORDER BARRIER: anything that isn't a same-key vote
                    # run (proposals, parts, timeouts, small runs) must
                    # observe every earlier vote's effect — join all
                    # in-flight batches before touching state
                    while pending:
                        self._join_vote_batch(*pending.popleft())
                    if batch is not None:
                        # runs too small to amortize a batch preverify
                        # take the single-vote path, in drain order
                        for rec in batch:
                            self._process_item(rec)
                    else:
                        self._process_item(item)
            except (ErrDoubleSign, FatalConsensusError) as e:
                # Internal failure: halt consensus rather than keep voting
                # from a half-advanced state (reference PanicConsensus —
                # crash recovery takes over on restart). The flight
                # recorder dumps its ring NOW: the events leading into
                # the halt are exactly what the post-mortem needs.
                import traceback

                traceback.print_exc()
                self.fatal_error = e
                self._running = False
                self.ticker.stop()
                FLIGHT.record(
                    "fatal",
                    error=type(e).__name__,
                    height=self.height,
                    round=self.round,
                )
                FLIGHT.dump(reason="consensus-fatal")
                raise
            except Exception:  # a bad peer message must not kill consensus
                import traceback

                traceback.print_exc()

    def _process_item(self, item) -> None:
        with self._mtx:
            # _TxsAvailable is a local wakeup hint, not a consensus
            # input — it is not WAL'd (matches the reference, where
            # txsAvailable arrives on a separate non-WAL'd channel)
            if self.wal is not None and not isinstance(item, _TxsAvailable):
                try:
                    self.wal.save(item)
                except Exception as e:
                    raise FatalConsensusError("WAL write failed") from e
            self._dispatch(item)

    def _process_vote_batch(self, records: list) -> None:
        """One batched verify for a drained same-key vote run, then
        per-vote tallying — the submit+join pipeline stages run
        back-to-back (kept for replay/tests; the receive loop overlaps
        them)."""
        self._join_vote_batch(*self._submit_vote_batch(records))

    def _submit_vote_batch(self, records: list):
        """Pipeline stage 1: WAL the drained run (drain order == WAL
        order == eventual processing order), prep the signature triples
        under the state lock, and launch their batch verify through the
        dispatch queue. No round state is mutated here.

        Trace attribution: the launch runs with the height's block
        context (the proposal's, which the proposer adopted from its
        first traced tx) — or a drained vote's own context — ambient,
        so the coalescer request and the device launch downstream join
        the trace of the block being decided."""
        submitted = time_mod.time()
        exemplar = self._proposal_ctx
        traced = [rec for rec in records if rec.ctx is not None]
        if exemplar is None and traced:
            exemplar = traced[0].ctx
        for rec in traced:
            if rec.arrived:
                _metrics.VOTE_STAGE.labels(stage="drain").observe(
                    submitted - rec.arrived, exemplar=rec.ctx.trace
                )
        FLIGHT.record(
            "vote_batch",
            n=len(records),
            height=self.height,
            round=self.round,
        )
        with self._mtx:
            if self.wal is not None:
                for rec in records:
                    try:
                        self.wal.save(rec)
                    except Exception as e:
                        raise FatalConsensusError("WAL write failed") from e
            with _trace.use(exemplar):
                handle = self._preverify_votes_async(
                    [rec.msg for rec in records],
                    skip={i for i, rec in enumerate(records) if rec.self_signed},
                )
            gen = self._valset_gen
        return records, handle, submitted, exemplar, gen

    def _join_vote_batch(
        self, records: list, handle, submitted: float = 0.0, exemplar=None, gen=None
    ) -> None:
        """Pipeline stage 2: join the verdict mask, then tally each vote
        with the mask deciding which skip the in-set signature check
        (failed lanes re-verify individually so error attribution matches
        the single-vote path exactly). A dispatch-layer failure degrades
        to all-False — every vote just re-verifies in-set. Verdicts
        minted under a stale valset generation (the cross-height join
        barrier rebuilt the set after EndBlock changes) degrade the same
        way — the speculative preverify bound indexes to superseded
        keys, so those votes re-verify against the real set."""
        try:
            verdicts = handle.result()
        except Exception:
            import traceback

            traceback.print_exc()
            verdicts = [False] * len(records)
        joined = time_mod.time()
        if exemplar is not None and submitted:
            _metrics.VOTE_STAGE.labels(stage="verify").observe(
                joined - submitted, exemplar=exemplar.trace
            )
        with self._mtx:
            for rec, ok in zip(records, verdicts):
                self._observe_vote_arrival(rec)
                # re-checked per vote: the first tally's join can rebuild
                # the valset mid-batch. Self-signed votes stay trusted
                # across rebuilds — the signature is this node's own.
                ok = rec.self_signed or (
                    bool(ok) and (gen is None or gen == self._valset_gen)
                )
                try:
                    with _trace.use(rec.ctx):
                        self._handle_vote(
                            rec.msg, rec.peer_id, preverified=ok
                        )
                    if rec.ctx is not None:
                        self._observe_vote_e2e(rec, joined)
                except (ErrDoubleSign, FatalConsensusError):
                    raise
                except Exception:  # per-vote fault isolation, as singles
                    import traceback

                    traceback.print_exc()

    def _observe_vote_e2e(self, rec, done: float) -> None:
        """One traced vote's gossip-arrival → verdict-applied span +
        e2e histogram slice (sampled votes only)."""
        if not rec.arrived:
            return
        v = rec.msg
        _metrics.VOTE_STAGE.labels(stage="e2e").observe(
            done - rec.arrived, exemplar=rec.ctx.trace
        )
        TRACER.add(
            "vote.e2e",
            rec.arrived,
            done,
            trace=rec.ctx.trace,
            origin=rec.ctx.origin,
            height=v.height,
            round=v.round,
            type=v.type,
        )

    def _observe_vote_arrival(self, rec) -> None:
        """Per-peer vote-arrival latency (vote timestamp → local
        arrival) for the rollup + the current height's laggard-validator
        attribution. Replayed WAL records (arrived == 0) are skipped;
        delays are clamped — a byzantine validator controls its own
        timestamps and must not poison the attribution."""
        v = rec.msg
        if not rec.arrived or not isinstance(v, Vote) or v.height != self.height:
            return
        delay = rec.arrived - v.timestamp / 1e9
        if delay < 0.0:
            delay = 0.0  # clock skew / future-stamped vote
        elif delay > _heightlog.MAX_ARRIVAL_S:
            delay = _heightlog.MAX_ARRIVAL_S
        self.vote_arrivals.observe(rec.peer_id or "self", delay)
        _metrics.VOTE_ARRIVAL_SECONDS.observe(delay)
        cur = self._val_arrivals.get(v.validator_index)
        if cur is None or delay > cur[1]:
            self._val_arrivals[v.validator_index] = (
                v.validator_address.hex()[:12],
                delay,
            )

    def _vote_queue(self):
        if self._vote_dispatch is None:
            from tendermint_tpu.services.dispatch import DispatchQueue

            self._vote_dispatch = DispatchQueue(
                depth=max(2, self.VOTE_PIPELINE_DEPTH), name="consensus"
            )
        return self._vote_dispatch

    def _preverify_votes_async(self, votes: list, skip=None):
        """Launch the batch preverify of current-height votes against
        the current validator set; returns a handle resolving to the
        per-vote bool list (False = re-verify individually in-set).
        Triples are prepped NOW — the verdict stays valid however far
        the loop advances before joining, because it binds the votes'
        height to the valset current at that height."""
        verifier = self.verifier
        if verifier is None:
            from tendermint_tpu.services.verifier import default_verifier

            verifier = default_verifier()
        idxs, triples = [], []
        for i, v in enumerate(votes):
            if skip is not None and i in skip:
                continue  # self-signed: trusted without a launch lane
            if v.height != self.height or self.validators is None:
                continue
            val = self.validators.get_by_index(v.validator_index)
            if val is None or val.address != v.validator_address:
                continue
            triples.append(
                (val.pub_key.data, v.sign_bytes(self.state.chain_id), v.signature)
            )
            idxs.append(i)
        out = [False] * len(votes)
        from tendermint_tpu.services.dispatch import CompletedHandle

        if not triples:
            return CompletedHandle(out)

        def _scatter(verdicts):
            for i, ok in zip(idxs, verdicts):
                out[i] = bool(ok)
            return out

        if hasattr(verifier, "verify_batch_async"):
            from tendermint_tpu.services.batcher import consumer_kwargs

            return verifier.verify_batch_async(
                triples,
                queue=self._vote_queue(),
                **consumer_kwargs(verifier, "consensus"),
            ).then(_scatter)
        return CompletedHandle(_scatter(verifier.verify_batch(triples)))

    def _preverify_votes(self, votes: list) -> list[bool]:
        """Synchronous preverify (replay/test seam): submit + join."""
        return self._preverify_votes_async(votes).result()

    def _dispatch(self, item) -> None:
        if isinstance(item, MsgRecord):
            m = item.msg
            # Re-establish the record's trace context for the whole
            # handling scope: events fired from here (EVENT_VOTE /
            # EVENT_COMPLETE_PROPOSAL push-gossip) re-attach it to
            # outbound frames without any reactor plumbing.
            with _trace.use(getattr(item, "ctx", None)):
                if isinstance(m, Vote):
                    self._observe_vote_arrival(item)
                    self._handle_vote(
                        m, item.peer_id, preverified=getattr(item, "self_signed", False)
                    )
                    if item.ctx is not None:
                        self._observe_vote_e2e(item, time_mod.time())
                elif isinstance(m, Proposal):
                    self.set_proposal_fn(m)
                else:
                    height, round_, part = m
                    self._handle_block_part(height, round_, part, item.peer_id)
        elif isinstance(item, TimeoutRecord):
            self._handle_timeout(
                TimeoutInfo(item.duration, item.height, item.round, item.step)
            )
        elif isinstance(item, _TxsAvailable):
            if item.height == self.height and self.step == RoundStepType.NEW_ROUND:
                self._enter_propose(self.height, self.round)

    def _enqueue_timeout(self, ti: TimeoutInfo) -> None:
        self._queue.put(TimeoutRecord(ti.duration, ti.height, ti.round, ti.step))

    def _on_txs_available(self) -> None:
        self._queue.put(_TxsAvailable(self.height))

    # ----------------------------------------------------------- telemetry

    def _observe_phase(self, next_name: str | None) -> None:
        """Close the open round-phase span (histogram + tracer) and open
        `next_name`. Called on every phase transition under the state
        lock; None closes without opening (height finalized).

        Ledger bookkeeping rides the same transitions: per-height phase
        durations accumulate (a phase can repeat across rounds) with a
        wait-vs-work split stitched from the exported verify/hash
        stopwatches, and the gap from height start to the first opened
        phase is the NewHeight wait."""
        now = time_mod.monotonic()
        work = _heightlog.work_totals()
        if self._phase_name is not None:
            dur = now - self._phase_started
            _metrics.CONSENSUS_PHASE_SECONDS.labels(
                phase=self._phase_name
            ).observe(dur)
            wall_end = time_mod.time()
            TRACER.add(
                f"consensus.{self._phase_name}",
                wall_end - dur,
                wall_end,
                height=self.height,
                round=self.round,
            )
            work_s = max(
                0.0,
                (work["verify"] + work["hash"])
                - (self._phase_work0["verify"] + self._phase_work0["hash"]),
            )
            acc = self._phase_acc.setdefault(self._phase_name, [0.0, 0.0])
            acc[0] += dur
            acc[1] += min(work_s, dur)
        elif next_name is not None:
            # first phase of the height opening: everything since the
            # height started (commit timeout + waiting for round 0)
            self._phase_acc.setdefault("new_height", [0.0, 0.0])[0] += max(
                0.0, now - self._height_started
            )
        self._phase_name = next_name
        self._phase_started = now
        self._phase_work0 = work

    # ------------------------------------------------------ state plumbing

    def _update_to_state(self, state: State) -> None:
        """Reset the round state for state.last_block_height+1
        (reference `updateToState consensus/state.go:415-477`)."""
        if self.commit_round > -1 and 0 < self.height != state.last_block_height:
            raise ValidationError(
                f"updateToState expected height {self.height}, got {state.last_block_height}"
            )
        # last_commit: the precommits that committed the last block
        last_commit = None
        if state.last_block_height > 0:
            if self.commit_round > -1 and self.votes is not None:
                precommits = self.votes.precommits(self.commit_round)
                if precommits is None or not precommits.has_two_thirds_majority():
                    raise ValidationError("updateToState called with unfinished commit")
                last_commit = precommits
            else:
                last_commit = self._reconstruct_last_commit(state)

        self.state = state
        self.height = state.last_block_height + 1
        self.round = 0
        self.step = RoundStepType.NEW_HEIGHT
        now = time_mod.time()
        if self.commit_time:
            self.start_time = self.commit_time + self.timeouts.commit_timeout()
        else:
            self.start_time = now + self.timeouts.commit_timeout()
        validators = state.validators.copy()
        self.validators = validators
        self.proposal = None
        self.proposal_block = None
        self.proposal_block_parts = None
        self.locked_round = -1
        self.locked_block = None
        self.locked_block_parts = None
        self.votes = HeightVoteSet(state.chain_id, self.height, validators)
        self.commit_round = -1
        self.last_commit = last_commit
        self._phase_name = None
        self._height_started = time_mod.monotonic()
        # fresh per-height ledger accumulators (phase durations,
        # work-stopwatch baseline, per-validator vote arrivals)
        self._phase_acc = {}
        self._height_work0 = _heightlog.work_totals()
        self._phase_work0 = self._height_work0
        self._val_arrivals = {}
        self._apply_s = 0.0
        # the height's block trace context: adopted from the proposal
        # (proposer: its first traced tx; receivers: the proposal
        # frame's context) — vote-batch verifies for this height are
        # attributed to it
        self._proposal_ctx = None
        _metrics.CONSENSUS_HEIGHT.set(self.height)
        _metrics.CONSENSUS_ROUND.set(0)

    def _reconstruct_last_commit(self, state: State) -> VoteSet | None:
        """Rebuild the precommit VoteSet from the stored seen-commit
        (reference `consensus/state.go:392-411`)."""
        if state.last_block_height == 0 or self.block_store is None:
            return None
        seen = self.block_store.load_seen_commit(state.last_block_height)
        if seen is None:
            raise ValidationError(
                f"no seen commit for height {state.last_block_height}"
            )
        vs = VoteSet(
            state.chain_id,
            state.last_block_height,
            seen.round(),
            VOTE_TYPE_PRECOMMIT,
            state.last_validators,
        )
        for v in seen.precommits:
            if v is not None:
                vs.add_vote(v, verifier=self.verifier)
        if not vs.has_two_thirds_majority():
            raise ValidationError("reconstructed last commit lacks +2/3")
        return vs

    def _catchup_replay(self) -> None:
        """Replay WAL records for the in-progress height
        (reference `consensus/replay.go:93-143`)."""
        if self.wal is None:
            return
        records = WAL.records_since_last_end_height(self.wal.path, self.height)
        if records is None:
            return
        saved_wal, self.wal = self.wal, None  # don't re-WAL replayed inputs
        try:
            for rec in records:
                if isinstance(rec, (EndHeightMessage, RoundStateRecord)):
                    continue
                try:
                    with self._mtx:
                        self._dispatch(rec)
                except (ErrDoubleSign, FatalConsensusError):
                    raise
                except Exception:
                    # Inputs are WAL'd BEFORE validation, so a bad peer
                    # message (invalid sig, conflicting vote) can be on
                    # disk; tolerate it here exactly like the live loop
                    # does, or the node can never restart (reference
                    # replay.go logs-and-continues the same way).
                    import traceback

                    traceback.print_exc()
        finally:
            self.wal = saved_wal

    # --------------------------------------------------------- scheduling

    def _schedule_round0(self) -> None:
        sleep = max(0.0, self.start_time - time_mod.time())
        self.ticker.schedule(
            TimeoutInfo(sleep, self.height, 0, RoundStepType.NEW_HEIGHT)
        )

    def _schedule_timeout(self, duration: float, height: int, round_: int, step: int) -> None:
        self.ticker.schedule(TimeoutInfo(duration, height, round_, step))

    def _handle_timeout(self, ti: TimeoutInfo) -> None:
        """Reference `handleTimeout consensus/state.go:589-622`."""
        if ti.height != self.height or ti.round < self.round or (
            ti.round == self.round and ti.step < self.step
        ):
            return
        if ti.step == RoundStepType.NEW_HEIGHT:
            self._enter_new_round(ti.height, 0)
        elif ti.step == RoundStepType.NEW_ROUND:
            # create_empty_blocks_interval expired while waiting for txs
            self._enter_propose(ti.height, 0)
        elif ti.step == RoundStepType.PROPOSE:
            self.event_switch.fire(ev.EVENT_TIMEOUT_PROPOSE, self._rs_event())
            self._enter_prevote(ti.height, ti.round)
        elif ti.step == RoundStepType.PREVOTE:
            # Round-skip (ROADMAP liveness gap): starved at PREVOTE with
            # no +2/3-any to arm PrevoteWait — precommit nil and move on,
            # later Tendermint's OnTimeoutPrevote. The guard above
            # filtered this tock out if the round advanced on its own.
            _metrics.CONSENSUS_ROUND_SKIPS.labels(phase="prevote").inc()
            self._enter_precommit(ti.height, ti.round)
        elif ti.step == RoundStepType.PRECOMMIT:
            # OnTimeoutPrecommit: starved at PRECOMMIT — next round.
            _metrics.CONSENSUS_ROUND_SKIPS.labels(phase="precommit").inc()
            self._enter_new_round(ti.height, ti.round + 1)
        elif ti.step == RoundStepType.PREVOTE_WAIT:
            self.event_switch.fire(ev.EVENT_TIMEOUT_WAIT, self._rs_event())
            self._enter_precommit(ti.height, ti.round)
        elif ti.step == RoundStepType.PRECOMMIT_WAIT:
            self.event_switch.fire(ev.EVENT_TIMEOUT_WAIT, self._rs_event())
            self._enter_new_round(ti.height, ti.round + 1)

    # -------------------------------------------------------- transitions

    def _rs_event(self):
        return ev.EventDataRoundState(
            height=self.height, round=self.round, step=RoundStepType.name(self.step)
        )

    def _new_step(self) -> None:
        if self.wal is not None:
            self.wal.save(RoundStateRecord(self.height, self.round, self.step))
        FLIGHT.record(
            "round_step",
            height=self.height,
            round=self.round,
            step=RoundStepType.name(self.step),
        )
        self.event_switch.fire(ev.EVENT_NEW_ROUND_STEP, self._rs_event())

    def _enter_new_round(self, height: int, round_: int) -> None:
        if height != self.height or round_ < self.round or (
            round_ == self.round and self.step != RoundStepType.NEW_HEIGHT
        ):
            return
        validators = self.validators
        if round_ > self.round:
            validators = validators.copy()
            validators.increment_accum(round_ - self.round)
        self.validators = validators
        self.round = round_
        self.step = RoundStepType.NEW_ROUND
        if round_ != 0:
            # round 0 fields were reset by _update_to_state
            self.proposal = None
            self.proposal_block = None
            self.proposal_block_parts = None
        self.votes.set_round(round_ + 1)  # track next round for skipping
        _metrics.CONSENSUS_ROUND.set(round_)
        self.event_switch.fire(ev.EVENT_NEW_ROUND, self._rs_event())

        wait_for_txs = (
            not self.config.create_empty_blocks
            and round_ == 0
            and not self.mempool.tx_available()
        )
        if wait_for_txs:
            self.step = RoundStepType.NEW_ROUND  # wait; _TxsAvailable resumes
            if self.config.create_empty_blocks_interval > 0:
                self._schedule_timeout(
                    self.config.create_empty_blocks_interval,
                    height,
                    round_,
                    RoundStepType.NEW_ROUND,
                )
            if self.priv_validator is not None:
                # signed liveness pings while the chain idles (reference
                # `proposalHeartbeat consensus/state.go:707-738`); the
                # reactor gossips them, WS subscribers observe them
                threading.Thread(
                    target=self._proposal_heartbeat,
                    args=(height, round_),
                    name="consensus-heartbeat",
                    daemon=True,
                ).start()
            return
        self._enter_propose(height, round_)

    def _proposal_heartbeat(self, height: int, round_: int) -> None:
        addr = self.priv_validator.address
        idx = -1
        with self._mtx:
            for i, v in enumerate(self.validators):
                if v.address == addr:
                    idx = i
                    break
            chain_id = self.state.chain_id
        if idx < 0:
            # not in the validator set: nothing to prove liveness for,
            # and the wire encoding (uvarint index) can't carry -1
            return
        sequence = 0
        while self._running:
            rs = self.get_round_state()
            if (
                rs.height > height
                or rs.round > round_
                or rs.step > RoundStepType.NEW_ROUND
            ):
                return
            hb = Heartbeat(
                validator_address=addr,
                validator_index=idx,
                height=rs.height,
                round=rs.round,
                sequence=sequence,
            )
            hb = self.priv_validator.sign_heartbeat(chain_id, hb)
            self.event_switch.fire(ev.EVENT_PROPOSAL_HEARTBEAT, hb)
            sequence += 1
            time_mod.sleep(self.config.proposal_heartbeat_interval)

    def _enter_propose(self, height: int, round_: int) -> None:
        if height != self.height or round_ < self.round or (
            round_ == self.round and self.step >= RoundStepType.PROPOSE
        ):
            return
        self.round = round_
        self.step = RoundStepType.PROPOSE
        self._observe_phase("propose")
        self._new_step()
        self._schedule_timeout(
            self.timeouts.propose_timeout(round_), height, round_, RoundStepType.PROPOSE
        )
        if self.priv_validator is not None and self.is_proposer():
            # JOIN BARRIER: the proposal header carries the applied
            # app_hash/validators_hash and reaps the updated mempool +
            # evidence pool. (A stale-valset is_proposer() miss above
            # costs at worst one proposer slot on a rotation height —
            # liveness the next round recovers, never safety.)
            self._join_apply("propose")
            if self.is_proposer():
                self.decide_proposal_fn(height, round_)
        if self._is_proposal_complete():
            self._enter_prevote(height, round_)

    def _default_decide_proposal(self, height: int, round_: int) -> None:
        """Reference `defaultDecideProposal :787-827`."""
        if self.locked_block is not None:
            block, parts = self.locked_block, self.locked_block_parts
        else:
            made = self._create_proposal_block()
            if made is None:
                return
            block, parts = made
        pol_round, pol_block_id = self.votes.pol_info()
        proposal = Proposal(
            height=height,
            round=round_,
            block_parts_header=parts.header,
            pol_round=pol_round,
            pol_block_id=pol_block_id if pol_block_id is not None else BlockID.zero(),
            timestamp=time_mod.time_ns(),
        )
        try:
            proposal = self.priv_validator.sign_proposal(self.state.chain_id, proposal)
        except ErrDoubleSign:
            return
        # Proposal creation is a trace edge: adopt the first traced
        # tx's context as the BLOCK's context (the verify work this
        # height does is causally that tx's), minting fresh otherwise.
        # The internal sends below run with it ambient, so the records
        # — and the push-gossiped proposal/parts frames they trigger —
        # carry it to every peer.
        ctx = None
        trace_for = getattr(self.mempool, "trace_for", None)
        if trace_for is not None:
            for tx in block.data.txs:
                ctx = trace_for(bytes(tx))
                if ctx is not None:
                    break
        if ctx is None:
            origin = (
                self.priv_validator.address.hex()[:12]
                if self.priv_validator is not None
                else ""
            )
            ctx = _trace.mint(origin)
        self._proposal_ctx = ctx
        # send to ourselves (internal queue, no peer id)
        with _trace.use(ctx):
            self.set_proposal(proposal, "")
            for i in range(parts.total):
                self.add_proposal_block_part(height, round_, parts.get_part(i), "")

    def _create_proposal_block(self) -> tuple[Block, PartSet] | None:
        """Reference `createProposalBlock :848-868`."""
        if self.height == 1:
            last_commit = Commit.empty()
        elif self.last_commit is not None and self.last_commit.has_two_thirds_majority():
            last_commit = self.last_commit.make_commit()
        else:
            return None  # can't propose without the last commit
        txs = self.mempool.reap(self.config.max_block_size_txs)
        # commit pending misbehavior proofs alongside the txs (reference
        # `createProposalBlock` reaps the evidence pool); expired proofs
        # would fail every honest validator's validate_block, so filter
        # here rather than waste the proposal
        evidence = []
        if self.evidence_pool is not None:
            params = self.state.consensus_params.evidence
            evidence = [
                e
                for e in self.evidence_pool.pending_evidence(params.max_evidence)
                if self.height - e.height <= params.max_age
            ]
        block = Block.make_block(
            height=self.height,
            chain_id=self.state.chain_id,
            txs=Txs(txs),
            last_commit=last_commit,
            last_block_id=self.state.last_block_id,
            time=time_mod.time_ns(),
            validators_hash=self.state.validators.hash(),
            app_hash=self.state.app_hash,
            hasher=self.hasher,
            evidence=evidence,
        )
        return block, block.make_part_set(
            self.state.consensus_params.block_gossip.block_part_size_bytes,
            hasher=self.hasher,
        )

    def _default_set_proposal(self, proposal: Proposal) -> None:
        """Reference `defaultSetProposal :1247-1278`."""
        if self.proposal is not None:
            return
        if proposal.height != self.height or proposal.round != self.round:
            return
        if not (-1 <= proposal.pol_round < proposal.round):
            raise ValidationError("proposal POLRound out of range")
        proposer = self.validators.proposer
        if not proposer.pub_key.verify(
            proposal.sign_bytes(self.state.chain_id), proposal.signature
        ):
            raise ValidationError("invalid proposal signature")
        self.proposal = proposal
        if self._proposal_ctx is None:
            # receiver side of block-context adoption: the proposal
            # frame's trace context is ambient here (via the record)
            self._proposal_ctx = _trace.current()
        if self.proposal_block_parts is None:
            self.proposal_block_parts = PartSet.from_header(proposal.block_parts_header)

    def _handle_block_part(
        self, height: int, round_: int, part: Part, peer_id: str = ""
    ) -> None:
        """Reference `addProposalBlockPart :1282-1315`."""
        if height != self.height or self.proposal_block_parts is None:
            return
        if self._proposal_ctx is None:
            # block parts carry the block's context too (push gossip);
            # adopt when the proposal itself arrived uncontexted
            self._proposal_ctx = _trace.current()
        try:
            added = self.proposal_block_parts.add_part(part)
        except ValidationError:
            return
        if self.gossip is not None:
            if added:
                self.gossip.first_seen("block_part", height, round_, part.index)
            elif peer_id:
                # PartSet already-have part: a peer re-shipped a part we
                # hold — redundant wire bytes (own enqueues gate out on
                # the empty peer_id, same rule as votes)
                self.gossip.redundant("block_part", len(part.encode()))
        if not added or not self.proposal_block_parts.is_complete():
            return
        buf = b"".join(
            self.proposal_block_parts.get_part(i).bytes_
            for i in range(self.proposal_block_parts.total)
        )
        self.proposal_block = Block.decode(buf)
        self.event_switch.fire(ev.EVENT_COMPLETE_PROPOSAL, self._rs_event())
        prevotes = self.votes.prevotes(self.round)
        bid = prevotes.two_thirds_majority() if prevotes is not None else None
        if bid is not None and not bid.is_zero() and self.step <= RoundStepType.PREVOTE:
            # +2/3 already prevoted this block before we had it
            self._enter_prevote(height, self.round)
        elif self.step == RoundStepType.PROPOSE and self._is_proposal_complete():
            self._enter_prevote(height, self.round)
        elif self.step == RoundStepType.COMMIT:
            self._try_finalize_commit(height)

    def _is_proposal_complete(self) -> bool:
        if self.proposal is None or self.proposal_block is None:
            return False
        if self.proposal.pol_round < 0:
            return True
        prevotes = self.votes.prevotes(self.proposal.pol_round)
        return prevotes is not None and prevotes.has_two_thirds_majority()

    def _enter_prevote(self, height: int, round_: int) -> None:
        if height != self.height or round_ < self.round or (
            round_ == self.round and self.step >= RoundStepType.PREVOTE
        ):
            return
        # JOIN BARRIER: prevoting validates the proposal against
        # applied state (app_hash, EndBlock valset) — never vote for a
        # block judged on speculation.
        self._join_apply("prevote")
        self.round = round_
        self.step = RoundStepType.PREVOTE
        self._observe_phase("prevote")
        self._new_step()
        skip = self.config.round_skip_timeout(round_)
        if skip > 0:
            # round-skip deadline: replaced by PrevoteWait/Precommit
            # scheduling when votes actually flow (ticker keys order by
            # step), fires only if this round truly starves here
            self._schedule_timeout(skip, height, round_, RoundStepType.PREVOTE)
        self.do_prevote_fn(height, round_)

    def _default_do_prevote(self, height: int, round_: int) -> None:
        """Reference `defaultDoPrevote :875-908`."""
        if self.locked_block is not None:
            self._sign_add_vote(
                VOTE_TYPE_PREVOTE,
                self.locked_block.hash(),
                self.locked_block_parts.header,
            )
            return
        if self.proposal_block is None:
            self._sign_add_vote(VOTE_TYPE_PREVOTE, b"", PartSetHeader.zero())
            return
        try:
            from tendermint_tpu.state import validate_block

            validate_block(
                self.state,
                self.proposal_block,
                verifier=self.verifier,
                hasher=self.hasher,
            )
        except ValidationError:
            self._sign_add_vote(VOTE_TYPE_PREVOTE, b"", PartSetHeader.zero())
            return
        self._sign_add_vote(
            VOTE_TYPE_PREVOTE,
            self.proposal_block.hash(),
            self.proposal_block_parts.header,
        )

    def _enter_prevote_wait(self, height: int, round_: int) -> None:
        if height != self.height or round_ < self.round or (
            round_ == self.round and self.step >= RoundStepType.PREVOTE_WAIT
        ):
            return
        self.round = round_
        self.step = RoundStepType.PREVOTE_WAIT
        self._new_step()
        self._schedule_timeout(
            self.timeouts.prevote_timeout(round_), height, round_, RoundStepType.PREVOTE_WAIT
        )

    def _enter_precommit(self, height: int, round_: int) -> None:
        """Reference `enterPrecommit :963-1053` — the POL lock logic."""
        if height != self.height or round_ < self.round or (
            round_ == self.round and self.step >= RoundStepType.PRECOMMIT
        ):
            return
        self.round = round_
        self.step = RoundStepType.PRECOMMIT
        self._observe_phase("precommit")
        self._new_step()
        skip = self.config.round_skip_timeout(round_)
        if skip > 0:
            self._schedule_timeout(skip, height, round_, RoundStepType.PRECOMMIT)

        prevotes = self.votes.prevotes(round_)
        block_id = prevotes.two_thirds_majority() if prevotes is not None else None

        if block_id is None:
            # no polka: precommit nil
            self._sign_add_vote(VOTE_TYPE_PRECOMMIT, b"", PartSetHeader.zero())
            return

        self.event_switch.fire(ev.EVENT_POLKA, self._rs_event())

        if block_id.is_zero():
            # polka for nil: unlock if locked
            if self.locked_block is not None:
                self.locked_round = -1
                self.locked_block = None
                self.locked_block_parts = None
                self.event_switch.fire(ev.EVENT_UNLOCK, self._rs_event())
            self._sign_add_vote(VOTE_TYPE_PRECOMMIT, b"", PartSetHeader.zero())
            return

        if self.locked_block is not None and self.locked_block.hash_to(block_id.hash):
            # relock
            self.locked_round = round_
            self.event_switch.fire(ev.EVENT_RELOCK, self._rs_event())
            self._sign_add_vote(VOTE_TYPE_PRECOMMIT, block_id.hash, block_id.parts_header)
            return

        if self.proposal_block is not None and self.proposal_block.hash_to(block_id.hash):
            # lock the polka block (it must validate)
            from tendermint_tpu.state import validate_block

            try:
                validate_block(
                    self.state,
                    self.proposal_block,
                    verifier=self.verifier,
                    hasher=self.hasher,
                )
            except ValidationError as e:
                raise ValidationError(f"+2/3 prevoted an invalid block: {e}") from e
            self.locked_round = round_
            self.locked_block = self.proposal_block
            self.locked_block_parts = self.proposal_block_parts
            self.event_switch.fire(ev.EVENT_LOCK, self._rs_event())
            self._sign_add_vote(VOTE_TYPE_PRECOMMIT, block_id.hash, block_id.parts_header)
            return

        # polka for a block we don't have: unlock, fetch it, precommit nil
        self.locked_round = -1
        self.locked_block = None
        self.locked_block_parts = None
        if self.proposal_block_parts is None or not self.proposal_block_parts.has_header(
            block_id.parts_header
        ):
            self.proposal_block = None
            self.proposal_block_parts = PartSet.from_header(block_id.parts_header)
        self.event_switch.fire(ev.EVENT_UNLOCK, self._rs_event())
        self._sign_add_vote(VOTE_TYPE_PRECOMMIT, b"", PartSetHeader.zero())

    def _enter_precommit_wait(self, height: int, round_: int) -> None:
        if height != self.height or round_ < self.round or (
            round_ == self.round and self.step >= RoundStepType.PRECOMMIT_WAIT
        ):
            return
        self.round = round_
        self.step = RoundStepType.PRECOMMIT_WAIT
        self._new_step()
        self._schedule_timeout(
            self.timeouts.precommit_timeout(round_),
            height,
            round_,
            RoundStepType.PRECOMMIT_WAIT,
        )

    def _enter_commit(self, height: int, commit_round: int) -> None:
        """Reference `enterCommit :1078-1143`."""
        if height != self.height or self.step >= RoundStepType.COMMIT:
            return
        self.commit_round = commit_round
        self.commit_time = time_mod.time()
        self.step = RoundStepType.COMMIT
        self._observe_phase("commit")
        self._new_step()

        block_id = self.votes.precommits(commit_round).two_thirds_majority()
        if block_id is None or block_id.is_zero():
            raise ValidationError("enterCommit without +2/3 precommits")
        if self.locked_block is not None and self.locked_block.hash_to(block_id.hash):
            self.proposal_block = self.locked_block
            self.proposal_block_parts = self.locked_block_parts
        if self.proposal_block is None or not self.proposal_block.hash_to(block_id.hash):
            if self.proposal_block_parts is None or not self.proposal_block_parts.has_header(
                block_id.parts_header
            ):
                # we don't have the committed block: fetch via gossip
                self.proposal_block = None
                self.proposal_block_parts = PartSet.from_header(block_id.parts_header)
                return
        self._try_finalize_commit(height)

    def _try_finalize_commit(self, height: int) -> None:
        block_id = self.votes.precommits(self.commit_round).two_thirds_majority()
        if block_id is None or block_id.is_zero():
            return
        if self.proposal_block is None or not self.proposal_block.hash_to(block_id.hash):
            return  # wait for gossip to complete the block
        self._finalize_commit(height)

    def _pipeline_on(self) -> bool:
        """Pipelined finalize only on the live receive loop — WAL replay
        and pre-start harness drives keep the strictly serial ladder."""
        return self.pipeline_enabled and self._running

    def _finalize_commit(self, height: int) -> None:
        """Reference `finalizeCommit :1146-1243` with fail points
        bracketing every persistence step.

        Two tails share the persistence prefix (block save + WAL
        ENDHEIGHT): the serial tail applies the block inline before
        entering H+1, the pipelined tail (`_finalize_pipelined`)
        launches the apply as a dispatch handle and enters H+1's
        NewHeight immediately on a speculated state."""
        block = self.proposal_block
        parts = self.proposal_block_parts
        block_id = self.votes.precommits(self.commit_round).two_thirds_majority()
        assert block is not None and block.hash_to(block_id.hash)

        # Any failure from here on is an internal invariant/persistence
        # error, not bad peer input: once the block is saved / ENDHEIGHT is
        # WAL'd, a swallowed exception would leave a live node half-advanced
        # (store=H, WAL done, state=H-1) but still voting. Escalate so the
        # receive loop halts and crash recovery handles it on restart
        # (reference panics via PanicConsensus in finalizeCommit/ApplyBlock).
        try:
            fail_point()  # before block save
            if self.block_store is not None and self.block_store.height < height:
                seen_commit = self.votes.precommits(self.commit_round).make_commit()
                self.block_store.save_block(block, parts, seen_commit)

            fail_point()  # block saved, before WAL ENDHEIGHT
            if self.wal is not None:
                self.wal.save(EndHeightMessage(height))

            fail_point()  # ENDHEIGHT written, before ApplyBlock
            state_copy = self.state.copy()
            if self._pipeline_on():
                self._finalize_pipelined(height, block, parts, state_copy)
                return
            tx_results: list[tuple[bytes, object]] = []
            t_apply = time_mod.monotonic()
            apply_block(
                state_copy,
                block,
                parts.header,
                self.app_conn,
                mempool=self.mempool,
                verifier=self.verifier,
                tx_indexer=self.tx_indexer,
                on_tx_result=lambda i, tx, res: tx_results.append((tx, res)),
                hasher=self.hasher,
            )
            self._apply_s = time_mod.monotonic() - t_apply

            fail_point()  # applied, before round-state reset
            if self.evidence_pool is not None:
                # retire committed proofs + prune expired stragglers
                self.evidence_pool.update(height, list(block.evidence))
            self._observe_phase(None)  # closes the "commit" span
            draft = self._close_height_telemetry(height, block)
            self._record_height_ledger(draft, apply_s=self._apply_s)
            self._close_tx_traces(block, draft["wall_end"], height)
            self._update_to_state(state_copy)
        except FatalConsensusError:
            raise
        except Exception as e:
            raise FatalConsensusError(
                f"finalize_commit failed at height {height}"
            ) from e
        # Listener callbacks are external code — a raising subscriber must
        # not be escalated to a consensus halt, so fire outside the scope.
        self._fire_commit_events(height, block, tx_results)
        self._schedule_round0()
        # Announce H+1's NewHeight right away (the pipelined tail does
        # the same): peers that hear we advanced push their H+1
        # proposal/votes immediately instead of rediscovering us on the
        # next gossip poll tick.
        self.event_switch.fire(ev.EVENT_NEW_ROUND_STEP, self._rs_event())

    # ------------------------------------------------ pipelined finalize

    def _apply_queue(self):
        if self._apply_dispatch is None:
            from tendermint_tpu.services.dispatch import DispatchQueue

            # depth 1: at most one height's apply in flight — the next
            # finalize can only be reached through a vote tally, which
            # joins first. launch_ledger off: host work, not a device
            # launch (the device observatory must not count it).
            self._apply_dispatch = DispatchQueue(
                depth=1, name="apply", launch_ledger=False
            )
        return self._apply_dispatch

    def _finalize_pipelined(self, height, block, parts, state_copy) -> None:
        """Overlapped-apply tail of `_finalize_commit`: launch height
        H's `apply_block` (ABCI execute + state-tree hash + persist) on
        the apply dispatch queue, then enter H+1's NewHeight on a
        PROVISIONAL state speculated without the ABCI responses
        (`State.speculate_next`). Everything H+1 does before the join
        barrier (`_join_apply`) is either derivable pre-apply
        (last_commit, heights, block ids) or degrade-safe speculation
        (vote preverify launches); applied fields — app_hash, EndBlock
        valset changes, the updated mempool — are only readable past a
        join. A faulted apply surfaces at the join and halts consensus
        exactly like the serial path, so speculative state can never
        reach a signature on a forged fork."""
        tx_results: list[tuple[bytes, object]] = []

        def _run_apply():
            t0 = time_mod.monotonic()
            apply_block(
                state_copy,
                block,
                parts.header,
                self.app_conn,
                mempool=self.mempool,
                verifier=self.verifier,
                tx_indexer=self.tx_indexer,
                on_tx_result=lambda i, tx, res: tx_results.append((tx, res)),
                hasher=self.hasher,
            )
            return time_mod.monotonic() - t0

        handle = self._apply_queue().submit(_run_apply, kind="apply")
        self._observe_phase(None)  # closes the "commit" span pre-apply
        draft = self._close_height_telemetry(height, block)
        provisional = self.state.speculate_next(block.header, parts.header)
        self._pending_apply = {
            "height": height,
            "block": block,
            "handle": handle,
            "state": state_copy,
            "tx_results": tx_results,
            "draft": draft,
            "spec_val_hash": provisional.validators.hash(),
            "launched": time_mod.monotonic(),
        }
        FLIGHT.record(
            "commit_pipelined",
            height=height,
            round=self.commit_round,
            txs=len(block.data.txs),
        )
        self._update_to_state(provisional)
        self._schedule_round0()
        self.event_switch.fire(ev.EVENT_NEW_ROUND_STEP, self._rs_event())

    def _join_apply(self, reason: str) -> None:
        """The hard join barrier: block until H's in-flight apply lands,
        swap the applied state in for the provisional one, and run the
        post-apply bookkeeping the serial path did inline (evidence
        retirement, ledger record, commit events). Callers sit at every
        point that reads applied state: the proposer's block creation
        (`_enter_propose`), prevote validation (`_enter_prevote`), and
        current-height vote tallies. Idempotent no-op when nothing is
        pending. Raises FatalConsensusError on a faulted apply — the
        receive loop halts, exactly the serial failure mode."""
        pend = self._pending_apply
        if pend is None:
            return
        self._pending_apply = None
        t0 = time_mod.monotonic()
        stalled = not pend["handle"].done()
        try:
            apply_s = pend["handle"].result()
        except FatalConsensusError:
            raise
        except Exception as e:
            _metrics.PIPELINE_STALLS.labels(reason="fault").inc()
            FLIGHT.record(
                "pipeline_fault", height=pend["height"], error=type(e).__name__
            )
            raise FatalConsensusError(
                f"pipelined apply failed at height {pend['height']}"
            ) from e
        stall_s = time_mod.monotonic() - t0
        # an "idle" join blocked nothing — the loop had no input to
        # process; only barrier joins that made H+1 wait count as stalls
        stalled = stalled and reason != "idle"
        if stalled:
            _metrics.PIPELINE_STALLS.labels(reason=reason).inc()
        overlap_s = max(0.0, min(apply_s, apply_s - stall_s))
        self._apply_s = apply_s
        _metrics.APPLY_OVERLAP_SECONDS.observe(overlap_s)
        st = self.pipeline_stats
        st["joins"] += 1
        st["stalls"] += 1 if stalled else 0
        st["overlap_s_total"] += overlap_s
        st["last_overlap_s"] = overlap_s
        applied = pend["state"]
        height = pend["height"]
        block = pend["block"]
        if applied.validators.hash() != pend["spec_val_hash"]:
            # EndBlock rotated the valset: rebuild everything H+1
            # derived from the speculation. Nothing was consumed under
            # it — vote tallies and the proposer's path join first — so
            # a fresh HeightVoteSet and re-derived accum are complete.
            base = applied.validators.copy()
            self.votes = HeightVoteSet(applied.chain_id, self.height, base)
            if self.round > 0:
                vals = base.copy()
                vals.increment_accum(self.round)
                self.validators = vals
            else:
                self.validators = base
            self._valset_gen += 1
            self.pipeline_stats["valset_rebuilds"] += 1
            FLIGHT.record(
                "pipeline_valset_rebuild", height=self.height, round=self.round
            )
        self.state = applied
        if self.evidence_pool is not None:
            self.evidence_pool.update(height, list(block.evidence))
        self._record_height_ledger(
            pend["draft"], apply_s=apply_s, overlap_s=overlap_s, pipelined=True
        )
        self._close_tx_traces(block, pend["draft"]["wall_end"], height)
        self._fire_commit_events(height, block, pend["tx_results"])

    # ------------------------------------------------ commit bookkeeping

    def _close_height_telemetry(self, height: int, block: Block) -> dict:
        """Metrics + tracer spans closed at commit decide time, plus the
        draft snapshot the ledger record is assembled from — captured
        BEFORE `_update_to_state` wipes the per-height accumulators (the
        pipelined tail records at the join, a height later)."""
        height_wall = time_mod.monotonic() - self._height_started
        _metrics.CONSENSUS_HEIGHT_SECONDS.observe(height_wall)
        _metrics.CONSENSUS_COMMITS.inc()
        _metrics.CONSENSUS_TXS_COMMITTED.inc(len(block.data.txs))
        wall_end = time_mod.time()
        TRACER.add(
            "consensus.height",
            wall_end - height_wall,
            wall_end,
            height=height,
            round=self.commit_round,
            txs=len(block.data.txs),
        )
        FLIGHT.record(
            "commit",
            height=height,
            round=self.commit_round,
            txs=len(block.data.txs),
            hash=block.hash().hex()[:12],
        )
        return {
            "height": height,
            "round": self.commit_round,
            "txs": len(block.data.txs),
            "wall_end": wall_end,
            "height_wall": height_wall,
            "phase_acc": dict(self._phase_acc),
            "work0": self._height_work0,
            "work1": _heightlog.work_totals(),
            "val_arrivals": dict(self._val_arrivals),
        }

    def _close_tx_traces(self, block: Block, wall_end: float, height: int) -> None:
        """Close every committed traced tx: first-seen -> committed on
        THIS node's clock, linked back by exemplar trace id."""
        take_trace = getattr(self.mempool, "take_trace", None)
        if take_trace is None:
            return
        for tx in block.data.txs:
            entry = take_trace(bytes(tx))
            if entry is None:
                continue
            tx_ctx, t_seen = entry
            _metrics.TX_E2E.observe(wall_end - t_seen, exemplar=tx_ctx.trace)
            TRACER.add(
                "tx.e2e",
                t_seen,
                wall_end,
                trace=tx_ctx.trace,
                origin=tx_ctx.origin,
                height=height,
            )

    def _fire_commit_events(self, height: int, block: Block, tx_results) -> None:
        self.event_switch.fire(ev.EVENT_NEW_BLOCK, ev.EventDataNewBlock(block))
        self.event_switch.fire(
            ev.EVENT_NEW_BLOCK_HEADER, ev.EventDataNewBlockHeader(block.header)
        )
        _log_mod.kv(
            _log_mod.logger("consensus"),
            _logging.INFO,
            "block committed",
            height=height,
            txs=len(block.data.txs),
            hash=block.hash().hex()[:12],
        )
        # per-tx results: generic stream + hash-keyed (broadcast_tx_commit
        # waits on the keyed event — reference EventDataTx via event cache)
        from tendermint_tpu.types.tx import tx_hash

        for tx, res in tx_results:
            data = ev.EventDataTx(
                height=height, tx=tx, data=res.data, log=res.log, code=res.code
            )
            self.event_switch.fire(ev.EVENT_TX, data)
            self.event_switch.fire(ev.event_tx(tx_hash(tx)), data)

    def _record_height_ledger(
        self,
        draft: dict,
        apply_s: float,
        overlap_s: float = 0.0,
        pipelined: bool = False,
    ) -> None:
        """Assemble the height's ledger record from the finalize-time
        draft: phase durations with their wait-vs-work split, the
        commit-to-commit gap, critical-path attribution over the
        candidate contributors, and the laggard validator from the
        vote-arrival tracking. Pipelined records carry the overlap
        (`apply_overlap_s`) and count only the NON-overlapped apply
        share toward the critical path. Observability must never fail
        the commit — errors are printed, not raised."""
        try:
            height = draft["height"]
            wall_end = draft["wall_end"]
            height_wall = draft["height_wall"]
            work1 = draft["work1"]
            w0 = draft["work0"]
            phase_acc = draft["phase_acc"]
            verify_s = max(0.0, work1["verify"] - w0["verify"])
            hash_s = max(0.0, work1["hash"] - w0["hash"])
            coalescer_s = max(0.0, work1["coalescer"] - w0["coalescer"])
            dispatch_s = max(0.0, work1["dispatch"] - w0["dispatch"])
            phases: dict[str, dict] = {}
            for name in ("new_height", "propose", "prevote", "precommit", "commit"):
                dur, work = phase_acc.get(name, (0.0, 0.0))
                if name == "commit" and not pipelined:
                    # the serial commit phase closes AFTER apply; split
                    # the apply stopwatch out so it reads as its own
                    # phase (the pipelined phase closes pre-launch)
                    dur = max(0.0, dur - apply_s)
                work = min(work, dur)
                phases[name] = {
                    "s": round(dur, 6),
                    "work_s": round(work, 6),
                    "wait_s": round(max(0.0, dur - work), 6),
                }
            phases["apply"] = {
                "s": round(apply_s, 6),
                "work_s": round(apply_s, 6),
                "wait_s": 0.0,
            }
            for name, p in phases.items():
                _metrics.HEIGHT_PHASE_SECONDS.labels(phase=name).observe(p["s"])
            finality_s = None
            if self._last_commit_wall is not None:
                finality_s = max(0.0, wall_end - self._last_commit_wall)
                _metrics.FINALITY_SECONDS.observe(finality_s)
            self._last_commit_wall = wall_end
            # critical-path candidates: wall-clock phase groups plus the
            # registry-stitched device/coalescer stopwatch deltas (the
            # latter are process-global — cross-node sums in multi-node
            # harnesses; the wall-clock groups are per-node exact)
            contributors = {
                "proposal_wait": phases["new_height"]["s"] + phases["propose"]["s"],
                "vote_gather": phases["prevote"]["s"] + phases["precommit"]["s"],
                "commit_wait": phases["commit"]["s"],
                "coalescer_wait": coalescer_s,
                "dispatch_launch": verify_s + dispatch_s,
                "abci_apply": max(0.0, apply_s - overlap_s),
                "merkle_hash": hash_s,
            }
            critical = max(contributors, key=lambda k: contributors[k])
            laggard = None
            if draft["val_arrivals"]:
                idx, (addr, delay) = max(
                    draft["val_arrivals"].items(), key=lambda kv: kv[1][1]
                )
                laggard = {
                    "validator": addr,
                    "index": idx,
                    "delay_s": round(delay, 6),
                }
                _metrics.VOTE_ARRIVAL_MAX.set(delay)
            self.height_ledger.record(
                {
                    "height": height,
                    "round": draft["round"],
                    "txs": draft["txs"],
                    "t_start": round(wall_end - height_wall, 6),
                    "t_commit": round(wall_end, 6),
                    "height_s": round(height_wall, 6),
                    "finality_s": round(finality_s, 6)
                    if finality_s is not None
                    else None,
                    "phases": phases,
                    "path": {k: round(v, 6) for k, v in contributors.items()},
                    "critical_path": critical,
                    "laggard": laggard,
                    "pipelined": pipelined,
                    "apply_overlap_s": round(overlap_s, 6),
                }
            )
        except Exception:
            import traceback

            traceback.print_exc()

    # ---------------------------------------------------------------- votes

    def _evidence_val_set(self, height: int):
        """Validator set evidence at `height` must verify against. Only
        the live and previous sets are retained in memory; older
        evidence verifies best-effort against the current set (a
        validator absent from both is unprovable here and the evidence
        is rejected — the max-age window bounds how far back proofs can
        reach anyway)."""
        if height == self.height - 1 and self.state.last_validators is not None:
            if self.state.last_validators.size():
                return self.state.last_validators
        return self.validators

    def _report_misbehavior(self, peer_id: str, kind: str, detail: str = "") -> None:
        cb = self.on_peer_misbehavior
        if cb is not None and peer_id:
            try:
                cb(peer_id, kind, detail)
            except Exception:
                pass  # scoring must never hurt consensus

    def _found_conflicting_votes(
        self, err: ErrVoteConflictingVotes, peer_id: str
    ) -> None:
        """An equivocation surfaced (reference `tryAddVote`'s
        ErrVoteConflictingVotes branch — which the reference, like the
        seed here, used to throw away). Both votes carry verified
        signatures by construction, so the pair IS the proof: build
        DuplicateVoteEvidence and feed the pool (which WALs, gossips on
        0x38, and surfaces it to the next proposal)."""
        from tendermint_tpu.types.evidence import DuplicateVoteEvidence

        evidence = DuplicateVoteEvidence.make(err.vote_a, err.vote_b)
        FLIGHT.record(
            "evidence_detected",
            validator=evidence.address.hex()[:12],
            height=evidence.height,
            round=evidence.vote_a.round,
            type=evidence.vote_a.type,
            peer=peer_id[:12],
        )
        _log_mod.kv(
            _log_mod.logger("consensus"),
            _logging.WARNING,
            "conflicting votes detected",
            validator=evidence.address.hex()[:12],
            height=evidence.height,
            round=evidence.vote_a.round,
        )
        if self.evidence_pool is None:
            return
        try:
            self.evidence_pool.add_evidence(
                evidence, val_set=self._evidence_val_set(evidence.height)
            )
        except ValidationError:
            # locally detected pairs verified on entry; a failure here
            # means the offender left the retained valsets — drop it
            pass

    def _handle_vote(self, vote: Vote, peer_id: str, preverified: bool = False) -> None:
        """Reference `tryAddVote/addVote :1318-1453`."""
        try:
            self._handle_vote_inner(vote, peer_id, preverified)
        except ErrVoteConflictingVotes as e:
            self._found_conflicting_votes(e, peer_id)
            # The conflict can surface AFTER the tally moved: a vote for
            # a peer-maj23-tracked block is counted first, raises second
            # (`VoteSet._add_verified_vote`), and that count may have
            # JUST tipped +2/3. Swallowing the error without running the
            # post-add transitions wedged the height permanently (no
            # later vote re-triggers them — duplicates don't re-add).
            # The transition handlers are guarded + idempotent, so run
            # them unconditionally for current-height votes.
            if vote.height == self.height:
                if vote.type == VOTE_TYPE_PREVOTE:
                    self._on_prevote_added(vote)
                elif vote.type == VOTE_TYPE_PRECOMMIT:
                    self._on_precommit_added(vote)
        except (
            ErrVoteInvalidSignature,
            ErrVoteNonDeterministicSignature,
        ) as e:
            # forged/malleated signature: adversarial input, not noise —
            # debit the sender (a garbage-sig flood bans it) and move on
            # without letting the error reach the loop's traceback dump
            self._report_misbehavior(peer_id, "bad_sig", str(e))
        except (
            ErrVoteInvalidValidatorAddress,
            ErrVoteInvalidValidatorIndex,
        ) as e:
            self._report_misbehavior(peer_id, "bad_vote", str(e))
        except ErrVoteUnexpectedStep:
            # A vote whose (height, round, type) misses every live
            # tally — a straggler from a round the node has moved past.
            # Routine under WAN delay/reordering; never actionable and
            # must not take the receive loop down with it.
            pass

    def _handle_vote_inner(
        self, vote: Vote, peer_id: str, preverified: bool = False
    ) -> None:
        # LastCommit catchup: precommit for height-1 while in NewHeight step
        if vote.height + 1 == self.height:
            if (
                self.step == RoundStepType.NEW_HEIGHT
                and vote.type == VOTE_TYPE_PRECOMMIT
                and self.last_commit is not None
            ):
                if vote.round != self.last_commit.round:
                    # last_commit only tallies the round the block
                    # actually committed in. Under WAN reordering a
                    # straggler precommit from an earlier round of H-1
                    # is benign gossip noise — drop it instead of
                    # letting VoteSet raise ErrVoteUnexpectedStep,
                    # which would kill the receive loop.
                    return
                if self.last_commit.add_vote(
                    vote, verifier=self.verifier, preverified=preverified
                ):
                    self.event_switch.fire(ev.EVENT_VOTE, ev.EventDataVote(vote))
                    if (
                        self.config.skip_timeout_commit
                        and self.last_commit.has_all()
                    ):
                        # every precommit of H-1 is in: nothing left for
                        # the commit pacing to gather — start round 0 now
                        # (reference `handleMsg`'s skipTimeoutCommit leg)
                        self._enter_new_round(self.height, 0)
                elif self.gossip is not None and peer_id:
                    # catchup precommit we already tallied: the sender
                    # re-gossiped a known vote (own re-queues have
                    # peer_id="" and don't count)
                    self.gossip.redundant("vote", len(vote.encode()))
            return
        if vote.height != self.height:
            return

        # JOIN BARRIER: tallying a current-height vote binds its
        # validator index to a pubkey — that mapping must be the
        # post-EndBlock one. (Height-1 precommits above tally into
        # last_commit, whose valset was final before the pipeline
        # launched — they ride the overlap freely.)
        self._join_apply("vote_tally")
        added = self.votes.add_vote(
            vote, peer_id, verifier=self.verifier, preverified=preverified
        )
        if not added:
            # a VoteSet exact-duplicate add — before the gossip
            # observatory this wasted wire traffic vanished silently
            if self.gossip is not None and peer_id:
                self.gossip.redundant("vote", len(vote.encode()))
            return
        if self.gossip is not None:
            # first delivery of this (height, round, validator) vote on
            # this node: the propagation-map stamp gossip_report merges
            # across nodes (bounded like VoteArrivalRollup)
            self.gossip.first_seen(
                "vote", vote.height, vote.round, vote.validator_index
            )
        self.event_switch.fire(ev.EVENT_VOTE, ev.EventDataVote(vote))

        if vote.type == VOTE_TYPE_PREVOTE:
            self._on_prevote_added(vote)
        elif vote.type == VOTE_TYPE_PRECOMMIT:
            self._on_precommit_added(vote)

    def _on_prevote_added(self, vote: Vote) -> None:
        prevotes = self.votes.prevotes(vote.round)
        block_id = prevotes.two_thirds_majority()

        # POL unlock (reference `:1400-1420`): a newer-round polka for a
        # different block releases our lock.
        if (
            self.locked_block is not None
            and self.locked_round < vote.round <= self.round
            and block_id is not None
            and not self.locked_block.hash_to(block_id.hash)
        ):
            self.locked_round = -1
            self.locked_block = None
            self.locked_block_parts = None
            self.event_switch.fire(ev.EVENT_UNLOCK, self._rs_event())

        if self.round < vote.round and prevotes.has_two_thirds_any():
            # round skip
            self._enter_new_round(self.height, vote.round)
        elif self.round == vote.round:
            if block_id is not None and (
                self._is_proposal_complete() or block_id.is_zero()
            ):
                self._enter_precommit(self.height, vote.round)
            elif prevotes.has_two_thirds_any() and self.step == RoundStepType.PREVOTE:
                self._enter_prevote_wait(self.height, vote.round)
        elif (
            self.proposal is not None
            and 0 <= self.proposal.pol_round == vote.round
            and self._is_proposal_complete()
        ):
            self._enter_prevote(self.height, self.round)

    def _on_precommit_added(self, vote: Vote) -> None:
        precommits = self.votes.precommits(vote.round)
        block_id = precommits.two_thirds_majority()
        if block_id is not None:
            self._enter_new_round(self.height, vote.round)
            self._enter_precommit(self.height, vote.round)
            if not block_id.is_zero():
                self._enter_commit(self.height, vote.round)
                if self.config.skip_timeout_commit and precommits.has_all():
                    self._enter_new_round(self.height, 0)
            else:
                self._enter_precommit_wait(self.height, vote.round)
        elif self.round <= vote.round and precommits.has_two_thirds_any():
            self._enter_new_round(self.height, vote.round)
            self._enter_precommit_wait(self.height, vote.round)

    def _sign_add_vote(self, type_: int, hash_: bytes, header: PartSetHeader) -> None:
        """Reference `signAddVote :1471-1487`."""
        if self.priv_validator is None or not self.validators.has_address(
            self.priv_validator.address
        ):
            return
        idx, _ = self.validators.get_by_address(self.priv_validator.address)
        vote = Vote(
            validator_address=self.priv_validator.address,
            validator_index=idx,
            height=self.height,
            round=self.round,
            timestamp=time_mod.time_ns(),
            type=type_,
            block_id=BlockID(hash_, header),
        )
        try:
            vote = self.priv_validator.sign_vote(self.state.chain_id, vote)
        except ErrDoubleSign:
            return
        # Enqueue our own vote instead of handling it inline (reference
        # `sendInternalMessage :1471-1487`): a synchronous _handle_vote
        # here re-enters the transition functions — _enter_precommit can
        # finalize the height mid-call, after which the CALLER's
        # still-running transition (e.g. _on_precommit_added's
        # `_enter_commit(self.height, ...)`) reads the NEW height's
        # round state and corrupts it (observed as a fatal "enterCommit
        # without +2/3 precommits" under multi-node gossip load). The
        # queue item is WAL'd by the receive loop like any other input.
        # Vote creation is a trace edge: a vote cast FOR this height's
        # block is causally part of that block's trace (which the
        # proposer adopted from its first traced tx), so it re-hops the
        # block context — the whole decision path of a traced tx shares
        # one trace_id, node to node. Votes before any block context
        # exists (nil prevotes, early rounds) mint their own,
        # head-sampled.
        if self._proposal_ctx is not None:
            ctx = self._proposal_ctx.rehop()
        else:
            ctx = _trace.mint(self.priv_validator.address.hex()[:12])
        # self_signed: the tally trusts the signature it just produced —
        # re-verifying our own fresh vote through the device path cost a
        # full batch-of-1 launch per vote for nothing (replay clears the
        # flag: WAL records re-verify)
        self._queue.put(
            MsgRecord(vote, "", ctx=ctx, arrived=time_mod.time(), self_signed=True)
        )
