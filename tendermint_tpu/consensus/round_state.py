"""Round-state types (reference `consensus/types/`): the step enum, the
RoundState snapshot, and HeightVoteSet (prevotes+precommits per round).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Any

from tendermint_tpu.types.block_id import BlockID
from tendermint_tpu.types.errors import ValidationError
from tendermint_tpu.types.validator_set import ValidatorSet
from tendermint_tpu.types.vote import VOTE_TYPE_PRECOMMIT, VOTE_TYPE_PREVOTE, Vote
from tendermint_tpu.types.vote_set import VoteSet


class RoundStepType:
    """Reference `consensus/types/state.go` RoundStepType."""

    NEW_HEIGHT = 1
    NEW_ROUND = 2
    PROPOSE = 3
    PREVOTE = 4
    PREVOTE_WAIT = 5
    PRECOMMIT = 6
    PRECOMMIT_WAIT = 7
    COMMIT = 8

    NAMES = {
        1: "NewHeight",
        2: "NewRound",
        3: "Propose",
        4: "Prevote",
        5: "PrevoteWait",
        6: "Precommit",
        7: "PrecommitWait",
        8: "Commit",
    }

    @classmethod
    def name(cls, step: int) -> str:
        return cls.NAMES.get(step, f"Unknown({step})")


@dataclass
class RoundState:
    """Immutable-ish snapshot of the consensus internals
    (reference `consensus/types/state.go` RoundState)."""

    height: int
    round: int
    step: int
    start_time: float
    commit_time: float
    validators: ValidatorSet
    proposal: Any = None
    proposal_block: Any = None
    proposal_block_parts: Any = None
    locked_round: int = -1
    locked_block: Any = None
    locked_block_parts: Any = None
    votes: "HeightVoteSet | None" = None
    commit_round: int = -1
    last_commit: VoteSet | None = None
    last_validators: ValidatorSet | None = None

    def round_state_event(self):
        from tendermint_tpu.types.events import EventDataRoundState

        return EventDataRoundState(
            height=self.height,
            round=self.round,
            step=RoundStepType.name(self.step),
            round_state=self,
        )


class HeightVoteSet:
    """All VoteSets for one height: prevotes + precommits keyed by round
    (reference `consensus/types/height_vote_set.go:30-39`).

    Peers can only make us instantiate vote sets for 2 rounds above
    `self.round` (catchup cap `:105-126`) — otherwise a byzantine peer
    could force unbounded allocations.
    """

    def __init__(self, chain_id: str, height: int, val_set: ValidatorSet):
        self.chain_id = chain_id
        self.height = height
        self.val_set = val_set
        self.round = 0
        self._lock = threading.RLock()
        self._round_vote_sets: dict[int, dict[int, VoteSet]] = {}
        self._peer_catchup_rounds: dict[str, list[int]] = {}
        self._add_round(0)

    def _add_round(self, round_: int) -> None:
        if round_ in self._round_vote_sets:
            return
        self._round_vote_sets[round_] = {
            VOTE_TYPE_PREVOTE: VoteSet(
                self.chain_id, self.height, round_, VOTE_TYPE_PREVOTE, self.val_set
            ),
            VOTE_TYPE_PRECOMMIT: VoteSet(
                self.chain_id, self.height, round_, VOTE_TYPE_PRECOMMIT, self.val_set
            ),
        }

    def set_round(self, round_: int) -> None:
        """Ensure vote sets exist up to round+1 (reference `SetRound`)."""
        with self._lock:
            if round_ < self.round:
                raise ValidationError("set_round cannot decrease round")
            for r in range(self.round, round_ + 2):
                self._add_round(r)
            self.round = round_

    def add_vote(
        self, vote: Vote, peer_id: str = "", verifier=None, preverified: bool = False
    ) -> bool:
        with self._lock:
            if not self._is_vote_allowed(vote, peer_id):
                return False
            vs = self._get(vote.round, vote.type)
            if vs is None:
                self._add_round(vote.round)
                vs = self._get(vote.round, vote.type)
        return vs.add_vote(vote, verifier=verifier, preverified=preverified)

    def _is_vote_allowed(self, vote: Vote, peer_id: str) -> bool:
        if vote.round <= self.round + 1:
            return True
        # catchup: each peer may open at most 2 future rounds
        rounds = self._peer_catchup_rounds.setdefault(peer_id, [])
        if vote.round in rounds:
            return True
        if len(rounds) < 2:
            rounds.append(vote.round)
            self._add_round(vote.round)
            return True
        return False

    def _get(self, round_: int, type_: int) -> VoteSet | None:
        d = self._round_vote_sets.get(round_)
        return d[type_] if d else None

    def prevotes(self, round_: int) -> VoteSet | None:
        with self._lock:
            return self._get(round_, VOTE_TYPE_PREVOTE)

    def precommits(self, round_: int) -> VoteSet | None:
        with self._lock:
            return self._get(round_, VOTE_TYPE_PRECOMMIT)

    def pol_info(self) -> tuple[int, BlockID | None]:
        """Highest round with a prevote polka (reference `POLInfo`)."""
        with self._lock:
            for r in sorted(self._round_vote_sets, reverse=True):
                vs = self._get(r, VOTE_TYPE_PREVOTE)
                bid = vs.two_thirds_majority() if vs else None
                if bid is not None:
                    return r, bid
        return -1, None

    def set_peer_maj23(self, round_: int, type_: int, peer_id: str, block_id: BlockID) -> None:
        """Record a peer's +2/3 claim. Future-round claims ride the SAME
        per-peer catchup allowance as votes (at most 2 rounds above
        round+1 per peer) — without the gate a flooding peer could mint
        a fresh VoteSet pair per claimed round, unbounded per-height
        state (`consensus/reactor.py` Maj23 receive path)."""
        with self._lock:
            if round_ > self.round + 1:
                rounds = self._peer_catchup_rounds.setdefault(peer_id, [])
                if round_ not in rounds:
                    if len(rounds) >= 2:
                        return  # claim flood: refuse the allocation
                    rounds.append(round_)
            self._add_round(round_)
            vs = self._get(round_, type_)
        if vs is not None:
            vs.set_peer_maj23(peer_id, block_id)
