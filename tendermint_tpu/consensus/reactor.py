"""Consensus gossip reactor: replicate votes/proposals/parts over p2p.

Reference `consensus/reactor.go:21-25,98-125` — four prioritized
channels (State 0x20, Data 0x21, Vote 0x22, VoteSetBits 0x23), a
`PeerState` mirror of each peer's round progress (`:767-1100`), and
three gossip routines per peer: block data (`gossipDataRoutine:418`),
votes (`gossipVotesRoutine:542`), and 2/3-majority set reconciliation
(`queryMaj23Routine:652`).

The reactor is pure control plane: it moves signed artifacts; all
signature verification stays in the ConsensusState/VoteSet path (which
batches through the TPU verifier seam).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass

from tendermint_tpu.codec.binary import Reader, Writer
from tendermint_tpu.consensus.round_state import RoundStepType
from tendermint_tpu.p2p.connection import ChannelDescriptor
from tendermint_tpu.p2p.peer import Peer
from tendermint_tpu.p2p.switch import Reactor
from tendermint_tpu.types import events as ev
from tendermint_tpu.types.block_id import BlockID
from tendermint_tpu.types.heartbeat import Heartbeat
from tendermint_tpu.types.part_set import Part, PartSetHeader
from tendermint_tpu.types.proposal import Proposal
from tendermint_tpu.types.vote import VOTE_TYPE_PRECOMMIT, VOTE_TYPE_PREVOTE, Vote
from tendermint_tpu.utils.bit_array import BitArray
from tendermint_tpu.utils.log import kv as _log_kv, logger as _log
import logging as _logging

STATE_CHANNEL = 0x20
DATA_CHANNEL = 0x21
VOTE_CHANNEL = 0x22
VOTE_SET_BITS_CHANNEL = 0x23

_MSG_NEW_ROUND_STEP = 0x01
_MSG_COMMIT_STEP = 0x02
_MSG_PROPOSAL = 0x03
_MSG_PROPOSAL_POL = 0x04
_MSG_BLOCK_PART = 0x05
_MSG_VOTE = 0x06
_MSG_HAS_VOTE = 0x07
_MSG_VOTE_SET_MAJ23 = 0x08
_MSG_VOTE_SET_BITS = 0x09
_MSG_PROPOSAL_HEARTBEAT = 0x20  # reference msgTypeProposalHeartbeat

_GOSSIP_SLEEP_S = 0.05  # reference peerGossipSleepDuration=100ms, scaled down
_MAJ23_SLEEP_S = 0.5  # reference peerQueryMaj23SleepDuration=2s, scaled


def _w_bits(w: Writer, ba: BitArray | None) -> Writer:
    if ba is None:
        return w.uvarint(0)
    return w.uvarint(ba.size).uvarint(ba.to_int())


def _r_bits(r: Reader) -> BitArray | None:
    n = r.uvarint()
    if n == 0:
        return None
    return BitArray(n, r.uvarint())


# -- wire messages ------------------------------------------------------------


@dataclass(frozen=True)
class NewRoundStepMessage:
    """Reference `NewRoundStepMessage` (`consensus/reactor.go:1163`)."""

    height: int
    round: int
    step: int
    last_commit_round: int

    def encode(self) -> bytes:
        return (
            Writer()
            .uvarint(_MSG_NEW_ROUND_STEP)
            .uvarint(self.height)
            .uvarint(self.round)
            .uvarint(self.step)
            .svarint(self.last_commit_round)
            .build()
        )


@dataclass(frozen=True)
class CommitStepMessage:
    """Peer entered commit: advertises the committed parts header + which
    parts it already has (reference `CommitStepMessage:1184`)."""

    height: int
    parts_header: PartSetHeader
    parts: BitArray | None

    def encode(self) -> bytes:
        w = Writer().uvarint(_MSG_COMMIT_STEP).uvarint(self.height)
        w.raw(self.parts_header.encode())
        return _w_bits(w, self.parts).build()


@dataclass(frozen=True)
class ProposalMessage:
    proposal: Proposal

    def encode(self) -> bytes:
        return Writer().uvarint(_MSG_PROPOSAL).bytes(self.proposal.encode()).build()


@dataclass(frozen=True)
class ProposalPOLMessage:
    """Prevote bits for the proposal's POL round (reference `:1219`)."""

    height: int
    proposal_pol_round: int
    proposal_pol: BitArray

    def encode(self) -> bytes:
        w = (
            Writer()
            .uvarint(_MSG_PROPOSAL_POL)
            .uvarint(self.height)
            .uvarint(self.proposal_pol_round)
        )
        return _w_bits(w, self.proposal_pol).build()


@dataclass(frozen=True)
class BlockPartMessage:
    height: int
    round: int
    part: Part

    def encode(self) -> bytes:
        return (
            Writer()
            .uvarint(_MSG_BLOCK_PART)
            .uvarint(self.height)
            .uvarint(self.round)
            .bytes(self.part.encode())
            .build()
        )


@dataclass(frozen=True)
class VoteMessage:
    vote: Vote

    def encode(self) -> bytes:
        return Writer().uvarint(_MSG_VOTE).bytes(self.vote.encode()).build()


@dataclass(frozen=True)
class HasVoteMessage:
    height: int
    round: int
    type: int
    index: int

    def encode(self) -> bytes:
        return (
            Writer()
            .uvarint(_MSG_HAS_VOTE)
            .uvarint(self.height)
            .uvarint(self.round)
            .uvarint(self.type)
            .uvarint(self.index)
            .build()
        )


@dataclass(frozen=True)
class VoteSetMaj23Message:
    """Claim: +2/3 for block_id at (height, round, type) (reference `:1895`)."""

    height: int
    round: int
    type: int
    block_id: BlockID

    def encode(self) -> bytes:
        return (
            Writer()
            .uvarint(_MSG_VOTE_SET_MAJ23)
            .uvarint(self.height)
            .uvarint(self.round)
            .uvarint(self.type)
            .raw(self.block_id.encode())
            .build()
        )


@dataclass(frozen=True)
class ProposalHeartbeatMessage:
    """Signed proposer liveness ping, gossiped while the chain idles in
    no-empty-blocks mode (reference `:1149` msgTypeProposalHeartbeat,
    `reactor.go:219-220,338-343`)."""

    heartbeat: Heartbeat

    def encode(self) -> bytes:
        return (
            Writer()
            .uvarint(_MSG_PROPOSAL_HEARTBEAT)
            .bytes(self.heartbeat.encode())
            .build()
        )


@dataclass(frozen=True)
class VoteSetBitsMessage:
    """Answer to a Maj23 claim: which of those votes we have (`:1922`)."""

    height: int
    round: int
    type: int
    block_id: BlockID
    votes: BitArray | None

    def encode(self) -> bytes:
        w = (
            Writer()
            .uvarint(_MSG_VOTE_SET_BITS)
            .uvarint(self.height)
            .uvarint(self.round)
            .uvarint(self.type)
            .raw(self.block_id.encode())
        )
        return _w_bits(w, self.votes).build()


def decode_message(payload: bytes):
    r = Reader(payload)
    tag = r.uvarint()
    if tag == _MSG_NEW_ROUND_STEP:
        return NewRoundStepMessage(
            height=r.uvarint(),
            round=r.uvarint(),
            step=r.uvarint(),
            last_commit_round=r.svarint(),
        )
    if tag == _MSG_COMMIT_STEP:
        height = r.uvarint()
        header = PartSetHeader.decode_from(r)
        return CommitStepMessage(height, header, _r_bits(r))
    if tag == _MSG_PROPOSAL:
        return ProposalMessage(Proposal.decode(r.bytes()))
    if tag == _MSG_PROPOSAL_POL:
        return ProposalPOLMessage(r.uvarint(), r.uvarint(), _r_bits(r))
    if tag == _MSG_BLOCK_PART:
        return BlockPartMessage(r.uvarint(), r.uvarint(), Part.decode(r.bytes()))
    if tag == _MSG_VOTE:
        return VoteMessage(Vote.decode(r.bytes()))
    if tag == _MSG_HAS_VOTE:
        return HasVoteMessage(r.uvarint(), r.uvarint(), r.uvarint(), r.uvarint())
    if tag == _MSG_VOTE_SET_MAJ23:
        return VoteSetMaj23Message(
            r.uvarint(), r.uvarint(), r.uvarint(), BlockID.decode_from(r)
        )
    if tag == _MSG_VOTE_SET_BITS:
        return VoteSetBitsMessage(
            r.uvarint(),
            r.uvarint(),
            r.uvarint(),
            BlockID.decode_from(r),
            _r_bits(r),
        )
    if tag == _MSG_PROPOSAL_HEARTBEAT:
        return ProposalHeartbeatMessage(Heartbeat.decode(r.bytes()))
    raise ValueError(f"unknown consensus message tag {tag:#x}")


# -- peer state ---------------------------------------------------------------


class PeerState:
    """Our mirror of one peer's round progress (reference
    `consensus/reactor.go:767-1100`). All mutation under one lock;
    gossip routines read consistent snapshots.
    """

    def __init__(self, peer: Peer) -> None:
        self.peer = peer
        self._lock = threading.RLock()
        self.height = 0
        self.round = 0
        self.step = RoundStepType.NEW_HEIGHT
        self.last_commit_round = -1
        # proposal progress at (height, round)
        self.proposal = False
        self.proposal_parts_header: PartSetHeader | None = None
        self.proposal_parts: BitArray | None = None
        self.proposal_pol_round = -1
        self.proposal_pol: BitArray | None = None
        # commit-step parts progress (catchup gossip target)
        self.commit_parts_header: PartSetHeader | None = None
        self.commit_parts: BitArray | None = None
        # which votes the peer has: (height, round, type) -> BitArray
        self._vote_bits: dict[tuple[int, int, int], BitArray] = {}

    # -- reads -------------------------------------------------------------

    def snapshot(self) -> "PeerState":
        with self._lock:
            s = object.__new__(PeerState)
            s.peer = self.peer
            s._lock = self._lock
            s.height = self.height
            s.round = self.round
            s.step = self.step
            s.last_commit_round = self.last_commit_round
            s.proposal = self.proposal
            s.proposal_parts_header = self.proposal_parts_header
            s.proposal_parts = (
                self.proposal_parts.copy() if self.proposal_parts else None
            )
            s.proposal_pol_round = self.proposal_pol_round
            s.proposal_pol = self.proposal_pol
            s.commit_parts_header = self.commit_parts_header
            s.commit_parts = (
                self.commit_parts.copy() if self.commit_parts else None
            )
            s._vote_bits = {k: v.copy() for k, v in self._vote_bits.items()}
            return s

    def vote_bits(self, height: int, round_: int, type_: int, n: int) -> BitArray:
        with self._lock:
            key = (height, round_, type_)
            ba = self._vote_bits.get(key)
            if ba is None or ba.size != n:
                ba = BitArray(n)
                self._vote_bits[key] = ba
            return ba

    # -- writes (from receive path + our own sends) ------------------------

    def apply_new_round_step(self, msg: NewRoundStepMessage) -> None:
        with self._lock:
            new_hr = (msg.height, msg.round) != (self.height, self.round)
            if new_hr:
                self.proposal = False
                self.proposal_parts_header = None
                self.proposal_parts = None
                self.proposal_pol_round = -1
                self.proposal_pol = None
            if msg.height != self.height:
                self.commit_parts_header = None
                self.commit_parts = None
                # prune vote bitmaps below height-1
                self._vote_bits = {
                    k: v
                    for k, v in self._vote_bits.items()
                    if k[0] >= msg.height - 1
                }
            self.height = msg.height
            self.round = msg.round
            self.step = msg.step
            self.last_commit_round = msg.last_commit_round

    def apply_commit_step(self, msg: CommitStepMessage) -> None:
        with self._lock:
            if self.height != msg.height:
                return
            self.commit_parts_header = msg.parts_header
            self.commit_parts = msg.parts or BitArray(msg.parts_header.total)

    def apply_proposal(self, msg: ProposalMessage) -> None:
        with self._lock:
            p = msg.proposal
            if (p.height, p.round) != (self.height, self.round):
                return
            if self.proposal:
                return
            self.proposal = True
            self.proposal_parts_header = p.block_parts_header
            self.proposal_parts = BitArray(p.block_parts_header.total)
            self.proposal_pol_round = p.pol_round

    def apply_proposal_pol(self, msg: ProposalPOLMessage) -> None:
        with self._lock:
            if self.height != msg.height:
                return
            if self.proposal_pol_round != msg.proposal_pol_round:
                return
            self.proposal_pol = msg.proposal_pol

    def set_has_proposal_part(self, height: int, index: int) -> None:
        with self._lock:
            if self.height == height and self.proposal_parts is not None:
                if index < self.proposal_parts.size:
                    self.proposal_parts.set(index, True)
            if (
                self.commit_parts is not None
                and self.height == height
                and index < self.commit_parts.size
            ):
                self.commit_parts.set(index, True)

    def set_has_vote(self, height: int, round_: int, type_: int, index: int, n: int) -> None:
        ba = self.vote_bits(height, round_, type_, n)
        with self._lock:
            if index < ba.size:
                ba.set(index, True)

    def clear_height_bits(self, height: int) -> None:
        """Liveness insurance: forget what we sent for `height` so the
        gossip routines retry. Any send that raced the peer's height/
        round transition is dropped at the peer but stays marked here —
        periodic clearing (the maj23 tick) bounds how long such
        poisoning can wedge a round; duplicate resends dedup at the
        receiver's VoteSet."""
        with self._lock:
            for key in [k for k in self._vote_bits if k[0] == height]:
                del self._vote_bits[key]

    def apply_vote_set_bits(self, msg: VoteSetBitsMessage, our_votes: BitArray | None) -> None:
        """Update the peer's vote bitmap from a VoteSetBits answer
        (reference `ApplyVoteSetBitsMessage`): claimed bits REPLACE our
        mirror for votes we ourselves hold (we can cross-check those by
        sending them), while previously-marked bits outside our own set
        are kept — a false claim therefore cannot permanently suppress
        gossip of votes we actually have."""
        if msg.votes is None:
            return
        ba = self.vote_bits(msg.height, msg.round, msg.type, msg.votes.size)
        with self._lock:
            if our_votes is None:
                ba.update(msg.votes)
            else:
                other = ba.sub(our_votes)
                ba.update(other.or_(msg.votes))


# -- the reactor --------------------------------------------------------------


class ConsensusReactor(Reactor):
    """Wires a ConsensusState into the Switch (reference
    `consensus/reactor.go`)."""

    PEER_STATE_KEY = "consensus_peer_state"

    def __init__(self, cs, fast_sync: bool = False) -> None:
        super().__init__()
        self.cs = cs
        self.fast_sync = fast_sync
        self._running = False
        self._threads: list[threading.Thread] = []

    # -- reactor interface -------------------------------------------------

    def get_channels(self) -> list[ChannelDescriptor]:
        # priorities per reference `GetChannels :98-125`
        return [
            ChannelDescriptor(STATE_CHANNEL, priority=5),
            ChannelDescriptor(DATA_CHANNEL, priority=10),
            ChannelDescriptor(VOTE_CHANNEL, priority=5),
            ChannelDescriptor(VOTE_SET_BITS_CHANNEL, priority=1),
        ]

    def on_start(self) -> None:
        self._running = True
        # adversarial vote input (forged sigs, bogus validator claims)
        # surfaces inside the consensus loop, not here — route it back
        # to the switch's misbehavior scorer by peer id
        self.cs.on_peer_misbehavior = self._report_peer_misbehavior
        # gossip observatory: the consensus loop stamps redundant
        # vote/part deliveries and first-seen propagation into the
        # switch-owned rollup (same wiring seam as misbehavior above)
        self.cs.gossip = self.switch.gossip if self.switch is not None else None
        es = self.cs.event_switch
        es.add_listener("reactor", ev.EVENT_NEW_ROUND_STEP, self._on_new_round_step)
        es.add_listener("reactor", ev.EVENT_VOTE, self._on_vote_event)
        es.add_listener(
            "reactor", ev.EVENT_COMPLETE_PROPOSAL, self._on_complete_proposal
        )
        es.add_listener(
            "reactor", ev.EVENT_PROPOSAL_HEARTBEAT, self._on_proposal_heartbeat
        )
        if not self.fast_sync:
            self.cs.start()

    def on_stop(self) -> None:
        self._running = False
        self.cs.event_switch.remove_listener("reactor")
        self.cs.stop()

    def _report_peer_misbehavior(self, peer_id: str, kind: str, detail: str = "") -> None:
        if self.switch is not None and peer_id:
            self.switch.report_misbehavior(peer_id, kind, detail=detail)

    def switch_to_consensus(self, state) -> None:
        """Fast-sync caught up: adopt the synced state and start the
        consensus loop (reference `SwitchToConsensus
        consensus/reactor.go:79-96`)."""
        self.fast_sync = False
        if state.last_block_height > 0:
            self.cs.update_to_state(state)
        self.cs.start()

    def add_peer(self, peer: Peer) -> None:
        ps = PeerState(peer)
        peer.set(self.PEER_STATE_KEY, ps)
        # tell the new peer where we are
        peer.try_send(STATE_CHANNEL, self._our_step_message().encode())
        for fn, name in (
            (self._gossip_data_routine, "data"),
            (self._gossip_votes_routine, "votes"),
            (self._query_maj23_routine, "maj23"),
        ):
            # daemon threads exit via _peer_alive when the peer drops;
            # not retained (a churning peer set would leak the list)
            threading.Thread(
                target=fn, args=(peer, ps), name=f"gossip-{name}-{peer.id}", daemon=True
            ).start()

    def remove_peer(self, peer: Peer, reason) -> None:
        peer.set(self.PEER_STATE_KEY, None)  # gossip routines observe and exit

    # -- event broadcast ---------------------------------------------------

    def _our_step_message(self) -> NewRoundStepMessage:
        rs = self.cs.get_round_state()
        last_commit_round = (
            rs.last_commit.round if rs.last_commit is not None else -1
        )
        return NewRoundStepMessage(rs.height, rs.round, rs.step, last_commit_round)

    def _on_new_round_step(self, data) -> None:
        if self.switch is None:
            return
        # the event payload carries names only — take a fresh snapshot
        rs = self.cs.get_round_state()
        last_commit_round = (
            rs.last_commit.round if rs.last_commit is not None else -1
        )
        msg = NewRoundStepMessage(rs.height, rs.round, rs.step, last_commit_round)
        self.switch.broadcast(STATE_CHANNEL, msg.encode())
        if rs.step == RoundStepType.COMMIT and rs.proposal_block_parts is not None:
            commit_msg = CommitStepMessage(
                rs.height,
                rs.proposal_block_parts.header,
                rs.proposal_block_parts.parts_bit_array.copy(),
            )
            self.switch.broadcast(STATE_CHANNEL, commit_msg.encode())

    def _on_vote_event(self, data) -> None:
        """Push-path vote propagation. The reference only broadcasts
        HasVote and relies on the 100ms-poll gossip routines for the
        votes themselves; under the Python threading model the polling
        loops are latency-bound (GIL churn across ~10 threads/peer), so
        the reactor pushes every newly-added vote immediately and keeps
        the poll routines as retry/catchup backfill."""
        if self.switch is None:
            return
        vote = data.vote
        has = HasVoteMessage(vote.height, vote.round, vote.type, vote.validator_index)
        vmsg = VoteMessage(vote).encode()
        n = len(self.cs.get_round_state().validators)
        for peer in self.switch.peers():
            ps: PeerState | None = peer.get(self.PEER_STATE_KEY)
            if ps is None:
                continue
            # Only push to peers AT the vote's height. A peer at any
            # other height drops the vote on its height check (its
            # last-commit catchup only accepts votes for height-1, never
            # height+1) while we mark it delivered — permanently
            # poisoning the sent-bits so gossip never resends (observed:
            # late joiners wedged forever at the height where live
            # pushes started, and end-of-height races wedging a round).
            if ps.height != vote.height:
                continue
            theirs = ps.vote_bits(vote.height, vote.round, vote.type, n)
            if not theirs.get(vote.validator_index):
                if peer.try_send(VOTE_CHANNEL, vmsg):
                    ps.set_has_vote(
                        vote.height, vote.round, vote.type, vote.validator_index, n
                    )
            peer.try_send(STATE_CHANNEL, has.encode())

    def _on_complete_proposal(self, data) -> None:
        """Push the completed proposal + parts to peers at our height
        (same GIL-latency rationale as `_on_vote_event`; the reference's
        poll loop `gossipDataRoutine:418` remains as backfill)."""
        if self.switch is None:
            return
        rs = self.cs.get_round_state()
        for peer in self.switch.peers():
            ps: PeerState | None = peer.get(self.PEER_STATE_KEY)
            if ps is not None:
                self._push_proposal_to(peer, ps, rs)

    def _push_proposal_to(self, peer: Peer, ps: PeerState, rs) -> None:
        """Send our current proposal + the parts this peer is missing,
        if the peer is at our height."""
        if rs.proposal is None or rs.proposal_block_parts is None:
            return
        prs = ps.snapshot()
        if prs.height != rs.height:
            return
        if not prs.proposal:
            pmsg = ProposalMessage(rs.proposal)
            if peer.try_send(DATA_CHANNEL, pmsg.encode()):
                ps.apply_proposal(pmsg)
            prs = ps.snapshot()
        for i in range(rs.proposal_block_parts.total):
            if prs.proposal_parts is not None and prs.proposal_parts.get(i):
                continue
            part = rs.proposal_block_parts.get_part(i)
            if part is None:
                continue
            if peer.try_send(
                DATA_CHANNEL,
                BlockPartMessage(rs.height, rs.round, part).encode(),
            ):
                ps.set_has_proposal_part(rs.height, i)

    def _push_catchup(self, peer: Peer, ps: PeerState) -> None:
        """Event-driven gossip handoff (the cross-height pipeline's peer
        half): the moment a peer announces a height/round advance, push
        the proposal, parts, and votes it is missing at its new position
        instead of letting it rediscover them on the next 50 ms poll
        tick. The poll routines remain as retry/backfill — this is the
        same push-over-poll rationale as `_on_vote_event`, applied to
        the commit→NewHeight handoff where a peer finishing height H
        used to miss every push for H+1 that happened while it was
        still finalizing."""
        if self.fast_sync or not self._running:
            return
        # runs on the peer's RECV thread: never stall it behind a
        # drowning peer's send queue — the poll routines backfill
        if peer.send_queue_depth() > 64:
            return
        rs = self.cs.get_round_state()
        prs = ps.snapshot()
        if prs.height != rs.height:
            return
        # Only the PROPOSER pushes the block here: if every peer pushed
        # its copy to every newly-advanced peer, each height's block
        # would cross the wire once per connection (measured ~2.5x
        # loaded finality regression). Non-proposers contribute the
        # cheap part — votes — and the poll routines backfill parts.
        if self.cs.is_proposer():
            self._push_proposal_to(peer, ps, rs)
        # one missing vote per call; bits advance so the loop terminates
        n = 2 * len(rs.validators) + 2
        for _ in range(n):
            if not self._gossip_votes_same_height(peer, ps, rs, ps.snapshot()):
                break

    # -- receive -----------------------------------------------------------

    def receive(self, chan_id: int, peer: Peer, payload: bytes) -> None:
        ps: PeerState | None = peer.get(self.PEER_STATE_KEY)
        if ps is None:
            return
        msg = decode_message(payload)
        if chan_id == STATE_CHANNEL:
            self._receive_state(peer, ps, msg)
        elif chan_id == DATA_CHANNEL:
            self._receive_data(peer, ps, msg)
        elif chan_id == VOTE_CHANNEL:
            self._receive_vote(peer, ps, msg)
        elif chan_id == VOTE_SET_BITS_CHANNEL:
            self._receive_vote_set_bits(peer, ps, msg)

    def _on_proposal_heartbeat(self, hb: Heartbeat) -> None:
        """Broadcast our own heartbeats to every peer (reference
        `broadcastProposalHeartbeatMessage reactor.go:344-349`)."""
        if not self._running or self.switch is None:
            return
        self.switch.broadcast(
            STATE_CHANNEL, ProposalHeartbeatMessage(hb).encode()
        )

    def _receive_state(self, peer: Peer, ps: PeerState, msg) -> None:
        if isinstance(msg, NewRoundStepMessage):
            prev = ps.snapshot()
            ps.apply_new_round_step(msg)
            if msg.height != prev.height:
                # the peer finished a height: hand it whatever it now
                # lacks at its new one, without waiting for a gossip
                # poll tick (round/step-only changes stay with the
                # normal push+poll paths — pushing on every step
                # transition measurably congests loaded nets)
                self._push_catchup(peer, ps)
        elif isinstance(msg, CommitStepMessage):
            ps.apply_commit_step(msg)
        elif isinstance(msg, HasVoteMessage):
            n = len(self.cs.get_round_state().validators)
            ps.set_has_vote(msg.height, msg.round, msg.type, msg.index, n)
        elif isinstance(msg, ProposalHeartbeatMessage):
            # Verify and log; do NOT re-fire the local event — only a
            # node's own heartbeats are broadcast (re-firing would gossip
            # forever between peers). Reference `reactor.go:219-222`.
            hb = msg.heartbeat
            rs = self.cs.get_round_state()
            val = None
            if 0 <= hb.validator_index < len(rs.validators):
                val = rs.validators.validators[hb.validator_index]
            if val is None or val.address != hb.validator_address:
                return
            if not val.pub_key.verify(
                hb.sign_bytes(self.cs.state.chain_id), hb.signature
            ):
                return
            _log_kv(
                _log("consensus"),
                _logging.DEBUG,
                "peer proposal heartbeat",
                peer=peer.id[:12],
                height=hb.height,
                round=hb.round,
                seq=hb.sequence,
            )
        elif isinstance(msg, VoteSetMaj23Message):
            rs = self.cs.get_round_state()
            if rs.height != msg.height or rs.votes is None:
                return
            # record the claim (triggers conflict-evidence tracking)
            rs.votes.set_peer_maj23(msg.round, msg.type, peer.id, msg.block_id)
            # answer with which of those votes we have
            vs = (
                rs.votes.prevotes(msg.round)
                if msg.type == VOTE_TYPE_PREVOTE
                else rs.votes.precommits(msg.round)
            )
            our_bits = vs.bit_array_by_block_id(msg.block_id) if vs else None
            answer = VoteSetBitsMessage(
                msg.height, msg.round, msg.type, msg.block_id, our_bits
            )
            peer.try_send(VOTE_SET_BITS_CHANNEL, answer.encode())

    def _receive_data(self, peer: Peer, ps: PeerState, msg) -> None:
        if self.fast_sync:
            return  # reference ignores consensus gossip during fast-sync
        if isinstance(msg, ProposalMessage):
            ps.apply_proposal(msg)
            self.cs.set_proposal(msg.proposal, peer.id)
        elif isinstance(msg, ProposalPOLMessage):
            ps.apply_proposal_pol(msg)
        elif isinstance(msg, BlockPartMessage):
            ps.set_has_proposal_part(msg.height, msg.part.index)
            self.cs.add_proposal_block_part(msg.height, msg.round, msg.part, peer.id)

    def _receive_vote(self, peer: Peer, ps: PeerState, msg) -> None:
        if self.fast_sync or not isinstance(msg, VoteMessage):
            return
        vote = msg.vote
        n = len(self.cs.get_round_state().validators)
        ps.set_has_vote(vote.height, vote.round, vote.type, vote.validator_index, n)
        self.cs.add_vote(vote, peer.id)

    def _receive_vote_set_bits(self, peer: Peer, ps: PeerState, msg) -> None:
        if not isinstance(msg, VoteSetBitsMessage):
            return
        rs = self.cs.get_round_state()
        our_votes = None
        if rs.height == msg.height and rs.votes is not None:
            vs = (
                rs.votes.prevotes(msg.round)
                if msg.type == VOTE_TYPE_PREVOTE
                else rs.votes.precommits(msg.round)
            )
            our_votes = vs.bit_array_by_block_id(msg.block_id) if vs else None
        ps.apply_vote_set_bits(msg, our_votes)

    # -- gossip: block data ------------------------------------------------

    def _peer_alive(self, peer: Peer) -> bool:
        return self._running and peer.get(self.PEER_STATE_KEY) is not None

    def _gossip_data_routine(self, peer: Peer, ps: PeerState) -> None:
        while self._peer_alive(peer):
            rs = self.cs.get_round_state()
            prs = ps.snapshot()

            # 1. send proposal block parts the peer is missing (same h/r)
            if (
                rs.proposal_block_parts is not None
                and rs.height == prs.height
                and prs.proposal_parts is not None
                and prs.proposal_parts_header is not None
                and rs.proposal_block_parts.has_header(prs.proposal_parts_header)
            ):
                ours = rs.proposal_block_parts.parts_bit_array.copy()
                idx, ok = ours.sub(prs.proposal_parts).pick_random()
                if ok:
                    part = rs.proposal_block_parts.get_part(idx)
                    if part is not None:
                        msg = BlockPartMessage(rs.height, rs.round, part)
                        if peer.send(DATA_CHANNEL, msg.encode()):
                            ps.set_has_proposal_part(rs.height, idx)
                        continue

            # 2. peer lags: feed parts of the stored block for its height
            if prs.height > 0 and prs.height < rs.height:
                if self._gossip_catchup_part(peer, prs):
                    continue

            # 3. send the proposal itself (+ POL bits)
            if (
                rs.proposal is not None
                and rs.height == prs.height
                and rs.round == prs.round
                and not prs.proposal
            ):
                if peer.send(
                    DATA_CHANNEL, ProposalMessage(rs.proposal).encode()
                ):
                    ps.apply_proposal(ProposalMessage(rs.proposal))
                if rs.proposal.pol_round >= 0 and rs.votes is not None:
                    pol = rs.votes.prevotes(rs.proposal.pol_round)
                    if pol is not None:
                        peer.try_send(
                            DATA_CHANNEL,
                            ProposalPOLMessage(
                                rs.height, rs.proposal.pol_round, pol.bit_array()
                            ).encode(),
                        )
                continue

            time.sleep(_GOSSIP_SLEEP_S)

    def _gossip_catchup_part(self, peer: Peer, prs: "PeerState") -> bool:
        """Send one stored-block part for a lagging peer (reference
        `gossipDataForCatchup :499-540`). The peer normally advertises
        its commit parts header via CommitStepMessage; if that one-shot
        message was lost (race with the first NewRoundStep), a peer
        stuck in commit step adopts OUR stored header — the +2/3
        precommit parts header is unique per height, so it must match."""
        if prs.commit_parts_header is None or prs.commit_parts is None:
            if prs.step != RoundStepType.COMMIT:
                return False
            meta = self.cs.block_store.load_block_meta(prs.height)
            if meta is None:
                return False
            ps: PeerState | None = peer.get(self.PEER_STATE_KEY)
            if ps is None:
                return False
            ps.apply_commit_step(
                CommitStepMessage(prs.height, meta.block_id.parts_header, None)
            )
            prs = ps.snapshot()
            if prs.commit_parts_header is None or prs.commit_parts is None:
                return False
        meta = self.cs.block_store.load_block_meta(prs.height)
        if meta is None or meta.block_id.parts_header != prs.commit_parts_header:
            return False
        missing = prs.commit_parts.not_()
        idx, ok = missing.pick_random()
        if not ok:
            return False
        part = self.cs.block_store.load_block_part(prs.height, idx)
        if part is None:
            return False
        msg = BlockPartMessage(prs.height, prs.round, part)
        if peer.send(DATA_CHANNEL, msg.encode()):
            ps: PeerState | None = peer.get(self.PEER_STATE_KEY)
            if ps is not None:
                ps.set_has_proposal_part(prs.height, idx)
        return True

    # -- gossip: votes -----------------------------------------------------

    def _gossip_votes_routine(self, peer: Peer, ps: PeerState) -> None:
        while self._peer_alive(peer):
            rs = self.cs.get_round_state()
            prs = ps.snapshot()

            sent = False
            if rs.height == prs.height:
                sent = self._gossip_votes_same_height(peer, ps, rs, prs)
            elif rs.height == prs.height + 1 and rs.last_commit is not None:
                # peer is finishing our previous height: its precommits
                # are our last_commit
                sent = self._send_vote_from_set(
                    peer, ps, rs.last_commit, prs.height, rs.last_commit.round,
                    VOTE_TYPE_PRECOMMIT,
                )
            elif rs.height > prs.height + 1 or (
                rs.height == prs.height + 1 and rs.last_commit is None
            ):
                sent = self._gossip_catchup_commit_vote(peer, ps, prs)
            if not sent:
                time.sleep(_GOSSIP_SLEEP_S)

    def _gossip_votes_same_height(self, peer, ps, rs, prs) -> bool:
        if rs.votes is None:
            return False
        # order per reference gossipVotesForHeight :607-652
        if prs.step == RoundStepType.NEW_HEIGHT and rs.last_commit is not None:
            if self._send_vote_from_set(
                peer, ps, rs.last_commit, rs.height - 1, rs.last_commit.round,
                VOTE_TYPE_PRECOMMIT,
            ):
                return True
        if (
            prs.step <= RoundStepType.PROPOSE
            and prs.proposal_pol_round >= 0
        ):
            pol = rs.votes.prevotes(prs.proposal_pol_round)
            if pol is not None and self._send_vote_from_set(
                peer, ps, pol, rs.height, prs.proposal_pol_round, VOTE_TYPE_PREVOTE
            ):
                return True
        if prs.step <= RoundStepType.PREVOTE_WAIT and prs.round <= rs.round:
            pv = rs.votes.prevotes(prs.round)
            if pv is not None and self._send_vote_from_set(
                peer, ps, pv, rs.height, prs.round, VOTE_TYPE_PREVOTE
            ):
                return True
        if prs.step <= RoundStepType.PRECOMMIT_WAIT and prs.round <= rs.round:
            pc = rs.votes.precommits(prs.round)
            if pc is not None and self._send_vote_from_set(
                peer, ps, pc, rs.height, prs.round, VOTE_TYPE_PRECOMMIT
            ):
                return True
        # catchup: peer in an older round
        if prs.round >= 0 and prs.round < rs.round:
            pv = rs.votes.prevotes(prs.round)
            if pv is not None and self._send_vote_from_set(
                peer, ps, pv, rs.height, prs.round, VOTE_TYPE_PREVOTE
            ):
                return True
            pc = rs.votes.precommits(prs.round)
            if pc is not None and self._send_vote_from_set(
                peer, ps, pc, rs.height, prs.round, VOTE_TYPE_PRECOMMIT
            ):
                return True
        return False

    def _send_vote_from_set(self, peer, ps, vote_set, height, round_, type_) -> bool:
        n = len(vote_set.val_set) if hasattr(vote_set, "val_set") else vote_set.bit_array().size
        theirs = ps.vote_bits(height, round_, type_, n)
        with ps._lock:
            missing = vote_set.bit_array().sub(theirs)
        idx, ok = missing.pick_random()
        if not ok:
            return False
        vote = vote_set.get_by_index(idx)
        if vote is None:
            return False
        if peer.send(VOTE_CHANNEL, VoteMessage(vote).encode()):
            ps.set_has_vote(height, round_, type_, idx, n)
            return True
        return False

    def _gossip_catchup_commit_vote(self, peer, ps, prs) -> bool:
        """Peer is ≥2 heights behind: replay precommits from the stored
        seen-commit for its height (reference `:577-595`)."""
        if prs.height == 0:
            return False
        commit = self.cs.block_store.load_seen_commit(prs.height)
        if commit is None:
            commit = self.cs.block_store.load_block_commit(prs.height)
        if commit is None:
            return False
        n = len(commit.precommits)
        theirs = ps.vote_bits(prs.height, commit.round(), VOTE_TYPE_PRECOMMIT, n)
        have = BitArray(n)
        for i, pc in enumerate(commit.precommits):
            if pc is not None:
                have.set(i, True)
        with ps._lock:
            missing = have.sub(theirs)
        idx, ok = missing.pick_random()
        if not ok:
            return False
        vote = commit.precommits[idx]
        if peer.send(VOTE_CHANNEL, VoteMessage(vote).encode()):
            ps.set_has_vote(prs.height, commit.round(), VOTE_TYPE_PRECOMMIT, idx, n)
            return True
        return False

    # -- gossip: maj23 queries --------------------------------------------

    def _query_maj23_routine(self, peer: Peer, ps: PeerState) -> None:
        """Periodically tell peers which vote sets we see majorities in so
        they can send us exactly the votes we miss (reference `:652-739`)."""
        last_hrs = None
        while self._peer_alive(peer):
            time.sleep(_MAJ23_SLEEP_S)
            rs = self.cs.get_round_state()
            prs = ps.snapshot()
            # Clear the sent-vote mirror ONLY when the peer has made no
            # height/round/step progress for a full tick — a send that
            # raced its transition may have been dropped, so retrying is
            # the liveness insurance. Clearing unconditionally (as the
            # old code did) re-sent every vote of the height to every
            # peer twice a second in steady state.
            cur = (prs.height, prs.round, prs.step)
            if cur == last_hrs:
                ps.clear_height_bits(prs.height)
            last_hrs = cur
            # Re-announce our own step every tick: a NewRoundStep lost
            # on a lossy link (or swallowed by a partition) leaves the
            # peer's mirror of US stale, which disables every gossip
            # path toward us — we cannot detect that from our side, so
            # the retry must be unconditional. 20 idempotent bytes per
            # 0.5 s per peer buys partition-heal and late-join liveness.
            peer.try_send(STATE_CHANNEL, self._our_step_message().encode())
            if rs.votes is None or rs.height != prs.height:
                continue
            for round_ in (rs.round, prs.round, prs.proposal_pol_round):
                if round_ is None or round_ < 0:
                    continue
                for type_, vs in (
                    (VOTE_TYPE_PREVOTE, rs.votes.prevotes(round_)),
                    (VOTE_TYPE_PRECOMMIT, rs.votes.precommits(round_)),
                ):
                    if vs is None:
                        continue
                    maj = vs.two_thirds_majority()
                    if maj is None:
                        continue
                    peer.try_send(
                        STATE_CHANNEL,
                        VoteSetMaj23Message(rs.height, round_, type_, maj).encode(),
                    )
