"""Handshake / block replay on startup (reference `consensus/replay.go`).

Recovery path (b) of SURVEY.md §5.4: on boot the app reports its
(height, app_hash) over ABCI Info; the Handshaker reconciles app vs
block store vs state by replaying stored blocks into the app, including
the delicate store == state+1 cases:

* app committed the final block but state didn't save → replay it
  against a mock app built from the saved ABCIResponses (so the real
  app, already at H, is never re-mutated) — reference `:284-289,362-398`;
* state saved but app didn't commit → replay the final block for real.
"""

from __future__ import annotations

from tendermint_tpu.abci.application import Application
from tendermint_tpu.abci.client import AppConns, local_client_creator
from tendermint_tpu.abci.types import Result, Validator as ABCIValidator
from tendermint_tpu.blockchain.store import BlockStore
from tendermint_tpu.state import apply_block, exec_commit_block
from tendermint_tpu.state.state import ABCIResponses, State
from tendermint_tpu.types.errors import ValidationError


class HandshakeError(ValidationError):
    pass


class _StoredResponsesApp(Application):
    """Mock app replaying saved ABCIResponses (reference mockProxyApp):
    DeliverTx/EndBlock answer from the stored responses; Commit returns
    the app hash the real app already reported."""

    def __init__(self, app_hash: bytes, responses: ABCIResponses) -> None:
        self._app_hash = app_hash
        self._responses = responses
        self._tx_index = 0

    def deliver_tx(self, tx: bytes) -> Result:
        res = self._responses.deliver_tx[self._tx_index]
        self._tx_index += 1
        return res

    def end_block(self, height: int) -> list[ABCIValidator]:
        if height != self._responses.height:
            raise HandshakeError(
                f"mock app EndBlock height {height} != stored {self._responses.height}"
            )
        return self._responses.end_block_changes

    def commit(self) -> Result:
        return Result(data=self._app_hash)


class Handshaker:
    def __init__(self, state: State, store: BlockStore, verifier=None) -> None:
        self.state = state
        self.store = store
        self.verifier = verifier
        self.n_blocks_replayed = 0

    def handshake(self, app_conns: AppConns) -> bytes:
        """Sync the app with the store/state; returns the final app hash
        (reference `Handshake :196-221`)."""
        info = app_conns.query.info_sync()
        app_height = info.last_block_height
        app_hash = info.last_block_app_hash
        if app_height < 0:
            raise HandshakeError(f"app reported negative height {app_height}")
        app_hash = self.replay_blocks(app_hash, app_height, app_conns)
        return app_hash

    def replay_blocks(self, app_hash: bytes, app_height: int, app_conns: AppConns) -> bytes:
        """Reference `ReplayBlocks :225-296`."""
        store_height = self.store.height
        state_height = self.state.last_block_height

        if app_height == 0:
            # genesis: tell the app the initial validator set
            validators = [
                ABCIValidator(pub_key=v.pub_key.data, power=v.voting_power)
                for v in self.state.validators.validators
            ]
            app_conns.consensus.init_chain_sync(validators)

        if store_height == 0:
            return app_hash

        if store_height < state_height:
            raise HandshakeError(
                f"block store height {store_height} < state height {state_height}"
            )
        if store_height > state_height + 1:
            raise HandshakeError(
                f"block store height {store_height} > state height {state_height}+1"
            )

        if store_height == state_height:
            # replay all missing blocks into the app, no state changes
            if app_height > store_height:
                raise HandshakeError(
                    f"app height {app_height} > store height {store_height}"
                )
            return self._replay_to_app(app_height, store_height, app_conns, app_hash)

        # store_height == state_height + 1: the final block is saved but
        # not applied to state
        if app_height == store_height:
            # app committed the final block; replay it against the mock
            # app from saved ABCIResponses so state catches up
            responses = self.state.load_abci_responses(store_height)
            if responses is None:
                raise HandshakeError(
                    f"no saved ABCIResponses for final block {store_height}"
                )
            mock_conns = local_client_creator(
                _StoredResponsesApp(app_hash, responses)
            )()
            self._apply_final_block(mock_conns, store_height)
            return app_hash

        # app is at or behind state: replay into app up to state height,
        # then apply the final block for real
        app_hash = self._replay_to_app(app_height, state_height, app_conns, app_hash)
        self._apply_final_block(app_conns, store_height)
        return app_conns.query.info_sync().last_block_app_hash

    def _replay_to_app(
        self, app_height: int, target: int, app_conns: AppConns, app_hash: bytes
    ) -> bytes:
        for h in range(app_height + 1, target + 1):
            block = self.store.load_block(h)
            if block is None:
                raise HandshakeError(f"missing block {h} in store")
            app_hash = exec_commit_block(app_conns.consensus, block)
            self.n_blocks_replayed += 1
        # cross-check the store's app-hash chain (header H+1 carries
        # the app hash after block H)
        meta = self.store.load_block_meta(target + 1)
        if meta is not None and meta.header.app_hash != app_hash:
            raise HandshakeError(
                f"app hash after replay to {target} is {app_hash.hex()}, "
                f"but header {target + 1} expects {meta.header.app_hash.hex()}"
            )
        return app_hash

    def _apply_final_block(self, app_conns: AppConns, height: int) -> None:
        block = self.store.load_block(height)
        meta = self.store.load_block_meta(height)
        if block is None or meta is None:
            raise HandshakeError(f"missing final block {height}")
        apply_block(
            self.state,
            block,
            meta.block_id.parts_header,
            app_conns.consensus,
            verifier=self.verifier,
        )
        self.n_blocks_replayed += 1
