"""Timeout scheduling (reference `consensus/ticker.go`).

One timer; a scheduled timeout replaces any older one and only fires if
still relevant (>= the height/round/step it was scheduled for). Tocks
land on the consensus message queue like any other input.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Callable


@dataclass(frozen=True)
class TimeoutInfo:
    duration: float  # seconds
    height: int
    round: int
    step: int

    def _key(self):
        return (self.height, self.round, self.step)


class TimeoutTicker:
    """Thread-timer implementation of the tick/tock contract."""

    def __init__(self) -> None:
        self._timer: threading.Timer | None = None
        self._current: TimeoutInfo | None = None
        self._lock = threading.Lock()
        self._on_timeout: Callable[[TimeoutInfo], None] | None = None
        self._stopped = False

    def set_on_timeout(self, fn: Callable[[TimeoutInfo], None]) -> None:
        self._on_timeout = fn

    def schedule(self, ti: TimeoutInfo) -> None:
        """Replace the pending timeout unless it's for an older HRS
        (reference ticker ignores ticks for the past)."""
        with self._lock:
            if self._stopped:
                return
            if self._current is not None and ti._key() < self._current._key():
                return
            if self._timer is not None:
                self._timer.cancel()
            self._current = ti
            self._timer = threading.Timer(ti.duration, self._fire, args=(ti,))
            self._timer.daemon = True
            self._timer.start()

    def _fire(self, ti: TimeoutInfo) -> None:
        with self._lock:
            if self._stopped or self._current is not ti:
                return
            self._current = None
        if self._on_timeout is not None:
            self._on_timeout(ti)

    def stop(self) -> None:
        with self._lock:
            self._stopped = True
            if self._timer is not None:
                self._timer.cancel()


class MockTicker:
    """Test ticker (reference `consensus/common_test.go:435-478`): only
    fires NewHeight timeouts (step 1) immediately-ish; tests drive every
    other transition by injecting messages."""

    def __init__(self, fire_steps: tuple[int, ...] = (1,)) -> None:
        self._on_timeout: Callable[[TimeoutInfo], None] | None = None
        self.fire_steps = fire_steps
        self.scheduled: list[TimeoutInfo] = []

    def set_on_timeout(self, fn: Callable[[TimeoutInfo], None]) -> None:
        self._on_timeout = fn

    def schedule(self, ti: TimeoutInfo) -> None:
        self.scheduled.append(ti)
        if ti.step in self.fire_steps and self._on_timeout is not None:
            # fire on a fresh thread to mimic the async tock channel
            t = threading.Thread(
                target=self._on_timeout,
                args=(ti,),
                name="consensus-timeout",
                daemon=True,
            )
            t.start()

    def stop(self) -> None:
        pass
