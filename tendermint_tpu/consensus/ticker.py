"""Timeout scheduling (reference `consensus/ticker.go`) and the
measured-latency timeout policy.

One timer; a scheduled timeout replaces any older one and only fires if
still relevant (>= the height/round/step it was scheduled for). Tocks
land on the consensus message queue like any other input.

`AdaptiveTimeouts` replaces the fixed timeout ladder with values derived
from what the node actually measures — the HeightLedger's per-phase
durations and the per-peer vote-arrival rollup — so a healthy net stops
sleeping out static worst-case timeouts (ROADMAP item 3). The derived
values are **clamped to the configured fixed values as ceilings** (a
byzantine peer that inflates its measured latencies can never push a
timeout past what the operator configured) and fall back to the fixed
ladder entirely until enough heights have been measured.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import Callable


@dataclass(frozen=True)
class TimeoutInfo:
    duration: float  # seconds
    height: int
    round: int
    step: int

    def _key(self):
        return (self.height, self.round, self.step)


class TimeoutTicker:
    """Thread-timer implementation of the tick/tock contract."""

    def __init__(self) -> None:
        self._timer: threading.Timer | None = None
        self._current: TimeoutInfo | None = None
        self._lock = threading.Lock()
        self._on_timeout: Callable[[TimeoutInfo], None] | None = None
        self._stopped = False

    def set_on_timeout(self, fn: Callable[[TimeoutInfo], None]) -> None:
        self._on_timeout = fn

    def schedule(self, ti: TimeoutInfo) -> None:
        """Replace the pending timeout unless it's for an older HRS
        (reference ticker ignores ticks for the past)."""
        with self._lock:
            if self._stopped:
                return
            if self._current is not None and ti._key() < self._current._key():
                return
            if self._timer is not None:
                self._timer.cancel()
            self._current = ti
            self._timer = threading.Timer(ti.duration, self._fire, args=(ti,))
            self._timer.daemon = True
            self._timer.start()

    def _fire(self, ti: TimeoutInfo) -> None:
        with self._lock:
            if self._stopped or self._current is not ti:
                return
            self._current = None
        if self._on_timeout is not None:
            self._on_timeout(ti)

    def stop(self) -> None:
        with self._lock:
            self._stopped = True
            if self._timer is not None:
                self._timer.cancel()


class AdaptiveTimeouts:
    """Measured-latency timeout derivation, clamped to the config.

    Exposes the same four methods `ConsensusConfig` does, so
    `ConsensusState` calls one policy object either way:

    * `propose_timeout(round)` — how long to wait for a complete
      proposal: p95 of the recent heights' measured `propose` phase
      (which spans proposal creation + gossip + part completion),
      times a safety factor.
    * `prevote_timeout(round)` / `precommit_timeout(round)` — the
      PrevoteWait/PrecommitWait grace after +2/3-any: p95 of the
      matching measured phase. Deliberately NOT the arrival estimate —
      arrival delay measures signing→arrival and misses the remote's
      time-to-decide (block validation before signing), which is
      exactly what grows under load; the phase duration includes it.
    * `commit_timeout()` — the NewHeight pacing whose only purpose is
      gathering the remaining precommits for a fuller last_commit:
      the vote-arrival estimate (signing→arrival IS the gather window).

    Byzantine robustness: the arrival estimate is the **median of the
    per-peer mean delays** — a minority of peers stamping absurd vote
    timestamps (already clamped to `heightlog.MAX_ARRIVAL_S` at
    observation) moves the estimate only if they outnumber honest
    peers, and whatever they achieve is still clamped to the configured
    fixed value. Cold start (fewer than `MIN_HEIGHTS` ledger records,
    or an empty rollup for the arrival-based phases) falls back to the
    fixed ladder.

    Derived values are exported per phase on
    `tendermint_consensus_timeout_derived_seconds{phase=}`.
    """

    MIN_HEIGHTS = 8  # ledger records before derivation engages
    WINDOW = 64  # newest heights considered
    SAFETY = 3.0  # measured -> timeout headroom multiplier
    # WAN floor: a timeout below ~1.5x the measured one-way network
    # delay can NEVER gather remote input — it expires while the
    # honest answer is still in flight. The original fixed floor
    # (`timeout_derived_floor`, 2 ms) was fit to in-process links;
    # under 100–300 ms WAN RTTs it let a fast-measured phase derive a
    # timeout shorter than the wire, and every such round is a
    # guaranteed spurious skip (validated by the slow-WAN scenario,
    # testing/scenario.py). The estimate is the byzantine-robust
    # median-of-per-peer-means, and the configured ceiling still wins,
    # so a minority of slow-stamping peers can only ever raise the
    # floor toward physics, never past the operator's cap.
    RTT_FLOOR_MULT = 1.5

    def __init__(self, config, rollup=None, ledger=None) -> None:
        self.config = config
        self.rollup = rollup
        self.ledger = ledger

    # -- measurement inputs ------------------------------------------------

    def _phase_p95(self, phase: str) -> float | None:
        """p95 of the phase's recent per-height durations (seconds)."""
        if self.ledger is None:
            return None
        recs = self.ledger.recent(self.WINDOW)
        vals = sorted(
            r["phases"][phase]["s"]
            for r in recs
            if isinstance(r.get("phases"), dict) and phase in r["phases"]
        )
        if len(vals) < self.MIN_HEIGHTS:
            return None
        return vals[min(len(vals) - 1, int(0.95 * len(vals)))]

    def _arrival_estimate(self) -> float | None:
        """Median of per-peer mean vote-arrival delays (seconds)."""
        if self.rollup is None:
            return None
        snap = self.rollup.snapshot()
        means = sorted(st["mean_ms"] / 1e3 for st in snap.values())
        if not means or (self.ledger is not None and len(self.ledger) < self.MIN_HEIGHTS):
            return None
        return means[len(means) // 2]

    # -- the policy --------------------------------------------------------

    def _enabled(self) -> bool:
        return bool(getattr(self.config, "adaptive_timeouts", False)) and (
            os.environ.get("TENDERMINT_TPU_ADAPTIVE_TIMEOUTS", "1") != "0"
        )

    def _floor(self) -> float:
        """Configured static floor OR the measured-RTT floor, whichever
        is higher — derived timeouts never drop below what the network
        round trip physically requires (clamping to the ceiling happens
        in `_derive`, so the operator's cap still dominates)."""
        static = getattr(self.config, "timeout_derived_floor", 2) / 1000.0
        rtt = self._arrival_estimate()
        if rtt is None:
            return static
        return max(static, rtt * self.RTT_FLOOR_MULT)

    def _derive(self, phase: str, measured: float | None, ceiling: float) -> float:
        """Clamp SAFETY×measured into [floor, ceiling]; None → ceiling
        (the configured fixed value — cold start / opt-out)."""
        if measured is None or not self._enabled():
            return ceiling
        derived = max(min(ceiling, self._floor()), min(ceiling, measured * self.SAFETY))
        from tendermint_tpu.telemetry import metrics as _metrics

        _metrics.CONSENSUS_TIMEOUT_DERIVED.labels(phase=phase).set(derived)
        return derived

    def propose_timeout(self, round_: int) -> float:
        return self._derive(
            "propose", self._phase_p95("propose"), self.config.propose_timeout(round_)
        )

    def prevote_timeout(self, round_: int) -> float:
        return self._derive(
            "prevote", self._phase_p95("prevote"), self.config.prevote_timeout(round_)
        )

    def precommit_timeout(self, round_: int) -> float:
        return self._derive(
            "precommit",
            self._phase_p95("precommit"),
            self.config.precommit_timeout(round_),
        )

    def commit_timeout(self) -> float:
        return self._derive(
            "commit", self._arrival_estimate(), self.config.commit_timeout()
        )


class MockTicker:
    """Test ticker (reference `consensus/common_test.go:435-478`): only
    fires NewHeight timeouts (step 1) immediately-ish; tests drive every
    other transition by injecting messages."""

    def __init__(self, fire_steps: tuple[int, ...] = (1,)) -> None:
        self._on_timeout: Callable[[TimeoutInfo], None] | None = None
        self.fire_steps = fire_steps
        self.scheduled: list[TimeoutInfo] = []

    def set_on_timeout(self, fn: Callable[[TimeoutInfo], None]) -> None:
        self._on_timeout = fn

    def schedule(self, ti: TimeoutInfo) -> None:
        self.scheduled.append(ti)
        if ti.step in self.fire_steps and self._on_timeout is not None:
            # fire on a fresh thread to mimic the async tock channel
            t = threading.Thread(
                target=self._on_timeout,
                args=(ti,),
                name="consensus-timeout",
                daemon=True,
            )
            t.start()

    def stop(self) -> None:
        pass
