"""Interactive / batch WAL replay — the ops tool for stepping a recorded
consensus WAL through a fresh state machine (reference
`consensus/replay_file.go:24-80` RunReplayFile + playback, CLI commands
`replay` / `replay_console`, `cmd/tendermint/commands/replay.go:9-26`).

The playback drives each WAL record through `ConsensusState._dispatch`
exactly like crash-recovery catchup does (`state.py` `_catchup_replay`),
but under manual control: `next [N]` steps records, `back [N]` rebuilds
the state machine from scratch and replays to count-N (the state machine
cannot step backwards), `rs [field]` inspects the live RoundState, `n`
prints the record count. Replaying writes through the real block/state
stores exactly like the reference's console does; point --home at a copy
to keep the original pristine.
"""

from __future__ import annotations

from tendermint_tpu.consensus.state import ConsensusState
from tendermint_tpu.consensus.wal import WAL, EndHeightMessage, RoundStateRecord


class Playback:
    """Stepper over a recorded WAL (reference `playback`,
    `replay_file.go:83-142`).

    `make_cs` builds a FRESH ConsensusState (new app conns + handshake)
    each time — called once up front and again on every `back`.
    """

    def __init__(self, make_cs, wal_path: str, out=print) -> None:
        self._make_cs = make_cs
        self._out = out
        self.cs: ConsensusState = make_cs()
        self.records = [
            rec
            for rec in WAL.iter_records(wal_path)
            if not isinstance(rec, (EndHeightMessage, RoundStateRecord))
        ]
        self.count = 0  # records applied so far

    # -- stepping ----------------------------------------------------------

    def _apply(self, rec) -> None:
        try:
            with self.cs._mtx:
                self.cs._dispatch(rec)
        except Exception as e:  # WAL'd-before-validation inputs may be bad
            self._out(f"record {self.count}: dispatch error: {e}")

    def step(self, n: int = 1) -> int:
        """Apply up to n records; returns how many were applied."""
        applied = 0
        while applied < n and self.count < len(self.records):
            self._apply(self.records[self.count])
            self.count += 1
            applied += 1
        return applied

    def run_all(self) -> int:
        return self.step(len(self.records) - self.count)

    def back(self, n: int = 1) -> None:
        """Rebuild from scratch and replay count-n records (reference
        `replayReset` — the state machine has no reverse gear)."""
        target = max(0, self.count - n)
        self._out(f"resetting from {self.count} to {target}")
        self.cs = self._make_cs()
        self.count = 0
        self.step(target)

    def done(self) -> bool:
        return self.count >= len(self.records)

    # -- inspection --------------------------------------------------------

    def round_state(self, field: str | None = None) -> str:
        rs = self.cs.get_round_state()
        if field is None:
            votes = rs.votes
            return (
                f"height={rs.height} round={rs.round} step={rs.step!r}\n"
                f"proposal={rs.proposal}\n"
                f"proposal_block={'set' if rs.proposal_block else None}\n"
                f"locked_round={rs.locked_round} "
                f"locked_block={'set' if rs.locked_block else None}\n"
                f"votes={votes.summary() if hasattr(votes, 'summary') else votes}"
            )
        if field == "short":
            return f"{rs.height}/{rs.round}/{rs.step!r}"
        if hasattr(rs, field):
            return repr(getattr(rs, field))
        return f"unknown field {field!r}"

    # -- console -----------------------------------------------------------

    def console(self, input_fn=input) -> None:
        """Interactive loop (reference `replayConsoleLoop`)."""
        self._out(
            f"{len(self.records)} records loaded. commands: "
            "next [N] | back [N] | rs [field|short] | n | quit"
        )
        while True:
            try:
                line = input_fn("> ")
            except EOFError:
                return
            tokens = line.strip().split()
            if not tokens:
                continue
            cmd, rest = tokens[0], tokens[1:]
            if cmd in ("quit", "exit", "q"):
                return
            elif cmd == "next":
                n = 1
                if rest:
                    try:
                        n = int(rest[0])
                    except ValueError:
                        self._out("next takes an integer argument")
                        continue
                if self.step(n) == 0:
                    self._out("end of WAL")
            elif cmd == "back":
                n = 1
                if rest:
                    try:
                        n = int(rest[0])
                    except ValueError:
                        self._out("back takes an integer argument")
                        continue
                if n > self.count:
                    self._out(
                        f"argument to back must not exceed the current "
                        f"count ({self.count})"
                    )
                    continue
                self.back(n)
            elif cmd == "rs":
                self._out(self.round_state(rest[0] if rest else None))
            elif cmd == "n":
                self._out(str(self.count))
            else:
                self._out(f"unknown command {cmd!r}")


def make_replay_cs_factory(config, app_factory=None, db_provider=None):
    """Factory-of-factories for the CLI: each call rebuilds app conns,
    handshakes the stores into the app, and returns a ConsensusState with
    no WAL, no signer, and a ticker whose timeouts never fire (the
    reference's replay ignores ticks — records drive every transition).
    """

    def make_cs() -> ConsensusState:
        from tendermint_tpu.abci.apps import KVStoreApp
        from tendermint_tpu.abci.client import local_client_creator
        from tendermint_tpu.blockchain.store import BlockStore
        from tendermint_tpu.consensus.replay import Handshaker
        from tendermint_tpu.consensus.ticker import MockTicker
        from tendermint_tpu.db.kv import SQLiteDB
        from tendermint_tpu.state.state import load_state, make_genesis_state
        from tendermint_tpu.types.genesis import GenesisDoc

        def _db(name):
            if db_provider is not None:
                return db_provider(name)
            return SQLiteDB(config.db_path(name))

        state_db = _db("state")
        st = load_state(state_db)
        if st is None:
            st = make_genesis_state(
                state_db, GenesisDoc.from_file(config.genesis_path())
            )
        store = BlockStore(_db("blockstore"))
        app = app_factory() if app_factory is not None else KVStoreApp()
        app_conns = local_client_creator(app)()
        Handshaker(st, store).handshake(app_conns)
        return ConsensusState(
            config.consensus,
            st,
            app_conns.consensus,
            store,
            ticker=MockTicker(fire_steps=()),  # records drive everything
        )

    return make_cs
