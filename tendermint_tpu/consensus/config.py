"""Consensus timing/behavior config (reference `config/config.go:295-400`)."""

from __future__ import annotations

from dataclasses import dataclass


@dataclass
class ConsensusConfig:
    # timeouts in milliseconds; *_delta grows per round
    timeout_propose: int = 3000
    timeout_propose_delta: int = 500
    timeout_prevote: int = 1000
    timeout_prevote_delta: int = 500
    timeout_precommit: int = 1000
    timeout_precommit_delta: int = 500
    timeout_commit: int = 1000
    # Round-skip deadline while parked at PREVOTE/PRECOMMIT without the
    # +2/3-any that would arm the *_wait timeouts (the CPU-starvation
    # liveness gap: gossip can idle for seconds and nothing else moves
    # the round forward). Generous by design — healthy networks never
    # hit it; 0 disables. Fires as later Tendermint's OnTimeoutPrevote/
    # OnTimeoutPrecommit: precommit nil, then next round.
    timeout_round_skip: int = 10_000
    timeout_round_skip_delta: int = 2_000
    skip_timeout_commit: bool = False
    # Cross-height pipeline (ROADMAP item 3): at finalize, height H's
    # ABCI apply + state advance run as a dispatch handle while H+1's
    # NewHeight/Propose proceed on a speculated (no-EndBlock-changes)
    # state; a hard join barrier guards every applied-state read. False
    # restores the strictly serial propose→...→commit→apply ladder
    # (also forced by TENDERMINT_TPU_PIPELINE=0).
    pipeline_commit: bool = True
    # Measured-latency timeouts: derive the propose/prevote/precommit
    # waits and the commit pacing from the live HeightLedger phase
    # percentiles + vote-arrival rollup, clamped to the fixed values
    # above as ceilings (consensus/ticker.py AdaptiveTimeouts). False
    # (or TENDERMINT_TPU_ADAPTIVE_TIMEOUTS=0) sleeps the fixed ladder.
    adaptive_timeouts: bool = True
    # floor any derived timeout so a burst of sub-ms measurements can
    # never spin the ticker (milliseconds)
    timeout_derived_floor: int = 2
    create_empty_blocks: bool = True
    create_empty_blocks_interval: int = 0  # seconds
    # proposer liveness ping cadence while waiting for txs in
    # no-empty-blocks mode (reference proposalHeartbeatIntervalSeconds)
    proposal_heartbeat_interval: float = 2.0
    max_block_size_txs: int = 10_000
    wal_light: bool = False

    def propose_timeout(self, round_: int) -> float:
        return (self.timeout_propose + self.timeout_propose_delta * round_) / 1000.0

    def prevote_timeout(self, round_: int) -> float:
        return (self.timeout_prevote + self.timeout_prevote_delta * round_) / 1000.0

    def precommit_timeout(self, round_: int) -> float:
        return (self.timeout_precommit + self.timeout_precommit_delta * round_) / 1000.0

    def commit_timeout(self) -> float:
        return self.timeout_commit / 1000.0

    def round_skip_timeout(self, round_: int) -> float:
        """Seconds before a starved PREVOTE/PRECOMMIT skips ahead; <= 0
        means disabled."""
        if self.timeout_round_skip <= 0:
            return 0.0
        return (
            self.timeout_round_skip + self.timeout_round_skip_delta * round_
        ) / 1000.0

    @classmethod
    def test_config(cls) -> "ConsensusConfig":
        """Shrunk timeouts (reference `TestConsensusConfig
        config/config.go:389-400`)."""
        return cls(
            timeout_propose=100,
            timeout_propose_delta=1,
            timeout_prevote=10,
            timeout_prevote_delta=1,
            timeout_precommit=10,
            timeout_precommit_delta=1,
            timeout_commit=10,
            # long enough that loaded CI never skips a healthy round,
            # short enough that starvation tests finish (nemesis tunes
            # it further down for its round-skip scenario)
            timeout_round_skip=2_000,
            timeout_round_skip_delta=100,
            skip_timeout_commit=True,
        )
