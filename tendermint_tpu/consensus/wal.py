"""Consensus write-ahead log (reference `consensus/wal.go`).

Every consensus input (peer/internal message, timeout) is persisted
*before* processing (`consensus/state.go:519-528`), so a crash at any
point replays deterministically. Records are CRC32-framed:

    [crc32 u32 BE][length u32 BE][type u8][payload]

`#ENDHEIGHT` markers delimit completed heights; on restart, replay
starts after the last marker (`SearchForEndHeight :122`). `light` mode
skips persisting peer block-parts (reference `wal.go:97-104`).
"""

from __future__ import annotations

import os
import struct
import time
import zlib
from dataclasses import dataclass, field
from typing import Iterator

from tendermint_tpu.codec import Reader, Writer
from tendermint_tpu.telemetry import metrics as _metrics
from tendermint_tpu.types.part_set import Part
from tendermint_tpu.types.proposal import Proposal
from tendermint_tpu.types.vote import Vote

# record types
_T_END_HEIGHT = 0x01
_T_VOTE = 0x02
_T_PROPOSAL = 0x03
_T_BLOCK_PART = 0x04
_T_TIMEOUT = 0x05
_T_ROUND_STATE = 0x06


@dataclass(frozen=True)
class EndHeightMessage:
    height: int


@dataclass(frozen=True)
class TimeoutRecord:
    duration: float
    height: int
    round: int
    step: int


@dataclass(frozen=True)
class RoundStateRecord:
    """Step-transition marker (the reference WALs EventDataRoundState)."""

    height: int
    round: int
    step: int


@dataclass(frozen=True)
class MsgRecord:
    """A consensus input: vote/proposal/block-part + its origin peer.

    `ctx`/`arrived` are IN-MEMORY tracing metadata (the trace context
    ambient when the input was enqueued + its wall-clock arrival) —
    deliberately not WAL-encoded and excluded from equality: replayed
    records are the same consensus input with or without a trace."""

    msg: object  # Vote | Proposal | (height, round, Part)
    peer_id: str
    ctx: object = field(default=None, compare=False, repr=False)
    arrived: float = field(default=0.0, compare=False, repr=False)
    # IN-MEMORY like ctx/arrived: True only for votes this node signed
    # itself this session — the tally skips re-verifying its own fresh
    # signature. Never WAL-encoded, so replayed records verify fully.
    self_signed: bool = field(default=False, compare=False, repr=False)


def _encode_record(item) -> bytes:
    if isinstance(item, EndHeightMessage):
        return bytes([_T_END_HEIGHT]) + Writer().uvarint(item.height).build()
    if isinstance(item, TimeoutRecord):
        payload = (
            Writer()
            .uvarint(int(item.duration * 1e6))
            .uvarint(item.height)
            .uvarint(item.round)
            .uvarint(item.step)
            .build()
        )
        return bytes([_T_TIMEOUT]) + payload
    if isinstance(item, RoundStateRecord):
        payload = Writer().uvarint(item.height).uvarint(item.round).uvarint(item.step).build()
        return bytes([_T_ROUND_STATE]) + payload
    if isinstance(item, MsgRecord):
        m = item.msg
        if isinstance(m, Vote):
            body = bytes([_T_VOTE]) + Writer().string(item.peer_id).bytes(m.encode()).build()
        elif isinstance(m, Proposal):
            body = bytes([_T_PROPOSAL]) + Writer().string(item.peer_id).bytes(m.encode()).build()
        else:
            height, round_, part = m
            payload = (
                Writer()
                .string(item.peer_id)
                .uvarint(height)
                .uvarint(round_)
                .bytes(part.encode())
                .build()
            )
            body = bytes([_T_BLOCK_PART]) + payload
        return body
    raise TypeError(f"cannot WAL {type(item)}")


def _decode_record(data: bytes):
    t, payload = data[0], data[1:]
    r = Reader(payload)
    if t == _T_END_HEIGHT:
        return EndHeightMessage(r.uvarint())
    if t == _T_TIMEOUT:
        dur = r.uvarint() / 1e6
        return TimeoutRecord(dur, r.uvarint(), r.uvarint(), r.uvarint())
    if t == _T_ROUND_STATE:
        return RoundStateRecord(r.uvarint(), r.uvarint(), r.uvarint())
    if t == _T_VOTE:
        peer = r.string()
        return MsgRecord(Vote.decode(r.bytes()), peer)
    if t == _T_PROPOSAL:
        peer = r.string()
        return MsgRecord(Proposal.decode(r.bytes()), peer)
    if t == _T_BLOCK_PART:
        peer = r.string()
        return MsgRecord((r.uvarint(), r.uvarint(), Part.decode(r.bytes())), peer)
    raise ValueError(f"unknown WAL record type {t}")


class WAL:
    """Write-ahead log with size-based file rotation (reference
    `autofile.Group` rolling files under `consensus/wal.go`). Segments
    rotate only at ENDHEIGHT boundaries, so every file is a valid
    record stream beginning at a height boundary; readers walk
    `path.N` segments oldest-first, then the live `path` file."""

    MAX_FILE_BYTES = 10 * 1024 * 1024  # reference autofile default
    MAX_SEGMENTS = 10  # pruned oldest-first (reference Group head cap)

    def __init__(
        self,
        path: str,
        light: bool = False,
        max_file_bytes: int | None = None,
        max_segments: int | None = None,
    ) -> None:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self.path = path
        self.light = light
        self.max_file_bytes = (
            self.MAX_FILE_BYTES if max_file_bytes is None else max_file_bytes
        )
        self.max_segments = (
            self.MAX_SEGMENTS if max_segments is None else max_segments
        )
        self._f = open(path, "ab")

    @staticmethod
    def segment_paths(path: str) -> list[str]:
        """All WAL files in replay order: rotated segments (ascending
        index) then the live file."""
        base = os.path.basename(path)
        dirname = os.path.dirname(path) or "."
        segs = []
        if os.path.isdir(dirname):
            for name in os.listdir(dirname):
                if name.startswith(base + "."):
                    suffix = name[len(base) + 1 :]
                    if suffix.isdigit():
                        segs.append((int(suffix), os.path.join(dirname, name)))
        out = [p for _, p in sorted(segs)]
        if os.path.exists(path):
            out.append(path)
        return out

    def save(self, item) -> None:
        """Frame + append + fsync (writes happen BEFORE processing)."""
        if self.light and isinstance(item, MsgRecord) and isinstance(item.msg, tuple):
            if item.peer_id != "":
                return  # light mode: drop peer block-parts
        body = _encode_record(item)
        frame = struct.pack(">II", zlib.crc32(body) & 0xFFFFFFFF, len(body)) + body
        # the fsync IS the consensus write barrier — its latency gates
        # every input the receive loop processes, so it gets a histogram
        t0 = time.perf_counter()
        self._f.write(frame)
        self._f.flush()
        os.fsync(self._f.fileno())
        _metrics.WAL_FSYNC_SECONDS.observe(time.perf_counter() - t0)
        _metrics.WAL_WRITTEN_BYTES.inc(len(frame))
        # rotate only at height boundaries: every segment then starts
        # with the records of a fresh height (replay never spans a cut)
        if (
            isinstance(item, EndHeightMessage)
            and self._f.tell() >= self.max_file_bytes
        ):
            self._rotate()

    def _rotate(self) -> None:
        self._f.close()
        existing = self.segment_paths(self.path)
        next_idx = 0
        for seg in existing:
            suffix = os.path.basename(seg)[len(os.path.basename(self.path)) + 1 :]
            if suffix.isdigit():
                next_idx = max(next_idx, int(suffix) + 1)
        os.replace(self.path, f"{self.path}.{next_idx}")
        # prune oldest segments beyond the cap
        segs = [p for p in self.segment_paths(self.path) if p != self.path]
        while len(segs) > self.max_segments:
            os.remove(segs.pop(0))
        self._f = open(self.path, "ab")

    def close(self) -> None:
        self._f.close()

    # -- reading --------------------------------------------------------------

    @staticmethod
    def iter_records(path: str) -> Iterator[object]:
        """Decode records across ALL segments in order. A truncated or
        corrupt TAIL of the live (last) file is tolerated — that is the
        crash-mid-write case recovery exists for. Corruption inside a
        ROTATED segment is data loss in the middle of the stream and
        raises instead of silently yielding a gapped replay."""
        segments = WAL.segment_paths(path)
        for i, seg in enumerate(segments):
            end = 0
            for off, rec, frame_len in WAL._iter_frames(seg):
                end = off + frame_len
                yield rec
            # non-tail segments must decode to EOF in the SAME pass
            if i < len(segments) - 1:
                size = os.path.getsize(seg)
                if end != size:
                    raise ValueError(
                        f"corrupt WAL segment {seg}: decoded {end} of {size} bytes"
                    )

    @staticmethod
    def iter_records_with_offsets(path: str) -> Iterator[tuple[int, object]]:
        """(start_offset, record) pairs — WAL tooling truncates at these
        offsets."""
        for off, rec, _ in WAL._iter_frames(path):
            yield off, rec

    @staticmethod
    def _iter_frames(path: str) -> Iterator[tuple[int, object, int]]:
        """(start_offset, record, frame_length) — the single place that
        knows the on-disk frame layout; stops at a truncated/corrupt
        tail."""
        with open(path, "rb") as f:
            data = f.read()
        off = 0
        while off + 8 <= len(data):
            crc, length = struct.unpack_from(">II", data, off)
            if off + 8 + length > len(data):
                return  # truncated tail
            body = data[off + 8 : off + 8 + length]
            if zlib.crc32(body) & 0xFFFFFFFF != crc:
                return  # corrupt tail
            try:
                rec = _decode_record(body)
            except Exception:
                return
            yield off, rec, 8 + length
            off += 8 + length

    @classmethod
    def records_since_last_end_height(cls, path: str, height: int) -> list[object] | None:
        """Records after `#ENDHEIGHT <height-1>` — the inputs to replay
        for an in-progress `height`. None if no marker for height-1
        exists (reference `SearchForEndHeight :122`)."""
        if not WAL.segment_paths(path):
            return None
        found = False
        out: list[object] = []
        for rec in cls.iter_records(path):
            if isinstance(rec, EndHeightMessage):
                if rec.height == height - 1:
                    found = True
                    out = []
                elif rec.height >= height:
                    # marker for a later height exists: caller's height is stale
                    found = True
                    out = []
                continue
            if found:
                out.append(rec)
        return out if found else None
