"""Consensus core (reference `consensus/`): the BFT state machine, its
write-ahead log, timeout scheduling, and crash recovery."""

from tendermint_tpu.consensus.config import ConsensusConfig
from tendermint_tpu.consensus.round_state import (
    HeightVoteSet,
    RoundState,
    RoundStepType,
)
from tendermint_tpu.consensus.state import ConsensusState
from tendermint_tpu.consensus.ticker import MockTicker, TimeoutTicker
from tendermint_tpu.consensus.wal import WAL, EndHeightMessage

__all__ = [
    "WAL",
    "ConsensusConfig",
    "ConsensusState",
    "EndHeightMessage",
    "HeightVoteSet",
    "MockTicker",
    "RoundState",
    "RoundStepType",
    "TimeoutTicker",
]
