"""TCP transport: run the Switch over real sockets.

Frames are 4-byte big-endian length-prefixed (carrying the same
channel-multiplexed payloads as the in-memory pipe). Connecting sides
exchange NodeInfo as the first frame (version/chain-id compat handshake
— reference `p2p/peer.go`). When a `priv_key` is supplied (config
p2p.secret_connections, the default), every link is wrapped in the
SecretConnection STS handshake (`p2p/secret.py`) before the NodeInfo
exchange, and the peer's claimed node_id must match the address of its
authenticated identity key (see `_check_identity`).
"""

from __future__ import annotations

import socket
import struct
import threading

from tendermint_tpu.p2p.peer import NodeInfo
from tendermint_tpu.p2p.switch import Switch
from tendermint_tpu.p2p.transport import EndpointClosed
from tendermint_tpu.utils.lockrank import ranked_lock

_MAX_FRAME = 8 * 1024 * 1024


class TcpEndpoint:
    """transport.Endpoint over a connected socket."""

    def __init__(self, sock: socket.socket) -> None:
        self._sock = sock
        self._wlock = ranked_lock("p2p.conn.write")
        self._closed = threading.Event()
        try:
            host, port = sock.getpeername()[:2]
            # the socket's REAL remote address — peer filters must use
            # this, never the peer's self-reported listen_addr
            self.remote_addr = f"{host}:{port}"
        except OSError:
            self.remote_addr = ""
        sock.settimeout(None)

    def send(self, data: bytes, timeout: float = 10.0) -> bool:
        if self._closed.is_set():
            raise EndpointClosed
        try:
            with self._wlock:
                self._sock.sendall(struct.pack(">I", len(data)) + data)
            return True
        except OSError as e:
            self.close()
            raise EndpointClosed from e

    def _read_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self._sock.recv(n - len(buf))
            if not chunk:
                raise EndpointClosed
            buf += chunk
        return buf

    def recv(self, timeout: float | None = None) -> bytes:
        if self._closed.is_set():
            raise EndpointClosed
        try:
            self._sock.settimeout(timeout)
            (length,) = struct.unpack(">I", self._read_exact(4))
            if length > _MAX_FRAME:
                raise EndpointClosed
            return self._read_exact(length)
        except socket.timeout as e:
            raise TimeoutError from e
        except OSError as e:
            self.close()
            raise EndpointClosed from e
        finally:
            try:
                self._sock.settimeout(None)
            except OSError:
                pass

    def close(self) -> None:
        if self._closed.is_set():
            return
        self._closed.set()
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._sock.close()

    @property
    def closed(self) -> bool:
        return self._closed.is_set()


def parse_laddr(laddr: str) -> tuple[str, int]:
    """'tcp://host:port' -> (host, port)."""
    addr = laddr.split("://", 1)[-1]
    host, _, port = addr.rpartition(":")
    return host or "0.0.0.0", int(port)


class TcpListener:
    """Accept loop: handshake NodeInfo, hand peers to the switch.

    With `priv_key` set, every connection is wrapped in a
    SecretConnection (X25519 + transcript-signature STS, see
    `p2p/secret.py`) before the NodeInfo exchange, and the peer's
    claimed node_id must match its authenticated identity key."""

    def __init__(
        self, switch: Switch, laddr: str, priv_key=None, start: bool = True
    ) -> None:
        self.switch = switch
        self.priv_key = priv_key
        host, port = parse_laddr(laddr)
        self._srv = socket.create_server((host, port), reuse_port=False)
        self.addr = self._srv.getsockname()  # actual (host, port) after bind
        self._running = False
        self._thread: threading.Thread | None = None
        if start:
            self.start_accepting()

    def start_accepting(self) -> None:
        """Begin accepting connections. Separated from binding so a node
        can learn its port (for the advertised listen_addr) BEFORE any
        inbound peer can reach reactors that haven't started."""
        if self._running:
            return
        self._running = True
        self._thread = threading.Thread(
            target=self._accept_loop, name="p2p-accept", daemon=True
        )
        self._thread.start()

    @property
    def port(self) -> int:
        return self.addr[1]

    def _accept_loop(self) -> None:
        while self._running:
            try:
                sock, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(
                target=self._handshake,
                args=(sock, False),
                name="p2p-handshake",
                daemon=True,
            ).start()

    def _handshake(self, sock: socket.socket, outbound: bool) -> None:
        ep = TcpEndpoint(sock)
        try:
            ep = _maybe_secure(ep, self.priv_key)
            ep.send(self.switch.node_info.encode())
            remote = NodeInfo.decode(ep.recv(timeout=10.0))
            _check_identity(ep, remote)
            self.switch.add_peer_endpoint(remote, ep, outbound=outbound)
        except Exception:
            ep.close()

    def stop(self) -> None:
        self._running = False
        try:
            self._srv.close()
        except OSError:
            pass


def _maybe_secure(ep, priv_key):
    if priv_key is None:
        return ep
    from tendermint_tpu.p2p.secret import SecretEndpoint

    return SecretEndpoint(ep, priv_key)


def _check_identity(ep, remote: NodeInfo) -> None:
    """On a secured link, the claimed node_id must be the address of the
    authenticated identity key (impersonation check)."""
    remote_pub = getattr(ep, "remote_pub_key", None)
    if remote_pub is not None and remote.node_id != remote_pub.address.hex():
        raise ValueError(
            f"node_id {remote.node_id[:12]} != authenticated identity "
            f"{remote_pub.address.hex()[:12]}"
        )


def dial(switch: Switch, addr: str, timeout: float = 10.0, priv_key=None):
    """Connect out to host:port (or tcp://host:port) and add the peer."""
    host, port = parse_laddr(addr)
    sock = socket.create_connection((host, port), timeout=timeout)
    sock.settimeout(None)
    ep = TcpEndpoint(sock)
    try:
        ep = _maybe_secure(ep, priv_key)
        ep.send(switch.node_info.encode())
        remote = NodeInfo.decode(ep.recv(timeout=timeout))
        _check_identity(ep, remote)
        return switch.add_peer_endpoint(remote, ep, outbound=True)
    except Exception:
        ep.close()
        raise
