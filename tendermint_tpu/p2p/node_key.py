"""Dedicated p2p transport identity key, separate from the validator
signing key.

The reference keeps the node's network identity distinct from the block
signing key so that (a) transport handshake signatures never share a key
with consensus signatures (no cross-protocol signing under the validator
key) and (b) remote-signer/HSM topologies — where the validator private
key never touches the network-facing host — can still run encrypted p2p.
The node id is the address (RIPEMD-160 of the pubkey) of THIS key, not
the validator's.
"""

from __future__ import annotations

import json
import os

from tendermint_tpu.crypto import PrivKey, gen_priv_key


class NodeKey:
    """File-backed p2p identity key (`$TMHOME/config/node_key.json`)."""

    def __init__(self, priv_key: PrivKey):
        self.priv_key = priv_key

    @property
    def node_id(self) -> str:
        return self.priv_key.pub_key.address.hex()

    def save(self, file_path: str) -> None:
        tmp = file_path + ".tmp"
        os.makedirs(os.path.dirname(file_path) or ".", exist_ok=True)
        # key material: owner-only from creation (reference WriteFileAtomic
        # 0600, `types/priv_validator.go`), never umask-dependent
        fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
        with os.fdopen(fd, "w") as f:
            json.dump({"priv_key_seed": self.priv_key.seed.hex()}, f)
        os.replace(tmp, file_path)

    @classmethod
    def load(cls, file_path: str) -> "NodeKey":
        with open(file_path) as f:
            doc = json.load(f)
        return cls(PrivKey(bytes.fromhex(doc["priv_key_seed"])))

    @classmethod
    def load_or_gen(cls, file_path: str, seed: bytes | None = None) -> "NodeKey":
        if os.path.exists(file_path):
            return cls.load(file_path)
        nk = cls(gen_priv_key(seed))
        nk.save(file_path)
        return nk
