"""Address book: remembered peer addresses with new/old buckets.

Reference `p2p/addrbook.go:28-98` — bitcoin-style: addresses arrive in
NEW buckets (hashed by source group so one gossiper can't flood a
bucket), get promoted to OLD buckets after a successful connection,
accumulate failed-attempt counts, and persist to a JSON file. The
bucket/eviction structure is kept; the crypto-hardened salting is a
plain keyed hash here.
"""

from __future__ import annotations

import hashlib
import json
import os
import random
import threading
import time
from dataclasses import dataclass

NEW_BUCKETS = 64
OLD_BUCKETS = 16
BUCKET_SIZE = 32
MAX_ATTEMPTS = 5  # drop a new address after this many failed dials
MAX_OLD_ATTEMPTS = 10  # demote an old address back to new after this many


@dataclass
class NetAddress:
    node_id: str
    addr: str  # "host:port"

    @property
    def host(self) -> str:
        return self.addr.rpartition(":")[0]

    def to_dict(self) -> dict:
        return {"node_id": self.node_id, "addr": self.addr}

    @classmethod
    def from_dict(cls, d: dict) -> "NetAddress":
        return cls(node_id=d["node_id"], addr=d["addr"])


@dataclass
class _Entry:
    address: NetAddress
    src_id: str = ""
    attempts: int = 0
    last_attempt: float = 0.0
    last_success: float = 0.0
    is_old: bool = False
    bucket: int = 0


SAVE_INTERVAL_S = 5.0  # debounce: reference persists on a periodic routine


class AddrBook:
    def __init__(self, file_path: str | None = None, key: bytes | None = None):
        self._path = file_path
        self._key = key if key is not None else os.urandom(8)
        self._lock = threading.RLock()
        self._entries: dict[str, _Entry] = {}  # node_id -> entry
        self._rng = random.Random()
        self._dirty = False
        self._last_save = 0.0
        if file_path and os.path.exists(file_path):
            self._load()

    # -- bucketing ---------------------------------------------------------

    def _bucket(self, addr: NetAddress, src_id: str, old: bool) -> int:
        n = OLD_BUCKETS if old else NEW_BUCKETS
        group = addr.host if old else src_id  # new buckets keyed by SOURCE
        h = hashlib.sha256(self._key + group.encode() + addr.node_id.encode())
        return int.from_bytes(h.digest()[:4], "big") % n

    def _bucket_load(self, bucket: int, old: bool) -> int:
        return sum(
            1
            for e in self._entries.values()
            if e.is_old == old and e.bucket == bucket
        )

    # -- writes ------------------------------------------------------------

    def add_address(self, addr: NetAddress, src_id: str = "") -> bool:
        """Record an address heard from `src_id` (reference `AddAddress`).
        Known-old addresses are left alone; a full bucket evicts the
        stalest NEW entry."""
        if not addr.addr or not addr.node_id:
            return False
        with self._lock:
            cur = self._entries.get(addr.node_id)
            if cur is not None:
                # never let later gossip overwrite a stored dial address
                # (an attacker could re-point NEW entries at itself — the
                # eclipse vector the reference's no-overwrite rule closes)
                return False
            bucket = self._bucket(addr, src_id, old=False)
            if self._bucket_load(bucket, old=False) >= BUCKET_SIZE:
                self._evict_new(bucket)
            self._entries[addr.node_id] = _Entry(
                address=addr, src_id=src_id, bucket=bucket
            )
            self._save()
            return True

    def _evict_new(self, bucket: int) -> None:
        victims = [
            (nid, e)
            for nid, e in self._entries.items()
            if not e.is_old and e.bucket == bucket
        ]
        if victims:
            nid, _ = min(victims, key=lambda kv: kv[1].last_success or 0)
            del self._entries[nid]

    def mark_attempt(self, node_id: str) -> None:
        with self._lock:
            e = self._entries.get(node_id)
            if e is None:
                return
            e.attempts += 1
            e.last_attempt = time.time()
            if not e.is_old and e.attempts >= MAX_ATTEMPTS:
                del self._entries[node_id]  # consistently unreachable
            elif e.is_old and e.attempts >= MAX_OLD_ATTEMPTS:
                # a proven address that went dark: demote so it competes
                # as NEW again and can age out instead of being redialed
                # forever (reference moveToNew on repeated failure)
                e.is_old = False
                e.attempts = 0
                e.bucket = self._bucket(e.address, e.src_id, old=False)
            self._save()

    def mark_good(self, node_id: str) -> None:
        """Successful connection: promote to an OLD bucket (reference
        `MarkGood` / `moveToOld`)."""
        with self._lock:
            e = self._entries.get(node_id)
            if e is None:
                return
            e.attempts = 0
            e.last_success = time.time()
            if not e.is_old:
                e.is_old = True
                e.bucket = self._bucket(e.address, e.src_id, old=True)
            self._save()

    def remove(self, node_id: str) -> None:
        with self._lock:
            self._entries.pop(node_id, None)
            self._save()

    # -- reads -------------------------------------------------------------

    def size(self) -> int:
        with self._lock:
            return len(self._entries)

    def has(self, node_id: str) -> bool:
        with self._lock:
            return node_id in self._entries

    def pick_address(
        self, bias_old: float = 0.5, exclude: set[str] | None = None
    ) -> NetAddress | None:
        """Random address to dial, biased between old (proven) and new
        entries (reference `PickAddress`); `exclude` filters node ids
        already connected or already attempted this pass."""
        with self._lock:
            entries = [
                e
                for e in self._entries.values()
                if not exclude or e.address.node_id not in exclude
            ]
            old = [e for e in entries if e.is_old]
            new = [e for e in entries if not e.is_old]
            pool = old if (old and self._rng.random() < bias_old) else new
            pool = pool or old
            if not pool:
                return None
            return self._rng.choice(pool).address

    def sample(self, n: int = 16) -> list[NetAddress]:
        """Random subset for a PEX response (reference `GetSelection`)."""
        with self._lock:
            entries = list(self._entries.values())
            self._rng.shuffle(entries)
            return [e.address for e in entries[:n]]

    # -- persistence -------------------------------------------------------

    def _save(self) -> None:
        """Debounced: mutations mark dirty; the file is rewritten at most
        once per SAVE_INTERVAL_S (reference `dumpAddressRoutine` persists
        periodically, not per mutation — a 32-address PEX message would
        otherwise serialize the whole book 32 times inside the peer's
        recv thread). Call `flush()` for a synchronous write (shutdown).
        """
        self._dirty = True
        if time.time() - self._last_save < SAVE_INTERVAL_S:
            return
        self._flush_locked()

    def flush(self) -> None:
        with self._lock:
            if self._dirty:
                self._flush_locked()

    def _flush_locked(self) -> None:
        if not self._path:
            self._dirty = False
            return
        self._dirty = False
        self._last_save = time.time()
        doc = {
            "key": self._key.hex(),
            "entries": [
                {
                    **e.address.to_dict(),
                    "src_id": e.src_id,
                    "attempts": e.attempts,
                    "last_success": e.last_success,
                    "is_old": e.is_old,
                    "bucket": e.bucket,
                }
                for e in self._entries.values()
            ],
        }
        tmp = self._path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, self._path)

    def _load(self) -> None:
        with open(self._path) as f:
            doc = json.load(f)
        self._key = bytes.fromhex(doc["key"])
        for d in doc["entries"]:
            e = _Entry(
                address=NetAddress.from_dict(d),
                src_id=d.get("src_id", ""),
                attempts=d.get("attempts", 0),
                last_success=d.get("last_success", 0.0),
                is_old=d.get("is_old", False),
                bucket=d.get("bucket", 0),
            )
            self._entries[e.address.node_id] = e
