"""Multiplexed prioritized channels over one endpoint.

The reference's MConnection (`p2p/connection.go:66-114`) carries N
logical channels with byte IDs and priorities over one TCP conn, with
send-queue backpressure and flow limits. Same design here over the
`transport.Endpoint` seam: a send thread drains per-channel queues
weighted by priority; a recv thread parses frames and hands
(chan_id, payload) to the owner's on_receive callback.

Frame format (TCP-ready, though the in-memory pipe preserves framing
anyway): uvarint chan_id || uvarint len || payload [|| trace block].

The trace block is OPTIONAL and trailing — `telemetry/tracectx.py`'s
TraceContext wire encoding. Codec-backward-compatible by construction:
a frame without it is byte-identical to the old format and decodes
unchanged; a receiver that doesn't know the block ignores trailing
bytes; a decode failure drops the context (counted), never the frame.
Sampled-out messages carry no context bytes at all. (tmlint rule W001
checks this trailing-optional discipline statically for every decoder.)

Concurrency: this class is deliberately LOCK-FREE — channels are
`queue.Queue`s, wakeups are Events, and per-link state is owned by the
send/recv/ping threads. The locks it leans on live in its
collaborators and rank near the top of the utils/lockrank.py table:
the flowrate monitors ("p2p.flowrate") and the endpoint write locks
("p2p.conn.write"), both leaves acquired under nothing else.
"""

from __future__ import annotations

import queue
import threading
import time
from dataclasses import dataclass

from tendermint_tpu.codec.binary import Reader, Writer
from tendermint_tpu.p2p.score import PeerMisbehavior
from tendermint_tpu.p2p.transport import Endpoint, EndpointClosed
from tendermint_tpu.telemetry import TRACER
from tendermint_tpu.telemetry import metrics as _metrics
from tendermint_tpu.telemetry import tracectx as _trace
from tendermint_tpu.telemetry.tracectx import TraceContext
from tendermint_tpu.utils.flowrate import Monitor


def build_frame(chan_id: int, payload: bytes, ctx: TraceContext | None = None) -> bytes:
    """One wire frame; `ctx=None` produces the exact legacy bytes."""
    w = Writer().uvarint(chan_id).bytes(payload)
    if ctx is not None:
        w.raw(ctx.encode_wire())
    return w.build()


def parse_frame(frame: bytes) -> tuple[int, bytes, TraceContext | None]:
    """(chan_id, payload, trace context or None). Absent or
    undecodable trailing block ⇒ no context (the frame itself always
    survives — tracing is forensic, never load-bearing)."""
    r = Reader(frame)
    chan_id = r.uvarint()
    payload = r.bytes()
    ctx = None
    if not r.done():
        try:
            ctx = TraceContext.decode_wire(r)
        except Exception:
            _metrics.TRACE_DROPPED.inc()
    return chan_id, payload, ctx

# Hard per-frame size cap: whole blocks ride fast-sync responses (21 MB
# block cap + framing overhead), nothing legitimate exceeds this. An
# oversize frame is adversarial by definition — the sender is dropped
# (and scored) before the payload is even parsed.
MAX_FRAME_SIZE = 32 * 1024 * 1024

# Internal keepalive channel (reference sends dedicated packetTypePing/
# packetTypePong frames, `p2p/connection.go:312-345`; here they ride a
# reserved channel id no reactor may claim).
CTRL_CHANNEL = 0xFF
_PING = b"\x01"
_PONG = b"\x02"
# Reference pingTimeout is 40s; pong grace on top before declaring dead.
DEFAULT_PING_INTERVAL = 40.0
DEFAULT_PONG_TIMEOUT = 20.0


@dataclass(frozen=True)
class ChannelDescriptor:
    """Reference `p2p/connection.go` ChannelDescriptor."""

    id: int
    priority: int = 1
    send_queue_capacity: int = 64


class _Channel:
    def __init__(self, desc: ChannelDescriptor) -> None:
        self.desc = desc
        # (payload, trace context or None, enqueue perf_counter) — the
        # timestamp feeds tendermint_p2p_send_wait_seconds when the
        # send loop dequeues (the wait twin of the depth gauges)
        self.queue: "queue.Queue[tuple[bytes, TraceContext | None, float]]" = (
            queue.Queue(maxsize=desc.send_queue_capacity)
        )
        self.recently_sent = 0


class MConnection:
    """One peer link: channel-multiplexed, priority-scheduled sends.

    on_receive(chan_id, payload) runs on the recv thread; on_error(exc)
    fires once when either side dies (the Switch uses it to drop the
    peer — reference `stopForError p2p/connection.go:212-219`).
    """

    def __init__(
        self,
        endpoint: Endpoint,
        channels: list[ChannelDescriptor],
        on_receive,
        on_error=None,
        send_limit: int = 0,
        recv_limit: int = 0,
        ping_interval: float = DEFAULT_PING_INTERVAL,
        pong_timeout: float = DEFAULT_PONG_TIMEOUT,
        local_node_id: str = "",
        on_traffic=None,
    ) -> None:
        # who WE are, for `p2p.hop` span attribution (the Switch wires
        # its node id through Peer; "" on bare test connections)
        self.local_node_id = local_node_id
        # gossip observatory hook: on_traffic(direction, chan_id,
        # payload, frame_len) fires per frame AFTER the wire bytes are
        # fixed — it observes, never alters (Peer binds the remote id
        # and forwards into the switch's GossipRollup; None = sampled
        # out, zero per-frame overhead)
        self._on_traffic = on_traffic
        # per-connection throughput stats + optional rate caps
        # (reference flowrate.Monitor at p2p/connection.go:72-73)
        self.send_monitor = Monitor(send_limit)
        self.recv_monitor = Monitor(recv_limit)
        self._endpoint = endpoint
        if any(d.id == CTRL_CHANNEL for d in channels):
            raise ValueError(f"channel id {CTRL_CHANNEL:#x} is reserved for keepalive")
        self._channels: dict[int, _Channel] = {
            d.id: _Channel(d) for d in channels
        }
        # keepalive frames outrank data so a saturated link still pongs
        self._ctrl = _Channel(
            ChannelDescriptor(id=CTRL_CHANNEL, priority=100, send_queue_capacity=4)
        )
        self._channels[CTRL_CHANNEL] = self._ctrl
        self._on_receive = on_receive
        self._on_error = on_error
        self._send_wake = threading.Event()
        self._running = False
        self._stopped = False  # terminal: refuses sends; pre-start queues them
        self._threads: list[threading.Thread] = []
        self._err_once = threading.Event()
        self.ping_interval = ping_interval
        self.pong_timeout = pong_timeout
        self._last_recv = time.monotonic()
        self._ping_stop = threading.Event()

    def start(self) -> None:
        self._running = True
        self._last_recv = time.monotonic()
        loops = [(self._send_loop, "send"), (self._recv_loop, "recv")]
        if self.ping_interval > 0:
            loops.append((self._ping_loop, "ping"))
        for fn, name in loops:
            t = threading.Thread(target=fn, name=f"mconn-{name}", daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._running = False
        self._stopped = True
        self._ping_stop.set()
        self._endpoint.close()
        self._send_wake.set()

    # -- sending -----------------------------------------------------------

    def send(
        self,
        chan_id: int,
        payload: bytes,
        timeout: float = 5.0,
        ctx: TraceContext | None = None,
    ) -> bool:
        """Queue for send; blocks up to timeout on a full channel queue
        (reference `Send` blocks, `TrySend` doesn't). Sends BEFORE
        start() queue up and flush once the send loop runs — reactors
        greet a new peer (add_peer step messages) before it starts.
        `ctx` (or, when None, the calling thread's ambient trace
        context) is captured NOW and framed with the payload — the send
        loop runs on its own thread."""
        ch = self._channels.get(chan_id)
        if ch is None:
            raise ValueError(f"unknown channel {chan_id:#x}")
        if self._stopped:
            return False
        if ctx is None:
            ctx = _trace.current()
        try:
            ch.queue.put((payload, ctx, time.perf_counter()), timeout=timeout)
        except queue.Full:
            return False
        self._send_wake.set()
        return True

    def try_send(
        self, chan_id: int, payload: bytes, ctx: TraceContext | None = None
    ) -> bool:
        ch = self._channels.get(chan_id)
        if ch is None:
            raise ValueError(f"unknown channel {chan_id:#x}")
        if self._stopped:
            return False
        if ctx is None:
            ctx = _trace.current()
        try:
            ch.queue.put_nowait((payload, ctx, time.perf_counter()))
        except queue.Full:
            return False
        self._send_wake.set()
        return True

    def send_queue_depth(self) -> int:
        """Frames queued across all channels — the backpressure signal
        the `tendermint_p2p_send_queue_*` gauges roll up."""
        return sum(ch.queue.qsize() for ch in self._channels.values())

    def _pick_channel(self) -> _Channel | None:
        """Least-recently-sent-relative-to-priority scheduling (the
        reference's sendSomePacketMsgs weighting)."""
        best: _Channel | None = None
        best_ratio = float("inf")
        for ch in self._channels.values():
            if ch.queue.empty():
                continue
            ratio = ch.recently_sent / max(ch.desc.priority, 1)
            if ratio < best_ratio:
                best_ratio = ratio
                best = ch
        return best

    def _send_loop(self) -> None:
        try:
            while self._running:
                ch = self._pick_channel()
                if ch is None:
                    # decay counters while idle so one burst doesn't
                    # starve a channel forever
                    for c in self._channels.values():
                        c.recently_sent //= 2
                    self._send_wake.wait(timeout=0.05)
                    self._send_wake.clear()
                    continue
                try:
                    payload, ctx, t_enq = ch.queue.get_nowait()
                except queue.Empty:
                    continue
                _metrics.P2P_SEND_WAIT.observe(time.perf_counter() - t_enq)
                frame = build_frame(ch.desc.id, payload, ctx)
                if ctx is not None:
                    _metrics.TRACE_PROPAGATED.inc()
                self.send_monitor.throttle()
                self._endpoint.send(frame)
                self.send_monitor.update(len(frame))
                # process-wide throughput counter alongside the per-peer
                # monitor (rates come from the monitors at scrape time)
                _metrics.P2P_SENT_BYTES.inc(len(frame))
                if self._on_traffic is not None:
                    self._on_traffic("send", ch.desc.id, payload, len(frame))
                ch.recently_sent += len(payload)
        except EndpointClosed:
            self._die(None)
        except Exception as e:  # transport failure kills the peer
            self._die(e)

    # -- receiving ---------------------------------------------------------

    def _recv_loop(self) -> None:
        try:
            while self._running:
                frame = self._endpoint.recv()
                self.recv_monitor.update(len(frame))
                _metrics.P2P_RECV_BYTES.inc(len(frame))
                # inbound flow control: delay further reads once over
                # the cap (the sender blocks on TCP backpressure)
                self.recv_monitor.throttle()
                # Adversarial-input boundary: a malformed, truncated, or
                # oversized frame is THIS PEER's fault — surface a typed
                # PeerMisbehavior through on_error (the switch debits
                # its score and drops only this peer) instead of letting
                # the parse error masquerade as a transport failure or,
                # worse, crash the reader thread.
                if len(frame) > MAX_FRAME_SIZE:
                    self._die(
                        PeerMisbehavior(
                            "oversize_frame", f"{len(frame)} bytes"
                        )
                    )
                    return
                try:
                    chan_id, payload, ctx = parse_frame(frame)
                except Exception as e:
                    self._die(PeerMisbehavior("bad_frame", str(e)))
                    return
                self._last_recv = time.monotonic()
                if self._on_traffic is not None:
                    self._on_traffic("recv", chan_id, payload, len(frame))
                if chan_id == CTRL_CHANNEL:
                    # keepalive (reference recvRoutine ping/pong handling
                    # `p2p/connection.go:412-425`): answer pings; any pong
                    # already refreshed _last_recv above
                    if payload == _PING:
                        try:
                            self._ctrl.queue.put_nowait((_PONG, None, time.perf_counter()))
                            self._send_wake.set()
                        except queue.Full:
                            pass  # a pong is already queued
                    continue
                if chan_id not in self._channels:
                    continue  # unknown channel: drop (fuzz/future-proof)
                if ctx is None:
                    self._on_receive(chan_id, payload)
                else:
                    # one hop span per traced frame, then process with
                    # the re-parented context ambient so reactor →
                    # mempool/consensus work (and any gossip-out it
                    # triggers) stays on this trace
                    now = time.time()
                    TRACER.add(
                        "p2p.hop",
                        now,
                        now,
                        trace=ctx.trace,
                        origin=ctx.origin,
                        node=self.local_node_id,
                        chan=chan_id,
                    )
                    with _trace.use(ctx.rehop()):
                        self._on_receive(chan_id, payload)
        except EndpointClosed:
            self._die(None)
        except Exception as e:
            self._die(e)

    # -- keepalive ---------------------------------------------------------

    def _ping_loop(self) -> None:
        """Ping idle peers; kill the conn when nothing (not even a pong)
        arrives for ping_interval + pong_timeout (reference pingTimeout,
        `p2p/connection.go:312-345`). Without this an idle-but-dead peer
        holds its slot until some send fails."""
        tick = min(1.0, self.ping_interval / 4)
        last_ping = 0.0
        while self._running and not self._ping_stop.wait(timeout=tick):
            now = time.monotonic()
            idle = now - self._last_recv
            if idle > self.ping_interval + self.pong_timeout:
                self._die(TimeoutError(f"peer silent for {idle:.1f}s (ping timeout)"))
                return
            # one ping per interval, not per tick — re-ping only after the
            # previous one has gone unanswered a full interval
            if idle > self.ping_interval and now - last_ping > self.ping_interval:
                last_ping = now
                try:
                    self._ctrl.queue.put_nowait((_PING, None, time.perf_counter()))
                    self._send_wake.set()
                except queue.Full:
                    pass  # a ping is already in flight

    def _die(self, exc: Exception | None) -> None:
        if self._err_once.is_set():
            return
        self._err_once.set()
        self._running = False
        self._stopped = True
        self._ping_stop.set()
        self._endpoint.close()
        if self._on_error is not None:
            self._on_error(exc)
