"""Multiplexed prioritized channels over one endpoint.

The reference's MConnection (`p2p/connection.go:66-114`) carries N
logical channels with byte IDs and priorities over one TCP conn, with
send-queue backpressure and flow limits. Same design here over the
`transport.Endpoint` seam: a send thread drains per-channel queues
weighted by priority; a recv thread parses frames and hands
(chan_id, payload) to the owner's on_receive callback.

Frame format (TCP-ready, though the in-memory pipe preserves framing
anyway): uvarint chan_id || uvarint len || payload.
"""

from __future__ import annotations

import queue
import threading
from dataclasses import dataclass

from tendermint_tpu.codec.binary import Reader, Writer
from tendermint_tpu.p2p.transport import Endpoint, EndpointClosed
from tendermint_tpu.utils.flowrate import Monitor


@dataclass(frozen=True)
class ChannelDescriptor:
    """Reference `p2p/connection.go` ChannelDescriptor."""

    id: int
    priority: int = 1
    send_queue_capacity: int = 64


class _Channel:
    def __init__(self, desc: ChannelDescriptor) -> None:
        self.desc = desc
        self.queue: "queue.Queue[bytes]" = queue.Queue(
            maxsize=desc.send_queue_capacity
        )
        self.recently_sent = 0


class MConnection:
    """One peer link: channel-multiplexed, priority-scheduled sends.

    on_receive(chan_id, payload) runs on the recv thread; on_error(exc)
    fires once when either side dies (the Switch uses it to drop the
    peer — reference `stopForError p2p/connection.go:212-219`).
    """

    def __init__(
        self,
        endpoint: Endpoint,
        channels: list[ChannelDescriptor],
        on_receive,
        on_error=None,
        send_limit: int = 0,
        recv_limit: int = 0,
    ) -> None:
        # per-connection throughput stats + optional rate caps
        # (reference flowrate.Monitor at p2p/connection.go:72-73)
        self.send_monitor = Monitor(send_limit)
        self.recv_monitor = Monitor(recv_limit)
        self._endpoint = endpoint
        self._channels: dict[int, _Channel] = {
            d.id: _Channel(d) for d in channels
        }
        self._on_receive = on_receive
        self._on_error = on_error
        self._send_wake = threading.Event()
        self._running = False
        self._threads: list[threading.Thread] = []
        self._err_once = threading.Event()

    def start(self) -> None:
        self._running = True
        for fn, name in ((self._send_loop, "send"), (self._recv_loop, "recv")):
            t = threading.Thread(target=fn, name=f"mconn-{name}", daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._running = False
        self._endpoint.close()
        self._send_wake.set()

    # -- sending -----------------------------------------------------------

    def send(self, chan_id: int, payload: bytes, timeout: float = 5.0) -> bool:
        """Queue for send; blocks up to timeout on a full channel queue
        (reference `Send` blocks, `TrySend` doesn't)."""
        ch = self._channels.get(chan_id)
        if ch is None:
            raise ValueError(f"unknown channel {chan_id:#x}")
        if not self._running:
            return False
        try:
            ch.queue.put(payload, timeout=timeout)
        except queue.Full:
            return False
        self._send_wake.set()
        return True

    def try_send(self, chan_id: int, payload: bytes) -> bool:
        ch = self._channels.get(chan_id)
        if ch is None:
            raise ValueError(f"unknown channel {chan_id:#x}")
        if not self._running:
            return False
        try:
            ch.queue.put_nowait(payload)
        except queue.Full:
            return False
        self._send_wake.set()
        return True

    def _pick_channel(self) -> _Channel | None:
        """Least-recently-sent-relative-to-priority scheduling (the
        reference's sendSomePacketMsgs weighting)."""
        best: _Channel | None = None
        best_ratio = float("inf")
        for ch in self._channels.values():
            if ch.queue.empty():
                continue
            ratio = ch.recently_sent / max(ch.desc.priority, 1)
            if ratio < best_ratio:
                best_ratio = ratio
                best = ch
        return best

    def _send_loop(self) -> None:
        try:
            while self._running:
                ch = self._pick_channel()
                if ch is None:
                    # decay counters while idle so one burst doesn't
                    # starve a channel forever
                    for c in self._channels.values():
                        c.recently_sent //= 2
                    self._send_wake.wait(timeout=0.05)
                    self._send_wake.clear()
                    continue
                try:
                    payload = ch.queue.get_nowait()
                except queue.Empty:
                    continue
                frame = (
                    Writer().uvarint(ch.desc.id).bytes(payload).build()
                )
                self.send_monitor.throttle()
                self._endpoint.send(frame)
                self.send_monitor.update(len(frame))
                ch.recently_sent += len(payload)
        except EndpointClosed:
            self._die(None)
        except Exception as e:  # transport failure kills the peer
            self._die(e)

    # -- receiving ---------------------------------------------------------

    def _recv_loop(self) -> None:
        try:
            while self._running:
                frame = self._endpoint.recv()
                self.recv_monitor.update(len(frame))
                # inbound flow control: delay further reads once over
                # the cap (the sender blocks on TCP backpressure)
                self.recv_monitor.throttle()
                r = Reader(frame)
                chan_id = r.uvarint()
                payload = r.bytes()
                if chan_id not in self._channels:
                    continue  # unknown channel: drop (fuzz/future-proof)
                self._on_receive(chan_id, payload)
        except EndpointClosed:
            self._die(None)
        except Exception as e:
            self._die(e)

    def _die(self, exc: Exception | None) -> None:
        if self._err_once.is_set():
            return
        self._err_once.set()
        self._running = False
        self._endpoint.close()
        if self._on_error is not None:
            self._on_error(exc)
