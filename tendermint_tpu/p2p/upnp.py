"""UPnP IGD port mapping (reference `p2p/upnp/` Discover/Probe).

NAT traversal for home nodes: SSDP multicast discovery of an Internet
Gateway Device, then SOAP calls for GetExternalIPAddress /
AddPortMapping / DeletePortMapping. Pure stdlib (socket + HTTP).
`probe` mirrors the reference's `probe_upnp` CLI flow: discover, map a
port, report the external address, clean up.
"""

from __future__ import annotations

import re
import socket
import urllib.error
import urllib.request
from dataclasses import dataclass

SSDP_ADDR = ("239.255.255.250", 1900)
_SEARCH = (
    "M-SEARCH * HTTP/1.1\r\n"
    f"HOST: {SSDP_ADDR[0]}:{SSDP_ADDR[1]}\r\n"
    'MAN: "ssdp:discover"\r\n'
    "MX: 2\r\n"
    "ST: urn:schemas-upnp-org:device:InternetGatewayDevice:1\r\n\r\n"
)
_WAN_SERVICES = (
    "urn:schemas-upnp-org:service:WANIPConnection:1",
    "urn:schemas-upnp-org:service:WANPPPConnection:1",
)


class UPnPError(Exception):
    pass


@dataclass
class Gateway:
    control_url: str
    service_type: str
    local_ip: str


def _soap(control_url: str, service: str, action: str, args: dict, timeout: float = 5.0) -> str:
    body = (
        '<?xml version="1.0"?>'
        '<s:Envelope xmlns:s="http://schemas.xmlsoap.org/soap/envelope/" '
        's:encodingStyle="http://schemas.xmlsoap.org/soap/encoding/">'
        f'<s:Body><u:{action} xmlns:u="{service}">'
        + "".join(f"<{k}>{v}</{k}>" for k, v in args.items())
        + f"</u:{action}></s:Body></s:Envelope>"
    ).encode()
    req = urllib.request.Request(
        control_url,
        data=body,
        headers={
            "Content-Type": 'text/xml; charset="utf-8"',
            "SOAPAction": f'"{service}#{action}"',
        },
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.read().decode(errors="replace")
    except (OSError, urllib.error.URLError) as e:
        # SOAP faults arrive as HTTP 500 (e.g. ConflictInMappingEntry)
        raise UPnPError(f"{action} failed: {e}") from e


def discover(timeout: float = 3.0, ssdp_addr=None) -> Gateway:
    """SSDP M-SEARCH for an IGD; fetch its description; find the WAN
    service control URL (reference `upnp.Discover`)."""
    addr = ssdp_addr or SSDP_ADDR
    sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    sock.settimeout(timeout)
    try:
        sock.sendto(_SEARCH.encode(), addr)
        data, server = sock.recvfrom(4096)
        local_ip = sock.getsockname()[0]
    except socket.timeout as e:
        raise UPnPError("no UPnP gateway responded") from e
    finally:
        sock.close()
    m = re.search(rb"(?im)^location:\s*(\S+)", data)
    if not m:
        raise UPnPError("SSDP response missing LOCATION")
    location = m.group(1).decode()
    try:
        with urllib.request.urlopen(location, timeout=timeout) as resp:
            desc = resp.read().decode(errors="replace")
    except (OSError, urllib.error.URLError) as e:
        raise UPnPError(f"gateway description fetch failed: {e}") from e
    for service in _WAN_SERVICES:
        pattern = (
            re.escape(service)
            + r".*?<controlURL>([^<]+)</controlURL>"
        )
        sm = re.search(pattern, desc, re.S)
        if sm:
            control = sm.group(1)
            if control.startswith("/"):
                base = location.split("/", 3)
                control = f"{base[0]}//{base[2]}{control}"
            if local_ip in ("0.0.0.0", ""):
                # learn our LAN-facing address by routing toward the gw
                probe_sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
                try:
                    probe_sock.connect((server[0], 80))
                    local_ip = probe_sock.getsockname()[0]
                finally:
                    probe_sock.close()
            return Gateway(control, service, local_ip)
    raise UPnPError("gateway exposes no WAN connection service")


def external_ip(gw: Gateway) -> str:
    resp = _soap(gw.control_url, gw.service_type, "GetExternalIPAddress", {})
    m = re.search(r"<NewExternalIPAddress>([^<]*)</NewExternalIPAddress>", resp)
    if not m:
        raise UPnPError("no external IP in response")
    return m.group(1)


def add_port_mapping(
    gw: Gateway, external_port: int, internal_port: int, description: str = "tendermint_tpu", lease_s: int = 0
) -> None:
    _soap(
        gw.control_url,
        gw.service_type,
        "AddPortMapping",
        {
            "NewRemoteHost": "",
            "NewExternalPort": external_port,
            "NewProtocol": "TCP",
            "NewInternalPort": internal_port,
            "NewInternalClient": gw.local_ip,
            "NewEnabled": 1,
            "NewPortMappingDescription": description,
            "NewLeaseDuration": lease_s,
        },
    )


def delete_port_mapping(gw: Gateway, external_port: int) -> None:
    _soap(
        gw.control_url,
        gw.service_type,
        "DeletePortMapping",
        {"NewRemoteHost": "", "NewExternalPort": external_port, "NewProtocol": "TCP"},
    )


def probe(port: int = 46656, ssdp_addr=None) -> dict:
    """Discover, map, verify, unmap (reference `probe_upnp` command)."""
    gw = discover(ssdp_addr=ssdp_addr)
    ip = external_ip(gw)
    add_port_mapping(gw, port, port, description="tendermint_tpu-probe")
    try:
        return {"external_ip": ip, "port": port, "control_url": gw.control_url}
    finally:
        delete_port_mapping(gw, port)
