"""Switch: reactor registry + peer lifecycle + channel dispatch.

Reference `p2p/switch.go:20-28,60-76,106-121`. Reactors register
channel descriptors; incoming frames dispatch to the reactor owning the
channel. `make_connected_switches` / `connect_switches` wire N switches
over in-memory pipes — the reference's net.Pipe test harness
(`p2p/switch.go:502-534`) promoted to the primary local transport.
"""

from __future__ import annotations

import logging
import threading
from typing import Callable

from tendermint_tpu.p2p.connection import ChannelDescriptor
from tendermint_tpu.p2p.peer import NodeInfo, Peer
from tendermint_tpu.p2p.score import PeerMisbehavior, PeerScorer
from tendermint_tpu.p2p.transport import Endpoint, pipe_pair
from tendermint_tpu.telemetry import metrics as _metrics
from tendermint_tpu.telemetry.gossiplog import GossipRollup
from tendermint_tpu.utils.lockrank import ranked_rlock
from tendermint_tpu.utils.log import kv, logger


class Reactor:
    """Plugin seam (reference `p2p/switch.go:20-28`)."""

    def __init__(self) -> None:
        self.switch: "Switch | None" = None

    def set_switch(self, switch: "Switch") -> None:
        self.switch = switch

    def get_channels(self) -> list[ChannelDescriptor]:
        raise NotImplementedError

    def add_peer(self, peer: Peer) -> None:  # noqa: B027 — optional hook
        pass

    def remove_peer(self, peer: Peer, reason) -> None:  # noqa: B027
        pass

    def receive(self, chan_id: int, peer: Peer, payload: bytes) -> None:
        raise NotImplementedError

    def on_start(self) -> None:  # noqa: B027
        pass

    def on_stop(self) -> None:  # noqa: B027
        pass


class Switch:
    def __init__(self, node_info: NodeInfo) -> None:
        self._base_info = node_info
        self._reactors: dict[str, Reactor] = {}
        self._chan_to_reactor: dict[int, Reactor] = {}
        self._descriptors: list[ChannelDescriptor] = []
        # Leaf lock by design: held only over _peers dict surgery, never
        # across reactor callbacks, peer.start/stop, or sends (lockrank
        # "p2p.switch" — near the top of the rank table).
        self._peers: dict[str, Peer] = {}
        self._mtx = ranked_rlock("p2p.switch")
        self._running = False
        self.listen_addr = node_info.listen_addr  # set once the listener binds
        # per-peer flow caps, bytes/s (0 = unlimited; reference 500 kB/s)
        self.send_rate = 0
        self.recv_rate = 0
        # keepalive cadence (reference pingTimeout `p2p/connection.go:312`);
        # 0 disables pings (in-proc test meshes don't need them)
        from tendermint_tpu.p2p.connection import (
            DEFAULT_PING_INTERVAL,
            DEFAULT_PONG_TIMEOUT,
        )

        self.ping_interval = DEFAULT_PING_INTERVAL
        self.pong_timeout = DEFAULT_PONG_TIMEOUT
        # fires after a peer is fully removed: fn(peer, reason). The node
        # hangs persistent-peer reconnection off this (reference
        # `reconnectToPeer p2p/switch.go:290-320`).
        self.on_peer_removed = None
        # optional admission hook (ABCI peer filters, reference
        # `node/node.go:259-281`): fn(remote_info, remote_addr) -> error
        # string or None; a non-None return rejects the peer before
        # registration. remote_addr is the SOCKET's remote address ("" on
        # in-memory transports) — never the peer's self-reported one.
        self.peer_filter = None
        # adversarial-input defense: per-peer misbehavior scores + bans
        # (p2p/score.py). Reactors and the connection layer report
        # offenses through report_misbehavior; crossing the threshold
        # disconnects AND refuses reconnection until the ban decays.
        self.scorer = PeerScorer()
        # gossip observatory (telemetry/gossiplog.py): per-peer/channel/
        # kind traffic + redundancy rollup. Owned here so every peer's
        # connection and every reactor's dedup site stamp ONE table;
        # TENDERMINT_TPU_GOSSIPLOG=0 samples the whole node out.
        self.gossip = GossipRollup()

    @property
    def node_info(self) -> NodeInfo:
        # advertise the registered channels + dialable address
        return NodeInfo(
            node_id=self._base_info.node_id,
            moniker=self._base_info.moniker,
            chain_id=self._base_info.chain_id,
            version=self._base_info.version,
            channels=tuple(d.id for d in self._descriptors),
            listen_addr=self.listen_addr,
        )

    # -- reactors ----------------------------------------------------------

    def add_reactor(self, name: str, reactor: Reactor) -> Reactor:
        """Register + claim channels (reference `AddReactor :106-121`)."""
        for d in reactor.get_channels():
            if d.id in self._chan_to_reactor:
                raise ValueError(f"channel {d.id:#x} already claimed")
            self._chan_to_reactor[d.id] = reactor
            self._descriptors.append(d)
        self._reactors[name] = reactor
        reactor.set_switch(self)
        return reactor

    def reactor(self, name: str) -> Reactor:
        return self._reactors[name]

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        self._running = True
        for r in self._reactors.values():
            r.on_start()

    def stop(self) -> None:
        self._running = False
        with self._mtx:
            peers = list(self._peers.values())
        for p in peers:
            self.stop_peer(p, "switch stopping")
        for r in self._reactors.values():
            r.on_stop()

    # -- peers -------------------------------------------------------------

    def peers(self) -> list[Peer]:
        with self._mtx:
            return list(self._peers.values())

    def n_peers(self) -> int:
        with self._mtx:
            return len(self._peers)

    def send_rate_total(self) -> float:
        """Aggregate live-peer send rate, bytes/s — the flowrate
        monitors rolled up for the telemetry gauges."""
        return sum(p.send_monitor.rate for p in self.peers())

    def recv_rate_total(self) -> float:
        return sum(p.recv_monitor.rate for p in self.peers())

    def send_queue_depth_total(self) -> int:
        """Frames queued for send across all peers — rollup for the
        `tendermint_p2p_send_queue_depth` gauge (per-peer label series
        would be unbounded cardinality; see telemetry/metrics.py)."""
        return sum(p.send_queue_depth() for p in self.peers())

    def send_queue_depth_max(self) -> int:
        """Deepest single-peer send queue (one slow peer vs global
        backpressure)."""
        return max((p.send_queue_depth() for p in self.peers()), default=0)

    def send_queue_depths(self) -> dict[str, int]:
        """Per-peer depths for diagnostics (dump_telemetry RPC), keyed
        by peer id — deliberately NOT exported as labeled series."""
        return {p.id: p.send_queue_depth() for p in self.peers()}

    def add_peer_endpoint(
        self, remote_info: NodeInfo, endpoint: Endpoint, outbound: bool
    ) -> Peer:
        """Attach a connected endpoint as a peer (version/chain/dup checks
        per reference `addPeer :216-260`)."""
        reason = self.node_info.compatible_with(remote_info)
        if reason is not None:
            endpoint.close()
            raise ValueError(f"incompatible peer: {reason}")
        if self.scorer.is_banned(remote_info.node_id):
            endpoint.close()
            raise ValueError(f"peer banned: {remote_info.node_id[:12]}")
        if self.peer_filter is not None:
            reason = self.peer_filter(
                remote_info, getattr(endpoint, "remote_addr", "")
            )
            if reason is not None:
                endpoint.close()
                raise ValueError(f"peer filtered: {reason}")
        with self._mtx:
            if remote_info.node_id in self._peers:
                endpoint.close()
                raise ValueError(f"duplicate peer {remote_info.node_id}")
            peer = Peer(
                remote_info,
                endpoint,
                self._descriptors,
                self._dispatch,
                self._on_peer_error,
                outbound,
                send_limit=self.send_rate,
                recv_limit=self.recv_rate,
                ping_interval=self.ping_interval,
                pong_timeout=self.pong_timeout,
                local_node_id=self._base_info.node_id,
                gossip=self.gossip,
            )
            self._peers[remote_info.node_id] = peer
        # Reactors install their per-peer state BEFORE the recv loop
        # starts: a frame dispatched in the gap would find no PeerState
        # and be silently dropped — at genesis a dropped NewRoundStep
        # wedged vote gossip for good (found by the nemesis harness).
        # Outbound sends queue in the channel buffers until start().
        for r in self._reactors.values():
            r.add_peer(peer)
        peer.start()
        kv(
            logger("p2p"),
            logging.INFO,
            "peer connected",
            peer=remote_info.moniker,
            id=remote_info.node_id[:12],
            outbound=outbound,
        )
        return peer

    def stop_peer(self, peer: Peer, reason) -> None:
        with self._mtx:
            if self._peers.get(peer.id) is not peer:
                return
            del self._peers[peer.id]
        peer.stop()
        kv(
            logger("p2p"),
            logging.INFO,
            "peer disconnected",
            id=peer.id[:12],
            reason=str(reason)[:60],
        )
        for r in self._reactors.values():
            r.remove_peer(peer, reason)
        if self.on_peer_removed is not None:
            self.on_peer_removed(peer, reason)

    def stop_peer_for_error(self, peer: Peer, reason) -> None:
        """Reference `StopPeerForError` — reactors call this on bad
        messages; the peer is dropped everywhere."""
        self.stop_peer(peer, reason)

    # -- misbehavior ---------------------------------------------------------

    def report_misbehavior(
        self, peer, kind: str, detail: str = "", weight: int | None = None
    ) -> None:
        """Debit one classified offense against a peer (`Peer` or node
        id). Crossing the ban threshold bans + disconnects it; below
        threshold the peer stays connected (score decay forgives honest
        noise). Severe kinds carry weights that ban in 1-2 offenses
        (p2p/score.py MISBEHAVIOR_WEIGHTS)."""
        peer_obj = peer if isinstance(peer, Peer) else None
        peer_id = peer_obj.id if peer_obj is not None else str(peer or "")
        if not peer_id:
            return  # internal / self-originated input: never self-ban
        _metrics.PEER_MISBEHAVIOR.labels(kind=kind).inc()
        kv(
            logger("p2p"),
            logging.WARNING,
            "peer misbehavior",
            id=peer_id[:12],
            kind=kind,
            score=round(self.scorer.score(peer_id), 1),
            detail=detail[:80],
        )
        if self.scorer.debit(peer_id, kind, weight=weight):
            self.ban_peer(peer_id, reason=f"misbehavior threshold ({kind})")

    def ban_peer(self, peer_id: str, reason: str = "banned") -> None:
        """Ban + disconnect by node id (idempotent)."""
        self.scorer.ban(peer_id)
        _metrics.PEER_BANS.inc()
        kv(logger("p2p"), logging.WARNING, "peer banned", id=peer_id[:12], reason=reason)
        with self._mtx:
            peer = self._peers.get(peer_id)
        if peer is not None:
            self.stop_peer(peer, reason)

    def _on_peer_error(self, peer: Peer, exc) -> None:
        if isinstance(exc, PeerMisbehavior):
            # connection-layer offense (bad/oversize frame): debit the
            # score BEFORE the drop so repeat offenders get banned and
            # cannot cycle reconnect->garbage->disconnect forever
            self.report_misbehavior(peer, exc.kind, detail=exc.detail)
        self.stop_peer(peer, exc)

    def _dispatch(self, chan_id: int, peer: Peer, payload: bytes) -> None:
        reactor = self._chan_to_reactor.get(chan_id)
        if reactor is None:
            return
        try:
            reactor.receive(chan_id, peer, payload)
        except Exception as e:
            # a reactor exploding on a message is peer-fault by default:
            # the frame parsed but its payload didn't survive the
            # reactor's decode/validate — score it, then drop the peer
            self.report_misbehavior(peer, "bad_msg", detail=str(e))
            self.stop_peer_for_error(peer, e)

    # -- broadcast ---------------------------------------------------------

    def broadcast(self, chan_id: int, payload: bytes) -> None:
        for p in self.peers():
            p.try_send(chan_id, payload)


def connect_switches(a: Switch, b: Switch, wrap=None) -> tuple[Peer, Peer]:
    """Wire two switches over an in-memory pipe (reference
    `Connect2Switches p2p/switch.go:526-534`).

    `wrap`, when given, maps the two raw endpoints to (possibly
    fault-injecting) replacements before the peers attach — the seam
    chaos drivers (`testing/nemesis.py`) use to own a link's faults:
    `wrap(endpoint_a, endpoint_b) -> (endpoint_a', endpoint_b')`."""
    ea, eb = pipe_pair()
    if wrap is not None:
        ea, eb = wrap(ea, eb)
    pa = a.add_peer_endpoint(b.node_info, ea, outbound=True)
    pb = b.add_peer_endpoint(a.node_info, eb, outbound=False)
    return pa, pb


def make_connected_switches(
    n: int, init: Callable[[int], Switch], full_mesh: bool = True
) -> list[Switch]:
    """N started switches, fully meshed (reference
    `MakeConnectedSwitches p2p/switch.go:502-524`)."""
    switches = [init(i) for i in range(n)]
    for s in switches:
        s.start()
    if full_mesh:
        for i in range(n):
            for j in range(i + 1, n):
                connect_switches(switches[i], switches[j])
    return switches
