"""Per-peer misbehavior scoring + time-limited bans.

Adversarial-input hardening for the switch: every classified offense
(malformed frame, invalid signature, forged block, bogus evidence, …)
debits a per-peer score that DECAYS with time — occasional noise from a
buggy-but-honest peer is forgiven, a sustained attack crosses the ban
threshold fast. Crossing it disconnects the peer and refuses
re-connection until the ban expires.

Scoring is deliberately coarse (integer weights per kind) and cheap
(one dict lookup per offense, exponential decay applied lazily at
touch time): the hot paths that call it — recv loops under flood — must
not pay for their own defense.

The weights encode severity: one garbage frame could be corruption;
one forged block or forged evidence proof is cryptographically
impossible to produce honestly, so it lands most of a ban by itself.
"""

from __future__ import annotations

import threading
import time

from tendermint_tpu.utils.lockrank import ranked_lock


class PeerMisbehavior(Exception):
    """A typed peer-fault signal: carried from the connection layer (bad
    frame, oversize frame) up to the switch, which debits the peer's
    score before dropping it — the recv loop itself never crashes."""

    def __init__(self, kind: str, detail: str = "") -> None:
        super().__init__(f"peer misbehavior [{kind}]: {detail}" if detail else kind)
        self.kind = kind
        self.detail = detail


# offense -> score debit (threshold 100 by default)
MISBEHAVIOR_WEIGHTS: dict[str, int] = {
    "bad_frame": 25,  # unparseable / truncated / length-lying frame
    "oversize_frame": 25,  # frame over the hard size cap
    "bad_msg": 20,  # frame parsed, reactor payload didn't
    "bad_sig": 10,  # invalid signature on a vote/tx/heartbeat
    "bad_vote": 15,  # structurally invalid vote (wrong address/index)
    # a block failing commit verification cannot be served honestly
    # (it would need 2/3 forged signatures): instant ban
    "forged_block": 100,
    # a FullCommit failing light-client certification cannot be served
    # honestly either (forged signatures or an impossible quorum): a
    # replica/peer caught serving one is lying about the chain
    "forged_fullcommit": 100,
    "bad_evidence": 50,  # evidence proof with forged signatures
    "flood": 10,  # per-round state-growth abuse (maj23 claim flood)
}
DEFAULT_WEIGHT = 20

DEFAULT_BAN_THRESHOLD = 100
DEFAULT_HALF_LIFE_S = 60.0
DEFAULT_BAN_DURATION_S = 300.0


class PeerScorer:
    """Decaying misbehavior scores + ban book, keyed by node id."""

    def __init__(
        self,
        threshold: int = DEFAULT_BAN_THRESHOLD,
        half_life_s: float = DEFAULT_HALF_LIFE_S,
        ban_duration_s: float = DEFAULT_BAN_DURATION_S,
        clock=time.monotonic,
    ) -> None:
        self.threshold = threshold
        self.half_life_s = max(1e-3, half_life_s)
        self.ban_duration_s = ban_duration_s
        self._clock = clock
        self._lock = ranked_lock("p2p.scorer")
        self._scores: dict[str, tuple[float, float]] = {}  # id -> (score, at)
        self._bans: dict[str, float] = {}  # id -> ban expiry

    # -- scoring -------------------------------------------------------------

    def _decayed_locked(self, peer_id: str, now: float) -> float:
        entry = self._scores.get(peer_id)
        if entry is None:
            return 0.0
        score, at = entry
        return score * 0.5 ** ((now - at) / self.half_life_s)

    def debit(self, peer_id: str, kind: str, weight: int | None = None) -> bool:
        """Charge one offense; True when the peer just crossed the ban
        threshold (the caller bans + disconnects)."""
        if not peer_id:
            return False
        if weight is None:
            weight = MISBEHAVIOR_WEIGHTS.get(kind, DEFAULT_WEIGHT)
        now = self._clock()
        with self._lock:
            score = self._decayed_locked(peer_id, now) + weight
            self._scores[peer_id] = (score, now)
            if score >= self.threshold:
                already = self._banned_locked(peer_id, now)
                self._bans[peer_id] = now + self.ban_duration_s
                del self._scores[peer_id]  # ban resets the ledger
                return not already
        return False

    def score(self, peer_id: str) -> float:
        with self._lock:
            return self._decayed_locked(peer_id, self._clock())

    # -- bans ----------------------------------------------------------------

    def _banned_locked(self, peer_id: str, now: float) -> bool:
        expiry = self._bans.get(peer_id)
        if expiry is None:
            return False
        if now >= expiry:
            del self._bans[peer_id]
            return False
        return True

    def ban(self, peer_id: str, duration_s: float | None = None) -> None:
        now = self._clock()
        with self._lock:
            self._bans[peer_id] = now + (
                self.ban_duration_s if duration_s is None else duration_s
            )

    def unban(self, peer_id: str) -> None:
        with self._lock:
            self._bans.pop(peer_id, None)

    def is_banned(self, peer_id: str) -> bool:
        with self._lock:
            return self._banned_locked(peer_id, self._clock())

    def banned_peers(self) -> list[str]:
        now = self._clock()
        with self._lock:
            return [p for p in list(self._bans) if self._banned_locked(p, now)]

    def snapshot(self) -> dict:
        """Diagnostics view (dump_telemetry-style, not exported series)."""
        now = self._clock()
        with self._lock:
            return {
                "scores": {
                    p: round(self._decayed_locked(p, now), 1)
                    for p in list(self._scores)
                },
                "bans": {
                    p: round(self._bans[p] - now, 1)
                    for p in list(self._bans)
                    if self._banned_locked(p, now)
                },
            }
