"""SecretConnection: authenticated encryption for peer links.

Fills the reference's `p2p/secret_connection.go:36-115` slot — a
Station-to-Station handshake (ephemeral ECDH, then each side signs the
session transcript with its long-lived ed25519 node key) under
per-direction nonce-counter AEAD framing.

DELIBERATE MODERNIZATION (not bit-compatible, like the rest of this
framework's wire layer): X25519 + HKDF-SHA256 + ChaCha20Poly1305
replaces the reference's hand-rolled nacl secretbox construction with
nonces derived from sorted ephemeral keys. Same security shape —
authenticated, forward-secret per connection, MITM-excluded by the
transcript signature — using reviewed primitives from `cryptography`.
"""

from __future__ import annotations

import struct
import threading

from cryptography.hazmat.primitives import hashes, serialization
from cryptography.hazmat.primitives.asymmetric.x25519 import (
    X25519PrivateKey,
    X25519PublicKey,
)
from cryptography.hazmat.primitives.ciphers.aead import ChaCha20Poly1305
from cryptography.hazmat.primitives.kdf.hkdf import HKDF

from tendermint_tpu.crypto.keys import PrivKey, PubKey
from tendermint_tpu.p2p.transport import EndpointClosed
from tendermint_tpu.utils.lockrank import ranked_lock

_TRANSCRIPT_PREFIX = b"tendermint_tpu/secret-connection/v1"


class HandshakeError(Exception):
    pass


def _kdf(shared: bytes, salt: bytes) -> bytes:
    return HKDF(
        algorithm=hashes.SHA256(), length=64, salt=salt, info=_TRANSCRIPT_PREFIX
    ).derive(shared)


class SecretEndpoint:
    """Wrap any Endpoint with an authenticated encrypted channel.

    After construction the handshake has completed: `remote_pub_key`
    holds the peer's verified long-lived ed25519 identity (the Switch
    checks it against the claimed NodeInfo.node_id).
    """

    def __init__(self, inner, priv_key: PrivKey) -> None:
        self._inner = inner
        # socket-level identity passes through the encryption layer
        self.remote_addr = getattr(inner, "remote_addr", "")
        self.remote_pub_key: PubKey | None = None
        self._send_nonce = 0
        self._recv_nonce = 0
        self._send_lock = ranked_lock("p2p.conn.write")
        self._handshake(priv_key)

    # -- handshake ---------------------------------------------------------

    def _handshake(self, priv_key: PrivKey) -> None:
        eph = X25519PrivateKey.generate()
        eph_pub = eph.public_key().public_bytes(
            serialization.Encoding.Raw, serialization.PublicFormat.Raw
        )
        self._inner.send(eph_pub)
        remote_eph = self._inner.recv(timeout=10.0)
        if len(remote_eph) != 32:
            raise HandshakeError("bad ephemeral key")
        shared = eph.exchange(X25519PublicKey.from_public_bytes(remote_eph))

        # both sides derive identical key material: salt = sorted eph keys
        lo, hi = sorted((eph_pub, remote_eph))
        keys = _kdf(shared, lo + hi)
        # the lexicographically-lower ephemeral key sends with the first
        # half (reference derives nonces from sorted eph keys the same way)
        if eph_pub == lo:
            self._send_key = ChaCha20Poly1305(keys[:32])
            self._recv_key = ChaCha20Poly1305(keys[32:])
        else:
            self._send_key = ChaCha20Poly1305(keys[32:])
            self._recv_key = ChaCha20Poly1305(keys[:32])

        # authenticate: sign the transcript with the node identity key
        # (sent through the just-established encrypted channel, so the
        # identity is hidden from passive observers — reference behavior)
        transcript = _TRANSCRIPT_PREFIX + lo + hi
        sig = priv_key.sign(transcript)
        self.send(priv_key.pub_key.data + sig)
        auth = self.recv(timeout=10.0)
        if len(auth) != 32 + 64:
            raise HandshakeError("bad auth frame")
        remote_pub = PubKey(auth[:32])
        if not remote_pub.verify(transcript, auth[32:]):
            raise HandshakeError("peer failed transcript authentication")
        self.remote_pub_key = remote_pub

    # -- framing -----------------------------------------------------------

    def _nonce(self, counter: int) -> bytes:
        return struct.pack(">IQ", 0, counter)

    def send(self, data: bytes, timeout: float = 10.0) -> bool:
        with self._send_lock:
            # the wire write stays INSIDE the lock: frames must hit the
            # transport in nonce order or the receiver's counter
            # desyncs and the AEAD check kills the link. The counter
            # advances ONLY on a successful write — a backpressure drop
            # (inner send returning False) must not burn a nonce, or
            # the very next frame kills the connection.
            nonce = self._nonce(self._send_nonce)
            sealed = self._send_key.encrypt(nonce, data, None)
            ok = self._inner.send(sealed, timeout)
            if ok:
                self._send_nonce += 1
            return ok

    def recv(self, timeout: float | None = None) -> bytes:
        sealed = self._inner.recv(timeout)
        nonce = self._nonce(self._recv_nonce)
        self._recv_nonce += 1
        try:
            return self._recv_key.decrypt(nonce, sealed, None)
        except Exception as e:
            # tampered/replayed/reordered frame: kill the link
            self._inner.close()
            raise EndpointClosed from e

    def close(self) -> None:
        self._inner.close()

    @property
    def closed(self) -> bool:
        return self._inner.closed
