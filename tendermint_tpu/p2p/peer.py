"""Peer: one remote node = NodeInfo + multiplexed connection.

Reference `p2p/peer.go:17` — here the secret-connection handshake is a
transport concern (the in-memory transport is already authenticated by
construction); NodeInfo exchange happens at connect time through the
Switch.
"""

from __future__ import annotations

from dataclasses import dataclass

from tendermint_tpu.codec.binary import Reader, Writer
from tendermint_tpu.p2p.connection import ChannelDescriptor, MConnection
from tendermint_tpu.p2p.transport import Endpoint


@dataclass(frozen=True)
class NodeInfo:
    """Reference `p2p/peer.go` NodeInfo (identity + compat handshake).
    `listen_addr` is the peer's dialable address ("host:port", empty for
    non-listening nodes) — PEX gossips it to other peers."""

    node_id: str  # hex of the node key address
    moniker: str
    chain_id: str
    version: str = "0.1.0"
    channels: tuple[int, ...] = ()
    listen_addr: str = ""

    def encode(self) -> bytes:
        w = (
            Writer()
            .string(self.node_id)
            .string(self.moniker)
            .string(self.chain_id)
            .string(self.version)
        )
        w.uvarint(len(self.channels))
        for c in self.channels:
            w.uvarint(c)
        w.string(self.listen_addr)
        return w.build()

    @classmethod
    def decode(cls, data: bytes) -> "NodeInfo":
        r = Reader(data)
        node_id, moniker, chain_id, version = (
            r.string(),
            r.string(),
            r.string(),
            r.string(),
        )
        channels = tuple(r.uvarint() for _ in range(r.uvarint()))
        listen_addr = r.string() if not r.done() else ""
        return cls(node_id, moniker, chain_id, version, channels, listen_addr)

    def compatible_with(self, other: "NodeInfo") -> str | None:
        """None if compatible, else the reason (reference
        `NodeInfo.CompatibleWith`)."""
        if self.chain_id != other.chain_id:
            return f"chain_id mismatch: {self.chain_id} != {other.chain_id}"
        if self.node_id == other.node_id:
            return "self-connection"
        return None


class Peer:
    """A connected peer: send/receive by channel + a KV store that
    reactors use for per-peer state (reference peer.Set/Get, used for
    `PeerState`)."""

    def __init__(
        self,
        node_info: NodeInfo,
        endpoint: Endpoint,
        channels: list[ChannelDescriptor],
        on_receive,
        on_error,
        outbound: bool,
        send_limit: int = 0,
        recv_limit: int = 0,
        ping_interval: float | None = None,
        pong_timeout: float | None = None,
        local_node_id: str = "",
        gossip=None,
    ) -> None:
        self.node_info = node_info
        self.outbound = outbound
        self.data: dict[str, object] = {}  # reactor KV (PeerState lives here)
        kw = {}
        if ping_interval is not None:
            kw["ping_interval"] = ping_interval
        if pong_timeout is not None:
            kw["pong_timeout"] = pong_timeout
        if gossip is not None and gossip.enabled:
            # gossip observatory: bind OUR view of the remote id to every
            # frame the connection sees (disabled rollup -> no hook at
            # all, the sampled-out fast path)
            remote = node_info.node_id
            kw["on_traffic"] = (
                lambda direction, chan_id, payload, frame_len: gossip.record(
                    remote, direction, chan_id, payload, frame_len
                )
            )
        self._conn = MConnection(
            endpoint,
            channels,
            lambda ch, payload: on_receive(ch, self, payload),
            lambda exc: on_error(self, exc),
            send_limit=send_limit,
            recv_limit=recv_limit,
            local_node_id=local_node_id,
            **kw,
        )

    @property
    def send_monitor(self):
        return self._conn.send_monitor

    @property
    def recv_monitor(self):
        return self._conn.recv_monitor

    def send_queue_depth(self) -> int:
        return self._conn.send_queue_depth()

    @property
    def remote_addr(self) -> str:
        """Socket-level remote address ("" for in-memory transports)."""
        return getattr(self._conn._endpoint, "remote_addr", "")

    @property
    def id(self) -> str:
        return self.node_info.node_id

    def start(self) -> None:
        self._conn.start()

    def stop(self) -> None:
        self._conn.stop()

    def send(self, chan_id: int, payload: bytes, ctx=None) -> bool:
        """`ctx` (a `telemetry.tracectx.TraceContext`) rides the frame;
        None falls back to the calling thread's ambient context."""
        return self._conn.send(chan_id, payload, ctx=ctx)

    def try_send(self, chan_id: int, payload: bytes, ctx=None) -> bool:
        return self._conn.try_send(chan_id, payload, ctx=ctx)

    def get(self, key: str, default=None):
        return self.data.get(key, default)

    def set(self, key: str, value) -> None:
        self.data[key] = value

    def __repr__(self) -> str:
        return f"Peer({self.node_info.moniker}:{self.id[:8]})"
