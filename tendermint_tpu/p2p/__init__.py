"""P2P communication backend (reference `p2p/`).

Host-side control plane: multiplexed prioritized channels over pluggable
byte transports (in-memory pipes for tests and local multi-node,
TCP-ready framing), a Reactor registry Switch, and network fault
injection. WAN gossip is latency-bound tiny-payload work — the wrong
shape for TPU interconnect — so this layer stays on host by design
(SURVEY.md §5.8); the TPU data plane lives in `parallel/` (ICI
collectives) and `ops/` (batch kernels).
"""

from tendermint_tpu.p2p.connection import ChannelDescriptor, MConnection
from tendermint_tpu.p2p.peer import NodeInfo, Peer
from tendermint_tpu.p2p.score import PeerMisbehavior, PeerScorer
from tendermint_tpu.p2p.switch import (
    Reactor,
    Switch,
    connect_switches,
    make_connected_switches,
)
from tendermint_tpu.p2p.transport import FuzzedEndpoint, FuzzConfig, pipe_pair

__all__ = [
    "ChannelDescriptor",
    "MConnection",
    "NodeInfo",
    "Peer",
    "PeerMisbehavior",
    "PeerScorer",
    "Reactor",
    "Switch",
    "connect_switches",
    "make_connected_switches",
    "FuzzedEndpoint",
    "FuzzConfig",
    "pipe_pair",
]
