"""Byte transports: in-memory pipes + fault injection.

The in-memory pipe fills the slot of the reference's `net.Pipe` test
transport (`p2p/switch.go:502-534` MakeConnectedSwitches wiring); the
`Endpoint` interface is what a TCP/secret-connection transport plugs
into later. `FuzzedEndpoint` is the network fault injector (reference
`p2p/fuzz.go:19-47`: probabilistic drops, delays, connection death).
"""

from __future__ import annotations

import queue
import random
import threading
import time
from dataclasses import dataclass


class EndpointClosed(Exception):
    pass


class Endpoint:
    """One end of a bidirectional message-framed byte link.

    send() never blocks forever (bounded queue, drop-on-close); recv()
    blocks until a frame arrives or the link closes (raises
    EndpointClosed). Framing is preserved: one send -> one recv.
    """

    def __init__(self, out_q: "queue.Queue", in_q: "queue.Queue") -> None:
        self._out = out_q
        self._in = in_q
        self._closed = threading.Event()

    def send(self, data: bytes, timeout: float = 10.0) -> bool:
        if self._closed.is_set():
            raise EndpointClosed
        try:
            self._out.put(data, timeout=timeout)
            return True
        except queue.Full:
            return False

    def recv(self, timeout: float | None = None) -> bytes:
        deadline = time.monotonic() + timeout if timeout is not None else None
        while True:
            if self._closed.is_set() and self._in.empty():
                raise EndpointClosed
            remaining = 0.05
            if deadline is not None:
                remaining = min(remaining, deadline - time.monotonic())
                if remaining <= 0:
                    raise TimeoutError
            try:
                item = self._in.get(timeout=remaining)
            except queue.Empty:
                continue
            if item is _CLOSE:
                self._closed.set()
                raise EndpointClosed
            return item

    def close(self) -> None:
        if self._closed.is_set():
            return
        self._closed.set()
        # wake the far side's recv
        try:
            self._out.put_nowait(_CLOSE)
        except queue.Full:
            pass
        # wake our own blocked recv
        try:
            self._in.put_nowait(_CLOSE)
        except queue.Full:
            pass

    @property
    def closed(self) -> bool:
        return self._closed.is_set()


_CLOSE = object()


def pipe_pair(capacity: int = 1024) -> tuple[Endpoint, Endpoint]:
    """Two connected in-memory endpoints (the net.Pipe analog)."""
    a_to_b: "queue.Queue" = queue.Queue(maxsize=capacity)
    b_to_a: "queue.Queue" = queue.Queue(maxsize=capacity)
    return Endpoint(a_to_b, b_to_a), Endpoint(b_to_a, a_to_b)


@dataclass
class FuzzConfig:
    """Reference `p2p/fuzz.go` FuzzConnConfig."""

    prob_drop_rw: float = 0.0  # drop an individual send
    prob_drop_conn: float = 0.0  # kill the link on a send
    prob_sleep: float = 0.0  # delay a send
    max_sleep_s: float = 0.05
    seed: int | None = None


class FuzzedEndpoint:
    """Wrap an Endpoint with probabilistic faults (network fault
    injection for tests — reference `p2p/fuzz.go:19-47`)."""

    def __init__(self, inner: Endpoint, config: FuzzConfig) -> None:
        self._inner = inner
        self._cfg = config
        self._rng = random.Random(config.seed)

    def send(self, data: bytes, timeout: float = 10.0) -> bool:
        c = self._cfg
        if c.prob_drop_conn and self._rng.random() < c.prob_drop_conn:
            self._inner.close()
            raise EndpointClosed
        if c.prob_drop_rw and self._rng.random() < c.prob_drop_rw:
            return True  # silently dropped
        if c.prob_sleep and self._rng.random() < c.prob_sleep:
            time.sleep(self._rng.uniform(0, c.max_sleep_s))
        return self._inner.send(data, timeout)

    def recv(self, timeout: float | None = None) -> bytes:
        return self._inner.recv(timeout)

    def close(self) -> None:
        self._inner.close()

    @property
    def closed(self) -> bool:
        return self._inner.closed
