"""Byte transports: in-memory pipes + fault injection.

The in-memory pipe fills the slot of the reference's `net.Pipe` test
transport (`p2p/switch.go:502-534` MakeConnectedSwitches wiring); the
`Endpoint` interface is what a TCP/secret-connection transport plugs
into later. `FuzzedEndpoint` is the network fault injector (reference
`p2p/fuzz.go:19-47`: probabilistic drops, delays, connection death).
"""

from __future__ import annotations

import queue
import random
import threading
import time
from dataclasses import dataclass


class EndpointClosed(Exception):
    pass


class Endpoint:
    """One end of a bidirectional message-framed byte link.

    send() never blocks forever (bounded queue, drop-on-close); recv()
    blocks until a frame arrives or the link closes (raises
    EndpointClosed). Framing is preserved: one send -> one recv.
    """

    def __init__(self, out_q: "queue.Queue", in_q: "queue.Queue") -> None:
        self._out = out_q
        self._in = in_q
        self._closed = threading.Event()

    def send(self, data: bytes, timeout: float = 10.0) -> bool:
        if self._closed.is_set():
            raise EndpointClosed
        try:
            self._out.put(data, timeout=timeout)
            return True
        except queue.Full:
            return False

    def recv(self, timeout: float | None = None) -> bytes:
        deadline = time.monotonic() + timeout if timeout is not None else None
        while True:
            if self._closed.is_set() and self._in.empty():
                raise EndpointClosed
            remaining = 0.05
            if deadline is not None:
                remaining = min(remaining, deadline - time.monotonic())
                if remaining <= 0:
                    raise TimeoutError
            try:
                item = self._in.get(timeout=remaining)
            except queue.Empty:
                continue
            if item is _CLOSE:
                self._closed.set()
                raise EndpointClosed
            return item

    def close(self) -> None:
        if self._closed.is_set():
            return
        self._closed.set()
        # wake the far side's recv
        try:
            self._out.put_nowait(_CLOSE)
        except queue.Full:
            pass
        # wake our own blocked recv
        try:
            self._in.put_nowait(_CLOSE)
        except queue.Full:
            pass

    @property
    def closed(self) -> bool:
        return self._closed.is_set()


_CLOSE = object()


def pipe_pair(capacity: int = 1024) -> tuple[Endpoint, Endpoint]:
    """Two connected in-memory endpoints (the net.Pipe analog)."""
    a_to_b: "queue.Queue" = queue.Queue(maxsize=capacity)
    b_to_a: "queue.Queue" = queue.Queue(maxsize=capacity)
    return Endpoint(a_to_b, b_to_a), Endpoint(b_to_a, a_to_b)


@dataclass
class FuzzConfig:
    """Reference `p2p/fuzz.go` FuzzConnConfig."""

    prob_drop_rw: float = 0.0  # drop an individual send
    prob_drop_conn: float = 0.0  # kill the link on a send
    prob_sleep: float = 0.0  # delay a send
    max_sleep_s: float = 0.05
    prob_dup: float = 0.0  # deliver a send twice (gossip must be idempotent)
    seed: int | None = None


class FuzzedEndpoint:
    """Wrap an Endpoint with probabilistic faults (network fault
    injection for tests — reference `p2p/fuzz.go:19-47`)."""

    def __init__(self, inner: Endpoint, config: FuzzConfig) -> None:
        self._inner = inner
        self._cfg = config
        self._rng = random.Random(config.seed)

    def send(self, data: bytes, timeout: float = 10.0) -> bool:
        c = self._cfg
        if c.prob_drop_conn and self._rng.random() < c.prob_drop_conn:
            self._inner.close()
            raise EndpointClosed
        if c.prob_drop_rw and self._rng.random() < c.prob_drop_rw:
            return True  # silently dropped
        if c.prob_sleep and self._rng.random() < c.prob_sleep:
            time.sleep(self._rng.uniform(0, c.max_sleep_s))
        if c.prob_dup and self._rng.random() < c.prob_dup:
            self._inner.send(data, timeout)
        return self._inner.send(data, timeout)

    def recv(self, timeout: float | None = None) -> bytes:
        return self._inner.recv(timeout)

    def close(self) -> None:
        self._inner.close()

    @property
    def closed(self) -> bool:
        return self._inner.closed


class LinkChaos:
    """Runtime-mutable fault knobs for ONE direction of a link.

    Unlike FuzzConfig (fixed probabilities for a connection's lifetime)
    these are flipped live by a chaos driver (`testing/nemesis.py`):
    partition a running network, heal it, add delay or duplication for
    a window, all without touching the peers' connection state.
    """

    def __init__(self, seed: int | None = None) -> None:
        self.partitioned = False  # black-hole every send (partition)
        self.delay_s = 0.0  # defer each delivery by this much
        self.dup_prob = 0.0  # deliver twice
        self.drop_prob = 0.0  # drop individual sends
        self._rng = random.Random(seed)


class ChaosEndpoint:
    """Endpoint wrapper governed by a live LinkChaos.

    Partitioned links swallow sends silently (a partition loses
    packets; it does not error — the consensus gossip layer must treat
    silence and loss identically). Delayed deliveries ride a timer
    thread, so delay also implies possible reordering, exactly like a
    real congested path. Composes over FuzzedEndpoint for probabilistic
    background faults plus driver-controlled chaos on one link.
    """

    def __init__(self, inner, chaos: LinkChaos) -> None:
        self._inner = inner
        self.chaos = chaos

    def send(self, data: bytes, timeout: float = 10.0) -> bool:
        c = self.chaos
        if c.partitioned:
            return True  # black hole
        if c.drop_prob and c._rng.random() < c.drop_prob:
            return True
        copies = 2 if (c.dup_prob and c._rng.random() < c.dup_prob) else 1
        if c.delay_s > 0:
            for _ in range(copies):
                t = threading.Timer(c.delay_s, self._late_send, args=(data,))
                t.daemon = True
                t.start()
            return True
        ok = True
        for _ in range(copies):
            ok = self._inner.send(data, timeout)
        return ok

    def _late_send(self, data: bytes) -> None:
        try:
            if not self.chaos.partitioned:  # partition may have started
                self._inner.send(data, timeout=1.0)
        except EndpointClosed:
            pass

    def recv(self, timeout: float | None = None) -> bytes:
        return self._inner.recv(timeout)

    def close(self) -> None:
        self._inner.close()

    @property
    def closed(self) -> bool:
        return self._inner.closed
