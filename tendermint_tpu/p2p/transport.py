"""Byte transports: in-memory pipes + fault injection.

The in-memory pipe fills the slot of the reference's `net.Pipe` test
transport (`p2p/switch.go:502-534` MakeConnectedSwitches wiring); the
`Endpoint` interface is what a TCP/secret-connection transport plugs
into later. `FuzzedEndpoint` is the network fault injector (reference
`p2p/fuzz.go:19-47`: probabilistic drops, delays, connection death).
"""

from __future__ import annotations

import heapq
import logging
import queue
import random
import threading
import time
from dataclasses import dataclass

from tendermint_tpu.utils.log import kv, logger

_log = logger("transport")


class EndpointClosed(Exception):
    pass


class Endpoint:
    """One end of a bidirectional message-framed byte link.

    send() never blocks forever (bounded queue, drop-on-close); recv()
    blocks until a frame arrives or the link closes (raises
    EndpointClosed). Framing is preserved: one send -> one recv.
    """

    def __init__(self, out_q: "queue.Queue", in_q: "queue.Queue") -> None:
        self._out = out_q
        self._in = in_q
        self._closed = threading.Event()

    def send(self, data: bytes, timeout: float = 10.0) -> bool:
        if self._closed.is_set():
            raise EndpointClosed
        try:
            self._out.put(data, timeout=timeout)
            return True
        except queue.Full:
            return False

    def recv(self, timeout: float | None = None) -> bytes:
        deadline = time.monotonic() + timeout if timeout is not None else None
        while True:
            if self._closed.is_set() and self._in.empty():
                raise EndpointClosed
            remaining = 0.05
            if deadline is not None:
                remaining = min(remaining, deadline - time.monotonic())
                if remaining <= 0:
                    raise TimeoutError
            try:
                item = self._in.get(timeout=remaining)
            except queue.Empty:
                continue
            if item is _CLOSE:
                self._closed.set()
                raise EndpointClosed
            return item

    def close(self) -> None:
        if self._closed.is_set():
            return
        self._closed.set()
        # wake the far side's recv
        try:
            self._out.put_nowait(_CLOSE)
        except queue.Full:
            pass
        # wake our own blocked recv
        try:
            self._in.put_nowait(_CLOSE)
        except queue.Full:
            pass

    @property
    def closed(self) -> bool:
        return self._closed.is_set()


_CLOSE = object()


def pipe_pair(capacity: int = 1024) -> tuple[Endpoint, Endpoint]:
    """Two connected in-memory endpoints (the net.Pipe analog)."""
    a_to_b: "queue.Queue" = queue.Queue(maxsize=capacity)
    b_to_a: "queue.Queue" = queue.Queue(maxsize=capacity)
    return Endpoint(a_to_b, b_to_a), Endpoint(b_to_a, a_to_b)


class _TokenBucket:
    """Virtual-clock token bucket: rate `bps` bits/s, burst
    `burst_bytes` of idle credit. `wait(nbytes, now)` returns how long
    the caller's delivery must lag `now` for the link to have drained
    this message — 0 while burst credit lasts. Thread-safe; zero or
    negative rate means uncapped (never waits)."""

    def __init__(self) -> None:
        self._vt = 0.0  # virtual time the modeled queue drains
        self._lock = threading.Lock()

    def wait(self, nbytes: int, now: float, bps: float, burst_bytes: int) -> float:
        if bps <= 0:
            return 0.0
        ser_s = nbytes * 8.0 / bps
        burst_s = max(0, burst_bytes) * 8.0 / bps
        with self._lock:
            self._vt = max(self._vt, now - burst_s) + ser_s
            return max(0.0, self._vt - now)


@dataclass
class FuzzConfig:
    """Reference `p2p/fuzz.go` FuzzConnConfig, grown WAN-shaped knobs.

    All-zero defaults are a byte-for-byte no-op (golden-tested): the
    added fields only change behavior when set, so existing fuzz
    configurations keep their exact semantics and RNG draw sequence.
    """

    prob_drop_rw: float = 0.0  # drop an individual send
    prob_drop_conn: float = 0.0  # kill the link on a send
    prob_sleep: float = 0.0  # delay a send
    max_sleep_s: float = 0.05
    prob_dup: float = 0.0  # deliver a send twice (gossip must be idempotent)
    # uniform [0, jitter_s) extra sender-side latency on EVERY send
    # (unlike prob_sleep's occasional max_sleep_s nap)
    jitter_s: float = 0.0
    # token-bucket bandwidth cap, bits/s; 0 = uncapped. The sender
    # blocks for the serialization wait — fuzzed links model sender-side
    # backpressure, chaos links (LinkChaos) model in-flight delay
    bandwidth_bps: float = 0.0
    bandwidth_burst_bytes: int = 16 * 1024
    seed: int | None = None


class FuzzedEndpoint:
    """Wrap an Endpoint with probabilistic faults (network fault
    injection for tests — reference `p2p/fuzz.go:19-47`)."""

    def __init__(self, inner: Endpoint, config: FuzzConfig) -> None:
        self._inner = inner
        self._cfg = config
        self._rng = random.Random(config.seed)
        self._bucket = _TokenBucket()

    def send(self, data: bytes, timeout: float = 10.0) -> bool:
        c = self._cfg
        if c.prob_drop_conn and self._rng.random() < c.prob_drop_conn:
            self._inner.close()
            raise EndpointClosed
        if c.prob_drop_rw and self._rng.random() < c.prob_drop_rw:
            return True  # silently dropped
        if c.prob_sleep and self._rng.random() < c.prob_sleep:
            time.sleep(self._rng.uniform(0, c.max_sleep_s))
        if c.jitter_s:
            time.sleep(self._rng.uniform(0, c.jitter_s))
        if c.bandwidth_bps:
            wait = self._bucket.wait(
                len(data), time.monotonic(), c.bandwidth_bps,
                c.bandwidth_burst_bytes,
            )
            if wait > 0:
                time.sleep(wait)
        if c.prob_dup and self._rng.random() < c.prob_dup:
            self._inner.send(data, timeout)
        return self._inner.send(data, timeout)

    def recv(self, timeout: float | None = None) -> bytes:
        return self._inner.recv(timeout)

    def close(self) -> None:
        self._inner.close()

    @property
    def closed(self) -> bool:
        return self._inner.closed


class _DeliveryWheel:
    """One process-global scheduler thread for every delayed chaos
    delivery. The old shape — one `threading.Timer` per delayed send —
    spawned a short-lived OS thread per message; at WAN delays on every
    link that is thousands of concurrent threads for a few seconds of
    simulated flight. The wheel holds (due, seq, endpoint, frame)
    entries in a heap and ONE daemon thread sleeps until the earliest
    due time, so steady-state thread count is O(1) for the whole
    process regardless of in-flight sends (regression-tested in
    tests/test_topology.py).

    Ordering: deliveries pop strictly by due time (seq breaks ties in
    submission order), so a fixed `delay_s` path preserves FIFO — like
    a real fixed-latency pipe — while `jitter_s` spreads due times and
    reorders, like a real congested path. Delivery callbacks run
    OUTSIDE the wheel lock; a closed endpoint never kills the thread.

    The wheel thread must NEVER block on a receiver: a full inbound
    queue on one link would head-of-line-block delivery for every
    other link in the process. Deliveries are attempted non-blocking;
    a congested receiver gets the frame RESCHEDULED `RETRY_S` later
    (a queue holding the frame — congestion becomes latency), and
    after `MAX_TRIES` the frame is dropped like a saturated path drops
    packets (gossip re-transmits; counted as result="congested").
    """

    RETRY_S = 0.05  # requeue delay when the receiver's queue is full
    MAX_TRIES = 100  # ~5s of sustained congestion before dropping

    def __init__(self) -> None:
        self._cond = threading.Condition()
        self._heap: list[tuple[float, int, object, bytes, int]] = []
        self._seq = 0
        self._thread: threading.Thread | None = None

    def schedule(
        self, due: float, endpoint: "ChaosEndpoint", data: bytes, tries: int = 0
    ) -> None:
        with self._cond:
            heapq.heappush(self._heap, (due, self._seq, endpoint, data, tries))
            self._seq += 1
            depth = len(self._heap)
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._wheel_loop,
                    name="link-delivery-wheel",
                    daemon=True,
                )
                self._thread.start()
            self._cond.notify()
        _metrics().LINK_INFLIGHT.set(depth)

    def pending(self) -> int:
        with self._cond:
            return len(self._heap)

    def _wheel_loop(self) -> None:
        while True:
            with self._cond:
                while not self._heap:
                    self._cond.wait()
                due = self._heap[0][0]
                now = time.monotonic()
                if due > now:
                    self._cond.wait(timeout=due - now)
                    continue
                _, _, endpoint, data, tries = heapq.heappop(self._heap)
                depth = len(self._heap)
            _metrics().LINK_INFLIGHT.set(depth)
            try:
                if not endpoint._late_send(data):  # receiver congested
                    if tries < self.MAX_TRIES:
                        self.schedule(
                            time.monotonic() + self.RETRY_S, endpoint, data,
                            tries + 1,
                        )
                    else:
                        _metrics().LINK_SENDS.labels(result="congested").inc()
            except Exception as e:  # a dead link must not stop the wheel
                kv(_log, logging.WARNING, "wheel delivery failed",
                   error=type(e).__name__)


_WHEEL = _DeliveryWheel()

_METRICS = None


def _metrics():
    """Lazy metrics import: transport is a leaf module and telemetry
    pulls in the registry; bind once on first chaos delivery."""
    global _METRICS
    if _METRICS is None:
        from tendermint_tpu.telemetry import metrics as m

        _METRICS = m
    return _METRICS


class LinkChaos:
    """Runtime-mutable fault knobs for ONE direction of a link.

    Unlike FuzzConfig (fixed probabilities for a connection's lifetime)
    these are flipped live by a chaos driver (`testing/nemesis.py`) or
    shaped per-link by a WAN topology (`testing/topology.py`):
    partition a running network, heal it, add delay / jitter /
    duplication / a bandwidth cap for a window, all without touching
    the peers' connection state. All-zero knobs are a byte-for-byte
    no-op (golden-tested): sends pass straight through with no RNG
    draws and nothing rides the delivery wheel.
    """

    def __init__(self, seed: int | None = None) -> None:
        self.partitioned = False  # black-hole every send (partition)
        self.delay_s = 0.0  # defer each delivery by this much (one-way)
        self.jitter_s = 0.0  # + uniform [0, jitter_s) per delivery
        self.dup_prob = 0.0  # deliver twice
        self.drop_prob = 0.0  # drop individual sends
        self.bandwidth_bps = 0.0  # token-bucket cap, bits/s; 0 = uncapped
        self.bandwidth_burst_bytes = 16 * 1024
        self._rng = random.Random(seed)
        self._bucket = _TokenBucket()

    def bandwidth_wait(self, nbytes: int, now: float) -> float:
        return self._bucket.wait(
            nbytes, now, self.bandwidth_bps, self.bandwidth_burst_bytes
        )


class ChaosEndpoint:
    """Endpoint wrapper governed by a live LinkChaos.

    Partitioned links swallow sends silently (a partition loses
    packets; it does not error — the consensus gossip layer must treat
    silence and loss identically). Delayed deliveries ride the shared
    delivery wheel (`_WHEEL`) instead of a per-send timer thread;
    bandwidth serialization waits are folded into the delivery time
    (the sender never blocks — in-flight queueing shows up as latency,
    which is what a WAN path does to a message-framed overlay).
    Composes over FuzzedEndpoint for probabilistic background faults
    plus driver-controlled chaos on one link.
    """

    def __init__(self, inner, chaos: LinkChaos) -> None:
        self._inner = inner
        self.chaos = chaos

    def send(self, data: bytes, timeout: float = 10.0) -> bool:
        c = self.chaos
        if c.partitioned:
            _metrics().LINK_SENDS.labels(result="partitioned").inc()
            return True  # black hole
        if c.drop_prob and c._rng.random() < c.drop_prob:
            _metrics().LINK_SENDS.labels(result="dropped").inc()
            return True
        copies = 2 if (c.dup_prob and c._rng.random() < c.dup_prob) else 1
        if copies == 2:
            _metrics().LINK_SENDS.labels(result="dup").inc()
        base = c.delay_s
        bw_wait = 0.0
        if c.bandwidth_bps > 0:
            # both copies of a dup consume link capacity
            bw_wait = c.bandwidth_wait(len(data) * copies, time.monotonic())
            _metrics().LINK_BANDWIDTH_WAIT.observe(bw_wait)
            base += bw_wait
        if base <= 0 and c.jitter_s <= 0:
            ok = True
            for _ in range(copies):
                ok = self._inner.send(data, timeout)
            return ok
        m = _metrics()
        now = time.monotonic()
        for _ in range(copies):
            d = base
            if c.jitter_s > 0:
                d += c._rng.uniform(0, c.jitter_s)
            m.LINK_DELIVERY_DELAY.observe(d)
            _WHEEL.schedule(now + d, self, data)
        m.LINK_SENDS.labels(result="delayed").inc(copies)
        return True

    def _late_send(self, data: bytes) -> bool:
        """Wheel-side delivery. NON-BLOCKING: returns False when the
        receiver's queue is full so the wheel can reschedule instead of
        stalling every other link; True when delivered or dropped for
        cause (partition started mid-flight, link closed)."""
        try:
            if self.chaos.partitioned:  # partition may have started
                return True
            return self._inner.send(data, timeout=0.0)
        except EndpointClosed:
            return True

    def recv(self, timeout: float | None = None) -> bytes:
        return self._inner.recv(timeout)

    def close(self) -> None:
        self._inner.close()

    @property
    def closed(self) -> bool:
        return self._inner.closed
