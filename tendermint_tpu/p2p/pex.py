"""PEX reactor: peer-exchange discovery on channel 0x00.

Reference `p2p/pex_reactor.go` — new peers land in the address book,
address requests are answered with a random selection, and an
ensure-peers loop dials book addresses until the switch reaches its
target peer count.
"""

from __future__ import annotations

import threading

from tendermint_tpu.codec.binary import Reader, Writer
from tendermint_tpu.p2p.addrbook import AddrBook, NetAddress
from tendermint_tpu.p2p.connection import ChannelDescriptor
from tendermint_tpu.p2p.peer import Peer
from tendermint_tpu.p2p.switch import Reactor

PEX_CHANNEL = 0x00

_MSG_REQUEST = 0x01
_MSG_ADDRS = 0x02

_MAX_ADDRS_PER_MSG = 32


def encode_request() -> bytes:
    return Writer().uvarint(_MSG_REQUEST).build()


def encode_addrs(addrs: list[NetAddress]) -> bytes:
    w = Writer().uvarint(_MSG_ADDRS).uvarint(len(addrs))
    for a in addrs:
        w.string(a.node_id).string(a.addr)
    return w.build()


def decode_message(payload: bytes):
    r = Reader(payload)
    tag = r.uvarint()
    if tag == _MSG_REQUEST:
        return ("request", None)
    if tag == _MSG_ADDRS:
        n = min(r.uvarint(), _MAX_ADDRS_PER_MSG)
        return ("addrs", [NetAddress(r.string(), r.string()) for _ in range(n)])
    raise ValueError(f"unknown pex message {tag:#x}")


class PEXReactor(Reactor):
    def __init__(
        self,
        book: AddrBook,
        dial_fn=None,
        max_peers: int = 10,
        node_key=None,
        ensure_interval_s: float = 30.0,
    ) -> None:
        super().__init__()
        self.book = book
        self.max_peers = max_peers
        self.node_key = node_key
        self.ensure_interval_s = ensure_interval_s
        self._dial_fn = dial_fn
        self._running = False
        # peer_id -> monotonic time of our last addr request to it.
        # Requests are re-issuable (rate-limited): a one-shot request can
        # race the remote book's own fills — e.g. our request reaches B
        # before B registered a third node — and discovery would deadlock
        # with both sides waiting for gossip that never re-fires.
        self._requested: dict[str, float] = {}
        # wakes the ensure loop the moment new addresses arrive, so
        # discovery latency is bounded by gossip, not the poll interval
        # (also what makes multi-node PEX tests deterministic instead of
        # racing wall-clock ticks)
        self._wake = threading.Event()

    def get_channels(self) -> list[ChannelDescriptor]:
        return [ChannelDescriptor(PEX_CHANNEL, priority=1)]

    def on_start(self) -> None:
        self._running = True
        threading.Thread(
            target=self._ensure_peers_routine, name="pex-ensure", daemon=True
        ).start()

    def on_stop(self) -> None:
        self._running = False
        self._wake.set()  # unblock the ensure loop so it exits promptly
        self.book.flush()

    def add_peer(self, peer: Peer) -> None:
        # a connected peer's own listen address is book-worthy, but a
        # SELF-CLAIMED inbound address stays in a NEW bucket until WE
        # successfully dial it — promoting unproven addresses to OLD
        # would let NAT'd/malicious peers poison the proven set
        if peer.node_info.listen_addr:
            self.book.add_address(
                NetAddress(peer.id, peer.node_info.listen_addr), src_id=peer.id
            )
            if peer.outbound:
                self.book.mark_good(peer.id)
        # ask it for more addresses
        self._request_addrs(peer)

    def _request_addrs(self, peer: Peer) -> None:
        import time as _time

        now = _time.monotonic()
        if now - self._requested.get(peer.id, -1e9) < self.REREQUEST_MIN_S:
            return
        self._requested[peer.id] = now
        peer.try_send(PEX_CHANNEL, encode_request())

    def remove_peer(self, peer: Peer, reason) -> None:
        self._requested.pop(peer.id, None)
        self._wake.set()  # top back up promptly after a peer drops

    def receive(self, chan_id: int, peer: Peer, payload: bytes) -> None:
        kind, arg = decode_message(payload)
        if kind == "request":
            peer.try_send(
                PEX_CHANNEL, encode_addrs(self.book.sample(_MAX_ADDRS_PER_MSG))
            )
        elif kind == "addrs":
            me = self.switch.node_info.node_id if self.switch else ""
            added = False
            for addr in arg:
                if addr.node_id != me:
                    added |= self.book.add_address(addr, src_id=peer.id)
            if added:
                # only GENUINELY new book entries wake the loop —
                # already-known gossip must not trigger re-dial passes
                self._wake.set()

    # -- ensure-peers loop -------------------------------------------------

    def _dial(self, addr: NetAddress):
        if self._dial_fn is not None:
            return self._dial_fn(addr)
        from tendermint_tpu.p2p.tcp import dial

        return dial(self.switch, addr.addr, priv_key=self.node_key)

    # wake-driven passes may not repeat faster than this — failed dials
    # return in milliseconds, so without a floor a book full of dead
    # addresses would be hammered in a tight burst
    MIN_PASS_SPACING_S = 1.0

    def _ensure_peers_routine(self) -> None:
        """Reference `ensurePeersRoutine`: top up outbound connections
        from the book while below target. Event-driven: fresh gossip
        wakes the loop instead of waiting out the poll interval, with a
        minimum spacing between passes as dial-storm backoff."""
        import time as _time

        last_pass = 0.0
        while self._running:
            self._wake.wait(timeout=self.ensure_interval_s)
            self._wake.clear()
            if not self._running:
                return
            spacing = min(self.MIN_PASS_SPACING_S, self.ensure_interval_s)
            since = _time.monotonic() - last_pass
            if since < spacing:
                _time.sleep(spacing - since)
                if not self._running:
                    return
            self.ensure_peers()
            last_pass = _time.monotonic()

    # dial attempts per top-up pass: bounds how long one pass can block
    # on unreachable addresses (each TCP connect can take its full
    # timeout) so stop() and fresh-gossip wakeups aren't starved
    MAX_DIALS_PER_PASS = 10

    # minimum spacing between addr requests to the SAME peer — re-asking
    # lets a pass recover from request/registration races without
    # becoming request spam (reference ensurePeers asks a random peer
    # whenever the book can't cover the deficit)
    REREQUEST_MIN_S = 1.0

    def ensure_peers(self) -> None:
        """One top-up pass: dial distinct book addresses until the peer
        target is met, candidates run out, or the per-pass dial budget
        is spent (the reference dials the whole deficit per tick,
        `pex_reactor.go` ensurePeers)."""
        if self.switch is None:
            return
        have = {p.id for p in self.switch.peers()}
        tried: set[str] = set()
        while self._running and len(have) < self.max_peers:
            if len(tried) >= self.MAX_DIALS_PER_PASS:
                # deficit continues next pass (interval tick or the next
                # genuine wake) — self-waking here would defeat the
                # dial-storm backoff
                return
            addr = self.book.pick_address(exclude=have | tried)
            if addr is None:
                # book can't cover the deficit: re-ask a connected peer
                # for addresses (rate-limited per peer) — the reference's
                # ensurePeers does the same when short of candidates
                peers = self.switch.peers()
                if peers:
                    import random as _random

                    self._request_addrs(_random.choice(peers))
                return
            tried.add(addr.node_id)
            self.book.mark_attempt(addr.node_id)
            try:
                peer = self._dial(addr)
            # tmlint: disable=T001 -- dial failures are normal churn: mark_attempt already recorded it and the book evicts flaky addresses
            except Exception:
                continue
            if peer is None:
                continue  # dial_fn signalled failure: stays unproven
            # promote ONLY if the authenticated identity matches the book
            # entry — otherwise gossip pointed this node_id at someone
            # else's address (eclipse attempt): purge it
            if peer.id != addr.node_id:
                self.book.remove(addr.node_id)
                if self.switch is not None:
                    self.switch.stop_peer_for_error(peer, "pex id mismatch")
                continue
            self.book.mark_good(addr.node_id)
            have.add(addr.node_id)
