"""Batched tx admission: signature windows through the verify spine.

CheckTx was the last signature path still verifying one triple at a
time on the submitting thread. `IngressBatcher` accumulates concurrent
CheckTx arrivals (RPC broadcast handler threads + per-peer gossip recv
threads) into **verify windows** and rides each window through the
PR 5 `VerifyCoalescer` as the fifth consumer (`consumer="mempool"`):
one device launch proves a whole window, the `VerifiedSigCache` makes
gossip re-arrivals of already-proven signatures near-free, and device
faults degrade a window to host verify through the breaker ladder
inside the verifier stack (a raw verifier that raises degrades here).

Admission callbacks fire on window join, in arrival order — the global
FIFO queue preserves per-caller submission order, so the existing
`check_tx(tx, cb)` contract holds: a blocking caller forces a barrier
flush (latency beats batching for whoever is already waiting) and gets
the same Result the synchronous path would return.

Signed-tx envelope (the payload signature CheckTx verifies):

    0xED 0x01 | pubkey(32) | sig(64) | payload        (>= 98 bytes)

`parse_signed_tx` returns None for anything else — plain txs ride the
same windows but skip the signature stage (the app's CheckTx remains
their only gate).

CONSENSUS-RELEVANT: with envelope recognition on (the default), the
`0xED 0x01` prefix is RESERVED — a plain app payload that happens to
start with those two bytes and is >= 98 bytes long is classified as an
envelope, signature-verified, and rejected UNAUTHORIZED instead of
reaching the app. Chains whose apps may emit such payloads must opt
out (`TENDERMINT_TPU_SIGNED_TXS=0`, config `[mempool] signed_txs`, or
`Mempool(signed_txs=False)`), which restores unconditional
pass-through; all nodes of a chain must agree on the setting.

Env knobs (mirroring the TENDERMINT_TPU_COALESCE discipline):
  TENDERMINT_TPU_INGRESS_BATCH=0      legacy synchronous admission
  TENDERMINT_TPU_INGRESS_WINDOW_MS    flush window (default 2 ms)
  TENDERMINT_TPU_INGRESS_MAX_BATCH    txs per window (default 1024)
  TENDERMINT_TPU_MEMPOOL_LANES        pool lanes (mempool.py)
  TENDERMINT_TPU_SIGNED_TXS=0         disable envelope recognition
"""

from __future__ import annotations

import os
import queue as queue_mod
import threading
import time
from collections import deque
from typing import Callable

from tendermint_tpu.abci.types import CodeType, Result
from tendermint_tpu.services.batcher import consumer_kwargs
from tendermint_tpu.telemetry import TRACER
from tendermint_tpu.telemetry import metrics as _metrics
from tendermint_tpu.utils.lockrank import ranked_lock

SIGNED_TX_MAGIC = b"\xed\x01"
_PK_LEN = 32
_SIG_LEN = 64
_HEADER_LEN = len(SIGNED_TX_MAGIC) + _PK_LEN + _SIG_LEN

_STOP = object()


def make_signed_tx(priv_key, payload: bytes) -> bytes:
    """Wrap `payload` in the signed-tx envelope under `priv_key`
    (a `crypto.keys.PrivKey`)."""
    payload = bytes(payload)
    sig = priv_key.sign(payload)
    return SIGNED_TX_MAGIC + priv_key.pub_key.data + sig + payload


def parse_signed_tx(tx: bytes) -> tuple[bytes, bytes, bytes] | None:
    """(pubkey, sig, payload) when `tx` is a signed-tx envelope, else
    None (plain txs skip the signature stage)."""
    if len(tx) < _HEADER_LEN or not tx.startswith(SIGNED_TX_MAGIC):
        return None
    off = len(SIGNED_TX_MAGIC)
    pk = tx[off : off + _PK_LEN]
    sig = tx[off + _PK_LEN : off + _PK_LEN + _SIG_LEN]
    return pk, sig, tx[_HEADER_LEN:]


class _Admission:
    """One queued CheckTx: resolves to a Result at window join."""

    __slots__ = (
        "tx",
        "cb",
        "ctx",
        "t_admit",
        "gen",
        "parsed",
        "event",
        "result",
        "flushed",
        "submitted_at",
    )

    def __init__(self, tx, cb, ctx, t_admit, parsed, gen=None):
        self.tx = tx
        self.cb = cb
        self.ctx = ctx
        self.t_admit = t_admit
        self.gen = gen  # mempool flush generation at submit
        self.parsed = parsed
        self.event = threading.Event()
        self.result: Result | None = None
        self.flushed = False
        self.submitted_at = time.perf_counter()

    def wait(self, timeout: float | None = None) -> Result:
        if not self.event.wait(timeout):
            raise TimeoutError(f"tx admission not resolved in {timeout}s")
        return self.result


class IngressBatcher:
    """Time/size-windowed admission merge in front of a mempool.

    `submit()` queues one tx (dup-cache already consulted by the
    mempool); a flusher thread cuts windows — the whole arrival-order
    FIFO up to the size cap — and launches ONE coalesced signature
    verify for each window's envelope txs; a joiner thread joins the
    verdict, runs the post-signature admission (`Mempool._admit_checked`:
    WAL, app CheckTx, lane insert) for every tx in arrival order, and
    fires callbacks. Flush triggers mirror the coalescer's
    (`tendermint_mempool_ingress_flush_total{reason}`): window age,
    size cap, or a barrier from a blocking caller.
    """

    def __init__(
        self,
        mempool,
        verifier=None,
        window_s: float | None = None,
        max_batch: int | None = None,
        signed_txs: bool = True,
    ) -> None:
        self._mempool = mempool
        self._verifier = verifier
        self._signed_txs = signed_txs
        if window_s is None:
            window_s = (
                float(os.environ.get("TENDERMINT_TPU_INGRESS_WINDOW_MS", "2.0"))
                / 1e3
            )
        self._window_s = max(0.0, window_s)
        if max_batch is None:
            max_batch = int(
                os.environ.get("TENDERMINT_TPU_INGRESS_MAX_BATCH", "1024")
            )
        self._max_batch = max(1, max_batch)
        # Non-reentrant by construction (every `with self._cond:` block
        # is self-contained); ranked BELOW the lane locks — the joiner
        # runs admissions with no window lock held.
        self._cond = threading.Condition(ranked_lock("mempool.ingress"))
        self._queue: "deque[_Admission]" = deque()
        self._barrier = False
        self._running = False
        self._closed = False
        self._flusher: threading.Thread | None = None
        self._joiner: threading.Thread | None = None
        self._join_q: "queue_mod.SimpleQueue" = queue_mod.SimpleQueue()

    # -- lifecycle ---------------------------------------------------------

    def _ensure_threads(self) -> None:
        if self._running:
            return
        with self._cond:
            if self._running or self._closed:
                return
            self._running = True
            self._flusher = threading.Thread(
                target=self._flush_loop, name="mempool-ingress", daemon=True
            )
            self._joiner = threading.Thread(
                target=self._join_loop, name="mempool-ingress-join", daemon=True
            )
            self._flusher.start()
            self._joiner.start()

    def close(self) -> None:
        """Drain the backlog and stop both threads."""
        with self._cond:
            self._closed = True
            running = self._running
            self._running = False
            self._cond.notify_all()
        if self._flusher is not None:
            self._flusher.join(timeout=5)
        if running:
            self._join_q.put(_STOP)
        if self._joiner is not None:
            self._joiner.join(timeout=5)
        # anything still queued resolves as an internal error so no
        # caller blocks forever on a closed pool
        with self._cond:
            leftovers = list(self._queue)
            self._queue.clear()
        for adm in leftovers:
            self._finish(adm, Result(CodeType.INTERNAL_ERROR, log="mempool closed"))
        # a flusher stuck past its join timeout can enqueue a window
        # AFTER the _STOP sentinel; the joiner exits at _STOP without
        # resolving it and _Admission.wait() has no timeout — drain the
        # join queue so no blocked caller hangs on an unresolved batch
        while True:
            try:
                item = self._join_q.get_nowait()
            except queue_mod.Empty:
                break
            if item is _STOP:
                continue
            _handle, batch, _signed = item
            for adm in batch:
                self._finish(
                    adm, Result(CodeType.INTERNAL_ERROR, log="mempool closed")
                )

    # -- submit side -------------------------------------------------------

    def submit(self, tx: bytes, cb, ctx, t_admit, gen=None) -> _Admission:
        parsed = parse_signed_tx(tx) if self._signed_txs else None
        adm = _Admission(tx, cb, ctx, t_admit, parsed, gen)
        self._ensure_threads()
        with self._cond:
            if self._closed:
                pass  # resolved below, outside the lock
            else:
                self._queue.append(adm)
                self._cond.notify_all()
                return adm
        self._finish(adm, Result(CodeType.INTERNAL_ERROR, log="mempool closed"))
        return adm

    def wait(self, adm: _Admission) -> Result:
        """Block until `adm` resolves; an unflushed window flushes NOW
        (a lone synchronous caller never waits out the window)."""
        if not adm.event.is_set() and not adm.flushed:
            with self._cond:
                self._barrier = True
                self._cond.notify_all()
        return adm.wait()

    def stats(self) -> dict:
        """Live window state for the `dump_telemetry?profile=1` queue
        view (the ingress leg of the queue-wait unification)."""
        with self._cond:
            return {
                "window_ms": round(self._window_s * 1e3, 3),
                "max_batch": self._max_batch,
                "pending": len(self._queue),
                "running": self._running,
            }

    # -- flusher -----------------------------------------------------------

    def _flush_reason_locked(self, now: float) -> str | None:
        if not self._queue:
            self._barrier = False
            return None
        if self._barrier:
            return "barrier"
        if len(self._queue) >= self._max_batch:
            return "size"
        if now - self._queue[0].submitted_at >= self._window_s:
            return "window"
        return None

    def _flush_loop(self) -> None:
        while True:
            with self._cond:
                now = time.perf_counter()
                reason = self._flush_reason_locked(now)
                while reason is None and self._running:
                    timeout = None
                    if self._queue:
                        timeout = max(
                            0.0,
                            self._window_s
                            - (now - self._queue[0].submitted_at),
                        )
                    self._cond.wait(timeout)
                    now = time.perf_counter()
                    reason = self._flush_reason_locked(now)
                if reason is None and not self._running:
                    return
                batch: list[_Admission] = []
                while self._queue and len(batch) < self._max_batch:
                    adm = self._queue.popleft()
                    adm.flushed = True
                    batch.append(adm)
                if not self._queue:
                    self._barrier = False
            if batch:
                self._launch(batch, reason)

    def _launch(self, batch: list[_Admission], reason: str) -> None:
        _metrics.MEMPOOL_INGRESS_FLUSH.labels(reason=reason).inc()
        _metrics.MEMPOOL_INGRESS_WINDOW.observe(len(batch))
        signed = [adm for adm in batch if adm.parsed is not None]
        handle = None
        if signed:
            triples = [
                (adm.parsed[0], adm.parsed[2], adm.parsed[1]) for adm in signed
            ]
            verifier = self._verifier
            if verifier is not None:
                from tendermint_tpu.telemetry import tracectx as _trace

                exemplar = next(
                    (adm.ctx for adm in signed if adm.ctx is not None), None
                )
                if exemplar is not None:
                    oldest = min(adm.t_admit for adm in batch)
                    TRACER.add(
                        "mempool.window",
                        oldest,
                        time.time(),
                        trace=exemplar.trace,
                        reason=reason,
                        txs=len(batch),
                        signed=len(signed),
                    )
                try:
                    # the coalescer captures the ambient context at
                    # submit: the window's exemplar rides into the
                    # merged launch's flush/dispatch spans
                    with _trace.use(exemplar):
                        handle = verifier.verify_batch_async(
                            triples, **consumer_kwargs(verifier, "mempool")
                        )
                except Exception:
                    handle = None  # degrade to host verify at the join
        self._join_q.put((handle, batch, signed))

    # -- joiner ------------------------------------------------------------

    def _join_loop(self) -> None:
        while True:
            item = self._join_q.get()
            if item is _STOP:
                return
            handle, batch, signed = item
            verdicts = self._join_verdicts(handle, signed)
            ok_by_id = {
                id(adm): bool(ok) for adm, ok in zip(signed, verdicts)
            }
            for adm in batch:
                sig_ok = ok_by_id.get(id(adm))  # None for plain txs
                try:
                    res = self._mempool._admit_checked(
                        adm.tx, adm.ctx, adm.t_admit, sig_ok=sig_ok,
                        gen=adm.gen,
                    )
                except Exception as e:  # admission must never wedge a caller
                    res = Result(CodeType.INTERNAL_ERROR, log=f"admission: {e}")
                self._finish(adm, res)

    def _join_verdicts(self, handle, signed: list[_Admission]) -> list[bool]:
        """The window's signature verdicts: from the coalesced launch
        when it resolved, else one host pass over the window — the
        per-window degradation rung below the verifier's own breaker
        ladder.

        AUDIT (docs/BYZANTINE.md): a forged signature is a FALSE VERDICT
        in the mask, never an exception — so a garbage-sig flood through
        this path cannot record breaker failures and cannot DoS the
        device fast path into host crypto. Only dispatch-layer faults
        (the `except` below / a raising launch) count against the
        breaker, inside the verifier stack itself. Pinned by
        tests/test_byzantine.py::TestIngressFloodRecovery."""
        if not signed:
            return []
        if handle is not None:
            try:
                mask = handle.result()
                return [bool(v) for v in mask]
            except Exception:
                pass
        from tendermint_tpu.crypto.keys import PubKey

        out = []
        for adm in signed:
            pk, sig, payload = adm.parsed
            try:
                out.append(PubKey(pk).verify(payload, sig))
            except Exception:
                out.append(False)
        return out

    def _finish(self, adm: _Admission, res: Result) -> None:
        adm.result = res
        if adm.cb is not None:
            try:
                adm.cb(res)
            except Exception:
                pass  # a broken callback must not poison the window
        adm.event.set()
