"""Mempool (reference `mempool/`)."""

from tendermint_tpu.mempool.ingress import (
    IngressBatcher,
    make_signed_tx,
    parse_signed_tx,
)
from tendermint_tpu.mempool.mempool import Mempool, TxCache

__all__ = [
    "IngressBatcher",
    "Mempool",
    "TxCache",
    "make_signed_tx",
    "parse_signed_tx",
]
