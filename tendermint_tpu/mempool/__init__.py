"""Mempool (reference `mempool/`)."""

from tendermint_tpu.mempool.mempool import Mempool, TxCache

__all__ = ["Mempool", "TxCache"]
