"""Mempool gossip reactor: stream valid txs to peers (reference
`mempool/reactor.go:20,27,74,114-152`, channel 0x30).

One broadcast thread per peer walks the mempool's append-order list via
the `get_after(index, wait=True)` seam (the reference blocks on
`clist.NextWait`); received txs feed `check_tx` (dup-cache +
app-validated before joining the pool).
"""

from __future__ import annotations

import threading

from tendermint_tpu.codec.binary import Reader, Writer
from tendermint_tpu.p2p.connection import ChannelDescriptor
from tendermint_tpu.p2p.peer import Peer
from tendermint_tpu.p2p.switch import Reactor

MEMPOOL_CHANNEL = 0x30

_MSG_TX = 0x01


def encode_tx_message(tx: bytes) -> bytes:
    return Writer().uvarint(_MSG_TX).bytes(tx).build()


def decode_tx_message(payload: bytes) -> bytes:
    r = Reader(payload)
    if r.uvarint() != _MSG_TX:
        raise ValueError("unknown mempool message")
    return r.bytes()


class MempoolReactor(Reactor):
    PEER_KEY = "mempool_peer_alive"

    def __init__(self, mempool, broadcast: bool = True) -> None:
        super().__init__()
        self.mempool = mempool
        self.broadcast = broadcast
        self._running = False

    def get_channels(self) -> list[ChannelDescriptor]:
        return [ChannelDescriptor(MEMPOOL_CHANNEL, priority=1)]

    def on_start(self) -> None:
        self._running = True

    def on_stop(self) -> None:
        self._running = False

    def add_peer(self, peer: Peer) -> None:
        if not self.broadcast:
            return
        peer.set(self.PEER_KEY, True)
        threading.Thread(
            target=self._broadcast_routine,
            args=(peer,),
            name=f"mempool-bcast-{peer.id}",
            daemon=True,
        ).start()

    def remove_peer(self, peer: Peer, reason) -> None:
        peer.set(self.PEER_KEY, None)

    def receive(self, chan_id: int, peer: Peer, payload: bytes) -> None:
        tx = decode_tx_message(payload)
        # bad txs answer with a code; gossip just drops them (reference
        # `Receive :74-86` ignores CheckTx results from peers). The
        # non-blocking submit keeps the recv thread off the verify
        # window: the tx joins the next ingress batch and this thread
        # goes back to draining frames (the sender's trace context is
        # ambient here and captured at submit).
        #
        # Adversarial-input hook: a FORGED envelope signature from a
        # gossiping peer is an attack on the verify spine (each one buys
        # a device lane) — debit the sender's misbehavior score at the
        # window join so a garbage-sig flooder gets banned while honest
        # traffic keeps flowing. App rejections stay unpenalized.
        cb = None
        if self.switch is not None:
            from tendermint_tpu.abci.types import CodeType

            switch, peer_id, nbytes = self.switch, peer.id, len(payload)

            def cb(res, _switch=switch, _peer_id=peer_id, _nbytes=nbytes):
                if res.code == CodeType.UNAUTHORIZED:
                    _switch.report_misbehavior(_peer_id, "bad_sig", detail="gossiped tx")
                elif res.code == CodeType.TX_IN_CACHE:
                    # gossip observatory: a dup-cache hit on re-arrival
                    # means a peer shipped a tx we already hold — the
                    # redundancy counter makes that wasted wire traffic
                    # visible (local RPC re-submits never reach here)
                    _switch.gossip.redundant("tx", _nbytes)

        submit = getattr(self.mempool, "check_tx_async", None)
        (submit or self.mempool.check_tx)(tx, cb)

    def _broadcast_routine(self, peer: Peer) -> None:
        """Reference `broadcastTxRoutine :114-152`. The cursor is the
        mempool's intake counter (commit-time compaction renumbers list
        positions but never counters). A traced tx's admission context
        rides the frame, so the receiving node's CheckTx joins the same
        trace."""
        cursor = 0
        trace_for = getattr(self.mempool, "trace_for", None)
        while self._running and peer.get(self.PEER_KEY):
            for counter, tx in self.mempool.get_after(
                cursor, wait=True, timeout=0.2
            ):
                ctx = trace_for(tx) if trace_for is not None else None
                peer.send(MEMPOOL_CHANNEL, encode_tx_message(tx), ctx=ctx)
                cursor = max(cursor, counter)
