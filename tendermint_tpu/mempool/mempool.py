"""Mempool: concurrent tx intake, dedup, ABCI validation, reaping.

Reference `mempool/mempool.go`: LRU dup-cache (100k), append-only
mempool WAL, ABCI CheckTx validation, ordered good-tx list consumed by
the proposer (`Reap :303`) and per-peer gossip routines; `Update :334`
removes committed txs and *rechecks* the remainder through the app.
The reference's lock-free clist becomes a version-counted list guarded
by the mempool mutex — gossip readers iterate by index and block on a
Condition for new entries (`TxsFront/NextWait`'s role).
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass
from typing import Callable

from tendermint_tpu.abci.client import AppConnMempool
from tendermint_tpu.abci.types import CodeType, Result
from tendermint_tpu.telemetry import TRACER
from tendermint_tpu.telemetry import metrics as _metrics
from tendermint_tpu.telemetry import tracectx as _trace
from tendermint_tpu.types.tx import Tx, Txs, tx_hash

DEFAULT_CACHE_SIZE = 100_000

# Bounded tx-hash -> (TraceContext, first_seen) table: big enough for
# several full blocks of in-flight traced txs, small enough that an
# abandoned tx can't pin memory. Evictions count as dropped traces.
TRACE_TABLE_SIZE = 4096


class TxCache:
    """Bounded FIFO-evicting dup cache (reference `txCache :414-474`)."""

    def __init__(self, size: int = DEFAULT_CACHE_SIZE) -> None:
        self._size = size
        self._map: OrderedDict[bytes, None] = OrderedDict()
        self._lock = threading.Lock()

    def push(self, tx: bytes) -> bool:
        """False if already present (and does not re-add)."""
        with self._lock:
            if tx in self._map:
                return False
            if len(self._map) >= self._size:
                self._map.popitem(last=False)
            self._map[tx] = None
            return True

    def remove(self, tx: bytes) -> None:
        with self._lock:
            self._map.pop(tx, None)

    def reset(self) -> None:
        with self._lock:
            self._map.clear()


@dataclass
class MempoolTx:
    counter: int
    height: int  # height when added
    tx: bytes


class Mempool:
    """Implements `types.services.MempoolI`."""

    def __init__(
        self,
        app_conn: AppConnMempool,
        height: int = 0,
        cache_size: int = DEFAULT_CACHE_SIZE,
        wal_dir: str | None = None,
        recheck: bool = True,
        node_id: str = "",
    ) -> None:
        self._app = app_conn
        self._txs: list[MempoolTx] = []
        self._lock = threading.RLock()
        self._txs_available = threading.Condition(self._lock)
        self._counter = 0
        self._height = height
        self._cache = TxCache(cache_size)
        self._recheck = recheck
        self._notified_available = False
        self._fire_available: Callable[[], None] | None = None
        # distributed tracing: who minted (span attr `node`) + the
        # tx-hash -> (ctx, first_seen) table the gossip reactor and the
        # commit-time tx.e2e observation read
        self._node_id = node_id
        self._traces: "OrderedDict[bytes, tuple[object, float]]" = OrderedDict()
        self._wal = None
        if wal_dir:
            os.makedirs(wal_dir, exist_ok=True)
            self._wal = open(os.path.join(wal_dir, "wal"), "ab")

    # -- MempoolI ------------------------------------------------------------

    def lock(self) -> None:
        self._lock.acquire()

    def unlock(self) -> None:
        self._lock.release()

    def size(self) -> int:
        with self._lock:
            return len(self._txs)

    def flush(self) -> None:
        """Drop everything (unsafe_flush_mempool RPC)."""
        with self._lock:
            self._txs.clear()
            self._cache.reset()
            self._traces.clear()
            _metrics.MEMPOOL_SIZE.set(0)

    def check_tx(self, tx: Tx, cb: Callable[[Result], None] | None = None) -> Result:
        """Validate through the app; good txs join the pool.

        Returns the CheckTx result (the reference returns err only for
        cache hits / full pool; the result flows via callback).
        """
        tx = bytes(tx)
        if not self._cache.push(tx):
            # Non-zero code so RPC/broadcast callers can distinguish an
            # accepted tx from a silently-dropped duplicate (reference
            # returns ErrTxInCache, mempool.go:172-178).
            _metrics.MEMPOOL_TXS.labels(result="duplicate").inc()
            res = Result(
                code=CodeType.TX_IN_CACHE, log="tx already exists in cache"
            )
            if cb is not None:
                cb(res)
            return res
        # Admission is a trace edge: a gossiped tx arrives with the
        # sender's context ambient on this thread (set by the p2p recv
        # loop); a locally-submitted one (RPC broadcast) mints here —
        # head-based sampling, so most txs pay one thread-local read.
        t_admit = time.time()
        ctx = _trace.current()
        if ctx is None:
            ctx = _trace.mint(self._node_id)
        if self._wal is not None:
            # length-framed (txs are arbitrary bytes); buffered+flushed but
            # NOT fsync'd per tx — the mempool WAL is best-effort, unlike
            # the consensus WAL (matches the reference's autofile writer)
            from tendermint_tpu.codec.binary import encode_bytes

            self._wal.write(encode_bytes(tx))
            self._wal.flush()
        res = self._app.check_tx_async(tx)
        if res.is_ok:
            with self._lock:
                self._counter += 1
                self._txs.append(MempoolTx(self._counter, self._height, tx))
                _metrics.MEMPOOL_SIZE.set(len(self._txs))
                self._notify_txs_available()
                self._txs_available.notify_all()
                if ctx is not None:
                    self._traces[tx_hash(tx)] = (ctx, t_admit)
                    while len(self._traces) > TRACE_TABLE_SIZE:
                        self._traces.popitem(last=False)
                        _metrics.TRACE_DROPPED.inc()
            _metrics.MEMPOOL_TXS.labels(result="ok").inc()
        else:
            # bad tx: evict from cache so a corrected app state can re-admit
            self._cache.remove(tx)
            _metrics.MEMPOOL_TXS.labels(result="rejected").inc()
        if ctx is not None:
            TRACER.add(
                "mempool.admission",
                t_admit,
                time.time(),
                trace=ctx.trace,
                node=self._node_id,
                tx=tx_hash(tx).hex()[:16],
                result="ok" if res.is_ok else "rejected",
            )
        if cb is not None:
            cb(res)
        return res

    # -- distributed tracing -------------------------------------------------

    def trace_for(self, tx: bytes):
        """The TraceContext admitted with `tx` (None when unsampled or
        unknown) — the gossip reactor re-attaches it on the wire and
        the proposer adopts it as the block's context."""
        with self._lock:
            entry = self._traces.get(tx_hash(bytes(tx)))
        return entry[0] if entry is not None else None

    def take_trace(self, tx: bytes):
        """Pop `tx`'s (ctx, first_seen) entry — consumed exactly once,
        at commit, for the `tendermint_tx_e2e_seconds` observation and
        the tx.e2e span."""
        with self._lock:
            return self._traces.pop(tx_hash(bytes(tx)), None)

    def reap(self, max_txs: int) -> Txs:
        """Up to max_txs txs for a proposal (-1 = all), pool unchanged
        (reference `Reap :303`)."""
        with self._lock:
            txs = self._txs if max_txs < 0 else self._txs[:max_txs]
            return Txs([Tx(m.tx) for m in txs])

    def update(self, height: int, txs: Txs) -> None:
        """Remove committed txs; recheck survivors against the new app
        state (reference `Update :334-360`). Caller holds the mempool
        lock (apply_block's CommitStateUpdateMempool)."""
        committed = {bytes(t) for t in txs}
        with self._lock:
            self._height = height
            self._notified_available = False
            keep = [m for m in self._txs if m.tx not in committed]
            if self._recheck and keep:
                still_good = []
                for m in keep:
                    if self._app.check_tx_async(m.tx).is_ok:
                        still_good.append(m)
                    else:
                        self._cache.remove(m.tx)
                keep = still_good
            self._txs = keep
            _metrics.MEMPOOL_SIZE.set(len(keep))
            if keep:
                self._notify_txs_available()

    # -- gossip / proposer wakeups -------------------------------------------

    def tx_available(self) -> bool:
        with self._lock:
            return len(self._txs) > 0

    def enable_txs_available(self) -> None:
        """Install no-empty-blocks gating (reference `:101-106`).
        Consensus sets `on_txs_available` to get woken."""
        self._fire_available = self._fire_available or (lambda: None)

    def set_on_txs_available(self, fn: Callable[[], None]) -> None:
        self._fire_available = fn

    def _notify_txs_available(self) -> None:
        """Fire once per height when the pool becomes non-empty
        (reference `notifyTxsAvailable :284-299`)."""
        if self._fire_available is not None and not self._notified_available:
            self._notified_available = True
            self._fire_available()

    def get_after(
        self, counter: int, wait: bool = False, timeout: float | None = None
    ) -> list[tuple[int, bytes]]:
        """(counter, tx) pairs with counter > `counter` — the gossip
        iteration seam (role of clist's TxsFront/NextWait). Cursors are
        the monotonically-increasing intake counter, NOT list positions:
        update() compacts the list after every commit, so a positional
        cursor would skip or stall. With wait=True blocks until a newer
        tx exists or timeout."""
        with self._lock:
            out = [(m.counter, m.tx) for m in self._txs if m.counter > counter]
            if wait and not out:
                self._txs_available.wait(timeout)
                out = [(m.counter, m.tx) for m in self._txs if m.counter > counter]
            return out

    def close(self) -> None:
        if self._wal is not None:
            self._wal.close()

    def replay_wal(self) -> int:
        """Restart recovery: re-validate WAL txs through the app, then
        atomically COMPACT the file to the txs that actually rejoined
        the pool. The old WAL stays intact on disk until the rewrite
        (a crash mid-replay loses nothing), and app-rejected txs are
        excluded (check_tx's own WAL append is suppressed during
        replay). Returns the number of txs that rejoined."""
        if self._wal is None:
            return 0
        from tendermint_tpu.codec.binary import encode_bytes

        txs = self.load_wal()
        path = self._wal.name
        wal, self._wal = self._wal, None  # suppress appends during replay
        before = self.size()
        try:
            for tx in txs:
                self.check_tx(tx)
        finally:
            self._wal = wal
        # atomic rewrite with only the survivors
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            with self._lock:
                for m in self._txs:
                    f.write(encode_bytes(m.tx))
            f.flush()
            os.fsync(f.fileno())
        self._wal.close()
        os.replace(tmp, path)
        self._wal = open(path, "ab")
        return self.size() - before

    def load_wal(self) -> list[bytes]:
        """Replay the mempool WAL (txs seen before a crash); stops at a
        truncated tail."""
        if self._wal is None:
            return []
        from tendermint_tpu.codec.binary import decode_bytes

        with open(self._wal.name, "rb") as f:
            data = f.read()
        out, off = [], 0
        while off < len(data):
            try:
                tx, off = decode_bytes(data, off)
            except ValueError:
                break
            out.append(tx)
        return out
