"""Mempool: concurrent tx intake, dedup, ABCI validation, reaping.

Reference `mempool/mempool.go`: LRU dup-cache (100k), append-only
mempool WAL, ABCI CheckTx validation, ordered good-tx list consumed by
the proposer (`Reap :303`) and per-peer gossip routines; `Update :334`
removes committed txs and *rechecks* the remainder through the app.

Traffic-scale ingress (ROADMAP open item 2): the single version-counted
list under one RLock became N tx-hash-partitioned **lanes** — per-lane
lock + dup-cache segment + tx list — so concurrent CheckTx admissions
(RPC broadcast threads + per-peer gossip recv threads), per-peer gossip
cursors (`get_after`), `reap`, and `update` stop serializing on one
lock. A process-global monotonically-increasing intake counter is
assigned at insert; `reap` merges lanes in counter order so proposals
stay deterministic regardless of lane layout, and gossip cursors stay
counters, never list positions.

Signed-tx admission batches through `mempool/ingress.py`: signature
windows ride the PR 5 `VerifyCoalescer` as the fifth consumer
(`consumer="mempool"`), the `VerifiedSigCache` makes gossip re-arrivals
near-free, and the breaker ladder degrades a window to host verify.
`TENDERMINT_TPU_INGRESS_BATCH=0` (or `lanes=1` + `ingress_batch=False`)
keeps today's synchronous one-at-a-time semantics.
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass
from typing import Callable

from tendermint_tpu.abci.client import AppConnMempool
from tendermint_tpu.abci.types import CodeType, Result
from tendermint_tpu.telemetry import TRACER
from tendermint_tpu.telemetry import metrics as _metrics
from tendermint_tpu.telemetry import tracectx as _trace
from tendermint_tpu.types.tx import Tx, Txs, tx_hash
from tendermint_tpu.utils.lockrank import ranked_lock, ranked_rlock

DEFAULT_CACHE_SIZE = 100_000

# Lane count: env wins (ops knob), then the constructor arg, then this.
# Lanes partition by tx hash, so dup detection and gossip re-arrivals
# always land on the lane that saw the tx first.
LANES_ENV = "TENDERMINT_TPU_MEMPOOL_LANES"
DEFAULT_LANES = 4

# Bounded tx-hash -> (TraceContext, first_seen) table: big enough for
# several full blocks of in-flight traced txs, small enough that an
# abandoned tx can't pin memory. Evictions count as dropped traces.
TRACE_TABLE_SIZE = 4096


class TxCache:
    """Bounded FIFO-evicting dup cache (reference `txCache :414-474`)."""

    def __init__(self, size: int = DEFAULT_CACHE_SIZE) -> None:
        self._size = size
        self._map: OrderedDict[bytes, None] = OrderedDict()
        self._lock = ranked_lock("mempool.txcache")

    def push(self, tx: bytes) -> bool:
        """False if already present (and does not re-add)."""
        with self._lock:
            if tx in self._map:
                return False
            if len(self._map) >= self._size:
                self._map.popitem(last=False)
            self._map[tx] = None
            return True

    def remove(self, tx: bytes) -> None:
        with self._lock:
            self._map.pop(tx, None)

    def reset(self) -> None:
        with self._lock:
            self._map.clear()


@dataclass
class MempoolTx:
    counter: int
    height: int  # height when added
    tx: bytes


class _Lane:
    """One tx-hash partition: its own lock, ordered tx list, and
    dup-cache segment. Counters inside a lane are strictly increasing
    (assignment and append happen under the lane lock), so cross-lane
    merges by counter reconstruct global admission order."""

    __slots__ = ("lock", "txs", "cache")

    def __init__(self, cache_size: int, seq: int = 0) -> None:
        # seq = lane index: the lockrank sanitizer allows same-rank lane
        # acquisitions only in ascending index order (what lock() does)
        self.lock = ranked_rlock("mempool.lane", seq=seq)
        self.txs: list[MempoolTx] = []
        self.cache = TxCache(cache_size)


def _resolve_lanes(lanes: int | None) -> int:
    env = os.environ.get(LANES_ENV)
    if env is not None:
        try:
            return max(1, int(env))
        except ValueError:
            pass
    if lanes is not None:
        return max(1, int(lanes))
    return DEFAULT_LANES


def _resolve_ingress(ingress_batch: bool | None) -> bool:
    env = os.environ.get("TENDERMINT_TPU_INGRESS_BATCH")
    if env is not None:
        return env != "0"
    if ingress_batch is not None:
        return bool(ingress_batch)
    return True


def _resolve_signed(signed_txs: bool | None) -> bool:
    env = os.environ.get("TENDERMINT_TPU_SIGNED_TXS")
    if env is not None:
        return env != "0"
    if signed_txs is not None:
        return bool(signed_txs)
    return True


class Mempool:
    """Implements `types.services.MempoolI`."""

    def __init__(
        self,
        app_conn: AppConnMempool,
        height: int = 0,
        cache_size: int = DEFAULT_CACHE_SIZE,
        wal_dir: str | None = None,
        recheck: bool = True,
        node_id: str = "",
        lanes: int | None = None,
        verifier=None,
        ingress_batch: bool | None = None,
        ingress_window_s: float | None = None,
        ingress_max_batch: int | None = None,
        signed_txs: bool | None = None,
    ) -> None:
        self._app = app_conn
        n_lanes = _resolve_lanes(lanes)
        per_lane_cache = max(1, cache_size // n_lanes)
        self._lanes = [_Lane(per_lane_cache, seq=i) for i in range(n_lanes)]
        self._counter = 0
        self._counter_lock = ranked_lock("mempool.counter")
        self._height = height
        self._recheck = recheck
        # Bumped by flush() while every lane lock is held; admissions
        # capture it at submit and re-check it under the lane lock at
        # insert, so a tx queued in an ingress window when the operator
        # flushes cannot re-enter the pool afterwards.
        self._flush_gen = 0
        # Lock ordering discipline (deadlock-free by construction):
        #   _avail -> lane locks                    (get_after's wait+rescan)
        #   lane locks -> _wal_lock -> _counter_lock (admission insert)
        # Nothing acquires _avail while holding a lane lock: admissions
        # insert under the lane lock, RELEASE it, then notify. The
        # once-per-height "txs available" latch has its own tiny lock so
        # update() (holding every lane lock via lock()) never touches
        # _avail either. This order is NORMATIVE: utils/lockrank.py's
        # RANKS table encodes it, tmlint L001 checks it statically, and
        # the ranked locks below enforce it at test time.
        self._avail = threading.Condition(ranked_lock("mempool.avail"))
        self._notif_lock = ranked_lock("mempool.notif")
        self._notified_available = False
        self._fire_available: Callable[[], None] | None = None
        # distributed tracing: who minted (span attr `node`) + the
        # tx-hash -> (ctx, first_seen) table the gossip reactor and the
        # commit-time tx.e2e observation read
        self._node_id = node_id
        self._traces: "OrderedDict[bytes, tuple[object, float]]" = OrderedDict()
        self._trace_lock = ranked_lock("mempool.trace")
        self._wal = None
        # Appends are length-framed; concurrent RPC + gossip admissions
        # used to interleave partial writes and corrupt the framing
        # load_wal replays. One dedicated lock serializes appends AND is
        # the ordering point for counter assignment: counter and WAL
        # record are produced under one _wal_lock hold, so WAL order ==
        # counter (admission) order, which replay for nonce-style serial
        # apps relies on.
        self._wal_lock = ranked_lock("mempool.wal")
        if wal_dir:
            os.makedirs(wal_dir, exist_ok=True)
            self._wal = open(os.path.join(wal_dir, "wal"), "ab")
        # batched ingress: signature windows through the verify spine.
        # _signed_txs gates envelope recognition: the 0xED 0x01 prefix is
        # RESERVED when on (a colliding app payload is treated as an
        # envelope and must carry a valid signature); chains whose apps
        # emit arbitrary payloads opt out and every tx passes through.
        self._verifier = verifier
        self._signed_txs = _resolve_signed(signed_txs)
        self._ingress = None
        if _resolve_ingress(ingress_batch):
            from tendermint_tpu.mempool.ingress import IngressBatcher

            self._ingress = IngressBatcher(
                self,
                verifier=verifier,
                window_s=ingress_window_s,
                max_batch=ingress_max_batch,
                signed_txs=self._signed_txs,
            )

    # -- lanes ---------------------------------------------------------------

    @property
    def n_lanes(self) -> int:
        return len(self._lanes)

    def _lane_for(self, tx: bytes) -> _Lane:
        if len(self._lanes) == 1:
            return self._lanes[0]
        return self._lanes[int.from_bytes(tx_hash(tx)[:4], "big") % len(self._lanes)]

    # -- MempoolI ------------------------------------------------------------

    def lock(self) -> None:
        """Freeze the whole pool (consensus holds this across update()).
        Acquires every lane lock in index order."""
        for lane in self._lanes:
            lane.lock.acquire()

    def unlock(self) -> None:
        for lane in reversed(self._lanes):
            lane.lock.release()

    def size(self) -> int:
        total = 0
        for lane in self._lanes:
            with lane.lock:
                total += len(lane.txs)
        return total

    def flush(self) -> None:
        """Drop everything (unsafe_flush_mempool RPC) — including txs
        still queued in ingress windows: the generation bump invalidates
        every in-flight admission, checked under the lane lock at insert
        time, so nothing queued before the flush re-enters after it."""
        self.lock()
        try:
            self._flush_gen += 1
            for lane in self._lanes:
                lane.txs.clear()
                lane.cache.reset()
        finally:
            self.unlock()
        with self._trace_lock:
            self._traces.clear()
        _metrics.MEMPOOL_SIZE.set(0)

    def check_tx(self, tx: Tx, cb: Callable[[Result], None] | None = None) -> Result:
        """Validate through the app; good txs join the pool.

        With batched ingress on, the tx rides the next verify window
        (concurrent callers share device launches) and this call blocks
        until the window joins — same Result contract, and a lone caller
        forces a barrier flush so it never waits out the window.
        """
        tx = bytes(tx)
        dup = self._dup_or_submit_ctx(tx, cb)
        if isinstance(dup, Result):
            return dup
        ctx, t_admit, gen = dup
        if self._ingress is not None:
            adm = self._ingress.submit(tx, cb, ctx, t_admit, gen)
            return self._ingress.wait(adm)
        return self._check_tx_sync(tx, cb, ctx, t_admit, gen)

    def check_tx_async(self, tx: Tx, cb: Callable[[Result], None] | None = None):
        """Non-blocking admission: queue the tx for the next verify
        window and return immediately (the Result flows via `cb`, the
        reference's CheckTx-callback shape). Gossip recv threads and the
        open-loop load generator use this so intake threads never stall
        on a window join. Falls back to the synchronous path when
        batching is off. Returns an awaitable admission (event/result)
        or the final Result when resolved synchronously."""
        tx = bytes(tx)
        dup = self._dup_or_submit_ctx(tx, cb)
        if isinstance(dup, Result):
            return dup
        ctx, t_admit, gen = dup
        if self._ingress is not None:
            return self._ingress.submit(tx, cb, ctx, t_admit, gen)
        return self._check_tx_sync(tx, cb, ctx, t_admit, gen)

    def _dup_or_submit_ctx(self, tx: bytes, cb):
        """Shared synchronous admission prologue: lane dup-cache push
        (so an immediate re-offer is rejected before any window) and
        trace-context capture on the CALLING thread (the p2p recv loop
        installs the sender's context ambient; batcher threads have
        none). Returns a Result for duplicates, else
        (ctx, t_admit, flush_gen)."""
        if not self._lane_for(tx).cache.push(tx):
            # Non-zero code so RPC/broadcast callers can distinguish an
            # accepted tx from a silently-dropped duplicate (reference
            # returns ErrTxInCache, mempool.go:172-178).
            _metrics.MEMPOOL_TXS.labels(result="duplicate").inc()
            res = Result(
                code=CodeType.TX_IN_CACHE, log="tx already exists in cache"
            )
            if cb is not None:
                cb(res)
            return res
        # Admission is a trace edge: a gossiped tx arrives with the
        # sender's context ambient on this thread (set by the p2p recv
        # loop); a locally-submitted one (RPC broadcast) mints here —
        # head-based sampling, so most txs pay one thread-local read.
        t_admit = time.time()
        ctx = _trace.current()
        if ctx is None:
            ctx = _trace.mint(self._node_id)
        return ctx, t_admit, self._flush_gen

    def _check_tx_sync(self, tx: bytes, cb, ctx, t_admit, gen=None) -> Result:
        """The legacy one-at-a-time admission path (ingress batching
        off): signed envelopes verify inline — one signature, one
        verify call — exactly the host-side shape the batched pipeline
        exists to replace."""
        from tendermint_tpu.mempool.ingress import parse_signed_tx

        parsed = parse_signed_tx(tx) if self._signed_txs else None
        sig_ok = None
        if parsed is not None:
            sig_ok = self._verify_sig_inline(parsed)
        res = self._admit_checked(tx, ctx, t_admit, sig_ok=sig_ok, gen=gen)
        if cb is not None:
            cb(res)
        return res

    def _verify_sig_inline(self, parsed) -> bool:
        """One-at-a-time signature check for the synchronous path:
        through the configured verifier stack when present (dedup cache
        + breaker ladder still apply), else the host library."""
        pk, sig, payload = parsed
        if self._verifier is not None:
            try:
                return bool(self._verifier.verify_batch([(pk, payload, sig)])[0])
            except Exception:
                pass  # degrade to host below; the ladder already counted it
        from tendermint_tpu.crypto.keys import PubKey

        try:
            return PubKey(pk).verify(payload, sig)
        except Exception:
            return False

    def _admit_checked(self, tx: bytes, ctx, t_admit, sig_ok=None, gen=None) -> Result:
        """Post-signature admission: app CheckTx, then counter + WAL +
        lane insert as one atomic step, telemetry. `sig_ok` is the
        envelope verdict (None for unsigned txs); a failed signature
        never reaches the app or the WAL and is evicted from the dup
        cache so a corrected re-offer re-verifies. `gen` is the flush
        generation captured at submit: a mismatch under the lane lock
        means the operator flushed while this tx sat in an ingress
        window, so it must not re-enter the pool (or the WAL).

        Counter assignment, WAL append, and lane append all happen under
        the lane lock (with _wal_lock as the cross-lane ordering point),
        which two invariants depend on: WAL record order == counter
        order (nonce-style serial apps replay in admission order), and
        _collect_after's counter-snapshot bound (a counter <= the
        snapshot is guaranteed visible once the lane lock is acquired).
        The WAL is best-effort (flushed, never fsync'd per tx, and only
        admitted txs are logged — an app-rejected tx would be dropped at
        replay's re-validation anyway)."""
        lane = self._lane_for(tx)
        if sig_ok is False:
            lane.cache.remove(tx)
            _metrics.MEMPOOL_TXS.labels(result="bad_sig").inc()
            res = Result(
                code=CodeType.UNAUTHORIZED, log="invalid tx signature"
            )
            self._finish_admission(tx, ctx, t_admit, res)
            return res
        res = self._app.check_tx_async(tx)
        if res.is_ok:
            from tendermint_tpu.codec.binary import encode_bytes

            with lane.lock:
                stale = gen is not None and gen != self._flush_gen
                if not stale:
                    with self._wal_lock:
                        with self._counter_lock:
                            self._counter += 1
                            counter = self._counter
                        if self._wal is not None:
                            self._wal.write(encode_bytes(tx))
                            self._wal.flush()
                    lane.txs.append(MempoolTx(counter, self._height, tx))
            if stale:
                # flushed while queued: the pool (and dup caches) were
                # wiped after this tx was accepted into a window — don't
                # resurrect it
                _metrics.MEMPOOL_TXS.labels(result="flushed").inc()
                res = Result(
                    code=CodeType.INTERNAL_ERROR,
                    log="mempool flushed during admission",
                )
                self._finish_admission(tx, ctx, t_admit, res, label="flushed")
                return res
            if ctx is not None:
                with self._trace_lock:
                    self._traces[tx_hash(tx)] = (ctx, t_admit)
                    while len(self._traces) > TRACE_TABLE_SIZE:
                        self._traces.popitem(last=False)
                        _metrics.TRACE_DROPPED.inc()
            _metrics.MEMPOOL_TXS.labels(result="ok").inc()
            _metrics.MEMPOOL_SIZE.set(self.size())
            self._notify_txs_available()
            with self._avail:
                self._avail.notify_all()
        else:
            # bad tx: evict from cache so a corrected app state can re-admit
            lane.cache.remove(tx)
            _metrics.MEMPOOL_TXS.labels(result="rejected").inc()
        self._finish_admission(tx, ctx, t_admit, res)
        return res

    def _finish_admission(
        self, tx: bytes, ctx, t_admit, res: Result, label: str | None = None
    ) -> None:
        """Admission telemetry shared by every outcome: the p99-tracked
        latency histogram (exemplar-linked to the trace id) and the
        admission span."""
        now = time.time()
        _metrics.MEMPOOL_ADMISSION_SECONDS.observe(
            now - t_admit,
            exemplar=ctx.trace if ctx is not None else None,
        )
        if ctx is not None:
            if label is not None:
                result = label
            elif res.is_ok:
                result = "ok"
            elif res.code == CodeType.UNAUTHORIZED:
                result = "bad_sig"
            else:
                result = "rejected"
            TRACER.add(
                "mempool.admission",
                t_admit,
                now,
                trace=ctx.trace,
                node=self._node_id,
                tx=tx_hash(tx).hex()[:16],
                result=result,
            )

    # -- distributed tracing -------------------------------------------------

    def trace_for(self, tx: bytes):
        """The TraceContext admitted with `tx` (None when unsampled or
        unknown) — the gossip reactor re-attaches it on the wire and
        the proposer adopts it as the block's context."""
        with self._trace_lock:
            entry = self._traces.get(tx_hash(bytes(tx)))
        return entry[0] if entry is not None else None

    def take_trace(self, tx: bytes):
        """Pop `tx`'s (ctx, first_seen) entry — consumed exactly once,
        at commit, for the `tendermint_tx_e2e_seconds` observation and
        the tx.e2e span."""
        with self._trace_lock:
            return self._traces.pop(tx_hash(bytes(tx)), None)

    def reap(self, max_txs: int) -> Txs:
        """Up to max_txs txs for a proposal (-1 = all), pool unchanged
        (reference `Reap :303`). Lanes merge in global-counter order, so
        the proposal ordering is identical to the single-list pool's —
        deterministic regardless of lane count."""
        merged: list[MempoolTx] = []
        self.lock()
        try:
            for lane in self._lanes:
                merged.extend(lane.txs)
        finally:
            self.unlock()
        merged.sort(key=lambda m: m.counter)
        if max_txs >= 0:
            merged = merged[:max_txs]
        return Txs([Tx(m.tx) for m in merged])

    def update(self, height: int, txs: Txs) -> None:
        """Remove committed txs; recheck survivors against the new app
        state (reference `Update :334-360`). Caller holds the mempool
        lock (apply_block's CommitStateUpdateMempool). Survivors are
        rechecked as ONE admission-ordered batch across all lanes —
        not one-by-one interleaved with per-lane list surgery — so the
        pool-frozen window stays as short as the app allows."""
        committed = {bytes(t) for t in txs}
        self.lock()
        try:
            self._height = height
            for lane in self._lanes:
                if committed:
                    lane.txs = [m for m in lane.txs if m.tx not in committed]
            if self._recheck:
                survivors: list[MempoolTx] = []
                for lane in self._lanes:
                    survivors.extend(lane.txs)
                if survivors:
                    # admission order: serial apps (nonce-style) must see
                    # survivors in the same order a single list kept them
                    survivors.sort(key=lambda m: m.counter)
                    dropped = self._recheck_batch(survivors)
                    if dropped:
                        for lane in self._lanes:
                            lane.txs = [
                                m for m in lane.txs if m.counter not in dropped
                            ]
            remaining = sum(len(lane.txs) for lane in self._lanes)
            # reset the once-per-height latch while the pool is still
            # frozen: an admission landing right after unlock must see
            # the fresh latch or its wakeup is lost for the height
            with self._notif_lock:
                self._notified_available = False
        finally:
            self.unlock()
        _metrics.MEMPOOL_SIZE.set(remaining)
        if remaining:
            self._notify_txs_available()

    def _recheck_batch(self, survivors: list[MempoolTx]) -> set[int]:
        """One pass over every lane's survivors through the app; returns
        the counters of txs that went stale (also evicted from their
        lane's dup cache so they can be re-offered later)."""
        dropped: set[int] = set()
        for m in survivors:
            if not self._app.check_tx_async(m.tx).is_ok:
                dropped.add(m.counter)
                self._lane_for(m.tx).cache.remove(m.tx)
        return dropped

    # -- gossip / proposer wakeups -------------------------------------------

    def tx_available(self) -> bool:
        return self.size() > 0

    def enable_txs_available(self) -> None:
        """Install no-empty-blocks gating (reference `:101-106`).
        Consensus sets `on_txs_available` to get woken."""
        self._fire_available = self._fire_available or (lambda: None)

    def set_on_txs_available(self, fn: Callable[[], None]) -> None:
        self._fire_available = fn

    def _notify_txs_available(self) -> None:
        """Fire once per height when the pool becomes non-empty
        (reference `notifyTxsAvailable :284-299`)."""
        with self._notif_lock:
            if self._fire_available is None or self._notified_available:
                return
            self._notified_available = True
            fire = self._fire_available
        fire()

    def _collect_after(self, counter: int) -> list[tuple[int, bytes]]:
        """Lane-by-lane scan with a GAP-FREE guarantee for cursor-style
        callers (the gossip reactor advances its cursor to the max
        returned counter, so a skipped counter would never be gossiped).

        Lanes are scanned one lock at a time, so without a bound a tx
        admitted into an already-scanned lane could be masked by a
        higher-counter tx admitted into a lane scanned later — the
        cursor would jump past it forever. The counter snapshot taken
        BEFORE the walk closes that race: counters <= `hi` were
        assigned inside a lane-lock critical section that also appends
        the tx (see _admit_checked), and that section had already begun
        when we read `hi` — so acquiring the lane lock afterwards
        observes the append. Counters > `hi` are withheld until the
        next scan, when a fresh snapshot covers them."""
        with self._counter_lock:
            hi = self._counter
        out: list[tuple[int, bytes]] = []
        for lane in self._lanes:
            with lane.lock:
                out.extend(
                    (m.counter, m.tx)
                    for m in lane.txs
                    if counter < m.counter <= hi
                )
        out.sort(key=lambda p: p[0])
        return out

    def get_after(
        self, counter: int, wait: bool = False, timeout: float | None = None
    ) -> list[tuple[int, bytes]]:
        """(counter, tx) pairs with counter > `counter`, merged across
        lanes in counter order — the gossip iteration seam (role of
        clist's TxsFront/NextWait). Cursors are the monotonically-
        increasing intake counter, NOT list positions: update() compacts
        lanes after every commit, so a positional cursor would skip or
        stall. With wait=True, LOOPS until a newer tx exists or the
        deadline passes — a spurious Condition wakeup (or a notify for
        an admission the cursor already covers) re-waits instead of
        returning empty."""
        out = self._collect_after(counter)
        if out or not wait:
            return out
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._avail:
            while True:
                # re-scan INSIDE the condition so an admission between
                # the outer scan and the wait can't be missed
                out = self._collect_after(counter)
                if out:
                    return out
                if deadline is None:
                    self._avail.wait()
                else:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0 or not self._avail.wait(remaining):
                        return self._collect_after(counter)

    def close(self) -> None:
        if self._ingress is not None:
            self._ingress.close()
        with self._wal_lock:
            if self._wal is not None:
                self._wal.close()

    def replay_wal(self) -> int:
        """Restart recovery: re-validate WAL txs through the app, then
        atomically COMPACT the file to the txs that actually rejoined
        the pool. The old WAL stays intact on disk until the rewrite
        (a crash mid-replay loses nothing), and app-rejected txs are
        excluded (check_tx's own WAL append is suppressed during
        replay). Returns the number of txs that rejoined."""
        if self._wal is None:
            return 0
        from tendermint_tpu.codec.binary import encode_bytes

        txs = self.load_wal()
        path = self._wal.name
        with self._wal_lock:
            wal, self._wal = self._wal, None  # suppress appends during replay
        before = self.size()
        try:
            for tx in txs:
                self.check_tx(tx)
        finally:
            with self._wal_lock:
                self._wal = wal
        # atomic rewrite with only the survivors, in admission order
        survivors = self.reap(-1)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            for tx in survivors:
                f.write(encode_bytes(bytes(tx)))
            f.flush()
            os.fsync(f.fileno())
        with self._wal_lock:
            self._wal.close()
            os.replace(tmp, path)
            self._wal = open(path, "ab")
        return self.size() - before

    def load_wal(self) -> list[bytes]:
        """Replay the mempool WAL (txs seen before a crash); stops at a
        truncated tail."""
        if self._wal is None:
            return []
        from tendermint_tpu.codec.binary import decode_bytes

        with open(self._wal.name, "rb") as f:
            data = f.read()
        out, off = [], 0
        while off < len(data):
            try:
                tx, off = decode_bytes(data, off)
            except ValueError:
                break
            out.append(tx)
        return out
