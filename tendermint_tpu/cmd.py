"""CLI: init / node / testnet / show_validator / gen_validator /
replay / replay_console / reset_all / reset_priv_validator /
probe_upnp / wal2json / cut_wal_until / version
(reference `cmd/tendermint/main.go:14-37` + `commands/`).

Run as `python -m tendermint_tpu <command> [--home DIR] ...`.
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import time


def _cmd_init(args) -> int:
    """Create config.toml, genesis.json, priv_validator.json (reference
    `commands/init.go`)."""
    from tendermint_tpu.config import Config, write_config
    from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator
    from tendermint_tpu.types.priv_validator import PrivValidatorFS

    cfg = Config.default(args.home)
    os.makedirs(args.home, exist_ok=True)
    write_config(cfg)
    pv = PrivValidatorFS.load_or_gen(cfg.priv_validator_path())
    if not os.path.exists(cfg.genesis_path()):
        gen = GenesisDoc(
            chain_id=args.chain_id or f"test-chain-{os.urandom(3).hex()}",
            genesis_time=time.time_ns(),
            validators=[GenesisValidator(pub_key=pv.pub_key, power=10)],
        )
        gen.save_as(cfg.genesis_path())
    print(f"initialized node home at {args.home}")
    return 0


def _cmd_show_validator(args) -> int:
    from tendermint_tpu.config import load_config
    from tendermint_tpu.types.priv_validator import PrivValidatorFS

    cfg = load_config(args.home)
    pv = PrivValidatorFS.load(cfg.priv_validator_path())
    print(pv.pub_key.data.hex())
    return 0


def _cmd_node(args) -> int:
    """Run a node until interrupted (reference `commands/run_node.go`)."""
    from tendermint_tpu.config import load_config
    from tendermint_tpu.node import Node
    from tendermint_tpu.utils.jax_cache import enable_persistent_cache

    # kernels (table builds, verify, merkle) compile once per MACHINE:
    # restarts deserialize from the on-disk executable cache
    enable_persistent_cache()

    cfg = load_config(args.home)
    if args.p2p_laddr:
        cfg.p2p.laddr = args.p2p_laddr
    if args.rpc_laddr:
        cfg.rpc.laddr = args.rpc_laddr
    if args.seeds:
        cfg.p2p.seeds = args.seeds
    if args.fast_sync is not None:
        cfg.base.fast_sync = args.fast_sync

    node = Node(cfg)
    node.start()
    print(
        f"node {node.node_id[:12]} up: p2p :{node.p2p_port} rpc :{node.rpc_port}",
        flush=True,
    )
    stop = []
    signal.signal(signal.SIGTERM, lambda *a: stop.append(1))
    signal.signal(signal.SIGINT, lambda *a: stop.append(1))
    try:
        while not stop:
            time.sleep(0.2)
    finally:
        node.stop()
    return 0


def _cmd_testnet(args) -> int:
    """Generate N validator node homes wired to each other (reference
    `commands/testnet.go:29-50`)."""
    from tendermint_tpu.config import Config, write_config
    from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator
    from tendermint_tpu.types.priv_validator import PrivValidatorFS

    n = args.n
    base_p2p = args.starting_port
    homes = [os.path.join(args.output, f"node{i}") for i in range(n)]
    pvs = []
    for home in homes:
        os.makedirs(home, exist_ok=True)
        pvs.append(
            PrivValidatorFS.load_or_gen(os.path.join(home, "priv_validator.json"))
        )
    gen = GenesisDoc(
        chain_id=args.chain_id or f"testnet-{os.urandom(3).hex()}",
        genesis_time=time.time_ns(),
        validators=[GenesisValidator(pub_key=pv.pub_key, power=10) for pv in pvs],
    )
    for i, home in enumerate(homes):
        cfg = Config.default(home)
        cfg.base.moniker = f"node{i}"
        cfg.p2p.laddr = f"tcp://127.0.0.1:{base_p2p + 2 * i}"
        cfg.rpc.laddr = f"tcp://127.0.0.1:{base_p2p + 2 * i + 1}"
        cfg.p2p.seeds = ",".join(
            f"127.0.0.1:{base_p2p + 2 * j}" for j in range(n) if j != i
        )
        write_config(cfg)
        gen.save_as(os.path.join(home, "genesis.json"))
    print(f"wrote {n} node homes under {args.output}")
    return 0


def _cmd_probe_upnp(args) -> int:
    """Discover a UPnP gateway and test a port mapping (reference
    `commands/probe_upnp.go`)."""
    import json as _json

    from tendermint_tpu.p2p import upnp

    try:
        result = upnp.probe(port=args.port)
    except upnp.UPnPError as e:
        print(f"probe failed: {e}", file=sys.stderr)
        return 1
    print(_json.dumps(result))
    return 0


def _cmd_version(args) -> int:
    from tendermint_tpu.version import __version__

    print(__version__)
    return 0


def _cmd_gen_validator(args) -> int:
    """Print a freshly generated validator keypair as JSON (reference
    `commands/gen_validator.go`). Does NOT touch any file."""
    import json as _json

    from tendermint_tpu.crypto import gen_priv_key

    pk = gen_priv_key()
    print(
        _json.dumps(
            {
                "address": pk.pub_key.address.hex(),
                "pub_key": pk.pub_key.data.hex(),
                "priv_key_seed": pk.seed.hex(),
                "last_height": 0,
                "last_round": 0,
                "last_step": 0,
                "last_signature": "",
                "last_signbytes": "",
            },
            indent=2,
            sort_keys=True,
        )
    )
    return 0


def _reset_priv_validator(path: str) -> None:
    from tendermint_tpu.types.priv_validator import PrivValidatorFS

    if os.path.exists(path):
        PrivValidatorFS.load(path).reset()
        print(f"reset priv validator sign state: {path}")
    else:
        PrivValidatorFS.load_or_gen(path)
        print(f"generated priv validator: {path}")


def _cmd_reset_priv_validator(args) -> int:
    """(unsafe) Forget the validator's last-sign state — double-sign
    protection goes with it (reference `commands/reset_priv_validator.go`).
    Testnets only."""
    from tendermint_tpu.config import load_config

    _reset_priv_validator(load_config(args.home).priv_validator_path())
    return 0


def _cmd_reset_all(args) -> int:
    """(unsafe) Remove all chain data + WALs and reset the validator
    (reference `commands/reset_priv_validator.go` resetAll)."""
    import shutil

    from tendermint_tpu.config import load_config

    cfg = load_config(args.home)
    data_dir = os.path.dirname(cfg.db_path("state"))
    for victim in (data_dir, cfg.mempool_wal_path()):
        if os.path.isdir(victim):
            shutil.rmtree(victim)
            print(f"removed {victim}")
    _reset_priv_validator(cfg.priv_validator_path())
    return 0


def _cmd_replay(args) -> int:
    """Replay the consensus WAL through a fresh state machine — all at
    once (`replay`) or interactively (`replay_console`); reference
    `consensus/replay_file.go`, `commands/replay.go`."""
    from tendermint_tpu.config import load_config
    from tendermint_tpu.consensus.replay_console import (
        Playback,
        make_replay_cs_factory,
    )

    cfg = load_config(args.home)
    wal = args.wal or cfg.wal_path()
    pb = Playback(make_replay_cs_factory(cfg), wal)
    if args.console:
        pb.console()
    else:
        n = pb.run_all()
        print(f"replayed {n} records; final state: {pb.round_state('short')}")
    return 0


def _cmd_wal2json(args) -> int:
    """Dump a consensus WAL as JSON lines (reference
    `scripts/wal2json/main.go:19-50`)."""
    import json as _json

    from tendermint_tpu.consensus.wal import (
        WAL,
        EndHeightMessage,
        MsgRecord,
        RoundStateRecord,
        TimeoutRecord,
    )

    for rec in WAL.iter_records(args.wal):
        if isinstance(rec, EndHeightMessage):
            out = {"type": "end_height", "height": rec.height}
        elif isinstance(rec, RoundStateRecord):
            out = {
                "type": "round_state",
                "height": rec.height,
                "round": rec.round,
                "step": rec.step,
            }
        elif isinstance(rec, TimeoutRecord):
            out = {
                "type": "timeout",
                "height": rec.height,
                "round": rec.round,
                "step": rec.step,
                "duration": rec.duration,
            }
        elif isinstance(rec, MsgRecord):
            m = rec.msg
            out = {
                "type": "msg",
                "peer": rec.peer_id,
                "msg": type(m).__name__ if not isinstance(m, tuple) else "BlockPart",
            }
            if hasattr(m, "height"):
                out["height"] = m.height
        else:
            out = {"type": type(rec).__name__}
        print(_json.dumps(out))
    return 0


def _cmd_cut_wal_until(args) -> int:
    """Truncate a WAL just before the first record at/after `height`
    (reference `scripts/cutWALUntil/main.go` — builds crash fixtures)."""
    from tendermint_tpu.consensus.wal import WAL

    segments = WAL.segment_paths(args.wal)
    if not segments:
        print(f"error: no WAL found at {args.wal}", file=sys.stderr)
        return 1
    # walk ALL segments in order (rotated files + live file) so the cut
    # point is found wherever rotation put it; output is one flat file
    out = bytearray()
    total = 0
    done = False
    for seg in segments:
        with open(seg, "rb") as f:
            data = f.read()
        total += len(data)
        if done:
            continue
        cut = len(data)
        for off, rec in WAL.iter_records_with_offsets(seg):
            rec_height = getattr(rec, "height", None)
            if rec_height is None:
                rec_height = getattr(getattr(rec, "msg", None), "height", None)
            if rec_height is not None and rec_height >= args.height:
                cut = off
                done = True
                break
        out += data[:cut]
    with open(args.output, "wb") as f:
        f.write(bytes(out))
    print(f"wrote {len(out)} of {total} bytes to {args.output}")
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="tendermint_tpu", description="TPU-native BFT consensus node"
    )
    sub = parser.add_subparsers(dest="command", required=True)

    p = sub.add_parser("init", help="initialize a node home directory")
    p.add_argument("--home", default=os.path.expanduser("~/.tendermint_tpu"))
    p.add_argument("--chain-id", default="")
    p.set_defaults(fn=_cmd_init)

    p = sub.add_parser("node", help="run a node")
    p.add_argument("--home", default=os.path.expanduser("~/.tendermint_tpu"))
    p.add_argument("--p2p-laddr", default="")
    p.add_argument("--rpc-laddr", default="")
    p.add_argument("--seeds", default="")
    p.add_argument(
        "--fast-sync", type=lambda s: s.lower() != "false", default=None
    )
    p.set_defaults(fn=_cmd_node)

    p = sub.add_parser("testnet", help="generate an N-node local testnet")
    p.add_argument("--n", type=int, default=4)
    p.add_argument("--output", default="./mytestnet")
    p.add_argument("--chain-id", default="")
    p.add_argument("--starting-port", type=int, default=46656)
    p.set_defaults(fn=_cmd_testnet)

    p = sub.add_parser("show_validator", help="print the validator pubkey")
    p.add_argument("--home", default=os.path.expanduser("~/.tendermint_tpu"))
    p.set_defaults(fn=_cmd_show_validator)

    p = sub.add_parser("probe_upnp", help="test UPnP gateway port mapping")
    p.add_argument("--port", type=int, default=46656)
    p.set_defaults(fn=_cmd_probe_upnp)

    p = sub.add_parser("gen_validator", help="generate a validator keypair")
    p.set_defaults(fn=_cmd_gen_validator)

    p = sub.add_parser(
        "reset_priv_validator",
        help="(unsafe) reset the validator's sign state",
    )
    p.add_argument("--home", default=os.path.expanduser("~/.tendermint_tpu"))
    p.set_defaults(fn=_cmd_reset_priv_validator)

    p = sub.add_parser(
        "reset_all", help="(unsafe) wipe chain data + reset the validator"
    )
    p.add_argument("--home", default=os.path.expanduser("~/.tendermint_tpu"))
    p.set_defaults(fn=_cmd_reset_all)

    p = sub.add_parser("replay", help="replay the consensus WAL")
    p.add_argument("--home", default=os.path.expanduser("~/.tendermint_tpu"))
    p.add_argument("--wal", default="", help="WAL path (default: the home's)")
    p.set_defaults(fn=_cmd_replay, console=False)

    p = sub.add_parser(
        "replay_console", help="step the consensus WAL interactively"
    )
    p.add_argument("--home", default=os.path.expanduser("~/.tendermint_tpu"))
    p.add_argument("--wal", default="", help="WAL path (default: the home's)")
    p.set_defaults(fn=_cmd_replay, console=True)

    p = sub.add_parser("version", help="print the version")
    p.set_defaults(fn=_cmd_version)

    p = sub.add_parser("wal2json", help="dump a consensus WAL as JSON lines")
    p.add_argument("wal")
    p.set_defaults(fn=_cmd_wal2json)

    p = sub.add_parser(
        "cut_wal_until", help="truncate a WAL before the given height"
    )
    p.add_argument("wal")
    p.add_argument("height", type=int)
    p.add_argument("output")
    p.set_defaults(fn=_cmd_cut_wal_until)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
