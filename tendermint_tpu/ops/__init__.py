"""Device kernels: the hot numeric plane (SURVEY.md §2b).

Everything here is pure, fixed-shape, integer-only JAX — deterministic by
construction (no floats), batched over the leading dimension, shardable over a
`jax.sharding.Mesh`. These kernels replace the reference's pure-Go crypto
libraries (ed25519, sha256, ripemd160) at the `BatchVerifier`/`TreeHasher`
seams.
"""

from tendermint_tpu.ops.sha256_kernel import sha256_batch_jax, sha256_digest_bytes
from tendermint_tpu.ops.sha512_kernel import sha512_batch_jax
from tendermint_tpu.ops.ripemd160_kernel import ripemd160_batch_jax
from tendermint_tpu.ops.merkle_kernel import merkle_root_device, merkle_root_from_leaf_words

__all__ = [
    "sha256_batch_jax",
    "sha256_digest_bytes",
    "sha512_batch_jax",
    "ripemd160_batch_jax",
    "merkle_root_device",
    "merkle_root_from_leaf_words",
]
