"""Batched ed25519 signature verification on device (JAX, TPU-first).

This is the TPU-native replacement for the reference's one-at-a-time
`crypto.PubKey.VerifyBytes` hot loop (reference call sites:
`types/vote_set.go:177`, `types/validator_set.go:253,327`,
`consensus/state.go:1271`). SURVEY.md §7 ranks this hard part #1.

Design (TPU-first, not a port — the reference uses pure-Go scalar code):

* GF(2^255-19) elements are vectors of 20 signed 13-bit limbs in int32
  (radix 2^13). A 13x13-bit limb product is <= 2^26 and a 39-column
  schoolbook convolution sums at most 20 of them, staying under 2^31 —
  so every multiply fits the TPU's native 32-bit integer VPU lanes with
  no 64-bit emulation. All ops are elementwise over an arbitrary batch
  shape: throughput comes purely from the batch dimension.
* Curve points use extended twisted-Edwards coordinates (X, Y, Z, T),
  a = -1, with the unified hwcd-3 addition law (no branches, so a
  single traced program covers every input — XLA-friendly).
* Verification checks the cofactorless equation  [S]B == R + [h]A  as
  [S]B + [h](-A) == R  via one Shamir double-scalar ladder:
  253 doublings, each followed by one unified add of a 4-entry table
  {O, B, -A, B-A} selected arithmetically (no gather) — all inside one
  `lax.scan`, fixed shapes, integer-only, bit-reproducible.
* h = SHA512(R || A || M) mod L is computed on host (variable-length
  messages are the wrong shape for the device; the curve math is ~1000x
  the hash cost). The device receives fixed-shape byte/bit arrays.

Validated against the host `cryptography` backend (RFC 8032 semantics,
cofactorless) including batches with planted bad signatures, bad points
and non-canonical encodings.
"""

from __future__ import annotations

import hashlib

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

# -- constants ---------------------------------------------------------------

P = 2**255 - 19
L = 2**252 + 27742317777372353535851937790883648493  # group order
D = (-121665 * pow(121666, P - 2, P)) % P  # Edwards d
D2 = (2 * D) % P
SQRT_M1 = pow(2, (P - 1) // 4, P)  # sqrt(-1)
# Base point
_BY = (4 * pow(5, P - 2, P)) % P
_BX_SQ = (_BY * _BY - 1) * pow(D * _BY * _BY + 1, P - 2, P) % P
_BX = pow(_BX_SQ, (P + 3) // 8, P)
if (_BX * _BX - _BX_SQ) % P != 0:
    _BX = (_BX * SQRT_M1) % P
if _BX % 2 != 0:
    _BX = P - _BX
BX, BY = _BX, _BY

NLIMBS = 20
RADIX = 13
MASK = (1 << RADIX) - 1
# 2^260 = 2^5 * 2^255 ≡ 32*19 = 608 (mod p): the fold factor for limbs >= 20.
FOLD = 608
SCALAR_BITS = 253  # scalars are < L < 2^253


def _int_to_limbs(x: int) -> np.ndarray:
    out = np.zeros(NLIMBS, dtype=np.int32)
    for i in range(NLIMBS):
        out[i] = x & MASK
        x >>= RADIX
    return out


def _limbs_to_int(limbs) -> int:
    x = 0
    for i in reversed(range(NLIMBS)):
        x = (x << RADIX) | int(limbs[..., i] if hasattr(limbs, "ndim") else limbs[i])
    return x


_D_L = _int_to_limbs(D)
_D2_L = _int_to_limbs(D2)
_SQRT_M1_L = _int_to_limbs(SQRT_M1)
_ONE_L = _int_to_limbs(1)
_P_L = _int_to_limbs(P)


# -- field arithmetic (batched over leading axes; last axis = 20 limbs) ------


def _carry_round(t):
    """One vectorized (all-limbs-at-once) carry round with the 608 fold.

    No sequential ripple: each round moves carries one limb over, which
    is enough because we only maintain a *loose* invariant (limbs small
    enough that the next multiply cannot overflow int32), not canonical
    form. Signed arithmetic shifts give floor semantics for negatives.
    """
    c = t >> RADIX
    r = t & MASK
    zero = jnp.zeros_like(c[..., :1])
    shifted = jnp.concatenate([zero, c[..., :-1]], axis=-1)
    r = r + shifted
    # carry out of limb 19 has weight 2^260 ≡ 608 (mod p)
    return r.at[..., 0].add(FOLD * c[..., -1])


def fe_carry(t):
    """Bring limbs into the loose range [0, 2^13 + 608·k) for small k.

    Three vectorized rounds: entering magnitudes < 2^30 fall to < 2^13.6
    which keeps the 20-term column sums of fe_mul below 2^31. Exact
    canonical form (needed only for equality/serialization) is fe_canon.
    """
    t = _carry_round(t)
    t = _carry_round(t)
    t = _carry_round(t)
    return t


def fe_add(a, b):
    return a + b  # callers must carry before multiplying


def fe_addc(a, b):
    return fe_carry(a + b)


def fe_sub(a, b):
    """a - b, re-normalized to the loose range (limbs may be ≥ 0 or tiny-)."""
    return fe_carry(fe_carry(a - b))


def fe_neg(a):
    return fe_carry(fe_carry(-a))


def fe_mul(a, b):
    """Schoolbook 20x20 limb product, 39 columns, folded mod 2^255-19.

    Inputs must satisfy the loose invariant (|limb| < 2^13.7); each of
    the 39 column sums is then < 20 · 2^27.4-ish... strictly: limbs
    < 2^13.7 ⇒ |product| < 2^27.4, 20 of them < 2^31 — fits int32.
    """
    width = 2 * NLIMBS - 1
    cols = jnp.zeros(jnp.broadcast_shapes(a.shape[:-1], b.shape[:-1]) + (width,), dtype=jnp.int32)
    for i in range(NLIMBS):
        prod = a[..., i : i + 1] * b  # (..., 20)
        cols = cols + jnp.pad(prod, [(0, 0)] * (prod.ndim - 1) + [(i, width - NLIMBS - i)])
    # One vectorized carry round bounds every column < 2^18.2, so the
    # 608 fold below cannot overflow.
    c = cols >> RADIX
    r = cols & MASK
    zero = jnp.zeros_like(c[..., :1])
    r = r + jnp.concatenate([zero, c[..., :-1]], axis=-1)
    lo = r[..., :NLIMBS]
    hi = jnp.concatenate([r[..., NLIMBS:], c[..., -1:]], axis=-1)  # cols 20..39
    return fe_carry(lo + FOLD * hi)


def fe_sq(a):
    return fe_mul(a, a)


def fe_cmov(a, b, flag):
    """flag ? b : a, elementwise over the batch (flag shape (...,))."""
    return jnp.where(flag[..., None], b, a)


_16P_L = _int_to_limbs(16 * P)


def _carry_exact(a):
    """Sequential 20-step carry: limbs exactly in [0, 2^13), 608-folded.

    Only used by fe_canon (equality / serialization), never in the hot
    ladder, so the sequential dependency chain is acceptable.
    """
    for _ in range(2):
        limbs = []
        c = jnp.zeros_like(a[..., 0])
        for i in range(NLIMBS):
            v = a[..., i] + c
            c = v >> RADIX
            limbs.append(v & MASK)
        a = jnp.stack(limbs, axis=-1)
        a = a.at[..., 0].add(FOLD * c)
    return a


def fe_canon(a):
    """Fully canonical representative in [0, p)."""
    a = fe_carry(a)
    # Loose limbs can be slightly negative; +16p makes the value positive
    # without changing it mod p, and keeps everything under 2^260.
    a = _carry_exact(a + jnp.asarray(_16P_L))
    # Fold bits >= 255 down: limb 19 holds bits 247..259, so k = top 5 bits.
    for _ in range(2):
        k = a[..., 19] >> 8
        a = a.at[..., 19].set(a[..., 19] & 0xFF)
        a = a.at[..., 0].add(19 * k)  # 2^255 ≡ 19 (mod p)
        a = _carry_exact(a)
    ge = _fe_ge_p(a)
    a = jnp.where(ge[..., None], a - jnp.asarray(_P_L)[None, :], a)
    return _carry_exact(a)


def _fe_ge_p(a):
    """value(a) >= p, for a with limbs in [0, 2^13) and value < 2^256."""
    # compare lexicographically from the top limb down.
    gt = jnp.zeros(a.shape[:-1], dtype=bool)
    eq = jnp.ones(a.shape[:-1], dtype=bool)
    for i in reversed(range(NLIMBS)):
        pi = int(_P_L[i])
        gt = gt | (eq & (a[..., i] > pi))
        eq = eq & (a[..., i] == pi)
    return gt | eq


def fe_eq(a, b):
    """Constant-shape equality mod p."""
    ca, cb = fe_canon(a), fe_canon(b)
    return jnp.all(ca == cb, axis=-1)


def fe_is_zero(a):
    return jnp.all(fe_canon(a) == 0, axis=-1)


def _sq_n(x, n):
    """n repeated squarings as a fori_loop: keeps the traced graph small
    (fe_invert would otherwise unroll ~254 multiplies into the HLO)."""
    if n <= 2:
        for _ in range(n):
            x = fe_sq(x)
        return x
    return lax.fori_loop(0, n, lambda _, v: fe_sq(v), x)


def fe_invert(a):
    """a^(p-2) via the standard curve25519 addition chain (11 muls, 254 sqs)."""
    z2 = fe_sq(a)
    z9 = fe_mul(_sq_n(z2, 2), a)
    z11 = fe_mul(z9, z2)
    z2_5_0 = fe_mul(fe_sq(z11), z9)
    z2_10_0 = fe_mul(_sq_n(z2_5_0, 5), z2_5_0)
    z2_20_0 = fe_mul(_sq_n(z2_10_0, 10), z2_10_0)
    z2_40_0 = fe_mul(_sq_n(z2_20_0, 20), z2_20_0)
    z2_50_0 = fe_mul(_sq_n(z2_40_0, 10), z2_10_0)
    z2_100_0 = fe_mul(_sq_n(z2_50_0, 50), z2_50_0)
    z2_200_0 = fe_mul(_sq_n(z2_100_0, 100), z2_100_0)
    z2_250_0 = fe_mul(_sq_n(z2_200_0, 50), z2_50_0)
    return fe_mul(_sq_n(z2_250_0, 5), z11)


def fe_pow_p58(a):
    """a^((p-5)/8), used by the combined sqrt(u/v) in decompression."""
    z2 = fe_sq(a)
    z9 = fe_mul(_sq_n(z2, 2), a)
    z11 = fe_mul(z9, z2)
    z2_5_0 = fe_mul(fe_sq(z11), z9)
    z2_10_0 = fe_mul(_sq_n(z2_5_0, 5), z2_5_0)
    z2_20_0 = fe_mul(_sq_n(z2_10_0, 10), z2_10_0)
    z2_40_0 = fe_mul(_sq_n(z2_20_0, 20), z2_20_0)
    z2_50_0 = fe_mul(_sq_n(z2_40_0, 10), z2_10_0)
    z2_100_0 = fe_mul(_sq_n(z2_50_0, 50), z2_50_0)
    z2_200_0 = fe_mul(_sq_n(z2_100_0, 100), z2_100_0)
    z2_250_0 = fe_mul(_sq_n(z2_200_0, 50), z2_50_0)
    return fe_mul(_sq_n(z2_250_0, 2), a)


# -- byte <-> limb conversion (device) ---------------------------------------


def bytes_to_fe(b):
    """(..., 32) uint8 little-endian -> (..., 20) int32 limbs (bit 255 kept)."""
    b = b.astype(jnp.int32)
    bits_total = 8 * 32
    # Build each 13-bit limb from the bytes covering its bit range.
    limbs = []
    for i in range(NLIMBS):
        lo_bit = i * RADIX
        hi_bit = min(lo_bit + RADIX, bits_total)
        acc = jnp.zeros_like(b[..., 0])
        for byte_idx in range(lo_bit // 8, (hi_bit + 7) // 8):
            byte = b[..., byte_idx]
            shift = byte_idx * 8 - lo_bit
            if shift >= 0:
                acc = acc + ((byte << shift) & MASK)
            else:
                acc = acc + ((byte >> (-shift)) & MASK)
        limbs.append(acc & MASK)
    return jnp.stack(limbs, axis=-1)


def fe_to_bytes(a):
    """Canonical little-endian 32 bytes from limbs: (..., 20) -> (..., 32) i32."""
    a = fe_canon(a)
    out = []
    for byte_idx in range(32):
        lo_bit = byte_idx * 8
        acc = jnp.zeros_like(a[..., 0])
        for i in range(NLIMBS):
            limb_lo = i * RADIX
            shift = limb_lo - lo_bit
            if -RADIX < shift < 8:
                if shift >= 0:
                    acc = acc + ((a[..., i] << shift) & 0xFF)
                else:
                    acc = acc + ((a[..., i] >> (-shift)) & 0xFF)
        out.append(acc & 0xFF)
    return jnp.stack(out, axis=-1)


# -- point ops: extended twisted Edwards (X, Y, Z, T), a = -1 ----------------
# A point is a tuple of four fe's, each (..., 20).


def pt_add(p, q):
    """Unified addition (add-2008-hwcd-3, a=-1): 8M + some adds; branch-free."""
    x1, y1, z1, t1 = p
    x2, y2, z2, t2 = q
    a_ = fe_mul(fe_sub(y1, x1), fe_sub(y2, x2))
    b_ = fe_mul(fe_carry(y1 + x1), fe_carry(y2 + x2))
    c_ = fe_mul(fe_mul(t1, jnp.asarray(_D2_L)), t2)
    d_ = fe_mul(fe_carry(z1 + z1), z2)
    e_ = fe_sub(b_, a_)
    f_ = fe_sub(d_, c_)
    g_ = fe_carry(d_ + c_)
    h_ = fe_carry(b_ + a_)
    return (fe_mul(e_, f_), fe_mul(g_, h_), fe_mul(f_, g_), fe_mul(e_, h_))


def pt_double(p):
    """dbl-2008-hwcd, a=-1: 4M + 4S."""
    x1, y1, z1, _ = p
    a_ = fe_sq(x1)
    b_ = fe_sq(y1)
    c_ = fe_carry(2 * fe_sq(z1))
    h_ = fe_carry(a_ + b_)
    e_ = fe_sub(h_, fe_sq(fe_carry(x1 + y1)))
    g_ = fe_sub(a_, b_)
    f_ = fe_carry(c_ + g_)
    return (fe_mul(e_, f_), fe_mul(g_, h_), fe_mul(f_, g_), fe_mul(e_, h_))


def pt_neg(p):
    x, y, z, t = p
    return (fe_neg(x), y, z, fe_neg(t))


def pt_cmov(p, q, flag):
    return tuple(fe_cmov(a, b, flag) for a, b in zip(p, q))


def pt_select4(t0, t1, t2, t3, sel):
    """Arithmetic 4-way select (sel in {0,1,2,3}, shape (...,)) — no gather."""
    out = []
    for c0, c1, c2, c3 in zip(t0, t1, t2, t3):
        m1 = (sel == 1)[..., None]
        m2 = (sel == 2)[..., None]
        m3 = (sel == 3)[..., None]
        v = jnp.where(m1, c1, c0)
        v = jnp.where(m2, c2, v)
        v = jnp.where(m3, c3, v)
        out.append(v)
    return tuple(out)


def pt_decompress(y_bytes):
    """Decode 32-byte point encodings -> (point, ok_mask).

    y_bytes: (..., 32) uint8/int32. Returns extended coords and a bool
    mask of valid encodings (on-curve, canonical y, consistent sign).
    Branch-free: invalid lanes still produce *some* point; callers must
    AND the mask into the final verdict.
    """
    y_bytes = y_bytes.astype(jnp.int32)
    sign = (y_bytes[..., 31] >> 7) & 1
    y_clean = y_bytes.at[..., 31].set(y_bytes[..., 31] & 0x7F)
    y = bytes_to_fe(y_clean)
    # canonical check: y < p
    y_noncanon = _fe_ge_p(y)
    one = jnp.broadcast_to(jnp.asarray(_ONE_L), y.shape).astype(jnp.int32)
    y2 = fe_sq(y)
    u = fe_sub(y2, one)  # y^2 - 1
    v = fe_addc(fe_mul(y2, jnp.asarray(_D_L)), one)  # d*y^2 + 1
    # x = u v^3 (u v^7)^((p-5)/8); then fix by sqrt(-1) if needed.
    v3 = fe_mul(fe_sq(v), v)
    v7 = fe_mul(fe_sq(v3), v)
    x = fe_mul(fe_mul(u, v3), fe_pow_p58(fe_mul(u, v7)))
    vxx = fe_mul(v, fe_sq(x))
    ok_direct = fe_eq(vxx, u)
    ok_flip = fe_eq(vxx, fe_neg(u))
    x = fe_cmov(x, fe_mul(x, jnp.asarray(_SQRT_M1_L)), ok_flip & ~ok_direct)
    on_curve = ok_direct | ok_flip
    # sign: RFC 8032 — if x == 0 and sign bit set, reject.
    x_bytes = fe_to_bytes(x)
    x_is_zero = fe_is_zero(x)
    x_odd = (x_bytes[..., 0] & 1) == 1
    need_neg = x_odd != (sign == 1)
    x = fe_cmov(x, fe_neg(x), need_neg)
    bad_sign_zero = x_is_zero & (sign == 1)
    ok = on_curve & ~y_noncanon & ~bad_sign_zero
    t = fe_mul(x, y)
    z = one
    return (x, y, z, t), ok


def pt_eq(p, q):
    """Projective equality: X1 Z2 == X2 Z1 and Y1 Z2 == Y2 Z1."""
    x1, y1, z1, _ = p
    x2, y2, z2, _ = q
    return fe_eq(fe_mul(x1, z2), fe_mul(x2, z1)) & fe_eq(
        fe_mul(y1, z2), fe_mul(y2, z1)
    )


# -- Shamir double-scalar ladder ---------------------------------------------


def _scalar_bits_from_le_bytes(s_bytes):
    """(..., 32) uint8 LE -> (..., 253) int32 bits, LSB first."""
    s = s_bytes.astype(jnp.int32)
    bits = []
    for i in range(SCALAR_BITS):
        bits.append((s[..., i // 8] >> (i % 8)) & 1)
    return jnp.stack(bits, axis=-1)


def double_scalar_mul(s_bits, p1, h_bits, p2):
    """[s]P1 + [h]P2 with one shared ladder (Shamir's trick).

    s_bits/h_bits: (..., 253) int32 bits LSB-first. Table: {O,P1,P2,P1+P2}.
    Runs as a lax.scan over 253 msb-first steps: double + table add.
    """
    # Build the identity from the inputs (not fresh constants) so the scan
    # carry is device-varying under shard_map — an invariant init vs a
    # varying carry-out is a vma type error.
    vzero = (s_bits[..., :1] * 0).astype(jnp.int32)  # (..., 1), all zeros
    zero = vzero + jnp.zeros(NLIMBS, dtype=jnp.int32)
    one = vzero + jnp.asarray(_ONE_L)
    t0 = (zero, one, one, zero)
    t1 = p1
    t2 = p2
    t3 = pt_add(p1, p2)

    # scan msb-first
    sel = s_bits + 2 * h_bits  # (..., 253)
    sel_rev = jnp.flip(sel, axis=-1)  # msb first
    sel_scan = jnp.moveaxis(sel_rev, -1, 0)  # (253, ...)

    def step(acc, sel_t):
        acc = pt_double(acc)
        addend = pt_select4(t0, t1, t2, t3, sel_t)
        acc = pt_add(acc, addend)
        return acc, None

    acc, _ = lax.scan(step, t0, sel_scan)
    return acc


# -- batch verification (device core) ----------------------------------------


@jax.jit
def verify_kernel(pub_bytes, r_bytes, s_bytes, h_bytes):
    """Core batched verify: all inputs (B, 32) uint8 arrays.

    pub_bytes: A encodings; r_bytes: R encodings (sig[:32]);
    s_bytes: S little-endian (sig[32:], already checked < L on host);
    h_bytes: SHA512(R||A||M) mod L little-endian.
    Returns (B,) bool verdicts for  [S]B == R + [h]A  (cofactorless).
    """
    a_pt, a_ok = pt_decompress(pub_bytes)
    r_pt, r_ok = pt_decompress(r_bytes)
    s_bits = _scalar_bits_from_le_bytes(s_bytes)
    h_bits = _scalar_bits_from_le_bytes(h_bytes)
    b_x = jnp.asarray(_int_to_limbs(BX))
    b_y = jnp.asarray(_int_to_limbs(BY))
    b_t = jnp.asarray(_int_to_limbs(BX * BY % P))
    one = jnp.asarray(_ONE_L)
    shape = pub_bytes.shape[:-1] + (NLIMBS,)
    b_pt = (
        jnp.broadcast_to(b_x, shape).astype(jnp.int32),
        jnp.broadcast_to(b_y, shape).astype(jnp.int32),
        jnp.broadcast_to(one, shape).astype(jnp.int32),
        jnp.broadcast_to(b_t, shape).astype(jnp.int32),
    )
    # [S]B + [h](-A) == R
    lhs = double_scalar_mul(s_bits, b_pt, h_bits, pt_neg(a_pt))
    return pt_eq(lhs, r_pt) & a_ok & r_ok


# -- host-side driver ---------------------------------------------------------


def _reduce_mod_l_le(data: bytes) -> bytes:
    return (int.from_bytes(data, "little") % L).to_bytes(32, "little")


def prepare_batch(pubkeys, msgs, sigs):
    """Host prep: compute h = SHA512(R||A||M) mod L; canonicality checks.

    Returns (pub, r, s, h) uint8 arrays of shape (B, 32) and a (B,) bool
    mask `precheck` that is False for signatures malformed beyond what
    the device checks (wrong length, S >= L).
    """
    n = len(pubkeys)
    pub = np.zeros((n, 32), dtype=np.uint8)
    r = np.zeros((n, 32), dtype=np.uint8)
    s = np.zeros((n, 32), dtype=np.uint8)
    h = np.zeros((n, 32), dtype=np.uint8)
    precheck = np.zeros(n, dtype=bool)
    for i, (pk, msg, sig) in enumerate(zip(pubkeys, msgs, sigs)):
        if len(pk) != 32 or len(sig) != 64:
            continue
        s_int = int.from_bytes(sig[32:], "little")
        if s_int >= L:
            # DELIBERATE STRICTNESS (divergence from the reference): we
            # reject non-canonical S >= L per RFC 8032 §5.1.7. The
            # reference's Go ed25519 only checks sig[63]&224 == 0, so it
            # accepts malleable S in [L, 2^253). Strictness removes
            # signature malleability; the cost is that reference-signed
            # artifacts with non-canonical S (never produced by honest
            # signers) fail here. Host fallback applies the same rule, so
            # the framework is internally consistent.
            continue
        precheck[i] = True
        pub[i] = np.frombuffer(pk, dtype=np.uint8)
        r[i] = np.frombuffer(sig[:32], dtype=np.uint8)
        s[i] = np.frombuffer(sig[32:], dtype=np.uint8)
        hh = hashlib.sha512(sig[:32] + pk + msg).digest()
        h[i] = np.frombuffer(_reduce_mod_l_le(hh), dtype=np.uint8)
    return pub, r, s, h, precheck


def bucket_size(n: int) -> int:
    """Pad batch sizes to power-of-two buckets (min 8) to bound recompiles."""
    size = 8
    while size < n:
        size *= 2
    return size


def launch_batch_verify(pubkeys, msgs, sigs):
    """Async half of `batch_verify`: host prep + device launch only.

    Returns `(device_verdict, precheck, n)` with the verdict left
    UN-materialized — JAX dispatch is asynchronous, so this call returns
    as soon as the kernel is enqueued and the caller is free to do host
    work (build the next window, apply the previous one) while the
    device computes. `materialize_batch_verify` blocks on the transfer.
    """
    n = len(pubkeys)
    pub, r, s, h, precheck = prepare_batch(pubkeys, msgs, sigs)
    size = bucket_size(n)
    if size != n:
        pad = size - n

        def _pad(a):
            return np.concatenate([a, np.zeros((pad, 32), dtype=np.uint8)])

        pub, r, s, h = _pad(pub), _pad(r), _pad(s), _pad(h)
    from tendermint_tpu.ops.ed25519_ladder_pallas import (
        use_pallas_ladder,
        verify_kernel_pallas,
    )

    if use_pallas_ladder(size):
        verdict = verify_kernel_pallas(pub, r, s, h)
    else:
        verdict = verify_kernel(pub, r, s, h)
    return verdict, precheck, n


def materialize_batch_verify(launched) -> np.ndarray:
    """Blocking half: pull the device verdict to host, mask prechecks."""
    verdict, precheck, n = launched
    return np.asarray(verdict)[:n] & precheck


def batch_verify(pubkeys, msgs, sigs) -> np.ndarray:
    """Verify a batch of ed25519 signatures on device; returns (B,) bool.

    Replaces the reference's sequential loop in
    `types/validator_set.go:236-261` / `types/vote_set.go:137-196`.
    Batches that pad to >= 1024 lanes take the Pallas ladder
    (VMEM-resident accumulator, `ops.ed25519_ladder_pallas`) on TPU;
    smaller ones the portable XLA scan.
    """
    if len(pubkeys) == 0:
        return np.zeros(0, dtype=bool)
    return materialize_batch_verify(launch_batch_verify(pubkeys, msgs, sigs))
