"""Host-side message padding into fixed-shape u32 block arrays.

Variable-length sign-bytes/leaves are padded to bucketed block counts so the
device kernels see only static shapes (bucketing avoids one XLA recompile per
message length — SURVEY.md §7 hard part 2). All functions return numpy arrays
ready to ship to device.
"""

from __future__ import annotations

import numpy as np

SHA256_BLOCK_BYTES = 64
SHA512_BLOCK_BYTES = 128


def bucket_blocks(n: int, buckets: tuple[int, ...] = (1, 2, 4, 8, 16, 32)) -> int:
    """Smallest bucket >= n (shape-stable compilation)."""
    for b in buckets:
        if n <= b:
            return b
    # beyond the largest bucket: round up to a multiple of it
    top = buckets[-1]
    return ((n + top - 1) // top) * top


def _md_pad(msg: bytes, block: int, length_bytes: int, length_le: bool) -> bytes:
    """Merkle-Damgård padding: 0x80, zeros, message bit-length."""
    bitlen = len(msg) * 8
    padded = msg + b"\x80"
    rem = (len(padded) + length_bytes) % block
    if rem:
        padded += b"\x00" * (block - rem)
    if length_le:
        padded += bitlen.to_bytes(length_bytes, "little")
    else:
        padded += bitlen.to_bytes(length_bytes, "big")
    return padded


def pad_sha256(msgs: list[bytes], max_blocks: int | None = None) -> tuple[np.ndarray, np.ndarray]:
    """-> (blocks[B, max_blocks, 16] u32 big-endian words, n_blocks[B] i32)."""
    return pad_sha256_prefixed(msgs, b"", max_blocks)


def pad_sha256_prefixed(
    msgs: list[bytes], prefix: bytes = b"", max_blocks: int | None = None
) -> tuple[np.ndarray, np.ndarray]:
    """Vectorized SHA-256 padding of `prefix || msg` for every message.

    -> (blocks[B, max_blocks, 16] u32 big-endian words, n_blocks[B] i32).

    Bulk numpy packing grouped by message length: per-item Python work is
    one len() only, so 65k+ Merkle leaves pack as a handful of C-speed
    array writes instead of 65k bytes concatenations (the round-2 ingest
    bottleneck at `merkle_kernel.py:113`).
    """
    n = len(msgs)
    plen = len(prefix)
    if n == 0:
        return np.zeros((0, max_blocks or 1, 16), dtype=np.uint32), np.zeros(
            0, dtype=np.int32
        )
    lens = np.fromiter((len(m) for m in msgs), dtype=np.int64, count=n) + plen
    # Merkle-Damgård: msg || 0x80 || zeros || 8-byte bit length
    counts = ((lens + 9 + 63) // 64).astype(np.int32)
    mb = max_blocks if max_blocks is not None else bucket_blocks(int(counts.max()))
    buf = np.zeros((n, mb * 64), dtype=np.uint8)
    prefix_arr = (
        np.frombuffer(prefix, dtype=np.uint8) if plen else None
    )
    # group by length in one O(n log n) pass (per-unique-length rescans
    # would be quadratic for mostly-distinct lengths)
    order = np.argsort(lens, kind="stable")
    sorted_lens = lens[order]
    run_starts = np.nonzero(np.diff(sorted_lens))[0] + 1
    for idx in np.split(order, run_starts):
        total = int(lens[idx[0]])
        body = total - plen
        if body:
            raw = np.frombuffer(
                b"".join(msgs[i] for i in idx), dtype=np.uint8
            ).reshape(len(idx), body)
            buf[idx, plen:total] = raw
        if plen:
            buf[idx, :plen] = prefix_arr
        buf[idx, total] = 0x80
        c = int((total + 9 + 63) // 64)
        length_be = np.frombuffer(
            int(total * 8).to_bytes(8, "big"), dtype=np.uint8
        )
        buf[idx, c * 64 - 8 : c * 64] = length_be
    blocks = (
        buf.view(">u4").astype(np.uint32).reshape(n, mb, 16)
    )
    return blocks, counts


def pad_sha512(msgs: list[bytes], max_blocks: int | None = None) -> tuple[np.ndarray, np.ndarray]:
    """-> (blocks[B, max_blocks, 32] u32: words 2i=hi, 2i+1=lo of 64-bit BE words,
    n_blocks[B] i32)."""
    padded = [_md_pad(m, 128, 16, length_le=False) for m in msgs]
    counts = np.array([len(p) // 128 for p in padded], dtype=np.int32)
    mb = max_blocks if max_blocks is not None else bucket_blocks(int(counts.max(initial=1)))
    out = np.zeros((len(msgs), mb, 32), dtype=np.uint32)
    for i, p in enumerate(padded):
        words = np.frombuffer(p, dtype=">u4").astype(np.uint32)  # already hi,lo pairs
        out[i, : counts[i]] = words.reshape(-1, 32)
    return out, counts


def pad_ripemd160(msgs: list[bytes], max_blocks: int | None = None) -> tuple[np.ndarray, np.ndarray]:
    """-> (blocks[B, max_blocks, 16] u32 little-endian words, n_blocks[B] i32)."""
    return pad_ripemd160_prefixed(msgs, b"", max_blocks)


def pad_ripemd160_prefixed(
    msgs: list[bytes], prefix: bytes = b"", max_blocks: int | None = None
) -> tuple[np.ndarray, np.ndarray]:
    """RIPEMD-160 padding of `prefix || msg` (LE words, LE bit length)."""
    padded = [_md_pad(prefix + m, 64, 8, length_le=True) for m in msgs]
    counts = np.array([len(p) // 64 for p in padded], dtype=np.int32)
    mb = max_blocks if max_blocks is not None else bucket_blocks(int(counts.max(initial=1)))
    out = np.zeros((len(msgs), mb, 16), dtype=np.uint32)
    for i, p in enumerate(padded):
        words = np.frombuffer(p, dtype="<u4").astype(np.uint32)
        out[i, : counts[i]] = words.reshape(-1, 16)
    return out, counts


def pad_rows_to(arrays: list[np.ndarray], size: int) -> list[np.ndarray]:
    """Zero-pad every array's leading (row) axis up to exactly `size`.

    The mesh shard path pads lane batches to a per-chip-bucket multiple
    of the mesh size with zero rows: a zero ed25519 row verifies False
    on device (see `parallel.mesh.pad_to_multiple`) and a zero leaf row
    carries n_blocks=0, so pad rows can never leak a True verdict or a
    real digest — callers slice outputs back to the true row count.
    """
    out = []
    for a in arrays:
        n = a.shape[0]
        if n == size:
            out.append(a)
            continue
        if n > size:
            raise ValueError(f"cannot pad {n} rows down to {size}")
        pad = np.zeros((size - n,) + a.shape[1:], dtype=a.dtype)
        out.append(np.concatenate([a, pad]))
    return out


def pad_rows_to_multiple(
    arrays: list[np.ndarray], multiple: int
) -> tuple[list[np.ndarray], int]:
    """Zero-pad row counts up to the next multiple of `multiple`;
    returns (padded arrays, true row count) for post-kernel slicing."""
    n = arrays[0].shape[0]
    size = ((n + multiple - 1) // multiple) * multiple
    return pad_rows_to(arrays, size), n


def digests_to_bytes_be(digests: np.ndarray) -> list[bytes]:
    """(B, W) u32 big-endian word digests -> list of byte digests."""
    arr = np.asarray(digests, dtype=np.uint32)
    return [w.astype(">u4").tobytes() for w in arr]


def digests_to_bytes_le(digests: np.ndarray) -> list[bytes]:
    """(B, W) u32 little-endian word digests (RIPEMD-160) -> bytes."""
    arr = np.asarray(digests, dtype=np.uint32)
    return [w.astype("<u4").tobytes() for w in arr]
