"""Batched SHA-512 in JAX with 64-bit words emulated as (hi, lo) u32 pairs.

Needed on-device for ed25519: h = SHA-512(R || A || M) feeds the batch
verifier (SURVEY.md §7 hard part 2). TPUs have no native u64 vector path, so
every 64-bit op is synthesized from u32 adds/shifts/logicals; rounds run as
`lax.scan` and throughput comes purely from the batch dimension.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from tendermint_tpu.ops.sha256_kernel import _first_primes, _frac_root_bits

_PRIMES80 = _first_primes(80)
# (hi, lo) u32 pairs for H0 (sqrt of first 8 primes) and K (cbrt of first 80).
_H0_64 = [_frac_root_bits(p, 2, 64) for p in _PRIMES80[:8]]
_K_64 = [_frac_root_bits(p, 3, 64) for p in _PRIMES80]
SHA512_H0_HI = np.array([v >> 32 for v in _H0_64], dtype=np.uint32)
SHA512_H0_LO = np.array([v & 0xFFFFFFFF for v in _H0_64], dtype=np.uint32)
SHA512_K_HI = np.array([v >> 32 for v in _K_64], dtype=np.uint32)
SHA512_K_LO = np.array([v & 0xFFFFFFFF for v in _K_64], dtype=np.uint32)


# -- 64-bit ops on (hi, lo) u32 pairs -----------------------------------------


def _add64(a, b):
    lo = a[1] + b[1]
    carry = (lo < a[1]).astype(jnp.uint32)
    return (a[0] + b[0] + carry, lo)


def _add64_many(*xs):
    acc = xs[0]
    for x in xs[1:]:
        acc = _add64(acc, x)
    return acc


def _xor64(a, b):
    return (a[0] ^ b[0], a[1] ^ b[1])


def _and64(a, b):
    return (a[0] & b[0], a[1] & b[1])


def _not64(a):
    return (~a[0], ~a[1])


def _rotr64(x, n: int):
    hi, lo = x
    if n == 32:
        return (lo, hi)
    if n < 32:
        nn = np.uint32(n)
        inv = np.uint32(32 - n)
        return ((hi >> nn) | (lo << inv), (lo >> nn) | (hi << inv))
    m = np.uint32(n - 32)
    inv = np.uint32(64 - n)  # = 32 - m
    return ((lo >> m) | (hi << inv), (hi >> m) | (lo << inv))


def _shr64(x, n: int):
    hi, lo = x
    if n < 32:
        nn = np.uint32(n)
        inv = np.uint32(32 - n)
        return (hi >> nn, (lo >> nn) | (hi << inv))
    m = np.uint32(n - 32)
    return (jnp.zeros_like(hi), hi >> m)


def _compress512(state, w_block):
    """state: (B, 16) u32 (hi,lo interleaved per 64-bit reg); w_block: (B, 32) u32."""
    whi0 = w_block[:, 0::2].T  # (16, B)
    wlo0 = w_block[:, 1::2].T

    def sched_step(carry, _):
        whi, wlo = carry  # (16, B) each: w[t-16..t-1]
        x = (whi[1], wlo[1])
        s0 = _xor64(_xor64(_rotr64(x, 1), _rotr64(x, 8)), _shr64(x, 7))
        y = (whi[14], wlo[14])
        s1 = _xor64(_xor64(_rotr64(y, 19), _rotr64(y, 61)), _shr64(y, 6))
        new = _add64_many((whi[0], wlo[0]), s0, (whi[9], wlo[9]), s1)
        whi = jnp.concatenate([whi[1:], new[0][None]], axis=0)
        wlo = jnp.concatenate([wlo[1:], new[1][None]], axis=0)
        return (whi, wlo), new

    _, w_rest = lax.scan(sched_step, (whi0, wlo0), None, length=64)
    W_hi = jnp.concatenate([whi0, w_rest[0]], axis=0)  # (80, B)
    W_lo = jnp.concatenate([wlo0, w_rest[1]], axis=0)

    def round_step(regs, xs):
        a, b, c, d, e, f, g, h = regs
        k_hi, k_lo, w_hi, w_lo = xs
        kt = (jnp.broadcast_to(k_hi, a[0].shape), jnp.broadcast_to(k_lo, a[1].shape))
        S1 = _xor64(_xor64(_rotr64(e, 14), _rotr64(e, 18)), _rotr64(e, 41))
        ch = _xor64(_and64(e, f), _and64(_not64(e), g))
        t1 = _add64_many(h, S1, ch, kt, (w_hi, w_lo))
        S0 = _xor64(_xor64(_rotr64(a, 28), _rotr64(a, 34)), _rotr64(a, 39))
        maj = _xor64(_xor64(_and64(a, b), _and64(a, c)), _and64(b, c))
        t2 = _add64(S0, maj)
        return (_add64(t1, t2), a, b, c, _add64(d, t1), e, f, g), None

    init = tuple((state[:, 2 * i], state[:, 2 * i + 1]) for i in range(8))
    regs, _ = lax.scan(
        round_step,
        init,
        (
            jnp.asarray(SHA512_K_HI),
            jnp.asarray(SHA512_K_LO),
            W_hi,
            W_lo,
        ),
    )
    out = [_add64(r, s) for r, s in zip(regs, init)]
    return jnp.stack([part for pair in out for part in pair], axis=1)


@partial(jax.jit, static_argnames=("max_blocks",))
def _sha512_masked(blocks, n_blocks, max_blocks: int):
    B = blocks.shape[0]
    h0 = np.empty(16, dtype=np.uint32)
    h0[0::2] = SHA512_H0_HI
    h0[1::2] = SHA512_H0_LO
    state0 = jnp.broadcast_to(jnp.asarray(h0), (B, 16)).astype(jnp.uint32)

    def block_step(state, xs):
        w_block, j = xs
        new_state = _compress512(state, w_block)
        return jnp.where((j < n_blocks)[:, None], new_state, state), None

    xs = (jnp.swapaxes(blocks, 0, 1), jnp.arange(max_blocks, dtype=jnp.int32))
    state, _ = lax.scan(block_step, state0, xs)
    return state


def sha512_batch_jax(blocks, n_blocks):
    """blocks: (B, max_blocks, 32) u32 (64-bit BE words as hi,lo pairs);
    n_blocks: (B,) i32. Returns (B, 16) u32 = the 64-byte digests as BE u32."""
    blocks = jnp.asarray(blocks, dtype=jnp.uint32)
    n_blocks = jnp.asarray(n_blocks, dtype=jnp.int32)
    return _sha512_masked(blocks, n_blocks, blocks.shape[1])
