"""Device Merkle tree reduction (log-depth, batched SHA-256 inner nodes).

Computes the same root as `tendermint_tpu.merkle.simple` (largest-power-of-two
split rule) via an equivalent level-by-level pairing: at each level adjacent
nodes pair into an inner hash and an unpaired trailing node is promoted
unchanged. Each level is one batched 2-block SHA-256 over all pairs — the
whole tree is log2(N) kernel steps (reference hot spots: `types/block.go:177`,
`types/tx.go:33-46`, `types/part_set.go:95-122`).

Inner-node messages (0x01 || left32 || right32 = 65 bytes) are assembled
directly in u32 registers (byte-shift composition), so no host round-trip
happens between levels.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from tendermint_tpu.merkle.simple import INNER_PREFIX, LEAF_PREFIX
from tendermint_tpu.ops.sha256_kernel import sha256_fixed2_from_words

_B8 = np.uint32(8)
_B24 = np.uint32(24)
# INNER_PREFIX byte placed in the top byte of the first message word.
_INNER_PREFIX_WORD = np.uint32(INNER_PREFIX[0] << 24)


def _inner_node_words(L, R):
    """Build the two 16-word SHA-256 blocks for H(INNER_PREFIX || L || R).

    L, R: (B, 8) u32 big-endian digest words. The 1-byte domain prefix shifts
    every digest byte by one, so each message word mixes two source words.
    """
    w0 = []
    w0.append(jnp.uint32(_INNER_PREFIX_WORD) | (L[:, 0] >> _B8))
    for i in range(1, 8):
        w0.append((L[:, i - 1] << _B24) | (L[:, i] >> _B8))
    w0.append((L[:, 7] << _B24) | (R[:, 0] >> _B8))
    for i in range(1, 8):
        w0.append((R[:, i - 1] << _B24) | (R[:, i] >> _B8))
    block0 = jnp.stack(w0, axis=1)

    B = L.shape[0]
    zero = jnp.zeros((B,), dtype=jnp.uint32)
    w1 = [(R[:, 7] << _B24) | jnp.uint32(0x00800000)]
    w1 += [zero] * 14
    w1.append(jnp.full((B,), np.uint32(65 * 8), dtype=jnp.uint32))
    block1 = jnp.stack(w1, axis=1)
    return block0, block1


def inner_hash_device(L, R):
    """(B,8),(B,8) -> (B,8): batched domain-separated inner-node hash."""
    b0, b1 = _inner_node_words(L, R)
    return sha256_fixed2_from_words(b0, b1)


@partial(jax.jit, static_argnames=("levels",))
def _tree_reduce(leaves, count, levels: int):
    """leaves: (P, 8) u32 with P = 2**levels; count: traced i32 valid prefix.
    Returns (8,) root words."""
    nodes = leaves
    for _ in range(levels):
        left = nodes[0::2]
        right = nodes[1::2]
        paired = inner_hash_device(left, right)
        idx = jnp.arange(left.shape[0], dtype=jnp.int32)
        # pair exists only if its right child is inside the valid prefix;
        # an unpaired trailing node is promoted (== left child unchanged).
        nodes = jnp.where((2 * idx + 1 < count)[:, None], paired, left)
        count = (count + 1) // 2
    return nodes[0]


def merkle_root_from_leaf_words(leaf_digests, count=None):
    """Root from device leaf hashes.

    leaf_digests: (N, 8) u32 (already leaf-prefixed hashes). N is padded up to
    the next power of two internally; `count` defaults to N.
    """
    leaf_digests = jnp.asarray(leaf_digests, dtype=jnp.uint32)
    n = leaf_digests.shape[0]
    if n == 0:
        raise ValueError(
            "empty leaf batch has no root (host simple_hash_from_hashes([]) is b'')"
        )
    if count is None:
        count = n
    P = 1
    while P < n:
        P *= 2
    if P != n:
        pad = jnp.zeros((P - n, 8), dtype=jnp.uint32)
        leaf_digests = jnp.concatenate([leaf_digests, pad], axis=0)
    levels = P.bit_length() - 1
    return _tree_reduce(leaf_digests, jnp.asarray(count, dtype=jnp.int32), levels)


def merkle_root_device(items: list[bytes]) -> bytes:
    """Host convenience: full device tree build over raw byte items.

    Bit-equal to `merkle.simple.simple_hash_from_byte_slices` (sha256 algo).
    """
    from tendermint_tpu.ops.padding import digests_to_bytes_be, pad_sha256
    from tendermint_tpu.ops.sha256_kernel import sha256_batch_jax

    if not items:
        return b""
    blocks, counts = pad_sha256([LEAF_PREFIX + x for x in items])
    leaf_digests = sha256_batch_jax(blocks, counts)
    root = merkle_root_from_leaf_words(leaf_digests)
    return digests_to_bytes_be(np.asarray(root)[None, :])[0]
