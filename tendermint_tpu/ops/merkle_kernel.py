"""Device Merkle tree reduction (log-depth, batched SHA-256 inner nodes).

Computes the same root as `tendermint_tpu.merkle.simple` (RFC 6962
largest-power-of-two split rule — the documented deviation from the
reference's ceil-split; see `merkle/simple.py` module docstring) via an
equivalent level-by-level pairing: at each level adjacent
nodes pair into an inner hash and an unpaired trailing node is promoted
unchanged. Each level is one batched 2-block SHA-256 over all pairs — the
whole tree is log2(N) kernel steps (reference hot spots: `types/block.go:177`,
`types/tx.go:33-46`, `types/part_set.go:95-122`).

Inner-node messages (0x01 || left32 || right32 = 65 bytes) are assembled
directly in u32 registers (byte-shift composition), so no host round-trip
happens between levels.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from tendermint_tpu.merkle.simple import INNER_PREFIX, LEAF_PREFIX
from tendermint_tpu.ops.sha256_kernel import sha256_fixed2_from_words

_B8 = np.uint32(8)
_B24 = np.uint32(24)
# INNER_PREFIX byte placed in the top byte of the first message word.
_INNER_PREFIX_WORD = np.uint32(INNER_PREFIX[0] << 24)


def _inner_node_words(L, R):
    """Build the two 16-word SHA-256 blocks for H(INNER_PREFIX || L || R).

    L, R: (B, 8) u32 big-endian digest words. The 1-byte domain prefix shifts
    every digest byte by one, so each message word mixes two source words.
    """
    w0 = []
    w0.append(jnp.uint32(_INNER_PREFIX_WORD) | (L[:, 0] >> _B8))
    for i in range(1, 8):
        w0.append((L[:, i - 1] << _B24) | (L[:, i] >> _B8))
    w0.append((L[:, 7] << _B24) | (R[:, 0] >> _B8))
    for i in range(1, 8):
        w0.append((R[:, i - 1] << _B24) | (R[:, i] >> _B8))
    block0 = jnp.stack(w0, axis=1)

    B = L.shape[0]
    zero = jnp.zeros((B,), dtype=jnp.uint32)
    w1 = [(R[:, 7] << _B24) | jnp.uint32(0x00800000)]
    w1 += [zero] * 14
    w1.append(jnp.full((B,), np.uint32(65 * 8), dtype=jnp.uint32))
    block1 = jnp.stack(w1, axis=1)
    return block0, block1


def inner_hash_device(L, R):
    """(B,8),(B,8) -> (B,8): batched domain-separated inner-node hash."""
    b0, b1 = _inner_node_words(L, R)
    return sha256_fixed2_from_words(b0, b1)


# numpy scalars, NOT jnp: module-level jnp calls initialize the XLA
# backend at import, which breaks jax.distributed.initialize for every
# later importer (multi-host workers must init before any backend use)
_B8_LE = np.uint32(8)
_B24_LE = np.uint32(24)


def _inner_node_words_ripemd(L, R):
    """(B,5),(B,5) u32 LE digests -> (B,16) u32 LE single-block message
    for H(0x01 || L20 || R20) = 41 bytes (fits one RIPEMD-160 block:
    0x80 at byte 41, bit length 328 LE at words 14-15)."""
    b = L.shape[0]
    w = [np.uint32(INNER_PREFIX[0]) | (L[:, 0] << _B8_LE)]
    for i in range(1, 5):
        w.append((L[:, i - 1] >> _B24_LE) | (L[:, i] << _B8_LE))
    w.append((L[:, 4] >> _B24_LE) | (R[:, 0] << _B8_LE))
    for i in range(1, 5):
        w.append((R[:, i - 1] >> _B24_LE) | (R[:, i] << _B8_LE))
    w.append((R[:, 4] >> _B24_LE) | jnp.uint32(0x80 << 8))
    zero = jnp.zeros((b,), dtype=jnp.uint32)
    w += [zero, zero, zero]
    w.append(jnp.full((b,), np.uint32(41 * 8), dtype=jnp.uint32))
    w.append(zero)
    return jnp.stack(w, axis=1)


def ripemd_inner_hash_device(L, R):
    """(B,5),(B,5) -> (B,5): batched RIPEMD-160 inner-node hash."""
    from tendermint_tpu.ops.ripemd160_kernel import _ripemd160_masked

    block = _inner_node_words_ripemd(L, R)
    ones = jnp.ones((L.shape[0],), dtype=jnp.int32)
    return _ripemd160_masked(block[:, None, :], ones, 1)


_ALGOS = {
    # algo -> (digest words, inner-node hash)
    "sha256": (8, inner_hash_device),
    "ripemd160": (5, ripemd_inner_hash_device),
}


def _forest_levels(nodes, cnt, levels: int, algo: str = "sha256"):
    """Shared level reduction: nodes (T, P, W) u32, cnt (T,) i32 valid leaf
    prefixes, P = 2**levels. Returns (T, W) root words. A pair exists only
    if its right child is inside the valid prefix; an unpaired trailing
    node is promoted (== left child unchanged)."""
    width, inner = _ALGOS[algo]
    t = nodes.shape[0]
    for _ in range(levels):
        left = nodes[:, 0::2]
        right = nodes[:, 1::2]
        half = left.shape[1]
        paired = inner(
            left.reshape(t * half, width), right.reshape(t * half, width)
        ).reshape(t, half, width)
        idx = jnp.arange(half, dtype=jnp.int32)
        nodes = jnp.where(
            (2 * idx[None, :] + 1 < cnt[:, None])[..., None], paired, left
        )
        cnt = (cnt + 1) // 2
    return nodes[:, 0]


@partial(jax.jit, static_argnames=("levels", "algo"))
def _tree_reduce(leaves, count, levels: int, algo: str = "sha256"):
    """leaves: (P, W) u32 with P = 2**levels; count: traced i32 valid prefix.
    Returns (W,) root words. The T=1 case of `_forest_levels`."""
    return _forest_levels(leaves[None], jnp.asarray(count)[None], levels, algo)[0]


def merkle_root_from_leaf_words(leaf_digests, count=None, algo: str = "sha256"):
    """Root from device leaf hashes.

    leaf_digests: (N, W) u32 (already leaf-prefixed hashes; W = 8 for
    sha256 BE words, 5 for ripemd160 LE words). N is padded up to the
    next power of two internally; `count` defaults to N.
    """
    width = _ALGOS[algo][0]
    leaf_digests = jnp.asarray(leaf_digests, dtype=jnp.uint32)
    n = leaf_digests.shape[0]
    if n == 0:
        raise ValueError(
            "empty leaf batch has no root (host simple_hash_from_hashes([]) is b'')"
        )
    if leaf_digests.shape[1] != width:
        raise ValueError(
            f"{algo} leaf digests must be (N, {width}) words, got {leaf_digests.shape}"
        )
    if count is None:
        count = n
    P = 1
    while P < n:
        P *= 2
    if P != n:
        pad = jnp.zeros((P - n, width), dtype=jnp.uint32)
        leaf_digests = jnp.concatenate([leaf_digests, pad], axis=0)
    levels = P.bit_length() - 1
    return _tree_reduce(
        leaf_digests, jnp.asarray(count, dtype=jnp.int32), levels, algo
    )


@partial(jax.jit, static_argnames=("max_blocks", "levels", "algo"))
def _leafhash_and_reduce(
    blocks, n_blocks, counts, max_blocks: int, levels: int, algo: str = "sha256"
):
    """Fused leaf hashing + forest reduction: ONE device launch.

    blocks:   (T, P, max_blocks, 16) u32 padded leaf messages, P = 2**levels
    n_blocks: (T, P) i32 per-leaf block counts (0 for pad rows)
    counts:   (T,) i32 valid leaf prefix per tree
    -> (T, 8) u32 root words.

    One launch matters: every executable execution through the axon
    tunnel costs ~86 ms wall-clock regardless of size (measured), so the
    leaf SHA-256 pass and all log2(P) tree levels must ship as a single
    executable rather than one call per stage.
    """
    t, p = blocks.shape[0], blocks.shape[1]
    flat = blocks.reshape(t * p, max_blocks, 16)
    if algo == "ripemd160":
        from tendermint_tpu.ops.ripemd160_kernel import _ripemd160_masked

        digs = _ripemd160_masked(flat, n_blocks.reshape(-1), max_blocks)
    else:
        from tendermint_tpu.ops.sha256_kernel import _sha256_masked

        digs = _sha256_masked(flat, n_blocks.reshape(-1), max_blocks)
    width = _ALGOS[algo][0]
    return _forest_levels(digs.reshape(t, p, width), counts, levels, algo)


def merkle_roots_forest(
    trees: list[list[bytes]], algo: str = "sha256"
) -> list[bytes]:
    """Batched device tree build: one root per item list, ONE device call.

    All trees pad to a common (P, max_blocks) shape — the fast-sync /
    mempool-flood shape (BASELINE config 4: batched Txs.Hash + PartSet
    roots) where many blocks' trees build concurrently. Bit-equal to
    `merkle.simple.simple_hash_from_byte_slices` per tree; `algo` picks
    sha256 (the framework's target variant) or ripemd160 (the
    reference's bit-compat variant, `docs/specification/merkle.rst`).
    """
    from tendermint_tpu.ops.padding import (
        bucket_blocks,
        digests_to_bytes_be,
        digests_to_bytes_le,
        pad_ripemd160_prefixed,
        pad_sha256_prefixed,
    )

    t = len(trees)
    if t == 0:
        return []
    counts = np.array([len(items) for items in trees], dtype=np.int32)
    if (counts == 0).any():
        raise ValueError("empty tree in forest (host root of [] is b'')")
    n_max = int(counts.max())
    p = 1
    while p < n_max:
        p *= 2
    levels = p.bit_length() - 1
    flat = [x for items in trees for x in items]
    if algo == "ripemd160":
        blocks, n_blocks = pad_ripemd160_prefixed(flat, LEAF_PREFIX)
        to_bytes = digests_to_bytes_le
    else:
        blocks, n_blocks = pad_sha256_prefixed(flat, LEAF_PREFIX)
        to_bytes = digests_to_bytes_be
    mb = blocks.shape[1]
    # bucket the forest size so varying tree counts reuse compiled shapes
    # (pad trees are all-masked rows; their garbage roots are sliced off)
    t_pad = bucket_blocks(t)
    all_blocks = np.zeros((t_pad, p, mb, 16), dtype=np.uint32)
    all_nblocks = np.zeros((t_pad, p), dtype=np.int32)
    all_counts = np.ones(t_pad, dtype=np.int32)
    all_counts[:t] = counts
    off = 0
    for i, c in enumerate(counts):
        all_blocks[i, :c] = blocks[off : off + c]
        all_nblocks[i, :c] = n_blocks[off : off + c]
        off += c
    roots = _leafhash_and_reduce(
        all_blocks, all_nblocks, all_counts, mb, levels, algo
    )
    return to_bytes(np.asarray(roots)[:t])


def merkle_root_device(items: list[bytes], algo: str = "sha256") -> bytes:
    """Host convenience: full device tree build over raw byte items.

    Bit-equal to `merkle.simple.simple_hash_from_byte_slices`.
    """
    if not items:
        return b""
    return merkle_roots_forest([items], algo)[0]


def leaf_hashes_sharded(items: list[bytes], algo: str, manager) -> list[bytes]:
    """`leaf_hashes_device` over a device mesh: the padded leaf messages
    are zero-row-padded to a multiple of the active mesh size and each
    chip hashes its shard in one launch (`parallel.mesh.MeshManager`
    owns compilation, shard-fault detection, and survivor re-mesh).
    Verdict-identical to the single-device lane; pad rows carry
    n_blocks=0 and are sliced off before returning.
    """
    from tendermint_tpu.ops.padding import (
        digests_to_bytes_be,
        digests_to_bytes_le,
        pad_ripemd160_prefixed,
        pad_rows_to_multiple,
        pad_sha256_prefixed,
    )
    from tendermint_tpu.utils.fail import ShardDeviceFault

    if not items:
        return []
    if algo == "ripemd160":
        blocks, n_blocks = pad_ripemd160_prefixed(items, LEAF_PREFIX)
        to_bytes = digests_to_bytes_le
    else:
        blocks, n_blocks = pad_sha256_prefixed(items, LEAF_PREFIX)
        to_bytes = digests_to_bytes_be
    manager.maybe_reprobe()
    while True:
        if manager.n_active == 0:
            from tendermint_tpu.parallel.mesh import MeshExhaustedError

            raise MeshExhaustedError(
                f"all {manager.n_total} mesh devices faulted"
            )
        try:
            manager.check_shard_faults()
            if manager.executor == "host":
                # choreography stand-in (CPU CI): identical outputs via
                # the host leaf hash, same fault/re-mesh cycle as above
                from tendermint_tpu.merkle import simple as host_merkle

                return [host_merkle.leaf_hash(x, algo) for x in items]
            (b_pad, n_pad), n = pad_rows_to_multiple(
                [blocks, n_blocks], manager.n_active
            )
            step = manager.leaf_hash_step(algo, blocks.shape[1])
            digs = step(b_pad, n_pad)
            # device observatory: the leaf lane's pad geometry +
            # shipped block bytes on the successful attempt only (a
            # shard-fault retry must not double-count)
            from tendermint_tpu.telemetry import launchlog as _launchlog

            _launchlog.annotate(
                _additive=True, rows_padded=b_pad.shape[0] - n
            )
            _launchlog.annotate(mesh_width=manager.n_active)
            _launchlog.add_transfer(b_pad.nbytes + n_pad.nbytes)
            return to_bytes(np.asarray(digs)[:n])
        except ShardDeviceFault as e:
            if not manager.record_shard_fault(e.shard):
                raise


def leaf_hashes_device(items: list[bytes], algo: str = "sha256") -> list[bytes]:
    """Domain-separated leaf hashes for every item in ONE batched device
    launch (bit-equal to `merkle.simple.leaf_hash` per item). The
    state-sync chunk verifier uses this to check received chunk windows
    against a manifest's hash list without a per-chunk host hash loop.
    """
    from tendermint_tpu.ops.padding import (
        digests_to_bytes_be,
        digests_to_bytes_le,
        pad_ripemd160_prefixed,
        pad_sha256_prefixed,
    )

    if not items:
        return []
    if algo == "ripemd160":
        from tendermint_tpu.ops.ripemd160_kernel import _ripemd160_masked

        blocks, n_blocks = pad_ripemd160_prefixed(items, LEAF_PREFIX)
        digs = _ripemd160_masked(blocks, n_blocks, blocks.shape[1])
        return digests_to_bytes_le(np.asarray(digs))
    from tendermint_tpu.ops.sha256_kernel import _sha256_masked

    blocks, n_blocks = pad_sha256_prefixed(items, LEAF_PREFIX)
    digs = _sha256_masked(blocks, n_blocks, blocks.shape[1])
    return digests_to_bytes_be(np.asarray(digs))
