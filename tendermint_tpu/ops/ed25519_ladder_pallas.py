"""Pallas fast path for the GENERIC double-scalar ladder.

The ad-hoc verify path (no cached valset tables — light-client first
contact, valset turnover beyond the cache, mixed-key batches; reference
`types/validator_set.go:284-349` VerifyCommitAny and every per-vote
check before tables exist) runs `ed25519_kernel.verify_kernel`'s
253-step Shamir ladder as a lax.scan, which round-trips the
4-coordinate accumulator through HBM on every step. This module runs
the same ladder as a Pallas kernel with the accumulator resident in
VMEM — the treatment that took the table path to 1.45M verifies/s
(`_fused_chain_pallas`), applied to the generic case.

Shape of the computation per lane:
  table = {O, B, -A, B-A} in affine ypx/ymx/t2d precomp form (built
  once per lane by XLA: one decompress + one point add + one batched
  inversion), then 253 identical steps of
      acc = madd(double(acc), table[s_bit + 2*h_bit])
  msb-first. Identity is the precomp (1, 1, 0), so selection is a
  4-way masked sum and every step is branch-free. The verdict is the
  table path's encode-and-compare (`_finish_encode_compare`) — R is
  never decompressed, halving the XLA prologue's sequential field work.

A width-2 windowed variant (127 steps, 16-entry table) was measured
SLOWER on this device (69k vs 91k @8k): the 16-way masked-sum select +
int16 conversions cost more than the madds it saves. Bit-serial with a
4-way select is the keeper (docs/PLATFORM_NOTES.md).

Tiles are as wide as VMEM allows (up to 4096 lanes -> (8, 512) planes):
fewer, fatter grid steps amortize Mosaic's per-step overhead the same
way the fused table kernel scales plane width with the commit stack.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from tendermint_tpu.ops.ed25519_kernel import (
    BX,
    BY,
    D2,
    NLIMBS,
    P,
    SCALAR_BITS,
    _int_to_limbs,
    fe_canon,
    fe_carry,
    fe_mul,
    fe_sub,
    pt_add,
    pt_decompress,
    pt_neg,
)
from tendermint_tpu.ops.ed25519_tables import (
    _addc_planes,
    _carry_planes,
    _finish_encode_compare,
    _madd_planes,
    _mul_planes,
    _sub_planes,
    fe_batch_invert,
)

# base-point precomp constants (host python ints -> limb arrays)
_YPX_B = _int_to_limbs((BY + BX) % P)
_YMX_B = _int_to_limbs((BY - BX) % P)
_T2D_B = _int_to_limbs(D2 * BX * BY % P)
_ONE = _int_to_limbs(1)

# widest tile whose working set (4x60-plane table + 80-plane acc +
# out block, int32) stays well inside ~16 MB VMEM: 4096 lanes ->
# (8, 512) planes -> ~4 MB table + ~2.6 MB scratch
MAX_TILE_LANES = 4096
MIN_LANES = 1024  # smallest plane geometry (8, 128)


def use_pallas_ladder(padded_size: int) -> bool:
    """THE routing rule for generic verifies — shared by batch_verify
    and bench so they can't drift: pallas ladder iff the padded bucket
    clears the plane geometry and a TPU is the backend."""
    import jax

    return padded_size >= MIN_LANES and jax.default_backend() == "tpu"


def _sq_planes(a):
    return _mul_planes(a, a)


def _double_planes(acc):
    """dbl-2008-hwcd (a=-1) on plane lists — mirrors pt_double exactly."""
    x1, y1, z1, _t1 = acc
    a = _sq_planes(x1)
    b = _sq_planes(y1)
    c = _carry_planes([2 * v for v in _sq_planes(z1)])
    h = _addc_planes(a, b)
    e = _sub_planes(h, _sq_planes(_addc_planes(x1, y1)))
    g = _sub_planes(a, b)
    f = _addc_planes(c, g)
    return (
        _mul_planes(e, f),
        _mul_planes(g, h),
        _mul_planes(f, g),
        _mul_planes(e, h),
    )


def _make_ladder_kernel(w: int):
    from jax.experimental import pallas as pl

    def kernel(gtab_ref, dig_ref, out_ref, acc_ref):
        t = pl.program_id(1)

        @pl.when(t == 0)
        def _():
            # extended identity (0, 1, 1, 0): Y limb 0 and Z limb 0 are 1
            rows = jax.lax.broadcasted_iota(jnp.int32, (80, 8, w), 0)
            acc_ref[:] = jnp.where((rows == 20) | (rows == 40), 1, 0)

        acc = tuple(
            [acc_ref[20 * ci + i] for i in range(20)] for ci in range(4)
        )
        acc = _double_planes(acc)

        dig = dig_ref[0, 0]  # (8, w) int32 in {0..3}, this step's selector
        gt = gtab_ref[0]  # (4, 60, 8, w) — this tile's per-lane entries
        masks = [dig == d for d in range(4)]
        ent = []
        for limb in range(60):
            v = jnp.where(masks[0], gt[0, limb], 0)
            for d in range(1, 4):
                v = v + jnp.where(masks[d], gt[d, limb], 0)
            ent.append(v)
        nxt = _madd_planes(acc, ent[:20], ent[20:40], ent[40:])
        acc_ref[:] = jnp.stack([p for coord in nxt for p in coord])

        @pl.when(t == SCALAR_BITS - 1)
        def _():
            out_ref[0] = acc_ref[:]

    return kernel


def _tile_lanes(bsz: int) -> int:
    t = MAX_TILE_LANES
    while bsz % t != 0:
        t //= 2
    if t < MIN_LANES:
        raise ValueError(f"batch {bsz} must be a multiple of {MIN_LANES}")
    return t


def _ladder_pallas(gtab, digits, w, interpret=False):
    """gtab (tiles, 4, 60, 8, w) int32, digits (tiles, 253, 8, w) int32
    -> extended acc coords, each (B, 20) int32 (lane-major)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    tiles = gtab.shape[0]
    out = pl.pallas_call(
        _make_ladder_kernel(w),
        grid=(tiles, SCALAR_BITS),
        in_specs=[
            pl.BlockSpec(
                (1, 4, 60, 8, w),
                lambda i, t: (i, 0, 0, 0, 0),  # resident across all steps
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, 1, 8, w),
                lambda i, t: (i, t, 0, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, 80, 8, w), lambda i, t: (i, 0, 0, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((tiles, 80, 8, w), jnp.int32),
        scratch_shapes=[pltpu.VMEM((80, 8, w), jnp.int32)],
        interpret=interpret,
    )(gtab, digits)
    coords = out.reshape(tiles, 4, 20, 8, w)
    coords = jnp.transpose(coords, (1, 0, 3, 4, 2)).reshape(4, -1, NLIMBS)
    return coords[0], coords[1], coords[2], coords[3]


def _affine_precomp(x, y):
    """Affine (x, y) -> (ypx, ymx, t2d) limbs, each (B, 20) carried."""
    ypx = fe_canon(fe_carry(y + x))
    ymx = fe_canon(fe_sub(y, x))
    t2d = fe_canon(fe_mul(fe_mul(x, y), jnp.asarray(_int_to_limbs(D2))))
    return ypx, ymx, t2d


def _ladder_digits(s_bytes, h_bytes):
    """(B, 32) uint8 LE scalars -> (B, 253) int32 selectors, msb-first:
    column t is s_bit(252-t) + 2*h_bit(252-t) — step t of the ladder
    adds table entry [selector_t] after the doubling."""
    s = s_bytes.astype(jnp.int32)
    h = h_bytes.astype(jnp.int32)
    cols = []
    for t in range(SCALAR_BITS):
        j = SCALAR_BITS - 1 - t
        sb = (s[:, j // 8] >> (j % 8)) & 1
        hb = (h[:, j // 8] >> (j % 8)) & 1
        cols.append(sb + 2 * hb)
    return jnp.stack(cols, axis=-1)


def _build_inputs(pub_bytes, s_bytes, h_bytes, tile):
    """XLA prologue: per-lane precomp tables + selection digits.

    Returns (gtab (tiles, 4, 60, 8, w) int32, dig (tiles, 253, 8, w)
    int32, a_ok (B,) bool). Unjitted-callable so tests can gate the
    ladder algorithm eagerly without tracing 253 unrolled steps."""
    bsz = pub_bytes.shape[0]
    w = tile // 8
    a_pt, a_ok = pt_decompress(pub_bytes)

    # per-lane table entries in affine precomp form
    neg_a = pt_neg(a_pt)  # Z = 1: already affine
    e2 = _affine_precomp(neg_a[0], neg_a[1])
    shape = pub_bytes.shape[:-1] + (NLIMBS,)
    b_pt = (
        jnp.broadcast_to(jnp.asarray(_int_to_limbs(BX)), shape).astype(jnp.int32),
        jnp.broadcast_to(jnp.asarray(_int_to_limbs(BY)), shape).astype(jnp.int32),
        jnp.broadcast_to(jnp.asarray(_ONE), shape).astype(jnp.int32),
        jnp.broadcast_to(
            jnp.asarray(_int_to_limbs(BX * BY % P)), shape
        ).astype(jnp.int32),
    )
    t3 = pt_add(b_pt, neg_a)  # B - A, projective
    zinv = fe_batch_invert(fe_carry(t3[2]))
    e3 = _affine_precomp(fe_mul(t3[0], zinv), fe_mul(t3[1], zinv))
    e1 = tuple(
        jnp.broadcast_to(jnp.asarray(c), shape).astype(jnp.int32)
        for c in (_YPX_B, _YMX_B, _T2D_B)
    )
    zero = jnp.zeros(shape, dtype=jnp.int32)
    one = jnp.broadcast_to(jnp.asarray(_ONE), shape).astype(jnp.int32)
    e0 = (one, one, zero)

    # (4, B, 60) entry-major -> (tiles, 4, 60, 8, w)
    gtab = jnp.stack(
        [jnp.concatenate(e, axis=-1) for e in (e0, e1, e2, e3)]
    )
    tiles = bsz // tile
    gtab = gtab.reshape(4, tiles, 8, w, 60)
    gtab = jnp.transpose(gtab, (1, 0, 4, 2, 3))

    dig = _ladder_digits(s_bytes, h_bytes)  # (B, 253)
    dig = dig.reshape(tiles, 8, w, SCALAR_BITS)
    dig = jnp.transpose(dig, (0, 3, 1, 2))
    return gtab, dig, a_ok


@partial(jax.jit, static_argnames=("interpret",))
def verify_kernel_pallas(pub_bytes, r_bytes, s_bytes, h_bytes, interpret=False):
    """Drop-in for `ed25519_kernel.verify_kernel` (B % 1024 == 0):
    same inputs, same cofactorless [S]B + [h](-A) == R verdicts (via
    byte-compare against the R encoding, so R is never decompressed)."""
    tile = _tile_lanes(pub_bytes.shape[0])
    gtab, dig, a_ok = _build_inputs(pub_bytes, s_bytes, h_bytes, tile)
    x, y, z, _t = _ladder_pallas(gtab, dig, tile // 8, interpret=interpret)
    return _finish_encode_compare(x, y, z, r_bytes.astype(jnp.int32)) & a_ok
