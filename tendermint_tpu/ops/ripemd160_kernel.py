"""Batched RIPEMD-160 in JAX (u32 lanes).

The reference's Merkle/part/address hash (`types/part_set.go:36-40`,
`docs/specification/merkle.rst`). Kept as the bit-compatibility variant next
to the SHA-256 target kernel. Dual-line ARX structure (ISO/IEC 10118-3): each
of the 5 round groups runs as a 16-step `lax.scan` with the group's static
boolean function; message-word selection uses per-step gathers.
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from tendermint_tpu.ops.sha256_kernel import _icbrt

# Message word order per 16-step round group (left line, right line).
_RL = [
    list(range(16)),
    [7, 4, 13, 1, 10, 6, 15, 3, 12, 0, 9, 5, 2, 14, 11, 8],
    [3, 10, 14, 4, 9, 15, 8, 1, 2, 7, 0, 6, 13, 11, 5, 12],
    [1, 9, 11, 10, 0, 8, 12, 4, 13, 3, 7, 15, 14, 5, 6, 2],
    [4, 0, 5, 9, 7, 12, 2, 10, 14, 1, 3, 8, 11, 6, 15, 13],
]
_RR = [
    [5, 14, 7, 0, 9, 2, 11, 4, 13, 6, 15, 8, 1, 10, 3, 12],
    [6, 11, 3, 7, 0, 13, 5, 10, 14, 15, 8, 12, 4, 9, 1, 2],
    [15, 5, 1, 3, 7, 14, 6, 9, 11, 8, 12, 2, 10, 0, 4, 13],
    [8, 6, 4, 1, 3, 11, 15, 0, 5, 12, 2, 13, 9, 7, 10, 14],
    [12, 15, 10, 4, 1, 5, 8, 7, 6, 2, 13, 14, 0, 3, 9, 11],
]
# Rotation amounts.
_SL = [
    [11, 14, 15, 12, 5, 8, 7, 9, 11, 13, 14, 15, 6, 7, 9, 8],
    [7, 6, 8, 13, 11, 9, 7, 15, 7, 12, 15, 9, 11, 7, 13, 12],
    [11, 13, 6, 7, 14, 9, 13, 15, 14, 8, 13, 6, 5, 12, 7, 5],
    [11, 12, 14, 15, 14, 15, 9, 8, 9, 14, 5, 6, 8, 6, 5, 12],
    [9, 15, 5, 11, 6, 8, 13, 12, 5, 12, 13, 14, 11, 8, 5, 6],
]
_SR = [
    [8, 9, 9, 11, 13, 15, 15, 5, 7, 7, 8, 11, 14, 14, 12, 6],
    [9, 13, 15, 7, 12, 8, 9, 11, 7, 7, 12, 7, 6, 15, 13, 11],
    [9, 7, 15, 11, 8, 6, 6, 14, 12, 13, 5, 14, 13, 13, 7, 5],
    [15, 5, 8, 11, 14, 14, 6, 14, 6, 9, 12, 9, 12, 5, 15, 8],
    [8, 5, 12, 9, 12, 5, 14, 6, 8, 13, 6, 5, 15, 13, 11, 11],
]
# Round constants: left = floor(2^30*sqrt(2,3,5,7)), right = floor(2^30*cbrt(2,3,5,7)).
_KL = [0] + [math.isqrt(p << 60) for p in (2, 3, 5, 7)]
_KR = [_icbrt(p << 90) for p in (2, 3, 5, 7)] + [0]

_H0 = np.array([0x67452301, 0xEFCDAB89, 0x98BADCFE, 0x10325476, 0xC3D2E1F0], dtype=np.uint32)


def _rotl_dyn(x, n):
    """Rotate-left by a per-step traced amount (1 <= n <= 31)."""
    n = n.astype(jnp.uint32)
    return (x << n) | (x >> (jnp.uint32(32) - n))


def _rotl10(x):
    return (x << jnp.uint32(10)) | (x >> jnp.uint32(22))


def _f(j: int, x, y, z):
    if j == 0:
        return x ^ y ^ z
    if j == 1:
        return (x & y) | (~x & z)
    if j == 2:
        return (x | ~y) ^ z
    if j == 3:
        return (x & z) | (y & ~z)
    return x ^ (y | ~z)


def _line(rnd_fns, R_TAB, S_TAB, K_TAB, X, state5):
    """Run one full 80-step line. X: (B, 16); state5: 5×(B,)."""
    a, b, c, d, e = state5
    for grp in range(5):
        xs = (
            jnp.asarray(R_TAB[grp], dtype=jnp.int32),
            jnp.asarray(S_TAB[grp], dtype=jnp.uint32),
        )
        k = np.uint32(K_TAB[grp])
        fn = rnd_fns[grp]

        def step(regs, xs, fn=fn, k=k):
            a, b, c, d, e = regs
            r_idx, s_amt = xs
            t = _rotl_dyn(a + fn(b, c, d) + X[:, r_idx] + k, s_amt) + e
            return (e, t, b, _rotl10(c), d), None

        (a, b, c, d, e), _ = lax.scan(step, (a, b, c, d, e), xs)
        # note: step returns (a', b', c', d', e') = (e, t, b, rotl10(c), d)
    return a, b, c, d, e


def _compress160(state, w_block):
    """state: (B, 5) u32; w_block: (B, 16) u32 little-endian words."""
    left_fns = [lambda x, y, z, j=j: _f(j, x, y, z) for j in range(5)]
    right_fns = [lambda x, y, z, j=j: _f(4 - j, x, y, z) for j in range(5)]
    init = tuple(state[:, i] for i in range(5))
    al, bl, cl, dl, el = _line(left_fns, _RL, _SL, _KL, w_block, init)
    ar, br, cr, dr, er = _line(right_fns, _RR, _SR, _KR, w_block, init)
    h0, h1, h2, h3, h4 = init
    return jnp.stack(
        [h1 + cl + dr, h2 + dl + er, h3 + el + ar, h4 + al + br, h0 + bl + cr],
        axis=1,
    )


@partial(jax.jit, static_argnames=("max_blocks",))
def _ripemd160_masked(blocks, n_blocks, max_blocks: int):
    B = blocks.shape[0]
    state0 = jnp.broadcast_to(jnp.asarray(_H0), (B, 5)).astype(jnp.uint32)

    def block_step(state, xs):
        w_block, j = xs
        new_state = _compress160(state, w_block)
        return jnp.where((j < n_blocks)[:, None], new_state, state), None

    xs = (jnp.swapaxes(blocks, 0, 1), jnp.arange(max_blocks, dtype=jnp.int32))
    state, _ = lax.scan(block_step, state0, xs)
    return state


def ripemd160_batch_jax(blocks, n_blocks):
    """blocks: (B, max_blocks, 16) u32 LE words; n_blocks: (B,) i32.
    Returns (B, 5) u32 digests (little-endian words)."""
    blocks = jnp.asarray(blocks, dtype=jnp.uint32)
    n_blocks = jnp.asarray(n_blocks, dtype=jnp.int32)
    return _ripemd160_masked(blocks, n_blocks, blocks.shape[1])
