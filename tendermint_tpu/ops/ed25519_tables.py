"""Table-driven batched ed25519 verification: the steady-state fast path.

The round-1 kernel (`ed25519_kernel.verify_kernel`) runs a generic
253-step Shamir ladder per signature (~4.8k field muls). But consensus,
fast-sync and the light client verify commits signed by a KNOWN validator
set that changes rarely (reference hot loops: `types/validator_set.go:
236-261`, `types/vote_set.go:137-196`; SURVEY.md §7 hard part 4 calls for
pre-staged validator-set device arrays cached by valset hash). This module
exploits that:

* **[S]B — fixed-base comb.** B is a compile-time constant: a w=8 table
  (32 windows x 256 entries = 8192 precomputed points, ~2.6 MB) turns
  [S]B into 32 mixed adds with zero doublings.
* **[h]A — cached per-validator window tables.** A per-validator w=4
  table (64 windows x 16 entries) is built ON DEVICE once per validator
  set, then every verification of that validator is 64 mixed adds with
  zero doublings and no point decompression.
* **No R decompression.** Like the reference's verifier (Go ed25519
  computes R' = [S]B - [h]A and byte-compares with sig[:32]), we encode
  the computed point and compare bytes. Affine normalization uses a
  log-depth batched tree inversion (~3 muls/signature amortized instead
  of ~265 for a per-lane inversion).
* Mixed additions use precomputed affine entries (y+x, y-x, 2d*x*y):
  7 field muls each (madd-2008-hwcd-3, a=-1) vs 9 for the unified add.

Net: ~0.7k field muls/signature vs ~4.8k for the generic ladder, with
identical per-signature verdict semantics (bad signatures localize).

Tables hold multiples of -A so the device accumulates
[S]B + [h](-A) and checks its encoding equals sig[:32].

Two device pipelines share these tables:

* the FUSED pallas kernel (production, TPU): selection happens inside
  the kernel from int16 table blocks, the accumulator lives in VMEM,
  and the table streams from HBM exactly once per launch — see the
  "fused select+accumulate" section below and
  docs/PLATFORM_NOTES.md for measured rates (1.44M verifies/s at
  K=64 x 10,240 on the bench chip);
* the materialized-entries path (XLA scan or the earlier pallas madd
  chain): portable, used for shapes that don't tile the fused kernel
  (single commits, tiny valsets) and by the CPU test mesh.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from tendermint_tpu.ops.ed25519_kernel import (
    BX,
    BY,
    D,
    D2,
    NLIMBS,
    P,
    _D2_L,
    _ONE_L,
    _int_to_limbs,
    fe_canon,
    fe_carry,
    fe_invert,
    fe_mul,
    fe_sub,
    fe_to_bytes,
    pt_add,
    pt_decompress,
    pt_double,
    pt_neg,
)

A_WINDOW = 4  # per-validator tables: 64 windows x 16 entries
A_NWIN = 64
B_NWIN = 32  # fixed-base table: 32 windows x 256 entries (w=8, XLA path)
SB_NWIN = 64  # fixed-base table: 64 windows x 16 entries (w=4, fused path)


# -- host EC over Python ints (B-table build + tests) -------------------------


def _hadd(p, q):
    """Extended twisted-Edwards add (a=-1), Python ints."""
    x1, y1, z1, t1 = p
    x2, y2, z2, t2 = q
    a = (y1 - x1) * (y2 - x2) % P
    b = (y1 + x1) * (y2 + x2) % P
    c = t1 * D2 % P * t2 % P
    d = 2 * z1 * z2 % P
    e, f, g, h = b - a, d - c, d + c, b + a
    return (e * f % P, g * h % P, f * g % P, e * h % P)


_H_IDENT = (0, 1, 1, 0)
_B_EXT = (BX, BY, 1, BX * BY % P)


def host_scalar_mul(k: int, p) -> tuple[int, int, int, int]:
    """[k]P by double-and-add over Python ints (tests / cross-checks)."""
    acc = _H_IDENT
    while k:
        if k & 1:
            acc = _hadd(acc, p)
        p = _hadd(p, p)
        k >>= 1
    return acc


def host_affine(p) -> tuple[int, int]:
    x, y, z, _ = p
    zi = pow(z, P - 2, P)
    return (x * zi % P, y * zi % P)


def _precomp_limbs(x: int, y: int) -> np.ndarray:
    """Affine point -> (3, 20) int32 precomp form (y+x, y-x, 2d*x*y)."""
    return np.stack(
        [
            _int_to_limbs((y + x) % P),
            _int_to_limbs((y - x) % P),
            _int_to_limbs(2 * D * x % P * y % P),
        ]
    )


def _host_decompress(pub: bytes) -> tuple[int, int] | None:
    """RFC 8032 point decoding over Python ints (host build path)."""
    from tendermint_tpu.ops.ed25519_kernel import SQRT_M1

    enc = int.from_bytes(pub, "little")
    sign = enc >> 255
    y = enc & ((1 << 255) - 1)
    if y >= P:
        return None
    y2 = y * y % P
    u = (y2 - 1) % P
    v = (D * y2 + 1) % P
    x = u * pow(v, 3, P) % P * pow(u * pow(v, 7, P) % P, (P - 5) // 8, P) % P
    if v * x * x % P != u:
        if v * x * x % P == (P - u) % P:
            x = x * SQRT_M1 % P
        else:
            return None
    if x == 0 and sign:
        return None
    if (x & 1) != sign:
        x = P - x
    return x, y


def host_build_key_tables(pubkeys) -> tuple[np.ndarray, np.ndarray]:
    """Python-int table build: same layout as build_key_tables
    ((64, 16, 60, N) int16 window/digit/limb/validator tables of -A
    multiples, (N,) ok) without compiling the device build kernel.
    Intended for small N (tests, the multichip dryrun); one Montgomery
    batched inversion per key normalizes all 960 entries.

    Invalid pubkey encodings get identity-entry columns and ok=False.
    An identity column degrades the check to encode([S]B) == R, which an
    attacker CAN satisfy — callers must AND key_ok into every verdict
    (the service layer and sharded step's lane_ok input both do)."""
    n = len(pubkeys)
    ok = np.zeros(n, dtype=bool)
    tbl = np.zeros((A_NWIN, 16, 3 * NLIMBS, n), dtype=np.int16)
    ident_entry = _precomp_limbs(0, 1).reshape(-1)
    for col, pk in enumerate(pubkeys):
        aff = _host_decompress(bytes(pk)) if len(pk) == 32 else None
        if aff is None:
            tbl[:, :, :, col] = ident_entry[None, None, :]
            continue
        ok[col] = True
        x, y = aff
        nx = (P - x) % P  # tables hold multiples of -A
        base = (nx, y, 1, nx * y % P)
        rows: list[tuple[int, int]] = []  # (window, digit) per entry
        entries: list[tuple[int, int, int, int]] = []
        for w in range(A_NWIN):
            e = _H_IDENT
            for d in range(16):
                if d == 0:
                    tbl[w, 0, :, col] = ident_entry
                else:
                    rows.append((w, d))
                    entries.append(e)
                e = _hadd(e, base)
            for _ in range(A_WINDOW):
                base = _hadd(base, base)
        # batched affine normalization (Montgomery trick): 1 modexp/key
        prefix = [1]
        for pt in entries:
            prefix.append(prefix[-1] * pt[2] % P)
        inv = pow(prefix[-1], P - 2, P)
        for i in reversed(range(len(entries))):
            zi = inv * prefix[i] % P
            inv = inv * entries[i][2] % P
            ex, ey = entries[i][0] * zi % P, entries[i][1] * zi % P
            w, d = rows[i]
            tbl[w, d, :, col] = _precomp_limbs(ex, ey).reshape(-1)
    return tbl, ok


_SB_TABLE: np.ndarray | None = None


def sb_table_w4() -> np.ndarray:
    """w=4 fixed-base comb: (64, 16, 60) int32; [w, j] holds
    j * 2^(4w) * B in affine precomp form (ypx|ymx|t2d). Used by the
    fused pallas path, whose per-step selection is a 16-way masked sum —
    w=4 for BOTH scalars makes every one of its 128 steps identical
    (the XLA path keeps the w=8 b_table and 96 steps instead)."""
    global _SB_TABLE
    if _SB_TABLE is not None:
        return _SB_TABLE
    entries = []
    base = _B_EXT
    for _ in range(SB_NWIN):
        e = _H_IDENT
        for _j in range(16):
            entries.append(e)
            e = _hadd(e, base)
        for _ in range(4):
            base = _hadd(base, base)
    prefix = [1]
    for pt in entries:
        prefix.append(prefix[-1] * pt[2] % P)
    inv = pow(prefix[-1], P - 2, P)
    out = np.zeros((len(entries), 3 * NLIMBS), dtype=np.int32)
    for i in reversed(range(len(entries))):
        zi = inv * prefix[i] % P
        inv = inv * entries[i][2] % P
        x, y = entries[i][0] * zi % P, entries[i][1] * zi % P
        out[i] = _precomp_limbs(x, y).reshape(-1)
    _SB_TABLE = out.reshape(SB_NWIN, 16, 3 * NLIMBS)
    return _SB_TABLE


_B_TABLE: np.ndarray | None = None


def b_table() -> np.ndarray:
    """Fixed-base table: (B_NWIN*256, 3, 20) int32; entry [w*256+j] holds
    j * 2^(8w) * B in affine precomp form. Built lazily once per process
    (~8k host point adds + one Montgomery batched inversion)."""
    global _B_TABLE
    if _B_TABLE is not None:
        return _B_TABLE
    entries = []  # extended points, Python ints
    base = _B_EXT
    for _ in range(B_NWIN):
        e = _H_IDENT
        for _j in range(256):
            entries.append(e)
            e = _hadd(e, base)
        for _ in range(8):
            base = _hadd(base, base)
    # batched affine normalization (Montgomery trick)
    zs = [p[2] for p in entries]
    prefix = [1]
    for z in zs:
        prefix.append(prefix[-1] * z % P)
    inv = pow(prefix[-1], P - 2, P)
    out = np.zeros((len(entries), 3, NLIMBS), dtype=np.int32)
    for i in reversed(range(len(entries))):
        zi = inv * prefix[i] % P
        inv = inv * zs[i] % P
        x, y = entries[i][0] * zi % P, entries[i][1] * zi % P
        out[i] = _precomp_limbs(x, y)
    _B_TABLE = out
    return out


# -- device primitives --------------------------------------------------------


def pt_madd(acc, entry):
    """Mixed add: extended acc + affine precomp entry (ypx, ymx, t2d).

    madd-2008-hwcd-3 with a=-1 and Z2=1: 7 muls. Entry limbs are
    canonical (< 2^13), acc limbs loose — both satisfy fe_mul's bound.
    """
    x1, y1, z1, t1 = acc
    ypx, ymx, t2d = entry
    a = fe_mul(fe_sub(y1, x1), ymx)
    b = fe_mul(fe_carry(y1 + x1), ypx)
    c = fe_mul(t1, t2d)
    d = fe_carry(z1 + z1)
    e = fe_sub(b, a)
    f = fe_sub(d, c)
    g = fe_carry(d + c)
    h = fe_carry(b + a)
    return (fe_mul(e, f), fe_mul(g, h), fe_mul(f, g), fe_mul(e, h))


def fe_batch_invert(z):
    """Invert every row of z (M, 20) via a log-depth product tree:
    ~3 muls per element + ONE fe_invert total (vs ~265 muls per element
    for per-lane inversion). Non-power-of-two M is padded with ones (a
    fixed point of inversion) so the tree halves evenly. Zero inputs are
    the caller's responsibility (Z of a valid point is never 0)."""
    m = z.shape[0]
    padded = 1
    while padded < m:
        padded *= 2
    if padded != m:
        ones = jnp.broadcast_to(
            jnp.asarray(_ONE_L), (padded - m, NLIMBS)
        ).astype(z.dtype)
        z = jnp.concatenate([z, ones], axis=0)
    levels = []
    cur = z
    while cur.shape[0] > 1:
        levels.append(cur)
        cur = fe_mul(cur[0::2], cur[1::2])
    inv = fe_invert(cur)
    for lev in reversed(levels):
        left, right = lev[0::2], lev[1::2]
        inv_left = fe_mul(inv, right)
        inv_right = fe_mul(inv, left)
        inv = jnp.stack([inv_left, inv_right], axis=1).reshape(lev.shape)
    return inv[:m]


def _identity_like(ref):
    """Extended identity (0,1,1,0) built FROM an input array so the scan
    carry is device-varying under shard_map (same trick as the generic
    kernel's ladder)."""
    vzero = (ref[..., :1] * 0).astype(jnp.int32)
    zero = vzero + jnp.zeros(NLIMBS, dtype=jnp.int32)
    one = vzero + jnp.asarray(_ONE_L)
    return (zero, one, one, zero)


# encoding of the identity point (y=1, x=0): decompresses cleanly, used
# as padding so padded build lanes stay on-curve
_IDENT_PUB = np.zeros((1, 32), dtype=np.uint8)
_IDENT_PUB[0, 0] = 1


# -- table build (device) -----------------------------------------------------


@jax.jit
def _build_tables_kernel(pub_bytes):
    """(N, 32) uint8 pubkeys -> ((1024, N, 60) int32 tables, (N,) ok).

    Window-major layout: row (window*16 + digit) column n holds
    digit * 2^(4*window) * (-A_n) in affine precomp form (ypx|ymx|t2d
    flattened to 60 limbs). Window-major keeps per-window slices on the
    MAJOR axis so the gather-free selection pass never forces a padded
    transpose of the whole table (minor dims of 16 tile to 128 and would
    8x the table's footprint). N must be a power of two (callers pad) so
    the entry count feeds the inversion tree exactly.
    """
    a_pt, ok = pt_decompress(pub_bytes)
    w0 = pt_neg(a_pt)  # tables hold multiples of -A

    def outer(w, _):
        def add_step(e, _x):
            e2 = pt_add(e, w)
            return e2, e2

        ident = _identity_like(w[0])
        _, steps = lax.scan(add_step, ident, None, length=15)
        # entries: identity + the 15 partial sums -> (16, N, 20) per coord
        entries = tuple(
            jnp.concatenate([iv[None], st], axis=0)
            for iv, st in zip(ident, steps)
        )
        nxt = w
        for _i in range(A_WINDOW):
            nxt = pt_double(nxt)
        return nxt, entries

    _, ent = lax.scan(outer, w0, None, length=A_NWIN)
    # ent: 4 arrays of (64, 16, N, 20) -> flatten the entry dimension
    ex, ey, ez, _et = (e.reshape(-1, NLIMBS) for e in ent)
    zinv = fe_batch_invert(fe_carry(ez))
    ax = fe_mul(ex, zinv)
    ay = fe_mul(ey, zinv)
    ypx = fe_canon(fe_carry(ay + ax))
    ymx = fe_canon(fe_sub(ay, ax))
    t2d = fe_canon(fe_mul(fe_mul(ax, ay), jnp.asarray(_D2_L)))
    n = pub_bytes.shape[0]
    # (64*16*N, 20) each, in (window, digit, val) order -> (1024, N, 60)
    tbl = jnp.stack([ypx, ymx, t2d], axis=-2).reshape(
        A_NWIN * 16, n, 3 * NLIMBS
    )
    return tbl, ok


@jax.jit
def _to_fused_layout(tbl):
    """(1024, M, 60) int32 -> (64, 16, 60, M) int16 canonical table form.

    Canonical entry limbs are in [0, 2^13) so int16 is lossless; halving
    the bytes halves the fused kernel's dominant HBM stream (the table
    is read once per verify launch)."""
    m = tbl.shape[1]
    return jnp.transpose(
        tbl.reshape(A_NWIN, 16, m, 3 * NLIMBS), (0, 1, 3, 2)
    ).astype(jnp.int16)


def build_key_tables(pub_bytes: np.ndarray, chunk: int = 2048):
    """Build per-validator window tables on device, chunked to bound peak
    memory (each chunk materializes chunk*1024 extended points).

    pub_bytes: (N, 32) uint8. Returns (tables (64, 16, 60, N) int16 on
    device — window, digit, limb, validator — and ok (N,) bool on host).

    On TPU every chunk pads to the FULL chunk size so all builds of any
    N share ONE compiled executable — a fresh pow2 shape would pay its
    own ~20 s per-process program upload (docs/PLATFORM_NOTES.md), while
    the extra pad columns cost <1 s of device work. Off-TPU (tests) the
    pad stays at the next power of two."""
    n = pub_bytes.shape[0]
    on_tpu = jax.default_backend() == "tpu"
    tbls, oks = [], []
    for lo in range(0, n, chunk):
        part = np.asarray(pub_bytes[lo : lo + chunk], dtype=np.uint8)
        m = part.shape[0]
        if on_tpu:
            padded = chunk
        else:
            padded = 1
            while padded < m:
                padded *= 2
        if padded != m:
            part = np.concatenate(
                [part, np.tile(_IDENT_PUB, (padded - m, 1))], axis=0
            )
        t, ok = _build_tables_kernel(jnp.asarray(part))
        # layout-convert at the padded shape, slice after: slicing first
        # would give _to_fused_layout a fresh executable per residual m
        tbls.append(_to_fused_layout(t)[..., :m])
        oks.append(np.asarray(ok)[:m])
    return jnp.concatenate(tbls, axis=3), np.concatenate(oks)


# -- verification (device) ----------------------------------------------------
#
# TPU gathers are slow (measured ~60x the cost of the arithmetic they
# feed), so table entries are selected WITHOUT gathers: one-hot f32
# matmuls ride the MXU (table limbs < 2^13 and one-hot rows have a single
# nonzero, so f32 accumulation is exact). The 96 sequential mixed adds
# then run either as an XLA scan (portable; CPU tests) or as a Pallas
# kernel that keeps the accumulator in VMEM across all steps (TPU fast
# path — XLA's scan materializes the carry through HBM every step).

NSTEPS = B_NWIN + A_NWIN  # 96 mixed adds per signature


def _select_entries(a_tables, s, h):
    """Gather-free operand selection -> (NSTEPS, B, 60) int32.

    a_tables: (64, 16, 60, N) canonical form (converted to the
    window-major (1024, N, 60) int32 this path indexes); lane b uses
    table column (b mod N), so one validator set verifies K stacked
    commits with B = K*N lanes. Selection is 16 fused mask-multiplies
    per window — the whole table streams through the VPU exactly once
    (a true gather would be ~60x slower on TPU, measured).
    """
    bsz = s.shape[0]
    n_vals = a_tables.shape[3]
    reps = bsz // n_vals
    btab = jnp.asarray(b_table()).reshape(B_NWIN, 256, 60).astype(jnp.float32)
    outs = []
    for w in range(B_NWIN):
        oh = (s[:, w : w + 1] == jnp.arange(256)[None, :]).astype(jnp.float32)
        # precision=HIGHEST: TPU matmuls default to bf16 operand passes,
        # which truncates 13-bit table limbs and corrupts every entry
        # (one-hot selection needs the full f32 mantissa, which HIGHEST's
        # multi-pass f32 guarantees; accumulation of a single nonzero
        # term is then exact).
        outs.append(
            jnp.dot(
                oh,
                btab[w],
                preferred_element_type=jnp.float32,
                precision=lax.Precision.HIGHEST,
            ).astype(jnp.int32)
        )
    for w in range(A_NWIN):
        byte = h[:, w // 2]
        digit = (byte >> (4 * (w % 2))) & 0xF
        acc = None
        for d in range(16):
            # per-slice transpose+convert of the canonical int16 layout:
            # fuses into the consumer as strided reads — materializing a
            # whole int32 copy of the table cost ~33 ms at N=10k
            twd = jnp.transpose(a_tables[w, d]).astype(jnp.int32)  # (N, 60)
            if reps != 1:
                twd = jnp.broadcast_to(twd[None], (reps, n_vals, 60)).reshape(
                    bsz, 60
                )
            term = jnp.where((digit == d)[:, None], twd, 0)
            acc = term if acc is None else acc + term
        outs.append(acc)
    return jnp.stack(outs, axis=0)


def _sum_entries_xla(ent):
    """Portable scan over the NSTEPS mixed adds; ent (NSTEPS, B, 60)."""
    acc = _identity_like(ent[0, :, :1])

    def step(a, e):
        e3 = e.reshape(e.shape[0], 3, NLIMBS)
        return pt_madd(a, (e3[:, 0], e3[:, 1], e3[:, 2])), None

    acc, _ = lax.scan(step, acc, ent)
    return acc


# ---- pallas fast path -------------------------------------------------------
#
# Layout: the batch is tiled into (8, 128) VPU tiles; every field-element
# limb is a separate (8, 128) plane so each vector op runs at full lane
# occupancy. The accumulator lives in a VMEM scratch (80 planes = X,Y,Z,T
# x 20 limbs) that persists across the NSTEPS minor grid steps; entry
# planes stream in as (60, 8, 128) blocks double-buffered by the Pallas
# pipeline. HBM traffic is therefore one read of the entries and one
# write of the final accumulator — the XLA scan's per-step carry
# round-trips are gone.

_LANES = 1024  # 8 x 128 batch elements per grid tile


def _carry_planes(t):
    """fe_carry on a list of 20 (8,128) planes (3 rounds, like fe_carry)."""
    for _ in range(3):
        c = [v >> 13 for v in t]
        r = [v & 8191 for v in t]
        t = [r[0] + 608 * c[-1]] + [r[i] + c[i - 1] for i in range(1, 20)]
    return t


def _mul_planes(a, b):
    """fe_mul on lists of 20 (8,128) planes (mirrors fe_mul exactly)."""
    cols = []
    for k in range(39):
        lo, hi = max(0, k - 19), min(k, 19)
        t = a[lo] * b[k - lo]
        for i in range(lo + 1, hi + 1):
            t = t + a[i] * b[k - i]
        cols.append(t)
    c = [v >> 13 for v in cols]
    r = [v & 8191 for v in cols]
    out = [r[0]] + [r[i] + c[i - 1] for i in range(1, 39)]
    lo_ = out[:20]
    hi_ = out[20:] + [c[-1]]
    return _carry_planes([lo_[i] + 608 * hi_[i] for i in range(20)])


def _sub_planes(a, b):
    d = [x - y for x, y in zip(a, b)]
    return _carry_planes(_carry_planes(d))


def _addc_planes(a, b):
    return _carry_planes([x + y for x, y in zip(a, b)])


def _madd_planes(acc, ypx, ymx, t2d):
    x1, y1, z1, t1 = acc
    a = _mul_planes(_sub_planes(y1, x1), ymx)
    b = _mul_planes(_addc_planes(y1, x1), ypx)
    c = _mul_planes(t1, t2d)
    d = _carry_planes([v + v for v in z1])
    e = _sub_planes(b, a)
    f = _sub_planes(d, c)
    g = _addc_planes(d, c)
    h = _addc_planes(b, a)
    return (
        _mul_planes(e, f),
        _mul_planes(g, h),
        _mul_planes(f, g),
        _mul_planes(e, h),
    )


def _madd_chain_kernel(ent_ref, out_ref, acc_ref):
    from jax.experimental import pallas as pl

    t = pl.program_id(1)

    @pl.when(t == 0)
    def _():
        # identity (0, 1, 1, 0): Y limb 0 and Z limb 0 are 1 (scatter is
        # not lowerable in pallas, so build via an iota select)
        rows = jax.lax.broadcasted_iota(jnp.int32, (80, 8, 128), 0)
        acc_ref[:] = jnp.where((rows == 20) | (rows == 40), 1, 0)

    ent = ent_ref[0, 0]  # (60, 8, 128)
    acc = tuple(
        [acc_ref[20 * ci + i] for i in range(20)] for ci in range(4)
    )
    ypx = [ent[i] for i in range(20)]
    ymx = [ent[20 + i] for i in range(20)]
    t2d = [ent[40 + i] for i in range(20)]
    nxt = _madd_planes(acc, ypx, ymx, t2d)
    acc_ref[:] = jnp.stack([p for coord in nxt for p in coord])

    @pl.when(t == NSTEPS - 1)
    def _():
        out_ref[0] = acc_ref[:]


def _sum_entries_pallas(ent):
    """ent (NSTEPS, B, 60) -> extended acc, B a multiple of 1024 lanes."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bsz = ent.shape[1]
    tiles = bsz // _LANES
    # (NSTEPS, B, 60) -> (tiles, NSTEPS, 60, 8, 128)
    e = ent.reshape(NSTEPS, tiles, 8, 128, 60)
    e = jnp.transpose(e, (1, 0, 4, 2, 3))
    out = pl.pallas_call(
        _madd_chain_kernel,
        grid=(tiles, NSTEPS),
        in_specs=[
            pl.BlockSpec(
                (1, 1, 60, 8, 128),
                lambda i, t: (i, t, 0, 0, 0),
                memory_space=pltpu.VMEM,
            )
        ],
        out_specs=pl.BlockSpec(
            (1, 80, 8, 128), lambda i, t: (i, 0, 0, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((tiles, 80, 8, 128), jnp.int32),
        scratch_shapes=[pltpu.VMEM((80, 8, 128), jnp.int32)],
    )(e)
    # (tiles, 80, 8, 128) -> 4 coords of (B, 20)
    coords = out.reshape(tiles, 4, 20, 8, 128)
    coords = jnp.transpose(coords, (1, 0, 3, 4, 2)).reshape(4, bsz, NLIMBS)
    return coords[0], coords[1], coords[2], coords[3]


from functools import partial  # noqa: E402

# ---- fused select+accumulate pallas path ------------------------------------
#
# The materialized-entries pipeline above streams a (96, B, 60) int32
# array through HBM twice (write at selection, read at accumulation) —
# 7.6 GB of traffic at the K=16 x 10,240 bench shape on a device
# measured at ~25 GB/s (docs/PLATFORM_NOTES.md). The fused kernel
# removes that array entirely: each grid step selects its operands
# INSIDE the kernel from the (int16, read-once) table block and feeds
# them straight to the VMEM-resident mixed-add accumulator. To make
# every step's selection the same cheap 16-way masked sum, the S comb
# uses a w=4 fixed-base table too: 128 identical steps (64 S windows +
# 64 h windows) instead of 96 asymmetric ones.
#
# Lane geometry: one grid tile covers V_TILE=128 validators x ALL K
# stacked commits (lane planes are (8, 16*K) — the plane width scales
# with K instead of adding commit-blocks to the grid), so each table
# block serves every lane that ever needs it and the full table is read
# EXACTLY ONCE per launch, independent of K. 128 is the smallest
# validator block pallas can address on the table's minor axis, which
# maximizes how much stacking a given VMEM budget allows.

NSTEPS_W4 = 2 * SB_NWIN  # 128: steps 0..63 = S comb, 64..127 = h comb
A_START = SB_NWIN
V_TILE = 128
MAX_FUSED_STACK = 64  # VMEM: acc+ent scratch = 140 * (8, 16K) planes


def _digits_w4(s, h):
    """(B, 32) int32 byte arrays -> (B, 128) int32 nibble-per-step."""
    cols = []
    for i in range(SB_NWIN):
        cols.append((s[:, i // 2] >> (4 * (i % 2))) & 0xF)
    for i in range(SB_NWIN):
        cols.append((h[:, i // 2] >> (4 * (i % 2))) & 0xF)
    return jnp.stack(cols, axis=-1)


def _to_kernel_order(x, n_vals, v_tile, c_tile):
    """Commit-major lanes (lane = c*N + v) -> fused-tile order: tile vb
    holds its 128 validators' lanes COMMIT-major (lane = c*128 + v
    within the tile), so a table column broadcasts to lane planes as a
    native row-splat; pure reshape/transpose."""
    b = x.shape[0]
    k = b // n_vals
    y = x.reshape((k, n_vals // v_tile, v_tile) + x.shape[1:])
    y = jnp.transpose(y, (1, 0, 2) + tuple(range(3, y.ndim)))
    return y.reshape((b,) + x.shape[1:])


def _from_kernel_order(x, n_vals, v_tile, c_tile):
    """Inverse of _to_kernel_order."""
    b = x.shape[0]
    k = b // n_vals
    y = x.reshape((n_vals // v_tile, k, v_tile) + x.shape[1:])
    y = jnp.transpose(y, (1, 0, 2) + tuple(range(3, y.ndim)))
    return y.reshape((b,) + x.shape[1:])


def _fused_tile_geometry(bsz: int, n_vals: int):
    """(v_tile, c_tile) for the fused kernel, or None if the shape won't
    tile. One tile = 128 validators x all K commits; the lane planes are
    (8, 128*K/8), whose minor dim must stay a multiple of 128 — hence
    K % 8 == 0 — and whose VMEM scratch bounds K at MAX_FUSED_STACK."""
    k = bsz // n_vals
    if n_vals % V_TILE == 0 and k % 8 == 0 and 8 <= k <= MAX_FUSED_STACK:
        return V_TILE, k
    return None


def _make_fused_kernel(c_tile: int):
    from jax.experimental import pallas as pl

    w = V_TILE * c_tile // 8  # lane plane shape (8, w)

    def kernel(sb_ref, atab_ref, dig_ref, out_ref, acc_ref, ent_ref):
        t = pl.program_id(1)

        @pl.when(t == 0)
        def _():
            rows = jax.lax.broadcasted_iota(jnp.int32, (80, 8, w), 0)
            acc_ref[:] = jnp.where((rows == 20) | (rows == 40), 1, 0)

        dig = dig_ref[0, 0]  # (8, w) int32 nibbles for this step
        masks = [dig == d for d in range(16)]

        @pl.when(t < A_START)
        def _():
            sb = sb_ref[0]  # (16, 60) int32 — shared by every lane
            planes = []
            for limb in range(60):
                acc = jnp.zeros((8, w), jnp.int32)
                for d in range(16):
                    acc = acc + jnp.where(masks[d], sb[d, limb], 0)
                planes.append(acc)
            ent_ref[:] = jnp.stack(planes)

        @pl.when(t >= A_START)
        def _():
            at = atab_ref[0].astype(jnp.int32)  # (16, 60, V_TILE)
            reps = w // V_TILE  # commits per plane row (= c_tile/8)
            planes = []
            for limb in range(60):
                acc = jnp.zeros((8, w), jnp.int32)
                for d in range(16):
                    col = at[d, limb]  # (V_TILE,) — this tile's validators
                    # lanes are commit-major (lane = c*128 + v), so the
                    # column expands by row-splat + minor concat — the
                    # only vector reshapes Mosaic supports here
                    bv = jnp.broadcast_to(col[None, :], (8, V_TILE))
                    if reps > 1:
                        bv = jnp.concatenate([bv] * reps, axis=1)
                    acc = acc + jnp.where(masks[d], bv, 0)
                planes.append(acc)
            ent_ref[:] = jnp.stack(planes)

        ent = ent_ref[:]
        acc = tuple(
            [acc_ref[20 * ci + i] for i in range(20)] for ci in range(4)
        )
        ypx = [ent[i] for i in range(20)]
        ymx = [ent[20 + i] for i in range(20)]
        t2d = [ent[40 + i] for i in range(20)]
        nxt = _madd_planes(acc, ypx, ymx, t2d)
        acc_ref[:] = jnp.stack([p for coord in nxt for p in coord])

        @pl.when(t == NSTEPS_W4 - 1)
        def _():
            out_ref[0] = acc_ref[:]

    return kernel


def _fused_chain_pallas(a_tables, digits, v_tile, c_tile, interpret=False):
    """a_tables (64,16,60,N) int16, digits (B,128) int32 kernel-order
    -> extended acc coords, each (B, 20) int32 kernel-order."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bsz = digits.shape[0]
    lanes_per_tile = v_tile * c_tile
    tiles = bsz // lanes_per_tile  # == N / V_TILE validator blocks
    w = lanes_per_tile // 8
    # digits -> (tiles, NSTEPS_W4, 8, w) step-major planes so the
    # pipeline hands each step its (8, w) nibble plane directly
    dig = digits.reshape(tiles, 8, w, NSTEPS_W4)
    dig = jnp.transpose(dig, (0, 3, 1, 2))
    sb = jnp.asarray(sb_table_w4())

    grid = (tiles, NSTEPS_W4)
    out = pl.pallas_call(
        _make_fused_kernel(c_tile),
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (1, 16, 60),
                lambda i, t: (jnp.minimum(t, A_START - 1), 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, 16, 60, v_tile),
                lambda i, t: (jnp.maximum(t - A_START, 0), 0, 0, i),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, 1, 8, w),
                lambda i, t: (i, t, 0, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, 80, 8, w), lambda i, t: (i, 0, 0, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((tiles, 80, 8, w), jnp.int32),
        scratch_shapes=[
            pltpu.VMEM((80, 8, w), jnp.int32),
            pltpu.VMEM((60, 8, w), jnp.int32),
        ],
        interpret=interpret,
    )(sb, a_tables, dig)
    coords = out.reshape(tiles, 4, 20, 8, w)
    coords = jnp.transpose(coords, (1, 0, 3, 4, 2)).reshape(4, bsz, NLIMBS)
    return coords[0], coords[1], coords[2], coords[3]


@partial(jax.jit, static_argnames=("impl",))
def verify_tables_kernel(a_tables, s_bytes, h_bytes, r_bytes, impl="auto"):
    """Batched verify against cached tables.

    a_tables: (64, 16, 60, N) int16 from build_key_tables.
    s_bytes:  (B, 32) uint8, S little-endian (host-checked < L).
    h_bytes:  (B, 32) uint8, SHA512(R||A||M) mod L little-endian.
    r_bytes:  (B, 32) uint8, the signature's R encoding (sig[:32]).

    Lane b verifies against validator row (b mod N) — one commit is
    B == N lanes in validator order; fast-sync stacks K commits of the
    same valset as B = K*N. Returns (B,) bool:
    encode([S]B + [h](-A)) == r_bytes, the same cofactorless
    byte-compare the reference's ed25519 performs. B must be a multiple
    of N.

    impl: "auto" picks the fused select+accumulate pallas kernel on TPU
    whenever the (K, N) shape tiles (see _fused_tile_geometry), falling
    back to the materialized-entries XLA scan elsewhere; "fused" forces
    the fused kernel (interpreted off-TPU — slow, test-only); "pallas"
    forces the materialized-entries pallas chain; "xla" the portable
    scan.
    """
    s = s_bytes.astype(jnp.int32)
    h = h_bytes.astype(jnp.int32)
    r = r_bytes.astype(jnp.int32)
    bsz = s.shape[0]
    n_vals = a_tables.shape[3]
    on_tpu = jax.default_backend() == "tpu"
    geom = _fused_tile_geometry(bsz, n_vals)

    if impl == "fused" and geom is None:
        raise ValueError(
            f"impl='fused' but shape (B={bsz}, N={n_vals}) does not tile: "
            f"needs N % {V_TILE} == 0 and K % 8 == 0, 8 <= K <= "
            f"{MAX_FUSED_STACK}"
        )
    if geom is not None and (impl == "fused" or (impl == "auto" and on_tpu)):
        v_tile, c_tile = geom
        digits = _to_kernel_order(_digits_w4(s, h), n_vals, v_tile, c_tile)
        x, y, z, _t = _fused_chain_pallas(
            a_tables, digits, v_tile, c_tile, interpret=not on_tpu
        )
        r = _to_kernel_order(r, n_vals, v_tile, c_tile)
        verdict = _finish_encode_compare(x, y, z, r)
        return _from_kernel_order(verdict, n_vals, v_tile, c_tile)

    ent = _select_entries(a_tables, s, h)
    use_pallas = impl == "pallas" or (impl == "auto" and on_tpu)
    if use_pallas:
        if bsz % _LANES != 0:
            # pad lanes with the identity precomp entry (ypx=1, ymx=1,
            # t2d=0 — the affine point (0,1)); madd with it keeps the
            # accumulator on the same projective point with Z != 0, so
            # padded lanes are harmless through the batched inversion.
            pad = _LANES - bsz % _LANES
            ident = jnp.zeros((NSTEPS, pad, 3, NLIMBS), dtype=jnp.int32)
            ident = ident.at[:, :, 0, 0].set(1).at[:, :, 1, 0].set(1)
            ent = jnp.concatenate(
                [ent, ident.reshape(NSTEPS, pad, 3 * NLIMBS)], axis=1
            )
        x, y, z, _t = _sum_entries_pallas(ent)
        x, y, z = x[:bsz], y[:bsz], z[:bsz]
    else:
        x, y, z, _t = _sum_entries_xla(ent)
    return _finish_encode_compare(x, y, z, r)


def _finish_encode_compare(x, y, z, r):
    """Affine-normalize via one tree inversion, encode y, compare to R."""
    zinv = fe_batch_invert(fe_carry(z))
    x_aff = fe_canon(fe_mul(x, zinv))
    y_bytes = fe_to_bytes(fe_mul(y, zinv))
    parity = x_aff[..., 0] & 1
    sign = (r[..., 31] >> 7) & 1
    r_clean = r.at[..., 31].set(r[..., 31] & 0x7F)
    return jnp.all(y_bytes == r_clean, axis=-1) & (parity == sign)


# -- host-side lane prep ------------------------------------------------------


def prepare_commit_lanes(pubkeys, commits):
    """Host prep for K stacked commits over one N-validator set.

    pubkeys: N 32-byte pubkey encodings in validator order.
    commits: K pairs (msgs, sigs) — each a length-N sequence aligned to
    validator index, with None marking absent votes.

    Returns (s, h, r) uint8 arrays of shape (K*N, 32) and a (K*N,) bool
    precheck mask (False for absent lanes and host-detected malformed
    signatures: wrong length or non-canonical S >= L, the same strict-S
    rule as `ed25519_kernel.prepare_batch`). Lane k*N+i aligns with
    `verify_tables_kernel`'s b-mod-N column mapping.
    """
    import hashlib

    from tendermint_tpu.ops.ed25519_kernel import L as _L

    n = len(pubkeys)
    k = len(commits)
    s = np.zeros((k * n, 32), dtype=np.uint8)
    h = np.zeros((k * n, 32), dtype=np.uint8)
    r = np.zeros((k * n, 32), dtype=np.uint8)
    precheck = np.zeros(k * n, dtype=bool)
    # hot loop kept lean (10k+ lanes on the single-commit latency path):
    # one bytes-join + frombuffer per commit instead of per-lane array
    # writes, hashlib only on present lanes
    zero64 = b"\x00" * 64
    from_bytes = int.from_bytes
    sha512 = hashlib.sha512
    for ci, (msgs, sigs) in enumerate(commits):
        if len(msgs) != n or len(sigs) != n:
            raise ValueError(f"commit {ci}: expected {n} lanes")
        lanes = precheck[ci * n : (ci + 1) * n]
        hrows = []
        sig_blob = []
        for i in range(n):
            msg, sig = msgs[i], sigs[i]
            if (
                msg is None
                or sig is None
                or len(sig) != 64
                or len(pubkeys[i]) != 32
                or from_bytes(sig[32:], "little") >= _L
            ):
                sig_blob.append(zero64)
                continue
            lanes[i] = True
            sig_blob.append(sig)
            hh = sha512(sig[:32] + pubkeys[i] + msg).digest()
            hrows.append(
                (i, (from_bytes(hh, "little") % _L).to_bytes(32, "little"))
            )
        sig_arr = np.frombuffer(b"".join(sig_blob), dtype=np.uint8).reshape(n, 64)
        r[ci * n : (ci + 1) * n] = sig_arr[:, :32]
        s[ci * n : (ci + 1) * n] = sig_arr[:, 32:]
        if hrows:
            idx, blobs = zip(*hrows)
            h[ci * n + np.asarray(idx, dtype=np.intp)] = np.frombuffer(
                b"".join(blobs), dtype=np.uint8
            ).reshape(len(blobs), 32)
    return s, h, r, precheck
