"""Batched SHA-256 in JAX (u32 lanes, batch over the trailing dim inside the
compression, leading dim at the API).

Replaces host hashlib at the TreeHasher seam for bulk tree builds
(reference call sites: `types/tx.go:33-46`, `types/part_set.go:95-122`).
The round structure is serial (FIPS 180-4) and expressed as `lax.scan` —
compiler-friendly control flow, no giant unrolled graphs — so all throughput
comes from the batch dimension, which the VPU vectorizes and the mesh shards.

Constants are generated from integer square/cube roots of the first primes —
no transcribed magic tables.
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def _first_primes(n: int) -> list[int]:
    primes, c = [], 2
    while len(primes) < n:
        if all(c % p for p in primes):
            primes.append(c)
        c += 1
    return primes


def _icbrt(n: int) -> int:
    """Exact integer cube root (floor)."""
    x = int(round(n ** (1 / 3)))
    while x**3 > n:
        x -= 1
    while (x + 1) ** 3 <= n:
        x += 1
    return x


def _frac_root_bits(p: int, root: int, bits: int) -> int:
    """floor(frac(p^(1/root)) * 2^bits), computed exactly in integers."""
    scaled = p << (root * bits)
    r = math.isqrt(scaled) if root == 2 else _icbrt(scaled)
    return r - ((r >> bits) << bits)


_PRIMES64 = _first_primes(64)
SHA256_H0 = np.array([_frac_root_bits(p, 2, 32) for p in _PRIMES64[:8]], dtype=np.uint32)
SHA256_K = np.array([_frac_root_bits(p, 3, 32) for p in _PRIMES64], dtype=np.uint32)


def _rotr(x, n: int):
    return (x >> np.uint32(n)) | (x << np.uint32(32 - n))


def _compress(state, w_block):
    """One SHA-256 compression. state: (B, 8) u32; w_block: (B, 16) u32."""
    w0 = w_block.T  # (16, B)

    def sched_step(window, _):
        # window holds w[t-16..t-1]; W[t] = w[t-16]+σ0(w[t-15])+w[t-7]+σ1(w[t-2])
        s0 = _rotr(window[1], 7) ^ _rotr(window[1], 18) ^ (window[1] >> np.uint32(3))
        s1 = _rotr(window[14], 17) ^ _rotr(window[14], 19) ^ (window[14] >> np.uint32(10))
        new = window[0] + s0 + window[9] + s1
        return jnp.concatenate([window[1:], new[None]], axis=0), new

    _, w_rest = lax.scan(sched_step, w0, None, length=48)
    W = jnp.concatenate([w0, w_rest], axis=0)  # (64, B)

    def round_step(regs, xs):
        a, b, c, d, e, f, g, h = regs
        k, w = xs
        S1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = h + S1 + ch + k + w
        S0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        t2 = S0 + maj
        return (t1 + t2, a, b, c, d + t1, e, f, g), None

    init = tuple(state[:, i] for i in range(8))
    regs, _ = lax.scan(round_step, init, (jnp.asarray(SHA256_K), W))
    return jnp.stack(regs, axis=1) + state


@partial(jax.jit, static_argnames=("max_blocks",))
def _sha256_masked(blocks, n_blocks, max_blocks: int):
    B = blocks.shape[0]
    state0 = jnp.broadcast_to(jnp.asarray(SHA256_H0), (B, 8)).astype(jnp.uint32)

    def block_step(state, xs):
        w_block, j = xs
        new_state = _compress(state, w_block)
        return jnp.where((j < n_blocks)[:, None], new_state, state), None

    xs = (jnp.swapaxes(blocks, 0, 1), jnp.arange(max_blocks, dtype=jnp.int32))
    state, _ = lax.scan(block_step, state0, xs)
    return state


def sha256_batch_jax(blocks, n_blocks):
    """Digest a batch of padded messages.

    blocks: (B, max_blocks, 16) u32 BE words; n_blocks: (B,) i32.
    Returns (B, 8) u32 digests. Messages shorter than max_blocks are masked
    (their state freezes after their last real block).
    """
    blocks = jnp.asarray(blocks, dtype=jnp.uint32)
    n_blocks = jnp.asarray(n_blocks, dtype=jnp.int32)
    return _sha256_masked(blocks, n_blocks, blocks.shape[1])


def sha256_fixed2_from_words(w0, w1):
    """Digest exactly-2-block messages given precomputed word arrays
    ((B,16) each) — the Merkle inner-node fast path (no masking)."""
    B = w0.shape[0]
    state = jnp.broadcast_to(jnp.asarray(SHA256_H0), (B, 8)).astype(jnp.uint32)
    state = _compress(state, w0)
    return _compress(state, w1)


def sha256_digest_bytes(msgs: list[bytes]) -> list[bytes]:
    """Convenience host API: pad → device hash → bytes."""
    from tendermint_tpu.ops.padding import digests_to_bytes_be, pad_sha256

    if not msgs:
        return []
    blocks, counts = pad_sha256(msgs)
    return digests_to_bytes_be(np.asarray(sha256_batch_jax(blocks, counts)))
