from tendermint_tpu.cmd import main

raise SystemExit(main())
