"""Block storage + fast-sync (reference `blockchain/`)."""

from tendermint_tpu.blockchain.pool import BlockPool
from tendermint_tpu.blockchain.reactor import BlockchainReactor
from tendermint_tpu.blockchain.store import BlockMeta, BlockStore

__all__ = ["BlockMeta", "BlockPool", "BlockStore", "BlockchainReactor"]
