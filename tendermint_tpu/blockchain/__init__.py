"""Block storage + fast-sync (reference `blockchain/`)."""

from tendermint_tpu.blockchain.store import BlockMeta, BlockStore

__all__ = ["BlockMeta", "BlockStore"]
