"""Persistent block storage (reference `blockchain/store.go:31-230`).

Per height: block meta, the block's parts (individually, so gossip can
serve single parts), the canonical commit of the *previous* block, and
the locally-seen commit (+2/3 precommits this node saw — may differ from
the canonical one and is needed to reconstruct the consensus LastCommit
on restart, `consensus/state.go:392-411`). A JSON height watermark marks
the contiguous store head.
"""

from __future__ import annotations

import json
from dataclasses import dataclass

from tendermint_tpu.codec import Reader, Writer
from tendermint_tpu.db.kv import DB
from tendermint_tpu.types.block import Block, Commit, Header
from tendermint_tpu.types.block_id import BlockID
from tendermint_tpu.types.errors import ValidationError
from tendermint_tpu.types.part_set import Part, PartSet


@dataclass
class BlockMeta:
    """BlockID + header, servable without the full block
    (reference `types.BlockMeta`)."""

    block_id: BlockID
    header: Header

    def encode(self) -> bytes:
        return Writer().raw(self.block_id.encode()).bytes(self.header.encode()).build()

    @classmethod
    def decode(cls, data: bytes) -> "BlockMeta":
        r = Reader(data)
        block_id = BlockID.decode_from(r)
        header = Header.decode_from(Reader(r.bytes()))
        return cls(block_id=block_id, header=header)


class BlockStore:
    def __init__(self, db: DB) -> None:
        self._db = db
        self._height = 0
        self._base = 0
        raw = db.get(b"blockStore")
        if raw is not None:
            doc = json.loads(raw.decode())
            self._height = doc["height"]
            # pre-base-tracking watermarks imply a store contiguous from 1
            self._base = doc.get("base", 1 if doc["height"] > 0 else 0)

    @property
    def height(self) -> int:
        """Height of the newest stored block (0 if empty)."""
        return self._height

    @property
    def base(self) -> int:
        """Lowest stored height (0 if empty). Snapshot-restored and
        pruned stores start above 1; `load_block*` below the base
        answers None, never a decode error."""
        return self._base

    def _save_watermark(self) -> None:
        self._db.set_sync(
            b"blockStore",
            json.dumps({"height": self._height, "base": self._base}).encode(),
        )

    # -- keys ----------------------------------------------------------------

    @staticmethod
    def _meta_key(height: int) -> bytes:
        return b"H:%d" % height

    @staticmethod
    def _part_key(height: int, index: int) -> bytes:
        return b"P:%d:%d" % (height, index)

    @staticmethod
    def _commit_key(height: int) -> bytes:
        return b"C:%d" % height

    @staticmethod
    def _seen_commit_key(height: int) -> bytes:
        return b"SC:%d" % height

    # -- save ----------------------------------------------------------------

    def save_block(self, block: Block, part_set: PartSet, seen_commit: Commit) -> None:
        """Reference `SaveBlock :148`: must be called with height ==
        store height + 1 (contiguous chain)."""
        height = block.header.height
        if height != self._height + 1:
            raise ValidationError(
                f"BlockStore can only save contiguous blocks: have {self._height}, got {height}"
            )
        if not part_set.is_complete():
            raise ValidationError("BlockStore can only save complete part sets")
        meta = BlockMeta(
            block_id=BlockID(block.hash(), part_set.header), header=block.header
        )
        self._db.set(self._meta_key(height), meta.encode())
        for i in range(part_set.total):
            part = part_set.get_part(i)
            self._db.set(self._part_key(height, i), part.encode())
        # commit of block H-1 (carried inside block H)
        self._db.set(self._commit_key(height - 1), block.last_commit.encode())
        # commit that made THIS block (what we saw locally)
        self._db.set(self._seen_commit_key(height), seen_commit.encode())
        self._height = height
        if self._base == 0:
            self._base = height
        self._save_watermark()

    def bootstrap(self, tail: list) -> None:
        """Seed an EMPTY store from a snapshot's block tail
        `[(block, seen_commit), ...]` (consecutive, ascending). The
        store's base becomes the first tail height — earlier heights
        were never stored here and `load_block*` answers None for them.
        """
        if self._height != 0:
            raise ValidationError(
                f"bootstrap requires an empty store (height {self._height})"
            )
        if not tail:
            return
        prev = None
        for block, seen_commit in tail:
            height = block.header.height
            if prev is not None and height != prev + 1:
                raise ValidationError(
                    f"snapshot tail is not consecutive: {prev} -> {height}"
                )
            prev = height
            part_set = block.make_part_set()
            meta = BlockMeta(
                block_id=BlockID(block.hash(), part_set.header), header=block.header
            )
            self._db.set(self._meta_key(height), meta.encode())
            for i in range(part_set.total):
                self._db.set(self._part_key(height, i), part_set.get_part(i).encode())
            self._db.set(self._commit_key(height - 1), block.last_commit.encode())
            self._db.set(self._seen_commit_key(height), seen_commit.encode())
        self._base = tail[0][0].header.height
        self._height = tail[-1][0].header.height
        self._save_watermark()

    def prune(self, retain_height: int) -> int:
        """Delete blocks below `retain_height` (exclusive), bounding the
        disk a snapshot-serving node spends on history (reference
        `PruneBlocks store.go`). Returns the number of heights pruned;
        the head block is always retained."""
        if retain_height > self._height:
            retain_height = self._height
        if self._base == 0 or retain_height <= self._base:
            return 0
        pruned = 0
        for h in range(self._base, retain_height):
            meta = self.load_block_meta(h)
            if meta is not None:
                for i in range(meta.block_id.parts_header.total):
                    self._db.delete(self._part_key(h, i))
            self._db.delete(self._meta_key(h))
            self._db.delete(self._seen_commit_key(h))
            # C:h-1 rides with block h, so this keeps the canonical
            # commit for retain_height-1 (written by block retain_height)
            self._db.delete(self._commit_key(h - 1))
            pruned += 1
        self._base = retain_height
        self._save_watermark()
        return pruned

    # -- load ----------------------------------------------------------------

    def load_block_meta(self, height: int) -> BlockMeta | None:
        raw = self._db.get(self._meta_key(height))
        return BlockMeta.decode(raw) if raw is not None else None

    def load_block_part(self, height: int, index: int) -> Part | None:
        raw = self._db.get(self._part_key(height, index))
        return Part.decode(raw) if raw is not None else None

    def load_block(self, height: int) -> Block | None:
        meta = self.load_block_meta(height)
        if meta is None:
            return None
        buf = b""
        for i in range(meta.block_id.parts_header.total):
            part = self.load_block_part(height, i)
            if part is None:
                return None
            buf += part.bytes_
        return Block.decode(buf)

    def load_block_commit(self, height: int) -> Commit | None:
        """Canonical commit for block at `height` (from block height+1)."""
        raw = self._db.get(self._commit_key(height))
        if raw is None:
            return None
        return Commit.decode_from(Reader(raw))

    def load_seen_commit(self, height: int) -> Commit | None:
        raw = self._db.get(self._seen_commit_key(height))
        if raw is None:
            return None
        return Commit.decode_from(Reader(raw))
