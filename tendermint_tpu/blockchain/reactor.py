"""Fast-sync reactor: catch a fresh node up by downloading + batch-
verifying blocks (reference `blockchain/reactor.go`, channel 0x40).

TPU-first twist on the reference's serial verify loop
(`reactor.go:242-289`, one `VerifyCommit` per block): the sync loop
gathers a WINDOW of consecutive downloaded blocks and verifies all
their commits in ONE device batch via
`ValidatorSet.verify_commit_batched` (the valset-table kernel path —
BASELINE config 3's 50k-blocks-at-1000-validators shape), then applies
them with the per-block signature pass skipped.

Trust rule per reference: block H is applied only once +2/3 of the
current validators are seen precommitting it — H's commit travels as
block H+1's LastCommit, so the window always holds one more block than
it applies.
"""

from __future__ import annotations

import os
import threading
import time

from tendermint_tpu.codec.binary import Reader, Writer
from tendermint_tpu.p2p.connection import ChannelDescriptor
from tendermint_tpu.p2p.peer import Peer
from tendermint_tpu.p2p.switch import Reactor
from tendermint_tpu.blockchain.pool import BlockPool
from tendermint_tpu.state.execution import apply_block
from tendermint_tpu.types.block import Block
from tendermint_tpu.types.block_id import BlockID
from tendermint_tpu.types.errors import ValidationError
from tendermint_tpu.utils.log import kv, logger
import logging

BLOCKCHAIN_CHANNEL = 0x40

_MSG_BLOCK_REQUEST = 0x01
_MSG_BLOCK_RESPONSE = 0x02
_MSG_NO_BLOCK = 0x03
_MSG_STATUS_REQUEST = 0x04
_MSG_STATUS_RESPONSE = 0x05

_SYNC_TICK_S = 0.01
_STATUS_INTERVAL_S = 2.0  # reference statusUpdateIntervalSeconds=10, scaled
VERIFY_WINDOW = 16  # commits batched per device call

# Verify windows kept in flight on device at once (docs/PERFORMANCE.md).
# 2 is the classic software pipeline: window K's verdict flies while the
# host preps K+1's part sets/lanes and applies K-1's blocks via ABCI.
# 1 degenerates to the synchronous verify->apply loop (the bench
# baseline); >2 only helps when launches are slower than applies.
PIPELINE_DEPTH = int(os.environ.get("TENDERMINT_TPU_PIPELINE_DEPTH", "2"))


def adaptive_pipeline_depth() -> int:
    """Default pipeline depth from the measured launch:apply ratio.

    The env knob always wins. Without it, the dispatch telemetry's
    overlap histogram (what the fastsync queue already exports) gives
    launch:apply ≈ (1-o)/o; `1 + round(ratio)` windows keep the device
    busy while one window applies — balanced pipelines land on the
    classic depth 2, launch-dominated ones deepen, apply-dominated ones
    collapse to the synchronous loop. Clamped to [1, 4]: beyond 4 the
    extra in-flight windows only add redo-drain latency.
    """
    env = os.environ.get("TENDERMINT_TPU_PIPELINE_DEPTH")
    if env:
        return max(1, int(env))
    from tendermint_tpu.services.dispatch import measured_launch_apply_ratio

    ratio = measured_launch_apply_ratio("fastsync")
    if ratio is None:
        return 2
    return max(1, min(4, 1 + int(round(ratio))))


def _enc(tag: int, *fields) -> bytes:
    w = Writer().uvarint(tag)
    for f in fields:
        if isinstance(f, int):
            w.uvarint(f)
        else:
            w.bytes(f)
    return w.build()


def decode_message(payload: bytes):
    r = Reader(payload)
    tag = r.uvarint()
    if tag == _MSG_BLOCK_REQUEST:
        return ("block_request", r.uvarint())
    if tag == _MSG_BLOCK_RESPONSE:
        return ("block_response", Block.decode(r.bytes()))
    if tag == _MSG_NO_BLOCK:
        return ("no_block", r.uvarint())
    if tag == _MSG_STATUS_REQUEST:
        return ("status_request", None)
    if tag == _MSG_STATUS_RESPONSE:
        return ("status_response", r.uvarint())
    raise ValueError(f"unknown blockchain message tag {tag:#x}")


class BlockchainReactor(Reactor):
    """Serves stored blocks to peers; optionally fast-syncs from them.

    `on_caught_up(state)` fires once when the pool has drained to every
    peer's advertised height — the node uses it to start consensus
    (reference `SwitchToConsensus reactor.go:233-241`).
    """

    def __init__(
        self,
        state,
        store,
        app_conn,
        fast_sync: bool = False,
        on_caught_up=None,
        verifier=None,
        tx_indexer=None,
        hasher=None,
        deferred: bool = False,
        pipeline_depth: int | None = None,
        follow: bool = False,
    ) -> None:
        super().__init__()
        self.state = state
        self.store = store
        self.app_conn = app_conn
        self.fast_sync = fast_sync
        self.on_caught_up = on_caught_up
        # follow mode (read replicas): never exit fast-sync — keep
        # tailing the chain as peers advance instead of handing off to
        # consensus. `is_caught_up` then just means "at the tip".
        self.follow = follow
        self.verifier = verifier
        self.tx_indexer = tx_indexer
        self.hasher = hasher
        # `deferred` parks the sync thread until state sync restores a
        # snapshot and calls begin_fast_sync() — the pool's start height
        # is unknowable before the restore lands
        self.deferred = deferred
        self.pool = BlockPool(start_height=store.height + 1)
        self.pipeline_depth = max(
            1,
            adaptive_pipeline_depth() if pipeline_depth is None else pipeline_depth,
        )
        self._dispatch_queue = None  # lazy: only fast-syncing nodes need it
        self._running = False
        self._thread: threading.Thread | None = None
        self.blocks_synced = 0
        self._progress_mark = time.monotonic()

    # -- reactor interface -------------------------------------------------

    def get_channels(self) -> list[ChannelDescriptor]:
        return [ChannelDescriptor(BLOCKCHAIN_CHANNEL, priority=5)]

    def on_start(self) -> None:
        self._running = True
        if self.fast_sync and not self.deferred:
            self._start_sync_thread()

    def _start_sync_thread(self) -> None:
        self._thread = threading.Thread(
            target=self._sync_routine, name="fastsync", daemon=True
        )
        self._thread.start()

    def begin_fast_sync(self, state=None) -> None:
        """State-sync handoff: adopt the restored state, re-aim the pool
        at the (snapshot-height advanced) store head, and start syncing
        the tail. The node calls this from the statesync reactor's
        `on_synced` (with state=None when state sync gave up and plain
        fast-sync from the current state proceeds)."""
        if not self.deferred:
            return
        self.deferred = False
        if state is not None:
            self.state = state
        self.pool = BlockPool(start_height=self.store.height + 1)
        # re-learn peer heights on the fresh pool (status responses that
        # arrived during state sync went to the old one)
        if self.switch is not None:
            self.switch.broadcast(BLOCKCHAIN_CHANNEL, _enc(_MSG_STATUS_REQUEST))
        if self._running and self.fast_sync:
            self._start_sync_thread()

    def on_stop(self) -> None:
        self._running = False
        if self._dispatch_queue is not None:
            self._dispatch_queue.close()

    def add_peer(self, peer: Peer) -> None:
        # advertise our height + learn theirs (reference `AddPeer`)
        peer.try_send(
            BLOCKCHAIN_CHANNEL, _enc(_MSG_STATUS_RESPONSE, self.store.height)
        )
        peer.try_send(BLOCKCHAIN_CHANNEL, _enc(_MSG_STATUS_REQUEST))

    def remove_peer(self, peer: Peer, reason) -> None:
        self.pool.remove_peer(peer.id)

    def receive(self, chan_id: int, peer: Peer, payload: bytes) -> None:
        kind, arg = decode_message(payload)
        if kind == "block_request":
            block = self.store.load_block(arg)
            if block is not None:
                peer.try_send(
                    BLOCKCHAIN_CHANNEL, _enc(_MSG_BLOCK_RESPONSE, block.encode())
                )
            else:
                peer.try_send(BLOCKCHAIN_CHANNEL, _enc(_MSG_NO_BLOCK, arg))
        elif kind == "block_response":
            self.pool.add_block(peer.id, arg, size=len(payload))
        elif kind == "status_request":
            peer.try_send(
                BLOCKCHAIN_CHANNEL, _enc(_MSG_STATUS_RESPONSE, self.store.height)
            )
        elif kind == "status_response":
            self.pool.set_peer_height(peer.id, arg)
        # no_block: ignore (the request will time out and reassign)

    # -- sync loop ---------------------------------------------------------

    def _send_request(self, peer_id: str, height: int) -> None:
        for p in self.switch.peers() if self.switch else []:
            if p.id == peer_id:
                p.try_send(BLOCKCHAIN_CHANNEL, _enc(_MSG_BLOCK_REQUEST, height))
                return

    def _sync_routine(self) -> None:
        last_status = 0.0
        while self._running and self.fast_sync:
            now = time.monotonic()
            if now - last_status > _STATUS_INTERVAL_S:
                last_status = now
                if self.switch is not None:
                    self.switch.broadcast(
                        BLOCKCHAIN_CHANNEL, _enc(_MSG_STATUS_REQUEST)
                    )
            requests, evictions = self.pool.schedule_requests(now)
            for peer_id in evictions:
                self._drop_peer(peer_id, "fast-sync request timeout")
            for peer_id, height in requests:
                self._send_request(peer_id, height)
            try:
                self._try_sync()
            except Exception:
                # _try_sync handles bad blocks via redo; anything else
                # (e.g. app execution failure) must not kill the sync
                # thread silently — log and keep going
                import logging

                logging.getLogger(__name__).exception("fast-sync step failed")
                time.sleep(0.5)
            if not self.follow and self.pool.is_caught_up():
                self.fast_sync = False
                if self.on_caught_up is not None:
                    self.on_caught_up(self.state)
                return
            time.sleep(_SYNC_TICK_S)

    def tip_lag(self) -> int:
        """Heights between the best-known peer tip and our store head
        (0 at the tip). Follow-mode replicas stay in fast-sync forever,
        so health derives their readiness from this instead of the
        `fast_sync` flag."""
        return max(0, self.pool.max_peer_height() - self.store.height)

    def _queue(self):
        """The reactor-owned dispatch queue (one per fast-syncing node,
        so another consumer's unjoined handles can't backpressure us)."""
        if self._dispatch_queue is None:
            from tendermint_tpu.services.dispatch import DispatchQueue

            self._dispatch_queue = DispatchQueue(
                depth=self.pipeline_depth, name="fastsync"
            )
        return self._dispatch_queue

    def _try_sync(self) -> None:
        """Verify + apply as many downloaded blocks as possible, commits
        batched per device call (reference `trySync` loop `:242-289`) —
        run as a SOFTWARE PIPELINE over the async dispatch layer: while
        window K's commit verdict is in flight on device, the host preps
        window K+1's part sets/lanes and applies window K-1's blocks
        through ABCI. Windows join strictly in submission order; any
        redo / valset change / verdict failure drains the in-flight
        suffix WITHOUT applying its blocks (they chain off the fault).
        """
        from collections import deque

        pipeline: "deque" = deque()  # submitted windows, oldest first
        try:
            while True:
                cursor = (
                    pipeline[-1]["next_height"]
                    if pipeline
                    else self.pool.height
                )
                entry = None
                if len(pipeline) < self.pipeline_depth:
                    entry = self._prep_window(cursor)
                if isinstance(entry, dict):
                    pipeline.append(entry)
                    continue  # keep filling until depth / no window
                if entry == "redo":
                    # linkage broke at `cursor`'s window: its suffix is
                    # already dropped from the pool, but the OLDER
                    # windows in flight verified under intact linkage —
                    # apply them before leaving
                    self._drain(pipeline, apply=True)
                    return
                if pipeline:
                    # depth reached, or no next window yet: join the
                    # oldest verdict and run its ABCI applies — this is
                    # the overlap stage, younger windows are in flight
                    if not self._join_and_apply(pipeline.popleft()):
                        self._drain(pipeline, apply=False)
                        return
                    continue
                if entry == "boundary":
                    # valset changed at the very next block: verify it
                    # alone via its successor's commit the slow way
                    # (pipeline is empty here, so the valset is current)
                    window = self.pool.peek(2)
                    if len(window) < 2:
                        return
                    self._sync_one(window[0], window[1])
                    continue
                return  # nothing downloaded, nothing in flight
        except BaseException:
            # non-verification failure (app execution, dispatch layer):
            # release the in-flight windows' queue slots, then let the
            # sync routine's catch-all log and retry
            self._drain(pipeline, apply=False)
            raise

    def _prep_window(self, cursor: int):
        """Host-prep stage: claim up to VERIFY_WINDOW applyable blocks
        at `cursor`, build part sets + block ids, check commit linkage,
        and submit ONE batched commit verify to the dispatch queue.

        Returns the in-flight window entry, None (no full window there
        yet), "boundary" (valset changes at `cursor` — needs a drained
        pipeline + `_sync_one`), or "redo" (linkage mismatch; the pool
        suffix is already dropped)."""
        window = self.pool.peek(VERIFY_WINDOW + 1, from_height=cursor)
        if len(window) < 2:
            return None
        # the batch spans consecutive blocks under ONE valset — windows
        # beyond an EndBlock valset rotation never enter the pipeline,
        # their headers carry a different validators_hash
        val_hash = self.state.validators.hash()
        usable = 0
        for b in window:
            if b.header.validators_hash != val_hash:
                break
            usable += 1
        if usable < 2:
            return "boundary"
        blocks = window[:usable]
        # commit for blocks[i] rides in blocks[i+1].last_commit; the
        # final block waits for its successor in a later window, so
        # only the applied prefix needs part sets / ids built
        apply_n = usable - 1
        parts = [b.make_part_set() for b in blocks[:apply_n]]
        block_ids = [
            BlockID(b.hash(), ps.header)
            for b, ps in zip(blocks[:apply_n], parts)
        ]
        entries = []
        for i in range(apply_n):
            commit = blocks[i + 1].last_commit
            if commit.block_id != block_ids[i]:
                self._redo(blocks[i].header.height)
                return "redo"
            entries.append((block_ids[i], blocks[i].header.height, commit))
        try:
            handle = self.state.validators.verify_commit_batched_async(
                self.state.chain_id,
                entries,
                verifier=self.verifier,
                queue=self._queue(),
                consumer="fastsync",
            )
        except ValidationError:
            # malformed commit caught during prep — same treatment as a
            # failed verdict on this window
            self._redo(blocks[0].header.height)
            return "redo"
        return {
            "blocks": blocks,
            "parts": parts,
            "handle": handle,
            "apply_n": apply_n,
            "start_height": blocks[0].header.height,
            "next_height": blocks[0].header.height + apply_n,
        }

    def _join_and_apply(self, entry) -> bool:
        """Join one window's in-flight verdict, then store + apply its
        blocks. False means the window failed and the pool suffix was
        redone — the caller must discard younger in-flight windows."""
        try:
            entry["handle"].result()
        except ValidationError:
            self._redo(entry["start_height"])
            return False
        blocks, parts = entry["blocks"], entry["parts"]
        for i in range(entry["apply_n"]):
            commit = blocks[i + 1].last_commit
            try:
                self.store.save_block(blocks[i], parts[i], commit)
                apply_block(
                    self.state,
                    blocks[i],
                    parts[i].header,
                    self.app_conn,
                    verifier=self.verifier,
                    tx_indexer=self.tx_indexer,
                    commit_preverified=True,
                    hasher=self.hasher,
                )
            except ValidationError:
                # commit verified but the block body is inconsistent
                # (possible only past a 2/3-byzantine signer set):
                # drop the suffix + serving peer rather than spin
                self._redo(blocks[i].header.height)
                return False
            self.pool.pop()
            self.blocks_synced += 1
            self._log_progress()
        return True

    def _drain(self, pipeline, apply: bool) -> None:
        """Empty the pipeline in submission order. While `apply` holds
        and windows keep verifying, their blocks go through ABCI; after
        the first failure (or when draining a stale suffix) remaining
        verdicts are joined ONLY to release their dispatch-queue slots —
        stale blocks are never applied."""
        while pipeline:
            entry = pipeline.popleft()
            if apply:
                apply = self._join_and_apply(entry)
            else:
                try:
                    entry["handle"].result()
                # tmlint: disable=T001 -- stale-suffix drain: joined only to release the dispatch slot, the failure was already handled upstream
                except Exception:
                    pass

    def _log_progress(self) -> None:
        """blocks/s every 100 blocks (reference `reactor.go:281-286`)."""
        if self.blocks_synced % 100 != 0:
            return
        now = time.monotonic()
        rate = 100.0 / max(now - self._progress_mark, 1e-9)
        self._progress_mark = now
        kv(
            logger("blockchain"),
            logging.INFO,
            "fast-sync progress",
            height=self.pool.height - 1,
            blocks_per_s=round(rate, 1),
        )

    def _sync_one(self, block, successor) -> None:
        if successor is None:
            return
        parts = block.make_part_set()
        block_id = BlockID(block.hash(), parts.header)
        commit = successor.last_commit
        if commit.block_id != block_id:
            self._redo(block.header.height)
            return
        try:
            self.state.validators.verify_commit(
                self.state.chain_id,
                block_id,
                block.header.height,
                commit,
                verifier=self.verifier,
                consumer="fastsync",
            )
        except ValidationError:
            self._redo(block.header.height)
            return
        try:
            self.store.save_block(block, parts, commit)
            apply_block(
                self.state,
                block,
                parts.header,
                self.app_conn,
                verifier=self.verifier,
                tx_indexer=self.tx_indexer,
                commit_preverified=True,
                hasher=self.hasher,
            )
        except ValidationError:
            self._redo(block.header.height)
            return
        self.pool.pop()
        self.blocks_synced += 1

    def _redo(self, height: int) -> None:
        """Bad block/commit: drop the chain suffix and the peer that
        served it (reference `RedoRequest` + peer eviction). A block
        whose commit fails verification cannot be produced honestly —
        debit the server's misbehavior score so a lying fast-sync peer
        gets banned, not just disconnected-and-redialed."""
        bad_peer = self.pool.redo(height)
        if bad_peer:
            if self.switch is not None:
                self.switch.report_misbehavior(
                    bad_peer, "forged_block", detail=f"height {height}"
                )
            self._drop_peer(bad_peer, "bad fast-sync block")

    def _drop_peer(self, peer_id: str, reason: str) -> None:
        self.pool.remove_peer(peer_id)
        if self.switch is not None:
            for p in self.switch.peers():
                if p.id == peer_id:
                    self.switch.stop_peer_for_error(p, reason)
                    return
