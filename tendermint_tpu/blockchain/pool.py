"""BlockPool: parallel block download with peer accounting.

Reference `blockchain/pool.go:49-64` — up to `max_pending` outstanding
height requests spread over peers, per-request timeouts with
reassignment, sorted assembly. The reference runs one goroutine per
in-flight height; here a single scheduler tick (driven by the reactor's
sync loop) computes which requests to (re)send — same behavior, no
thread-per-height.

The pool is pure bookkeeping: the reactor supplies a `send_request`
callback and feeds `add_block`; verification/apply happens in the
reactor's sync loop (batched through the TPU verifier — the whole point
of fast-sync, BASELINE config 3).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass

from tendermint_tpu.utils.flowrate import Monitor

REQUEST_TIMEOUT_S = 15.0  # reference peerTimeout (pool.go:33)
MAX_PENDING_PER_PEER = 50  # reference maxPendingRequestsPerPeer (pool.go:31)
MAX_PENDING = 1000  # reference maxTotalRequesters (pool.go:29-30)
MIN_RECV_RATE = 10240  # bytes/s floor before eviction (pool.go:33)
# how long a peer may hold pending requests before the rate floor
# applies (stands in for the reference's e*minRecvRate REMA seeding,
# which keeps fresh peers above the floor for the first seconds)
MIN_RECV_GRACE_S = 4.0


@dataclass
class _Request:
    peer_id: str
    sent_at: float


class _PeerRec:
    __slots__ = ("height", "monitor")

    def __init__(self, height: int, time_fn) -> None:
        self.height = height
        self.monitor = Monitor(window_s=1.0, time_fn=time_fn)


class BlockPool:
    def __init__(
        self,
        start_height: int,
        max_pending: int = MAX_PENDING,
        time_fn=time.monotonic,
    ) -> None:
        # next height we still need to hand to the executor
        self.height = start_height
        self._lock = threading.RLock()
        self._blocks: dict[int, tuple[object, str]] = {}  # height -> (block, peer)
        self._requests: dict[int, _Request] = {}
        self._peers: dict[str, _PeerRec] = {}  # peer_id -> record
        self._max_pending = max_pending
        self._time_fn = time_fn

    # -- peers ---------------------------------------------------------------

    def set_peer_height(self, peer_id: str, height: int) -> None:
        with self._lock:
            rec = self._peers.get(peer_id)
            if rec is None:
                self._peers[peer_id] = _PeerRec(height, self._time_fn)
            else:
                rec.height = height

    def remove_peer(self, peer_id: str) -> None:
        """Forget the peer; its in-flight requests become reassignable."""
        with self._lock:
            self._peers.pop(peer_id, None)
            for h in [h for h, r in self._requests.items() if r.peer_id == peer_id]:
                del self._requests[h]

    def max_peer_height(self) -> int:
        with self._lock:
            return max((r.height for r in self._peers.values()), default=0)

    def num_peers(self) -> int:
        with self._lock:
            return len(self._peers)

    # -- scheduling ------------------------------------------------------------

    def schedule_requests(
        self, now: float | None = None
    ) -> tuple[list[tuple[str, int]], list[str]]:
        """One scheduler tick -> (requests to send, peers to evict).

        A request that exceeds REQUEST_TIMEOUT_S evicts its peer — the
        reference's `bpRequester` timeout drops the peer outright
        (`pool.go:115ff`), which is also the byzantine defense: a peer
        advertising a height it never serves would otherwise pin
        `max_peer_height` above reach and keep fast-sync from ever
        completing. A peer that DOES respond but below MIN_RECV_RATE
        (10 kB/s, `pool.go:33,121-126`) is evicted too once its oldest
        pending request is older than MIN_RECV_GRACE_S — a slow-drip
        peer must not throttle the whole sync to its trickle. Freed
        heights reschedule to the remaining peers in the same tick
        (reference `makeRequestersRoutine`)."""
        now = now if now is not None else self._time_fn()
        out: list[tuple[str, int]] = []
        evict: list[str] = []
        with self._lock:
            if not self._peers:
                return [], []
            oldest: dict[str, float] = {}
            for h, req in list(self._requests.items()):
                if now - req.sent_at > REQUEST_TIMEOUT_S:
                    if req.peer_id in self._peers and req.peer_id not in evict:
                        evict.append(req.peer_id)
                cur = oldest.get(req.peer_id)
                if cur is None or req.sent_at < cur:
                    oldest[req.peer_id] = req.sent_at
            for peer_id, sent_at in oldest.items():
                if peer_id in evict or peer_id not in self._peers:
                    continue
                if now - sent_at <= MIN_RECV_GRACE_S:
                    continue
                rate = self._peers[peer_id].monitor.rate
                # rate == 0 means no response COMPLETED yet — a large
                # first block may still be in flight, so only the hard
                # 15 s timeout may evict then (the reference makes the
                # same zero-rate exception, pool.go:122-123)
                if rate != 0 and rate < MIN_RECV_RATE:
                    evict.append(peer_id)
            for peer_id in evict:
                self._peers.pop(peer_id, None)
                for h in [
                    h for h, r in self._requests.items() if r.peer_id == peer_id
                ]:
                    del self._requests[h]
            # new + freed requests
            h = self.height
            target = self.max_peer_height()
            while len(self._requests) < self._max_pending and h <= target:
                if h not in self._blocks and h not in self._requests:
                    peer = self._pick_peer(h)
                    if peer is None:
                        break
                    self._requests[h] = _Request(peer, now)
                    out.append((peer, h))
                h += 1
        return out, evict

    def _pick_peer(self, height: int, exclude: str | None = None) -> str | None:
        """Least-loaded peer that advertises the height."""
        loads: dict[str, int] = {p: 0 for p in self._peers}
        for req in self._requests.values():
            if req.peer_id in loads:
                loads[req.peer_id] += 1
        best, best_load = None, None
        for p, rec in self._peers.items():
            if p == exclude or rec.height < height:
                continue
            if loads[p] >= MAX_PENDING_PER_PEER:
                continue
            if best_load is None or loads[p] < best_load:
                best, best_load = p, loads[p]
        return best

    # -- data ------------------------------------------------------------------

    def add_block(self, peer_id: str, block, size: int = 0) -> bool:
        """Accept a response only for a height we requested from that
        peer (reference `AddBlock pool.go:203-224`); `size` (the wire
        payload bytes) feeds the peer's recv-rate monitor."""
        height = block.header.height
        with self._lock:
            req = self._requests.get(height)
            if req is None or req.peer_id != peer_id:
                return False
            del self._requests[height]
            self._blocks[height] = (block, peer_id)
            rec = self._peers.get(peer_id)
            if rec is not None and size > 0:
                rec.monitor.update(size)
        return True

    def peek(self, n: int, from_height: int | None = None) -> list:
        """Up to n CONSECUTIVE blocks starting at `from_height`
        (default self.height). The offset form lets the fast-sync
        pipeline prep window K+1 while window K's blocks — still below
        self.height+... — wait un-popped for their in-flight verdict."""
        start = self.height if from_height is None else from_height
        with self._lock:
            out = []
            for h in range(start, start + n):
                if h not in self._blocks:
                    break
                out.append(self._blocks[h][0])
            return out

    def pop(self) -> None:
        """Advance past self.height (block was applied)."""
        with self._lock:
            self._blocks.pop(self.height, None)
            self._requests.pop(self.height, None)
            self.height += 1

    def redo(self, height: int) -> str | None:
        """A block failed verification: drop it (and everything after —
        they chain off it) and return the peer that sent it so the
        switch can drop the peer (reference `RedoRequest`)."""
        with self._lock:
            bad_peer = None
            if height in self._blocks:
                bad_peer = self._blocks[height][1]
            for h in list(self._blocks):
                if h >= height:
                    del self._blocks[h]
            for h in list(self._requests):
                if h >= height:
                    del self._requests[h]
            return bad_peer

    def is_caught_up(self) -> bool:
        """Within one block of every peer's tip (with at least one peer
        heard from — reference `IsCaughtUp pool.go:170-185`). The TIP
        block itself cannot fast-sync: its commit travels in its
        successor's LastCommit, so consensus takes over for it."""
        with self._lock:
            if not self._peers:
                return False
            return self.height >= self.max_peer_height()
