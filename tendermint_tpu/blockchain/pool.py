"""BlockPool: parallel block download with peer accounting.

Reference `blockchain/pool.go:49-64` — up to `max_pending` outstanding
height requests spread over peers, per-request timeouts with
reassignment, sorted assembly. The reference runs one goroutine per
in-flight height; here a single scheduler tick (driven by the reactor's
sync loop) computes which requests to (re)send — same behavior, no
thread-per-height.

The pool is pure bookkeeping: the reactor supplies a `send_request`
callback and feeds `add_block`; verification/apply happens in the
reactor's sync loop (batched through the TPU verifier — the whole point
of fast-sync, BASELINE config 3).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass


REQUEST_TIMEOUT_S = 15.0  # reference peerTimeout (pool.go:33)
MAX_PENDING_PER_PEER = 20  # reference maxPendingRequestsPerPeer scaled


@dataclass
class _Request:
    peer_id: str
    sent_at: float


class BlockPool:
    def __init__(self, start_height: int, max_pending: int = 64) -> None:
        # next height we still need to hand to the executor
        self.height = start_height
        self._lock = threading.RLock()
        self._blocks: dict[int, tuple[object, str]] = {}  # height -> (block, peer)
        self._requests: dict[int, _Request] = {}
        self._peers: dict[str, int] = {}  # peer_id -> advertised height
        self._max_pending = max_pending

    # -- peers ---------------------------------------------------------------

    def set_peer_height(self, peer_id: str, height: int) -> None:
        with self._lock:
            self._peers[peer_id] = height

    def remove_peer(self, peer_id: str) -> None:
        """Forget the peer; its in-flight requests become reassignable."""
        with self._lock:
            self._peers.pop(peer_id, None)
            for h in [h for h, r in self._requests.items() if r.peer_id == peer_id]:
                del self._requests[h]

    def max_peer_height(self) -> int:
        with self._lock:
            return max(self._peers.values(), default=0)

    def num_peers(self) -> int:
        with self._lock:
            return len(self._peers)

    # -- scheduling ------------------------------------------------------------

    def schedule_requests(
        self, now: float | None = None
    ) -> tuple[list[tuple[str, int]], list[str]]:
        """One scheduler tick -> (requests to send, peers to evict).

        A request that exceeds REQUEST_TIMEOUT_S evicts its peer — the
        reference's `bpRequester` timeout drops the peer outright
        (`pool.go:115ff`), which is also the byzantine defense: a peer
        advertising a height it never serves would otherwise pin
        `max_peer_height` above reach and keep fast-sync from ever
        completing. Freed heights reschedule to the remaining peers in
        the same tick (reference `makeRequestersRoutine`)."""
        now = now if now is not None else time.monotonic()
        out: list[tuple[str, int]] = []
        evict: list[str] = []
        with self._lock:
            if not self._peers:
                return [], []
            for h, req in list(self._requests.items()):
                if now - req.sent_at > REQUEST_TIMEOUT_S:
                    if req.peer_id in self._peers and req.peer_id not in evict:
                        evict.append(req.peer_id)
            for peer_id in evict:
                self._peers.pop(peer_id, None)
                for h in [
                    h for h, r in self._requests.items() if r.peer_id == peer_id
                ]:
                    del self._requests[h]
            # new + freed requests
            h = self.height
            target = self.max_peer_height()
            while len(self._requests) < self._max_pending and h <= target:
                if h not in self._blocks and h not in self._requests:
                    peer = self._pick_peer(h)
                    if peer is None:
                        break
                    self._requests[h] = _Request(peer, now)
                    out.append((peer, h))
                h += 1
        return out, evict

    def _pick_peer(self, height: int, exclude: str | None = None) -> str | None:
        """Least-loaded peer that advertises the height."""
        loads: dict[str, int] = {p: 0 for p in self._peers}
        for req in self._requests.values():
            if req.peer_id in loads:
                loads[req.peer_id] += 1
        best, best_load = None, None
        for p, max_h in self._peers.items():
            if p == exclude or max_h < height:
                continue
            if loads[p] >= MAX_PENDING_PER_PEER:
                continue
            if best_load is None or loads[p] < best_load:
                best, best_load = p, loads[p]
        return best

    # -- data ------------------------------------------------------------------

    def add_block(self, peer_id: str, block) -> bool:
        """Accept a response only for a height we requested from that
        peer (reference `AddBlock pool.go:203-224`)."""
        height = block.header.height
        with self._lock:
            req = self._requests.get(height)
            if req is None or req.peer_id != peer_id:
                return False
            del self._requests[height]
            self._blocks[height] = (block, peer_id)
        return True

    def peek(self, n: int) -> list:
        """Up to n CONSECUTIVE blocks starting at self.height."""
        with self._lock:
            out = []
            for h in range(self.height, self.height + n):
                if h not in self._blocks:
                    break
                out.append(self._blocks[h][0])
            return out

    def pop(self) -> None:
        """Advance past self.height (block was applied)."""
        with self._lock:
            self._blocks.pop(self.height, None)
            self._requests.pop(self.height, None)
            self.height += 1

    def redo(self, height: int) -> str | None:
        """A block failed verification: drop it (and everything after —
        they chain off it) and return the peer that sent it so the
        switch can drop the peer (reference `RedoRequest`)."""
        with self._lock:
            bad_peer = None
            if height in self._blocks:
                bad_peer = self._blocks[height][1]
            for h in list(self._blocks):
                if h >= height:
                    del self._blocks[h]
            for h in list(self._requests):
                if h >= height:
                    del self._requests[h]
            return bad_peer

    def is_caught_up(self) -> bool:
        """Within one block of every peer's tip (with at least one peer
        heard from — reference `IsCaughtUp pool.go:170-185`). The TIP
        block itself cannot fast-sync: its commit travels in its
        successor's LastCommit, so consensus takes over for it."""
        with self._lock:
            if not self._peers:
                return False
            return self.height >= self.max_peer_height()
