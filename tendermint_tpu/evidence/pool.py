"""EvidencePool: verified misbehavior proofs awaiting commitment.

Reference `evidence/pool.go` + `evidence/store.go`, collapsed to one
WAL-backed pool. Lifecycle of one piece of evidence:

    detected (vote_set conflict / gossip on 0x38)
      -> add_evidence: verify (2-lane batch through the BatchVerifier
         seam), dedup by canonical hash, append to the evidence WAL,
         enter the pending set, fire on_evidence_added (gossip out)
      -> reaped into a proposal (pending_evidence)
      -> update(height, committed): committed evidence leaves pending
         (tendermint_evidence_committed_total), expired evidence
         (height - ev.height > max_age) is pruned
         (tendermint_evidence_expired_total)

Persistence is an append-only log of framed records — `P <evidence>` on
add, `C <hash>` on commit — replayed at construction; the committed
markers keep a restarted node from re-proposing evidence the chain
already holds. A torn tail (crash mid-append) truncates cleanly at the
first bad frame, like the consensus WAL.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict

from tendermint_tpu.codec.binary import Reader, Writer
from tendermint_tpu.telemetry import metrics as _metrics
from tendermint_tpu.telemetry.flightrec import FLIGHT
from tendermint_tpu.types.errors import ValidationError
from tendermint_tpu.types.evidence import decode_evidence
from tendermint_tpu.types.params import EvidenceParams
from tendermint_tpu.utils.lockrank import ranked_rlock
from tendermint_tpu.utils.log import kv, logger
import logging

_log = logger("evidence")

_REC_PENDING = 0x01
_REC_COMMITTED = 0x02

_HOST_VERIFIER = None


def _host_verifier():
    global _HOST_VERIFIER
    if _HOST_VERIFIER is None:
        from tendermint_tpu.services.verifier import HostBatchVerifier

        _HOST_VERIFIER = HostBatchVerifier()
    return _HOST_VERIFIER

# committed-hash memory bound: enough to cover any plausible re-gossip
# window without growing forever on a long-lived node
_MAX_COMMITTED_REMEMBERED = 4096


class EvidencePool:
    """Thread-safe pending-evidence set with WAL persistence.

    `val_set_fn(height) -> ValidatorSet | None` resolves the validator
    set evidence must verify against; consensus wires its live set.
    `best_height_fn() -> int` is the freshness clock for max-age expiry
    at admission time (update() prunes at commit time)."""

    def __init__(
        self,
        wal_path: str | None = None,
        params: EvidenceParams | None = None,
        verifier=None,
        chain_id: str = "",
        val_set_fn=None,
        best_height_fn=None,
    ) -> None:
        self.params = params or EvidenceParams()
        self.verifier = verifier
        self.chain_id = chain_id
        self.val_set_fn = val_set_fn
        self.best_height_fn = best_height_fn
        # fires with the freshly added evidence (the reactor broadcasts)
        self.on_evidence_added = None
        # ranked just above consensus.state: _found_conflicting_votes
        # admits evidence while holding the consensus lock; the gossip
        # hook fires OUTSIDE this lock (see add_evidence)
        self._lock = ranked_rlock("evidence.pool")
        self._pending: "OrderedDict[bytes, object]" = OrderedDict()
        self._committed: "OrderedDict[bytes, None]" = OrderedDict()
        self._wal_path = wal_path
        self._wal = None
        if wal_path:
            os.makedirs(os.path.dirname(wal_path) or ".", exist_ok=True)
            self._replay_wal(wal_path)
            self._wal = open(wal_path, "ab")
        self._observe_depth()

    # -- persistence ---------------------------------------------------------

    def _replay_wal(self, path: str) -> None:
        """Rebuild pending/committed from the log; truncate a torn tail."""
        if not os.path.exists(path):
            return
        good = 0
        with open(path, "rb") as f:
            data = f.read()
        r = Reader(data)
        while not r.done():
            try:
                tag = r.uvarint()
                payload = r.bytes()
                if tag == _REC_PENDING:
                    ev = decode_evidence(payload)
                    self._pending[ev.hash()] = ev
                elif tag == _REC_COMMITTED:
                    self._pending.pop(payload, None)
                    self._remember_committed(payload)
                # unknown tags: skip (forward compatibility)
                good = r.offset
            except (ValueError, ValidationError):
                break  # torn tail: keep the prefix that framed cleanly
        if good < len(data):
            with open(path, "r+b") as f:
                f.truncate(good)

    def _append(self, tag: int, payload: bytes) -> None:
        if self._wal is None:
            return
        try:
            self._wal.write(Writer().uvarint(tag).bytes(payload).build())
            self._wal.flush()
        except Exception:
            # the pool must keep working in memory even on a dead disk;
            # the evidence re-arrives via gossip after a restart anyway
            kv(_log, logging.WARNING, "evidence WAL append failed")

    def close(self) -> None:
        with self._lock:
            if self._wal is not None:
                try:
                    self._wal.close()
                finally:
                    self._wal = None

    # -- admission -----------------------------------------------------------

    def add_evidence(self, ev, val_set=None) -> bool:
        """Verify + admit one piece of evidence. Returns True when it
        newly entered the pending set, False for duplicates / already
        committed / expired. Raises ValidationError when the proof is
        INVALID — callers treat that as peer misbehavior."""
        key = ev.hash()
        with self._lock:
            if key in self._pending or key in self._committed:
                return False
        best = self.best_height_fn() if self.best_height_fn else 0
        if best and best - ev.height > self.params.max_age:
            return False  # expired: unverifiable, not an offense
        if val_set is None and self.val_set_fn is not None:
            val_set = self.val_set_fn(ev.height)
        if val_set is not None:
            # unconfigured pools verify on a plain host backend, NOT the
            # lazily-created process-global coalescer: add_evidence runs
            # on the consensus receive-loop thread, and a 2-lane proof
            # must never wait out (or wedge behind) a shared merge
            # window there — commit-side evidence verification
            # (validate_block) still batches through the node's device
            # spine
            ev.verify(
                self.chain_id, val_set, verifier=self.verifier or _host_verifier()
            )
        else:
            ev.validate_basic()
        with self._lock:
            if key in self._pending or key in self._committed:
                return False
            self._pending[key] = ev
            self._append(_REC_PENDING, ev.encode())
            self._observe_depth()
        kv(
            _log,
            logging.WARNING,
            "evidence admitted",
            evidence=type(ev).__name__,
            validator=ev.address.hex()[:12],
            height=ev.height,
        )
        FLIGHT.record(
            "evidence_added",
            evidence=type(ev).__name__,
            validator=ev.address.hex()[:12],
            height=ev.height,
        )
        cb = self.on_evidence_added
        if cb is not None:
            try:
                cb(ev)
            except Exception:
                pass  # gossip is best-effort; the pool state is what matters
        return True

    # -- queries -------------------------------------------------------------

    def pending_evidence(self, max_n: int | None = None) -> list:
        """Oldest-first pending evidence for a block proposal."""
        with self._lock:
            evs = list(self._pending.values())
        if max_n is None:
            max_n = self.params.max_evidence
        return evs[:max_n]

    def has(self, ev) -> bool:
        key = ev.hash()
        with self._lock:
            return key in self._pending or key in self._committed

    def depth(self) -> int:
        with self._lock:
            return len(self._pending)

    # -- commit-time maintenance ---------------------------------------------

    def update(self, height: int, committed) -> None:
        """A block at `height` committed carrying `committed` evidence:
        retire it from pending, remember its hashes, prune expired
        stragglers (reference `EvidencePool.Update`)."""
        with self._lock:
            for ev in committed:
                key = ev.hash()
                first_seen = key not in self._committed
                self._pending.pop(key, None)
                self._remember_committed(key)
                self._append(_REC_COMMITTED, key)
                if first_seen:
                    _metrics.EVIDENCE_COMMITTED.inc()
            expired = [
                key
                for key, ev in self._pending.items()
                if height - ev.height > self.params.max_age
            ]
            for key in expired:
                del self._pending[key]
                _metrics.EVIDENCE_EXPIRED.inc()
            self._observe_depth()

    def _remember_committed(self, key: bytes) -> None:
        self._committed[key] = None
        self._committed.move_to_end(key)
        while len(self._committed) > _MAX_COMMITTED_REMEMBERED:
            self._committed.popitem(last=False)

    def _observe_depth(self) -> None:
        _metrics.EVIDENCE_POOL_DEPTH.set(len(self._pending))
