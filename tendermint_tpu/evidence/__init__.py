"""Evidence subsystem: pool + p2p gossip for validator-misbehavior proofs.

The accountability half of BFT (PAPERS.md: fork detection is only
useful if the fork is *attributable*): `EvidencePool` collects verified
`DuplicateVoteEvidence` (WAL-backed, deduped, pruned on commit/expiry),
`EvidenceReactor` gossips it on channel 0x38, and consensus commits
pending evidence into proposed blocks so every full node — and the app,
at BeginBlock — learns who equivocated.
"""

from tendermint_tpu.evidence.pool import EvidencePool
from tendermint_tpu.evidence.reactor import EVIDENCE_CHANNEL, EvidenceReactor

__all__ = ["EVIDENCE_CHANNEL", "EvidencePool", "EvidenceReactor"]
