"""Evidence gossip reactor (reference `evidence/reactor.go`, channel 0x38).

Push-plus-retry gossip in the repo's house style (the consensus
reactor's `_on_vote_event` rationale): every newly admitted piece of
evidence is pushed to all peers immediately, a new peer gets the whole
pending set on connect, and a slow background tick re-offers pending
evidence — the clist-walk of the reference collapsed onto the pool's
ordered pending set, with the pool's dedup making every re-offer
idempotent. Gossip loops are impossible by construction: a node only
re-broadcasts evidence that NEWLY entered its pool.

Invalid evidence from a peer is an attack (forged proofs cost us a
2-lane verify each): the peer is reported to the switch's misbehavior
scorer and dropped.
"""

from __future__ import annotations

import threading

from tendermint_tpu.codec.binary import Reader, Writer
from tendermint_tpu.p2p.connection import ChannelDescriptor
from tendermint_tpu.p2p.peer import Peer
from tendermint_tpu.p2p.switch import Reactor
from tendermint_tpu.types.errors import ErrEvidenceUnprovable, ValidationError
from tendermint_tpu.types.evidence import decode_evidence

EVIDENCE_CHANNEL = 0x38

_MSG_EVIDENCE_LIST = 0x01

_REBROADCAST_INTERVAL_S = 0.5
# evidence per gossip frame; a list message stays well under frame caps
_GOSSIP_BATCH = 32


def encode_evidence_list(evidence: list) -> bytes:
    w = Writer().uvarint(_MSG_EVIDENCE_LIST).uvarint(len(evidence))
    for ev in evidence:
        w.bytes(ev.encode())
    return w.build()


def decode_evidence_list(payload: bytes) -> list:
    r = Reader(payload)
    if r.uvarint() != _MSG_EVIDENCE_LIST:
        raise ValueError("unknown evidence message")
    n = r.uvarint()
    if n > 4 * _GOSSIP_BATCH:
        raise ValueError(f"evidence list too long ({n})")
    out = [decode_evidence(r.bytes()) for _ in range(n)]
    r.expect_done()
    return out


class EvidenceReactor(Reactor):
    def __init__(self, pool) -> None:
        super().__init__()
        self.pool = pool
        pool.on_evidence_added = self._on_evidence_added
        self._running = False
        self._stop = threading.Event()

    # -- reactor interface ---------------------------------------------------

    def get_channels(self) -> list[ChannelDescriptor]:
        return [ChannelDescriptor(EVIDENCE_CHANNEL, priority=3)]

    def on_start(self) -> None:
        self._running = True
        self._stop.clear()
        threading.Thread(
            target=self._rebroadcast_routine, name="evidence-gossip", daemon=True
        ).start()

    def on_stop(self) -> None:
        self._running = False
        self._stop.set()

    def add_peer(self, peer: Peer) -> None:
        self._send_pending(peer)

    def receive(self, chan_id: int, peer: Peer, payload: bytes) -> None:
        evidence = decode_evidence_list(payload)
        for ev in evidence:
            try:
                added = self.pool.add_evidence(ev)
                if not added and self.switch is not None:
                    # pool dedup (already pending or already committed):
                    # the rebroadcast routine re-offers pending batches
                    # by design, so re-arrivals are common — the gossip
                    # observatory counts what that retry policy costs
                    self.switch.gossip.redundant("evidence", len(ev.encode()))
            except ErrEvidenceUnprovable:
                # offender outside every retained valset (rotation /
                # max-age horizon): unverifiable here, NOT the relaying
                # peer's crime — drop without a debit
                continue
            except ValidationError as e:
                # a forged proof is adversarial input, not noise
                if self.switch is not None:
                    self.switch.report_misbehavior(
                        peer, "bad_evidence", detail=str(e)
                    )
                return

    # -- gossip --------------------------------------------------------------

    def _on_evidence_added(self, ev) -> None:
        if self.switch is None:
            return
        self.switch.broadcast(EVIDENCE_CHANNEL, encode_evidence_list([ev]))

    def _send_pending(self, peer: Peer) -> None:
        pending = self.pool.pending_evidence(_GOSSIP_BATCH)
        if pending:
            peer.try_send(EVIDENCE_CHANNEL, encode_evidence_list(pending))

    def _rebroadcast_routine(self) -> None:
        """Retry/catchup backfill: a push dropped on a full queue or a
        partition must not strand evidence forever."""
        while self._running and not self._stop.wait(_REBROADCAST_INTERVAL_S):
            if self.switch is None:
                continue
            pending = self.pool.pending_evidence(_GOSSIP_BATCH)
            if not pending:
                continue
            msg = encode_evidence_list(pending)
            for peer in self.switch.peers():
                peer.try_send(EVIDENCE_CHANNEL, msg)
