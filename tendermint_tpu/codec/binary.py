"""Deterministic binary codec.

Role of go-wire's ReadBinary/WriteBinary in the reference (used by the WAL
`consensus/wal.go:177`, state persistence `state/state.go:232`, block parts,
and every p2p message). Encoding rules:

- ``uvarint``: LEB128 (7 bits per byte, little-endian groups, MSB=continue).
- ``svarint``: zigzag-mapped uvarint.
- ``bytes``: uvarint length prefix + raw bytes.
- ``string``: utf-8 encoded, as bytes.
- structs: fields in declaration order via each type's ``encode``/``decode``.

Every encoder is a pure function of the value — no maps with nondeterministic
iteration order, no floats. All integers are arbitrary-precision Python ints;
heights/rounds fit int64 by validation at the type layer.
"""

from __future__ import annotations


def encode_uvarint(n: int) -> bytes:
    if n < 0:
        raise ValueError(f"uvarint cannot encode negative {n}")
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def encode_svarint(n: int) -> bytes:
    # zigzag: 0,-1,1,-2,2 ... -> 0,1,2,3,4
    return encode_uvarint((n << 1) if n >= 0 else ((-n << 1) - 1))


def encode_bytes(b: bytes) -> bytes:
    return encode_uvarint(len(b)) + bytes(b)


def encode_string(s: str) -> bytes:
    return encode_bytes(s.encode("utf-8"))


def decode_uvarint(data: bytes, offset: int = 0) -> tuple[int, int]:
    n = 0
    shift = 0
    while True:
        if offset >= len(data):
            raise ValueError("truncated uvarint")
        b = data[offset]
        offset += 1
        n |= (b & 0x7F) << shift
        if not (b & 0x80):
            return n, offset
        shift += 7
        if shift > 70:
            raise ValueError("uvarint too long")


def decode_svarint(data: bytes, offset: int = 0) -> tuple[int, int]:
    u, offset = decode_uvarint(data, offset)
    return ((u >> 1) ^ -(u & 1)), offset


def decode_bytes(data: bytes, offset: int = 0) -> tuple[bytes, int]:
    n, offset = decode_uvarint(data, offset)
    if offset + n > len(data):
        raise ValueError("truncated bytes")
    return bytes(data[offset : offset + n]), offset + n


def decode_string(data: bytes, offset: int = 0) -> tuple[str, int]:
    b, offset = decode_bytes(data, offset)
    return b.decode("utf-8"), offset


class Writer:
    """Append-only deterministic encoder."""

    __slots__ = ("_parts",)

    def __init__(self) -> None:
        self._parts: list[bytes] = []

    def uvarint(self, n: int) -> "Writer":
        self._parts.append(encode_uvarint(n))
        return self

    def svarint(self, n: int) -> "Writer":
        self._parts.append(encode_svarint(n))
        return self

    def bytes(self, b: bytes) -> "Writer":
        self._parts.append(encode_bytes(b))
        return self

    def raw(self, b: bytes) -> "Writer":
        self._parts.append(bytes(b))
        return self

    def string(self, s: str) -> "Writer":
        self._parts.append(encode_string(s))
        return self

    def bool(self, v: bool) -> "Writer":
        self._parts.append(b"\x01" if v else b"\x00")
        return self

    def build(self) -> bytes:
        return b"".join(self._parts)


class Reader:
    """Sequential decoder with bounds checking."""

    __slots__ = ("data", "offset")

    def __init__(self, data: bytes, offset: int = 0) -> None:
        self.data = data
        self.offset = offset

    def uvarint(self) -> int:
        n, self.offset = decode_uvarint(self.data, self.offset)
        return n

    def svarint(self) -> int:
        n, self.offset = decode_svarint(self.data, self.offset)
        return n

    def bytes(self) -> bytes:
        b, self.offset = decode_bytes(self.data, self.offset)
        return b

    def raw(self, n: int) -> bytes:
        if self.offset + n > len(self.data):
            raise ValueError("truncated raw read")
        b = self.data[self.offset : self.offset + n]
        self.offset += n
        return bytes(b)

    def string(self) -> str:
        s, self.offset = decode_string(self.data, self.offset)
        return s

    def bool(self) -> bool:
        b = self.raw(1)
        if b == b"\x01":
            return True
        if b == b"\x00":
            return False
        raise ValueError(f"invalid bool byte {b!r}")

    def done(self) -> bool:
        return self.offset >= len(self.data)

    def expect_done(self) -> None:
        if not self.done():
            raise ValueError(f"{len(self.data) - self.offset} trailing bytes")
