"""Canonical JSON for sign-bytes.

Signatures in the reference are over canonical JSON with alphabetically sorted
fields wrapped with the chain ID (`types/canonical_json.go:50-53`,
`types/signable.go`). Same contract here:

- keys sorted lexicographically at every level,
- compact separators (no whitespace),
- bytes values hex-encoded (uppercase, like the reference's go-wire JSON),
- integers as JSON numbers (all values fit int64 by type-layer validation),
- timestamps as integer nanoseconds since the Unix epoch (determinism —
  no float seconds, no timezone ambiguity).
"""

from __future__ import annotations

import json
from typing import Any


def _canonicalize(v: Any) -> Any:
    if isinstance(v, (bytes, bytearray, memoryview)):
        return bytes(v).hex().upper()
    if isinstance(v, dict):
        return {k: _canonicalize(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_canonicalize(x) for x in v]
    if isinstance(v, float):
        raise TypeError("floats are forbidden in canonical JSON (nondeterministic)")
    return v


def canonical_dumps(obj: Any) -> bytes:
    """Serialize to canonical JSON bytes (sorted keys, compact, hex bytes)."""
    return json.dumps(
        _canonicalize(obj), sort_keys=True, separators=(",", ":"), ensure_ascii=True
    ).encode("ascii")
