"""Deterministic codecs.

The reference serializes every signed/persisted/wire structure with go-wire
(SURVEY.md §2b: `go-wire` deterministic binary/JSON codec). This package is a
clean-room equivalent: a compact varint-based deterministic binary codec
(`binary`) and canonical JSON for sign-bytes (`canonical_json`).
"""

from tendermint_tpu.codec.binary import (
    Reader,
    Writer,
    decode_bytes,
    decode_string,
    decode_svarint,
    decode_uvarint,
    encode_bytes,
    encode_string,
    encode_svarint,
    encode_uvarint,
)
from tendermint_tpu.codec.canonical_json import canonical_dumps

__all__ = [
    "Reader",
    "Writer",
    "encode_uvarint",
    "decode_uvarint",
    "encode_svarint",
    "decode_svarint",
    "encode_bytes",
    "decode_bytes",
    "encode_string",
    "decode_string",
    "canonical_dumps",
]
